"""Docstring examples are tested code — parity with pylibraft's
``test_doctests.py`` (SURVEY.md §4: "docs are tested code"), which walks
the public modules and executes every docstring example."""

import doctest
import importlib
import pkgutil

import numpy as np
import pytest

import raft_tpu

# Modules whose import is cheap and whose docstrings may carry examples.
# (Walking everything keeps new examples enrolled automatically.)


def _iter_modules():
    pkg = raft_tpu
    names = ["raft_tpu"]
    for m in pkgutil.walk_packages(pkg.__path__, prefix="raft_tpu."):
        names.append(m.name)
    return names


@pytest.mark.parametrize("name", _iter_modules())
def test_docstring_examples(name):
    try:
        mod = importlib.import_module(name)
    except ImportError as e:
        pytest.skip(f"{name}: {e}")
    finder = doctest.DocTestFinder(exclude_empty=True)
    runner = doctest.DocTestRunner(optionflags=doctest.ELLIPSIS
                                   | doctest.NORMALIZE_WHITESPACE)
    globs = {"np": np}
    failures = 0
    for test in finder.find(mod, mod.__name__):
        test.globs.update(globs)
        result = runner.run(test)
        failures += result.failed
    assert failures == 0, f"{failures} doctest failure(s) in {name}"
