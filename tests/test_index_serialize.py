"""Index persistence roundtrips (SURVEY.md §5.4 checkpoint/resume parity;
search results must be identical after save → load)."""

import numpy as np
import pytest

from raft_tpu.neighbors import load_index, save_index


def _blobs(rng, n=400, d=16):
    return (rng.normal(size=(n, d)) +
            rng.integers(0, 4, size=(n, 1)) * 5.0).astype(np.float32)


def test_ivf_flat_roundtrip(tmp_path, rng):
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build, search

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=8, kmeans_n_iters=4))
    save_index(tmp_path / "ivf", idx)
    idx2 = load_index(tmp_path / "ivf")
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))
    assert idx2.metric == idx.metric


def test_ivf_pq_roundtrip(tmp_path, rng):
    from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams, build, search

    x = _blobs(rng)
    idx = build(x, IvfPqIndexParams(n_lists=8, pq_dim=4, kmeans_n_iters=4,
                                    pq_kmeans_n_iters=4))
    save_index(tmp_path / "pq", idx)
    idx2 = load_index(tmp_path / "pq")
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))


def test_cagra_roundtrip(tmp_path, rng):
    from raft_tpu.neighbors.cagra import CagraIndexParams, build, search

    x = _blobs(rng, n=300)
    idx = build(x, CagraIndexParams(graph_degree=8,
                                    intermediate_graph_degree=16, n_routers=8))
    save_index(tmp_path / "cagra", idx)
    idx2 = load_index(tmp_path / "cagra")
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_load_host_only(tmp_path, rng):
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=4, kmeans_n_iters=2))
    save_index(tmp_path / "h", idx)
    host_idx = load_index(tmp_path / "h", device=False)
    assert isinstance(host_idx.centroids, np.ndarray)


def test_reject_unknown_type(tmp_path):
    with pytest.raises(TypeError):
        save_index(tmp_path / "bad", object())


def test_artifacts_are_plain_npy(tmp_path, rng):
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=4, kmeans_n_iters=2))
    save_index(tmp_path / "npy", idx)
    # interop: plain numpy can read every array artifact
    got = np.load(tmp_path / "npy" / "centroids.npy")
    np.testing.assert_array_equal(got, np.asarray(idx.centroids))

def test_orbax_checkpoint_roundtrip(tmp_path, rng):
    """Orbax tier: parallel/sharded checkpointing (SURVEY.md §5.4's
    'orbax-style checkpoint' role)."""
    pytest.importorskip("orbax.checkpoint")
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build, search
    from raft_tpu.neighbors.serialize import (load_index_checkpoint,
                                              save_index_checkpoint)

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=8, kmeans_n_iters=4))
    save_index_checkpoint(tmp_path / "ockpt", idx)
    idx2 = load_index_checkpoint(tmp_path / "ockpt")
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))
    assert idx2.metric == idx.metric


def test_orbax_checkpoint_sharded_restore(tmp_path, rng, mesh8):
    """shardings= restores fields directly into a mesh placement."""
    pytest.importorskip("orbax.checkpoint")
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build
    from raft_tpu.neighbors.serialize import (load_index_checkpoint,
                                              save_index_checkpoint)

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=8, kmeans_n_iters=4))
    save_index_checkpoint(tmp_path / "ockpt", idx)
    s = NamedSharding(mesh8, P("shard"))
    idx2 = load_index_checkpoint(tmp_path / "ockpt",
                                 shardings={"data": s, "ids": s})
    assert idx2.data.sharding.is_equivalent_to(s, idx2.data.ndim)
    np.testing.assert_array_equal(np.asarray(idx2.data), np.asarray(idx.data))


def test_orbax_checkpoint_pq_rebuilds_recon(tmp_path, rng):
    pytest.importorskip("orbax.checkpoint")
    from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams, build, search
    from raft_tpu.neighbors.serialize import (load_index_checkpoint,
                                              save_index_checkpoint)

    x = _blobs(rng)
    idx = build(x, IvfPqIndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4))
    save_index_checkpoint(tmp_path / "pq", idx)
    idx2 = load_index_checkpoint(tmp_path / "pq")
    assert idx2.recon is not None  # derived tier rebuilt, never serialized
    import os
    names = {f for _, _, fs in os.walk(tmp_path / "pq") for f in fs}
    assert not any("recon" in n for n in names)
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
