"""Index persistence roundtrips (SURVEY.md §5.4 checkpoint/resume parity;
search results must be identical after save → load)."""

import numpy as np
import pytest

from raft_tpu.neighbors import load_index, save_index


def _blobs(rng, n=400, d=16):
    return (rng.normal(size=(n, d)) +
            rng.integers(0, 4, size=(n, 1)) * 5.0).astype(np.float32)


def test_ivf_flat_roundtrip(tmp_path, rng):
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build, search

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=8, kmeans_n_iters=4))
    save_index(tmp_path / "ivf", idx)
    idx2 = load_index(tmp_path / "ivf")
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))
    assert idx2.metric == idx.metric


def test_ivf_pq_roundtrip(tmp_path, rng):
    from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams, build, search

    x = _blobs(rng)
    idx = build(x, IvfPqIndexParams(n_lists=8, pq_dim=4, kmeans_n_iters=4,
                                    pq_kmeans_n_iters=4))
    save_index(tmp_path / "pq", idx)
    idx2 = load_index(tmp_path / "pq")
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))


def test_cagra_roundtrip(tmp_path, rng):
    from raft_tpu.neighbors.cagra import CagraIndexParams, build, search

    x = _blobs(rng, n=300)
    idx = build(x, CagraIndexParams(graph_degree=8,
                                    intermediate_graph_degree=16, n_routers=8))
    save_index(tmp_path / "cagra", idx)
    idx2 = load_index(tmp_path / "cagra")
    d1, i1 = search(idx, x[:10], 5)
    d2, i2 = search(idx2, x[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_load_host_only(tmp_path, rng):
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=4, kmeans_n_iters=2))
    save_index(tmp_path / "h", idx)
    host_idx = load_index(tmp_path / "h", device=False)
    assert isinstance(host_idx.centroids, np.ndarray)


def test_reject_unknown_type(tmp_path):
    with pytest.raises(TypeError):
        save_index(tmp_path / "bad", object())


def test_artifacts_are_plain_npy(tmp_path, rng):
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, build

    x = _blobs(rng)
    idx = build(x, IvfFlatIndexParams(n_lists=4, kmeans_n_iters=2))
    save_index(tmp_path / "npy", idx)
    # interop: plain numpy can read every array artifact
    got = np.load(tmp_path / "npy" / "centroids.npy")
    np.testing.assert_array_equal(got, np.asarray(idx.centroids))