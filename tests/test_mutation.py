"""Mutable index lifecycle — online insert, tombstone delete, compaction.

The contracts under test (ISSUE 6 acceptance criteria):

* online ``extend()`` is **bit-identical** (values AND ids) to a
  rebuild-from-scratch for both IVF families, including across multiple
  incremental calls;
* the insert path is zero-retrace / zero-implicit-transfer in steady
  state under :class:`TraceGuard` (``transfer="disallow"``);
* capacity exhaustion grows the slabs and never drops a row;
* deleted ids never appear in results across all four families'
  ``searcher()`` entry points, including sharded and extra-filtered
  paths;
* ``compact()`` drops tombstoned rows, preserves surviving results
  exactly, and re-derives every IVF-PQ storage tier.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from raft_tpu.core import TraceGuard
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq, mutation
from raft_tpu.neighbors.mutation import (Tombstoned, compact, delete,
                                         deleted_count)

N, D, K = 400, 16, 5


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(20).standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    return np.random.default_rng(21).standard_normal((12, D)).astype(np.float32)


def _empty_like_flat(full):
    """Same trained centroids, zero rows — the extend-vs-rebuild oracle."""
    import jax.numpy as jnp

    return ivf_flat.IvfFlatIndex(
        full.centroids, jnp.zeros_like(full.data),
        jnp.full_like(full.ids, -1), jnp.zeros_like(full.counts),
        jnp.zeros_like(full.norms), full.metric)


def _empty_like_pq(full):
    import jax.numpy as jnp

    return ivf_pq.IvfPqIndex(
        full.centroids, full.codebooks, jnp.zeros_like(full.codes),
        jnp.zeros_like(full.code_norms), jnp.full_like(full.ids, -1),
        jnp.zeros_like(full.counts), full.metric)


# ---------------------------------------------------------------------------
# online extend — bit-identity vs rebuild


def test_ivf_flat_extend_bit_identical_to_build(db, queries):
    full = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    ext = ivf_flat.extend(_empty_like_flat(full), db)
    sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
    d0, i0 = ivf_flat.search(full, queries, K, sp)
    d1, i1 = ivf_flat.search(ext, queries, K, sp)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_ivf_flat_incremental_extends_match_one_shot(db, queries):
    full = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    idx = _empty_like_flat(full)
    for lo, hi in ((0, 150), (150, 280), (280, N)):
        idx = ivf_flat.extend(idx, db[lo:hi], np.arange(lo, hi))
    assert idx.size == N
    sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
    d0, i0 = ivf_flat.search(full, queries, K, sp)
    d1, i1 = ivf_flat.search(idx, queries, K, sp)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_ivf_pq_extend_bit_identical_to_build(db, queries):
    full = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8,
                                                    pq_bits=4))
    # match the build's tier config (store_recon default) so mode="auto"
    # picks the same engine on both sides of the comparison
    ext = ivf_pq.extend(_empty_like_pq(full), db).with_recon()
    sp = ivf_pq.IvfPqSearchParams(n_probes=8)
    d0, i0 = ivf_pq.search(full, queries, K, sp)
    d1, i1 = ivf_pq.search(ext, queries, K, sp)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_ivf_pq_extend_rederives_storage_tiers(db, queries):
    """extend on an index with recon + ADC tiers must return the same
    tiers, matching a from-scratch derivation bit-for-bit."""
    full = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8,
                                                    pq_bits=4,
                                                    store_recon=True))
    assert full.recon is not None and full.adc_norms is not None
    ext = ivf_pq.extend(_empty_like_pq(full).with_adc_luts().with_recon(), db)
    assert ext.recon is not None and ext.adc_norms is not None
    for mode in ("recon", "lut"):
        sp = ivf_pq.IvfPqSearchParams(n_probes=8, mode=mode)
        d0, i0 = ivf_pq.search(full, queries, K, sp)
        d1, i1 = ivf_pq.search(ext, queries, K, sp)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_extend_growth_path_never_drops_rows(db, queries):
    """Inserting 8x the built size exhausts list capacity: the slow path
    must grow the slabs and place every row (n_probes = n_lists makes the
    search exhaustive, so results match a fresh build exactly)."""
    small = ivf_flat.build(db[:50], ivf_flat.IvfFlatIndexParams(n_lists=8))
    grown = ivf_flat.extend(small, db[50:], np.arange(50, N))
    assert grown.size == N
    assert grown.list_cap > small.list_cap
    sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
    d0, i0 = ivf_flat.search(ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(
        n_lists=8)), queries, K, sp)
    d1, i1 = ivf_flat.search(grown, queries, K, sp)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_ivf_pq_extend_growth_path(db):
    small = ivf_pq.build(db[:64], ivf_pq.IvfPqIndexParams(n_lists=8,
                                                          pq_dim=8,
                                                          pq_bits=4))
    grown = ivf_pq.extend(small, db[64:], np.arange(64, N))
    assert grown.size == N
    assert grown.list_cap > small.list_cap


def test_extend_validation(db):
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    with pytest.raises(RaftError):
        ivf_flat.extend(idx, db[:3, :-1])  # dim mismatch
    with pytest.raises(RaftError):
        ivf_flat.extend(idx, db[:3], np.array([1, 2]))  # id count mismatch
    with pytest.raises(RaftError):
        ivf_flat.extend(idx, db[:2], np.array([-1, 4]))  # −1 is the pad


def test_extend_steady_state_trace_guard(db):
    """Acceptance gate: after one warm insert, further same-sized inserts
    run with zero retraces, zero compiles, and zero implicit transfers
    (the full ``transfer_guard("disallow")`` regime — the chunk staging
    uses explicit device_put, the spill check explicit device_get)."""
    rng = np.random.default_rng(22)
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=6))
    nxt = N
    idx = ivf_flat.extend(idx, rng.standard_normal((16, D)).astype(np.float32),
                          np.arange(nxt, nxt + 16))
    nxt += 16
    jax.block_until_ready(idx.counts)
    with TraceGuard() as tg:
        for _ in range(4):
            new = rng.standard_normal((16, D)).astype(np.float32)
            idx = ivf_flat.extend(idx, new, np.arange(nxt, nxt + 16))
            nxt += 16
        jax.block_until_ready(idx.counts)
    tg.assert_steady_state()
    assert idx.size == N + 5 * 16


# ---------------------------------------------------------------------------
# tombstone deletes


def _top1_ids(di):
    return set(int(i) for i in np.asarray(di)[:, 0] if int(i) >= 0)


def test_delete_composition_and_counts(db):
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    t = delete(idx, [5, 9])
    assert isinstance(t, Tombstoned) and deleted_count(t) == 2
    t = delete(t, [9, 40])  # re-delete is a no-op, not an error
    assert deleted_count(t) == 3
    with pytest.raises(RaftError):
        delete(idx, [-2])
    with pytest.raises(RaftError):
        delete(idx, [10 ** 9])  # outside the inferred id space
    with pytest.raises(RaftError):
        delete(t, [1], id_space=2)  # cannot shrink an existing mask
    t2 = delete(idx, [1], id_space=4 * N)  # headroom for future inserts
    assert t2.keep.n_bits == 4 * N


@pytest.mark.parametrize("family", ["brute_force", "ivf_flat", "ivf_pq",
                                    "cagra"])
def test_deleted_ids_never_in_searcher_results(db, queries, family):
    """The serving contract: tombstoned ids are unreachable through the
    family's ``searcher()`` entry point (the path the serve runtime
    compiles), and the holes are backfilled by live neighbors."""
    if family == "brute_force":
        index, params = db, None
        fn0, ops0 = brute_force.searcher(db, K)
    elif family == "ivf_flat":
        index = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
        params = ivf_flat.IvfFlatSearchParams(n_probes=8)
        fn0, ops0 = ivf_flat.searcher(index, K, params)
    elif family == "ivf_pq":
        index = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8,
                                                         pq_bits=4))
        params = ivf_pq.IvfPqSearchParams(n_probes=8)
        fn0, ops0 = ivf_pq.searcher(index, K, params)
    else:
        index = cagra.build(db, cagra.CagraIndexParams(graph_degree=8))
        params = cagra.CagraSearchParams(itopk_size=32)
        fn0, ops0 = cagra.searcher(index, K, params)
    _, di0 = fn0(queries, *ops0)
    dead = _top1_ids(di0)
    assert dead, "fixture should return real neighbors"
    t = delete(index, np.array(sorted(dead), np.int32))

    from raft_tpu.serve.searchers import make_searcher

    fn, ops = make_searcher(t, K, params)
    dv, di = fn(queries, *ops)
    got = set(np.asarray(di).ravel().tolist())
    assert not (got & dead), f"deleted ids {got & dead} leaked into results"
    # every slot is a live id: deletions are backfilled, not blanked
    # (k << live rows here; graph search may legitimately pad with −1)
    if family != "cagra":
        assert -1 not in got


def test_delete_through_sharded_search(db, mesh8):
    """Tombstone masks ride the sharded searchers' filter plumbing."""
    x = np.random.default_rng(23).standard_normal((1600, D)).astype(np.float32)
    q = x[:8]
    fidx = ivf_flat.build_sharded(x, mesh8, ivf_flat.IvfFlatIndexParams(
        n_lists=32, kmeans_n_iters=4))
    t = delete(fidx, np.arange(8), id_space=1600)
    _, ids = ivf_flat.search_sharded(
        fidx, q, 3, ivf_flat.IvfFlatSearchParams(n_probes=4),
        mesh=mesh8, filter=t.keep)
    ids = np.asarray(ids)
    assert not ((ids >= 0) & (ids < 8)).any()

    cidx = cagra.build_sharded(x, mesh8, cagra.CagraIndexParams(
        intermediate_graph_degree=16, graph_degree=8, n_routers=16))
    tc = delete(cidx, np.arange(8), id_space=1600)
    _, ids2 = cagra.search_sharded(
        cidx, q, 3, cagra.CagraSearchParams(itopk_size=16),
        mesh=mesh8, filter=tc.keep)
    ids2 = np.asarray(ids2)
    assert not ((ids2 >= 0) & (ids2 < 8)).any()


def test_delete_composes_with_extra_filter(db, queries):
    """mutation.search ANDs a caller filter into the tombstone mask."""
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
    _, di0 = ivf_flat.search(idx, queries, K, sp)
    dead = _top1_ids(di0)
    extra_banned = _top1_ids(np.asarray(di0)[:, 1:2])
    t = delete(idx, np.array(sorted(dead), np.int32))
    extra = np.ones(t.keep.n_bits, bool)
    extra[sorted(extra_banned)] = False
    _, di = mutation.search(t, queries, K, sp, filter=extra)
    got = set(np.asarray(di).ravel().tolist())
    assert not (got & (dead | extra_banned))


def test_tombstoned_extend_preserves_mask(db, queries):
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
    _, di0 = ivf_flat.search(idx, queries, K, sp)
    dead = _top1_ids(di0)
    t = delete(idx, np.array(sorted(dead), np.int32), id_space=2 * N)
    rng = np.random.default_rng(24)
    t = mutation.extend(t, rng.standard_normal((32, D)).astype(np.float32),
                        np.arange(N, N + 32))
    assert isinstance(t, Tombstoned) and t.size == N + 32
    assert t.keep.n_bits == 2 * N  # sized up front: no mask reshape
    _, di = mutation.search(t, queries, K, sp)
    assert not (set(np.asarray(di).ravel().tolist()) & dead)


# ---------------------------------------------------------------------------
# compaction


def test_compact_preserves_surviving_results_ivf_flat(db, queries):
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
    _, di0 = ivf_flat.search(idx, queries, K, sp)
    dead = _top1_ids(di0)
    t = delete(idx, np.array(sorted(dead), np.int32))
    d_t, i_t = mutation.search(t, queries, K, sp)
    c = compact(t)
    assert not isinstance(c, Tombstoned)  # tombstones consumed
    assert c.size == N - len(dead)
    d_c, i_c = ivf_flat.search(c, queries, K, sp)
    np.testing.assert_array_equal(np.asarray(d_t), np.asarray(d_c))
    np.testing.assert_array_equal(np.asarray(i_t), np.asarray(i_c))


def test_compact_shrinks_cap_after_heavy_deletion(db):
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    t = delete(idx, np.arange(0, N, 2))  # tombstone half the corpus
    c = compact(t, headroom=1.5)
    assert c.size == N // 2
    assert c.list_cap < idx.list_cap


def test_compact_ivf_pq_rederives_tiers(db, queries):
    idx = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(
        n_lists=8, pq_dim=8, pq_bits=4, store_recon=True, pack_codes=True))
    sp = ivf_pq.IvfPqSearchParams(n_probes=8)
    _, di0 = ivf_pq.search(idx, queries, K, sp)
    dead = _top1_ids(di0)
    t = delete(idx, np.array(sorted(dead), np.int32))
    d_t, i_t = mutation.search(t, queries, K, sp)
    c = compact(t)
    assert c.packed and c.recon is not None and c.adc_norms is not None
    d_c, i_c = ivf_pq.search(c, queries, K, sp)
    np.testing.assert_array_equal(np.asarray(d_t), np.asarray(d_c))
    np.testing.assert_array_equal(np.asarray(i_t), np.asarray(i_c))


def test_compact_refuses_graphs_and_bad_headroom(db):
    cg = cagra.build(db, cagra.CagraIndexParams(graph_degree=8))
    with pytest.raises(RaftError):  # graph edges are positional: rebuild
        compact(delete(cg, [1]))
    with pytest.raises(RaftError):
        compact(delete(ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(
            n_lists=8)), [1]), headroom=0.5)


def test_compact_brute_force_drops_rows(db):
    # brute compact is real since the durability PR: kept rows gather
    # into a dense array with positional renumbering (row i = old
    # kept[i]); the deeper equality checks live in tests/test_wal.py
    c = compact(delete(db, [1]))
    assert c.shape == (N - 1, db.shape[1])
    np.testing.assert_array_equal(np.asarray(c[1]), db[2])
