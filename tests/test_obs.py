"""raft_tpu.obs — telemetry subsystem tests (ISSUE 9).

All tier-1 (CPU, fast).  The observability contract under test:

* spans nest per-thread, parent explicitly across threads, and survive
  in fixed-capacity per-thread rings (the flight recorder);
* one serve request produces a **connected span tree**
  (request -> enqueue/batch_form/dispatch/device_exec/reply) visible in
  the exported Chrome-trace JSON — the acceptance criterion;
* the Prometheus exposition parses, and its histogram-derived p95 agrees
  with the JSON snapshot's exact reservoir p95 within one bucket width;
* ``ServingMetrics.count()`` raises :class:`UnknownCounter` on typos
  (the old ``AttributeError``-in-``setattr`` bug) and ``declare()`` is
  the documented dynamic-create path;
* ``dump_metrics`` / ``write_text_atomic`` never leave a torn file;
* ``tracing.pop_range`` is balanced-safe and exception-safe;
* an injected ``wedge`` fault trips the stall watchdog and leaves a
  flight-recorder dump on disk;
* the whole telemetry surface adds **zero** retraces / recompiles /
  transfers to the warmed serve hot path (TraceGuard).
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

import jax

from raft_tpu.core import tracing
from raft_tpu.core.errors import RaftError
from raft_tpu.core.serialize import write_text_atomic
from raft_tpu.core.trace_guard import TraceGuard
from raft_tpu.obs import (DEFAULT_LATENCY_BOUNDARIES_MS, Counter, Gauge,
                          Histogram, MetricRegistry, SpanRecorder,
                          StallWatchdog, chrome_trace, export_chrome_trace,
                          parse_text, render)
from raft_tpu.obs import spans as obs_spans
from raft_tpu.serve import (FaultInjector, RetryPolicy, SearchServer,
                            ServerConfig, ServingMetrics, UnknownCounter)

N, D = 160, 16


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeNsClock:
    """Deterministic monotonic_ns stand-in for span timing tests."""

    def __init__(self, t: int = 1_000) -> None:
        self.t = t

    def __call__(self) -> int:
        self.t += 1_000
        return self.t


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(90).standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries(db):
    return db[:3]


@pytest.fixture()
def isolated_recorder():
    """Fresh process-default recorder per test, restored afterwards."""
    rec = SpanRecorder(256)
    prev = obs_spans.set_recorder(rec)
    yield rec
    obs_spans.set_recorder(prev)


# ---------------------------------------------------------------------------
# span recorder


def test_span_nesting_auto_parents():
    rec = SpanRecorder(16, clock_ns=FakeNsClock())
    with rec.span("outer", rows=4) as outer:
        with rec.span("inner") as inner:
            assert rec.current() is inner
        assert rec.current() is outer
    assert rec.current() is None
    spans = rec.snapshot()
    assert [s.name for s in spans] == ["outer", "inner"]
    o, i = spans
    assert i.parent_id == o.span_id and i.trace_id == o.trace_id
    assert o.parent_id is None and o.trace_id == o.span_id
    assert o.attrs == {"rows": 4}
    assert o.t_end_ns > o.t_start_ns and i.duration_ns > 0


def test_span_explicit_parent_crosses_threads():
    rec = SpanRecorder(16)
    root = rec.start("request")
    got = {}

    def worker():
        with rec.span("dispatch", parent=root):
            pass
        got["tid"] = threading.get_ident()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    rec.finish(root, status="ok")
    spans = {s.name: s for s in rec.snapshot()}
    assert spans["dispatch"].parent_id == spans["request"].span_id
    assert spans["dispatch"].trace_id == spans["request"].trace_id
    assert spans["dispatch"].tid == got["tid"] != spans["request"].tid


def test_ring_overwrites_oldest_keeps_order():
    rec = SpanRecorder(4, clock_ns=FakeNsClock())
    for j in range(7):
        rec.event(f"e{j}")
    names = [s.name for s in rec.snapshot()]
    assert names == ["e3", "e4", "e5", "e6"]
    st = rec.stats()
    assert st["retained"] == 4 and st["recorded"] == 7


def test_record_and_event_forms():
    rec = SpanRecorder(16)
    sp = rec.record("measured", 100, 300, bucket=8)
    ev = rec.event("marker", reason="stale")
    assert sp.duration_ns == 200 and sp.attrs == {"bucket": 8}
    assert ev.duration_ns == 0
    assert [s.name for s in rec.snapshot()] == ["measured", "marker"]


def test_finish_is_idempotent_one_ring_entry():
    # split requests share one root span; every part's resolve calls
    # finish on it — the ring must retain it exactly once
    rec = SpanRecorder(16)
    root = rec.start("request")
    rec.finish(root, status="ok", part=0)
    end = root.t_end_ns
    rec.finish(root, part=1)
    assert root.t_end_ns == end           # not re-stamped
    assert root.attrs["part"] == 1        # attrs still update
    assert len(rec.snapshot()) == 1


def test_disabled_recorder_records_nothing():
    rec = SpanRecorder(16, enabled=False)
    assert rec.start("x") is None
    rec.finish(None)
    with rec.span("y") as sp:
        assert sp is None
    assert rec.event("z") is None
    assert rec.snapshot() == [] and rec.stats()["recorded"] == 0


def test_span_records_error_attr_and_pops_on_raise():
    rec = SpanRecorder(16)
    with pytest.raises(ValueError):
        with rec.span("boom"):
            raise ValueError("x")
    (sp,) = rec.snapshot()
    assert sp.attrs["error"] == "ValueError" and sp.t_end_ns > 0
    assert rec.current() is None


def test_clear_and_capacity_validation():
    rec = SpanRecorder(8)
    rec.event("a")
    rec.clear()
    assert rec.snapshot() == []
    with pytest.raises(RaftError):
        SpanRecorder(0)


# ---------------------------------------------------------------------------
# metrics registry


def test_counter_labels_and_monotonicity():
    c = Counter("hits")
    c.inc()
    c.inc(2, kernel="fused")
    c.inc(kernel="fused")
    assert c.value() == 1.0 and c.value(kernel="fused") == 3.0
    assert c.samples() == [({}, 1.0), ({"kernel": "fused"}, 3.0)]
    with pytest.raises(RaftError):
        c.inc(-1)


def test_gauge_sets_point_in_time():
    g = Gauge("depth")
    g.set(3)
    g.set(7)
    assert g.value() == 7.0


def test_histogram_buckets_quantile_width():
    h = Histogram("lat", boundaries=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 3.5, 10.0):
        h.observe(v)
    ((labels, counts, total),) = h.samples()
    assert labels == {} and counts == [1, 1, 2, 1]   # last slot = +Inf
    assert total == pytest.approx(18.5) and h.count() == 5
    assert h.quantile(0.2) == 1.0
    assert h.quantile(0.8) == 4.0
    assert h.quantile(1.0) == 4.0   # overflow clamps to top boundary
    assert h.bucket_width(1.5) == 1.0 and h.bucket_width(3.0) == 2.0
    assert h.bucket_width(99.0) == 2.0
    assert Histogram("empty").quantile(0.95) == 0.0
    with pytest.raises(RaftError):
        Histogram("bad", boundaries=(2.0, 1.0))
    with pytest.raises(RaftError):
        h.quantile(0.0)


def test_histogram_interpolated_quantile():
    h = Histogram("lat", boundaries=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 3.5, 10.0):
        h.observe(v)
    # linear placement inside the bucket: q=0.7 -> need 3.5 of 5, bucket
    # (2, 4] holds ranks 3..4, so 2 + (3.5-2)/2 * 2 = 3.5
    assert h.quantile(0.7, interpolate=True) == pytest.approx(3.5)
    # both estimates always land in the SAME bucket, interpolated <= edge
    for q in (0.2, 0.5, 0.7, 0.8):
        edge = h.quantile(q)
        interp = h.quantile(q, interpolate=True)
        assert edge - h.bucket_width(edge) <= interp <= edge
    # overflow and empty behave exactly like the conservative default
    assert h.quantile(1.0, interpolate=True) == 4.0
    assert Histogram("empty").quantile(0.95, interpolate=True) == 0.0


def test_family_and_histogram_remove_label_set():
    c = Counter("x")
    c.inc(3, generation="1")
    c.inc(5, generation="2")
    assert c.remove(generation="1") and not c.remove(generation="1")
    assert c.samples() == [({"generation": "2"}, 5.0)]
    h = Histogram("lat", boundaries=(1.0,))
    h.observe(0.5, generation="1")
    h.observe(0.5, generation="2")
    assert h.remove(generation="1") and not h.remove(generation="1")
    assert [labels for labels, _, _ in h.samples()] == [{"generation": "2"}]


def test_registry_idempotent_and_type_checked():
    reg = MetricRegistry()
    c1 = reg.counter("x", "help")
    assert reg.counter("x") is c1
    with pytest.raises(RaftError):
        reg.gauge("x")
    reg.histogram("h")
    assert [m.name for m in reg.collect()] == ["x", "h"]
    assert reg.get("h") is not None and reg.get("nope") is None


# ---------------------------------------------------------------------------
# prometheus exposition


def test_render_parse_roundtrip():
    reg = MetricRegistry()
    reg.counter("req_total", "requests").inc(5, route="search")
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_ms", "latency", (1.0, 4.0))
    h.observe(0.5)
    h.observe(2.0)
    h.observe(9.0)
    text = render(reg)
    assert "# TYPE req_total counter" in text
    assert "# TYPE lat_ms histogram" in text
    parsed = parse_text(text)
    assert parsed["req_total"] == [({"route": "search"}, 5.0)]
    assert parsed["depth"] == [({}, 2.0)]
    buckets = {l["le"]: v for l, v in parsed["lat_ms_bucket"]}
    assert buckets == {"1": 1.0, "4": 2.0, "+Inf": 3.0}  # cumulative
    assert parsed["lat_ms_count"] == [({}, 3.0)]
    assert parsed["lat_ms_sum"][0][1] == pytest.approx(11.5)


def test_render_escapes_and_dedups():
    reg1, reg2 = MetricRegistry(), MetricRegistry()
    reg1.counter("c", 'a "quoted" \\ help\nline').inc(msg='x"y\\z\nw')
    reg2.counter("c", "shadowed duplicate").inc(9)
    text = render((reg1, reg2))
    assert text.count("# TYPE c counter") == 1   # first registry wins
    parsed = parse_text(text)
    ((labels, v),) = parsed["c"]
    assert labels == {"msg": 'x"y\\z\nw'} and v == 1.0
    with pytest.raises(ValueError):
        parse_text("what even is this line")


def test_render_registered_but_empty_family():
    reg = MetricRegistry()
    reg.counter("quiet_total", "never fired")
    assert parse_text(render(reg))["quiet_total"] == [({}, 0.0)]


# ---------------------------------------------------------------------------
# perfetto / chrome trace export


def test_chrome_trace_events_and_flows():
    rec = SpanRecorder(32)
    root = rec.start("request", rows=2)

    def worker():
        with rec.span("dispatch", parent=root):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    rec.finish(root)
    open_span = rec.start("still-open")     # must be skipped
    doc = chrome_trace(rec.snapshot() + [open_span])
    evs = doc["traceEvents"]
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"request", "dispatch"}
    assert xs["dispatch"]["args"]["parent_id"] == \
        xs["request"]["args"]["span_id"]
    assert xs["request"]["args"]["rows"] == 2
    # cross-thread lineage draws a flow arrow pair
    assert [e["ph"] for e in evs if e.get("cat") == "flow"] == ["s", "f"]
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert len(names) == 2
    assert json.loads(json.dumps(doc))      # strictly JSON-serializable


def test_export_chrome_trace_atomic(tmp_path):
    rec = SpanRecorder(8)
    rec.event("e", arr=np.arange(2))        # non-JSON attr -> repr()
    path = export_chrome_trace(tmp_path / "t.json", rec.snapshot())
    doc = json.loads(open(path).read())
    (ev,) = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert isinstance(ev["args"]["arr"], str)
    assert not [p for p in os.listdir(tmp_path) if ".tmp-" in p]


# ---------------------------------------------------------------------------
# serving metrics (satellite 1: UnknownCounter regression)


def test_count_unknown_counter_raises_with_registered_names():
    m = ServingMetrics()
    with pytest.raises(UnknownCounter) as ei:
        m.count("compleeted")          # the historical typo class
    assert "compleeted" in str(ei.value) and "completed" in str(ei.value)
    with pytest.raises(UnknownCounter):
        m.counter_value("nope")


def test_declare_is_the_dynamic_create_path():
    m = ServingMetrics()
    m.declare("frobnications", "custom host counter")
    m.declare("frobnications")               # idempotent
    m.count("frobnications", 3)
    assert m.frobnications == 3
    assert m.snapshot()["frobnications"] == 3
    assert parse_text(m.prometheus_text())[
        "raft_serve_frobnications_total"][0][1] == 3.0


def test_counters_read_as_attributes_and_snapshot_schema():
    m = ServingMetrics()
    m.count("submitted")
    m.observe_batch(8, rows=5, level=1)
    m.observe_latency(3.0)
    m.observe_latency(12.0, late=True)
    assert m.submitted == 1 and m.batches == 1 and m.completed == 2
    assert m.late_completions == 1
    with pytest.raises(AttributeError):
        m.not_a_counter
    snap = m.snapshot()
    # the historical JSON schema survives...
    for key in ("submitted", "completed", "batches", "batch_fill_ratio",
                "degrade_dispatches", "latency_ms"):
        assert key in snap
    assert snap["batch_fill_ratio"] == pytest.approx(5 / 8)
    assert snap["degrade_dispatches"] == {"1": 1}
    # ...plus the mergeable histogram block
    hist = snap["latency_hist"]
    assert hist["boundaries_ms"] == list(DEFAULT_LATENCY_BOUNDARIES_MS)
    assert sum(hist["counts"]) == 2
    assert hist["sum_ms"] == pytest.approx(15.0)


# ---------------------------------------------------------------------------
# crash-consistent dumps (satellite 2)


def test_write_text_atomic_no_torn_file(tmp_path, monkeypatch):
    target = tmp_path / "m.json"
    write_text_atomic(target, "old\n")
    calls = {"n": 0}
    real_replace = os.replace

    def failing_replace(src, dst):
        calls["n"] += 1
        raise OSError("disk went away")

    monkeypatch.setattr(os, "replace", failing_replace)
    with pytest.raises(OSError):
        write_text_atomic(target, "new\n")
    monkeypatch.setattr(os, "replace", real_replace)
    assert calls["n"] == 1
    assert target.read_text() == "old\n"            # old content intact
    assert not [p for p in os.listdir(tmp_path) if ".tmp-" in p]  # no litter
    write_text_atomic(target, "new\n")
    assert target.read_text() == "new\n"


def test_dump_metrics_writes_valid_json_atomically(db, tmp_path):
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=FakeClock())
    fut = srv.submit(db[:2])
    srv.step()
    fut.result(timeout=5)
    path = tmp_path / "metrics.json"
    srv.dump_metrics(path)
    snap = json.loads(path.read_text())
    assert snap["completed"] == 1 and "cache" in snap
    assert not [p for p in os.listdir(tmp_path) if ".tmp-" in p]


# ---------------------------------------------------------------------------
# tracing push/pop (satellite 3)


def test_pop_range_empty_stack_is_counted_noop(isolated_recorder):
    from raft_tpu.obs.metrics import registry

    c = registry().counter("raft_tracing_unbalanced_pops_total")
    before = c.value()
    assert tracing.pop_range() is False
    assert tracing.stack_depth() == 0
    assert c.value() == before + 1


def test_push_pop_balanced_records_spans(isolated_recorder):
    tracing.push_range("outer(%d)", 1)
    tracing.push_range("inner")
    assert tracing.stack_depth() == 2
    assert tracing.pop_range() is True
    assert tracing.pop_range() is True
    assert tracing.stack_depth() == 0
    names = [s.name for s in isolated_recorder.snapshot()]
    assert names == ["outer(1)", "inner"]   # snapshot orders by start time


def test_push_pop_stacks_are_per_thread(isolated_recorder):
    tracing.push_range("main-range")
    depths = {}

    def worker():
        depths["start"] = tracing.stack_depth()   # fresh stack, not 1
        tracing.push_range("worker-range")
        depths["pushed"] = tracing.stack_depth()
        tracing.pop_range()
        depths["end"] = tracing.stack_depth()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert depths == {"start": 0, "pushed": 1, "end": 0}
    assert tracing.stack_depth() == 1
    assert tracing.pop_range() is True


def test_pop_range_finishes_span_when_exit_raises(isolated_recorder):
    class ExplodingAnnotation:
        def __exit__(self, *exc):
            raise RuntimeError("profiler backend fell over")

    span = isolated_recorder.start("doomed")
    tracing._stack().append((ExplodingAnnotation(), span))
    with pytest.raises(RuntimeError):
        tracing.pop_range()
    assert tracing.stack_depth() == 0               # stack still popped
    assert [s.name for s in isolated_recorder.snapshot()] == ["doomed"]
    assert span.t_end_ns > 0                        # span still finished


def test_range_is_exception_safe(isolated_recorder):
    with pytest.raises(KeyError):
        with tracing.range("risky"):
            raise KeyError("x")
    (sp,) = isolated_recorder.snapshot()
    assert sp.name == "risky" and sp.attrs["error"] == "KeyError"


# ---------------------------------------------------------------------------
# the serve span tree (ACCEPTANCE: connected request tree in the export)


def test_one_request_produces_connected_span_tree(db, queries, tmp_path):
    rec = SpanRecorder(512)
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=FakeClock(), recorder=rec)
    fut = srv.submit(queries)
    srv.step()
    d, i = fut.result(timeout=5)
    assert np.asarray(i).shape == (3, 3)

    by_name = {}
    for s in rec.snapshot():
        by_name.setdefault(s.name, []).append(s)
    root = by_name["serve.request"][0]
    assert root.attrs["rows"] == 3 and root.attrs["status"] == "ok"
    for name in ("serve.enqueue", "serve.batch_form", "serve.dispatch",
                 "serve.reply"):
        (sp,) = by_name[name]
        assert sp.parent_id == root.span_id, name
        assert sp.trace_id == root.trace_id, name
    (dispatch,) = by_name["serve.dispatch"]
    (dev,) = by_name["serve.device_exec"]
    assert dev.parent_id == dispatch.span_id
    assert dispatch.attrs["status"] == "ok" and dispatch.attrs["attempts"] == 1

    # ...and the same tree is reachable in the exported chrome trace
    path = export_chrome_trace(tmp_path / "req.json", rec.snapshot())
    doc = json.loads(open(path).read())
    xs = {e["args"]["span_id"]: e for e in doc["traceEvents"]
          if e["ph"] == "X" and e["name"].startswith("serve.")}
    root_ev = [e for e in xs.values() if e["name"] == "serve.request"]
    assert len(root_ev) == 1
    root_id = root_ev[0]["args"]["span_id"]

    def climbs_to_root(ev, hops=10):
        while hops:
            pid = ev["args"]["parent_id"]
            if pid is None:
                return ev["args"]["span_id"] == root_id
            ev = xs[pid]
            hops -= 1
        return False

    for ev in xs.values():
        assert climbs_to_root(ev), ev["name"]


def test_split_request_parts_share_one_root(db):
    rec = SpanRecorder(512)
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=FakeClock(), recorder=rec)
    fut = srv.submit(db[:7])      # 7 rows over a (4,) ladder: two parts
    while not fut.done():
        srv.step()
    d, i = fut.result(timeout=5)
    assert np.asarray(i).shape == (7, 3)
    spans = rec.snapshot()
    roots = [s for s in spans if s.name == "serve.request"]
    assert len(roots) == 1                      # one ring entry, not two
    dispatches = [s for s in spans if s.name == "serve.dispatch"]
    assert len(dispatches) == 2
    assert all(sp.parent_id == roots[0].span_id for sp in dispatches)


def test_rejected_requests_finish_their_spans(db):
    rec = SpanRecorder(128)
    clock = FakeClock()
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=clock, recorder=rec)
    fut = srv.submit(db[:2], deadline_ms=10.0)
    clock.advance(1.0)            # expire in queue
    srv.step()
    with pytest.raises(Exception):
        fut.result(timeout=5)
    roots = [s for s in rec.snapshot() if s.name == "serve.request"]
    assert len(roots) == 1
    assert roots[0].attrs["status"] == "rejected_deadline"


# ---------------------------------------------------------------------------
# prometheus <-> snapshot agreement (ACCEPTANCE: p95 within a bucket)


def test_prometheus_p95_agrees_with_snapshot_within_bucket(db):
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=FakeClock(), recorder=SpanRecorder(64))
    for j in range(20):
        fut = srv.submit(db[j:j + 2])
        srv.step()
        fut.result(timeout=5)
    snap = srv.metrics.snapshot()
    text = srv.prometheus_text()
    parsed = parse_text(text)

    # rebuild the histogram p95 FROM THE EXPOSITION, the way a scraper
    # would (cumulative buckets -> first le= at the 95th percentile rank)
    buckets = sorted(
        ((float("inf") if l["le"] == "+Inf" else float(l["le"])), v)
        for l, v in parsed["raft_serve_latency_ms_bucket"])
    total = parsed["raft_serve_latency_ms_count"][0][1]
    assert total == 20.0 == float(snap["completed"])
    need = 0.95 * total
    p95_prom = next(le for le, cum in buckets if cum >= need)
    p95_snap = snap["latency_ms"]["p95"]
    width = srv.metrics.latency_hist.bucket_width(
        min(p95_prom, DEFAULT_LATENCY_BOUNDARIES_MS[-1]))
    # tightened from PR 9's two-sided slack: the exposition p95 is the
    # conservative bucket edge, so it NEVER understates the exact
    # reservoir p95 and overstates by at most one bucket width
    assert 0 <= p95_prom - p95_snap <= width
    # the interpolated estimate lands inside that same bucket
    p95_interp = srv.metrics.latency_hist.quantile(0.95, interpolate=True)
    assert p95_prom - width <= p95_interp <= p95_prom
    # and the library-level gauges ride along in the same scrape body
    assert "raft_serve_queue_depth" in parsed
    assert "raft_obs_flight_recorder_spans" in parsed


def test_metrics_snapshot_carries_obs_stats(db):
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=FakeClock(), recorder=SpanRecorder(64))
    snap = srv.metrics_snapshot()
    assert snap["obs"]["capacity_per_thread"] == 64
    assert snap["obs"]["enabled"] is True


# ---------------------------------------------------------------------------
# stall watchdog (ACCEPTANCE: wedge fault -> dump on disk)


def _wedged_server(db, tmp_path, *, times=2):
    clock = FakeClock()
    probes = {"dumps": []}

    faults = FaultInjector(sleep=lambda s: clock.advance(s))
    rec = SpanRecorder(256)
    srv = SearchServer(
        db, k=3, config=ServerConfig(
            ladder=(4,), retry=RetryPolicy(max_retries=times,
                                           backoff_ms=50.0)),
        clock=clock, faults=faults, recorder=rec,
        sleep=lambda s: probes["poll"]())
    wd = srv.attach_watchdog(tmp_path / "quarantine",
                             stall_timeout_s=0.01, capture_s=0.0)

    def poll():
        # backoff sleep during the wedge: the dispatch marker is live;
        # advance past the stall timeout and run one watchdog poll
        clock.advance(0.1)
        out = wd.check()
        if out:
            probes["dumps"].append(out)

    probes["poll"] = poll
    srv.faults.arm("execute", "wedge", times=times)
    return srv, wd, probes


def test_wedge_fault_trips_watchdog_and_dumps(db, queries, tmp_path):
    srv, wd, probes = _wedged_server(db, tmp_path)
    fut = srv.submit(queries)
    srv.step()
    d, i = fut.result(timeout=5)          # wedge retried through; answered
    assert np.asarray(i).shape == (3, 3)

    assert len(probes["dumps"]) == 1      # one episode -> ONE dump
    dump = probes["dumps"][0]
    assert os.path.basename(dump).startswith("stall-001-execute")
    flight = json.loads(open(os.path.join(dump, "flight.trace.json")).read())
    names = {e["name"] for e in flight["traceEvents"] if e["ph"] == "X"}
    assert "serve.retry" in names         # the wedge evidence
    assert "obs.stall_detected" in names
    metrics = json.loads(open(os.path.join(dump, "metrics.json")).read())
    assert metrics["stalls"] == 1
    capture = json.loads(open(os.path.join(dump, "capture.json")).read())
    assert capture == {"requested_s": 0.0}
    assert srv.metrics.stalls == 1 and wd.stalls_detected == 1
    # episode over: the marker cleared, the latch re-arms
    assert srv.dispatch_inflight() is None
    assert wd.check() is None


def test_watchdog_latches_one_dump_per_episode(db, tmp_path):
    clock = FakeClock()
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=clock, recorder=SpanRecorder(32))
    wd = srv.attach_watchdog(tmp_path, stall_timeout_s=5.0, capture_s=0.0)
    assert wd.check() is None             # nothing in flight
    srv._inflight = ("execute", clock())
    clock.advance(1.0)
    assert wd.check() is None             # in flight but under timeout
    clock.advance(10.0)
    first = wd.check()
    assert first is not None
    assert wd.check() is None             # latched: same episode
    srv._inflight = None
    assert wd.check() is None             # re-armed
    srv._inflight = ("execute", clock())
    clock.advance(10.0)
    second = wd.check()                   # fresh episode -> fresh dump
    assert second is not None and second != first
    assert srv.metrics.stalls == 2
    assert sorted(os.listdir(tmp_path)) == [os.path.basename(first),
                                            os.path.basename(second)]


def test_watchdog_thread_lifecycle(db, tmp_path):
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       recorder=SpanRecorder(32))
    with srv.attach_watchdog(tmp_path, stall_timeout_s=30.0,
                             poll_interval_s=0.01) as wd:
        assert wd._thread.is_alive()
    assert wd._thread is None
    assert wd.stalls_detected == 0


# ---------------------------------------------------------------------------
# zero-overhead steady state (satellite 4: TraceGuard + exporters)


@pytest.mark.parametrize("family_build", [
    pytest.param(lambda db: db, id="brute_force"),
])
def test_serve_hot_path_steady_state_with_telemetry(db, family_build):
    rec = SpanRecorder(1024)
    srv = SearchServer(family_build(db), k=3,
                       config=ServerConfig(ladder=(4,)),
                       clock=FakeClock(), recorder=rec)
    assert rec.enabled
    srv.warmup()
    # one dispatch outside the guard absorbs first-call layout quirks
    fut = srv.submit(db[:4])
    srv.step()
    fut.result(timeout=5)

    with TraceGuard() as tg, jax.transfer_guard("disallow"):
        for j in range(6):
            fut = srv.submit(db[j:j + 4])
            srv.step()
            fut.result(timeout=5)
        # the exporters themselves must also be trace-free
        srv.prometheus_text()
        srv.metrics.snapshot()
        chrome_trace(rec.snapshot())
    tg.assert_steady_state()
    assert srv.metrics.completed == 7
    assert any(s.name == "serve.device_exec" for s in rec.snapshot())
