"""Test harness config.

RAFT validates MNMG logic without a real cluster via LocalCUDACluster
(``raft-dask/raft_dask/tests/conftest.py:14-49``); the TPU analog is a virtual
8-device CPU mesh via ``--xla_force_host_platform_device_count`` (SURVEY.md §4).
Must run before jax initializes its backends, hence top of conftest.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# JAX_PLATFORMS=cpu via env is NOT honored here: the axon PJRT plugin's
# sitecustomize register() overrides it. The programmatic config update wins
# as long as it runs before backend initialization (verified).
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    return jax.sharding.Mesh(np.asarray(devices[:8]), ("shard",))


@pytest.fixture(scope="session")
def mesh2x4(devices):
    return jax.sharding.Mesh(np.asarray(devices[:8]).reshape(2, 4), ("data", "shard"))


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


@pytest.fixture()
def lockdep_enabled():
    """Arm lockdep for one test with a clean order graph; restores the
    prior arming state (so a RAFT_LOCKDEP=1 session keeps its census)."""
    from raft_tpu.core import lockdep

    was = lockdep.enabled()
    if not was:
        lockdep.reset()
    lockdep.enable()
    yield lockdep
    if not was:
        lockdep.disable()
        lockdep.reset()


def pytest_sessionfinish(session, exitstatus):
    """RAFT_LOCKDEP_REPORT=<path>: write the lock-order census (edges,
    inversions) observed across the whole session — the artifact the
    zero-inversion suite gate and ``scripts/tpu_jobs_r18.sh`` read."""
    path = os.environ.get("RAFT_LOCKDEP_REPORT")
    if not path:
        return
    import json

    from raft_tpu.core import lockdep

    with open(path, "w", encoding="utf-8") as fh:
        json.dump(lockdep.report(), fh, indent=2, sort_keys=True)
        fh.write("\n")
