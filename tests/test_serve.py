"""raft_tpu.serve — serving-runtime tests.

All tier-1 (CPU, fast).  The serving contract under test:

* bucket/ladder planning and padding are pure and deterministic;
* served results are **bit-identical** to direct ``search()`` for every
  index family (exact array equality — padding must not perturb rows);
* deadlines, queue bounds and degradation use an injectable clock and a
  manual ``step()`` loop, so no test sleeps or races;
* the AOT executable cache never compiles more than ``len(ladder)``
  programs per (family, k, dtype, level) under mixed-shape traffic —
  the zero-recompilation guard the subsystem exists for.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.serve import (DEFAULT_LADDER, DeadlineExceeded, QueueFull,
                            SearchServer, ServerConfig, bucket_for,
                            family_of, normalize_ladder)
from raft_tpu.serve.admission import AdmissionController, AdmissionPolicy
from raft_tpu.serve.batcher import Request, plan_batch
from raft_tpu.serve.bucketing import pad_rows, split_rows
from raft_tpu.serve.metrics import ServingMetrics, percentile
from raft_tpu.serve.searchers import BruteForceSearchParams
from raft_tpu.core.errors import RaftError


class FakeClock:
    """Deterministic monotonic clock for deadline tests."""

    def __init__(self, t: float = 100.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# pure planning logic


def test_normalize_ladder():
    assert normalize_ladder((512, 1, 8, 8, 64)) == (1, 8, 64, 512)
    assert normalize_ladder([3]) == (3,)
    with pytest.raises(RaftError):
        normalize_ladder(())
    with pytest.raises(RaftError):
        normalize_ladder((0, 4))


def test_bucket_for():
    lad = (1, 8, 64, 512)
    assert bucket_for(1, lad) == 1
    assert bucket_for(2, lad) == 8
    assert bucket_for(8, lad) == 8
    assert bucket_for(9, lad) == 64
    assert bucket_for(512, lad) == 512
    assert bucket_for(513, lad) is None


def test_split_rows():
    assert split_rows(1000, 512) == [512, 488]
    assert split_rows(512, 512) == [512]
    assert split_rows(3, 512) == [3]


def test_pad_rows_zero_pad_and_noop():
    q = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = pad_rows(q, 5)
    assert out.shape == (5, 3)
    np.testing.assert_array_equal(out[:2], q)
    np.testing.assert_array_equal(out[2:], 0)
    assert pad_rows(q, 2) is q  # full bucket: no copy
    with pytest.raises(RaftError):
        pad_rows(q, 1)


def _req(rows, k=5, dtype=np.float32, deadline=1e9):
    from concurrent.futures import Future

    return Request(np.zeros((rows, 4), dtype=dtype), k, deadline, 0.0,
                   future=Future())


def test_plan_batch_coalesces_fifo_prefix():
    pending = [_req(3), _req(2), _req(1)]
    take, bucket = plan_batch(pending, (1, 8, 64))
    assert take == pending  # all fit in 8
    assert bucket == 8


def test_plan_batch_skips_incompatible_but_preserves_order():
    a, b, c = _req(3, k=5), _req(2, k=7), _req(1, k=5)
    take, bucket = plan_batch([a, b, c], (1, 8))
    assert take == [a, c]  # b (different k) keeps its queue slot
    assert bucket == 8
    d = _req(2, dtype=np.float64)
    take, _ = plan_batch([a, d, c], (1, 8))
    assert take == [a, c]  # dtype splits the batch too


def test_plan_batch_respects_max_bucket():
    pending = [_req(6), _req(6), _req(6)]
    take, bucket = plan_batch(pending, (1, 8))
    assert take == [pending[0]]  # 6+6 > 8 stops the fill
    assert bucket == 8


# ---------------------------------------------------------------------------
# admission + metrics units


def test_admission_levels_and_deadline():
    ctl = AdmissionController(AdmissionPolicy(
        max_queue=10, default_deadline_ms=250.0,
        degrade_queue_fractions=(0.5, 0.8)))
    assert ctl.admit(9) and not ctl.admit(10)
    assert [ctl.level(d) for d in (0, 4, 5, 7, 8, 10)] == [0, 0, 1, 1, 2, 2]
    assert ctl.deadline(2.0, None) == pytest.approx(2.25)
    assert ctl.deadline(2.0, 100.0) == pytest.approx(2.1)
    with pytest.raises(RaftError):
        ctl.deadline(0.0, -5.0)


def test_admission_policy_validation():
    with pytest.raises(RaftError):
        AdmissionPolicy(max_queue=0)
    with pytest.raises(RaftError):
        AdmissionPolicy(degrade_queue_fractions=(0.8, 0.5))
    with pytest.raises(RaftError):
        AdmissionPolicy(degrade_queue_fractions=(0.0,))


def test_server_config_validation():
    with pytest.raises(RaftError):
        ServerConfig(degrade_effort_scales=(1.0, 0.5))  # count mismatch
    with pytest.raises(RaftError):
        ServerConfig(degrade_effort_scales=(0.9, 0.5, 0.25))  # level 0 != 1.0


def test_percentile_nearest_rank():
    assert percentile([], 50) == 0.0
    assert percentile([7.0], 99) == 7.0
    vals = sorted(float(v) for v in range(1, 101))
    assert percentile(vals, 50) == 50.0
    assert percentile(vals, 95) == 95.0
    assert percentile(vals, 99) == 99.0


def test_metrics_snapshot_schema():
    m = ServingMetrics(latency_window=8)
    m.count("submitted", 3)
    m.observe_batch(bucket=8, rows=5, level=1)
    m.observe_latency(10.0)
    m.observe_latency(20.0, late=True)
    snap = m.snapshot()
    assert snap["submitted"] == 3 and snap["completed"] == 2
    assert snap["batch_fill_ratio"] == pytest.approx(5 / 8)
    assert snap["late_completions"] == 1
    assert snap["degrade_dispatches"] == {"1": 1}
    assert snap["latency_ms"]["max"] == 20.0
    text = m.to_json(extra={"queue_depth": 0})
    assert '"queue_depth"' in text


# ---------------------------------------------------------------------------
# bit-identity vs direct search() — all four families


N, D, K = 192, 16, 4


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(7).standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries(db):
    return np.random.default_rng(8).standard_normal((7, D)).astype(np.float32)


@pytest.fixture(scope="module")
def built(db):
    """index + params + direct-search closure per family."""
    fi = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=6))
    fp = ivf_flat.IvfFlatSearchParams(n_probes=3)
    pi = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(n_lists=6, pq_dim=8,
                                                  pq_bits=4))
    pp = ivf_pq.IvfPqSearchParams(n_probes=3)
    ci = cagra.build(db, cagra.CagraIndexParams(graph_degree=8))
    cp = cagra.CagraSearchParams(itopk_size=16)
    return {
        "brute_force": (db, None,
                        lambda q: brute_force.knn(q, db, k=K)),
        "ivf_flat": (fi, fp, lambda q: ivf_flat.search(fi, q, K, params=fp)),
        "ivf_pq": (pi, pp, lambda q: ivf_pq.search(pi, q, K, params=pp)),
        "cagra": (ci, cp, lambda q: cagra.search(ci, q, K, params=cp)),
    }


@pytest.mark.parametrize("family", ["brute_force", "ivf_flat", "ivf_pq",
                                    "cagra"])
def test_served_results_bit_identical(built, queries, family):
    index, params, direct = built[family]
    assert family_of(index) == family
    d0, i0 = direct(queries)
    srv = SearchServer(index, k=K, params=params,
                       config=ServerConfig(ladder=(2, 8, 32)))
    d, i = srv.search(queries)  # step-driven (no thread): deterministic
    # exact equality — the padded-bucket executable must not perturb rows
    np.testing.assert_array_equal(np.asarray(i0), i)
    np.testing.assert_array_equal(np.asarray(d0), d)


def test_single_query_1d_and_split_requests(db):
    srv = SearchServer(db, k=K, config=ServerConfig(ladder=(2, 8)))
    d, i = srv.search(db[3])  # 1-D query promotes to (1, d)
    assert d.shape == (1, K) and i[0, 0] == 3
    big = np.random.default_rng(9).standard_normal((19, D)).astype(np.float32)
    d0, i0 = brute_force.knn(big, db, k=K)
    d, i = srv.search(big)  # 19 rows > max bucket 8: split into 8+8+3
    np.testing.assert_array_equal(np.asarray(i0), i)
    np.testing.assert_array_equal(np.asarray(d0), d)
    assert srv.metrics.batches >= 3


def test_submit_validation(db):
    srv = SearchServer(db, k=K)
    with pytest.raises(RaftError):
        srv.submit(np.zeros((2, D + 1), np.float32))  # dim mismatch
    with pytest.raises(RaftError):
        srv.submit(db[:2], k=N + 1)  # k > index rows
    with pytest.raises(RaftError):
        SearchServer(db, k=0)


# ---------------------------------------------------------------------------
# deadlines, queue bounds, degradation — fake clock, manual step()


def test_deadline_expiry_rejects_before_dispatch(db):
    clock = FakeClock()
    srv = SearchServer(db, k=K, config=ServerConfig(ladder=(4,)),
                       clock=clock)
    fut = srv.submit(db[:2], deadline_ms=50.0)
    clock.advance(0.051)  # deadline passes while queued
    retired = srv.step()
    assert retired == 1
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=0)
    assert srv.metrics.rejected_deadline == 1
    assert srv.metrics.batches == 0  # never reached the accelerator


def test_deadline_not_expired_completes(db):
    clock = FakeClock()
    srv = SearchServer(db, k=K, config=ServerConfig(ladder=(4,)),
                       clock=clock)
    fut = srv.submit(db[:2], deadline_ms=50.0)
    clock.advance(0.010)
    srv.step()
    d, i = fut.result(timeout=0)
    assert i.shape == (2, K)
    assert srv.metrics.completed == 1 and srv.metrics.late_completions == 0


def test_queue_full_rejects_at_submit(db):
    srv = SearchServer(db, k=K, config=ServerConfig(max_queue=2,
                                                    ladder=(4,)),
                       clock=FakeClock())
    srv.submit(db[:1])
    srv.submit(db[:1])
    with pytest.raises(QueueFull):
        srv.submit(db[:1])
    assert srv.metrics.rejected_queue_full == 1
    # draining the queue restores admission
    while srv.step():
        pass
    srv.submit(db[:1])


def test_degradation_activates_under_pressure(db):
    cfg = ServerConfig(max_queue=4, ladder=(1,), max_wait_ms=0.0,
                       degrade_queue_fractions=(0.5, 0.75),
                       degrade_effort_scales=(1.0, 0.5, 0.25))
    srv = SearchServer(db, k=K,
                       params=BruteForceSearchParams(mode="fast", cand=32),
                       config=cfg, clock=FakeClock())
    for _ in range(4):  # depth 4 >= 0.75*4: level 2
        srv.submit(db[:1])
    srv.step()
    assert srv.metrics.degrade_dispatches.get(2) == 1
    # pressure released: the tail of the queue drains at lower levels
    while srv.step():
        pass
    assert 0 in srv.metrics.degrade_dispatches
    # degraded dispatches still return k valid neighbors
    snap = srv.metrics_snapshot()
    assert snap["completed"] == 4 and snap["latency_ms"]["count"] == 4
    # the snapshot surfaces host staging-pool stats (and lands the
    # raft_host_pool_* gauges in the global registry as a side effect)
    assert set(snap["host_pool"]) >= {"hits", "misses", "held_bytes"}


def test_degraded_search_returns_valid_topk(db):
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=8))
    cfg = ServerConfig(max_queue=2, ladder=(2,),
                       degrade_queue_fractions=(0.9,),
                       degrade_effort_scales=(1.0, 0.25))
    srv = SearchServer(idx, k=K,
                       params=ivf_flat.IvfFlatSearchParams(n_probes=8),
                       config=cfg, clock=FakeClock())
    futs = [srv.submit(db[:2]), srv.submit(db[:2])]  # depth 2 -> level 1
    while srv.step():
        pass
    d, i = futs[0].result(timeout=0)
    assert i.shape == (2, K) and (np.asarray(i) >= 0).all()
    assert srv.metrics.degrade_dispatches.get(1, 0) >= 1


# ---------------------------------------------------------------------------
# AOT cache guard — the zero-recompilation contract


def test_mixed_shape_workload_never_recompiles(db):
    """>= 200 mixed-shape requests after warmup must be served entirely by
    the precompiled ladder: compiles == len(ladder), misses == compiles."""
    ladder = (1, 8, 64)
    srv = SearchServer(db, k=K, config=ServerConfig(ladder=ladder))
    assert srv.warmup() == len(ladder)
    assert srv.warmup() == 0  # idempotent
    rng = np.random.default_rng(11)
    futs = []
    for _ in range(200):
        rows = int(rng.integers(1, 40))
        q = rng.standard_normal((rows, D)).astype(np.float32)
        futs.append((q, srv.submit(q)))
        while len(srv._pending) >= 32:  # keep under max_queue
            srv.step()
    while srv.step():
        pass
    for q, fut in futs:
        d, i = fut.result(timeout=0)
        assert i.shape == (q.shape[0], K)
    assert srv.metrics.completed == 200
    assert srv.cache.compiles == len(ladder)  # warmup only — zero extra
    assert srv.cache.hits >= srv.metrics.batches
    snap = srv.metrics_snapshot()
    assert snap["cache"]["compiles"] == len(ladder)
    assert 0 < snap["batch_fill_ratio"] <= 1.0


def test_distinct_k_gets_its_own_executables(db):
    srv = SearchServer(db, k=K, config=ServerConfig(ladder=(4,)))
    srv.warmup()
    srv.search(db[:2])
    assert srv.cache.compiles == 1
    srv.search(db[:2], k=K + 1)  # new cache coordinate: one more compile
    assert srv.cache.compiles == 2
    srv.search(db[:3], k=K + 1)  # same coordinate: cache hit
    assert srv.cache.compiles == 2


# ---------------------------------------------------------------------------
# threaded smoke — real clock, real dispatch thread


def test_threaded_server_smoke(db):
    d0, i0 = brute_force.knn(db[:6], db, k=K)
    with SearchServer(db, k=K,
                      config=ServerConfig(ladder=(1, 8),
                                          max_wait_ms=1.0)) as srv:
        results = [None] * 4
        def client(j):
            results[j] = srv.search(db[:6])
        threads = [threading.Thread(target=client, args=(j,))
                   for j in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        snap = srv.metrics_snapshot()
    for d, i in results:
        np.testing.assert_array_equal(np.asarray(i0), i)
        np.testing.assert_array_equal(np.asarray(d0), d)
    assert snap["completed"] == 4
    assert snap["cache"]["compiles"] <= 2  # the warmed ladder, nothing more
    assert DEFAULT_LADDER == (1, 8, 64, 512)


# ---------------------------------------------------------------------------
# bench driver wiring


def test_bench_serve_emits_final_json_line():
    """bench/serve.py end-to-end at smoke scale: final line is the
    driver-format metric and the cache census shows zero recompilation."""
    import json
    import os
    import subprocess
    import sys

    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench", "serve.py")
    env = dict(os.environ)
    env.update({"RAFT_BENCH_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu",
                "RAFT_BENCH_SERVE_ROWS": "2000",
                "RAFT_BENCH_SERVE_DIM": "16",
                "RAFT_BENCH_SERVE_SECONDS": "0.5",
                "RAFT_BENCH_SERVE_CLIENTS": "2",
                "RAFT_BENCH_SERVE_LADDER": "1,8"})
    p = subprocess.run([sys.executable, bench], capture_output=True,
                       text=True, timeout=300, env=env)
    assert p.returncode == 0, p.stderr
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    final = json.loads(lines[-1])
    assert final["metric"] == "serve_qps_at_p95_budget"
    assert final["value"] > 0
    assert final["unit"].startswith("qps@p95")
    assert final["serving_metrics"]["cache"]["compiles"] == 2  # len(ladder)
    assert final["serving_metrics"]["rejected_queue_full"] == 0
