"""The shared blocked-scan core: the one ``scan(carry, slab) -> carry``
contract every neighbors engine routes through.

Pins, at the core level (engine-level parity lives in test_probe_block /
test_cagra_frontier / test_neighbors):

* **bit-invariance across block sizes** — ``slab_dots`` keeps the block
  axis in the einsum's *batch* dims, so scores (and therefore scan
  results, values AND ids) are bit-identical however the candidate stream
  is blocked;
* **payload lanes** — ``fold_topk_payload`` selects the same (value, id)
  set as the payload-free fold and gathers payloads through the same
  winning positions;
* **filter-mask compose** — +inf'd lanes never surface, a fully-masked
  block is a no-op on the carry;
* **fused-kernel parity** — ``fused_slab_topk`` under ``interpret=True``
  (the CPU parity mode) shortlists a superset of the true top-k, and
  ``scan_topk_fused``'s exact re-score returns the reference answer;
* **dispatch gate** — stale/missing/off-hardware ``MOSAIC_CHECK`` stamps
  close the Mosaic gate with a reason and fall back cleanly (the
  BENCH_r04/r05 wedged-tunnel failure mode);
* **steady state** — alternating warm scan specializations neither
  re-traces nor transfers.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import TraceGuard
from raft_tpu.core.errors import LogicError
from raft_tpu.ops import blocked_scan as bs
from raft_tpu.ops.pallas import gate as gate_mod
from raft_tpu.ops.pallas.fused_scan import fused_slab_topk

NQ, D, K = 8, 24, 10


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(11)
    data = rng.standard_normal((1536, D)).astype(np.float32)
    q = rng.standard_normal((NQ, D)).astype(np.float32)
    return jnp.asarray(data), jnp.asarray(q)


def _reference_topk(data, q, k):
    """lax.top_k over the SAME slab_dots scoring (one whole-corpus slab):
    the scan must reproduce a direct full-matrix selection bit-for-bit."""
    n = data.shape[0]
    vid = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (q.shape[0], n))
    dots = bs.slab_dots(data[vid][:, None], q).reshape(q.shape[0], n)
    dist = jnp.sum(data.astype(jnp.float32) ** 2, axis=1)[vid] - 2.0 * dots
    neg, idx = jax.lax.top_k(-dist, k)
    return np.asarray(-neg), np.asarray(idx)


def _scan_over_blocks(data, q, n_blocks, k):
    """scan_topk over the corpus split into ``n_blocks`` slabs, scored
    through slab_dots with the block dim pinned (B = 1 per step here; the
    B-axis invariance is pinned separately below)."""
    n = data.shape[0]
    c = n // n_blocks
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=1)
    xs = jnp.arange(n_blocks, dtype=jnp.int32)
    lane = jnp.arange(c, dtype=jnp.int32)

    def score(blk):
        vid = jnp.broadcast_to(blk * c + lane, (q.shape[0], c))
        dots = bs.slab_dots(data[vid][:, None], q)
        return norms[vid] - 2.0 * dots.reshape(q.shape[0], c), vid

    return bs.scan_topk(score, xs, q.shape[0], k)


# ---------------------------------------------------------------------------
# bit-invariance


def test_scan_topk_matches_reference(corpus):
    data, q = corpus
    rv, ri = _reference_topk(data, q, K)
    gv, gi = _scan_over_blocks(data, q, 1, K)
    np.testing.assert_array_equal(np.asarray(gv), rv)
    np.testing.assert_array_equal(np.asarray(gi), ri)


def test_scan_topk_bit_invariant_across_block_counts(corpus):
    data, q = corpus
    ref = _scan_over_blocks(data, q, 1, K)
    for n_blocks in (2, 4, 12):
        gv, gi = _scan_over_blocks(data, q, n_blocks, K)
        np.testing.assert_array_equal(np.asarray(gv), np.asarray(ref[0]),
                                      err_msg=f"n_blocks={n_blocks}")
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(ref[1]),
                                      err_msg=f"n_blocks={n_blocks}")


def test_slab_dots_pins_block_axis(corpus):
    """Scoring a [nq, B, C, d] slab must equal B separate [nq, 1, C, d]
    scorings bit-for-bit — the accumulation-shape contract that makes
    every block size produce identical distance bits."""
    data, q = corpus
    b, c = 4, 96
    slab = data[: b * c].reshape(1, b, c, D)
    slab = jnp.broadcast_to(slab, (NQ, b, c, D))
    whole = bs.slab_dots(slab, q)
    for j in range(b):
        part = bs.slab_dots(slab[:, j:j + 1], q)
        np.testing.assert_array_equal(np.asarray(whole[:, j]),
                                      np.asarray(part[:, 0]))


# ---------------------------------------------------------------------------
# folds: payload lanes, masks, carry


def test_fold_topk_payload_matches_plain_fold(corpus):
    data, q = corpus
    rng = np.random.default_rng(3)
    bv = jnp.asarray(rng.standard_normal((NQ, K)).astype(np.float32))
    bi = jnp.asarray(rng.integers(0, 500, (NQ, K)).astype(np.int32))
    tv = jnp.asarray(rng.standard_normal((NQ, 64)).astype(np.float32))
    ti = jnp.asarray(rng.integers(500, 1000, (NQ, 64)).astype(np.int32))
    pv, pi = bs.fold_topk(bv, bi, tv, ti, K, sorted=True)
    mv, mi, (mp,) = bs.fold_topk_payload(bv, bi, (bi * 2,), tv, ti,
                                         (ti * 2,), K)
    mv, mpos = bs.ranked_finish(mv, mi, K)
    # ranked sets agree (payload fold keeps an unsorted carry)
    np.testing.assert_array_equal(np.sort(np.asarray(pv), axis=1),
                                  np.sort(np.asarray(mv), axis=1))
    # payloads rode the same winners: payload ≡ 2 · id by construction
    np.testing.assert_array_equal(np.asarray(mp), 2 * np.asarray(mi))


def test_masked_block_is_noop_on_carry():
    bv, bi = bs.topk_carry(NQ, K)
    rng = np.random.default_rng(5)
    vals = jnp.asarray(rng.standard_normal((NQ, 32)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, 99, (NQ, 32)).astype(np.int32))
    # fold one real block, then a fully-masked one: carry must not change
    bv, bi = bs.fold_topk(bv, bi, vals, ids, K, sorted=False)
    v2, i2 = bs.fold_topk(bv, bi, jnp.full_like(vals, jnp.inf),
                          jnp.full_like(ids, -1), K, sorted=False)
    rv, ri = bs.ranked_finish(bv, bi, K)
    r2v, r2i = bs.ranked_finish(v2, i2, K)
    np.testing.assert_array_equal(np.asarray(rv), np.asarray(r2v))
    np.testing.assert_array_equal(np.asarray(ri), np.asarray(r2i))


def test_filter_mask_composes(corpus):
    """+inf'ing a keep-mask's rejects inside score must drop exactly those
    ids from the result — the compose every engine's prefilter uses."""
    data, q = corpus
    n = data.shape[0]
    keep = np.ones(n, bool)
    _, ri = _reference_topk(data, q, K)
    banned = set(map(int, ri[:, 0]))  # ban every query's top hit
    keep[list(banned)] = False
    keepj = jnp.asarray(keep)
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=1)
    c = n // 4
    lane = jnp.arange(c, dtype=jnp.int32)

    def score(blk):
        vid = jnp.broadcast_to(blk * c + lane, (NQ, c))
        dots = bs.slab_dots(data[vid][:, None], q)
        dist = norms[vid] - 2.0 * dots.reshape(NQ, c)
        return jnp.where(keepj[vid], dist, jnp.inf), vid

    gv, gi = bs.scan_topk(score, jnp.arange(4, dtype=jnp.int32), NQ, K)
    assert not (set(map(int, np.asarray(gi).ravel())) & banned)
    assert np.isfinite(np.asarray(gv)).all()


def test_topk_carry_id_fill():
    _, bi = bs.topk_carry(3, 4)
    assert (np.asarray(bi) == -1).all()
    _, bi0 = bs.topk_carry(3, 4, id_fill=0)
    assert (np.asarray(bi0) == 0).all()


# ---------------------------------------------------------------------------
# fused Pallas arm: interpret-mode parity on CPU


def test_fused_slab_topk_interpret_shortlists_true_topk(corpus):
    data, q = corpus
    c = 640  # not a multiple of bn: exercises the +inf candidate pad
    vecs = jnp.broadcast_to(data[:c][None], (NQ, c, D))
    base = jnp.broadcast_to(
        jnp.sum(data[:c].astype(jnp.float32) ** 2, axis=1)[None], (NQ, c))
    sv, spos = fused_slab_topk(vecs, base, q, bn=256, interpret=True)
    assert sv.shape == spos.shape == (NQ, 512)
    assert (np.asarray(spos) >= 0).all() and (np.asarray(spos) < c).all()
    # shortlist ⊇ exact top-k of the same bf16 surrogate distances
    d2 = np.asarray(base - 2.0 * jnp.einsum(
        "qcd,qd->qc", vecs.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32))
    true = np.argsort(d2, axis=1, kind="stable")[:, :K]
    got = np.asarray(spos)
    rec = np.mean([len(set(t) & set(s)) for t, s in zip(true, got)]) / K
    assert rec == 1.0, f"shortlist recall {rec}"


def test_scan_topk_fused_interpret_matches_reference(corpus):
    """End-to-end fused scan under interpret=True: the exact re-score must
    return the reference ids and exact (f32) values at recall 1 on this
    well-separated corpus."""
    data, q = corpus
    n = data.shape[0]
    n_blocks = 3
    c = n // n_blocks
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=1)
    qn = jnp.sum(q.astype(jnp.float32) ** 2, axis=1)
    lane = jnp.arange(c, dtype=jnp.int32)

    def slab_step(blk):
        vid = jnp.broadcast_to(blk * c + lane, (NQ, c))
        return data[vid], norms[vid], vid, vid

    rescore = bs.l2_rescorer(data, norms, q, qn, "sqeuclidean")
    gv, gi = bs.scan_topk_fused(q, slab_step,
                                jnp.arange(n_blocks, dtype=jnp.int32),
                                rescore, NQ, K, interpret=True)
    rv, ri = _reference_topk(data, q, K)
    rec = np.mean([len(set(map(int, a)) & set(map(int, b))) / K
                   for a, b in zip(ri, np.asarray(gi))])
    assert rec == 1.0, f"fused recall {rec}"
    # values are exact per the rescore algebra (norms − 2·dots + qn)
    want = rv + np.asarray(qn)[:, None]
    order = np.argsort(np.asarray(gi), axis=1)
    worder = np.argsort(ri, axis=1)
    np.testing.assert_allclose(
        np.take_along_axis(np.asarray(gv), order, axis=1),
        np.take_along_axis(want, worder, axis=1), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# dispatch gate: stale stamps and wedged probes fall back cleanly


@pytest.fixture
def clean_gate(monkeypatch):
    gate_mod.reset_gate()
    monkeypatch.delenv("RAFT_MOSAIC_GATE", raising=False)
    yield
    gate_mod.reset_gate()


def _fake_tpu(monkeypatch):
    monkeypatch.setitem(gate_mod._cache, "backend", "tpu")


def test_gate_off_tpu_interprets(clean_gate):
    if jax.default_backend() == "tpu":
        pytest.skip("CPU-only dispatch expectation")
    assert gate_mod.dispatch_mode("fused_scan") == "interpret"


def test_gate_missing_artifact_closes(clean_gate, monkeypatch, tmp_path):
    _fake_tpu(monkeypatch)
    monkeypatch.setattr(gate_mod, "_ARTIFACT", str(tmp_path / "absent.json"))
    ok, reason = gate_mod.mosaic_gate("select_k")
    assert not ok and "missing" in reason
    assert gate_mod.dispatch_mode("select_k") == "xla"


def test_gate_cpu_stamp_closes(clean_gate, monkeypatch, tmp_path):
    _fake_tpu(monkeypatch)
    art = tmp_path / "MOSAIC_CHECK.json"
    art.write_text(json.dumps({"backend": "cpu", "ok": True,
                               "kernel_sha": gate_mod.pallas_kernel_sha()}))
    monkeypatch.setattr(gate_mod, "_ARTIFACT", str(art))
    ok, reason = gate_mod.mosaic_gate()
    assert not ok and "not a hardware validation" in reason


def test_gate_sha_stale_closes(clean_gate, monkeypatch, tmp_path):
    _fake_tpu(monkeypatch)
    art = tmp_path / "MOSAIC_CHECK.json"
    art.write_text(json.dumps({"backend": "tpu", "ok": True,
                               "kernel_sha": "deadbeefdeadbeef"}))
    monkeypatch.setattr(gate_mod, "_ARTIFACT", str(art))
    ok, reason = gate_mod.mosaic_gate()
    assert not ok and "stale" in reason
    assert gate_mod.dispatch_mode("fused_l2_topk") == "xla"


def test_gate_valid_stamp_opens(clean_gate, monkeypatch, tmp_path):
    _fake_tpu(monkeypatch)
    art = tmp_path / "MOSAIC_CHECK.json"
    art.write_text(json.dumps({"backend": "tpu", "ok": True,
                               "kernel_sha": gate_mod.pallas_kernel_sha()}))
    monkeypatch.setattr(gate_mod, "_ARTIFACT", str(art))
    ok, reason = gate_mod.mosaic_gate()
    assert ok and reason == "validated"
    assert gate_mod.dispatch_mode("select_k") == "mosaic"


def test_gate_wedged_probe_falls_back(clean_gate, monkeypatch):
    monkeypatch.setitem(gate_mod._cache, "backend", None)  # wedged verdict
    assert gate_mod.dispatch_mode("select_k") == "xla"
    ok, reason = gate_mod.mosaic_gate()
    assert not ok and "probe" in reason


def test_gate_env_bypass(clean_gate, monkeypatch):
    monkeypatch.setenv("RAFT_MOSAIC_GATE", "off")
    ok, reason = gate_mod.mosaic_gate()
    assert ok and "bypass" in reason


def test_select_k_pallas_xla_fallback_matches(clean_gate, monkeypatch):
    """satellite-6 regression: a closed gate must route select_k_pallas to
    stock XLA with identical results, not error or wedge."""
    from raft_tpu.ops.pallas.select_k import select_k_pallas

    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((32, 256)).astype(np.float32))
    ref_v, ref_i = select_k_pallas(x, 8)  # interpret (CPU) or mosaic (TPU)
    gate_mod.reset_gate()
    monkeypatch.setitem(gate_mod._cache, "backend", None)  # now: wedged
    got_v, got_i = select_k_pallas(x, 8)
    np.testing.assert_array_equal(np.asarray(ref_v), np.asarray(got_v))
    np.testing.assert_array_equal(np.asarray(ref_i), np.asarray(got_i))


# ---------------------------------------------------------------------------
# resolve_scan_kernel


def test_resolve_scan_kernel_passthrough_and_validation():
    assert bs.resolve_scan_kernel("xla", "ivf_flat", 4096, 10) == "xla"
    assert bs.resolve_scan_kernel("fused", "ivf_pq", 4096, 10) == "fused"
    with pytest.raises(LogicError):
        bs.resolve_scan_kernel("mosaic", "ivf_flat", 4096, 10)


def test_resolve_scan_kernel_auto_closed_gate_is_xla(monkeypatch):
    gate_mod.reset_gate()
    if jax.default_backend() != "tpu":
        # off-TPU the gate is closed → auto must resolve to the XLA path
        assert bs.resolve_scan_kernel("auto", "ivf_flat", 4096, 10) == "xla"
    gate_mod.reset_gate()


# ---------------------------------------------------------------------------
# steady state


def test_scan_steady_state(corpus):
    data, q = corpus
    qd = jax.device_put(q)

    @jax.jit
    def run(qx):
        return _scan_over_blocks(data, qx, 4, K)

    jax.block_until_ready(run(qd))  # warm
    with TraceGuard() as tg, jax.transfer_guard("disallow"):
        for _ in range(4):
            jax.block_until_ready(run(qd))
    tg.assert_steady_state()
