"""Compaction scheduler — triggers, pacing, failure safety, durability.

ISSUE 7 acceptance: the scheduler fires under a mutation workload and
compacts through ``swap_index`` with zero dropped requests; a failing
compaction parks in ``compactions_failed`` with the old generation still
serving; with a ``DurableStore`` attached the compaction is WAL-logged
and a fresh ``recover()`` lands on the exact compacted state.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from raft_tpu.neighbors import ivf_flat, mutation
from raft_tpu.serve import (CompactionPolicy, CompactionScheduler,
                            FaultInjector, SearchServer, ServerConfig,
                            SwapFailed)

N, D = 192, 16
ID_SPACE = 256


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(40).standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    return np.random.default_rng(41).standard_normal((5, D)).astype(np.float32)


@pytest.fixture(scope="module")
def built(db):
    return ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=6))


DEAD = list(range(0, 128, 2))  # 64 of 192 rows -> dead fraction 1/3


def _server(index, **cfg):
    clock = FakeClock()
    srv = SearchServer(index, k=3,
                       params=ivf_flat.IvfFlatSearchParams(n_probes=3),
                       config=ServerConfig(ladder=(8,), **cfg),
                       clock=clock, faults=FaultInjector())
    return srv, clock


def test_dead_fraction_trigger_compacts_and_swaps(built, queries):
    srv, clock = _server(mutation.delete(built, DEAD, id_space=ID_SPACE))
    sched = CompactionScheduler(srv, CompactionPolicy(dead_fraction=0.3),
                                clock=clock)
    s = sched.stats()
    assert s["rows"] == N and s["dead"] == len(DEAD)
    assert s["dead_fraction"] == pytest.approx(len(DEAD) / N)
    assert sched.due() == "dead_fraction"
    assert sched.run_once() == "dead_fraction"
    snap = srv.metrics.snapshot()
    assert snap["compactions_scheduled"] == 1
    assert snap["compactions_completed"] == 1
    assert snap["compactions_failed"] == 0
    assert srv.generation == 1
    # the dead rows are physically gone; the rewrapped mask is all-live
    # at the SAME bit width (no searcher operand reshape)
    s2 = sched.stats()
    assert s2["rows"] == N - len(DEAD) and s2["dead"] == 0
    assert isinstance(srv.index, mutation.Tombstoned)
    assert srv.index.keep.n_bits == ID_SPACE
    assert sched.due() is None  # nothing left to reclaim
    d, i = srv.search(queries)
    assert i.shape == (5, 3)
    assert not (set(np.asarray(i).ravel().tolist()) & set(DEAD))


def test_overfull_trigger_recaps_lists(built, queries):
    srv, clock = _server(built)
    sched = CompactionScheduler(
        srv, CompactionPolicy(overfull_fraction=0.05), clock=clock)
    occ0 = sched.stats()["occupancy"]
    assert occ0 >= 0.05
    assert sched.due() == "overfull"
    assert sched.run_once() == "overfull"
    assert srv.generation == 1
    # re-capped to headroom x the fullest live list: the next insert
    # burst has slack again instead of hitting the slab-growth slow path
    assert sched.stats()["occupancy"] < occ0
    d, i = srv.search(queries)
    assert i.shape == (5, 3) and (np.asarray(i)[:, 0] >= 0).all()


def test_min_interval_cooldown(built):
    srv, clock = _server(built)
    sched = CompactionScheduler(
        srv, CompactionPolicy(overfull_fraction=0.05, min_interval_s=100.0),
        clock=clock)
    assert sched.run_once() == "overfull"
    clock.advance(50.0)
    assert sched.due() is None  # still overfull, but cooling down
    clock.advance(100.0)
    assert sched.due() == "overfull"


def test_failed_compaction_counts_and_old_generation_serves(built, queries):
    srv, clock = _server(mutation.delete(built, DEAD, id_space=ID_SPACE))
    sched = CompactionScheduler(srv, CompactionPolicy(dead_fraction=0.3),
                                clock=clock)
    srv.faults.arm("swap", "fail")
    assert sched.run_once() is None
    snap = srv.metrics.snapshot()
    assert snap["compactions_scheduled"] == 1
    assert snap["compactions_failed"] == 1
    assert snap["compactions_completed"] == 0
    assert isinstance(sched.last_error, SwapFailed)
    assert srv.generation == 0  # rollback: old generation still serving
    d, i = srv.search(queries)
    assert i.shape == (5, 3)
    # the fault was one-shot: the next poll retries and succeeds
    assert sched.run_once() == "dead_fraction"
    assert sched.last_error is None
    assert srv.metrics.snapshot()["compactions_completed"] == 1


def test_scheduler_under_live_traffic_zero_dropped(built, queries):
    """Daemon-thread scheduler + dispatch thread + client threads: the
    mutation workload (delete bursts swapped in) pushes the dead
    fraction over threshold, a background compaction fires, and every
    submitted request resolves (zero dropped)."""
    srv = SearchServer(mutation.delete(built, [0], id_space=ID_SPACE), k=3,
                       params=ivf_flat.IvfFlatSearchParams(n_probes=3),
                       config=ServerConfig(ladder=(8,), max_wait_ms=0.5),
                       faults=FaultInjector())
    sched = CompactionScheduler(
        srv, CompactionPolicy(dead_fraction=0.25, poll_interval_s=0.01))
    results: list = []
    errors: list = []

    def client():
        for _ in range(6):
            try:
                d, i = srv.search(queries, deadline_ms=30000.0)
                results.append(np.asarray(i))
            except Exception as exc:  # noqa: BLE001 — collected for assert
                errors.append(exc)

    with srv, sched:
        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        # the mutation workload: tombstone bursts, swapped in live
        for lo in range(0, 120, 24):
            srv.swap_index(mutation.delete(
                srv.index, list(range(lo, lo + 24)), id_space=ID_SPACE))
            time.sleep(0.02)
        deadline = time.monotonic() + 30.0
        while (srv.metrics.snapshot()["compactions_completed"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        for t in threads:
            t.join(60.0)
    snap = srv.metrics.snapshot()
    assert errors == []
    assert len(results) == 18  # every request answered
    assert all(r.shape == (5, 3) for r in results)
    assert snap["compactions_completed"] >= 1
    assert snap["rejected_deadline"] == 0 and snap["rejected_queue_full"] == 0
    assert snap["failed_swaps"] == 0


def test_durable_compaction_recovers_to_compacted_state(built, queries,
                                                        tmp_path):
    from raft_tpu.neighbors.wal import DurableStore

    store = DurableStore.create(
        tmp_path / "store", mutation.delete(built, DEAD, id_space=ID_SPACE))
    srv = SearchServer(store.index, k=3,
                       params=ivf_flat.IvfFlatSearchParams(n_probes=3),
                       config=ServerConfig(ladder=(8,)),
                       clock=FakeClock(), faults=FaultInjector())
    srv.adopt_store(store)
    sched = CompactionScheduler(srv, CompactionPolicy(dead_fraction=0.3),
                                store=store, clock=srv.clock)
    appends0 = srv.metrics.wal_appends
    assert sched.run_once() == "dead_fraction"
    # the compaction went through the WAL (logged before it applied) and
    # the swapped-in generation IS the store's durable state
    assert srv.metrics.wal_appends == appends0 + 1
    assert srv.index is store.index
    assert srv.metrics_snapshot()["server"]["wal_lsn"] == store.wal_lsn
    live = store.index
    store.close()
    rec = DurableStore.recover(tmp_path / "store")
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(live),
                    jax.tree_util.tree_leaves(rec.index)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))
    assert rec.counters["wal_replayed"] == 1  # the compact record
    rec.close()
