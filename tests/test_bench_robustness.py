"""``bench.py`` must emit a parseable final JSON line no matter what the
backend does (VERDICT r3 weak #1/#6: a wedged TPU tunnel erased the round's
bench artifact).  These tests wedge the backend deliberately — via the
documented test hooks — and assert the driver contract survives:

* wedged backend at probe time → final line with ``error``, exit 0, fast;
* a hung jax op inside a config → per-config watchdog fires, ladder
  continues, final line still prints;
* SIGTERM mid-run (the driver's external timeout) → handler flushes the
  final line with whatever completed.

The parent bench process never imports jax, so the tests drive the real
``python bench.py`` entry end-to-end in subprocesses.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py")


def _env(**extra):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.update({"JAX_PLATFORMS": "cpu", "RAFT_BENCH_PLATFORM": "cpu"})
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _final_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON lines in output:\n{stdout}"
    d = json.loads(lines[-1])
    assert "metric" in d and "value" in d, d
    return d


def test_wedged_probe_emits_final_line_fast():
    """Machine-level hang ("hard" wedge: even a CPU-pinned child sleeps) —
    nothing to fall back to, so the contract is the errored final line,
    fast.  RAFT_BENCH_PLATFORM is pinned here, so the CPU fallback path
    correctly does not engage either."""
    t0 = time.time()
    p = subprocess.run([sys.executable, BENCH], capture_output=True, text=True,
                       timeout=120,
                       env=_env(RAFT_BENCH_FAKE_WEDGE="hard",
                                RAFT_BENCH_PROBE_TIMEOUT_S=3))
    assert p.returncode == 0
    d = _final_line(p.stdout)
    assert "backend unavailable" in d["error"]
    assert d["value"] == 0.0
    assert time.time() - t0 < 60


def test_wedged_probe_falls_back_to_cpu():
    """The r5 failure shape (BENCH_r05.json: value 0.0, "probe timed out
    after 180s"): the bare-init probe wedges but the host is healthy.  The
    driver must pin the CPU backend, re-probe, and record a CPU-tagged
    smoke measurement — NOT an empty errored run."""
    env = _env(RAFT_BENCH_FAKE_WEDGE=1,        # wedge only while unpinned
               RAFT_BENCH_PROBE_TIMEOUT_S=3,
               RAFT_BENCH_BF_ROWS=2000,        # CPU-feasible scale
               RAFT_BENCH_SKIP="pairwise,ivf_pq,cagra,ivf_flat")
    del env["RAFT_BENCH_PLATFORM"]             # fallback is the pinner
    p = subprocess.run([sys.executable, BENCH], capture_output=True, text=True,
                       timeout=600, env=env)
    assert p.returncode == 0, p.stderr
    d = _final_line(p.stdout)
    assert "error" not in d, d
    assert d["backend"] == "cpu"
    assert d["value"] > 0                      # a real measurement landed
    assert "smoke" in d["metric"]              # and is labeled CPU-smoke
    fb = d["profile"]["probe_fallback"]
    assert fb["backend"] == "cpu"
    assert "timed out" in fb["primary_error"]


def test_hung_config_watchdog_keeps_ladder_alive():
    p = subprocess.run([sys.executable, BENCH], capture_output=True, text=True,
                       timeout=300,
                       env=_env(RAFT_BENCH_FAKE_SLOW_CONFIG=1,
                                RAFT_BENCH_CONFIG_TIMEOUT_S=3,
                                RAFT_BENCH_SKIP="ivf_pq,cagra,ivf_flat"))
    assert p.returncode == 0
    d = _final_line(p.stdout)
    assert d["configs_done"] == 2  # brute_force + pairwise both attempted
    assert d["profile"].get("skipped") == "watchdog_timeout"
    assert d["north_star"]["pairwise_10kx128"]["skipped"] == "watchdog_timeout"
    assert "error" not in d  # backend stayed healthy; ladder ran to the end


def test_ckpt_rerun_replays_completed_configs(tmp_path):
    """VERDICT r4 weak #5 drill: wedge config 3 of the ladder mid-run; the
    rerun must serve configs 1–2 from the run-scoped checkpoint instead of
    re-measuring (or worse, losing) them."""
    env = _env(RAFT_BENCH_CKPT_DIR=str(tmp_path),
               RAFT_BENCH_BF_ROWS=2000,           # CPU-feasible scales
               RAFT_BENCH_SKIP="cagra,ivf_flat",
               RAFT_BENCH_FAKE_SLOW_CONFIG="ivf_pq",  # wedge config 3 only
               RAFT_BENCH_CONFIG_TIMEOUT_S="ivf_pq=5")
    p1 = subprocess.run([sys.executable, BENCH], capture_output=True,
                        text=True, timeout=600, env=env)
    assert p1.returncode == 0, p1.stderr
    d1 = _final_line(p1.stdout)
    assert d1["value"] > 0, d1                      # config 1 measured
    assert d1["north_star"]["pairwise_10kx128"]["tflops"] > 0  # config 2
    # the wedged config hit its watchdog and must NOT have checkpointed
    assert d1["north_star"]["ivf_pq_deep10m_class"]["skipped"] \
        == "watchdog_timeout"
    assert sorted(f.name for f in tmp_path.iterdir()) \
        == ["brute_force.json", "pairwise.json"]

    # rerun: configs 1–2 replay from checkpoint (fast — the watchdogged
    # config is the only one that spends wall time), config 3 retried
    env["RAFT_BENCH_CONFIG_TIMEOUT_S"] = "ivf_pq=3"
    p2 = subprocess.run([sys.executable, BENCH], capture_output=True,
                        text=True, timeout=300, env=env)
    assert p2.returncode == 0, p2.stderr
    d2 = _final_line(p2.stdout)
    assert d2["value"] == d1["value"]               # config 1 survived
    assert d2["profile"].get("from_checkpoint") is True
    assert d2["north_star"]["pairwise_10kx128"]["from_checkpoint"] is True
    assert d2["north_star"]["pairwise_10kx128"]["tflops"] \
        == d1["north_star"]["pairwise_10kx128"]["tflops"]


@pytest.mark.skipif(sys.platform == "win32", reason="POSIX signals")
def test_sigterm_flushes_final_line():
    p = subprocess.Popen([sys.executable, BENCH], stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True,
                         env=_env(RAFT_BENCH_FAKE_SLOW_CONFIG=1,
                                  RAFT_BENCH_CONFIG_TIMEOUT_S=600))
    # wait for the probe to pass (config child then hangs), then TERM
    time.sleep(20)
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=60)
    assert p.returncode == 0
    d = _final_line(out)
    assert "signal" in d["error"]
