"""linalg tests — parity with ``cpp/tests/linalg/`` (42 suites): each primitive
validated against a naive numpy reference with tolerance (devArrMatch style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg
from raft_tpu.linalg import Apply, NormType


def assert_close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


class TestElementwise:
    def test_binary_family(self, rng):
        x = rng.random((8, 5)).astype(np.float32)
        y = rng.random((8, 5)).astype(np.float32) + 0.5
        assert_close(linalg.add(x, y), x + y)
        assert_close(linalg.subtract(x, y), x - y)
        assert_close(linalg.multiply(x, y), x * y)
        assert_close(linalg.divide(x, y), x / y)
        assert_close(linalg.power(np.abs(x), y), np.abs(x) ** y)
        assert_close(linalg.sqrt(np.abs(x)), np.sqrt(np.abs(x)))
        assert_close(linalg.add_scalar(x, 2.0), x + 2.0)

    def test_map_and_offset(self, rng):
        x = rng.random((4, 4)).astype(np.float32)
        out = linalg.map(lambda a, b: a * 2 + b, x, x)
        assert_close(out, 3 * x)
        off = linalg.map_offset(lambda i: i * 2, (3, 3))
        assert_close(off, (np.arange(9) * 2).reshape(3, 3))

    def test_shape_mismatch_raises(self, rng):
        with pytest.raises(Exception):
            linalg.add(np.zeros((2, 3)), np.zeros((3, 2)))


class TestReduce:
    def test_reduce_directions(self, rng):
        x = rng.random((6, 4)).astype(np.float32)
        assert_close(linalg.reduce(x, apply=Apply.ALONG_ROWS), x.sum(axis=1))
        assert_close(linalg.reduce(x, apply=Apply.ALONG_COLUMNS), x.sum(axis=0))

    def test_reduce_ops(self, rng):
        x = rng.random((6, 4)).astype(np.float32)
        # sum of squares with sqrt epilogue = L2 row norm
        out = linalg.reduce(x, main_op=lambda v: v * v, final_op=jnp.sqrt)
        assert_close(out, np.linalg.norm(x, axis=1), rtol=1e-4)
        out = linalg.reduce(x, reduce_op=jnp.minimum, init=np.inf)
        assert_close(out, x.min(axis=1))

    def test_map_reduce(self, rng):
        x = rng.random(64).astype(np.float32)
        assert_close(linalg.map_reduce(lambda v: v * v, jnp.add, x), (x * x).sum(), rtol=1e-4)

    def test_reduce_rows_by_key(self, rng):
        x = rng.random((10, 3)).astype(np.float32)
        keys = np.array([0, 1, 0, 2, 1, 0, 2, 2, 1, 0])
        out = linalg.reduce_rows_by_key(x, keys, 3)
        expected = np.stack([x[keys == k].sum(axis=0) for k in range(3)])
        assert_close(out, expected, rtol=1e-4)

    def test_reduce_cols_by_key(self, rng):
        x = rng.random((3, 6)).astype(np.float32)
        keys = np.array([0, 1, 1, 0, 2, 2])
        out = linalg.reduce_cols_by_key(x, keys, 3)
        expected = np.stack([x[:, keys == k].sum(axis=1) for k in range(3)], axis=1)
        assert_close(out, expected, rtol=1e-4)

    def test_mse(self, rng):
        a, b = rng.random(32).astype(np.float32), rng.random(32).astype(np.float32)
        assert_close(linalg.mean_squared_error(a, b), np.mean((a - b) ** 2), rtol=1e-5)


class TestNorm:
    def test_row_norms(self, rng):
        x = (rng.random((5, 7)).astype(np.float32) - 0.5) * 4
        assert_close(linalg.row_norm(x, NormType.L1Norm), np.abs(x).sum(axis=1), rtol=1e-4)
        # reference L2 norm is sum-of-squares unless rooted
        assert_close(linalg.row_norm(x, NormType.L2Norm), (x * x).sum(axis=1), rtol=1e-4)
        assert_close(linalg.row_norm(x, NormType.L2Norm, root=True), np.linalg.norm(x, axis=1), rtol=1e-4)
        assert_close(linalg.col_norm(x, NormType.LinfNorm), np.abs(x).max(axis=0))

    def test_normalize(self, rng):
        x = rng.random((5, 7)).astype(np.float32) + 0.1
        out = np.asarray(linalg.normalize(x))
        assert_close(np.linalg.norm(out, axis=1), np.ones(5), rtol=1e-4)

    def test_normalize_zero_row_stays(self):
        x = np.zeros((2, 3), np.float32)
        out = linalg.normalize(x)
        assert_close(out, x)

    def test_matrix_vector_op(self, rng):
        m = rng.random((4, 6)).astype(np.float32)
        v = rng.random(6).astype(np.float32)
        assert_close(linalg.matrix_vector_op(m, v, jnp.add), m + v[None, :])
        v2 = rng.random(4).astype(np.float32)
        assert_close(linalg.matrix_vector_op(m, v2, jnp.multiply, along_rows=False), m * v2[:, None])

    def test_binary_div_skip_zero(self, rng):
        m = rng.random((3, 4)).astype(np.float32)
        v = np.array([2.0, 0.0, 4.0, 0.0], np.float32)
        out = np.asarray(linalg.binary_div_skip_zero(m, v, return_zero=True))
        assert_close(out[:, 0], m[:, 0] / 2.0)
        assert_close(out[:, 1], np.zeros(3))


class TestBlas:
    def test_gemm(self, rng):
        a = rng.random((5, 3)).astype(np.float32)
        b = rng.random((3, 4)).astype(np.float32)
        assert_close(linalg.gemm(a, b), a @ b, rtol=1e-4)
        assert_close(linalg.gemm(a.T, b, trans_a=True), a @ b, rtol=1e-4)
        c = rng.random((5, 4)).astype(np.float32)
        assert_close(linalg.gemm(a, b, alpha=2.0, beta=0.5, c=c), 2 * a @ b + 0.5 * c, rtol=1e-4)

    def test_gemv_dot_axpy(self, rng):
        a = rng.random((5, 3)).astype(np.float32)
        x = rng.random(3).astype(np.float32)
        assert_close(linalg.gemv(a, x), a @ x, rtol=1e-4)
        y = rng.random(5).astype(np.float32)
        assert_close(linalg.axpy(2.0, y, y), 3 * y, rtol=1e-5)
        assert_close(linalg.dot(x, x), x @ x, rtol=1e-5)

    def test_bf16_gemm_accumulates_f32(self, rng):
        a = jnp.asarray(rng.random((64, 64)), jnp.bfloat16)
        out = linalg.gemm(a, a)
        assert out.dtype == jnp.float32


class TestDecomp:
    def test_eig_dc(self, rng):
        a = rng.random((8, 8)).astype(np.float32)
        sym = (a + a.T) / 2
        vals, vecs = linalg.eig_dc(sym)
        recon = np.asarray(vecs) @ np.diag(np.asarray(vals)) @ np.asarray(vecs).T
        assert_close(recon, sym, rtol=1e-3, atol=1e-4)

    def test_eig_jacobi_matches_eigh(self, rng):
        a = rng.random((6, 6)).astype(np.float32)
        sym = (a + a.T) / 2
        vals_j, vecs_j = linalg.eig_jacobi(sym)
        vals_ref = np.linalg.eigvalsh(sym)
        assert_close(vals_j, vals_ref, rtol=1e-3, atol=1e-4)
        recon = np.asarray(vecs_j) @ np.diag(np.asarray(vals_j)) @ np.asarray(vecs_j).T
        assert_close(recon, sym, rtol=1e-3, atol=1e-3)

    def test_eig_selective(self, rng):
        a = rng.random((8, 8)).astype(np.float32)
        sym = (a + a.T) / 2
        vals, vecs = linalg.eig_dc_selective(sym, 3, "largest")
        assert vals.shape == (3,) and vecs.shape == (8, 3)
        assert_close(vals, np.linalg.eigvalsh(sym)[-3:], rtol=1e-3, atol=1e-4)

    def test_qr(self, rng):
        a = rng.random((10, 4)).astype(np.float32)
        q, r = linalg.qr_get_qr(a)
        assert_close(np.asarray(q) @ np.asarray(r), a, rtol=1e-3, atol=1e-4)
        assert_close(np.asarray(q).T @ np.asarray(q), np.eye(4), atol=1e-4)

    def test_svd_qr_and_eig(self, rng):
        a = rng.random((12, 5)).astype(np.float32)
        for fn in (linalg.svd_qr, linalg.svd_eig, linalg.svd_jacobi):
            u, s, v = fn(a)
            recon = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(v).T
            assert_close(recon, a, rtol=1e-2, atol=1e-3)
            assert_close(np.sort(np.asarray(s)), np.sort(np.linalg.svd(a)[1]), rtol=1e-3, atol=1e-3)

    def test_rsvd(self, rng):
        # low-rank + noise: rsvd should recover the dominant singular values
        u0 = rng.standard_normal((100, 5)).astype(np.float32)
        v0 = rng.standard_normal((5, 40)).astype(np.float32)
        a = u0 @ v0
        u, s, v = linalg.rsvd_fixed_rank(a, k=5, key=jax.random.PRNGKey(1))
        s_ref = np.linalg.svd(a)[1][:5]
        assert_close(s, s_ref, rtol=1e-2)

    def test_lstsq_all_paths(self, rng):
        a = rng.standard_normal((30, 4)).astype(np.float32)
        x_true = rng.standard_normal(4).astype(np.float32)
        b = a @ x_true
        for fn in (linalg.lstsq_svd_qr, linalg.lstsq_eig, linalg.lstsq_qr):
            assert_close(fn(a, b), x_true, rtol=1e-2, atol=1e-3)

    def test_cholesky_r1_update(self, rng):
        a = rng.standard_normal((5, 5)).astype(np.float32)
        spd = a @ a.T + 5 * np.eye(5, dtype=np.float32)
        L_small = np.linalg.cholesky(spd[:4, :4])
        new_col = np.concatenate([spd[4, :4], [spd[4, 4]]]).astype(np.float32)
        L_full = linalg.cholesky_r1_update(L_small, new_col)
        assert_close(np.asarray(L_full), np.linalg.cholesky(spd), rtol=1e-3, atol=1e-4)


class TestPca:
    def test_fit_transform_roundtrip(self, rng):
        x = rng.standard_normal((200, 10)).astype(np.float32)
        x[:, 0] *= 10  # dominant direction
        params = linalg.PcaParams(n_components=3)
        proj, model = linalg.pca_fit_transform(x, params)
        assert proj.shape == (200, 3)
        # components orthonormal
        c = np.asarray(model.components)
        assert_close(c @ c.T, np.eye(3), atol=1e-4)
        # variance ordering
        ev = np.asarray(model.explained_variance)
        assert (np.diff(ev) <= 1e-3).all()
        # reconstruct ≈ best rank-3 approx
        recon = linalg.pca_inverse_transform(proj, model, params)
        assert np.mean((np.asarray(recon) - x) ** 2) < np.var(x)

    def test_jacobi_solver_agrees(self, rng):
        x = rng.standard_normal((100, 6)).astype(np.float32)
        ev_dq = linalg.pca_fit(x, linalg.PcaParams(3, linalg.PcaSolver.COV_EIG_DQ)).explained_variance
        ev_j = linalg.pca_fit(x, linalg.PcaParams(3, linalg.PcaSolver.COV_EIG_JACOBI)).explained_variance
        assert_close(ev_dq, ev_j, rtol=1e-3, atol=1e-4)
