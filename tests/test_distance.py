"""Distance module tests — kernels vs scipy references, the reference's test
pattern (naive-reference comparison, ``cpp/tests/test_utils.cuh:45``)."""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance import DistanceType, pairwise_distance, fused_l2_nn, fused_l2_nn_argmin

SCIPY_METRICS = [
    ("sqeuclidean", "sqeuclidean"),
    ("euclidean", "euclidean"),
    ("cosine", "cosine"),
    ("cityblock", "l1"),
    ("chebyshev", "chebyshev"),
    ("canberra", "canberra"),
    ("braycurtis", "braycurtis"),
    ("correlation", "correlation"),
]


@pytest.mark.parametrize("scipy_name,our_name", SCIPY_METRICS)
def test_pairwise_vs_scipy(rng, scipy_name, our_name):
    x = rng.standard_normal((33, 17)).astype(np.float32)
    y = rng.standard_normal((29, 17)).astype(np.float32)
    ref = spd.cdist(x.astype(np.float64), y.astype(np.float64), scipy_name)
    got = np.asarray(pairwise_distance(x, y, our_name))
    assert got.shape == (33, 29)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_pairwise_minkowski(rng):
    x = rng.standard_normal((10, 8)).astype(np.float32)
    y = rng.standard_normal((12, 8)).astype(np.float32)
    ref = spd.cdist(x, y, "minkowski", p=3.0)
    got = np.asarray(pairwise_distance(x, y, "minkowski", p=3.0))
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_pairwise_hamming(rng):
    x = (rng.random((9, 31)) > 0.5).astype(np.float32)
    y = (rng.random((7, 31)) > 0.5).astype(np.float32)
    ref = spd.cdist(x, y, "hamming")
    got = np.asarray(pairwise_distance(x, y, "hamming"))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_pairwise_hellinger(rng):
    x = rng.random((6, 13)).astype(np.float32)
    y = rng.random((5, 13)).astype(np.float32)
    x /= x.sum(1, keepdims=True)
    y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, "hellinger"))
    ref = np.sqrt(np.maximum(1.0 - np.sqrt(x[:, None, :] * y[None, :, :]).sum(-1), 0))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_pairwise_jensenshannon(rng):
    x = rng.random((5, 11)).astype(np.float32) + 1e-3
    y = rng.random((4, 11)).astype(np.float32) + 1e-3
    x /= x.sum(1, keepdims=True)
    y /= y.sum(1, keepdims=True)
    ref = spd.cdist(x.astype(np.float64), y.astype(np.float64), "jensenshannon")
    got = np.asarray(pairwise_distance(x, y, "jensenshannon"))
    # our formulation: sqrt(0.5*(KL(x||m)+KL(y||m))); scipy: sqrt(JSD) with same base
    np.testing.assert_allclose(got / np.sqrt(2.0), ref / np.sqrt(2.0), rtol=5e-3, atol=5e-3)


def test_inner_product(rng):
    x = rng.standard_normal((8, 16)).astype(np.float32)
    y = rng.standard_normal((9, 16)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, "inner_product"))
    np.testing.assert_allclose(got, x @ y.T, rtol=1e-5, atol=1e-5)


def test_pairwise_self(rng):
    x = rng.standard_normal((20, 6)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, None, "sqeuclidean"))
    np.testing.assert_allclose(np.diag(got), np.zeros(20), atol=1e-4)


def test_pairwise_tiled_padding(rng):
    # length not a multiple of tile → padding path
    x = rng.standard_normal((7, 5)).astype(np.float32)
    y = rng.standard_normal((103, 5)).astype(np.float32)
    ref = spd.cdist(x, y, "cityblock")
    got = np.asarray(pairwise_distance(x, y, "l1", tile=16))
    assert got.shape == (7, 103)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_fused_l2_nn(rng):
    x = rng.standard_normal((50, 12)).astype(np.float32)
    y = rng.standard_normal((77, 12)).astype(np.float32)
    d2 = spd.cdist(x, y, "sqeuclidean")
    val, idx = fused_l2_nn(x, y, tile=16)
    np.testing.assert_array_equal(np.asarray(idx), d2.argmin(1))
    np.testing.assert_allclose(np.asarray(val), d2.min(1), rtol=1e-4, atol=1e-4)


def test_fused_l2_nn_sqrt(rng):
    x = rng.standard_normal((10, 4)).astype(np.float32)
    y = rng.standard_normal((33, 4)).astype(np.float32)
    d = spd.cdist(x, y, "euclidean")
    val, idx = fused_l2_nn(x, y, sqrt=True, tile=8)
    np.testing.assert_allclose(np.asarray(val), d.min(1), rtol=1e-4, atol=1e-4)
    assert np.asarray(fused_l2_nn_argmin(x, y, tile=8)).tolist() == d.argmin(1).tolist()


def test_distance_type_enum():
    assert DistanceType.L2Expanded.value == "sqeuclidean"
    got = pairwise_distance(np.eye(3, dtype=np.float32), None, DistanceType.L1)
    np.testing.assert_allclose(np.asarray(got)[0, 1], 2.0)
