"""lockdep runtime tests: wrapper semantics, the order graph, inversion
injection, hold-time metrics — and the zero-inversion gate over the four
threaded suites (slow; ``scripts/tpu_jobs_r18.sh`` stages it on real
hardware).

Also pins satellite #1 of the racelint PR: counter increments stay exact
under thread contention with the instrumented stack armed.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from raft_tpu.core import lockdep
from raft_tpu.obs.metrics import MetricRegistry, set_registry
from raft_tpu.serve.metrics import ServingMetrics


@pytest.fixture()
def fresh_registry():
    """Swap in an empty process registry so metric assertions are exact."""
    reg = MetricRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


# -- wrapper semantics --------------------------------------------------


def test_disabled_wrappers_are_passthrough():
    lockdep.reset()
    a = lockdep.lock("T.a")
    assert not lockdep.enabled() or True  # env may arm the session
    with a:
        assert a.locked()
    assert not a.locked()


def test_edges_record_nesting_order(lockdep_enabled):
    a, b = lockdep.lock("T.a"), lockdep.lock("T.b")
    with a:
        assert lockdep.held() == ["T.a"]
        with b:
            assert lockdep.held() == ["T.a", "T.b"]
    assert lockdep.held() == []
    assert ("T.a", "T.b") in lockdep.edges()
    assert ("T.b", "T.a") not in lockdep.edges()
    assert lockdep.inversions() == []


def test_rlock_reentry_adds_no_self_edge(lockdep_enabled):
    r = lockdep.rlock("T.r")
    with r:
        with r:
            assert lockdep.held() == ["T.r", "T.r"]
    assert ("T.r", "T.r") not in lockdep.edges()
    assert lockdep.inversions() == []


def test_condition_wait_releases_the_hold(lockdep_enabled):
    cond = lockdep.condition("T.cond")
    ready = []

    def producer():
        with cond:
            ready.append(1)
            cond.notify_all()

    with cond:
        t = threading.Thread(target=producer)
        t.start()
        # wait() must release T.cond or the producer deadlocks here
        assert cond.wait_for(lambda: ready, timeout=5.0)
    t.join(5.0)
    assert ready and lockdep.held() == []


# -- inversion detection ------------------------------------------------


def test_inversion_injection_ab_then_ba(lockdep_enabled, fresh_registry):
    a, b = lockdep.lock("T.a"), lockdep.lock("T.b")
    with a:
        with b:
            pass

    def reversed_order():
        with b:
            with a:
                pass

    t = threading.Thread(target=reversed_order, name="inverter")
    t.start()
    t.join(5.0)
    inv = lockdep.inversions()
    assert len(inv) == 1
    assert inv[0]["acquiring"] == "T.a"
    assert inv[0]["while_holding"] == "T.b"
    assert inv[0]["thread"] == "inverter"
    rep = lockdep.report()
    assert rep["inversion_total"] == 1
    assert "T.a -> T.b" in rep["edges"]
    c = fresh_registry.counter("raft_lockdep_inversions_total")
    assert c.value() == 1.0


def test_inversion_counted_once_not_per_reacquire(lockdep_enabled):
    a, b = lockdep.lock("T.a2"), lockdep.lock("T.b2")
    with a, b:
        pass
    for _ in range(3):
        with b, a:
            pass
    assert len(lockdep.inversions()) == 1


# -- hold-time metrics --------------------------------------------------


def test_hold_seconds_histogram_and_blocking_flag(lockdep_enabled,
                                                  fresh_registry):
    prev = lockdep.hold_threshold_s(0.01)
    try:
        lk = lockdep.lock("T.slow")
        with lk:
            time.sleep(0.03)
        with lk:
            pass
    finally:
        lockdep.hold_threshold_s(prev)
    hist = fresh_registry.get("raft_lockdep_hold_seconds")
    # two completed holds observed, one of them over the threshold
    assert hist is not None and hist.count(lock="T.slow") == 2
    blocking = fresh_registry.counter("raft_lockdep_blocking_holds_total")
    assert blocking.value(lock="T.slow") == 1.0


# -- satellite: counters stay exact under contention --------------------


def test_obs_counter_exact_under_threads(lockdep_enabled, fresh_registry):
    c = fresh_registry.counter("t_hammer_total")
    n_threads, n_inc = 8, 2500

    def hammer():
        for _ in range(n_inc):
            c.inc()

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert c.value() == float(n_threads * n_inc)


def test_serving_metrics_count_exact_under_threads(lockdep_enabled):
    m = ServingMetrics(registry=MetricRegistry())
    n_threads, n_inc = 8, 2000

    def hammer():
        for _ in range(n_inc):
            m.count("submitted")
            m.observe_latency(1.0)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    snap = m.snapshot()
    assert snap["submitted"] == n_threads * n_inc
    assert snap["completed"] == n_threads * n_inc


# -- the gate: threaded suites run inversion-free -----------------------


@pytest.mark.slow
def test_threaded_suites_zero_inversions(tmp_path):
    """Run the four threaded suites with lockdep armed; the session
    report must show zero lock-order inversions.  This is the runtime
    complement of ``tests/test_racelint.py``'s zero-active tree gate."""
    report = tmp_path / "lockdep_report.json"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               RAFT_LOCKDEP="1",
               RAFT_LOCKDEP_REPORT=str(report))
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         "-m", "not slow",
         "tests/test_serve_lifecycle.py", "tests/test_compaction.py",
         "tests/test_replication.py", "tests/test_fleet.py"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=840)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    census = json.loads(report.read_text())
    assert census["enabled"] is True
    assert census["inversions"] == [], census["inversions"]
    assert census["inversion_total"] == 0
    # the graph actually observed the stack (not a vacuous pass)
    assert census["edges"], "no lock-order edges recorded — lockdep unarmed?"
