"""Shared crash/recover driver for ``tests/test_durability.py``.

Runs in two roles with the SAME deterministic op schedule:

* **child** (``python tests/_durability_driver.py``, env ``DUR_ROOT`` +
  ``DUR_SITE``): builds the seed index, publishes the initial snapshot,
  arms a ``crash`` fault at ``DUR_SITE``, then walks the op list writing
  an atomically-renamed progress marker *before* each op.  The armed
  site kills the process mid-operation (``os._exit(137)`` — nothing
  flushes, nothing unwinds), exactly like ``kill -9``.
* **parent** (imported by the test): replays the same schedule against a
  fault-free store to produce the expected-state ladder
  ``states[m]`` = index after the first ``m`` ops, which the recovered
  child store is compared against bit-for-bit.

Keeping both roles in one module is the determinism guarantee: the
child's mutations and the parent's expectations are the same code.
"""

from __future__ import annotations

import os

import numpy as np

N, D = 192, 8
OP_COUNT = 7


def initial_tombstoned():
    from raft_tpu.neighbors import ivf_flat, mutation

    rng = np.random.default_rng(7)
    db = rng.standard_normal((N, D)).astype(np.float32)
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=4, seed=0))
    return mutation.delete(idx, [2], id_space=2048)


def op_list():
    """The mutation schedule — hits every crash site: ``extend``/
    ``delete`` (wal_append + extend sites), ``compact`` (compact site),
    ``snapshot`` (snapshot + rename sites)."""
    orng = np.random.default_rng(11)
    ops = [
        ("extend", (orng.standard_normal((16, D)).astype(np.float32),)),
        ("delete", ([5, 9],)),
        ("snapshot", ()),
        ("extend", (orng.standard_normal((8, D)).astype(np.float32),)),
        ("compact", ()),
        ("delete", ([30, 31],)),
        ("snapshot", ()),
    ]
    assert len(ops) == OP_COUNT
    return ops


def apply_op(store, op, args):
    if op == "extend":
        store.extend(*args)
    elif op == "delete":
        store.delete(*args)
    elif op == "compact":
        store.compact()
    elif op == "snapshot":
        store.snapshot()
    else:  # pragma: no cover — schedule typo guard
        raise ValueError(op)


def expected_states(root):
    """``states[m]`` = the committed index after ops ``[0, m)`` (so
    ``states[0]`` is the freshly-created store), built with NO faults."""
    from raft_tpu.neighbors import wal

    store = wal.DurableStore.create(root, initial_tombstoned())
    states = [store.index]
    for op, args in op_list():
        apply_op(store, op, args)
        states.append(store.index)
    store.close()
    return states


def child_main():
    from raft_tpu.neighbors import wal
    from raft_tpu.serve.faults import FaultInjector

    root = os.environ["DUR_ROOT"]
    site = os.environ["DUR_SITE"]
    store = wal.DurableStore.create(root, initial_tombstoned())
    # arm AFTER the initial snapshot: the drill is crashing a healthy
    # store mid-mutation, not failing to be born
    store.faults = FaultInjector().arm(site, "crash")
    marker = os.path.join(root, "progress")
    for m, (op, args) in enumerate(op_list()):
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(m))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, marker)
        apply_op(store, op, args)
    raise SystemExit(3)  # fault never fired — the parent asserts 137


if __name__ == "__main__":
    # mirror conftest.py: the axon PJRT plugin ignores JAX_PLATFORMS, so
    # force CPU programmatically before backends initialize, with the
    # same 8-virtual-device topology the parent builds under
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = \
            (_flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    child_main()
