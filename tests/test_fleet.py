"""Pod-scale serving fleet tests (ISSUE 16).

The acceptance criteria these pin:

* the sharded fan-out searcher is **bit-identical** — values AND ids —
  to the single-device :func:`serve.make_searcher` reference at mesh
  widths 2, 4 and 8, for every fleet-enabled family (brute_force exact,
  ivf_flat, ivf_rabitq) and both metric families, including a
  Tombstoned/filtered query routed through the fan-out;
* :func:`plan_placement` enforces anti-affinity — a shard's standby
  never lands on its primary's host — with deterministic round-robin
  load spread;
* :func:`init_distributed` rejects an ``axis_shape`` that does not
  cover the visible devices, and ``FleetServer`` refuses to serve when
  the comms selftest battery fails (broken-collective startup gate);
* the replica group serves through the router bit-identically to a
  direct index ``search()``, sheds from a killed replica to survivors,
  and exposes per-replica metrics under an injected ``replica`` label;
* :class:`FleetDurability` gives every shard a primary store + WAL
  shipped to anti-affinity standbys, and ``promote_expired`` fails over
  on lease expiry.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu.comms import Comms, init_distributed, verify_comms
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_rabitq, mutation
from raft_tpu.serve import (FleetRouter, FleetServer, QueueFull, ReplicaDead,
                            ReplicationConfig, ServerConfig,
                            make_fleet_searcher, make_searcher,
                            plan_placement, shard_sub_indexes)
from raft_tpu.serve.searchers import BruteForceSearchParams

pytestmark = pytest.mark.usefixtures("devices")

K = 7
WIDTHS = (2, 4, 8)


def _mesh(devices, width: int) -> Mesh:
    return Mesh(np.asarray(devices[:width]), ("shard",))


def _eq(got, want):
    dv, iv = got
    rv, ri = want
    np.testing.assert_array_equal(np.asarray(jax.device_get(dv)),
                                  np.asarray(jax.device_get(rv)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(iv)),
                                  np.asarray(jax.device_get(ri)))


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    db = rng.standard_normal((600, 32)).astype(np.float32)
    # queries off the db manifold so no distance ties hide an id swap
    q = (1.3 * rng.standard_normal((9, 32))).astype(np.float32)
    return db, q


@pytest.fixture(scope="module")
def flat_index(data):
    db, _ = data
    return ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=13))


@pytest.fixture(scope="module")
def rabitq_index(data):
    db, _ = data
    return ivf_rabitq.build(db, ivf_rabitq.IvfRabitqIndexParams(n_lists=13))


# ---------------------------------------------------------------------------
# bit-identity across mesh widths — the fan-out contract


@pytest.mark.parametrize("width", WIDTHS)
def test_brute_fanout_bit_identity(data, devices, width):
    db, q = data
    db = db[:257]  # odd row count: pad lanes exercised on every width
    p = BruteForceSearchParams(tile=64)
    fn, ops = make_fleet_searcher(db, K, p, mesh=_mesh(devices, width))
    rfn, rops = make_searcher(db, K, p)
    _eq(fn(q, *ops), rfn(q, *rops))


def test_brute_fanout_inner_product(data, devices):
    db, q = data
    p = BruteForceSearchParams(metric="inner_product")
    fn, ops = make_fleet_searcher(db[:200], K, p, mesh=_mesh(devices, 4))
    rfn, rops = make_searcher(db[:200], K, p)
    _eq(fn(q, *ops), rfn(q, *rops))


@pytest.mark.parametrize("width", WIDTHS)
def test_ivf_flat_fanout_bit_identity(data, flat_index, devices, width):
    _, q = data
    p = ivf_flat.IvfFlatSearchParams(n_probes=5)
    fn, ops = make_fleet_searcher(flat_index, K, p,
                                  mesh=_mesh(devices, width))
    rfn, rops = make_searcher(flat_index, K, p)
    _eq(fn(q, *ops), rfn(q, *rops))


def test_ivf_flat_fanout_inner_product(data, devices):
    db, q = data
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(
        n_lists=13, metric="inner_product"))
    p = ivf_flat.IvfFlatSearchParams(n_probes=5)
    fn, ops = make_fleet_searcher(idx, K, p, mesh=_mesh(devices, 4))
    rfn, rops = make_searcher(idx, K, p)
    _eq(fn(q, *ops), rfn(q, *rops))


@pytest.mark.parametrize("width", WIDTHS)
def test_ivf_rabitq_fanout_bit_identity(data, rabitq_index, devices, width):
    _, q = data
    p = ivf_rabitq.IvfRabitqSearchParams(n_probes=5, rerank_k=24)
    fn, ops = make_fleet_searcher(rabitq_index, K, p,
                                  mesh=_mesh(devices, width))
    rfn, rops = make_searcher(rabitq_index, K, p)
    _eq(fn(q, *ops), rfn(q, *rops))


def test_tombstoned_query_through_fanout(data, flat_index, devices):
    """A deleted-rows view serves through the fan-out exactly as through
    the single-device searcher, and deleted ids never surface."""
    _, q = data
    dead = np.arange(0, 51)
    view = mutation.delete(flat_index, dead)
    p = ivf_flat.IvfFlatSearchParams(n_probes=5)
    fn, ops = make_fleet_searcher(view, K, p, mesh=_mesh(devices, 4))
    rfn, rops = make_searcher(view, K, p)
    got = fn(q, *ops)
    _eq(got, rfn(q, *rops))
    ids = np.asarray(jax.device_get(got[1]))
    assert not np.isin(ids[ids >= 0], dead).any()


def test_explicit_filter_ands_with_tombstones(data, flat_index, devices):
    _, q = data
    view = mutation.delete(flat_index, np.arange(0, 20))
    keep = np.ones(600, bool)
    keep[300:] = False
    p = ivf_flat.IvfFlatSearchParams(n_probes=5)
    fn, ops = make_fleet_searcher(view, K, p, mesh=_mesh(devices, 2),
                                  filter=keep)
    rfn, rops = make_searcher(view, K, p, filter=keep)
    got = fn(q, *ops)
    _eq(got, rfn(q, *rops))
    ids = np.asarray(jax.device_get(got[1]))
    live = ids[ids >= 0]
    assert (live >= 20).all() and (live < 300).all()


def test_effort_scale_parity_with_single_device(data, flat_index, devices):
    """Degraded tiers shard identically: the fleet at effort 0.5 matches
    the single-device searcher at effort 0.5 (fewer probes, same fold)."""
    _, q = data
    p = ivf_flat.IvfFlatSearchParams(n_probes=8)
    fn, ops = make_fleet_searcher(flat_index, K, p, mesh=_mesh(devices, 2),
                                  effort_scale=0.5)
    rfn, rops = make_searcher(flat_index, K, p, effort_scale=0.5)
    _eq(fn(q, *ops), rfn(q, *rops))


def test_fleet_rejects_unpinnable_modes(data, devices):
    db, _ = data
    mesh = _mesh(devices, 2)
    with pytest.raises(Exception, match="exact mode only"):
        make_fleet_searcher(db, K, BruteForceSearchParams(mode="fast"),
                            mesh=mesh)
    with pytest.raises(Exception, match="effort_scale"):
        make_fleet_searcher(db, K, None, mesh=mesh, effort_scale=1.5)
    with pytest.raises(Exception, match="axis"):
        make_fleet_searcher(db, K, None, mesh=mesh, axis="replica")


# ---------------------------------------------------------------------------
# placement — anti-affinity policy


def test_placement_anti_affinity_and_round_robin():
    plan = plan_placement(4, ["a", "b", "c"], n_standbys=2)
    plan.validate()
    assert [a.primary for a in plan.assignments] == ["a", "b", "c", "a"]
    for a in plan.assignments:
        assert a.primary not in a.standbys
        assert len(set(a.standbys)) == 2
    # standby load spreads: no host hoards followers
    counts = [len(plan.standbys_on(h)) for h in plan.hosts]
    assert max(counts) - min(counts) <= 1
    assert plan.primaries_on("a") == [0, 3]
    # deterministic: same inputs, same plan
    assert plan == plan_placement(4, ["a", "b", "c"], n_standbys=2)


def test_placement_rejects_impossible_topologies():
    with pytest.raises(Exception):
        plan_placement(2, ["a"], n_standbys=1)  # nowhere anti-affine
    with pytest.raises(Exception):
        plan_placement(2, ["a", "a"], n_standbys=1)  # duplicate host
    with pytest.raises(Exception):
        plan_placement(0, ["a"])  # no shards


# ---------------------------------------------------------------------------
# bootstrap validation + the broken-collective startup gate


def test_init_distributed_rejects_partial_device_cover():
    with pytest.raises(ValueError, match="must use every visible device"):
        init_distributed(axis_shape=(3,))
    with pytest.raises(Exception, match="axis_shape"):
        init_distributed(axis_shape=(2, 4))  # one axis name, two dims
    comms = init_distributed(axis_shape=(len(jax.devices()),))
    assert comms.mesh.devices.size == len(jax.devices())


def test_verify_comms_passes_on_healthy_mesh(devices):
    results = verify_comms(Comms(_mesh(devices, 2), "shard"))
    assert results and all(results.values())


def test_fleet_server_refuses_broken_collective(data, devices, monkeypatch):
    from raft_tpu.comms import selftest

    db, _ = data
    monkeypatch.setattr(selftest, "run_all",
                        lambda comms: {"allgather": False, "allreduce": True})
    with pytest.raises(RuntimeError, match="refusing to serve"):
        FleetServer(db[:64], k=3, mesh=_mesh(devices, 2))


# ---------------------------------------------------------------------------
# router — duck-typed fakes (no jax in the loop)


class _FakeReplica:
    def __init__(self, name, depth=0, fail=None):
        self.name, self.alive, self.depth, self.fail = name, True, depth, fail
        self.served = 0

    def load(self):
        return self.depth

    def search(self, queries, k=None, deadline_ms=None):
        if not self.alive:
            raise ReplicaDead(self.name)
        if self.fail is not None:
            raise self.fail
        self.served += 1
        return ("d", self.name)


def test_router_prefers_least_loaded():
    a, b = _FakeReplica("a", depth=5), _FakeReplica("b", depth=0)
    r = FleetRouter([a, b])
    assert r.search(None)[1] == "b"


def test_router_spills_queue_full_to_peer():
    a = _FakeReplica("a", depth=0, fail=QueueFull("full"))
    b = _FakeReplica("b", depth=9)
    r = FleetRouter([a, b])
    assert r.search(None)[1] == "b"  # spilled off the saturated favorite


def test_router_sheds_dead_replica_and_raises_when_none_left():
    a, b = _FakeReplica("a"), _FakeReplica("b", depth=3)
    a.alive = False
    r = FleetRouter([a, b])
    assert r.search(None)[1] == "b"
    assert [x.name for x in r.live()] == ["b"]
    b.alive = False
    with pytest.raises(ReplicaDead):
        r.search(None)


# ---------------------------------------------------------------------------
# the fleet server end-to-end (manual drive — no dispatch threads)


def test_fleet_server_end_to_end(data, flat_index, devices, tmp_path):
    db, q = data
    mesh = _mesh(devices, 4)
    p = ivf_flat.IvfFlatSearchParams(n_probes=5)
    fleet = FleetServer(flat_index, k=K, params=p, mesh=mesh,
                        n_replicas=2, selftest=False,
                        config=ServerConfig(ladder=(16,)))
    assert fleet.n_shards == 4

    # routed search == direct index search, values AND ids
    d_ref, i_ref = ivf_flat.search(flat_index, q, K, p)
    _eq(fleet.search(q), (d_ref, i_ref))

    # kill drill: router sheds to the survivor, results unchanged
    fleet.kill_replica("r0")
    assert [r.name for r in fleet.router.live()] == ["r1"]
    _eq(fleet.search(q), (d_ref, i_ref))
    assert "r0: dead" in fleet.describe()

    # scrape parses, and per-replica families carry the injected label
    from raft_tpu.obs.prometheus import parse_text
    samples = parse_text(fleet.prometheus_text())
    assert samples["raft_fleet_shards"][0][1] == 4.0
    reps = {lab["replica"]
            for lab, _ in samples["raft_serve_completed_total"]}
    assert reps == {"r0", "r1"}
    fleet.stop()


def test_fleet_durability_ship_and_promote(flat_index, devices, tmp_path):
    mesh = _mesh(devices, 2)
    fleet = FleetServer(flat_index, k=K,
                        params=ivf_flat.IvfFlatSearchParams(n_probes=5),
                        mesh=mesh, selftest=False,
                        config=ServerConfig(ladder=(16,)))
    dur = fleet.attach_durability(
        tmp_path, ["hostA", "hostB", "hostC"], n_standbys=2,
        config=ReplicationConfig(ack_mode="async", lease_s=3.0))
    assert len(dur.shards) == 2
    for sh in dur.shards:
        assert len(sh.standbys) == 2
        assert sh.assignment.primary not in sh.assignment.standbys
    dur.pump()

    # a durable mutation on shard 0 ships to both of its standbys
    s0 = dur.shards[0].store
    new = np.full((3, 32), 0.5, np.float32)
    s0.extend(new, np.array([9000, 9001, 9002]))
    dur.pump()
    assert all(st.applied == s0.wal_lsn
               for st in dur.shards[0].standbys)
    assert all(lag == 0 for shard in dur.lag().values()
               for lag in shard.values())

    # lease expiry: every shard promotes exactly one standby
    now = fleet.replicas[0].server.clock() + 100.0
    promoted = fleet.promote_expired(now)
    assert promoted == [0, 1]
    for sh in dur.shards:
        serving = [st for st in sh.standbys if st.promoted]
        assert len(serving) == 1 and serving[0].is_serving
    fleet.stop()


def test_shard_sub_indexes_cover_the_whole_index(flat_index):
    subs = shard_sub_indexes(flat_index, 4)
    assert len(subs) == 4
    got = np.sort(np.concatenate(
        [np.asarray(jax.device_get(s.ids)).ravel() for s in subs]))
    want = np.sort(np.asarray(jax.device_get(flat_index.ids)).ravel())
    np.testing.assert_array_equal(got[got >= 0], want[want >= 0])
    # each sub-index is self-contained: its centroid table matches its
    # own list count, so durable extend works per shard
    for s in subs:
        assert s.centroids.shape[0] == s.data.shape[0]


def test_brute_sub_indexes_roundtrip(data):
    db, q = data
    subs = shard_sub_indexes(db[:100], 4)
    stacked = np.concatenate([np.asarray(s) for s in subs])
    np.testing.assert_array_equal(stacked, db[:100])
