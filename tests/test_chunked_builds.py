"""Chunked (out-of-core) and truly-distributed index builds.

VERDICT r2 missing #2: builds must not be whole-dataset-resident
single-device programs.  These tests check (a) chunked streaming builds
produce the same layout/quality as one-shot builds, (b) the per-chunk
device programs provably never need the whole dataset on device
(``core.memory.analyze_memory`` assertion), and (c) the sharded builds
construct each shard's index from its own rows (global ids correct,
search merges exactly).  Reference analog: the SNMG build model,
``/root/reference/cpp/include/raft/core/device_resources_snmg.hpp:36-154``.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cluster.kmeans import capped_assign, capped_assign_room
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.neighbors._packing import pack_lists, scatter_append
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((4096, 32)).astype(np.float32)
    q = rng.standard_normal((128, 32)).astype(np.float32)
    _, gt = brute_force.knn(q, x, 10)
    return x, q, np.asarray(gt)


class TestScatterAppend:
    def test_matches_pack_lists_one_shot(self, rng):
        n, L, cap = 500, 8, 100
        labels = rng.integers(0, L, n).astype(np.int32)
        vals = rng.standard_normal((n, 4)).astype(np.float32)
        ids = np.arange(n, dtype=np.int32)
        (ref_v, ref_i), ref_c = pack_lists(
            jnp.asarray(labels), (jnp.asarray(vals), jnp.asarray(ids)),
            n_lists=L, cap=cap, fills=(0.0, -1))
        slab_v = jnp.zeros((L, cap, 4), jnp.float32)
        slab_i = jnp.full((L, cap), -1, jnp.int32)
        counts = jnp.zeros((L,), jnp.int32)
        for lo in range(0, n, 128):
            hi = min(n, lo + 128)
            (slab_v, slab_i), counts = scatter_append(
                (slab_v, slab_i), counts, jnp.asarray(labels[lo:hi]),
                (jnp.asarray(vals[lo:hi]), jnp.asarray(ids[lo:hi])),
                n_lists=L, cap=cap)
        np.testing.assert_array_equal(np.asarray(counts), np.asarray(ref_c))
        # same rows in the same per-list order (stream order == row order)
        np.testing.assert_array_equal(np.asarray(slab_i), np.asarray(ref_i))
        np.testing.assert_allclose(np.asarray(slab_v), np.asarray(ref_v))

    def test_overflow_rows_dropped(self):
        labels = jnp.zeros((10,), jnp.int32)
        slab = jnp.full((1, 4), -1, jnp.int32)
        counts = jnp.zeros((1,), jnp.int32)
        (slab,), counts = scatter_append(
            (slab,), counts, labels, (jnp.arange(10, dtype=jnp.int32),),
            n_lists=1, cap=4)
        assert int(counts[0]) == 4
        np.testing.assert_array_equal(np.asarray(slab[0]), [0, 1, 2, 3])


class TestCappedAssignRoom:
    def test_matches_static_cap(self, rng):
        x = jnp.asarray(rng.standard_normal((256, 8)).astype(np.float32))
        c = jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32))
        l1, c1 = capped_assign(x, c, 32)
        l2, c2 = capped_assign_room(x, c, jnp.full((16,), 32, jnp.int32))
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))

    def test_respects_partial_room(self, rng):
        x = jnp.asarray(rng.standard_normal((64, 8)).astype(np.float32))
        c = jnp.asarray(rng.standard_normal((4, 8)).astype(np.float32))
        room = jnp.asarray([0, 64, 64, 64], jnp.int32)
        labels, counts = capped_assign_room(x, c, room)
        assert int(counts[0]) == 0
        assert not bool(jnp.any(labels == 0))


class TestChunkedBuilds:
    def test_ivf_flat_chunked_quality(self, data):
        x, q, gt = data
        p = ivf_flat.IvfFlatIndexParams(n_lists=32, seed=3)
        ref = ivf_flat.build(x, p)
        idx = ivf_flat.build_chunked(x, p, chunk_rows=700)
        assert idx.size == x.shape[0]
        sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
        _, ir = ivf_flat.search(ref, q, 10, sp)
        _, ic = ivf_flat.search(idx, q, 10, sp)
        r_ref = float(neighborhood_recall(np.asarray(ir), gt))
        r_chk = float(neighborhood_recall(np.asarray(ic), gt))
        assert r_chk >= r_ref - 0.05  # same quality within noise

    def test_ivf_pq_chunked_quality(self, data):
        x, q, gt = data
        p = ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=16, seed=3)
        ref = ivf_pq.build(x, p)
        idx = ivf_pq.build_chunked(x, p, chunk_rows=700)
        assert idx.size == x.shape[0]
        sp = ivf_pq.IvfPqSearchParams(n_probes=16)
        _, ir = ivf_pq.search(ref, q, 10, sp)
        _, ic = ivf_pq.search(idx, q, 10, sp)
        r_ref = float(neighborhood_recall(np.asarray(ir), gt))
        r_chk = float(neighborhood_recall(np.asarray(ic), gt))
        assert r_chk >= r_ref - 0.05

    def test_ivf_pq_chunk_program_memory_budget(self):
        """The streamed build's device programs must be independent of the
        dataset size: at DEEP-1M-class shapes the chunk working set (assign
        + encode + scatter, slab excluded via donation aliasing) is < 1% of
        the f32 dataset — the larger-than-HBM buildability proof (VERDICT
        r2 next #3)."""
        from raft_tpu.core.memory import analyze_memory
        from raft_tpu.cluster.kmeans import capped_assign_room as car

        n, d = 1_000_000, 96          # virtual DEEP-1M: 384 MB f32 on host
        dataset_bytes = n * d * 4
        L, capr, m, chunk = 1024, 1.5, 24, 4096
        cap = int(np.ceil(capr * n / L))
        cents = jnp.zeros((L, d), jnp.float32)
        xc = jnp.zeros((chunk, d), jnp.float32)
        room = jnp.full((L,), cap, jnp.int32)
        ma_assign = analyze_memory(car, xc, cents, room)
        # PQ slabs: codes + norms + ids — the only dataset-proportional state
        slab_bytes = L * cap * (m + 4 + 4)
        codes = jnp.zeros((L, cap, m), jnp.uint8)
        cnorms = jnp.zeros((L, cap), jnp.float32)
        ids = jnp.full((L, cap), -1, jnp.int32)
        counts = jnp.zeros((L,), jnp.int32)
        labels = jnp.zeros((chunk,), jnp.int32)
        pay = (jnp.zeros((chunk, m), jnp.uint8), jnp.zeros((chunk,), jnp.float32),
               jnp.zeros((chunk,), jnp.int32))
        ma_scatter = analyze_memory(
            scatter_append, (codes, cnorms, ids), counts, labels, pay,
            n_lists=L, cap=cap)
        # donation must alias the slabs (in-place update, no 2× copy)
        assert ma_scatter.alias_size >= slab_bytes * 0.9
        # chunk-step working set (minus the donated slab) ≪ dataset: the
        # device never needs more than slab + O(chunk·(L+d)) regardless of n
        assign_peak = ma_assign.peak_estimate
        scatter_extra = ma_scatter.peak_estimate - ma_scatter.alias_size
        assert assign_peak + scatter_extra < dataset_bytes * 0.2, (
            f"chunk programs need {assign_peak + scatter_extra} bytes vs "
            f"dataset {dataset_bytes}")
        # and the PQ slab itself is ~8× smaller than the f32 dataset
        # (32 bytes/slot incl. norm+id vs 384 bytes/vector, ×1.5 padding)
        assert slab_bytes < dataset_bytes / 4

    def test_ivf_pq_chunked_accepts_memmap(self, tmp_path, data):
        x, q, gt = data
        f = tmp_path / "db.npy"
        np.save(f, x)
        mm = np.load(f, mmap_mode="r")
        idx = ivf_pq.build_chunked(
            mm, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=16, seed=0),
            chunk_rows=1024)
        assert idx.size == x.shape[0]


class TestDistributedSharded:
    def test_ivf_flat_sharded_builds_locally(self, data, mesh8):
        x, q, gt = data
        p = ivf_flat.IvfFlatIndexParams(n_lists=64, seed=5)
        idx = ivf_flat.build_sharded(x, mesh8, p)
        assert idx.size == x.shape[0]
        # shard s's lists may only hold shard s's global row range
        per = x.shape[0] // 8
        ll = idx.n_lists // 8
        ids = np.asarray(idx.ids)
        for s in range(8):
            blk = ids[s * ll:(s + 1) * ll]
            valid = blk[blk >= 0]
            assert valid.min() >= s * per and valid.max() < (s + 1) * per
        _, i2 = ivf_flat.search_sharded(
            idx, q, 10, ivf_flat.IvfFlatSearchParams(n_probes=8), mesh=mesh8)
        assert float(neighborhood_recall(np.asarray(i2), gt)) > 0.8

    def test_ivf_pq_sharded_builds_locally(self, data, mesh8):
        x, q, gt = data
        p = ivf_pq.IvfPqIndexParams(n_lists=64, pq_dim=16, seed=5)
        idx = ivf_pq.build_sharded(x, mesh8, p)
        assert idx.size == x.shape[0]
        per = x.shape[0] // 8
        ll = idx.n_lists // 8
        ids = np.asarray(idx.ids)
        for s in range(8):
            blk = ids[s * ll:(s + 1) * ll]
            valid = blk[blk >= 0]
            assert valid.min() >= s * per and valid.max() < (s + 1) * per
        _, i2 = ivf_pq.search_sharded(
            idx, q, 10, ivf_pq.IvfPqSearchParams(n_probes=8), mesh=mesh8)
        # PQ-compressed recall on gaussian data is modest; refine-level
        # checks live in test_ivf_pq.py — here assert the merge works
        assert float(neighborhood_recall(np.asarray(i2), gt)) > 0.3

    def test_cagra_sharded_single_program(self, data, mesh8):
        x, q, gt = data
        p = cagra.CagraIndexParams(
            intermediate_graph_degree=32, graph_degree=16, n_routers=32)
        idx = cagra.build_sharded(x, mesh8, p)
        assert idx.datasets.shape == (8, x.shape[0] // 8, x.shape[1])
        d, i = cagra.search_sharded(
            idx, q, 10, cagra.CagraSearchParams(itopk_size=32), mesh=mesh8)
        assert float(neighborhood_recall(np.asarray(i), gt)) > 0.9


class TestDataParallelSearch:
    """2-D (data x shard) mesh: queries partitioned over the data axis,
    index over the shard axis — the hybrid ICI/DCN composition."""

    def test_ivf_flat_2d(self, data, mesh2x4):
        x, q, gt = data
        idx = ivf_flat.build_sharded(x, mesh2x4,
                                     ivf_flat.IvfFlatIndexParams(n_lists=32, seed=5))
        sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
        _, i1 = ivf_flat.search_sharded(idx, q, 10, sp, mesh=mesh2x4)
        _, i2 = ivf_flat.search_sharded(idx, q, 10, sp, mesh=mesh2x4,
                                        data_axis="data")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_ivf_pq_2d(self, data, mesh2x4):
        x, q, gt = data
        idx = ivf_pq.build_sharded(
            x, mesh2x4, ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=16, seed=5))
        sp = ivf_pq.IvfPqSearchParams(n_probes=8)
        _, i1 = ivf_pq.search_sharded(idx, q, 10, sp, mesh=mesh2x4)
        _, i2 = ivf_pq.search_sharded(idx, q, 10, sp, mesh=mesh2x4,
                                      data_axis="data")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_cagra_2d(self, data, mesh2x4):
        x, q, gt = data
        idx = cagra.build_sharded(x, mesh2x4, cagra.CagraIndexParams(
            intermediate_graph_degree=32, graph_degree=16, n_routers=32))
        sp = cagra.CagraSearchParams(itopk_size=32)
        _, i1 = cagra.search_sharded(idx, q, 10, sp, mesh=mesh2x4)
        _, i2 = cagra.search_sharded(idx, q, 10, sp, mesh=mesh2x4,
                                     data_axis="data")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestPrefetchChunks:
    def test_yields_all_rows_in_order(self, rng):
        from raft_tpu.neighbors._packing import prefetch_chunks
        x = rng.standard_normal((1000, 4)).astype(np.float32)
        seen = []
        for lo, hi, xc, idc in prefetch_chunks(x, 256):
            np.testing.assert_array_equal(xc, x[lo:hi])
            np.testing.assert_array_equal(idc, np.arange(lo, hi))
            seen.append((lo, hi))
        assert seen == [(0, 256), (256, 512), (512, 768), (768, 1000)]

    def test_custom_ids_pass_through(self, rng):
        from raft_tpu.neighbors._packing import prefetch_chunks
        x = rng.standard_normal((100, 4)).astype(np.float32)
        ids = np.arange(1000, 1100, dtype=np.int32)
        got = [idc for *_, idc in prefetch_chunks(x, 64, ids)]
        np.testing.assert_array_equal(np.concatenate(got), ids)
