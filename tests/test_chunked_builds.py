"""Chunked (out-of-core) and truly-distributed index builds.

VERDICT r2 missing #2: builds must not be whole-dataset-resident
single-device programs.  These tests check (a) chunked streaming builds
produce the same layout/quality as one-shot builds, (b) the per-chunk
device programs provably never need the whole dataset on device
(``core.memory.analyze_memory`` assertion), and (c) the sharded builds
construct each shard's index from its own rows (global ids correct,
search merges exactly).  Reference analog: the SNMG build model,
``/root/reference/cpp/include/raft/core/device_resources_snmg.hpp:36-154``.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cluster.kmeans import capped_assign, capped_assign_room
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.neighbors._packing import pack_lists, scatter_append
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((4096, 32)).astype(np.float32)
    q = rng.standard_normal((128, 32)).astype(np.float32)
    _, gt = brute_force.knn(q, x, 10)
    return x, q, np.asarray(gt)


class TestScatterAppend:
    def test_matches_pack_lists_one_shot(self, rng):
        n, L, cap = 500, 8, 100
        labels = rng.integers(0, L, n).astype(np.int32)
        vals = rng.standard_normal((n, 4)).astype(np.float32)
        ids = np.arange(n, dtype=np.int32)
        (ref_v, ref_i), ref_c = pack_lists(
            jnp.asarray(labels), (jnp.asarray(vals), jnp.asarray(ids)),
            n_lists=L, cap=cap, fills=(0.0, -1))
        slab_v = jnp.zeros((L, cap, 4), jnp.float32)
        slab_i = jnp.full((L, cap), -1, jnp.int32)
        counts = jnp.zeros((L,), jnp.int32)
        for lo in range(0, n, 128):
            hi = min(n, lo + 128)
            (slab_v, slab_i), counts = scatter_append(
                (slab_v, slab_i), counts, jnp.asarray(labels[lo:hi]),
                (jnp.asarray(vals[lo:hi]), jnp.asarray(ids[lo:hi])),
                n_lists=L, cap=cap)
        np.testing.assert_array_equal(np.asarray(counts), np.asarray(ref_c))
        # same rows in the same per-list order (stream order == row order)
        np.testing.assert_array_equal(np.asarray(slab_i), np.asarray(ref_i))
        np.testing.assert_allclose(np.asarray(slab_v), np.asarray(ref_v))

    def test_overflow_rows_dropped(self):
        labels = jnp.zeros((10,), jnp.int32)
        slab = jnp.full((1, 4), -1, jnp.int32)
        counts = jnp.zeros((1,), jnp.int32)
        (slab,), counts = scatter_append(
            (slab,), counts, labels, (jnp.arange(10, dtype=jnp.int32),),
            n_lists=1, cap=4)
        assert int(counts[0]) == 4
        np.testing.assert_array_equal(np.asarray(slab[0]), [0, 1, 2, 3])


class TestCappedAssignRoom:
    def test_matches_static_cap(self, rng):
        x = jnp.asarray(rng.standard_normal((256, 8)).astype(np.float32))
        c = jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32))
        l1, c1 = capped_assign(x, c, 32)
        l2, c2 = capped_assign_room(x, c, jnp.full((16,), 32, jnp.int32))
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))

    def test_respects_partial_room(self, rng):
        x = jnp.asarray(rng.standard_normal((64, 8)).astype(np.float32))
        c = jnp.asarray(rng.standard_normal((4, 8)).astype(np.float32))
        room = jnp.asarray([0, 64, 64, 64], jnp.int32)
        labels, counts = capped_assign_room(x, c, room)
        assert int(counts[0]) == 0
        assert not bool(jnp.any(labels == 0))


class TestChunkedBuilds:
    def test_ivf_flat_chunked_quality(self, data):
        x, q, gt = data
        p = ivf_flat.IvfFlatIndexParams(n_lists=32, seed=3)
        ref = ivf_flat.build(x, p)
        idx = ivf_flat.build_chunked(x, p, chunk_rows=700)
        assert idx.size == x.shape[0]
        sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
        _, ir = ivf_flat.search(ref, q, 10, sp)
        _, ic = ivf_flat.search(idx, q, 10, sp)
        r_ref = float(neighborhood_recall(np.asarray(ir), gt))
        r_chk = float(neighborhood_recall(np.asarray(ic), gt))
        assert r_chk >= r_ref - 0.05  # same quality within noise

    def test_ivf_pq_chunked_quality(self, data):
        x, q, gt = data
        p = ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=16, seed=3)
        ref = ivf_pq.build(x, p)
        idx = ivf_pq.build_chunked(x, p, chunk_rows=700)
        assert idx.size == x.shape[0]
        sp = ivf_pq.IvfPqSearchParams(n_probes=16)
        _, ir = ivf_pq.search(ref, q, 10, sp)
        _, ic = ivf_pq.search(idx, q, 10, sp)
        r_ref = float(neighborhood_recall(np.asarray(ir), gt))
        r_chk = float(neighborhood_recall(np.asarray(ic), gt))
        assert r_chk >= r_ref - 0.05

    def test_ivf_pq_chunk_program_memory_budget(self):
        """The streamed build's device programs must be independent of the
        dataset size: at DEEP-1M-class shapes the chunk working set (assign
        + encode + scatter, slab excluded via donation aliasing) is < 1% of
        the f32 dataset — the larger-than-HBM buildability proof (VERDICT
        r2 next #3)."""
        from raft_tpu.core.memory import analyze_memory
        from raft_tpu.cluster.kmeans import capped_assign_room as car

        n, d = 1_000_000, 96          # virtual DEEP-1M: 384 MB f32 on host
        dataset_bytes = n * d * 4
        L, capr, m, chunk = 1024, 1.5, 24, 4096
        cap = int(np.ceil(capr * n / L))
        cents = jnp.zeros((L, d), jnp.float32)
        xc = jnp.zeros((chunk, d), jnp.float32)
        room = jnp.full((L,), cap, jnp.int32)
        ma_assign = analyze_memory(car, xc, cents, room)
        # PQ slabs: codes + norms + ids — the only dataset-proportional state
        slab_bytes = L * cap * (m + 4 + 4)
        codes = jnp.zeros((L, cap, m), jnp.uint8)
        cnorms = jnp.zeros((L, cap), jnp.float32)
        ids = jnp.full((L, cap), -1, jnp.int32)
        counts = jnp.zeros((L,), jnp.int32)
        labels = jnp.zeros((chunk,), jnp.int32)
        pay = (jnp.zeros((chunk, m), jnp.uint8), jnp.zeros((chunk,), jnp.float32),
               jnp.zeros((chunk,), jnp.int32))
        ma_scatter = analyze_memory(
            scatter_append, (codes, cnorms, ids), counts, labels, pay,
            n_lists=L, cap=cap)
        # donation must alias the slabs (in-place update, no 2× copy)
        assert ma_scatter.alias_size >= slab_bytes * 0.9
        # chunk-step working set (minus the donated slab) ≪ dataset: the
        # device never needs more than slab + O(chunk·(L+d)) regardless of n
        assign_peak = ma_assign.peak_estimate
        scatter_extra = ma_scatter.peak_estimate - ma_scatter.alias_size
        assert assign_peak + scatter_extra < dataset_bytes * 0.2, (
            f"chunk programs need {assign_peak + scatter_extra} bytes vs "
            f"dataset {dataset_bytes}")
        # and the PQ slab itself is ~8× smaller than the f32 dataset
        # (32 bytes/slot incl. norm+id vs 384 bytes/vector, ×1.5 padding)
        assert slab_bytes < dataset_bytes / 4

    def test_ivf_pq_chunked_accepts_memmap(self, tmp_path, data):
        x, q, gt = data
        f = tmp_path / "db.npy"
        np.save(f, x)
        mm = np.load(f, mmap_mode="r")
        idx = ivf_pq.build_chunked(
            mm, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=16, seed=0),
            chunk_rows=1024)
        assert idx.size == x.shape[0]


class TestDistributedSharded:
    def test_ivf_flat_sharded_builds_locally(self, data, mesh8):
        x, q, gt = data
        p = ivf_flat.IvfFlatIndexParams(n_lists=64, seed=5)
        idx = ivf_flat.build_sharded(x, mesh8, p)
        assert idx.size == x.shape[0]
        # shard s's lists may only hold shard s's global row range
        per = x.shape[0] // 8
        ll = idx.n_lists // 8
        ids = np.asarray(idx.ids)
        for s in range(8):
            blk = ids[s * ll:(s + 1) * ll]
            valid = blk[blk >= 0]
            assert valid.min() >= s * per and valid.max() < (s + 1) * per
        _, i2 = ivf_flat.search_sharded(
            idx, q, 10, ivf_flat.IvfFlatSearchParams(n_probes=8), mesh=mesh8)
        assert float(neighborhood_recall(np.asarray(i2), gt)) > 0.8

    def test_ivf_pq_sharded_builds_locally(self, data, mesh8):
        x, q, gt = data
        p = ivf_pq.IvfPqIndexParams(n_lists=64, pq_dim=16, seed=5)
        idx = ivf_pq.build_sharded(x, mesh8, p)
        assert idx.size == x.shape[0]
        per = x.shape[0] // 8
        ll = idx.n_lists // 8
        ids = np.asarray(idx.ids)
        for s in range(8):
            blk = ids[s * ll:(s + 1) * ll]
            valid = blk[blk >= 0]
            assert valid.min() >= s * per and valid.max() < (s + 1) * per
        _, i2 = ivf_pq.search_sharded(
            idx, q, 10, ivf_pq.IvfPqSearchParams(n_probes=8), mesh=mesh8)
        # PQ-compressed recall on gaussian data is modest; refine-level
        # checks live in test_ivf_pq.py — here assert the merge works
        assert float(neighborhood_recall(np.asarray(i2), gt)) > 0.3

    def test_cagra_sharded_single_program(self, data, mesh8):
        x, q, gt = data
        p = cagra.CagraIndexParams(
            intermediate_graph_degree=32, graph_degree=16, n_routers=32)
        idx = cagra.build_sharded(x, mesh8, p)
        assert idx.datasets.shape == (8, x.shape[0] // 8, x.shape[1])
        d, i = cagra.search_sharded(
            idx, q, 10, cagra.CagraSearchParams(itopk_size=32), mesh=mesh8)
        assert float(neighborhood_recall(np.asarray(i), gt)) > 0.9


class TestDataParallelSearch:
    """2-D (data x shard) mesh: queries partitioned over the data axis,
    index over the shard axis — the hybrid ICI/DCN composition."""

    def test_ivf_flat_2d(self, data, mesh2x4):
        x, q, gt = data
        idx = ivf_flat.build_sharded(x, mesh2x4,
                                     ivf_flat.IvfFlatIndexParams(n_lists=32, seed=5))
        sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
        _, i1 = ivf_flat.search_sharded(idx, q, 10, sp, mesh=mesh2x4)
        _, i2 = ivf_flat.search_sharded(idx, q, 10, sp, mesh=mesh2x4,
                                        data_axis="data")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_ivf_pq_2d(self, data, mesh2x4):
        x, q, gt = data
        idx = ivf_pq.build_sharded(
            x, mesh2x4, ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=16, seed=5))
        sp = ivf_pq.IvfPqSearchParams(n_probes=8)
        _, i1 = ivf_pq.search_sharded(idx, q, 10, sp, mesh=mesh2x4)
        _, i2 = ivf_pq.search_sharded(idx, q, 10, sp, mesh=mesh2x4,
                                      data_axis="data")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_cagra_2d(self, data, mesh2x4):
        x, q, gt = data
        idx = cagra.build_sharded(x, mesh2x4, cagra.CagraIndexParams(
            intermediate_graph_degree=32, graph_degree=16, n_routers=32))
        sp = cagra.CagraSearchParams(itopk_size=32)
        _, i1 = cagra.search_sharded(idx, q, 10, sp, mesh=mesh2x4)
        _, i2 = cagra.search_sharded(idx, q, 10, sp, mesh=mesh2x4,
                                     data_axis="data")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestPipelinedChunkEngine:
    """PR 4 tentpole: fused, slab-donating, fixed-shape chunk engine.

    The pipelined stream (masked assignment + padded tail + single fused
    dispatch per chunk) is a pure *scheduling* change, so it must be
    BIT-identical to the seed per-op loop — slabs, norms, ids, counts —
    at every chunk-boundary shape, and the whole stream must run through
    one cached executable (TraceGuard: zero recompiles and zero implicit
    transfers after the first chunk).
    """

    # n % chunk_rows ∈ {0, 1, chunk_rows−1}: exact fit, one-row tail,
    # near-full tail — the three padding regimes of the fixed-shape engine
    BOUNDARY = [1024, 1025, 1279]

    @pytest.fixture(scope="class")
    def xbig(self):
        rng = np.random.default_rng(11)
        return rng.standard_normal((1279, 32)).astype(np.float32)

    @pytest.mark.parametrize("n", BOUNDARY)
    def test_ivf_flat_bitwise_vs_perop(self, xbig, n):
        p = ivf_flat.IvfFlatIndexParams(n_lists=16, seed=1)
        a = ivf_flat.build_chunked(xbig[:n], p, chunk_rows=256)
        b = ivf_flat._build_chunked_perop(xbig[:n], p, chunk_rows=256)
        for f in ("centroids", "data", "ids", "counts", "norms"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f)
        assert int(np.asarray(a.counts).sum()) == n

    @pytest.mark.parametrize("n", BOUNDARY)
    def test_ivf_pq_bitwise_vs_perop(self, xbig, n):
        p = ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=8, seed=1)
        a = ivf_pq.build_chunked(xbig[:n], p, chunk_rows=256)
        b = ivf_pq._build_chunked_perop(xbig[:n], p, chunk_rows=256)
        for f in ("centroids", "codebooks", "codes", "code_norms", "ids",
                  "counts"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f)
        assert int(np.asarray(a.counts).sum()) == n

    def test_ivf_flat_chunked_matches_build_bitwise(self, xbig):
        """Full trainset + ample capacity: training sees the same rows in
        the same order and capacity never binds, so each row lands in its
        nearest list in stream order == row order — the streamed build
        must equal the one-shot :func:`ivf_flat.build` bit-for-bit."""
        p = ivf_flat.IvfFlatIndexParams(n_lists=8, seed=2,
                                        kmeans_trainset_fraction=1.0,
                                        list_cap_ratio=8.0)
        ref = ivf_flat.build(xbig, p)
        idx = ivf_flat.build_chunked(xbig, p, chunk_rows=256)
        # regime check: the ample-capacity assumption actually held
        assert int(np.asarray(idx.counts).max()) < ref.list_cap
        for f in ("centroids", "data", "ids", "counts", "norms"):
            np.testing.assert_array_equal(
                np.asarray(getattr(idx, f)), np.asarray(getattr(ref, f)),
                err_msg=f)

    def test_ivf_pq_chunked_matches_build_bitwise(self, xbig):
        p = ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8, seed=2,
                                    kmeans_trainset_fraction=1.0,
                                    list_cap_ratio=8.0)
        ref = ivf_pq.build(xbig, p)
        idx = ivf_pq.build_chunked(xbig, p, chunk_rows=256)
        assert int(np.asarray(idx.counts).max()) < ref.list_cap
        for f in ("centroids", "codebooks", "codes", "code_norms", "ids",
                  "counts"):
            np.testing.assert_array_equal(
                np.asarray(getattr(idx, f)), np.asarray(getattr(ref, f)),
                err_msg=f)

    def test_ivf_flat_stream_steady_state(self, xbig):
        """One executable serves every chunk: warm the fused step on a
        SHORT stream, then push a LONGER stream (more chunks, padded tail)
        through a :class:`TraceGuard` — zero retraces, zero recompiles,
        zero implicit transfers in the chunk loop."""
        from raft_tpu.core import TraceGuard
        from raft_tpu.neighbors.ivf_flat import (_coarse_train_chunked,
                                                 _stream_pipelined)
        p = ivf_flat.IvfFlatIndexParams(n_lists=16, seed=3)
        n = xbig.shape[0]
        cap = int(np.ceil(p.list_cap_ratio * n / p.n_lists))
        centroids = _coarse_train_chunked(xbig, p, n)
        # warmup: 2 chunks (first chunk compiles the one fused program)
        _stream_pipelined(xbig[:512], centroids, p, 512, cap, 256, None,
                          jnp.float32)
        with TraceGuard() as tg:  # transfer_guard("disallow") inside
            _, _, counts = _stream_pipelined(
                xbig, centroids, p, n, cap, 256, None, jnp.float32)
        assert int(np.asarray(counts).sum()) == n
        tg.assert_steady_state(max_traces=0, max_compiles=0)

    def test_ivf_pq_stream_steady_state(self, xbig):
        from raft_tpu.core import TraceGuard
        from raft_tpu.neighbors.ivf_pq import (_pq_train_chunked,
                                               _pq_stream_pipelined)
        p = ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=8, seed=3)
        n = xbig.shape[0]
        m, c = 8, 256
        cap = int(np.ceil(p.list_cap_ratio * n / p.n_lists))
        centroids, codebooks = _pq_train_chunked(xbig, p, n, m, c)
        _pq_stream_pipelined(xbig[:512], centroids, codebooks, p, 512, m,
                             cap, 256, None)
        with TraceGuard() as tg:
            *_, counts = _pq_stream_pipelined(
                xbig, centroids, codebooks, p, n, m, cap, 256, None)
        assert int(np.asarray(counts).sum()) == n
        tg.assert_steady_state(max_traces=0, max_compiles=0)

    def test_source_ids_roundtrip(self, xbig):
        """Caller ids survive the padded stream (pads are −1 internally
        and must never leak into the packed lists)."""
        n = 1025
        ids = np.arange(5000, 5000 + n, dtype=np.int32)
        p = ivf_flat.IvfFlatIndexParams(n_lists=16, seed=1)
        idx = ivf_flat.build_chunked(xbig[:n], p, chunk_rows=256,
                                     source_ids=ids)
        got = np.asarray(idx.ids)
        np.testing.assert_array_equal(np.sort(got[got >= 0]), ids)


class TestChunkedSharded:
    """PR 4: ``build_chunked_sharded`` — the build-side analog of
    ``search_sharded``: chunks split contiguously over the mesh axis, each
    device streaming its slice into its OWN local lists."""

    def test_ivf_flat_chunked_sharded(self, data, mesh8):
        x, q, gt = data
        p = ivf_flat.IvfFlatIndexParams(n_lists=64, seed=5)
        idx = ivf_flat.build_chunked_sharded(x, mesh8, p, chunk_rows=1024)
        assert idx.size == x.shape[0]
        ids = np.asarray(idx.ids)
        got = np.sort(ids[ids >= 0])
        np.testing.assert_array_equal(got, np.arange(x.shape[0]))
        # shard s's lists hold only rows from shard s's chunk stripes
        ll = idx.n_lists // 8
        pc = 1024 // 8
        for s in range(8):
            blk = ids[s * ll:(s + 1) * ll]
            valid = blk[blk >= 0]
            assert valid.size and np.all((valid // pc) % 8 == s)
        _, i2 = ivf_flat.search_sharded(
            idx, q, 10, ivf_flat.IvfFlatSearchParams(n_probes=16), mesh=mesh8)
        assert float(neighborhood_recall(np.asarray(i2), gt)) > 0.8

    def test_ivf_pq_chunked_sharded(self, data, mesh8):
        x, q, gt = data
        p = ivf_pq.IvfPqIndexParams(n_lists=64, pq_dim=16, seed=5)
        idx = ivf_pq.build_chunked_sharded(x, mesh8, p, chunk_rows=1024)
        assert idx.size == x.shape[0]
        ids = np.asarray(idx.ids)
        np.testing.assert_array_equal(np.sort(ids[ids >= 0]),
                                      np.arange(x.shape[0]))
        _, i2 = ivf_pq.search_sharded(
            idx, q, 10, ivf_pq.IvfPqSearchParams(n_probes=16), mesh=mesh8)
        assert float(neighborhood_recall(np.asarray(i2), gt)) > 0.3


class TestPrefetchChunks:
    def test_yields_all_rows_in_order(self, rng):
        from raft_tpu.neighbors._packing import prefetch_chunks
        x = rng.standard_normal((1000, 4)).astype(np.float32)
        seen = []
        for lo, hi, xc, idc in prefetch_chunks(x, 256):
            np.testing.assert_array_equal(xc, x[lo:hi])
            np.testing.assert_array_equal(idc, np.arange(lo, hi))
            seen.append((lo, hi))
        assert seen == [(0, 256), (256, 512), (512, 768), (768, 1000)]

    def test_custom_ids_pass_through(self, rng):
        from raft_tpu.neighbors._packing import prefetch_chunks
        x = rng.standard_normal((100, 4)).astype(np.float32)
        ids = np.arange(1000, 1100, dtype=np.int32)
        got = [idc for *_, idc in prefetch_chunks(x, 64, ids)]
        np.testing.assert_array_equal(np.concatenate(got), ids)

    def test_padded_fixed_shapes_and_tail_mask(self, rng):
        """Every staged chunk has the SAME device shape; tail pads carry
        id −1 (the chunk step's row mask) and zero data."""
        from raft_tpu.neighbors._packing import prefetch_chunks_padded
        x = rng.standard_normal((1000, 4)).astype(np.float32)
        chunks = list(prefetch_chunks_padded(x, 256))
        assert [(lo, hi) for lo, hi, *_ in chunks] == [
            (0, 256), (256, 512), (512, 768), (768, 1000)]
        for lo, hi, xc, idc in chunks:
            assert xc.shape == (256, 4) and idc.shape == (256,)
            np.testing.assert_array_equal(np.asarray(xc)[:hi - lo], x[lo:hi])
            np.testing.assert_array_equal(np.asarray(idc)[:hi - lo],
                                          np.arange(lo, hi))
            assert np.all(np.asarray(idc)[hi - lo:] == -1)
            assert np.all(np.asarray(xc)[hi - lo:] == 0.0)

    def test_padded_casts_dtype(self, rng):
        from raft_tpu.neighbors._packing import prefetch_chunks_padded
        x = rng.standard_normal((100, 4)).astype(np.float64)
        (_, _, xc, _), = prefetch_chunks_padded(x, 128, dtype=jnp.bfloat16)
        assert xc.dtype == jnp.bfloat16

    def test_resolve_chunk_rows(self):
        from raft_tpu.neighbors._packing import (DEFAULT_CHUNK_ROWS,
                                                 resolve_chunk_rows)
        # explicit request wins, clamped to the dataset
        assert resolve_chunk_rows(512, 10_000, 64, "ivf_flat") == 512
        assert resolve_chunk_rows(512, 100, 64, "ivf_flat") == 100
        # auto: table entry if measured, else the default, clamped to n
        auto = resolve_chunk_rows(0, 10 ** 9, 64, "ivf_flat")
        assert 1 <= auto <= 10 ** 9
        assert resolve_chunk_rows(0, 100, 64, "ivf_flat") <= 100
        assert DEFAULT_CHUNK_ROWS > 0

    def test_chunked_shard_rows_partition(self):
        """Stripe accounting: per-shard valid-row totals partition n for
        any (n, chunk_rows, n_dev) — incl. short tails that starve the
        high shards."""
        from raft_tpu.neighbors._packing import chunked_shard_rows
        for n, c, s in [(1000, 256, 8), (1024, 256, 4), (999, 512, 8),
                        (4096, 1024, 8)]:
            per = chunked_shard_rows(n, c, s)
            assert per.sum() == n, (n, c, s)
            assert per.min() >= 0

    def test_chunked_shard_trainsets_rows_come_from_own_stripes(self, rng):
        from raft_tpu.neighbors._packing import chunked_shard_trainsets
        n, c, s, t = 4096, 1024, 8, 64
        x = rng.standard_normal((n, 4)).astype(np.float32)
        xt = chunked_shard_trainsets(x, n, c, s, t, seed=0)
        assert xt.shape == (s, t, 4)
        pc = c // s
        # recover each sampled row's global index and check its stripe
        flat = {tuple(r): i for i, r in enumerate(x)}
        for sh in range(s):
            for r in xt[sh]:
                gi = flat[tuple(r)]
                assert (gi // pc) % s == sh
