"""Comms layer tests — orchestration parity with
``raft-dask/raft_dask/tests/test_comms.py:62-110`` (Python drives the comms
layer's own self-test kernels; the virtual 8-device CPU mesh plays the
LocalCUDACluster role, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from raft_tpu.core.compat import shard_map

from raft_tpu import comms as comms_mod
from raft_tpu.comms import Comms, Op, selftest
from raft_tpu.core import resources as res_mod


@pytest.fixture(scope="module")
def comms():
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:8]), ("shard",))
    return Comms(mesh)


# -- self-test kernel orchestration (test_comms.py parity) -------------------

def test_selftests_all_pass(comms):
    results = selftest.run_all(comms)
    failed = [k for k, ok in results.items() if not ok]
    assert not failed, f"comms self-tests failed: {failed}"


def test_rank_size(comms):
    assert comms.get_size() == 8
    assert 0 <= comms.get_rank() < 8


# -- eager verb behavior -----------------------------------------------------

def test_allreduce_ops(comms):
    n = comms.get_size()
    data = jnp.arange(n, dtype=jnp.float32)[:, None] + 1.0
    assert np.all(np.asarray(comms.allreduce(data, Op.SUM)) == n * (n + 1) / 2)
    assert np.all(np.asarray(comms.allreduce(data, Op.MAX)) == n)
    assert np.all(np.asarray(comms.allreduce(data, Op.MIN)) == 1)
    prod = np.asarray(comms.allreduce(data, Op.PROD))
    assert np.allclose(prod, np.prod(np.arange(1, n + 1, dtype=np.float64)))


def test_alltoall(comms):
    n = comms.get_size()
    # rank r sends value r*n+c to rank c
    data = (jnp.arange(n)[:, None] * n + jnp.arange(n)[None, :]).astype(jnp.float32)
    out = np.asarray(comms.alltoall(data))
    # rank c receives [r*n+c for r in ranks]
    want = np.arange(n)[None, :] * n + np.arange(n)[:, None]
    assert np.all(out == want.astype(np.float32))


def test_reducescatter_sum(comms):
    n = comms.get_size()
    data = jnp.tile(jnp.arange(n, dtype=jnp.float32)[None, :], (n, 1))
    out = np.asarray(comms.reducescatter(data, Op.SUM))
    assert np.all(out[:, 0] == np.arange(n) * n)


def test_comm_split_four_colors(comms):
    n = comms.get_size()
    color = [r % 4 for r in range(n)]
    split = comms.comm_split(color)
    assert split.get_size_of(0) == n // 4
    assert split.get_rank_of(5) == 1  # ranks 1,5 share color 1; 5 is second
    out = np.asarray(split.allreduce(jnp.arange(n, dtype=jnp.float32)[:, None]))
    for r in range(n):
        want = sum(q for q in range(n) if q % 4 == r % 4)
        assert out[r, 0] == want


# -- traced verbs inside user shard_map programs -----------------------------

def test_traced_verbs_compose_in_shard_map(comms):
    """The production pattern: comms verbs called inside a jitted,
    shard_map-decorated program (not via the eager wrappers)."""
    mesh = comms.mesh
    n = comms.get_size()

    def program(x):  # x: per-rank block [1, 4]
        total = comms_mod.allreduce(x, Op.SUM, axis="shard")
        nbr = comms_mod.ring_shift(x, 1, axis="shard")
        rs = comms_mod.reducescatter(
            jnp.tile(x.reshape(-1)[None, :2], (n, 1)), Op.SUM, axis="shard"
        )
        return total + nbr + jnp.sum(rs)

    fn = jax.jit(
        shard_map(program, mesh=mesh, in_specs=P("shard"), out_specs=P("shard"),
                  check_vma=False)
    )
    x = jnp.ones((n, 4), jnp.float32)
    out = np.asarray(fn(x))
    # total=n each; nbr=1 each; rs: each rank receives sum over ranks of its
    # 2-chunk of ones*n... tile gives [n,2] of ones -> psum_scatter chunk = [2//?]
    assert out.shape == (n, 4)
    assert np.all(out > n)  # smoke: collective outputs composed


def test_allgatherv_ragged(comms):
    n = comms.get_size()
    counts = list(range(1, n + 1))
    pad = max(counts)
    buf = np.full((n, pad), -1.0, np.float32)
    want = []
    for r in range(n):
        buf[r, : counts[r]] = r
        want += [r] * counts[r]
    out = np.asarray(comms.allgatherv(jnp.asarray(buf), counts))
    assert out.shape == (n, sum(counts))
    assert np.all(out == np.asarray(want, np.float32)[None, :])


# -- resources injection -----------------------------------------------------

def test_inject_comms_on_resources(comms):
    res = res_mod.Resources()
    comms_mod.inject_comms_on_resources(res, comms)
    assert res_mod.get_comms(res) is comms
    assert res_mod.get_mesh(res) is comms.mesh


def test_barrier_returns(comms):
    comms.barrier()  # must not deadlock / raise


class TestSplitCommsVerbs:
    """Grouped verb set of the split communicator (comm_split returns a
    full comms_t in the reference, core/comms.hpp:122)."""

    @pytest.fixture()
    def split(self, mesh8):
        from raft_tpu.comms import build_comms
        c = build_comms(mesh8)
        return c, c.comm_split([0, 0, 0, 0, 1, 1, 1, 1])

    def test_bcast_group_roots(self, split):
        _, sc = split
        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        out = np.asarray(sc.bcast(x, root=0))
        np.testing.assert_allclose(out[:4, 0], 0.0)  # group 0's root = rank 0
        np.testing.assert_allclose(out[4:, 0], 4.0)  # group 1's root = rank 4

    def test_reduce_at_group_root(self, split):
        _, sc = split
        x = jnp.ones((8, 1), jnp.float32)
        out = np.asarray(sc.reduce(x, root=1))
        # group roots (ranks 1 and 5) hold the sum; others get zeros (same
        # non-root contract as the parent-axis reduce)
        assert out[1, 0] == 4.0 and out[5, 0] == 4.0
        assert out[0, 0] == 0.0 and out[7, 0] == 0.0

    def test_bcast_invalid_root_rejected(self, split):
        from raft_tpu.core.errors import RaftError
        _, sc = split
        x = jnp.ones((8, 1), jnp.float32)
        with pytest.raises(RaftError):
            sc.bcast(x, root=4)  # groups have 4 members: valid roots 0..3
        with pytest.raises(RaftError):
            sc.bcast(x, root=-1)

    def test_allgather_groups(self, split):
        _, sc = split
        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        out = np.asarray(sc.allgather(x))  # [8, gmax=4, 1]
        np.testing.assert_allclose(out[0, :, 0], [0, 1, 2, 3])
        np.testing.assert_allclose(out[6, :, 0], [4, 5, 6, 7])

    def test_unequal_groups_pad_with_self(self, mesh8):
        from raft_tpu.comms import build_comms
        sc = build_comms(mesh8).comm_split([0, 0, 0, 0, 0, 0, 1, 1])
        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        out = np.asarray(sc.allgather(x))  # gmax = 6
        np.testing.assert_allclose(out[7, :2, 0], [6, 7])
        np.testing.assert_allclose(out[7, 2:, 0], 7.0)  # pad = own value
