"""Hardware-gated Mosaic compile test (VERDICT r4 next #4).

The rest of the suite pins CPU (conftest) and runs Pallas kernels in
interpret mode, so nothing in CI exercises the Mosaic compiler.  Setting
``RAFT_RUN_MOSAIC=1`` runs ``scripts/mosaic_check.py`` in a subprocess
that does NOT pin a platform — on a machine with a healthy TPU backend it
compiles the three Pallas kernels non-interpreted at production block
shapes and asserts agreement with interpret mode.

Always-on here: a CPU smoke of the script itself (``--cpu``), so the
check logic cannot rot between tunnel windows.
"""

import json
import os
import subprocess
import sys

import pytest

CHECK = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "scripts", "mosaic_check.py")


def _run(*extra):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    return subprocess.run([sys.executable, CHECK, *extra],
                          capture_output=True, text=True, timeout=900, env=env)


def test_mosaic_check_script_cpu_smoke():
    p = _run("--cpu")
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"
    final = json.loads([ln for ln in p.stdout.splitlines()
                        if '"mosaic_check"' in ln][-1])
    assert final["backend"] == "cpu" and final["mosaic"] is False


@pytest.mark.skipif(not os.environ.get("RAFT_RUN_MOSAIC"),
                    reason="hardware gate: set RAFT_RUN_MOSAIC=1 on a "
                           "machine with a TPU backend")
def test_mosaic_compile_on_hardware():
    p = _run()
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"
    final = json.loads([ln for ln in p.stdout.splitlines()
                        if '"mosaic_check"' in ln][-1])
    assert final["ok"] is True
    assert final["mosaic"] is True, \
        f"backend was {final['backend']}, not tpu — gate run on wrong host?"
