"""CAGRA graph index tests: graph structure invariants + search recall vs
brute force."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, cagra
from raft_tpu.random.datagen import make_blobs
from raft_tpu.stats.neighborhood import neighborhood_recall


@pytest.fixture(scope="module")
def blob_data():
    x, _ = make_blobs(jax.random.PRNGKey(2), n_samples=5000, n_features=32,
                      n_clusters=25, cluster_std=1.2)
    return np.asarray(x), np.asarray(x[:150])


def _recall(got, want):
    return float(neighborhood_recall(jnp.asarray(got), jnp.asarray(want)))


def test_optimize_graph_shape_and_no_self():
    knn = np.asarray([[1, 2, 3], [0, 2, 3], [0, 1, 3], [0, 1, 2]], np.int32)
    g = cagra.optimize_graph(knn, 2)
    assert g.shape == (4, 2)
    for u in range(4):
        assert u not in g[u].tolist()


def test_cagra_recall(blob_data):
    x, q = blob_data
    params = cagra.CagraIndexParams(intermediate_graph_degree=48,
                                    graph_degree=24)
    index = cagra.build(x, params)
    assert index.graph.shape == (x.shape[0], 24)
    _, want = brute_force.knn(q, x, 10)
    _, got = cagra.search(index, q, 10,
                          cagra.CagraSearchParams(itopk_size=64,
                                                  search_width=4,
                                                  n_seeds=32))
    assert _recall(got, want) > 0.9


def test_cagra_higher_effort_higher_recall(blob_data):
    x, q = blob_data
    index = cagra.build(x, cagra.CagraIndexParams(graph_degree=16,
                                                  intermediate_graph_degree=32))
    _, want = brute_force.knn(q, x, 10)
    _, low = cagra.search(index, q, 10,
                          cagra.CagraSearchParams(itopk_size=16,
                                                  search_width=1,
                                                  max_iterations=2, n_seeds=4))
    _, high = cagra.search(index, q, 10,
                           cagra.CagraSearchParams(itopk_size=96,
                                                   search_width=8, n_seeds=48))
    assert _recall(high, want) >= _recall(low, want)
    assert _recall(high, want) > 0.85


def test_cagra_ivf_build_n_probes(blob_data):
    """build_n_probes steers the intermediate-graph accuracy of the IVF
    build path; more probes must not degrade recall (quality lever for the
    1M-scale gate)."""
    x, q = blob_data
    _, want = brute_force.knn(q, x, 10)
    sp = cagra.CagraSearchParams(itopk_size=64, search_width=4)
    recalls = []
    for probes in (2, 24):
        p = cagra.CagraIndexParams(intermediate_graph_degree=48,
                                   graph_degree=24, build_algo="ivf",
                                   build_n_probes=probes)
        # the ivf path needs >= 4096 rows; blob_data is sized above that
        assert x.shape[0] >= 4096
        _, got = cagra.search(cagra.build(x, p), q, 10, sp)
        recalls.append(_recall(got, want))
    assert recalls[1] >= recalls[0] - 0.02  # never meaningfully worse
    assert recalls[1] > 0.9


def test_nn_descent_improves_degraded_graph(blob_data):
    """NN-descent must recover kNN-graph recall that a cheap approximate
    build left out (the quality lever for IVF-sourced graphs at scale)."""
    x, _ = blob_data
    kk = 16
    _, exact = brute_force.knn(x, x, kk + 1)
    exact = cagra._drop_self(jnp.asarray(exact), kk)

    # degraded starting graph: exact edges with half the columns replaced
    # by random ids (simulating a low-probe IVF build)
    rng = np.random.default_rng(0)
    g0 = np.asarray(exact).copy()
    g0[:, kk // 2:] = rng.integers(0, x.shape[0], g0[:, kk // 2:].shape)

    def graph_recall(g):
        hit = (np.asarray(g)[:, :, None] == np.asarray(exact)[:, None, :])
        return hit.any(axis=1).mean()

    r0 = graph_recall(g0)
    g1 = cagra.refine_knn_graph(x, g0, n_iters=2, seed=0)
    r1 = graph_recall(g1)
    assert r1 > r0 + 0.1, (r0, r1)
    # refined rows are valid ids sorted by ascending exact distance
    g1 = np.asarray(g1)
    assert (g1 >= 0).all() and (g1 < x.shape[0]).all()
    d0 = np.linalg.norm(x[g1[5]] - x[5][None, :], axis=1)
    assert (np.diff(d0) >= -1e-4).all()


def test_nn_descent_block_invariant(blob_data):
    """Row-block chunking is a memory knob, not a semantic one: results
    must be identical for any block size (incl. non-dividing)."""
    x, _ = blob_data
    _, nbrs = brute_force.knn(x, x, 9)
    g0 = cagra._drop_self(jnp.asarray(nbrs), 8)
    a = cagra.refine_knn_graph(x, g0, n_iters=1, seed=3, block=x.shape[0])
    b = cagra.refine_knn_graph(x, g0, n_iters=1, seed=3, block=700)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cagra_build_with_refine_iters(blob_data):
    """build(graph_refine_iters=2) plumbs the NN-descent pass: the refined
    build produces a different (never worse-searching) graph."""
    x, q = blob_data
    _, want = brute_force.knn(q, x, 10)
    sp = cagra.CagraSearchParams(itopk_size=64, search_width=4)
    base = cagra.CagraIndexParams(intermediate_graph_degree=24,
                                  graph_degree=16, build_algo="ivf",
                                  build_n_probes=1)
    idx0 = cagra.build(x, base)
    refined = dataclasses.replace(base, graph_refine_iters=2)
    idx1 = cagra.build(x, refined)
    assert (np.asarray(idx0.graph) != np.asarray(idx1.graph)).any()
    _, got0 = cagra.search(idx0, q, 10, sp)
    _, got1 = cagra.search(idx1, q, 10, sp)
    assert _recall(got1, want) >= _recall(got0, want) - 0.01
    assert _recall(got1, want) > 0.85


def test_cagra_router_coverage_auto(blob_data):
    """Auto router sizing must cover every natural region: recall with the
    auto table beats a deliberately-undersized one on many-cluster data
    (the 300k-probe failure mode, shrunk to CPU scale)."""
    from raft_tpu.random.datagen import make_blobs as mb

    x, _ = mb(jax.random.PRNGKey(5), n_samples=8000, n_features=24,
              n_clusters=200, cluster_std=0.5)
    x = np.asarray(x)
    q = x[:200]
    _, want = brute_force.knn(q, x, 5)
    sp = cagra.CagraSearchParams(itopk_size=64, search_width=4)
    base = cagra.CagraIndexParams(intermediate_graph_degree=24,
                                  graph_degree=16)
    small = dataclasses.replace(base, n_routers=64)  # < 200 clusters
    _, got_small = cagra.search(cagra.build(x, small), q, 5, sp)
    _, got_auto = cagra.search(cagra.build(x, base), q, 5, sp)
    r_small, r_auto = _recall(got_small, want), _recall(got_auto, want)
    assert r_auto > r_small + 0.1, (r_small, r_auto)
    assert r_auto > 0.9, r_auto


def test_cagra_build_from_graph(blob_data):
    x, q = blob_data
    _, nbrs = brute_force.knn(x, x, 33)
    index = cagra.build_from_graph(x, np.asarray(nbrs)[:, 1:], graph_degree=24)
    _, want = brute_force.knn(q, x, 5)
    _, got = cagra.search(index, q, 5)
    assert _recall(got, want) > 0.9


def test_cagra_no_duplicate_results(blob_data):
    x, q = blob_data
    index = cagra.build(x, cagra.CagraIndexParams(graph_degree=16,
                                                  intermediate_graph_degree=32))
    _, got = cagra.search(index, q, 10)
    got = np.asarray(got)
    for row in got:
        valid = row[row >= 0]
        assert len(set(valid.tolist())) == len(valid)


def test_cagra_sharded(blob_data, mesh8):
    x, q = blob_data
    params = cagra.CagraIndexParams(intermediate_graph_degree=32,
                                    graph_degree=16)
    index = cagra.build_sharded(x, mesh8, params)
    _, want = brute_force.knn(q, x, 10)
    _, got = cagra.search_sharded(
        index, q, 10,
        cagra.CagraSearchParams(itopk_size=32, search_width=4, n_seeds=16),
        mesh=mesh8)
    assert _recall(got, want) > 0.9


@pytest.mark.skipif(os.environ.get("RAFT_RUN_SLOW") != "1",
                    reason="1M-row build; set RAFT_RUN_SLOW=1 (run on TPU)")
def test_graph_quality_1m_rows():
    """Recall >= 0.95 at itopk <= 128 on >= 1M rows (VERDICT r2 next #6).
    The committed quality table lives in bench/CAGRA_QUALITY.json
    (bench/cagra_quality.py regenerates it on the target backend)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "bench"))
    from ann import ground_truth, make_clustered

    n, d = 1_000_000, 96
    data = make_clustered(n + 2000, d, n // 1000, seed=3, scale=2.0)
    db, q = data[:n], data[n:]
    gt = ground_truth(q, db, 10)
    idx = cagra.build(db, cagra.CagraIndexParams(
        intermediate_graph_degree=64, graph_degree=32, build_algo="ivf",
        n_routers=512))
    _, found = cagra.search(idx, q, 10, cagra.CagraSearchParams(itopk_size=128))
    from raft_tpu.stats import neighborhood_recall
    rec = float(neighborhood_recall(np.asarray(found), np.asarray(gt)))
    assert rec >= 0.95, f"1M-row graph recall {rec}"
