"""Host staging-buffer pool (core.host_memory — the pinned/host-MR analog,
SURVEY §2.1 #17) and its IO integrations (read_npy/read_*vecs ``out=``,
``BatchLoader(reuse_buffers=True)``)."""

import os
import threading

import numpy as np
import pytest

from raft_tpu.core.host_memory import HostBufferPool, default_host_pool
from raft_tpu import io


class TestHostBufferPool:
    def test_reuse_identity(self):
        pool = HostBufferPool()
        a = pool.acquire((8, 4), np.float32)
        pool.release(a)
        assert pool.acquire((8, 4), np.float32) is a

    def test_shape_dtype_keying(self):
        pool = HostBufferPool()
        a = pool.acquire((8, 4), np.float32)
        pool.release(a)
        assert pool.acquire((8, 4), np.int32) is not a
        assert pool.acquire((4, 8), np.float32) is not a

    def test_limit_drops_over_budget(self):
        pool = HostBufferPool(limit_bytes=100)
        big = pool.acquire((1000,), np.float64)  # 8 kB > limit
        pool.release(big)
        assert pool.stats()["held_bytes"] == 0
        assert pool.acquire((1000,), np.float64) is not big

    def test_release_rejects_views(self):
        pool = HostBufferPool()
        base = np.zeros((10, 10), np.float32)
        pool.release(base[:5])  # a view — must not enter the pool
        assert pool.stats()["free_buffers"] == 0

    def test_borrow_scope(self):
        pool = HostBufferPool()
        with pool.borrow((4,), np.float32) as buf:
            buf[:] = 7
        assert pool.stats()["free_buffers"] == 1
        assert pool.acquire((4,), np.float32) is buf

    def test_trim(self):
        pool = HostBufferPool()
        pool.release(pool.acquire((4,), np.float32))
        pool.trim()
        assert pool.stats() == {"hits": 0, "misses": 1, "held_bytes": 0,
                                "free_buffers": 0}

    def test_thread_safety(self):
        pool = HostBufferPool()
        errs = []

        def worker():
            try:
                for _ in range(200):
                    b = pool.acquire((16,), np.float32)
                    pool.release(b)
            except Exception as e:  # noqa: BLE001 — collected for assert
                errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert not errs

    def test_default_pool_is_a_resource_cell(self):
        from raft_tpu.core.resources import Resources, get_host_pool

        res = Resources()
        assert get_host_pool(res) is get_host_pool(res)  # lazy, then shared
        assert isinstance(default_host_pool(res), HostBufferPool)

    def test_export_metrics_gauges(self):
        from raft_tpu.core.host_memory import export_host_pool_metrics
        from raft_tpu.obs.metrics import MetricRegistry

        pool = HostBufferPool()
        pool.release(pool.acquire((8, 4), np.float32))   # 1 miss, held
        pool.release(pool.acquire((8, 4), np.float32))   # 1 hit
        reg = MetricRegistry()
        stats = export_host_pool_metrics(pool, registry=reg)
        assert stats == pool.stats()

        def gauge(name):
            [(_, v)] = reg.gauge(name, "").samples()
            return v

        assert gauge("raft_host_pool_idle_bytes") == 8 * 4 * 4
        assert gauge("raft_host_pool_hits") == 1.0
        assert gauge("raft_host_pool_misses") == 1.0

    def test_export_metrics_defaults_to_process_pool(self):
        from raft_tpu.core.host_memory import export_host_pool_metrics
        from raft_tpu.obs.metrics import MetricRegistry

        reg = MetricRegistry()
        assert export_host_pool_metrics(registry=reg) == \
            default_host_pool().stats()


@pytest.fixture()
def npy_file(tmp_path, rng):
    x = rng.standard_normal((64, 16)).astype(np.float32)
    p = os.path.join(tmp_path, "x.npy")
    np.save(p, x)
    return p, x


@pytest.fixture()
def fvecs_file(tmp_path, rng):
    x = rng.standard_normal((40, 8)).astype(np.float32)
    p = os.path.join(tmp_path, "x.fvecs")
    with open(p, "wb") as f:
        for row in x:
            np.int32(8).tofile(f)
            row.tofile(f)
    return p, x


class TestIoOut:
    def test_read_npy_into_buffer(self, npy_file):
        p, x = npy_file
        buf = np.empty((64, 16), np.float32)
        got = io.read_npy(p, out=buf)
        assert got is buf
        np.testing.assert_array_equal(buf, x)

    def test_read_npy_out_mismatch_raises(self, npy_file):
        p, _ = npy_file
        with pytest.raises(ValueError, match="out"):
            io.read_npy(p, out=np.empty((64, 16), np.float64))
        with pytest.raises(ValueError, match="mutually exclusive"):
            io.read_npy(p, mmap=True, out=np.empty((64, 16), np.float32))

    def test_read_fvecs_into_buffer(self, fvecs_file):
        p, x = fvecs_file
        buf = np.empty((10, 8), np.float32)
        got = io._read_vecs(p, 5, 10, 2, out=buf)
        assert got is buf
        np.testing.assert_array_equal(buf, x[5:15])

    def test_batch_loader_reuse(self, fvecs_file):
        p, x = fvecs_file
        pool = HostBufferPool()
        batches = []
        for b in io.BatchLoader(p, 16, reuse_buffers=True, host_pool=pool):
            batches.append(b.copy())  # the lending contract: copy to retain
        np.testing.assert_array_equal(np.concatenate(batches), x)
        # the ring really cycled: full batches came from <= 2 distinct
        # buffers, and they are back in the pool afterwards
        assert pool.stats()["misses"] <= 3  # 2 full-batch + 1 boundary shape
        assert pool.stats()["free_buffers"] >= 1

    def test_batch_loader_reuse_matches_fresh(self, fvecs_file):
        p, x = fvecs_file
        fresh = [b.copy() for b in io.BatchLoader(p, 16)]
        reused = [b.copy() for b in io.BatchLoader(p, 16, reuse_buffers=True)]
        for a, b in zip(fresh, reused):
            np.testing.assert_array_equal(a, b)
