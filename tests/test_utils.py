"""Utility-layer tests (SURVEY.md §2.2 portable subset)."""

import numpy as np
import pytest

from raft_tpu.utils import (Seive, bounded, canonical_dtype, ceildiv,
                            check_contiguous, check_finite, dtype_code,
                            is_pow2, next_pow2, prev_pow2, primes_up_to,
                            product_of, round_down_safe, round_up_safe)


def test_pow2_family():
    assert ceildiv(10, 3) == 4 and ceildiv(9, 3) == 3 and ceildiv(0, 5) == 0
    assert is_pow2(1) and is_pow2(1024)
    assert not is_pow2(0) and not is_pow2(12)
    assert next_pow2(1) == 1 and next_pow2(5) == 8 and next_pow2(1024) == 1024
    assert prev_pow2(5) == 4 and prev_pow2(1024) == 1024
    assert round_up_safe(10, 8) == 16 and round_down_safe(10, 8) == 8
    assert bounded(5, 0, 3) == 3 and bounded(-1, 0, 3) == 0


def test_seive():
    np.testing.assert_array_equal(primes_up_to(20),
                                  [2, 3, 5, 7, 11, 13, 17, 19])
    s = Seive(100)
    assert s.is_prime(97) and not s.is_prime(91)
    with pytest.raises(ValueError):
        s.is_prime(101)


def test_product_of():
    cases = product_of(rows=[1, 2], cols=[3], k=[4, 5])
    assert len(cases) == 4
    assert {"rows": 2, "cols": 3, "k": 5} in cases


def test_dtype_mapping():
    assert canonical_dtype(np.zeros(2, np.float64)) == np.float32  # x64 off
    assert canonical_dtype("int32") == np.int32
    assert dtype_code(np.float32) == "f4"
    assert dtype_code(np.zeros(1, np.uint8)) == "u1"
    with pytest.raises(ValueError):
        dtype_code(np.dtype([("a", np.int32)]))


def test_validation():
    from raft_tpu.core.errors import LogicError

    check_contiguous(np.zeros((4, 4)))
    with pytest.raises(LogicError):
        check_contiguous(np.zeros((8, 8))[::2, ::2])
    check_finite(np.ones(3))
    with pytest.raises(LogicError):
        check_finite(np.array([1.0, np.nan]))
    check_finite(np.array([1, 2, 3]))  # ints pass trivially
