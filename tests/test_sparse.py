"""Sparse subsystem tests — strategy parity with ``cpp/tests/sparse/`` (25
suites comparing kernels against naive host references, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import sparse
from raft_tpu.sparse import COO, CSR


def _rand_dense(rng, m, n, density=0.3):
    d = rng.standard_normal((m, n)).astype(np.float32)
    mask = rng.random((m, n)) < density
    return d * mask


@pytest.fixture()
def dense(rng):
    return _rand_dense(rng, 17, 23)


# -- containers / conversions ------------------------------------------------

def test_csr_dense_roundtrip(dense):
    csr = CSR.from_dense(dense)
    assert csr.nnz == int(np.count_nonzero(dense))
    np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)


def test_coo_dense_roundtrip(dense):
    coo = COO.from_dense(dense)
    np.testing.assert_allclose(np.asarray(coo.to_dense()), dense)


def test_coo_csr_conversions(dense):
    coo = COO.from_dense(dense)
    csr = sparse.coo_to_csr(coo)
    np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)
    back = sparse.csr_to_coo(csr)
    np.testing.assert_allclose(np.asarray(back.to_dense()), dense)


def test_row_ids_with_empty_rows():
    d = np.zeros((5, 4), np.float32)
    d[0, 1] = 1.0
    d[3, 0] = 2.0
    d[3, 3] = 3.0
    csr = CSR.from_dense(d)
    rid = np.asarray(csr.row_ids())
    np.testing.assert_array_equal(rid, [0, 3, 3])


def test_adj_to_csr(rng):
    adj = rng.random((6, 6)) < 0.4
    csr = sparse.adj_to_csr(adj)
    np.testing.assert_allclose(np.asarray(csr.to_dense()), adj.astype(np.float32))


def test_bitmap_to_csr():
    from raft_tpu.core.bitset import Bitmap

    bm = Bitmap.create_2d(3, 5, default_value=False)
    bm = bm.set2(jnp.asarray([0, 2]), jnp.asarray([1, 4]))
    csr = sparse.bitmap_to_csr(bm)
    dense = np.asarray(csr.to_dense())
    assert dense[0, 1] == 1 and dense[2, 4] == 1 and dense.sum() == 2


# -- linalg ------------------------------------------------------------------

def test_spmv(dense, rng):
    csr = CSR.from_dense(dense)
    x = rng.standard_normal(dense.shape[1]).astype(np.float32)
    out = np.asarray(sparse.spmv(csr, jnp.asarray(x)))
    np.testing.assert_allclose(out, dense @ x, rtol=1e-5, atol=1e-5)


def test_spmm(dense, rng):
    csr = CSR.from_dense(dense)
    b = rng.standard_normal((dense.shape[1], 7)).astype(np.float32)
    out = np.asarray(sparse.spmm(csr, jnp.asarray(b)))
    np.testing.assert_allclose(out, dense @ b, rtol=1e-5, atol=1e-5)


def test_spmm_jit_composes(dense, rng):
    csr = CSR.from_dense(dense)
    b = jnp.asarray(rng.standard_normal((dense.shape[1], 4)).astype(np.float32))
    out = jax.jit(lambda m, x: sparse.spmm(m, x))(csr, b)
    np.testing.assert_allclose(np.asarray(out), dense @ np.asarray(b), rtol=1e-5, atol=1e-5)


def test_sddmm(dense, rng):
    a = rng.standard_normal((17, 9)).astype(np.float32)
    b = rng.standard_normal((9, 23)).astype(np.float32)
    mask = CSR.from_dense(dense)
    out = sparse.sddmm(jnp.asarray(a), jnp.asarray(b), mask, alpha=2.0, beta=0.5)
    full = 2.0 * (a @ b)
    want = np.where(dense != 0, full + 0.5 * dense, 0)
    np.testing.assert_allclose(np.asarray(out.to_dense()), want, rtol=1e-4, atol=1e-4)


def test_masked_matmul(dense, rng):
    a = rng.standard_normal((17, 9)).astype(np.float32)
    b = rng.standard_normal((23, 9)).astype(np.float32)
    mask = CSR.from_dense((dense != 0).astype(np.float32))
    out = sparse.masked_matmul(jnp.asarray(a), jnp.asarray(b), mask)
    want = np.where(dense != 0, a @ b.T, 0)
    np.testing.assert_allclose(np.asarray(out.to_dense()), want, rtol=1e-4, atol=1e-4)


def test_csr_add(rng):
    d1 = _rand_dense(rng, 8, 6)
    d2 = _rand_dense(rng, 8, 6)
    out = sparse.csr_add(CSR.from_dense(d1), CSR.from_dense(d2))
    np.testing.assert_allclose(np.asarray(out.to_dense()), d1 + d2, rtol=1e-5, atol=1e-5)


def test_degree_and_norms(dense):
    csr = CSR.from_dense(dense)
    coo = COO.from_dense(dense)
    np.testing.assert_array_equal(
        np.asarray(sparse.coo_degree(coo)), np.count_nonzero(dense, axis=1)
    )
    np.testing.assert_allclose(
        np.asarray(sparse.csr_row_norm(csr, "l1")), np.abs(dense).sum(1), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(sparse.csr_row_norm(csr, "l2")), (dense ** 2).sum(1), rtol=1e-5
    )
    l1 = sparse.csr_row_normalize_l1(csr)
    sums = np.abs(np.asarray(l1.to_dense())).sum(1)
    nz = np.count_nonzero(dense, axis=1) > 0
    np.testing.assert_allclose(sums[nz], 1.0, rtol=1e-5)


def test_transpose(dense):
    csr = CSR.from_dense(dense)
    t = sparse.csr_transpose(csr)
    np.testing.assert_allclose(np.asarray(t.to_dense()), dense.T)


def test_symmetrize(rng):
    d = _rand_dense(rng, 9, 9)
    np.fill_diagonal(d, 0)
    coo = COO.from_dense(d)
    sym = sparse.coo_symmetrize(coo)
    np.testing.assert_allclose(np.asarray(sym.to_dense()), d + d.T, rtol=1e-5, atol=1e-6)


def test_laplacian(rng):
    adj_mask = rng.random((10, 10)) < 0.3
    adj_mask = np.triu(adj_mask, 1)
    a = (adj_mask | adj_mask.T).astype(np.float32)
    lap = sparse.compute_graph_laplacian(CSR.from_dense(a))
    want = np.diag(a.sum(1)) - a
    np.testing.assert_allclose(np.asarray(lap.to_dense()), want, rtol=1e-5, atol=1e-6)


# -- structural ops ----------------------------------------------------------

def test_coo_sort_and_dedup():
    rows = np.asarray([2, 0, 0, 2, 1], np.int32)
    cols = np.asarray([1, 3, 3, 1, 0], np.int32)
    vals = np.asarray([5.0, 1.0, 2.0, 7.0, 3.0], np.float32)
    coo = COO.from_arrays(rows, cols, vals, (3, 4))
    summed = sparse.coo_sum_duplicates(coo)
    dense = np.asarray(summed.to_dense())
    assert dense[0, 3] == 3.0 and dense[2, 1] == 12.0 and dense[1, 0] == 3.0
    assert summed.nnz == 3
    kept = sparse.coo_max_duplicates(coo)
    dense = np.asarray(kept.to_dense())
    assert dense[0, 3] == 2.0 and dense[2, 1] == 7.0


def test_coo_remove_scalar():
    coo = COO.from_arrays([0, 0, 1], [0, 1, 2], [1.0, 0.0, 2.0], (2, 3))
    out = sparse.coo_remove_zeros(coo)
    assert out.nnz == 2
    dense = np.asarray(out.to_dense())
    assert dense[0, 0] == 1.0 and dense[1, 2] == 2.0


def test_csr_slice_rows(dense):
    csr = CSR.from_dense(dense)
    sl = sparse.csr_slice_rows(csr, 3, 9)
    np.testing.assert_allclose(np.asarray(sl.to_dense()), dense[3:9])


def test_csr_diagonal(rng):
    d = _rand_dense(rng, 7, 7)
    np.fill_diagonal(d, np.arange(1, 8))
    csr = CSR.from_dense(d)
    np.testing.assert_allclose(np.asarray(sparse.csr_diagonal(csr)), np.arange(1, 8))
    updated = sparse.csr_set_diagonal(csr, jnp.full((7,), 9.0))
    np.testing.assert_allclose(np.asarray(sparse.csr_diagonal(updated)), 9.0)


def test_csr_row_op(dense):
    csr = CSR.from_dense(dense)
    doubled = sparse.csr_row_op(csr, lambda rid, vals: vals * 2.0)
    np.testing.assert_allclose(np.asarray(doubled.to_dense()), dense * 2)


# -- preprocessing -----------------------------------------------------------

def test_tfidf_matches_formula(rng):
    counts = (rng.random((12, 20)) < 0.3) * rng.integers(1, 5, (12, 20))
    counts = counts.astype(np.float32)
    csr = CSR.from_dense(counts)
    out = np.asarray(sparse.encode_tfidf(csr).to_dense())
    df = np.count_nonzero(counts, axis=0)
    idf = np.log1p(12 / (1.0 + df))
    want = counts * idf[None, :]
    np.testing.assert_allclose(out, want.astype(np.float32), rtol=1e-5, atol=1e-6)


def test_bm25_basic_properties(rng):
    counts = ((rng.random((10, 15)) < 0.4) * rng.integers(1, 6, (10, 15))).astype(np.float32)
    csr = CSR.from_dense(counts)
    out = np.asarray(sparse.encode_bm25(csr).to_dense())
    assert out.shape == counts.shape
    assert np.all((out != 0) == (counts != 0))
    assert np.all(out[counts != 0] > 0)


# -- CSR select_k ------------------------------------------------------------

def test_csr_select_k(dense):
    csr = CSR.from_dense(dense)
    vals, cols = sparse.csr_select_k(csr, 3, select_min=True)
    for r in range(dense.shape[0]):
        nz_cols = np.nonzero(dense[r])[0]
        nz_vals = dense[r, nz_cols]
        order = np.argsort(nz_vals)[:3]
        got_vals = np.asarray(vals[r])
        finite = np.isfinite(got_vals)
        np.testing.assert_allclose(got_vals[finite], np.sort(nz_vals)[: finite.sum()], rtol=1e-6)
        got_cols = np.asarray(cols[r])[finite]
        np.testing.assert_array_equal(np.sort(got_cols), np.sort(nz_cols[order[: finite.sum()]]))
