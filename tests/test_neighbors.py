"""Brute-force kNN tests — exact results vs numpy argsort; sharded variant on
the virtual 8-device mesh (SURVEY.md §4 TPU translation of LocalCUDACluster)."""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.neighbors import knn
from raft_tpu.neighbors.brute_force import knn_sharded
from raft_tpu.stats import neighborhood_recall


def _ref_knn(x, y, k, metric="sqeuclidean"):
    d = spd.cdist(x, y, metric if metric != "inner_product" else "cosine")
    if metric == "inner_product":
        d = -(x @ y.T)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "cosine"])
def test_knn_exact(rng, metric):
    x = rng.standard_normal((25, 10)).astype(np.float32)
    y = rng.standard_normal((200, 10)).astype(np.float32)
    ref_d, ref_i = _ref_knn(x, y, 5, metric)
    d, i = knn(x, y, 5, metric=metric, tile=64)
    np.testing.assert_allclose(np.asarray(d), ref_d, rtol=1e-3, atol=1e-3)
    # indices can differ on exact ties; compare via recall
    rec = float(neighborhood_recall(np.asarray(i), ref_i))
    assert rec >= 0.999


def test_knn_inner_product(rng):
    x = rng.standard_normal((12, 8)).astype(np.float32)
    y = rng.standard_normal((90, 8)).astype(np.float32)
    sims = x @ y.T
    ref_i = np.argsort(-sims, axis=1)[:, :4]
    d, i = knn(x, y, 4, metric="inner_product", tile=32)
    assert float(neighborhood_recall(np.asarray(i), ref_i)) >= 0.999
    # returned "distances" are similarities, descending
    got = np.asarray(d)
    assert np.all(np.diff(got, axis=1) <= 1e-5)


def test_knn_k1_and_padding(rng):
    x = rng.standard_normal((5, 3)).astype(np.float32)
    y = rng.standard_normal((17, 3)).astype(np.float32)  # not multiple of tile
    d, i = knn(x, y, 1, tile=8)
    ref = spd.cdist(x, y, "sqeuclidean")
    np.testing.assert_array_equal(np.asarray(i)[:, 0], ref.argmin(1))


def test_knn_sorted_output(rng):
    x = rng.standard_normal((9, 6)).astype(np.float32)
    y = rng.standard_normal((64, 6)).astype(np.float32)
    d, _ = knn(x, y, 10, tile=16)
    d = np.asarray(d)
    assert np.all(np.diff(d, axis=1) >= -1e-6)


def test_knn_sharded_matches_single(rng, mesh8):
    x = rng.standard_normal((16, 12)).astype(np.float32)
    y = rng.standard_normal((320, 12)).astype(np.float32)  # 40 rows/shard
    d_ref, i_ref = knn(x, y, 8)
    d, i = knn_sharded(x, y, 8, mesh=mesh8)
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), rtol=1e-4, atol=1e-4)
    rec = float(neighborhood_recall(np.asarray(i), np.asarray(i_ref)))
    assert rec >= 0.999


def test_knn_sharded_inner_product(rng, mesh8):
    x = rng.standard_normal((6, 5)).astype(np.float32)
    y = rng.standard_normal((80, 5)).astype(np.float32)
    d_ref, i_ref = knn(x, y, 3, metric="inner_product")
    d, i = knn_sharded(x, y, 3, mesh=mesh8, metric="inner_product")
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), rtol=1e-4, atol=1e-4)


def test_end_to_end_blobs_recall(rng):
    """SURVEY.md §7 minimum slice: blobs → brute kNN → recall ≈ 1."""
    from raft_tpu.random import RngState, make_blobs

    x, labels = make_blobs(RngState(3), 256, 16, n_clusters=8)
    x = np.asarray(x)
    ref_d, ref_i = _ref_knn(x, x, 10)
    d, i = knn(x, x, 10, tile=64)
    assert float(neighborhood_recall(np.asarray(i), ref_i)) >= 0.999


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "cosine", "inner_product"])
def test_knn_fast_mode(rng, metric):
    """fast mode = bf16 shortlist + exact refine; on the CPU fallback the
    shortlist is wide enough that results should match exact for small n."""
    x = rng.standard_normal((12, 24)).astype(np.float32)
    y = rng.standard_normal((300, 24)).astype(np.float32)
    d_ref, i_ref = knn(x, y, 5, metric=metric)
    d, i = knn(x, y, 5, metric=metric, mode="fast", cand=64)
    rec = float(neighborhood_recall(np.asarray(i), np.asarray(i_ref)))
    assert rec >= 0.95, rec
    np.testing.assert_allclose(np.sort(np.asarray(d), axis=1),
                               np.sort(np.asarray(d_ref), axis=1)[:, :5],
                               rtol=2e-2, atol=2e-2)


def test_knn_fast_mode_approx_cut(rng):
    """cut='approx' (approx_max_k shortlist cut) must stay near-exact —
    the final ranking is still an exact f32 rescore.  n > one 65536-row
    tile, so the CPU-fallback shortlist (kk per tile, concatenated) is
    wider than cand and the cut genuinely selects (cand of 2·cand) —
    with n <= tile the cut is width-preserving and the test is vacuous."""
    x = rng.standard_normal((8, 8)).astype(np.float32)
    y = rng.standard_normal((70_000, 8)).astype(np.float32)
    _, i_ref = knn(x, y, 5)
    _, i = knn(x, y, 5, mode="fast", cand=32, cut="approx")
    assert float(neighborhood_recall(np.asarray(i), np.asarray(i_ref))) >= 0.95


def test_knn_fast_mode_refine_precision(rng):
    """refine_precision='high' (bf16x3 rescore) must keep the ranking on
    clearly-separated data, and unknown values must be rejected."""
    from raft_tpu.core.errors import LogicError

    x = rng.standard_normal((10, 16)).astype(np.float32)
    y = rng.standard_normal((400, 16)).astype(np.float32)
    _, i_ref = knn(x, y, 5)
    _, i = knn(x, y, 5, mode="fast", cand=64, refine_precision="high")
    assert float(neighborhood_recall(np.asarray(i), np.asarray(i_ref))) >= 0.95
    with pytest.raises(LogicError, match="refine_precision"):
        knn(x, y, 5, mode="fast", refine_precision="medium")


def test_knn_sharded_ring_matches_gather(rng, mesh8):
    x = rng.standard_normal((10, 8)).astype(np.float32)
    y = rng.standard_normal((160, 8)).astype(np.float32)
    d_g, i_g = knn_sharded(x, y, 5, mesh=mesh8, merge="gather")
    d_r, i_r = knn_sharded(x, y, 5, mesh=mesh8, merge="ring")
    np.testing.assert_allclose(np.asarray(d_r), np.asarray(d_g), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(i_r), np.asarray(i_g))


def test_knn_sharded_ring_inner_product(rng, mesh8):
    x = rng.standard_normal((6, 5)).astype(np.float32)
    y = rng.standard_normal((80, 5)).astype(np.float32)
    d_ref, i_ref = knn(x, y, 3, metric="inner_product")
    d, i = knn_sharded(x, y, 3, mesh=mesh8, metric="inner_product", merge="ring")
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), rtol=1e-4, atol=1e-4)


def test_knn_sharded_ring_k_exceeds_rows(rng, mesh8):
    # per-shard rows (2) < k (5): ring buffers must pad correctly
    x = rng.standard_normal((4, 6)).astype(np.float32)
    y = rng.standard_normal((16, 6)).astype(np.float32)
    d_ref, i_ref = knn(x, y, 5)
    d, i = knn_sharded(x, y, 5, mesh=mesh8, merge="ring")
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), rtol=1e-5)


def test_knn_sharded_2d_mesh_data_parallel(rng, mesh2x4):
    """Query-data-parallel x index-shard-parallel search on a 2-D mesh
    (the hybrid ICI/DCN composition; collectives stay on the shard axis)."""
    x = rng.standard_normal((512, 24)).astype(np.float32)
    q = rng.standard_normal((64, 24)).astype(np.float32)
    d_ref, i_ref = knn(q, x, 7)
    d, i = knn_sharded(q, x, 7, mesh=mesh2x4, axis="shard", data_axis="data")
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_make_hybrid_mesh_virtual(devices):
    from raft_tpu.core import make_hybrid_mesh

    mesh = make_hybrid_mesh(dcn_size=2)
    assert mesh.axis_names == ("data", "shard")
    assert mesh.shape["data"] == 2 and mesh.shape["shard"] == len(devices) // 2
