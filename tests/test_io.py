"""IO layer tests: native C++ reader vs NumPy references (SURVEY.md §4's
kernel-vs-naive-host-reference pattern applied to the IO subsystem)."""

import os
import shutil

import numpy as np
import pytest

from raft_tpu import io as rio
from raft_tpu.io import native


def _write_vecs(path, mat, elem_dtype):
    rows, dim = mat.shape
    with open(path, "wb") as f:
        for r in range(rows):
            np.int32(dim).tofile(f)
            mat[r].astype(elem_dtype).tofile(f)


@pytest.mark.skipif(
    not (shutil.which("g++") and shutil.which("make")),
    reason="no C++ toolchain — package contract degrades to pure NumPy",
)
def test_native_builds():
    # with a toolchain present the fast path must load
    assert native.available()


def test_npy_ndim_overflow_falls_back(tmp_path):
    """ndim > 8 exceeds the native header struct: the native parser must
    error (not silently truncate) so the np.load fallback returns the full
    array (ADVICE r1, cpp/raft_tpu_io.cpp rt_npy_header)."""
    a = np.arange(2 ** 9, dtype=np.float32).reshape((2,) * 9)
    p = str(tmp_path / "deep.npy")
    np.save(p, a)
    out = rio.read_npy(p)
    assert out.shape == a.shape
    np.testing.assert_array_equal(out, a)


@pytest.mark.parametrize("ext,dtype", [(".fvecs", np.float32),
                                       (".ivecs", np.int32),
                                       (".bvecs", np.uint8)])
def test_vecs_roundtrip(tmp_path, rng, ext, dtype):
    mat = (rng.normal(size=(37, 12)) * 10).astype(dtype)
    p = str(tmp_path / f"data{ext}")
    _write_vecs(p, mat, dtype)
    assert rio.vecs_shape(p) == (37, 12)
    np.testing.assert_array_equal(rio.read_fvecs(p) if ext == ".fvecs"
                                  else rio.read_ivecs(p) if ext == ".ivecs"
                                  else rio.read_bvecs(p), mat)
    # partial range
    part = rio.read_fvecs(p, 5, 9) if ext == ".fvecs" else \
        rio.read_ivecs(p, 5, 9) if ext == ".ivecs" else rio.read_bvecs(p, 5, 9)
    np.testing.assert_array_equal(part, mat[5:14])


def test_read_npy_native_matches_numpy(tmp_path, rng):
    for arr in [rng.normal(size=(50, 7)).astype(np.float32),
                (rng.normal(size=(3, 4, 5)) * 100).astype(np.int64),
                rng.normal(size=(2049,)).astype(np.float64)]:
        p = str(tmp_path / "a.npy")
        np.save(p, arr)
        np.testing.assert_array_equal(rio.read_npy(p), arr)
        np.testing.assert_array_equal(rio.read_npy(p, mmap=True), arr)


def test_npy_header_parse(tmp_path):
    p = str(tmp_path / "h.npy")
    np.save(p, np.zeros((6, 3), np.float32))
    if not native.available():
        pytest.skip("native lib unavailable")
    descr, shape, fortran, off = native.npy_header(p)
    assert descr == "<f4" and shape == (6, 3) and not fortran and off >= 64


def test_batch_loader(tmp_path, rng):
    mat = rng.normal(size=(100, 8)).astype(np.float32)
    p = str(tmp_path / "d.fvecs")
    _write_vecs(p, mat, np.float32)
    loader = rio.BatchLoader(p, 32)
    assert len(loader) == 4 and loader.dim == 8
    batches = list(loader)
    assert [b.shape[0] for b in batches] == [32, 32, 32, 4]
    np.testing.assert_array_equal(np.concatenate(batches), mat)


def test_vecs_out_of_range(tmp_path, rng):
    mat = rng.normal(size=(10, 4)).astype(np.float32)
    p = str(tmp_path / "d.fvecs")
    _write_vecs(p, mat, np.float32)
    with pytest.raises(ValueError):
        rio.read_fvecs(p, 5, 100)


def test_read_npy_structured_dtype_falls_back(tmp_path):
    # the C parser can't express structured dtypes; read_npy must still load
    arr = np.zeros(5, dtype=[("a", np.float32), ("b", np.int32)])
    p = str(tmp_path / "s.npy")
    np.save(p, arr)
    got = rio.read_npy(p)
    np.testing.assert_array_equal(got, arr)


# ---------------------------------------------------------------------------
# load-once / fallback behavior (ISSUE 14 satellite)


def test_pread_dense_matches_npy_bytes(tmp_path, rng):
    """Native threaded pread of a .npy's data region returns exactly the
    bytes np.load sees — the shard store's fast path contract."""
    if not native.available():
        pytest.skip("native lib unavailable")
    arr = rng.normal(size=(257, 12)).astype(np.float32)
    p = str(tmp_path / "a.npy")
    np.save(p, arr)
    _, _, _, off = native.npy_header(p)
    out = np.empty_like(arr)
    assert native.pread_dense_into(p, off, out, threads=4)
    np.testing.assert_array_equal(out, np.load(p))


def test_reset_for_tests_pins_fallback(tmp_path, rng):
    """_reset_for_tests(None) forces every entry point onto the pure
    NumPy path without touching the filesystem or spawning a build."""
    arr = rng.normal(size=(40, 6)).astype(np.float32)
    p = str(tmp_path / "a.npy")
    np.save(p, arr)
    try:
        native._reset_for_tests(None)
        assert not native.available()
        assert native.npy_header(p) is None
        assert native.vecs_info(p, 4) is None
        out = np.empty_like(arr)
        assert not native.pread_dense_into(p, 128, out)
        # the public readers still work, through the fallback
        np.testing.assert_array_equal(rio.read_npy(p), arr)
    finally:
        native._reset_for_tests()


def test_missing_toolchain_is_quiet(monkeypatch, tmp_path):
    """No library on disk + no toolchain: _load() returns None without
    raising or attempting a subprocess — the package degrades silently
    to pure NumPy (auto-build is strictly best-effort)."""
    calls = []
    monkeypatch.setattr(native.subprocess, "run",
                        lambda *a, **k: calls.append(a))
    monkeypatch.setattr(native, "_LIB_NAME", "libdoes_not_exist.so")
    import shutil as _shutil
    monkeypatch.setattr(_shutil, "which", lambda *_: None)
    try:
        native._reset_for_tests()        # re-arm the load-once latch
        assert native._load() is None
        assert not native.available()    # latched: no repeat attempts
        assert calls == []               # and no build was ever spawned
    finally:
        native._reset_for_tests()


def test_build_optout_env_is_quiet(monkeypatch):
    """RAFT_TPU_BUILD_NATIVE=0 skips the auto-build even with a full
    toolchain present."""
    calls = []
    monkeypatch.setattr(native.subprocess, "run",
                        lambda *a, **k: calls.append(a))
    monkeypatch.setattr(native, "_LIB_NAME", "libdoes_not_exist.so")
    monkeypatch.setenv("RAFT_TPU_BUILD_NATIVE", "0")
    try:
        native._reset_for_tests()
        assert native._load() is None
        assert calls == []
    finally:
        native._reset_for_tests()


# ---------------------------------------------------------------------------
# sharded-store read robustness (ISSUE 15)


def _sharded(tmp_path, rng, **open_kw):
    from raft_tpu.io import shards

    data = rng.standard_normal((64, 8)).astype(np.float32)
    root = str(tmp_path / "store")
    shards.write_store(root, data, rows_per_shard=16)
    return shards.ShardedVectorStore.open(root, **open_kw), data, root


def test_shard_gather_retries_transient_failures(tmp_path, rng):
    from raft_tpu.obs.metrics import registry

    st, data, _ = _sharded(tmp_path, rng)
    counter = registry().counter("raft_ooc_shard_read_retries_total", "")
    before = counter.value()
    orig = st._read_with_retry
    fails = {"left": 2}

    def flaky_retry(what, fn):
        def flaky():
            if fails["left"] > 0:
                fails["left"] -= 1
                raise OSError(4, "interrupted system call")  # EINTR
            return fn()
        return orig(what, flaky)

    st._read_with_retry = flaky_retry
    got = st.gather(np.array([3, 21, 48]))
    np.testing.assert_array_equal(got, data[[3, 21, 48]])
    assert counter.value() - before == 2  # both transients were counted


def test_shard_retry_budget_exhausts_loudly(tmp_path, rng):
    from raft_tpu.io import shards

    st, _, _ = _sharded(tmp_path, rng)

    def always_fails():
        raise OSError(5, "I/O error")

    with pytest.raises(OSError):
        st._read_with_retry("gather:test", always_fails)


def test_shard_verify_on_gather_catches_bitflip(tmp_path, rng):
    from raft_tpu.core.serialize import CorruptArtifact

    st, data, root = _sharded(tmp_path, rng, verify_on_gather=True)
    # clean store: verification passes and is cached per shard
    np.testing.assert_array_equal(st.gather(np.array([17])), data[[17]])
    shard1 = os.path.join(root, "shard-00001.npy")
    with open(shard1, "r+b") as f:
        f.seek(-3, os.SEEK_END)
        byte = f.read(1)[0]
        f.seek(-3, os.SEEK_END)
        f.write(bytes([byte ^ 0xFF]))
    # already-verified shard: the first-touch check does not re-run ...
    np.testing.assert_array_equal(
        np.asarray(st.gather(np.array([0]))), data[[0]])  # shard 0 clean
    # ... but a fresh open sees the corruption on first touch
    from raft_tpu.io import shards

    st2 = shards.ShardedVectorStore.open(root, verify_on_gather=True)
    with pytest.raises(CorruptArtifact):
        st2.gather(np.array([17]))
    # default mode stays permissive (checksums opt-in, as before)
    st3 = shards.ShardedVectorStore.open(root)
    assert st3.gather(np.array([17])).shape == (1, 8)


def test_shard_verify_env_opt_in(tmp_path, rng, monkeypatch):
    from raft_tpu.io import shards

    _, _, root = _sharded(tmp_path, rng)
    monkeypatch.setenv("RAFT_TPU_SHARD_VERIFY", "1")
    assert shards.ShardedVectorStore.open(root).verify_on_gather
    monkeypatch.delenv("RAFT_TPU_SHARD_VERIFY")
    assert not shards.ShardedVectorStore.open(root).verify_on_gather
