"""racelint rule fixtures: each of JX10–JX14 firing AND waived, the
JXW1 reasonless-waiver contract, and the tree gate (the library scans
clean — the census ``bench/RACELINT.json`` commits).

The fixtures pass ``rel`` paths under ``raft_tpu/`` so the driver/test
allowlists (which exempt ``tests/`` itself) do not apply.
"""

import os
import textwrap

from raft_tpu.analysis import racelint

LIB = "raft_tpu/serve/fixture.py"


def _scan(src: str, rel: str = LIB):
    return racelint.scan_source(textwrap.dedent(src), rel, rel)


def _active(findings, code):
    return [f for f in findings if f.code == code and not f.waived]


def _waived(findings, code):
    return [f for f in findings if f.code == code and f.waived]


# -- JX10: guarded-attribute writes -------------------------------------


def test_jx10_fires_on_unguarded_assign_and_mutator():
    fs = _scan("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []  # guarded_by: _lock

            def put(self, x):
                self.items.append(x)

            def reset(self):
                self.items = []
        """)
    hits = _active(fs, "JX10")
    assert len(hits) == 2
    assert all("items" in f.msg for f in hits)


def test_jx10_quiet_under_lock_ctor_and_holds_annotation():
    fs = _scan("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []  # guarded_by: _lock
                self.items = ["ctor writes are thread-private"]

            def put(self, x):
                with self._lock:
                    self.items.append(x)

            def _put_locked(self, x):  # racelint: holds _lock
                self.items.append(x)
        """)
    assert not _active(fs, "JX10")


def test_jx10_module_level_guard():
    fs = _scan("""
        import threading

        _lock = threading.Lock()
        _stats = {"n": 0}  # guarded_by: _lock

        def bump():
            _stats["n"] += 1

        def bump_locked():
            with _lock:
                _stats["n"] += 1
        """)
    hits = _active(fs, "JX10")
    assert len(hits) == 1 and "_stats" in hits[0].msg


def test_jx10_waiver_with_reason():
    fs = _scan("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []  # guarded_by: _lock

            def rebuild(self):
                self.items = []  # racelint: disable=JX10 swap happens before worker start
        """)
    assert not _active(fs, "JX10")
    w = _waived(fs, "JX10")
    assert len(w) == 1 and "worker start" in w[0].reason


# -- JX11: lock-order consistency ---------------------------------------


def test_jx11_fires_on_reversed_order():
    fs = _scan("""
        import threading

        class Two:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
        """)
    hits = _active(fs, "JX11")
    assert len(hits) == 2
    assert any("Two._a" in f.msg and "Two._b" in f.msg for f in hits)


def test_jx11_quiet_on_consistent_order():
    fs = _scan("""
        import threading

        class Two:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def also_ab(self):
                with self._a, self._b:
                    pass
        """)
    assert not _active(fs, "JX11")


# -- JX12: blocking under a lock ----------------------------------------


def test_jx12_fires_on_sleep_and_fsync_under_lock():
    fs = _scan("""
        import os
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def spin(self):
                with self._lock:
                    time.sleep(0.5)

            def flush(self, fd):
                with self._lock:
                    os.fsync(fd)
        """)
    assert len(_active(fs, "JX12")) == 2


def test_jx12_matches_underscored_seams_and_respects_waivers():
    fs = _scan("""
        import threading

        class W:
            def __init__(self, fsync):
                self._lock = threading.Lock()
                self._fsync = fsync

            def flush(self, fd):
                with self._lock:
                    self._fsync(fd)  # racelint: disable=JX12 the fsync is this path's whole job

            def flush_loud(self, fd):
                with self._lock:
                    self._fsync(fd)
        """)
    assert len(_active(fs, "JX12")) == 1
    assert len(_waived(fs, "JX12")) == 1


def test_jx12_exempt_in_tests_and_scripts():
    src = """
        import threading
        import time

        _lock = threading.Lock()

        def drill():
            with _lock:
                time.sleep(1.0)
        """
    assert _active(_scan(src, "tests/test_drill.py"), "JX12") == []
    assert _active(_scan(src, "scripts/drill.py"), "JX12") == []
    assert len(_active(_scan(src, LIB), "JX12")) == 1


# -- JX13: callbacks under undocumented locks ---------------------------


def test_jx13_fires_on_undocumented_hook_call_and_loop():
    fs = _scan("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_commit = []

            def commit(self, rec):
                with self._lock:
                    for hook in list(self.on_commit):
                        hook(rec)
        """)
    assert len(_active(fs, "JX13")) == 1


def test_jx13_quiet_when_documented_called_under():
    fs = _scan("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_commit = []  # called_under: _lock hooks see LSN order

            def commit(self, rec):
                with self._lock:
                    for hook in list(self.on_commit):
                        hook(rec)
        """)
    assert not _active(fs, "JX13")


def test_jx13_waiver():
    fs = _scan("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_swap = None

            def swap(self):
                with self._lock:
                    self.on_swap()  # racelint: disable=JX13 single wired callee, documented in the class docstring
        """)
    assert not _active(fs, "JX13")
    assert len(_waived(fs, "JX13")) == 1


# -- JX14: daemon threads touching jax dispatch -------------------------

_JX14_SRC = """
    import threading

    import jax

    class Worker:
        def _loop(self):
            self._step()

        def _step(self):
            jax.effects_barrier()

        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
"""


def test_jx14_fires_through_same_class_helpers():
    hits = _active(_scan(_JX14_SRC), "JX14")
    assert len(hits) == 1 and "_loop" in hits[0].msg


def test_jx14_quiet_for_jax_free_target_and_exempt_paths():
    fs = _scan("""
        import threading

        class Worker:
            def _loop(self):
                pass

            def start(self):
                self._thread = threading.Thread(target=self._loop)
        """)
    assert not _active(fs, "JX14")
    assert _active(_scan(_JX14_SRC, "tests/test_worker.py"), "JX14") == []


def test_jx14_waiver():
    fs = _scan(_JX14_SRC.replace(
        "threading.Thread(target=self._loop, daemon=True)",
        "threading.Thread(  # racelint: disable=JX14 owns its compiled executable\n"
        "                target=self._loop, daemon=True)"))
    assert not _active(fs, "JX14")
    assert len(_waived(fs, "JX14")) == 1


# -- JXW1 + report plumbing ---------------------------------------------


def test_reasonless_waiver_still_waives_but_is_itself_a_finding():
    fs = _scan("""
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def spin(self):
                with self._lock:
                    time.sleep(0.5)  # racelint: disable=JX12
        """)
    assert not _active(fs, "JX12")
    assert len(_waived(fs, "JX12")) == 1
    assert len(_active(fs, "JXW1")) == 1


def test_unparseable_source_is_jx99():
    fs = _scan("def broken(:\n")
    assert [f.code for f in fs] == ["JX99"]


def test_stats_schema_matches_jaxlint_contract():
    rep = racelint.Report([], [], 3)
    st = rep.stats()
    for key in ("tool", "files_scanned", "rules_fired", "unwaived_findings",
                "waivers", "waiver_total", "waiver_sites", "rule_catalog"):
        assert key in st
    assert st["tool"] == "racelint"
    assert st["rule_catalog"] == racelint.ALL_RULES


# -- the gate: the library tree scans clean -----------------------------


def test_library_tree_has_zero_active_findings():
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "raft_tpu")
    rep = racelint.scan_tree(root)
    assert rep.files > 100
    msgs = [f"{f.path}:{f.line} {f.code} {f.msg}" for f in rep.findings]
    assert not msgs, "\n".join(msgs)
    # every waiver in the tree carries a written reason
    assert all(f.reason for f in rep.waived)
