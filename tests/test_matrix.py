"""matrix tests — parity with ``cpp/tests/matrix/`` (20 suites), esp.
``select_k.cu`` + ``select_large_k.cu``: every algo validated against a full
argsort reference, including ties and infinities."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import matrix
from raft_tpu.matrix import SelectAlgo


def select_k_reference(vals, k, select_min=True):
    order = np.argsort(vals if select_min else -vals, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(vals, order, axis=1), order


# every algorithm, kAuto included — the dispatch table makes each one
# production-reachable (Pallas runs in interpret mode on the CPU mesh)
ALGOS = list(SelectAlgo)


class TestSelectK:
    @pytest.mark.parametrize("algo", ALGOS)
    @pytest.mark.parametrize("batch,length,k", [(1, 100, 10), (16, 1024, 32), (4, 5000, 128), (3, 7, 7)])
    def test_values_match_reference(self, rng, algo, batch, length, k):
        x = rng.standard_normal((batch, length)).astype(np.float32)
        vals, idx = matrix.select_k(x, k, select_min=True, algo=algo)
        ref_vals, _ = select_k_reference(x, k)
        np.testing.assert_allclose(np.sort(np.asarray(vals), axis=1), np.sort(ref_vals, axis=1), rtol=1e-6)
        # indices must point at the returned values
        gathered = np.take_along_axis(x, np.asarray(idx), axis=1)
        np.testing.assert_allclose(gathered, np.asarray(vals), rtol=1e-6)

    @pytest.mark.parametrize("algo", ALGOS)
    def test_select_max(self, rng, algo):
        x = rng.standard_normal((8, 256)).astype(np.float32)
        vals, idx = matrix.select_k(x, 16, select_min=False, algo=algo)
        ref_vals, _ = select_k_reference(x, 16, select_min=False)
        np.testing.assert_allclose(np.sort(np.asarray(vals)), np.sort(ref_vals), rtol=1e-6)

    def test_with_ties(self):
        x = np.tile(np.array([[3.0, 1.0, 1.0, 1.0, 2.0]], np.float32), (2, 1))
        vals, idx = matrix.select_k(x, 3)
        np.testing.assert_allclose(np.asarray(vals), [[1, 1, 1], [1, 1, 1]])
        assert set(np.asarray(idx)[0]) == {1, 2, 3}

    def test_with_inf(self):
        x = np.array([[np.inf, 1.0, -np.inf, 5.0]], np.float32)
        vals, _ = matrix.select_k(x, 2)
        np.testing.assert_allclose(np.asarray(vals), [[-np.inf, 1.0]])

    def test_in_idx_payload(self, rng):
        x = rng.standard_normal((2, 50)).astype(np.float32)
        payload = (np.arange(100).reshape(2, 50) * 7).astype(np.int64)
        vals, idx = matrix.select_k(x, 5, in_idx=payload)
        _, ref_order = select_k_reference(x, 5)
        assert set(np.asarray(idx)[0]) == set(payload[0][ref_order[0]])

    def test_k_larger_than_length_pads(self, rng):
        x = rng.standard_normal((2, 4)).astype(np.float32)
        vals, idx = matrix.select_k(x, 6)
        assert vals.shape == (2, 6)
        assert np.isinf(np.asarray(vals)[:, 4:]).all()
        assert (np.asarray(idx)[:, 4:] == -1).all()

    def test_large_k(self, rng):
        # select_large_k.cu parity: k > 256
        x = rng.standard_normal((2, 2048)).astype(np.float32)
        vals, idx = matrix.select_k(x, 512)
        ref_vals, _ = select_k_reference(x, 512)
        np.testing.assert_allclose(np.sort(np.asarray(vals)), np.sort(ref_vals), rtol=1e-6)

    @pytest.mark.parametrize("select_min", [True, False])
    # no int64: jax demotes it to int32 without x64 mode, so the output
    # dtype (and pad extreme) would be int32's, not the input's
    @pytest.mark.parametrize("dtype", [np.int32, np.uint8])
    def test_k_larger_than_length_integer_pads(self, rng, dtype, select_min):
        # integer rows can't pad with inf — regression: this used to raise
        # inside jnp.full; pads must use the dtype's never-selected extreme
        x = rng.integers(0, 50, size=(2, 4)).astype(dtype)
        vals, idx = matrix.select_k(x, 6, select_min=select_min)
        assert vals.shape == (2, 6) and vals.dtype == dtype
        info = np.iinfo(dtype)
        want_pad = info.max if select_min else info.min
        assert (np.asarray(vals)[:, 4:] == want_pad).all()
        assert (np.asarray(idx)[:, 4:] == -1).all()
        # the real entries are still the full (sorted) row
        ref_vals, _ = select_k_reference(x.astype(np.int64), 4,
                                         select_min=select_min)
        np.testing.assert_array_equal(np.asarray(vals)[:, :4].astype(np.int64),
                                      ref_vals)

    @pytest.mark.parametrize("algo", ALGOS)
    @pytest.mark.parametrize("select_min", [True, False])
    def test_unsorted_returns_exact_set(self, rng, algo, select_min):
        # sorted=False relaxes only the ORDER: the (value, index) pairs
        # must still be exactly the top-k set.  Never assert the output is
        # actually unordered — argpartition may legally return sorted rows.
        x = rng.standard_normal((8, 300)).astype(np.float32)
        k = 17
        vals, idx = matrix.select_k(x, k, select_min=select_min,
                                    sorted=False, algo=algo)
        ref_vals, _ = select_k_reference(x, k, select_min=select_min)
        np.testing.assert_allclose(np.sort(np.asarray(vals), axis=1),
                                   np.sort(ref_vals, axis=1), rtol=1e-6)
        gathered = np.take_along_axis(x, np.asarray(idx), axis=1)
        np.testing.assert_allclose(gathered, np.asarray(vals), rtol=1e-6)

    def test_unsorted_k_ge_length_whole_row(self, rng):
        # k >= length routes to kSortFull; unsorted must still return every
        # element exactly once (the blocked-scan carry relies on this)
        x = rng.standard_normal((3, 9)).astype(np.float32)
        vals, idx = matrix.select_k(x, 9, sorted=False)
        np.testing.assert_allclose(np.sort(np.asarray(vals), axis=1),
                                   np.sort(x, axis=1), rtol=0)
        for row in np.asarray(idx):
            assert sorted(row.tolist()) == list(range(9))


class TestGatherScatter:
    def test_gather(self, rng):
        m = rng.random((10, 4)).astype(np.float32)
        rows = np.array([3, 1, 7])
        np.testing.assert_array_equal(np.asarray(matrix.gather(m, rows)), m[rows])

    def test_gather_if(self, rng):
        m = rng.random((10, 4)).astype(np.float32)
        rows = np.array([0, 1, 2, 3])
        stencil = np.array([1.0, 0.0, 1.0, 0.0])
        out = np.asarray(matrix.gather_if(m, rows, stencil, lambda s: s > 0.5))
        np.testing.assert_array_equal(out[0], m[0])
        np.testing.assert_array_equal(out[1], np.zeros(4))

    def test_scatter(self, rng):
        m = rng.random((4, 3)).astype(np.float32)
        dest = np.array([2, 0, 3, 1])
        out = np.asarray(matrix.scatter(m, dest))
        for i, d in enumerate(dest):
            np.testing.assert_array_equal(out[d], m[i])


class TestOps:
    def test_argmax_argmin(self, rng):
        m = rng.standard_normal((6, 9)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.argmax(m)), m.argmax(axis=1))
        np.testing.assert_array_equal(np.asarray(matrix.argmin(m)), m.argmin(axis=1))

    def test_col_wise_sort(self, rng):
        m = rng.standard_normal((7, 3)).astype(np.float32)
        srt, order = matrix.col_wise_sort(m)
        np.testing.assert_allclose(np.asarray(srt), np.sort(m, axis=0), rtol=1e-6)

    def test_diagonal_ops(self, rng):
        m = rng.random((4, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(matrix.get_diagonal(m)), np.diag(m))
        out = np.asarray(matrix.set_diagonal(m, np.zeros(4, np.float32)))
        np.testing.assert_allclose(np.diag(out), np.zeros(4))

    def test_sign_flip(self, rng):
        m = rng.standard_normal((5, 3)).astype(np.float32)
        out = np.asarray(matrix.sign_flip(m))
        for c in range(3):
            assert out[np.abs(out[:, c]).argmax(), c] >= 0

    def test_slice_reverse_threshold_tri(self, rng):
        m = rng.standard_normal((6, 6)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.slice(m, (1, 4), (2, 5))), m[1:4, 2:5])
        np.testing.assert_array_equal(np.asarray(matrix.reverse(m)), m[:, ::-1])
        np.testing.assert_array_equal(np.asarray(matrix.lower_triangular(m)), np.tril(m))
        thr = np.asarray(matrix.threshold(m, 0.0))
        assert (thr[m < 0] == 0).all()

    def test_sample_rows(self, rng):
        import jax

        m = rng.random((100, 4)).astype(np.float32)
        out = matrix.sample_rows(m, 10, key=jax.random.PRNGKey(0))
        assert out.shape == (10, 4)
        # every sampled row exists in the source
        src = {tuple(r) for r in m.round(6).tolist()}
        for r in np.asarray(out).round(6).tolist():
            assert tuple(r) in src


def _bucket_shape(key):
    """Invert a table key 'rb:cb:kb' (bit_lengths) to a concrete shape."""
    rb, cb, kb = (int(p) for p in key.split(":"))
    return 1 << (rb - 1), 1 << (cb - 1), 1 << (kb - 1)


def test_select_k_property_sweep():
    """Seeded randomized sweep over shapes × algos × adversarial value
    mixes (ties, ±inf blocks, tiny subnormal ranges): selected VALUES must
    always equal the argsort reference's first k.  Bounded (fixed seed,
    5 mixes × 3 shapes × 4 algos) so CI stays fast — the select_k dispatch
    table makes every algorithm reachable in production, so each must
    survive every mix."""
    rng = np.random.default_rng(123)
    mixes = {
        "normal": lambda b, n: rng.standard_normal((b, n)),
        "ties": lambda b, n: rng.integers(0, 4, (b, n)).astype(np.float64),
        "inf_blocks": lambda b, n: np.where(
            rng.random((b, n)) < 0.4, np.inf, rng.standard_normal((b, n))),
        "neg_inf": lambda b, n: np.where(
            rng.random((b, n)) < 0.2, -np.inf, rng.standard_normal((b, n))),
        "tiny_range": lambda b, n: rng.standard_normal((b, n)) * 1e-30,
    }
    shapes = [(3, 65), (7, 257), (2, 1031)]
    for name, gen in mixes.items():
        for b, n in shapes:
            x = gen(b, n).astype(np.float32)
            k = min(17, n)
            want, _ = select_k_reference(x, k)
            for algo in (a for a in SelectAlgo if a != SelectAlgo.kAuto):
                vals, idx = matrix.select_k(x, k, algo=algo, select_min=True)
                np.testing.assert_array_equal(
                    np.asarray(vals), want, err_msg=f"{name} {b}x{n} {algo}")
                # returned ids must actually hold the returned values
                got = np.take_along_axis(x, np.asarray(idx), axis=1)
                np.testing.assert_array_equal(got, np.asarray(vals),
                                              err_msg=f"{name} ids {algo}")


def test_select_k_tuned_table_routes():
    """The committed dispatch table (bench/tune_select_k.py, measured on
    TPU) must load, contain every candidate algorithm somewhere, and route
    each measured bucket to its recorded winner — kAuto is provably not
    lax.top_k-always (VERDICT r2 #3).  Structural only: the specific
    winners are whatever the last tuner run measured."""
    from raft_tpu.matrix.select_k import SelectAlgo, _choose_algo, _tuned_table

    table = _tuned_table()
    assert table, "raft_tpu/matrix/_select_k_table.json missing or empty"
    valid = {a.value for a in SelectAlgo}
    assert set(table.values()) <= valid
    assert {"partial_bitonic", "bin_select"} <= set(table.values()), (
        "custom kernels unreachable: tuner measured lax.top_k fastest "
        "everywhere — retire them or re-tune")
    for key, algo in table.items():
        rows, cols, k = _bucket_shape(key)
        assert _choose_algo(rows, cols, k) == SelectAlgo(algo), key
    # unmeasured bucket falls back to the default
    assert _choose_algo(3, 100, 2) == SelectAlgo.kTopK
    # provenance sidecar (VERDICT r3 weak #2): the table must carry its
    # backend/date so a CPU stand-in can never masquerade as TPU-tuned
    import importlib
    import json as _json
    import os as _os

    _sk = importlib.import_module("raft_tpu.matrix.select_k")
    meta_path = _os.path.join(_os.path.dirname(_sk.__file__),
                              "_select_k_table.meta.json")
    with open(meta_path) as f:
        meta = _json.load(f)
    assert meta.get("backend") and meta.get("date")


def test_select_k_auto_correct_on_tuned_buckets():
    """kAuto must stay correct on buckets the table reroutes away from
    the default (one representative shape per rerouted algorithm)."""
    from raft_tpu.matrix.select_k import _tuned_table

    rng = np.random.default_rng(0)
    # smallest bucket per rerouted algorithm (CPU-mesh friendly)
    smallest = {}
    for key, algo in _tuned_table().items():
        if algo == "top_k":
            continue
        rows, cols, k = _bucket_shape(key)
        if algo not in smallest or rows * cols < smallest[algo][0] * smallest[algo][1]:
            smallest[algo] = (rows, cols, k)
    assert smallest, "no rerouted buckets found"
    for rows, cols, k in smallest.values():
        x = jnp.asarray(rng.standard_normal((rows, cols)).astype(np.float32))
        vals, idx = matrix.select_k(x, k)  # kAuto — exercises the reroute
        ref_vals, _ = select_k_reference(np.asarray(x), k)
        np.testing.assert_allclose(np.asarray(vals), ref_vals, rtol=1e-6)


def test_bin_select_inf_sentinels_exact():
    """+inf-masked rows (filtered search) must stay exact AND keep the
    refinement effective: bounds come from finite values only."""
    from raft_tpu.ops.bin_select import bin_select_k

    rng = np.random.default_rng(9)
    x = rng.standard_normal((16, 400)).astype(np.float32)
    x[:, 150:] = np.inf            # most of every row masked out
    x[3, :] = np.inf               # fully-masked row
    x[5, :8] = np.inf              # fewer finite entries than k... almost
    v, i = bin_select_k(jnp.asarray(x), 10)
    v = np.asarray(v)
    ref = np.sort(x, axis=1)[:, :10]
    np.testing.assert_allclose(v, ref)
    # returned indices must point at the returned values
    got = np.take_along_axis(x, np.asarray(i), axis=1)
    np.testing.assert_allclose(np.sort(got, axis=1), ref)


def test_bin_select_fewer_finite_than_k():
    from raft_tpu.ops.bin_select import bin_select_k

    x = np.full((4, 64), np.inf, np.float32)
    x[:, :3] = [[1, 2, 3]] * 4      # only 3 finite < k=8
    v, i = bin_select_k(jnp.asarray(x), 8)
    v = np.asarray(v)
    np.testing.assert_allclose(np.sort(v, axis=1)[:, :3], [[1, 2, 3]] * 4)
    assert np.isinf(np.sort(v, axis=1)[:, 3:]).all()


def test_select_k_tuned_nearest_bucket(monkeypatch):
    """Shapes between tuner grid points interpolate to the closest
    measured bucket instead of silently falling back to the default."""
    import importlib

    sk = importlib.import_module("raft_tpu.matrix.select_k")
    table = {"12:11:4": "bin_select", "15:11:4": "partial_bitonic"}
    monkeypatch.setattr(sk, "_tuned_table", lambda: table)
    # exact hit
    assert sk._tuned_entry(2048, 1024, 8) == "bin_select"
    # rows 10000 -> bucket 14: nearest is 15 (distance 1)
    assert sk._tuned_entry(10_000, 1024, 8) == "partial_bitonic"
    # length four octaves away: no interpolation, default path
    assert sk._tuned_entry(2048, 16384, 8) is None
    # k far away: no interpolation
    assert sk._tuned_entry(2048, 1024, 128) is None
    # batch far off-grid (bucket 7 vs 12/15): must NOT extrapolate
    assert sk._tuned_entry(64, 1024, 8) is None
