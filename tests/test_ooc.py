"""raft_tpu.neighbors.ooc — the out-of-core cooperative search tier.

The contract under test (ISSUE 14):

* **rerank-everything oracle** — with ``rerank_k = n`` every stored row
  crosses the host round-trip into the exact rerank, so results must be
  bit-identical (values AND ids) to ``brute_force.knn``: fetching rows
  from the mmap-backed shard store must reproduce the device slab
  rescore exactly.
* **rabitq parity** — same build params ⇒ the device half (centroids,
  codes, slabs) is bit-identical to ``ivf_rabitq.build_chunked`` and
  search results match bitwise at every ``(n_probes, rerank_k)``.
* **overlap transparency** — ``device_prefetch`` double-buffering is a
  wall-clock optimisation only: overlap on/off and any query chunking
  are bit-identical.
* **device-memory boundedness** — the search loop's only H2D path is
  ``_stage_to_device``; under ``jax.transfer_guard("disallow")`` the
  largest single staging put is bounded by the resolved query chunk,
  never the whole raw slab.
* **zero steady-state allocation** — all staging buffers come from the
  host pool at fixed shapes: no pool misses after the first chunk.

Bitwise comparisons use integer-valued f32 data (each arithmetic step
exact in f32) — the tie-free fixture pinning the acceptance criterion.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core.errors import RaftError
from raft_tpu.core.host_memory import default_host_pool
from raft_tpu.io.shards import ShardedVectorStore, ShardWriter, write_store
from raft_tpu.neighbors import brute_force, ivf_rabitq, ooc, serialize
from raft_tpu.neighbors.ooc import (OocIndex, OocIndexParams,
                                    OocSearchParams)

N, D, NQ, K = 3000, 64, 16, 10
PARAMS = OocIndexParams(n_lists=8, kmeans_n_iters=10, list_cap_ratio=3.0)
RQ_PARAMS = ivf_rabitq.IvfRabitqIndexParams(n_lists=8, kmeans_n_iters=10,
                                            list_cap_ratio=3.0)


def _int_data(rng, rows, d=D):
    """Integer-valued f32: every arithmetic step lands on exact floats,
    enabling bitwise comparisons across accumulation orders — and
    making the brute-force oracle tie-free for this seed (distinct
    distances ⇒ a unique top-k ordering to pin bit-identity against)."""
    return rng.integers(0, 256, size=(rows, d)).astype(np.float32)


@pytest.fixture(scope="module")
def db():
    return _int_data(np.random.default_rng(7), N)


@pytest.fixture(scope="module")
def queries():
    return jnp.asarray(_int_data(np.random.default_rng(8), NQ))


@pytest.fixture(scope="module")
def index(db, tmp_path_factory):
    path = tmp_path_factory.mktemp("ooc") / "store"
    return ooc.build(db, PARAMS, store_path=str(path))


# ---------------------------------------------------------------------------
# the sharded host store


def test_store_roundtrip_and_gather(tmp_path, rng):
    x = rng.standard_normal((10_000, 24)).astype(np.float32)
    store = write_store(str(tmp_path / "s"), x, rows_per_shard=3000,
                        chunk_rows=1111)
    assert (store.rows, store.dim, store.n_shards) == (10_000, 24, 4)
    assert store.dtype == np.float32 and len(store) == 10_000
    np.testing.assert_array_equal(store.read_rows(2500, 6500), x[2500:6500])
    ids = rng.integers(0, 10_000, size=777)
    np.testing.assert_array_equal(store.gather(ids), x[ids])
    # out-of-range ids clip (masked downstream by the search path)
    ids2 = np.array([-5, 0, 9999, 123456])
    np.testing.assert_array_equal(store.gather(ids2),
                                  x[np.clip(ids2, 0, 9999)])
    assert store.verify() == []


def test_store_partial_final_shard(tmp_path, rng):
    """A dataset that doesn't divide rows_per_shard ends in a short
    shard: the writer rewrites that shard's header in place at close."""
    x = rng.standard_normal((701, 8)).astype(np.float32)
    w = ShardWriter(str(tmp_path / "s"), 8, np.dtype(np.float32),
                    rows_per_shard=256)
    for lo in range(0, 701, 97):
        w.append(x[lo:lo + 97])
    store = w.close()
    assert store.rows == 701 and store.n_shards == 3
    np.testing.assert_array_equal(store.read_rows(0, 701), x)
    # each shard is a plain np.load-able .npy — the format is inspectable
    last = np.load(str(tmp_path / "s" / "shard-00002.npy"))
    np.testing.assert_array_equal(last, x[512:])
    assert store.verify() == []


def test_store_crc_detects_corruption(tmp_path, rng):
    x = rng.standard_normal((100, 8)).astype(np.float32)
    store = write_store(str(tmp_path / "s"), x, rows_per_shard=64)
    shard = tmp_path / "s" / "shard-00001.npy"
    raw = bytearray(shard.read_bytes())
    raw[-3] ^= 0xFF
    shard.write_bytes(bytes(raw))
    problems = ShardedVectorStore.open(str(tmp_path / "s")).verify()
    assert problems and any("shard-00001" in p for p in problems)


def test_store_gather_native_fallback_parity(tmp_path, rng):
    """The pure-NumPy mmap path and the native pread path return the
    same bytes (whichever is active, forcing the fallback must agree)."""
    from raft_tpu.io import native

    x = rng.standard_normal((5000, 16)).astype(np.float32)
    store = write_store(str(tmp_path / "s"), x, rows_per_shard=2048)
    # dense-ish windows trigger the pread branch when native is present
    ids = np.arange(100, 1600)
    got = store.gather(ids, fetch_batch=2000)
    try:
        native._reset_for_tests(None)        # pin the NumPy fallback
        fallback = store.gather(ids, fetch_batch=2000)
    finally:
        native._reset_for_tests()
    np.testing.assert_array_equal(got, x[ids])
    np.testing.assert_array_equal(fallback, x[ids])


# ---------------------------------------------------------------------------
# search correctness


def test_rerank_everything_bitwise_vs_brute(index, db, queries):
    """rerank_k = n: the estimator admits everything, so the host
    round-trip + exact rerank must reproduce brute force bit-for-bit
    (values AND ids) — the ISSUE 14 acceptance pin."""
    dv, di = ooc.search(index, queries, K, OocSearchParams(
        n_probes=PARAMS.n_lists, rerank_k=N))
    bv, bi = brute_force.knn(queries, jnp.asarray(db), K)
    np.testing.assert_array_equal(np.asarray(di), np.asarray(bi))
    np.testing.assert_array_equal(np.asarray(dv), np.asarray(bv))


def test_device_half_matches_ivf_rabitq(index, db):
    """Same params ⇒ the resident device arrays are bit-identical to the
    all-on-device rabitq tier (shared training, rotation, encode)."""
    ridx = ivf_rabitq.build_chunked(db, RQ_PARAMS)
    for f in ("centroids", "rotation", "codes", "sabs", "res_norms",
              "code_cdots", "ids", "counts"):
        np.testing.assert_array_equal(np.asarray(getattr(index, f)),
                                      np.asarray(getattr(ridx, f)), err_msg=f)
    assert index.list_cap == ridx.list_cap


def test_search_parity_vs_ivf_rabitq(index, db, queries):
    """At practical (n_probes, rerank_k) the ooc tier returns exactly
    what rabitq returns: fetching survivors host-side instead of
    gathering the device slab must not change a single bit."""
    ridx = ivf_rabitq.build_chunked(db, RQ_PARAMS)
    for n_probes, rerank_k in [(2, 32), (4, 64), (8, 128)]:
        rv, ri = ivf_rabitq.search(ridx, queries, K,
                                   ivf_rabitq.IvfRabitqSearchParams(
                                       n_probes=n_probes, rerank_k=rerank_k))
        ov, oi = ooc.search(index, queries, K, OocSearchParams(
            n_probes=n_probes, rerank_k=rerank_k))
        np.testing.assert_array_equal(np.asarray(oi), np.asarray(ri))
        np.testing.assert_array_equal(np.asarray(ov), np.asarray(rv))


def test_overlap_and_chunking_bit_identity(index, queries):
    base = ooc.search(index, queries, K,
                      OocSearchParams(n_probes=4, rerank_k=64))
    for overlap in (True, False):
        for chunk in (5, 16, 1024):
            dv, di = ooc.search(index, queries, K, OocSearchParams(
                n_probes=4, rerank_k=64, overlap=overlap,
                query_chunk=chunk))
            np.testing.assert_array_equal(np.asarray(di),
                                          np.asarray(base[1]))
            np.testing.assert_array_equal(np.asarray(dv),
                                          np.asarray(base[0]))


def test_estimator_recall(index, db, queries):
    """Practical rerank_k: the 1-bit estimator must recover near the
    probe-coverage ceiling — same data, gates, and bound as the rabitq
    tier's worst-case (uniform) recall test, and recall must grow with
    the rerank gate."""
    _, bi = brute_force.knn(queries, jnp.asarray(db), K)
    gt = np.asarray(bi)

    def recall_at(rk):
        _, di = ooc.search(index, queries, K, OocSearchParams(
            n_probes=PARAMS.n_lists, rerank_k=rk))
        return np.mean([len(set(a) & set(b)) / K
                        for a, b in zip(np.asarray(di), gt)])

    lo, hi = recall_at(8 * K), recall_at(32 * K)
    assert hi >= 0.95, (lo, hi)
    assert hi >= lo


def test_filtered_search(index, db, queries):
    _, oi = ooc.search(index, queries, K,
                       OocSearchParams(n_probes=8, rerank_k=N))
    keep = np.ones(N, dtype=bool)
    keep[np.asarray(oi).reshape(-1)[:50]] = False
    kv, ki = ooc.search(index, queries, K,
                        OocSearchParams(n_probes=8, rerank_k=N), filter=keep)
    bv, bi = brute_force.knn(queries, jnp.asarray(db), K,
                             filter=jnp.asarray(keep))
    np.testing.assert_array_equal(np.asarray(ki), np.asarray(bi))
    np.testing.assert_array_equal(np.asarray(kv), np.asarray(bv))


def test_metric_and_dim_validation(index, queries):
    with pytest.raises(RaftError):
        ooc.search(index, jnp.zeros((2, D + 1), jnp.float32), K)
    with pytest.raises(RaftError):
        ooc.build(np.zeros((10, 4), np.float32),
                  OocIndexParams(n_lists=20), store_path="/tmp/unused")


# ---------------------------------------------------------------------------
# build engines


def test_build_perop_pipelined_parity(db, tmp_path):
    """The double-buffered streaming build and the blocking per-op
    reference produce bit-identical device state AND shard bytes."""
    a = ooc.build_chunked(db, PARAMS, store_path=str(tmp_path / "a"),
                          chunk_rows=512)
    b = ooc._build_chunked_perop(db, PARAMS, store_path=str(tmp_path / "b"),
                                 chunk_rows=512)
    for f in ("centroids", "rotation", "codes", "sabs", "res_norms",
              "code_cdots", "ids", "counts"):
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)), err_msg=f)
    np.testing.assert_array_equal(a.store.read_rows(0, N),
                                  b.store.read_rows(0, N))
    np.testing.assert_array_equal(a.store.read_rows(0, N), db)


def test_build_streams_store_in_chunks(db, tmp_path):
    """rows_per_shard below n forces multiple shards; the rows land in
    dataset order so stored ids are positional."""
    p = dataclasses.replace(PARAMS, rows_per_shard=1024)
    idx = ooc.build(db, p, store_path=str(tmp_path / "s"), chunk_rows=500)
    assert idx.store.n_shards == 3
    np.testing.assert_array_equal(idx.store.read_rows(0, N), db)


# ---------------------------------------------------------------------------
# resource contracts


def test_device_memory_boundedness(index, queries):
    """The search loop never device_puts more than one staged chunk:
    codes tier + bounded staging, no hidden full-slab transfer.  All H2D
    goes through _stage_to_device (explicit device_put), so the loop is
    clean under a disallow transfer guard and the accounting is total."""
    p = OocSearchParams(n_probes=4, rerank_k=64, query_chunk=4)
    ooc.search(index, queries, K, p)          # warm the executables
    ooc.reset_transfer_stats()
    with jax.transfer_guard("disallow"):
        ooc.search(index, queries, K, p)
    ts = ooc.transfer_stats()
    chunk_bytes = 4 * 64 * D * 4 + 4 * D * 4  # staged slab + staged queries
    assert 0 < ts["max_put_bytes"] <= chunk_bytes
    raw_slab_bytes = N * D * 4
    assert ts["put_bytes"] < raw_slab_bytes
    assert int(index.resident_bytes) < raw_slab_bytes
    assert int(index.host_bytes) == raw_slab_bytes


def test_pool_zero_misses_after_warmup(index, queries):
    """Fixed staging shapes ⇒ after the first search every buffer is a
    pool hit: the hot loop allocates nothing."""
    p = OocSearchParams(n_probes=4, rerank_k=64, query_chunk=4)
    ooc.search(index, queries, K, p)          # warm up pool shapes
    before = default_host_pool().stats()
    ooc.search(index, queries, K, p)
    after = default_host_pool().stats()
    assert after["misses"] == before["misses"]
    assert after["hits"] > before["hits"]


def test_fetch_counter_and_transfer_stats(index, queries):
    from raft_tpu.obs.metrics import registry

    c = registry().counter("raft_ooc_rerank_fetch_bytes_total",
                           "host rows fetched for exact rerank")

    def total():
        return sum(v for _, v in c.samples())

    before = total()
    ooc.reset_transfer_stats()
    ooc.search(index, queries, K, OocSearchParams(n_probes=4, rerank_k=64))
    assert total() - before == NQ * 64 * D * 4
    assert ooc.transfer_stats()["fetch_bytes"] == NQ * 64 * D * 4


# ---------------------------------------------------------------------------
# serve integration


def test_family_and_searcher_dispatch(index, queries):
    from raft_tpu.serve import searchers

    assert searchers.family_of(index) == "ooc"
    assert searchers.index_dim(index) == D
    assert searchers.index_size(index) == N
    assert searchers.query_dtype_of(index) == jnp.float32
    p = OocSearchParams(n_probes=4, rerank_k=64)
    ov, oi = ooc.search(index, queries, K, p)
    fn, ops = searchers.make_searcher(index, K, p)
    sv, si = fn(queries, *ops)
    np.testing.assert_array_equal(np.asarray(si), np.asarray(oi))
    np.testing.assert_array_equal(np.asarray(sv), np.asarray(ov))


def test_searcher_aot_compiles(index, queries):
    """The serve contract: queries are the only shape-varying input and
    the host gather rides inside via pure_callback, so the searcher
    lowers and compiles ahead of time."""
    p = OocSearchParams(n_probes=4, rerank_k=64)
    fn, ops = ooc.searcher(index, K, p)
    compiled = jax.jit(fn).lower(
        jax.ShapeDtypeStruct((NQ, D), jnp.float32), *ops).compile()
    cv, ci = compiled(queries, *ops)
    ov, oi = ooc.search(index, queries, K, p)
    np.testing.assert_array_equal(np.asarray(ci), np.asarray(oi))
    np.testing.assert_array_equal(np.asarray(cv), np.asarray(ov))


def test_health_reports_memory_split(index):
    from raft_tpu.neighbors import health

    stats = health.index_health(index)
    assert stats["family"] == "ooc"
    assert stats["rows"] == N
    assert stats["resident_bytes"] == float(index.resident_bytes)
    assert stats["host_bytes"] == float(N * D * 4)
    assert stats["rerank_fetch_bytes"] >= 0.0
    assert stats["residual_energy_mean"] > 0.0


def test_quality_oracle_reads_store(index, db):
    from raft_tpu.obs import quality

    vecs, ids = quality.oracle_database(index)
    assert vecs.shape == (N, D) and ids.shape == (N,)
    np.testing.assert_array_equal(vecs[np.argsort(ids)], db)


def test_fused_scan_counted_fallback(index, queries):
    """scan_kernel="fused" has no mosaic lowering yet: the gate must
    COUNT the fallback (not silently dispatch) and results must match
    the xla path exactly."""
    from raft_tpu.obs.metrics import registry

    c = registry().counter("raft_pallas_gate_fallback_total", "x")

    def count():
        return sum(v for labels, v in c.samples()
                   if labels.get("kernel") == "rabitq_scan")

    before = count()
    fv, fi = ooc.search(index, queries, K, OocSearchParams(
        n_probes=4, rerank_k=64, scan_kernel="fused"))
    assert count() > before
    xv, xi = ooc.search(index, queries, K, OocSearchParams(
        n_probes=4, rerank_k=64, scan_kernel="xla"))
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(xi))
    np.testing.assert_array_equal(np.asarray(fv), np.asarray(xv))


# ---------------------------------------------------------------------------
# persistence (format v5: manifest directory + sharded store)


def test_serialize_v5_roundtrip(index, queries, tmp_path):
    path = str(tmp_path / "idx")
    serialize.save_index(path, index, manifest={"note": "t"})
    assert serialize.verify_index(path) == []
    assert serialize.index_manifest(path)["note"] == "t"
    p = OocSearchParams(n_probes=4, rerank_k=64)
    ov, oi = ooc.search(index, queries, K, p)
    idx2 = serialize.load_index(path, verify=True)
    assert isinstance(idx2, OocIndex)
    rv, ri = ooc.search(idx2, queries, K, p)
    np.testing.assert_array_equal(np.asarray(ri), np.asarray(oi))
    np.testing.assert_array_equal(np.asarray(rv), np.asarray(ov))


def test_open_is_lazy_and_verify_catches_corruption(index, tmp_path):
    path = str(tmp_path / "idx")
    ooc.save(path, index)
    idx2 = ooc.open(path)
    assert int(idx2.size) == N
    # store shards are opened lazily: no mmap until a row is read
    assert all(m is None for m in idx2.store._maps)
    shard = next(p for p in (tmp_path / "idx" / "shards").iterdir()
                 if p.name.endswith(".npy"))
    raw = bytearray(shard.read_bytes())
    raw[-1] ^= 0xFF
    shard.write_bytes(bytes(raw))
    assert ooc.verify(path) != []
    assert serialize.verify_index(path) != []


def test_future_version_rejected(index, tmp_path):
    path = str(tmp_path / "idx")
    ooc.save(path, index)
    meta_path = os.path.join(path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["format_version"] = 99
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ValueError):
        ooc.open(path)
