"""Pallas kernel tests (interpret mode on the CPU mesh — same kernel code
that compiles on TPU; SURVEY.md §4's "test both compiled and exported
paths" discipline)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.ops.pallas.fused_l2_topk import fused_shortlist
from raft_tpu.ops.pallas.select_k import select_k_pallas


@pytest.mark.parametrize("batch,length,k", [(16, 300, 5), (9, 128, 3), (32, 4096, 32)])
def test_select_k_pallas_exact(rng, batch, length, k):
    x = rng.normal(size=(batch, length)).astype(np.float32)
    v, i = select_k_pallas(jnp.asarray(x), k)
    v, i = np.asarray(v), np.asarray(i)
    np.testing.assert_allclose(v, np.sort(x, axis=1)[:, :k])
    assert np.all(np.take_along_axis(x, i, axis=1) == v)


def test_select_k_pallas_max(rng):
    x = rng.normal(size=(8, 500)).astype(np.float32)
    v, _ = select_k_pallas(jnp.asarray(x), 4, select_min=False)
    np.testing.assert_allclose(np.asarray(v), -np.sort(-x, axis=1)[:, :4])


def test_fused_shortlist_contains_true_topk(rng):
    m, n, d, k = 32, 6000, 96, 10
    x = rng.normal(size=(m, d)).astype(np.float32)
    y = rng.normal(size=(n, d)).astype(np.float32)
    yn = (y * y).sum(axis=1).astype(np.float32)
    _, si = fused_shortlist(jnp.asarray(x), jnp.asarray(y), jnp.asarray(yn),
                            bm=32, bn=512)
    si = np.asarray(si)
    d2 = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    true = np.argsort(d2, axis=1)[:, :k]
    rec = np.mean([len(set(t) & set(s)) for t, s in zip(true, si)]) / k
    assert rec > 0.99, rec


def test_fused_shortlist_padding(rng):
    # n not a multiple of bn: padded rows must never surface
    m, n, d = 8, 700, 64
    x = rng.normal(size=(m, d)).astype(np.float32)
    y = rng.normal(size=(n, d)).astype(np.float32)
    yn = (y * y).sum(axis=1).astype(np.float32)
    sv, si = fused_shortlist(jnp.asarray(x), jnp.asarray(y), jnp.asarray(yn),
                             bm=8, bn=512)
    si, sv = np.asarray(si), np.asarray(sv)
    finite = np.isfinite(sv)
    assert np.all(si[finite] >= 0) and np.all(si[finite] < n)


@pytest.mark.parametrize("dtype", [np.uint8, np.int8])
def test_fused_shortlist_int8_path(rng, dtype):
    """Integer inputs take the int8 MXU branch (centered for uint8, with
    the correction folded into yn) — the true L2 top-k must still be in
    the shortlist."""
    from raft_tpu.ops.pallas.fused_l2_topk import int8_surrogate_norms

    m, n, d, k = 16, 3000, 32, 10
    if dtype == np.uint8:
        x = rng.integers(0, 256, (m, d)).astype(dtype)
        y = rng.integers(0, 256, (n, d)).astype(dtype)
    else:
        x = rng.integers(-128, 128, (m, d)).astype(dtype)
        y = rng.integers(-128, 128, (n, d)).astype(dtype)
    yn = int8_surrogate_norms(jnp.asarray(y))
    _, si = fused_shortlist(jnp.asarray(x), jnp.asarray(y), yn,
                            bm=16, bn=512)
    si = np.asarray(si)
    d2 = ((x.astype(np.int64)[:, None, :]
           - y.astype(np.int64)[None, :, :]) ** 2).sum(-1)
    true = np.argsort(d2, axis=1)[:, :k]
    rec = np.mean([len(set(t) & set(s)) for t, s in zip(true, si)]) / k
    assert rec > 0.99, rec
