"""Narrow-dtype dataset support (uint8/int8 SIFT-1B-class corpora, bf16).

The reference instantiates its neighbor methods for int8_t/uint8_t as well
as float (e.g. the brute-force/IVF template instantiation lists under
``cpp/src/``, and the ``.bvecs`` loaders the ANN benchmarks consume);
narrow dtypes matter on TPU for the same reason — a billion-row uint8
corpus is 4× smaller in HBM, with the cast to bf16/f32 done per tile at
compute time.  These tests pin the whole ingestion surface: results on an
integer-valued dataset must agree with the f32 pipeline run on the same
values.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, cagra, ivf_flat


@pytest.fixture(scope="module")
def int_data():
    rng = np.random.default_rng(7)
    db = rng.integers(0, 256, (3000, 24)).astype(np.uint8)
    sel = rng.choice(3000, 64, replace=False)
    return db, db[sel], sel


@pytest.mark.parametrize("mode", ["exact", "fast"])
def test_knn_uint8_matches_f32(int_data, mode):
    db, q, _ = int_data
    vu, iu = brute_force.knn(q, db, 5, mode=mode)
    vf, if_ = brute_force.knn(q.astype(np.float32), db.astype(np.float32),
                              5, mode=mode)
    np.testing.assert_array_equal(np.asarray(iu), np.asarray(if_))
    np.testing.assert_allclose(np.asarray(vu), np.asarray(vf), rtol=1e-5)


def test_knn_int8(int_data):
    db, q, _ = int_data
    db8 = (db.astype(np.int16) - 128).astype(np.int8)
    q8 = (q.astype(np.int16) - 128).astype(np.int8)
    v, i = brute_force.knn(q8, db8, 1)
    # shifting every coordinate by a constant preserves L2 self-matches
    assert (np.asarray(v)[:, 0] == 0).all()


def test_ivf_flat_uint8_storage_and_recall(int_data):
    db, q, _ = int_data
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=16, seed=0))
    # the packed lists must keep the narrow dtype (4x HBM saving vs f32)
    assert idx.data.dtype == jnp.uint8
    d, i = ivf_flat.search(idx, q, 5, ivf_flat.IvfFlatSearchParams(n_probes=16))
    gt = np.asarray(brute_force.knn(q.astype(np.float32),
                                    db.astype(np.float32), 5)[1])
    from raft_tpu.stats import neighborhood_recall

    assert float(neighborhood_recall(np.asarray(i), gt)) == 1.0


def test_cagra_uint8_build_search(int_data):
    db, q, _ = int_data
    p = cagra.CagraIndexParams(intermediate_graph_degree=16, graph_degree=8,
                               build_algo="brute_force", n_routers=32, seed=0)
    idx = cagra.build(db, p)
    d, i = cagra.search(idx, q, 5, cagra.CagraSearchParams(itopk_size=32))
    gt = np.asarray(brute_force.knn(q.astype(np.float32),
                                    db.astype(np.float32), 5)[1])
    from raft_tpu.stats import neighborhood_recall

    assert float(neighborhood_recall(np.asarray(i), gt)) > 0.9


def test_ivf_pq_uint8_build_search(int_data):
    """IVF-PQ on an integer corpus (reference ships int8/uint8 IVF-PQ):
    the quantizer chain must run in f32 — uint8 residual arithmetic would
    wrap (200-250 mod 256) and train garbage codebooks."""
    from raft_tpu.neighbors import ivf_pq

    db, q, _ = int_data
    idx = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8,
                                                   seed=0))
    assert idx.centroids.dtype == jnp.float32
    for mode in ("recon", "lut"):
        _, ids = ivf_pq.search(
            idx, db[:16], 1, ivf_pq.IvfPqSearchParams(n_probes=8, mode=mode))
        assert (np.asarray(ids)[:, 0] == np.arange(16)).mean() > 0.9, mode


def test_kmeans_integer_corpus_f32_centroids(int_data):
    """Centroid outputs for integer corpora are f32 (continuous
    quantities); float corpora keep their dtype."""
    from raft_tpu.cluster.kmeans import (KMeansParams, kmeans_balanced_fit,
                                         kmeans_fit)

    db, _, _ = int_data
    c, _, _ = kmeans_fit(db, KMeansParams(n_clusters=8, max_iter=4, seed=0))
    assert c.dtype == jnp.float32
    cb, _, _ = kmeans_balanced_fit(db, KMeansParams(n_clusters=8, max_iter=4,
                                                    seed=0))
    assert cb.dtype == jnp.float32
    cf, _, _ = kmeans_fit(db.astype(np.float32) / 255.0,
                          KMeansParams(n_clusters=8, max_iter=4, seed=0))
    assert cf.dtype == jnp.float32


def test_knn_bfloat16_inputs(int_data):
    db, q, sel = int_data
    dbb = jnp.asarray(db, jnp.bfloat16)
    qb = jnp.asarray(q, jnp.bfloat16)
    v, i = brute_force.knn(qb, dbb, 1)
    # each query is a database row: bf16 ingest must still find exactly it
    np.testing.assert_array_equal(np.asarray(i)[:, 0], sel)
    assert float(np.asarray(v)[:, 0].max()) <= 1e-3


def test_knn_uint8_cosine_fast_matches_exact(int_data):
    db, q, _ = int_data
    vf, i_ref = brute_force.knn(q, db, 5, metric="cosine")
    v, i = brute_force.knn(q, db, 5, metric="cosine", mode="fast", cand=64)
    from raft_tpu.stats import neighborhood_recall

    assert float(neighborhood_recall(np.asarray(i), np.asarray(i_ref))) >= 0.99


def test_knn_mixed_dtype_queries(int_data):
    """f32 queries against an integer database must take the float path
    (no silent truncation through the int8 centering)."""
    db, q, _ = int_data
    qf = q.astype(np.float32) + 0.25  # real-valued: would corrupt if cast
    _, i_ref = brute_force.knn(qf, db.astype(np.float32), 5)
    _, i = brute_force.knn(qf, db, 5, mode="fast", cand=64)
    from raft_tpu.stats import neighborhood_recall

    assert float(neighborhood_recall(np.asarray(i), np.asarray(i_ref))) >= 0.99


def test_integer_scoring_tier_matches_f32(int_data):
    """The single-pass bf16 scoring tier for 8-bit corpora (ivf_flat probe
    scan, cagra beam) must agree exactly with the f32 pipeline on the same
    values (uint8 values and their ≤-256-dim dot sums are bf16/f32-exact)."""
    db, q, _ = int_data
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=16, seed=0))
    sp = ivf_flat.IvfFlatSearchParams(n_probes=16)
    _, i_u8 = ivf_flat.search(idx, q, 5, sp)
    idx_f = ivf_flat.build(db.astype(np.float32),
                           ivf_flat.IvfFlatIndexParams(n_lists=16, seed=0))
    _, i_f = ivf_flat.search(idx_f, q.astype(np.float32), 5, sp)
    np.testing.assert_array_equal(np.asarray(i_u8), np.asarray(i_f))

    p = cagra.CagraIndexParams(intermediate_graph_degree=16, graph_degree=8,
                               build_algo="brute_force", n_routers=32, seed=0)
    cidx = cagra.build(db, p)
    csp = cagra.CagraSearchParams(itopk_size=32)
    _, ci_u8 = cagra.search(cidx, q, 5, csp, seed=0)
    cidx_f = cagra.CagraIndex(cidx.dataset.astype(jnp.float32), cidx.graph,
                              cidx.router_centroids.astype(jnp.float32),
                              cidx.router_nodes, cidx.metric)
    _, ci_f = cagra.search(cidx_f, q.astype(np.float32), 5, csp, seed=0)
    np.testing.assert_array_equal(np.asarray(ci_u8), np.asarray(ci_f))


def test_int8_tier_dimension_guard():
    """Past the exact-accumulation bound (partial sums < 2^24) the tier
    must fall back to HIGHEST — integer dot gaps of 1 would round away.
    uint8 caps at d=256, int8 at d=1024; and high-d searches still agree
    exactly with the f32 pipeline via the fallback."""
    from raft_tpu.ops.blocked_scan import int8_tier_eligible

    u8 = np.zeros((2, 2), np.uint8)
    i8 = np.zeros((2, 2), np.int8)
    f32 = np.zeros((2, 2), np.float32)
    assert int8_tier_eligible(u8, u8, 256)
    assert not int8_tier_eligible(u8, u8, 257)
    assert int8_tier_eligible(i8, i8, 1024)
    assert not int8_tier_eligible(i8, i8, 1025)
    assert not int8_tier_eligible(u8, i8, 512)  # mixed pair uses uint8 cap
    assert not int8_tier_eligible(u8, f32, 8)

    rng = np.random.default_rng(11)
    db = rng.integers(0, 256, (400, 300)).astype(np.uint8)  # d > 256
    _, i_u8 = brute_force.knn(db[:8], db, 5)
    _, i_f = brute_force.knn(db[:8].astype(np.float32),
                             db.astype(np.float32), 5)
    np.testing.assert_array_equal(np.asarray(i_u8), np.asarray(i_f))


def test_sharded_builds_uint8(int_data, mesh8):
    """Distributed builds on integer corpora: the per-shard quantizer
    chain must run in f32 end to end (uint8 residual wraparound and
    uint8-rounded centroids were the single-device bug, duplicated in the
    shard_map programs)."""
    from raft_tpu.neighbors import ivf_pq

    db, _, _ = int_data
    db8 = db[:2960]  # divisible by 8
    idx = ivf_pq.build_sharded(db8, mesh8,
                               ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=8,
                                                       seed=0))
    assert idx.centroids.dtype == jnp.float32
    _, ids = ivf_pq.search_sharded(
        idx, db8[:16], 1, ivf_pq.IvfPqSearchParams(n_probes=4), mesh=mesh8)
    assert (np.asarray(ids)[:, 0] == np.arange(16)).mean() > 0.9


def test_knn_sharded_uint8(int_data, mesh8):
    db, q, sel = int_data
    from raft_tpu.neighbors.brute_force import knn_sharded

    db8 = db[:2960]  # divisible by 8
    d, i = knn_sharded(q, db8, 5, mesh=mesh8)
    _, i_ref = brute_force.knn(q.astype(np.float32),
                               db8.astype(np.float32), 5)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
