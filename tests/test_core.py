"""Core runtime tests — parity with ``cpp/tests/core/`` (handle, bitset,
numpy_serializer, interruptible suites)."""

import io
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import raft_tpu
from raft_tpu.core import (
    Bitset, Bitmap, DeviceResources, LogicError, Resources, expects,
    interruptible, serialize_mdspan, save_arrays, load_arrays, wrap_array,
)


class TestResources:
    def test_lazy_factory_runs_once(self):
        res = Resources()
        calls = []
        res.add_resource_factory("thing", lambda r: calls.append(1) or "made")
        assert res.get_resource("thing") == "made"
        assert res.get_resource("thing") == "made"
        assert len(calls) == 1

    def test_copy_shares_cells(self):
        res = Resources()
        res.add_resource_factory("thing", lambda r: object())
        a = res.get_resource("thing")
        dup = res.copy()
        assert dup.get_resource("thing") is a

    def test_missing_resource_raises(self):
        res = Resources()
        with pytest.raises(raft_tpu.core.RaftError):
            res.get_resource("no_such_slot")

    def test_rng_key_stream_advances(self):
        res = DeviceResources(seed=123)
        k1, k2 = res.rng_key(), res.rng_key()
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))

    def test_default_mesh(self):
        res = Resources()
        mesh = res.mesh
        assert isinstance(mesh, jax.sharding.Mesh)
        assert mesh.devices.size == len(jax.devices())

    def test_comms_not_initialized_raises(self):
        res = Resources()
        with pytest.raises(LogicError):
            raft_tpu.core.get_comms(res)

    def test_thread_safety(self):
        res = Resources()
        made = []
        res.add_resource_factory("slot", lambda r: made.append(1) or object())
        out = []

        def work():
            out.append(res.get_resource("slot"))

        threads = [threading.Thread(target=work) for _ in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert len(made) == 1
        assert all(o is out[0] for o in out)


class TestBitset:
    def test_roundtrip(self, rng):
        mask = rng.random(1000) < 0.3
        bs = Bitset.from_bool_array(mask)
        np.testing.assert_array_equal(np.asarray(bs.to_bool_array()), mask)
        assert int(bs.count()) == mask.sum()

    def test_create_set_flip(self):
        bs = Bitset.create(70, default_value=False)
        assert int(bs.count()) == 0
        bs = bs.set(jnp.array([0, 33, 69]))
        assert int(bs.count()) == 3
        assert bool(bs.test(33))
        assert not bool(bs.test(34))
        flipped = bs.flip()
        assert int(flipped.count()) == 67

    def test_tail_masking(self):
        bs = Bitset.create(33, default_value=True)
        assert int(bs.count()) == 33

    def test_and_or(self):
        a = Bitset.from_bool_array(np.array([1, 0, 1, 0], bool))
        b = Bitset.from_bool_array(np.array([1, 1, 0, 0], bool))
        assert int((a & b).count()) == 1
        assert int((a | b).count()) == 3

    def test_resize(self):
        """bitset::resize parity (core/bitset.hpp:357): grown bits take the
        default — including the old tail word's previously-masked bits —
        and truncation re-masks the new tail."""
        bs = Bitset.create(33, default_value=False).set(np.array([0, 32]))
        grown = bs.resize(70, default_value=True)
        assert grown.n_bits == 70
        assert int(grown.count()) == 2 + (70 - 33)  # old bits kept
        assert bool(grown.test(32)) and not bool(grown.test(5))
        assert bool(grown.test(33)) and bool(grown.test(69))
        shrunk = grown.resize(33, default_value=True)
        assert shrunk.n_bits == 33 and int(shrunk.count()) == 2
        grown0 = bs.resize(70, default_value=False)
        assert int(grown0.count()) == 2

    def test_any_all_none(self):
        bs = Bitset.create(10, default_value=False)
        assert bool(bs.none()) and not bool(bs.any()) and not bool(bs.all())
        bs = bs.set(np.array([3]))
        assert bool(bs.any()) and not bool(bs.all()) and not bool(bs.none())
        assert bool(bs.reset(True).all())

    def test_bitmap(self):
        bm = Bitmap.create_2d(4, 40, default_value=False)
        bm = bm.set2(2, 5)
        assert bool(bm.test2(2, 5))
        assert not bool(bm.test2(2, 6))

    def test_jit_compatible(self):
        bs = Bitset.create(256, default_value=False)

        @jax.jit
        def f(b: Bitset):
            return b.set(jnp.arange(10)).count()

        assert int(f(bs)) == 10


class TestSerialize:
    def test_mdspan_roundtrip_npy(self, rng):
        arr = rng.standard_normal((7, 5)).astype(np.float32)
        buf = io.BytesIO()
        serialize_mdspan(buf, jnp.asarray(arr))
        buf.seek(0)
        # the stream is genuine .npy — numpy can read it directly
        out = np.load(buf)
        np.testing.assert_array_equal(out, arr)

    def test_bundle_roundtrip(self, tmp_path, rng):
        arrays = {"a": rng.random((3, 3)).astype(np.float32), "b": np.arange(10)}
        save_arrays(tmp_path / "ckpt", arrays, {"kind": "test", "k": 5})
        loaded, meta = load_arrays(tmp_path / "ckpt")
        assert meta["kind"] == "test" and meta["k"] == 5
        np.testing.assert_array_equal(loaded["a"], arrays["a"])
        np.testing.assert_array_equal(loaded["b"], arrays["b"])


class TestInterruptible:
    def test_cancel_then_yield_raises(self):
        interruptible.clear()
        interruptible.cancel()
        with pytest.raises(interruptible.InterruptedException):
            interruptible.yield_now()
        interruptible.yield_now()  # flag cleared by the raise

    def test_synchronize_passthrough(self):
        interruptible.clear()
        x = interruptible.synchronize(jnp.arange(4))
        np.testing.assert_array_equal(np.asarray(x), np.arange(4))


class TestArrayWrap:
    def test_wrap_list(self):
        x = wrap_array([[1.0, 2.0]], ndim=2)
        assert x.shape == (1, 2)

    def test_rank_check(self):
        with pytest.raises(LogicError):
            wrap_array(np.zeros((2, 2)), ndim=1)

    def test_expects(self):
        expects(True)
        with pytest.raises(LogicError):
            expects(False, "boom")
