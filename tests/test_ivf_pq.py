"""IVF-PQ + refine tests: recall vs brute force on blobs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, ivf_pq, refine
from raft_tpu.random.datagen import make_blobs
from raft_tpu.stats.neighborhood import neighborhood_recall


@pytest.fixture(scope="module")
def blob_data():
    x, _ = make_blobs(jax.random.PRNGKey(1), n_samples=4000, n_features=32,
                      n_clusters=20, cluster_std=1.0)
    return np.asarray(x), np.asarray(x[:150])


def _recall(got, want):
    return float(neighborhood_recall(jnp.asarray(got), jnp.asarray(want)))


def test_ivf_pq_recall(blob_data):
    x, q = blob_data
    params = ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=8, pq_bits=8,
                                     kmeans_trainset_fraction=0.5)
    index = ivf_pq.build(x, params)
    assert index.size == x.shape[0]
    assert index.codes.dtype == jnp.uint8
    _, want = brute_force.knn(q, x, 10)
    _, got = ivf_pq.search(index, q, 10, ivf_pq.IvfPqSearchParams(n_probes=32))
    # PQ-compressed recall: full probes, 4x compression → decent recall
    assert _recall(got, want) > 0.7


def test_ivf_pq_refine_recovers_recall(blob_data):
    x, q = blob_data
    params = ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=8,
                                     kmeans_trainset_fraction=0.5)
    index = ivf_pq.build(x, params)
    _, want = brute_force.knn(q, x, 10)
    _, cand = ivf_pq.search(index, q, 40, ivf_pq.IvfPqSearchParams(n_probes=32))
    dist, got = refine.refine(x, q, cand, 10)
    assert _recall(got, want) > 0.97
    assert np.all(np.diff(np.asarray(dist), axis=1) >= -1e-5)


def test_ivf_pq_compression_ratio(blob_data):
    x, _ = blob_data
    params = ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=4,
                                     kmeans_trainset_fraction=0.3)
    index = ivf_pq.build(x, params)
    # 32 f32 dims -> 4 uint8 codes = 32x payload compression
    assert index.codes.shape[2] == 4


def test_ivf_pq_inner_product(blob_data):
    x, q = blob_data
    params = ivf_pq.IvfPqIndexParams(n_lists=32, pq_dim=8,
                                     metric="inner_product",
                                     kmeans_trainset_fraction=0.5)
    index = ivf_pq.build(x, params)
    _, want = brute_force.knn(q, x, 10, metric="inner_product")
    _, cand = ivf_pq.search(index, q, 40, ivf_pq.IvfPqSearchParams(n_probes=32))
    _, got = refine.refine(x, q, cand, 10, metric="inner_product")
    assert _recall(got, want) > 0.9


def test_refine_standalone_exact(blob_data):
    x, q = blob_data
    wd, want = brute_force.knn(q, x, 5)
    # refining the true top-40 must give the true top-5
    _, cand = brute_force.knn(q, x, 40)
    dist, got = refine.refine(x, q, cand, 5)
    assert _recall(got, want) == 1.0
    np.testing.assert_allclose(np.asarray(dist), np.asarray(wd), rtol=1e-4,
                               atol=1e-3)


def test_ivf_pq_sharded_matches_single(rng, mesh8):
    from raft_tpu.neighbors.ivf_pq import (IvfPqIndexParams, IvfPqSearchParams,
                                           build_sharded, search_sharded)

    x = (rng.normal(size=(512, 16)) +
         rng.integers(0, 8, size=(512, 1)) * 4.0).astype(np.float32)
    q = x[:24]
    idx = build_sharded(x, mesh8, IvfPqIndexParams(
        n_lists=16, pq_dim=4, kmeans_n_iters=4, pq_kmeans_n_iters=4))
    d, i = search_sharded(idx, q, 5, IvfPqSearchParams(n_probes=2), mesh=mesh8)
    d, i = np.asarray(d), np.asarray(i)
    assert d.shape == (24, 5) and i.shape == (24, 5)
    # self-queries must find themselves (IVF with per-shard probing covers
    # the owning list)
    assert (i[:, 0] == np.arange(24)).mean() > 0.9
    assert np.all(np.diff(d, axis=1) >= -1e-5)


def test_packed_codes_roundtrip_and_search(rng):
    """4-bit packed storage: half-size codes, identical LUT results."""
    from raft_tpu.neighbors import ivf_pq

    x = rng.standard_normal((1200, 16)).astype(np.float32)
    p = ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8, pq_bits=4, seed=0)
    idx = ivf_pq.build(x, p)
    packed = idx.with_packed_codes()
    assert packed.codes.shape[-1] == 4 and packed.packed
    assert packed.pq_dim == 8  # logical width preserved
    sp = ivf_pq.IvfPqSearchParams(n_probes=8, mode="lut")
    d1, i1 = ivf_pq.search(idx, x[:16], 5, sp)
    d2, i2 = ivf_pq.search(packed, x[:16], 5, sp)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))
    # unpack restores the exact byte codes
    back = packed.with_unpacked_codes()
    np.testing.assert_array_equal(np.asarray(back.codes), np.asarray(idx.codes))


def test_packed_codes_recon_and_build_param(rng):
    from raft_tpu.neighbors import ivf_pq

    x = rng.standard_normal((800, 16)).astype(np.float32)
    idx = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=8, pq_dim=8, pq_bits=4, pack_codes=True, seed=0))
    assert idx.packed and idx.recon is not None
    # recon tier rebuilt FROM packed codes must match byte-code decode
    ref = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=8, pq_dim=8, pq_bits=4, seed=0))
    np.testing.assert_array_equal(
        np.asarray(idx.without_recon().with_recon().recon_norms),
        np.asarray(ref.recon_norms))
    d, i = ivf_pq.search(idx, x[:8], 5)  # recon tier on a packed index
    assert (np.asarray(i)[:, 0] == np.arange(8)).all()
    with pytest.raises(Exception, match="unpacked"):
        ivf_pq.extend(idx, x[:4])
    with pytest.raises(Exception, match="pq_bits"):
        ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
            n_lists=8, pq_dim=8, pq_bits=8, pack_codes=True))


def test_packed_codes_serialize_roundtrip(rng, tmp_path):
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.serialize import load_index, save_index

    x = rng.standard_normal((600, 16)).astype(np.float32)
    idx = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=8, pq_dim=8, pq_bits=4, pack_codes=True, seed=0))
    save_index(tmp_path / "pq4", idx)
    idx2 = load_index(tmp_path / "pq4")
    assert idx2.packed
    sp = ivf_pq.IvfPqSearchParams(n_probes=8, mode="lut")
    np.testing.assert_array_equal(
        np.asarray(ivf_pq.search(idx, x[:8], 5, sp)[1]),
        np.asarray(ivf_pq.search(idx2, x[:8], 5, sp)[1]))
