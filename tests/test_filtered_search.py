"""Bitset-prefiltered search (cuVS filtered-ANN parity: filter bit = keep)
and IVF-PQ extend."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def fdata():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((3000, 24)).astype(np.float32)
    q = rng.standard_normal((64, 24)).astype(np.float32)
    keep = rng.random(3000) < 0.5
    # exact filtered reference: brute force over the kept subset
    sub = np.where(keep)[0]
    _, gt_sub = brute_force.knn(q, x[sub], 10)
    gt = sub[np.asarray(gt_sub)]
    return x, q, keep, gt


class TestFilteredBruteForce:
    def test_exact_mode_matches_subset_search(self, fdata):
        x, q, keep, gt = fdata
        _, ids = brute_force.knn(q, x, 10, filter=keep)
        np.testing.assert_array_equal(np.asarray(ids), gt)

    def test_bitset_filter_equivalent(self, fdata):
        x, q, keep, gt = fdata
        bs = Bitset.from_bool_array(keep)
        _, ids = brute_force.knn(q, x, 10, filter=bs)
        np.testing.assert_array_equal(np.asarray(ids), gt)

    def test_fast_mode_filtered_recall(self, fdata):
        x, q, keep, gt = fdata
        _, ids = brute_force.knn(q, x, 10, mode="fast", filter=keep)
        ids = np.asarray(ids)
        assert not np.isin(ids, np.where(~keep)[0]).any()
        assert float(neighborhood_recall(ids, gt)) > 0.95

    def test_filter_length_checked(self, fdata):
        x, q, _, _ = fdata
        with pytest.raises(Exception):
            brute_force.knn(q, x, 10, filter=np.ones(10, bool))


class TestFilteredIvf:
    def test_ivf_flat_filter_excludes(self, fdata):
        x, q, keep, gt = fdata
        idx = ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(n_lists=16))
        sp = ivf_flat.IvfFlatSearchParams(n_probes=16)  # exhaustive probes
        _, ids = ivf_flat.search(idx, q, 10, sp, filter=keep)
        ids = np.asarray(ids)
        assert not np.isin(ids, np.where(~keep)[0]).any()
        assert float(neighborhood_recall(ids, gt)) > 0.95

    def test_ivf_pq_filter_excludes(self, fdata):
        x, q, keep, gt = fdata
        idx = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=12))
        for mode in ("recon", "lut"):
            sp2 = ivf_pq.IvfPqSearchParams(n_probes=16, mode=mode)
            _, ids = ivf_pq.search(idx, q, 10, sp2, filter=keep)
            assert not np.isin(np.asarray(ids), np.where(~keep)[0]).any()


class TestIvfPqExtend:
    def test_extend_appends_and_searches(self):
        rng = np.random.default_rng(5)
        x1 = rng.standard_normal((2000, 16)).astype(np.float32)
        x2 = rng.standard_normal((500, 16)).astype(np.float32)
        idx = ivf_pq.build(x1, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8))
        ext = ivf_pq.extend(idx, x2)
        assert ext.size == 2500
        # new rows are findable: search for them, ids land in [2000, 2500)
        sp = ivf_pq.IvfPqSearchParams(n_probes=8)
        _, ids = ivf_pq.search(ext, x2[:32], 1, sp)
        hits = (np.asarray(ids)[:, 0] >= 2000).mean()
        assert hits > 0.8

    def test_extend_grows_capacity(self):
        rng = np.random.default_rng(6)
        x1 = rng.standard_normal((400, 16)).astype(np.float32)
        # skew: all new rows near one point → one list must grow
        x2 = np.tile(x1[:1], (300, 1)) + 0.01 * rng.standard_normal(
            (300, 16)).astype(np.float32)
        idx = ivf_pq.build(x1, ivf_pq.IvfPqIndexParams(
            n_lists=8, pq_dim=8, list_cap_ratio=1.2))
        ext = ivf_pq.extend(idx, x2)
        assert ext.size == 700
        assert ext.list_cap > idx.list_cap

    def test_extend_without_recon_stays_lut(self):
        rng = np.random.default_rng(7)
        x = rng.standard_normal((500, 16)).astype(np.float32)
        idx = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
            n_lists=8, pq_dim=8, store_recon=False))
        ext = ivf_pq.extend(idx, x[:100])
        assert ext.recon is None and ext.size == 600


class TestSubKFilter:
    """Fewer passing rows than k: tails must be (-1, ±inf), never real
    filtered ids."""

    def test_brute_force_exact_and_fast(self, fdata):
        x, q, _, _ = fdata
        keep = np.zeros(x.shape[0], bool)
        keep[:3] = True
        for mode in ("exact", "fast"):
            d, ids = brute_force.knn(q, x, 10, mode=mode, filter=keep)
            ids = np.asarray(ids)
            assert set(np.unique(ids[:, 3:])) == {-1}
            assert set(np.unique(ids[:, :3])) <= {0, 1, 2}

    def test_brute_force_inner_product(self, fdata):
        x, q, _, _ = fdata
        keep = np.zeros(x.shape[0], bool)
        keep[:2] = True
        d, ids = brute_force.knn(q, x, 5, metric="inner_product", filter=keep)
        assert set(np.unique(np.asarray(ids)[:, 2:])) == {-1}

    def test_ivf_flat_sub_k(self, fdata):
        x, q, _, _ = fdata
        keep = np.zeros(x.shape[0], bool)
        keep[:3] = True
        idx = ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(n_lists=16))
        _, ids = ivf_flat.search(
            idx, q, 10, ivf_flat.IvfFlatSearchParams(n_probes=16), filter=keep)
        assert not np.isin(np.asarray(ids), np.arange(3, x.shape[0])).any()

    def test_short_filter_rejected_ivf(self, fdata):
        x, q, _, _ = fdata
        idx = ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(n_lists=16))
        with pytest.raises(Exception):
            ivf_flat.search(idx, q, 10, filter=np.ones(10, bool))


class TestExtendPreservesSource:
    def test_source_index_usable_after_extend(self):
        """extend must not donate the live source index's buffers."""
        rng = np.random.default_rng(8)
        x = rng.standard_normal((600, 16)).astype(np.float32)
        idx = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8))
        before = int(idx.size)
        _ = ivf_pq.extend(idx, x[:50])
        # the ORIGINAL index still searches (buffers not deleted)
        assert int(idx.size) == before
        d, i = ivf_pq.search(idx, x[:8], 3, ivf_pq.IvfPqSearchParams(n_probes=8))
        assert np.asarray(i).shape == (8, 3)


class TestCagraExtend:
    def test_extend_finds_new_nodes(self):
        from raft_tpu.neighbors import cagra
        rng = np.random.default_rng(9)
        x1 = rng.standard_normal((2000, 16)).astype(np.float32)
        x2 = rng.standard_normal((300, 16)).astype(np.float32)
        idx = cagra.build(x1, cagra.CagraIndexParams(
            intermediate_graph_degree=32, graph_degree=16, n_routers=32))
        ext = cagra.extend(idx, x2)
        assert ext.size == 2300 and ext.graph.shape == (2300, 16)
        # querying the new vectors finds them (or a very near old row)
        d, ids = cagra.search(ext, x2[:64], 1,
                              cagra.CagraSearchParams(itopk_size=64))
        hits = (np.asarray(ids)[:, 0] >= 2000).mean()
        assert hits > 0.8
        # old content still searchable
        d, ids = cagra.search(ext, x1[:64], 1,
                              cagra.CagraSearchParams(itopk_size=64))
        assert (np.asarray(ids)[:, 0] == np.arange(64)).mean() > 0.9

    def test_extend_preserves_source(self):
        from raft_tpu.neighbors import cagra
        rng = np.random.default_rng(10)
        x = rng.standard_normal((500, 16)).astype(np.float32)
        idx = cagra.build(x, cagra.CagraIndexParams(
            intermediate_graph_degree=16, graph_degree=8, n_routers=16))
        _ = cagra.extend(idx, x[:50])
        assert idx.size == 500  # source untouched
        d, i = cagra.search(idx, x[:8], 3, cagra.CagraSearchParams(itopk_size=16))
        assert np.asarray(i).shape == (8, 3)


class TestBitmapFilter:
    """Per-query (nq, n) bitmap filters — cuVS bitmap_filter parity."""

    @pytest.fixture(scope="class")
    def bdata(self):
        rng = np.random.default_rng(17)
        x = rng.standard_normal((2000, 16)).astype(np.float32)
        q = x[:32]  # queries ARE rows: the classic "exclude self" setup
        bitmap = np.ones((32, 2000), bool)
        bitmap[np.arange(32), np.arange(32)] = False  # each excludes itself
        _, gt_all = brute_force.knn(q, x, 2)
        return x, q, bitmap, np.asarray(gt_all)

    def test_exact_mode_excludes_self(self, bdata):
        x, q, bitmap, gt_all = bdata
        d, ids = brute_force.knn(q, x, 1, filter=bitmap)
        ids = np.asarray(ids)
        assert not (ids[:, 0] == np.arange(32)).any()
        # the answer is exactly each query's second-nearest overall
        np.testing.assert_array_equal(ids[:, 0], gt_all[:, 1])

    def test_fast_mode_excludes_self(self, bdata):
        x, q, bitmap, gt_all = bdata
        _, ids = brute_force.knn(q, x, 1, mode="fast", cand=32, filter=bitmap)
        ids = np.asarray(ids)
        assert not (ids[:, 0] == np.arange(32)).any()
        np.testing.assert_array_equal(ids[:, 0], gt_all[:, 1])

    def test_core_bitmap_object(self, bdata):
        from raft_tpu.core.bitset import Bitmap

        x, q, bitmap, gt_all = bdata
        bm = Bitmap(Bitset.from_bool_array(bitmap.reshape(-1)).words,
                    *bitmap.shape)
        _, ids = brute_force.knn(q, x, 1, filter=bm)
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], gt_all[:, 1])

    def test_ivf_flat_bitmap(self, bdata):
        x, q, bitmap, gt_all = bdata
        idx = ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(n_lists=8, seed=0))
        _, ids = ivf_flat.search(idx, q, 1,
                                 ivf_flat.IvfFlatSearchParams(n_probes=8),
                                 filter=bitmap)
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], gt_all[:, 1])

    def test_ivf_flat_bitmap_chunked(self, bdata):
        x, q, bitmap, gt_all = bdata
        idx = ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(n_lists=8, seed=0))
        _, ids = ivf_flat.search(
            idx, q, 1,
            ivf_flat.IvfFlatSearchParams(n_probes=8, query_chunk=10),
            filter=bitmap)  # chunk size not dividing nq: aux slicing path
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], gt_all[:, 1])

    def test_ivf_pq_bitmap_both_tiers(self, bdata):
        x, q, bitmap, gt_all = bdata
        idx = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8,
                                                      seed=0))
        for mode in ("recon", "lut"):
            _, ids = ivf_pq.search(
                idx, q, 1, ivf_pq.IvfPqSearchParams(n_probes=8, mode=mode),
                filter=bitmap)
            assert not (np.asarray(ids)[:, 0] == np.arange(32)).any(), mode

    def test_bitmap_query_count_checked(self, bdata):
        from raft_tpu.core.errors import LogicError

        x, q, bitmap, _ = bdata
        with pytest.raises(LogicError, match="bitmap filter has 5"):
            brute_force.knn(q, x, 1, filter=bitmap[:5])

    def test_fast_mode_bitmap_inside_jit(self, bdata):
        """The headroom check must not concretize a traced mask — fast-mode
        knn with a bitmap filter stays jittable."""
        import jax

        x, q, bitmap, gt_all = bdata
        f = jax.jit(lambda qq, m: brute_force.knn(qq, x, 1, mode="fast",
                                                  cand=32, filter=m))
        _, ids = f(q, jnp.asarray(bitmap))
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], gt_all[:, 1])

    def test_fast_mode_dense_bitmap_warns(self, bdata, caplog):
        """Dense per-query exclusions with no cand headroom must warn
        (ADVICE r3: starved shortlists silently return sentinels)."""
        import logging

        from raft_tpu.neighbors.brute_force import _excl_checked

        x, q, _, _ = bdata
        n, half = x.shape[0], x.shape[0] // 2
        dense = np.ones((q.shape[0], n), bool)
        for i in range(q.shape[0]):  # per-query-DIFFERENT exclusion windows
            dense[i, i % half: i % half + half] = False
        _excl_checked.clear()
        with caplog.at_level(logging.WARNING, logger="raft_tpu"):
            brute_force.knn(q, x, 4, mode="fast", cand=8, filter=dense)
        assert any("headroom" in r.getMessage() for r in caplog.records)
        caplog.clear()
        # the check runs once per (shape, cand, k): the next dispatch at the
        # same config must pay no sync and re-raise no warning
        with caplog.at_level(logging.WARNING, logger="raft_tpu"):
            brute_force.knn(q, x, 4, mode="fast", cand=8, filter=dense)
        assert not any("headroom" in r.getMessage() for r in caplog.records)
        # identical masks for every query carry no starvation risk (the
        # shared row mask pre-drops them) — no warning
        same = np.ones((q.shape[0], n), bool)
        same[:, :half] = False
        _excl_checked.clear()
        with caplog.at_level(logging.WARNING, logger="raft_tpu"):
            brute_force.knn(q, x, 4, mode="fast", cand=8, filter=same)
        assert not any("headroom" in r.getMessage() for r in caplog.records)


class TestCagraFilter:
    @pytest.fixture(scope="class")
    def cdata(self):
        from raft_tpu.neighbors import cagra

        rng = np.random.default_rng(23)
        x = (rng.standard_normal((2000, 16)) +
             4 * rng.standard_normal((30, 16))[rng.integers(0, 30, 2000)]
             ).astype(np.float32)
        idx = cagra.build(x, cagra.CagraIndexParams(
            intermediate_graph_degree=24, graph_degree=12,
            build_algo="brute_force", n_routers=32, seed=0))
        return x, idx

    def test_bitset_filter_excludes(self, cdata):
        from raft_tpu.neighbors import cagra

        x, idx = cdata
        q = x[:24]
        keep = np.ones(2000, bool)
        keep[:500] = False
        _, ids = cagra.search(idx, q, 5,
                              cagra.CagraSearchParams(itopk_size=64),
                              filter=keep)
        ids = np.asarray(ids)
        assert not ((ids >= 0) & (ids < 500)).any()
        # recall vs exact filtered reference on surviving slots
        sub = np.where(keep)[0]
        _, gt_sub = brute_force.knn(q, x[sub], 5)
        gt = sub[np.asarray(gt_sub)]
        assert float(neighborhood_recall(ids, gt)) > 0.8

    def test_bitmap_filter_excludes_self(self, cdata):
        from raft_tpu.neighbors import cagra

        x, idx = cdata
        q = x[:24]
        bitmap = np.ones((24, 2000), bool)
        bitmap[np.arange(24), np.arange(24)] = False
        _, ids = cagra.search(idx, q, 3,
                              cagra.CagraSearchParams(itopk_size=32),
                              filter=bitmap)
        assert not (np.asarray(ids)[:, 0] == np.arange(24)).any()

    def test_sub_k_survivors_sentinel(self, cdata):
        from raft_tpu.neighbors import cagra

        x, idx = cdata
        keep = np.zeros(2000, bool)
        keep[:2] = True  # fewer keepers than k
        d, ids = cagra.search(idx, x[:4], 5,
                              cagra.CagraSearchParams(itopk_size=64),
                              filter=keep)
        ids = np.asarray(ids)
        assert ((ids == -1) | (ids < 2)).all()


class TestShardedFilter:
    """filter= on the sharded search paths (masks slice with the shards)."""

    def test_knn_sharded_bitset_and_bitmap(self, mesh8):
        from raft_tpu.neighbors.brute_force import knn, knn_sharded

        rng = np.random.default_rng(29)
        y = rng.standard_normal((1600, 16)).astype(np.float32)
        q = y[:16]
        keep = rng.random(1600) < 0.5
        _, ref = knn(q, y, 5, filter=keep)
        _, ids = knn_sharded(q, y, 5, mesh=mesh8, filter=keep)
        np.testing.assert_array_equal(np.asarray(ids), np.asarray(ref))

        bm = np.ones((16, 1600), bool)
        bm[np.arange(16), np.arange(16)] = False
        _, ids2 = knn_sharded(q, y, 1, mesh=mesh8, filter=bm)
        assert not (np.asarray(ids2)[:, 0] == np.arange(16)).any()

    def test_ivf_sharded_filters(self, mesh8):
        from raft_tpu.neighbors import ivf_flat, ivf_pq

        rng = np.random.default_rng(31)
        x = rng.standard_normal((1600, 16)).astype(np.float32)
        q = x[:8]
        keep = np.ones(1600, bool)
        keep[:8] = False  # the query rows themselves

        fidx = ivf_flat.build_sharded(x, mesh8, ivf_flat.IvfFlatIndexParams(
            n_lists=32, kmeans_n_iters=4))
        _, ids = ivf_flat.search_sharded(
            fidx, q, 3, ivf_flat.IvfFlatSearchParams(n_probes=4),
            mesh=mesh8, filter=keep)
        assert not ((np.asarray(ids) >= 0) & (np.asarray(ids) < 8)).any()

        pidx = ivf_pq.build_sharded(x, mesh8, ivf_pq.IvfPqIndexParams(
            n_lists=16, pq_dim=8, kmeans_n_iters=4, pq_kmeans_n_iters=4))
        bm = np.ones((8, 1600), bool)
        bm[np.arange(8), np.arange(8)] = False
        _, ids2 = ivf_pq.search_sharded(
            pidx, q, 1, ivf_pq.IvfPqSearchParams(n_probes=4),
            mesh=mesh8, filter=bm)
        assert not (np.asarray(ids2)[:, 0] == np.arange(8)).any()

    def test_hybrid_mesh_bitmap_specs(self, mesh2x4):
        """2-D mesh: bitmap rows follow the data axis, cols the shard axis
        (the P(data_axis, axis) / P(data_axis) spec branches)."""
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.neighbors.brute_force import knn_sharded

        rng = np.random.default_rng(37)
        y = rng.standard_normal((1600, 16)).astype(np.float32)
        q = y[:16]
        bm = np.ones((16, 1600), bool)
        bm[np.arange(16), np.arange(16)] = False
        _, ids = knn_sharded(q, y, 1, mesh=mesh2x4, axis="shard",
                             data_axis="data", filter=bm)
        assert not (np.asarray(ids)[:, 0] == np.arange(16)).any()

        fidx = ivf_flat.build_sharded(y, mesh2x4, ivf_flat.IvfFlatIndexParams(
            n_lists=16, kmeans_n_iters=4))
        _, ids2 = ivf_flat.search_sharded(
            fidx, q, 1, ivf_flat.IvfFlatSearchParams(n_probes=4),
            mesh=mesh2x4, data_axis="data", filter=bm)
        assert not (np.asarray(ids2)[:, 0] == np.arange(16)).any()

    def test_cagra_sharded_filter(self, mesh8):
        from raft_tpu.neighbors import cagra

        rng = np.random.default_rng(41)
        x = rng.standard_normal((1600, 16)).astype(np.float32)
        idx = cagra.build_sharded(x, mesh8, cagra.CagraIndexParams(
            intermediate_graph_degree=16, graph_degree=8, n_routers=16))
        q = x[:8]
        bm = np.ones((8, 1600), bool)
        bm[np.arange(8), np.arange(8)] = False
        _, ids = cagra.search_sharded(
            idx, q, 1, cagra.CagraSearchParams(itopk_size=16),
            mesh=mesh8, filter=bm)
        assert not (np.asarray(ids)[:, 0] == np.arange(8)).any()
        keep = np.ones(1600, bool)
        keep[:8] = False
        _, ids2 = cagra.search_sharded(
            idx, q, 3, cagra.CagraSearchParams(itopk_size=16),
            mesh=mesh8, filter=keep)
        ids2 = np.asarray(ids2)
        assert not ((ids2 >= 0) & (ids2 < 8)).any()
