"""Serve lifecycle — generation handoff under traffic + chaos harness.

Every injected failure mode has a deterministic *recovery* assertion
(the fault demonstrably fired AND the server demonstrably recovered),
per the ISSUE 6 acceptance criteria:

* ``wedge``/``oom`` on dispatch — retry with backoff, answer delivered;
* retry exhaustion — the batch fails, the server keeps serving;
* deadline-aware retry — backoff that outlives the deadline rejects
  immediately instead of burning it;
* ``slow`` — late completion is accounted, not dropped;
* ``fail`` on swap / ``oom`` on a background build — :class:`SwapFailed`
  rollback with the old generation still serving;
* swap under live threaded traffic — zero dropped requests and zero
  post-warmup recompiles for a same-shaped generation;
* interleaved insert/delete/search/swap — zero retraces after warmup.
"""

from __future__ import annotations

import threading

import jax
import numpy as np
import pytest

from raft_tpu.core import TraceGuard
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import ivf_flat, mutation
from raft_tpu.serve import (DeadlineExceeded, FaultInjector, RetryPolicy,
                            SearchServer, ServerConfig, SwapFailed,
                            WedgedDevice)

N, D = 192, 16


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeSleep:
    """Backoff sleeper that advances a fake clock instead of blocking."""

    def __init__(self, clock: FakeClock) -> None:
        self.clock = clock
        self.calls: list = []

    def __call__(self, seconds: float) -> None:
        self.calls.append(seconds)
        self.clock.advance(seconds)


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(30).standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    return np.random.default_rng(31).standard_normal((5, D)).astype(np.float32)


@pytest.fixture(scope="module")
def built(db):
    return ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=6))


def _server(index, *, clock=None, sleep=None, retry=None, **cfg):
    clock = clock or FakeClock()
    sleep = sleep or FakeSleep(clock)
    faults = FaultInjector(sleep=sleep)
    config = ServerConfig(ladder=(8,), retry=retry or RetryPolicy(), **cfg)
    srv = SearchServer(index, k=3,
                       params=ivf_flat.IvfFlatSearchParams(n_probes=3),
                       config=config, clock=clock, faults=faults, sleep=sleep)
    return srv, clock, sleep


# ---------------------------------------------------------------------------
# chaos: dispatch faults


def test_wedge_recovery_retries_then_answers(built, queries):
    srv, _, sleep = _server(built)
    srv.faults.arm("execute", "wedge", times=2)
    d, i = srv.search(queries)
    assert i.shape == (5, 3) and (np.asarray(i)[:, 0] >= 0).all()
    assert srv.faults.fired_count("execute", "wedge") == 2
    snap = srv.metrics.snapshot()
    assert snap["retries"] == 2 and snap["faulted_batches"] == 0
    assert snap["completed"] == 1
    assert len(sleep.calls) == 2
    p = srv.config.retry  # decorrelated jitter stays inside the hard bounds
    assert all(p.backoff_ms / 1e3 <= s <= p.max_backoff_ms / 1e3
               for s in sleep.calls)


def test_retry_exhaustion_fails_batch_not_server(built, queries):
    srv, _, _ = _server(built, retry=RetryPolicy(max_retries=1))
    srv.faults.arm("execute", "wedge", times=3)
    with pytest.raises(WedgedDevice):
        srv.search(queries)
    snap = srv.metrics.snapshot()
    assert snap["faulted_batches"] == 1 and snap["retries"] == 1
    srv.faults.disarm()
    d, i = srv.search(queries)  # server survives the faulted batch
    assert i.shape == (5, 3)
    assert srv.metrics.snapshot()["completed"] == 1


def test_retry_respects_request_deadline(built, queries):
    # the only backoff step (200ms) outlives the 50ms deadline: reject
    # NOW with DeadlineExceeded instead of sleeping through the budget
    srv, _, sleep = _server(
        built, retry=RetryPolicy(max_retries=2, backoff_ms=200.0,
                                 max_backoff_ms=200.0))
    srv.faults.arm("execute", "wedge", times=1)
    with pytest.raises(DeadlineExceeded):
        srv.search(queries, deadline_ms=50.0)
    assert sleep.calls == []  # never slept — the deadline math said no
    snap = srv.metrics.snapshot()
    assert snap["faulted_batches"] == 1 and snap["retries"] == 0


def test_slow_fault_counts_late_completion(built, queries):
    srv, _, _ = _server(built)
    srv.faults.arm("execute", "slow", delay_ms=500.0)
    d, i = srv.search(queries, deadline_ms=100.0)  # answered, but late
    assert i.shape == (5, 3)
    snap = srv.metrics.snapshot()
    assert snap["completed"] == 1 and snap["late_completions"] == 1
    assert srv.faults.fired_count("execute", "slow") == 1


def test_fault_injector_env_spec(built, queries):
    inj = FaultInjector.from_env("execute:wedge:2, execute:slow:1:250")
    assert inj.pending("execute") == 3
    with pytest.raises(RaftError):
        FaultInjector().arm("nowhere", "wedge")
    with pytest.raises(RaftError):
        FaultInjector().arm("execute", "sparks")


def test_fault_spec_multi_site_arming():
    inj = FaultInjector.from_env("execute:wedge:2,swap:fail,extend:oom:3")
    assert inj.pending("execute") == 2
    assert inj.pending("swap") == 1
    assert inj.pending("extend") == 3
    assert inj.pending("snapshot") == 0  # durability sites arm too
    inj2 = FaultInjector.from_env("snapshot:crash,rename:corrupt:2")
    assert inj2.pending("snapshot") == 1
    assert inj2.pending("rename") == 2


def test_fault_spec_empty_and_whitespace_are_unarmed():
    for spec in ("", "  ", ",", " , "):
        inj = FaultInjector.from_env(spec)
        assert all(inj.pending(s) == 0
                   for s in ("execute", "swap", "extend"))


@pytest.mark.parametrize("spec", [
    "execute",                       # missing kind
    "execute:wedge:1:0:extra",       # too many fields
    "execute:wedge:one",             # non-int times
    "execute:slow:1:fast",           # non-float delay
    "nowhere:wedge",                 # unknown site
    "execute:sparks",                # unknown kind
])
def test_fault_spec_malformed_raises(spec):
    with pytest.raises(RaftError):
        FaultInjector.from_env(spec)


# ---------------------------------------------------------------------------
# retry backoff: decorrelated jitter


def test_backoff_jitter_bounds_and_hard_cap():
    import random

    p = RetryPolicy(max_retries=8, backoff_ms=10.0, max_backoff_ms=50.0)
    draws = []
    for seed in range(20):
        b = p.start(random.Random(seed))
        draws.extend(b.next_s() for _ in range(8))
    lo, hi = p.backoff_ms / 1e3, p.max_backoff_ms / 1e3
    assert all(lo <= s <= hi for s in draws)   # hard cap, both sides
    assert len({round(s, 6) for s in draws}) > 10  # it actually jitters
    assert max(draws) <= hi + 1e-12


def test_backoff_decorrelated_desynchronizes_replicas():
    import random

    p = RetryPolicy(max_retries=4, backoff_ms=5.0, max_backoff_ms=1000.0)
    a = [p.start(random.Random(1)).next_s() for _ in range(1)]
    seqs = [[p.start(random.Random(s)).next_s() for _ in range(3)]
            for s in range(8)]
    # two replicas retrying the same shared fault should not share a
    # schedule (the retry-storm failure mode jitter exists to break)
    assert len({tuple(round(x, 9) for x in s) for s in seqs}) == 8
    assert a  # non-empty draw from the same API


def test_backoff_jitter_none_matches_exponential_envelope():
    p = RetryPolicy(max_retries=4, backoff_ms=5.0, multiplier=2.0,
                    max_backoff_ms=100.0, jitter="none")
    b = p.start()
    got = [b.next_s() for i in range(6)]
    want = [p.backoff_s(i) for i in range(6)]
    assert got == want
    assert got[-1] == 0.1  # capped


def test_retry_policy_rejects_unknown_jitter():
    with pytest.raises(RaftError):
        RetryPolicy(jitter="bogus")


# ---------------------------------------------------------------------------
# generation handoff


def test_swap_serves_new_generation_zero_recompiles(built, db, queries):
    srv, _, _ = _server(built)
    srv.warmup()
    base = srv.cache.compiles
    d0, i0 = srv.search(queries)
    # rebuild (same shapes) with a permuted corpus: results must change,
    # executables must not
    perm = np.random.default_rng(32).permutation(N)
    idx2 = ivf_flat.build(db[perm], ivf_flat.IvfFlatIndexParams(n_lists=6))
    gen = srv.swap_index(idx2)
    assert gen.gen_id == 1 and srv.generation == 1
    d1, i1 = srv.search(queries)
    assert srv.cache.compiles == base  # same operand scope → cache hits
    assert not np.array_equal(np.asarray(i0), np.asarray(i1))
    # the new generation's answers match a direct search of the new index
    dd, ii = ivf_flat.search(idx2, queries, 3,
                             ivf_flat.IvfFlatSearchParams(n_probes=3))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(ii))
    assert srv.metrics.snapshot()["swaps"] == 1
    assert srv.metrics_snapshot()["server"]["generation"] == 1


def test_failed_swap_keeps_old_generation(built, db, queries):
    srv, _, _ = _server(built)
    d0, i0 = srv.search(queries)
    srv.faults.arm("swap", "fail")
    idx2 = ivf_flat.build(db[::-1].copy(),
                          ivf_flat.IvfFlatIndexParams(n_lists=6))
    with pytest.raises(SwapFailed):
        srv.swap_index(idx2)
    assert srv.generation == 0
    assert srv.metrics.snapshot()["failed_swaps"] == 1
    d1, i1 = srv.search(queries)  # old generation still serving, unchanged
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    gen = srv.swap_index(idx2)  # transient operator error: retry succeeds
    assert gen.gen_id == 1 and srv.metrics.snapshot()["swaps"] == 1


def test_swap_validation_rejects_mismatched_generation(built, db):
    srv, _, _ = _server(built)
    with pytest.raises(SwapFailed):
        srv.swap_index(db)  # family change (ivf_flat -> brute_force)
    with pytest.raises(SwapFailed):
        srv.swap_index(ivf_flat.build(
            db[:, :D - 4].copy(), ivf_flat.IvfFlatIndexParams(n_lists=6)))
    with pytest.raises(RaftError):
        srv.swap_index()  # neither new_index nor build
    with pytest.raises(RaftError):
        srv.swap_index(built, build=lambda: built)
    assert srv.generation == 0
    assert srv.metrics.snapshot()["failed_swaps"] == 2


def test_oom_on_background_extend_retries_then_swaps(built, db):
    srv, _, sleep = _server(built)
    srv.faults.arm("extend", "oom", times=1)
    calls = []

    def build():
        calls.append(1)
        new = np.random.default_rng(33).standard_normal(
            (32, D)).astype(np.float32)
        return ivf_flat.extend(built, new, np.arange(N, N + 32))

    gen = srv.swap_index(build=build)
    assert gen.gen_id == 1 and len(calls) == 1
    assert srv.faults.fired_count("extend", "oom") == 1
    snap = srv.metrics.snapshot()
    assert snap["retries"] == 1 and snap["swaps"] == 1
    assert len(sleep.calls) == 1


def test_oom_exhaustion_aborts_swap(built):
    srv, _, _ = _server(built, retry=RetryPolicy(max_retries=2))
    srv.faults.arm("extend", "oom", times=3)
    with pytest.raises(SwapFailed) as err:
        srv.swap_index(build=lambda: built)
    assert "generation 0 still serving" in str(err.value)
    assert srv.generation == 0
    assert srv.metrics.snapshot()["failed_swaps"] == 1


def test_tombstoned_index_serves_transparently(built, queries):
    fn0, ops0 = ivf_flat.searcher(built, 3,
                                  ivf_flat.IvfFlatSearchParams(n_probes=3))
    _, di0 = fn0(queries, *ops0)
    dead = set(int(v) for v in np.asarray(di0)[:, 0] if int(v) >= 0)
    t = mutation.delete(built, np.array(sorted(dead), np.int32))
    srv, _, _ = _server(t)
    d, i = srv.search(queries)
    got = set(np.asarray(i).ravel().tolist())
    assert not (got & dead) and -1 not in got
    # bit-identical to the direct tombstoned search
    dd, ii = mutation.search(t, queries, 3,
                             ivf_flat.IvfFlatSearchParams(n_probes=3))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
    np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))


# ---------------------------------------------------------------------------
# swap under live traffic


def test_swap_under_load_zero_drops_zero_recompiles(db):
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=6))
    cfg = ServerConfig(ladder=(4, 16), max_wait_ms=0.5,
                       default_deadline_ms=60_000.0)
    rng = np.random.default_rng(34)
    stop = threading.Event()
    results: list = []
    errors: list = []

    with SearchServer(idx, k=3,
                      params=ivf_flat.IvfFlatSearchParams(n_probes=3),
                      config=cfg) as srv:
        warm = srv.cache.compiles

        def client(seed):
            r = np.random.default_rng(seed)
            while not stop.is_set():
                q = r.standard_normal((int(r.integers(1, 9)), D)).astype(
                    np.float32)
                try:
                    results.append(srv.search(q))
                except Exception as exc:  # noqa: BLE001 — any drop fails the test
                    errors.append(exc)

        threads = [threading.Thread(target=client, args=(50 + t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        swaps = 0
        for _ in range(5):  # five generations while traffic flows
            perm = rng.permutation(N)
            srv.swap_index(ivf_flat.build(
                db[perm], ivf_flat.IvfFlatIndexParams(n_lists=6)))
            swaps += 1
        stop.set()
        for t in threads:
            t.join(30.0)
        snap = srv.metrics.snapshot()
        compiles = srv.cache.compiles

    assert not errors, f"dropped {len(errors)} requests: {errors[:3]}"
    assert swaps == 5 and snap["swaps"] == 5
    assert snap["completed"] == snap["submitted"] >= len(results) > 0
    assert snap["rejected_deadline"] == 0 and snap["faulted_batches"] == 0
    assert compiles == warm  # same-shaped generations: zero recompiles


# ---------------------------------------------------------------------------
# full mutable lifecycle, steady state


def test_interleaved_lifecycle_zero_retraces_after_warmup(db, queries):
    """insert → delete → swap → search, repeatedly, with ZERO retraces
    and zero compiles after one warmup round.  A fixed id_space keeps the
    tombstone mask shape constant; fixed-size inserts stay inside the
    slab headroom, so every generation shares one operand scope.

    ``transfer="allow"``: Bitset edits build tiny host constants (that's
    delete's documented cost); the *dispatch* path's transfer discipline
    is covered by ``test_extend_steady_state_trace_guard`` and the serve
    suite under the full ``disallow`` regime.
    """
    ID_SPACE = 512
    idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=6))
    view = mutation.delete(idx, [0], id_space=ID_SPACE)
    srv, _, _ = _server(view)
    srv.warmup()

    nxt = N
    rng = np.random.default_rng(35)

    def one_round(view, nxt):
        new = rng.standard_normal((16, D)).astype(np.float32)
        view = mutation.extend(view, new, np.arange(nxt, nxt + 16))
        view = mutation.delete(view, [nxt])  # retire one fresh row
        srv.swap_index(view)
        d, i = srv.search(queries)
        assert int(np.asarray(i)[0, 0]) >= 0
        return view, nxt + 16

    view, nxt = one_round(view, nxt)  # warmup round compiles everything
    base = srv.cache.compiles
    with TraceGuard(transfer="allow") as tg:
        for _ in range(3):
            view, nxt = one_round(view, nxt)
    tg.assert_steady_state()
    assert srv.cache.compiles == base
    assert srv.generation == 4
    assert view.size == N + 4 * 16
    assert mutation.deleted_count(view) == 5
