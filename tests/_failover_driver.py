"""Shared failover driver for ``tests/test_replication.py``.

Two roles with ONE deterministic mutation schedule (the same
one-module discipline as ``_durability_driver.py``):

* **child** (``python tests/_failover_driver.py``, env ``FO_ROOT`` /
  ``FO_PORT`` / ``FO_ACK_MODE`` / ``FO_CRASH_AT``): the PRIMARY.
  Builds the seed store, connects a :class:`SocketTransport` back to
  the parent's listener, attaches a :class:`LogShipper`, waits for the
  standby's hello (which streams the snapshot bootstrap), then walks
  the op list writing an atomically-renamed progress marker before
  each op.  Op ``FO_CRASH_AT`` runs with a ``crash`` fault armed at
  the ``wal_append`` site — ``os._exit(137)`` mid-mutation, before the
  record exists anywhere, exactly like ``kill -9``.
* **parent** (imported by the test): the STANDBY + the expectations.
  ``expected_states(root)`` replays the same schedule fault-free; the
  promoted standby is compared bit-for-bit against the rung matching
  its applied watermark.

The ack-mode contract the parent asserts:

* ``semi_sync``: every op whose ``extend``/``delete``/``compact``
  returned was acked first, so applied == marker exactly — zero acked
  mutations lost;
* ``async``: loss is bounded by the ship-queue backpressure window,
  ``marker - applied <= ship_queue + 1`` (+1 for the record in flight
  when the window check ran).
"""

from __future__ import annotations

import os
import time

import numpy as np

from _durability_driver import initial_tombstoned  # noqa: F401

D = 8
OP_COUNT = 7


def op_list():
    """Mutation-only schedule (replication ships mutations; snapshots
    are checkpoint-local).  Deterministic: both roles call this."""
    orng = np.random.default_rng(23)
    ops = [
        ("extend", (orng.standard_normal((16, D)).astype(np.float32),)),
        ("delete", ([5, 9],)),
        ("extend", (orng.standard_normal((8, D)).astype(np.float32),)),
        ("compact", ()),
        ("delete", ([30, 31],)),
        ("extend", (orng.standard_normal((4, D)).astype(np.float32),)),
        ("extend", (orng.standard_normal((4, D)).astype(np.float32),)),
    ]
    assert len(ops) == OP_COUNT
    return ops


def apply_op(store, op, args):
    if op == "extend":
        store.extend(*args)
    elif op == "delete":
        store.delete(*args)
    elif op == "compact":
        store.compact()
    else:  # pragma: no cover — schedule typo guard
        raise ValueError(op)


def expected_states(root):
    """``states[m]`` = the committed index after ops ``[0, m)``,
    built with NO faults and NO replication."""
    from raft_tpu.neighbors import wal

    store = wal.DurableStore.create(root, initial_tombstoned())
    states = [store.index]
    for op, args in op_list():
        apply_op(store, op, args)
        states.append(store.index)
    store.close()
    return states


def child_main():
    from raft_tpu.neighbors import wal
    from raft_tpu.serve.faults import FaultInjector
    from raft_tpu.serve.replication import (LogShipper, ReplicationConfig,
                                            SocketTransport)

    root = os.environ["FO_ROOT"]
    port = int(os.environ["FO_PORT"])
    mode = os.environ.get("FO_ACK_MODE", "semi_sync")
    crash_at = int(os.environ.get("FO_CRASH_AT", str(OP_COUNT - 1)))
    queue = int(os.environ.get("FO_QUEUE", "256"))

    store = wal.DurableStore.create(root, initial_tombstoned())
    transport = SocketTransport.connect("127.0.0.1", port, timeout=60)
    shipper = LogShipper(
        store, transport,
        config=ReplicationConfig(ack_mode=mode, ack_timeout_s=60.0,
                                 ship_queue=queue))
    # wait for the standby's hello: catch-up ships the cold bootstrap
    # snapshot, so every later record lands on a warm follower
    deadline = time.monotonic() + 60
    while not store.followers() and time.monotonic() < deadline:
        shipper.pump(0.1)
    assert store.followers(), "standby never said hello"

    marker = os.path.join(root, "progress")
    for m, (op, args) in enumerate(op_list()):
        if m == crash_at:
            # arm mid-schedule: the drill is killing a primary that has
            # already replicated a healthy prefix, not a newborn
            store.faults = FaultInjector().arm("wal_append", "crash")
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(m))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, marker)
        apply_op(store, op, args)
        shipper.pump(0.0)  # absorb acks opportunistically (async mode)
    raise SystemExit(3)  # fault never fired — the parent asserts 137


if __name__ == "__main__":
    # mirror conftest.py: force CPU programmatically before backends
    # initialize, same 8-virtual-device topology as the parent
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = \
            (_flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    child_main()
