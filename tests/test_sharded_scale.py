"""Mid-scale sharded-path gate (RAFT_RUN_SLOW=1).

The always-on sharded tests and the driver dryrun certify the sharded
programs at tiny shapes; this gate runs the flagship sharded build+search
at 200k rows on the 8-device virtual CPU mesh with a measured recall
floor against exact ground truth — the scale where list skew, capacity
spill, and shard-merge bugs actually show up.
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("RAFT_RUN_SLOW") != "1",
    reason="200k-row sharded builds; set RAFT_RUN_SLOW=1")


def _corpus(n, d, k_clusters, seed):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(k_clusters, d)).astype(np.float32)
    lab = rng.integers(0, k_clusters, n)
    x = (centers[lab] + rng.normal(size=(n, d))).astype(np.float32)
    return x


def _exact_gt(q, x, k):
    from raft_tpu.neighbors.brute_force import knn

    return np.asarray(knn(q, x, k)[1])


def test_ivf_pq_sharded_200k_recall(mesh8):
    from raft_tpu.neighbors.ivf_pq import (IvfPqIndexParams, IvfPqSearchParams,
                                           build_sharded, search_sharded)
    from raft_tpu.neighbors.refine import refine
    from raft_tpu.stats import neighborhood_recall

    n, d, k = 200_000, 64, 10
    x = _corpus(n, d, 200, seed=3)
    q = x[:512] + 0.01
    gt = _exact_gt(q, x, k)
    idx = build_sharded(x, mesh8, IvfPqIndexParams(n_lists=256, pq_dim=32,
                                                   seed=0))
    _, cand = search_sharded(idx, q, 4 * k,
                             IvfPqSearchParams(n_probes=32), mesh=mesh8)
    _, found = refine(x, q, np.asarray(cand), k)
    rec = float(neighborhood_recall(np.asarray(found), gt))
    assert rec >= 0.9, f"sharded IVF-PQ recall@10 at 200k: {rec}"


def test_ivf_flat_sharded_200k_recall(mesh8):
    from raft_tpu.neighbors.ivf_flat import (IvfFlatIndexParams,
                                             IvfFlatSearchParams,
                                             build_sharded, search_sharded)
    from raft_tpu.stats import neighborhood_recall

    n, d, k = 200_000, 64, 10
    x = _corpus(n, d, 200, seed=4)
    q = x[:512] + 0.01
    gt = _exact_gt(q, x, k)
    idx = build_sharded(x, mesh8, IvfFlatIndexParams(n_lists=256, seed=0))
    _, found = search_sharded(idx, q, k, IvfFlatSearchParams(n_probes=32),
                              mesh=mesh8)
    rec = float(neighborhood_recall(np.asarray(found), gt))
    assert rec >= 0.95, f"sharded IVF-Flat recall@10 at 200k: {rec}"
