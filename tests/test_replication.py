"""Replicated durability drills (ISSUE 15).

The acceptance criteria these pin:

* a standby built ONLY from shipped WAL records (plus the cold snapshot
  bootstrap) is bit-identical — values AND ids — to the primary, because
  both fold mutations through the same ``DurableStore._apply``;
* the ack-mode contract: ``semi_sync`` loses zero acked mutations across
  a primary SIGKILL; ``async`` loss is bounded by the ship-queue window;
* every wire failure heals deterministically: partition-dropped records
  surface as gaps/heartbeat lag and trigger a watermark resync,
  partition-dropped acks re-register via hello, semi-sync ack waits
  degrade (counted) instead of wedging the primary;
* fencing: a deposed primary's appends and swaps raise ``FencedError``
  (counted), and a double promotion converges to exactly one serving
  epoch;
* replication lag and failover counts are scrapeable from
  ``SearchServer.prometheus_text()``.

The subprocess SIGKILL drill lives in ``tests/_failover_driver.py`` —
the same module computes the parent's expected-state ladder, so the
child's mutations and the parent's expectations are one code path.
"""

from __future__ import annotations

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import _durability_driver as dur  # noqa: E402
import _failover_driver as fo  # noqa: E402

from raft_tpu.core.serialize import CorruptArtifact  # noqa: E402
from raft_tpu.neighbors import mutation  # noqa: E402
from raft_tpu.neighbors.wal import DurableStore  # noqa: E402
from raft_tpu.obs.metrics import MetricRegistry  # noqa: E402
from raft_tpu.serve import (CRASH_EXIT_CODE, EpochFence,  # noqa: E402
                            EpochToken, FaultInjector, FencedError,
                            LogShipper, Partitioned, QueuePair,
                            ReplicationConfig, SearchServer, ServerConfig,
                            SocketListener, StandbyReplica)
from raft_tpu.serve.replication import (decode_message,  # noqa: E402
                                        encode_message)

D = fo.D


def _leaves(tree):
    return [np.asarray(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(tree)]


def assert_bit_identical(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype and x.shape == y.shape
        np.testing.assert_array_equal(x, y)


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _pair(tmp_path, mode="semi_sync", *, hello=True, **cfg_kw):
    """Primary store + shipper wired to a cold standby over an
    in-process queue pair, with separate metric registries."""
    a, b = QueuePair.create()
    pstore = DurableStore.create(tmp_path / "primary",
                                 dur.initial_tombstoned())
    cfg = ReplicationConfig(ack_mode=mode, **cfg_kw)
    reg_p, reg_s = MetricRegistry(), MetricRegistry()
    shipper = LogShipper(pstore, a, config=cfg, registry=reg_p)
    replica = StandbyReplica(tmp_path / "standby", b, config=cfg,
                             registry=reg_s, hello=hello)
    return pstore, shipper, replica


def _bootstrap(shipper, replica):
    shipper.pump()   # hello -> cold catch-up ships a snapshot
    replica.poll()   # standby installs it and acks the watermark
    shipper.pump()   # primary records the ack
    assert replica.store is not None, "bootstrap never landed"


# ---------------------------------------------------------------------------
# wire format


def test_message_frame_roundtrip_and_crc():
    blob = encode_message("record", {"x": np.arange(4, dtype=np.float32)},
                          lsn=3, op="extend", node="p")
    msg = decode_message(blob)
    assert msg.kind == "record"
    assert msg.static["lsn"] == 3 and msg.static["node"] == "p"
    np.testing.assert_array_equal(msg.arrays["x"],
                                  np.arange(4, dtype=np.float32))
    bad = bytearray(blob)
    bad[-1] ^= 0xFF  # payload bitflip -> crc mismatch
    with pytest.raises(CorruptArtifact):
        decode_message(bytes(bad))
    with pytest.raises(CorruptArtifact):
        decode_message(b"XXXX" + blob[4:])  # wrong magic


def test_epoch_token_total_order_and_persistence(tmp_path):
    assert EpochToken(1, "a") < EpochToken(1, "b") < EpochToken(2, "a")
    f = EpochFence.load(tmp_path, "n1", writer=True)
    assert f.epoch == 0 and not f.fenced
    f.advance()
    assert f.epoch == 1
    f.observe(5, "other")
    assert f.fenced
    # both the claim and the highest seen epoch survive a restart
    g = EpochFence.load(tmp_path, "n1", writer=True)
    assert g.epoch == 1 and g.max_seen == EpochToken(5, "other") \
        and g.fenced


def test_partition_fault_kind_from_env():
    inj = FaultInjector.from_env("ship_send:partition")
    assert inj.pending("ship_send") == 1
    with pytest.raises(Partitioned):
        inj.fire("ship_send")
    inj.fire("ship_send")  # consumed: healed, no-op


# ---------------------------------------------------------------------------
# ship bit-identity


def test_cold_bootstrap_then_ship_bit_identity(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "semi_sync",
                                     ack_timeout_s=30.0)
    _bootstrap(shipper, replica)
    replica.start()  # semi-sync needs a live follower to ack
    try:
        for op, args in fo.op_list():
            fo.apply_op(pstore, op, args)
    finally:
        replica.stop()
    while replica.poll(0.05):
        pass
    assert replica.applied == pstore.wal_lsn == fo.OP_COUNT
    # bit-identity three ways: standby == primary == fault-free replay
    assert_bit_identical(replica.store.index, pstore.index)
    states = fo.expected_states(tmp_path / "expected")
    assert_bit_identical(replica.store.index, states[fo.OP_COUNT])
    # semi-sync acked every record; lag is zero on both ends
    assert shipper.metrics.counter(
        "raft_replication_acks_total", "").value() >= fo.OP_COUNT
    assert replica.lag() == {"lsn": 0.0, "seconds": 0.0}
    shipper.pump()
    assert pstore.follower_floor() == fo.OP_COUNT


def test_warm_standby_restart_catches_up_from_watermark(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "async")
    _bootstrap(shipper, replica)
    ops = fo.op_list()
    for op, args in ops[:3]:
        fo.apply_op(pstore, op, args)
    replica.poll()
    assert replica.applied == 3
    replica.stop()
    # standby restarts over the same root: recovers locally, then its
    # hello asks only for the tail past its watermark
    a, b = QueuePair.create()
    shipper.transport = a
    replica2 = StandbyReplica(tmp_path / "standby", b,
                              config=replica.config,
                              registry=MetricRegistry())
    assert replica2.applied == 3  # local recovery, before any traffic
    for op, args in ops[3:]:
        fo.apply_op(pstore, op, args)
    shipper.pump()   # hello -> tail catch-up (no snapshot re-ship)
    replica2.poll()
    assert replica2.applied == pstore.wal_lsn == len(ops)
    assert_bit_identical(replica2.store.index, pstore.index)


# ---------------------------------------------------------------------------
# chaos: partitions, gaps, ack loss, timeouts


def test_partition_gap_detected_and_resynced(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "async")
    _bootstrap(shipper, replica)
    shipper.faults = FaultInjector().arm("ship_send", "partition")
    ops = fo.op_list()
    fo.apply_op(pstore, *ops[0])   # record 1 dropped on the wire
    fo.apply_op(pstore, *ops[1])   # record 2 delivered -> gap
    assert shipper.metrics.counter(
        "raft_replication_drops_total", "").value() == 1
    replica.poll()
    assert replica.metrics.counter(
        "raft_replication_gaps_total", "").value() == 1
    assert replica.applied == 0     # never applied out of order
    shipper.pump()                  # resync hello -> tail re-ship
    replica.poll()
    assert replica.applied == 2
    assert_bit_identical(replica.store.index, pstore.index)


def test_partition_all_records_healed_by_heartbeat(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "async")
    _bootstrap(shipper, replica)
    shipper.faults = FaultInjector().arm("ship_send", "partition", times=2)
    for op, args in fo.op_list()[:2]:
        fo.apply_op(pstore, op, args)  # both drops: standby sees nothing
    replica.poll()
    assert replica.applied == 0
    shipper.beat(force=True)  # lag surfaces on the next heartbeat
    replica.poll()            # lsn 2 > applied 0 -> resync request
    assert replica.lag()["lsn"] == 2.0
    shipper.pump()
    replica.poll()
    assert replica.applied == 2
    assert_bit_identical(replica.store.index, pstore.index)


def test_ack_partition_reregisters_via_hello(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "async")
    _bootstrap(shipper, replica)
    replica.faults = FaultInjector().arm("ship_ack", "partition")
    fo.apply_op(pstore, *fo.op_list()[0])
    replica.poll()              # applied, but the ack was dropped
    assert replica.applied == 1
    shipper.pump()
    assert pstore.follower_floor() == 0  # primary never saw the ack
    replica.hello()             # re-introduction carries the watermark
    shipper.pump()
    assert pstore.follower_floor() == 1


def test_semi_sync_ack_timeout_degrades_not_wedges(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "semi_sync",
                                     ack_timeout_s=0.05)
    _bootstrap(shipper, replica)
    fo.apply_op(pstore, *fo.op_list()[0])  # standby never polls
    # the mutation returned (no wedge) and the degrade was counted
    assert pstore.wal_lsn == 1
    assert shipper.metrics.counter(
        "raft_replication_ack_timeouts_total", "").value() == 1


def test_async_backpressure_bounds_unacked_window(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "async", ship_queue=2,
                                     ack_timeout_s=0.05)
    _bootstrap(shipper, replica)
    for op, args in fo.op_list()[:4]:
        fo.apply_op(pstore, op, args)  # floor stuck at 0, window is 2
    timeouts = shipper.metrics.counter(
        "raft_replication_ack_timeouts_total", "").value()
    assert timeouts >= 1  # lsn 3+ pushed past the window and waited
    replica.poll()        # queue retained everything: full catch-up
    assert replica.applied == 4
    assert_bit_identical(replica.store.index, pstore.index)


# ---------------------------------------------------------------------------
# fencing + promotion


def test_promotion_fences_deposed_primary(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "async")
    _bootstrap(shipper, replica)
    for op, args in fo.op_list()[:2]:
        fo.apply_op(pstore, op, args)
    replica.poll()
    store = replica.promote(drain_timeout_s=0.01)
    assert replica.is_serving
    assert_bit_identical(store.index, pstore.index)
    shipper.pump()  # the fence announcement deposes the old primary
    with pytest.raises(FencedError):
        pstore.extend(np.zeros((2, D), np.float32))
    with pytest.raises(FencedError):
        pstore.snapshot()
    assert pstore.counters["fenced_writes"] == 2
    # the promoted store is a writable primary at the shipped lsn
    store.extend(np.ones((2, D), np.float32))
    assert store.wal_lsn == 3


def test_double_promotion_converges_to_one_serving_epoch(tmp_path):
    # two warm standbys (seeded with identical local state) race
    for name in ("a", "b"):
        DurableStore.create(tmp_path / name, dur.initial_tombstoned()).close()
    ta, tb = QueuePair.create()
    ra = StandbyReplica(tmp_path / "a", ta, node_id="a",
                        registry=MetricRegistry(), hello=False)
    rb = StandbyReplica(tmp_path / "b", tb, node_id="b",
                        registry=MetricRegistry(), hello=False)
    ra.promote(drain_timeout_s=0.01)
    rb.promote(drain_timeout_s=0.01)  # drains ra's fence, outbids it
    ra.poll()
    rb.poll()
    assert [ra.is_serving, rb.is_serving].count(True) == 1
    assert rb.is_serving and ra.fence.fenced
    assert ra.fence.max_seen == rb.fence.token


def test_lease_expiry_detects_dead_primary(tmp_path):
    clock = FakeClock()
    a, b = QueuePair.create()
    pstore = DurableStore.create(tmp_path / "primary",
                                 dur.initial_tombstoned())
    cfg = ReplicationConfig(lease_s=3.0)
    shipper = LogShipper(pstore, a, config=cfg,
                         registry=MetricRegistry(), clock=clock)
    replica = StandbyReplica(tmp_path / "standby", b, config=cfg,
                             registry=MetricRegistry(), clock=clock)
    assert not replica.primary_alive()  # no traffic yet
    _bootstrap(shipper, replica)
    shipper.beat(force=True)
    replica.poll()
    assert replica.primary_alive()
    clock.advance(2.9)
    assert replica.primary_alive()
    clock.advance(0.2)  # lease expired: 3.1s of silence
    assert not replica.primary_alive()


# ---------------------------------------------------------------------------
# serving integration


def test_standby_serves_bounded_staleness_reads(tmp_path):
    pstore, shipper, replica = _pair(tmp_path, "async", refresh_every=2)
    _bootstrap(shipper, replica)
    srv = SearchServer(replica.store.index, k=3,
                       config=ServerConfig(ladder=(4,)))
    replica.attach_server(srv)
    gen0 = srv.index
    ops = fo.op_list()
    fo.apply_op(pstore, *ops[0])
    replica.poll()
    assert srv.index is gen0          # 1 applied < refresh_every
    fo.apply_op(pstore, *ops[1])
    replica.poll()
    assert srv.index is replica.store.index  # staleness bound hit: swap
    q = np.random.default_rng(3).standard_normal((2, D)).astype(np.float32)
    d_srv, i_srv = srv.search(q)
    d_ref, i_ref = mutation.search(pstore.index, q, 3)
    np.testing.assert_array_equal(np.asarray(d_srv),
                                  np.asarray(jax.device_get(d_ref)))
    np.testing.assert_array_equal(np.asarray(i_srv),
                                  np.asarray(jax.device_get(i_ref)))


def test_server_attach_replication_scrape_and_failover(tmp_path):
    # primary server over a recovered durable store
    DurableStore.create(tmp_path / "p", dur.initial_tombstoned()).close()
    psrv = SearchServer.recover(tmp_path / "p", k=3,
                                config=ServerConfig(ladder=(4,)))
    a, b = QueuePair.create()
    shipper = psrv.attach_replication("primary", a)
    assert psrv.fence is shipper.fence and psrv.replication is shipper
    # standby server wired via the same entry point
    ssrv = SearchServer(dur.initial_tombstoned(), k=3,
                        config=ServerConfig(ladder=(4,)))
    replica = ssrv.attach_replication("standby", b, root=tmp_path / "s")
    _bootstrap(shipper, replica)
    fo.apply_op(psrv.durable_store, *fo.op_list()[0])
    replica.poll()
    shipper.pump()
    text_p, text_s = psrv.prometheus_text(), ssrv.prometheus_text()
    assert "raft_replication_acks_total" in text_p
    assert "raft_replication_lag_lsn" in text_s
    assert "raft_replication_lag_seconds" in text_s
    assert "raft_failovers_total" in text_s
    assert psrv.metrics.registry.counter(
        "raft_replication_acks_total", "").value() >= 1
    assert replica.lag() == {"lsn": 0.0, "seconds": 0.0}
    # failover: standby promotes, old server's swap is fenced
    replica.promote(drain_timeout_s=0.01)
    shipper.pump()
    assert "raft_failovers_total" in ssrv.prometheus_text()
    assert ssrv.metrics.registry.counter(
        "raft_failovers_total", "").value() == 1
    with pytest.raises(FencedError):
        psrv.swap_index(dur.initial_tombstoned())
    assert psrv.metrics.counter_value("fenced_writes") == 1
    # the promoted server answers from the replicated generation
    q = np.random.default_rng(5).standard_normal((2, D)).astype(np.float32)
    d_new, i_new = ssrv.search(q)
    d_ref, i_ref = mutation.search(replica.store.index, q, 3)
    np.testing.assert_array_equal(np.asarray(d_new),
                                  np.asarray(jax.device_get(d_ref)))
    np.testing.assert_array_equal(np.asarray(i_new),
                                  np.asarray(jax.device_get(i_ref)))


# ---------------------------------------------------------------------------
# the SIGKILL failover drill (subprocess, socket transport)


def _run_failover_child(root, port, mode, crash_at):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, FO_ROOT=str(root), FO_PORT=str(port),
               FO_ACK_MODE=mode, FO_CRASH_AT=str(crash_at),
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.path.dirname(os.path.abspath(
                       __file__)), os.environ.get("PYTHONPATH")) if p))
    return subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "_failover_driver.py")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


@pytest.mark.parametrize("mode", ["semi_sync", "async"])
def test_sigkill_failover_promoted_standby_bit_identical(mode, tmp_path):
    crash_at = fo.OP_COUNT - 2
    listener = SocketListener()
    proc = _run_failover_child(tmp_path / "primary", listener.port, mode,
                               crash_at)
    try:
        transport = listener.accept(timeout=120)
        replica = StandbyReplica(tmp_path / "standby", transport,
                                 config=ReplicationConfig(ack_mode=mode),
                                 registry=MetricRegistry())
        replica.start()
        _, err = proc.communicate(timeout=540)
        assert proc.returncode == CRASH_EXIT_CODE, \
            f"child should die at the armed wal_append site " \
            f"(rc={proc.returncode}):\n{err[-2000:]}"
        replica.stop()
        while replica.poll(0.2):  # drain what TCP already delivered
            pass
    finally:
        proc.kill()
        listener.close()
    m = int((tmp_path / "primary" / "progress").read_text())
    assert m == crash_at  # the schedule reached the armed op
    w = replica.applied
    if mode == "semi_sync":
        # zero acked mutations lost: every completed op reached the
        # standby before its mutator returned
        assert w == m, f"semi_sync lost acked records (applied {w} of {m})"
    else:
        assert w <= m
        assert m - w <= replica.config.ship_queue + 1, \
            f"async loss {m - w} exceeds the ship-queue bound"
    store = replica.promote(drain_timeout_s=0.05)
    assert replica.is_serving
    states = fo.expected_states(tmp_path / "expected")
    assert_bit_identical(store.index, states[w])
    # search-results identity — values AND ids — at the acked watermark
    q = np.random.default_rng(17).standard_normal((3, D)).astype(np.float32)
    d_new, i_new = mutation.search(store.index, q, 3)
    d_ref, i_ref = mutation.search(states[w], q, 3)
    np.testing.assert_array_equal(np.asarray(jax.device_get(d_new)),
                                  np.asarray(jax.device_get(d_ref)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(i_new)),
                                  np.asarray(jax.device_get(i_ref)))
    # the promoted store is a writable primary: life goes on
    store.extend(np.ones((2, D), np.float32))
    assert store.wal_lsn == w + 1


# ---------------------------------------------------------------------------
# multi-follower fan-out (ISSUE 16 satellite: WAL shipping to N standbys)


def test_two_follower_fanout_acks_floor_and_lag(tmp_path):
    clk = FakeClock()
    a1, b1 = QueuePair.create()
    a2, b2 = QueuePair.create()
    pstore = DurableStore.create(tmp_path / "primary",
                                 dur.initial_tombstoned(), clock=clk)
    cfg = ReplicationConfig(ack_mode="async")
    reg_p = MetricRegistry()
    shipper = LogShipper(pstore, [a1, a2], config=cfg, registry=reg_p,
                         clock=clk)
    s1 = StandbyReplica(tmp_path / "s1", b1, config=cfg, node_id="s1",
                        registry=MetricRegistry(), clock=clk)
    s2 = StandbyReplica(tmp_path / "s2", b2, config=cfg, node_id="s2",
                        registry=MetricRegistry(), clock=clk)
    # one pump serves BOTH hellos (snapshot bootstrap is per-link)
    shipper.pump()
    s1.poll()
    s2.poll()
    shipper.pump()
    assert s1.store is not None and s2.store is not None
    assert set(pstore.followers()) == {"s1", "s2"}

    ops = fo.op_list()
    for op, args in ops:
        fo.apply_op(pstore, op, args)
    # only s1 drains: the floor tracks the SLOWEST follower
    s1.poll()
    shipper.pump()
    assert s1.applied == pstore.wal_lsn == len(ops)
    assert pstore.followers()["s1"] == len(ops)
    assert pstore.followers()["s2"] == 0
    assert pstore.follower_floor() == 0
    lag = shipper.metrics.gauge("raft_replication_follower_lag_lsn", "")
    assert lag.value(follower="s1") == 0.0
    assert lag.value(follower="s2") == float(len(ops))
    assert shipper.metrics.gauge(
        "raft_replication_lag_lsn", "").value() == float(len(ops))

    # s2 catches up; floor converges and both replicas are bit-identical
    s2.poll()
    shipper.pump()
    assert s2.applied == len(ops)
    assert pstore.follower_floor() == len(ops)
    assert lag.value(follower="s2") == 0.0
    assert_bit_identical(s1.store.index, pstore.index)
    assert_bit_identical(s2.store.index, pstore.index)
    s1.stop()
    s2.stop()
    shipper.stop()
