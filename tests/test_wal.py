"""WAL + durable store unit tests (ISSUE 7 tentpole, in-process half).

Covers the log format (framing, CRC, LSN monotonicity, torn-tail
detection), group-commit fsync batching, deterministic replay pinned
bit-identical against the live-mutated index, checksummed snapshots
(verify/corrupt/quarantine), and recovery fallback to the previous good
snapshot.  The subprocess crash/recover driver lives in
``tests/test_durability.py``.
"""

from __future__ import annotations

import os

import jax
import numpy as np
import pytest

from raft_tpu.core.errors import RaftError
from raft_tpu.core.serialize import CorruptArtifact, save_arrays, verify_arrays
from raft_tpu.neighbors import ivf_flat, mutation
from raft_tpu.neighbors.serialize import (index_manifest, load_index,
                                          save_index, verify_index)
from raft_tpu.neighbors.wal import (DurableStore, WalConfig, WriteAheadLog,
                                    read_wal, replay)

N, D = 256, 8


@pytest.fixture(scope="module", autouse=True)
def _fresh_compile_cache():
    # By the time the full suite reaches this module it carries ~700
    # tests' worth of live compiled executables in one process, and the
    # store's compact/pack_lists compile segfaulted XLA:CPU's JIT
    # deterministically on the 1-core runner (backend_compile, code-memory
    # exhaustion).  Dropping the caches frees the dead executables first;
    # standalone runs are unaffected beyond a few warm-up compiles.
    jax.clear_caches()


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(50).standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def built(db):
    return ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=4, seed=0))


def _leaves(tree):
    return [np.asarray(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(tree)]


def assert_bit_identical(a, b):
    """Values AND ids: every pytree leaf equal, bit for bit."""
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype and x.shape == y.shape
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# log format


def test_wal_roundtrip_framing(tmp_path):
    path = tmp_path / "wal.log"
    w = WriteAheadLog(path)
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    ids = np.array([7, 8, 9], np.int64)
    assert w.append("extend", {"vectors": a, "ids": ids},
                    {"insert_chunk": 0}) == 1
    assert w.append("delete", {"ids": ids}, {"id_space": 64}) == 2
    assert w.append("compact", {}, {"headroom": 1.5}) == 3
    w.close()
    records, good_end, problems = read_wal(path)
    assert problems == [] and good_end == os.path.getsize(path)
    assert [r.lsn for r in records] == [1, 2, 3]
    assert [r.op for r in records] == ["extend", "delete", "compact"]
    np.testing.assert_array_equal(records[0].arrays["vectors"], a)
    np.testing.assert_array_equal(records[1].arrays["ids"], ids)
    assert records[2].static["headroom"] == 1.5


def test_wal_rejects_unknown_op(tmp_path):
    w = WriteAheadLog(tmp_path / "wal.log")
    with pytest.raises(RaftError):
        w.append("truncate", {}, {})


def test_wal_reopen_resumes_lsn(tmp_path):
    path = tmp_path / "wal.log"
    w = WriteAheadLog(path)
    w.append("compact", {}, {})
    w.close()
    w2 = WriteAheadLog(path)
    assert w2.lsn == 1
    assert w2.append("compact", {}, {}) == 2
    w2.close()
    records, _, problems = read_wal(path)
    assert problems == [] and [r.lsn for r in records] == [1, 2]


def test_wal_torn_tail_detected_and_reopen_refuses(tmp_path):
    path = tmp_path / "wal.log"
    w = WriteAheadLog(path)
    w.append("compact", {}, {"headroom": 2.0})
    w.append("delete", {"ids": np.array([1])}, {})
    w.close()
    clean_records, clean_end, _ = read_wal(path)
    with open(path, "ab") as f:  # torn write: half a record header
        f.write(b"\x01\x02\x03garbage")
    records, good_end, problems = read_wal(path)
    assert [r.lsn for r in records] == [1, 2]  # intact prefix survives
    assert good_end == clean_end
    assert problems  # the tail is flagged, not silently parsed
    with pytest.raises(CorruptArtifact):
        WriteAheadLog(path)  # plain reopen never appends after garbage


def test_wal_corrupt_record_stops_scan_at_last_good(tmp_path):
    path = tmp_path / "wal.log"
    w = WriteAheadLog(path)
    w.append("compact", {}, {})
    mid_end = os.path.getsize(path)
    w.append("delete", {"ids": np.array([1, 2])}, {"id_space": 32})
    w.close()
    with open(path, "r+b") as f:  # flip one payload byte of record 2
        f.seek(mid_end + 21)
        b = f.read(1)
        f.seek(mid_end + 21)
        f.write(bytes([b[0] ^ 0xFF]))
    records, good_end, problems = read_wal(path)
    assert [r.lsn for r in records] == [1]
    assert good_end == mid_end
    assert any("crc mismatch" in p or "lsn" in p for p in problems)


def test_wal_group_commit_batches_fsyncs(tmp_path):
    clock = [0.0]
    syncs = []
    w = WriteAheadLog(tmp_path / "wal.log",
                      WalConfig(group_window_s=1.0),
                      clock=lambda: clock[0], _fsync=syncs.append)
    base = len(syncs)  # header sync
    for _ in range(5):  # all inside the window: zero extra fsyncs
        w.append("compact", {}, {})
    assert len(syncs) == base
    clock[0] += 2.0  # window elapsed: next append syncs
    w.append("compact", {}, {})
    assert len(syncs) == base + 1
    w.sync()  # explicit flush (snapshot watermark discipline)
    assert len(syncs) == base + 2

    strict = WriteAheadLog(tmp_path / "strict.log", WalConfig(),
                           _fsync=syncs.append)
    n0 = len(syncs)
    strict.append("compact", {}, {})
    strict.append("compact", {}, {})
    assert len(syncs) == n0 + 2  # window 0: every append is durable


# ---------------------------------------------------------------------------
# replay determinism


def test_replay_pinned_bit_identical_to_live(built, db):
    rng = np.random.default_rng(51)
    live = mutation.delete(built, [2, 9], id_space=2048)
    ops = [
        ("extend", {"vectors": rng.standard_normal((32, D)).astype(
            np.float32)}, {"insert_chunk": 0}),
        ("delete", {"ids": np.array([30, 40, 50])}, {"id_space": 0}),
        ("compact", {}, {"headroom": 2.0, "rewrap_bits": 2048}),
        ("extend", {"vectors": rng.standard_normal((16, D)).astype(
            np.float32), "ids": np.arange(1000, 1016)}, {"insert_chunk": 0}),
        ("delete", {"ids": np.array([1003])}, {"id_space": 0}),
    ]
    from raft_tpu.neighbors.wal import WalRecord, _apply

    records = [WalRecord(i + 1, op, arrays, static)
               for i, (op, arrays, static) in enumerate(ops)]
    for rec in records:
        live = _apply(live, rec)
    start = mutation.delete(built, [2, 9], id_space=2048)
    recovered = replay(start, records)
    assert_bit_identical(live, recovered)


# ---------------------------------------------------------------------------
# checksummed artifacts


def test_save_arrays_checksums_catch_bitflip_and_truncation(tmp_path):
    path = tmp_path / "bundle"
    save_arrays(path, {"a": np.arange(100, dtype=np.float32)},
                {"k": 1}, fsync=True)
    assert verify_arrays(path) == []
    f = path / "a.npy"
    blob = bytearray(f.read_bytes())
    blob[len(blob) // 2] ^= 0x01
    f.write_bytes(bytes(blob))
    assert any("checksum" in p for p in verify_arrays(path))
    f.write_bytes(bytes(blob[:-10]))  # truncation also caught
    assert any("checksum" in p for p in verify_arrays(path))


def test_verify_index_and_manifest(tmp_path, built):
    path = tmp_path / "idx"
    save_index(path, built, manifest={"wal_lsn": 17})
    assert verify_index(path) == []
    assert index_manifest(path) == {"wal_lsn": 17}
    back = load_index(path, verify=True)
    assert_bit_identical(built, back)
    os.remove(os.path.join(path, "ids.npy"))
    assert any("ids.npy" in p for p in verify_index(path))
    with pytest.raises(CorruptArtifact):
        load_index(path, verify=True)


def test_atomic_save_never_exposes_partial_bundle(tmp_path, built):
    path = tmp_path / "idx"
    save_index(path, built)  # atomic=True default
    assert not any(".tmp-" in n for n in os.listdir(tmp_path))
    save_index(path, built, manifest={"wal_lsn": 3})  # refresh-in-place
    assert index_manifest(path) == {"wal_lsn": 3}
    assert verify_index(path) == []


# ---------------------------------------------------------------------------
# durable store: snapshots, recovery, quarantine


def _store_with_history(tmp_path, built, *, retain=4):
    rng = np.random.default_rng(52)
    t = mutation.delete(built, [5], id_space=2048)
    store = DurableStore.create(tmp_path / "dur", t,
                                config=WalConfig(retain_snapshots=retain))
    store.extend(rng.standard_normal((24, D)).astype(np.float32))
    store.delete([40, 41])
    store.snapshot()
    store.extend(rng.standard_normal((8, D)).astype(np.float32))
    store.compact()
    return store


def test_store_recover_bit_identical(tmp_path, built):
    store = _store_with_history(tmp_path, built)
    live = store.index
    lsn = store.wal_lsn
    store.close()
    rec = DurableStore.recover(tmp_path / "dur")
    assert_bit_identical(live, rec.index)
    assert rec.wal_lsn == lsn
    assert rec.counters["recoveries"] == 1
    assert rec.counters.get("quarantined_files", 0) == 0
    # replayed exactly the records past the newest snapshot's watermark
    assert rec.counters["wal_replayed"] == 2
    rec.close()


def test_store_corrupt_snapshot_quarantined_with_fallback(tmp_path, built):
    store = _store_with_history(tmp_path, built)
    live = store.index
    newest = store.snapshots()[-1]
    store.close()
    snap_dir = tmp_path / "dur" / "snapshots"
    victim = snap_dir / newest / "data.npy"
    blob = bytearray(victim.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    victim.write_bytes(bytes(blob))
    rec = DurableStore.recover(tmp_path / "dur")
    # fell back to the previous good snapshot + a LONGER replay, landing
    # on the same state — corruption costs time, not data
    assert_bit_identical(live, rec.index)
    assert rec.counters["quarantined_files"] == 1
    assert rec.counters["wal_replayed"] == 4
    assert newest in os.listdir(tmp_path / "dur" / "quarantine")
    assert newest not in os.listdir(snap_dir)  # never parsed again
    rec.close()


def test_store_torn_wal_tail_quarantined_and_truncated(tmp_path, built):
    store = _store_with_history(tmp_path, built)
    store.close()
    wal_path = tmp_path / "dur" / "wal.log"
    records, _, _ = read_wal(wal_path)
    with open(wal_path, "ab") as f:
        f.write(os.urandom(13))
    rec = DurableStore.recover(tmp_path / "dur")
    assert rec.counters["quarantined_files"] == 1
    qdir = tmp_path / "dur" / "quarantine"
    assert any(n.startswith("wal-tail-") and not n.endswith(".reason")
               for n in os.listdir(qdir))
    # truncated back to a clean log: a further mutation appends fine
    clean, _, problems = read_wal(wal_path)
    assert problems == [] and len(clean) == len(records)
    rec.extend(np.zeros((4, D), np.float32))
    assert rec.wal_lsn == records[-1].lsn + 1
    rec.close()


def test_store_no_valid_snapshot_raises(tmp_path, built):
    store = DurableStore.create(tmp_path / "dur",
                                mutation.delete(built, [1], id_space=1024),
                                config=WalConfig(retain_snapshots=1))
    snap = store.snapshots()[-1]
    store.close()
    victim = tmp_path / "dur" / "snapshots" / snap / "meta.json"
    victim.write_text("not json{{{")
    with pytest.raises(CorruptArtifact):
        DurableStore.recover(tmp_path / "dur")


def test_store_prunes_snapshots_but_keeps_fallback(tmp_path, built):
    t = mutation.delete(built, [3], id_space=1024)
    store = DurableStore.create(tmp_path / "dur", t,
                                config=WalConfig(retain_snapshots=2))
    for _ in range(4):
        store.delete([int(np.random.default_rng(0).integers(10, 100))])
        store.snapshot()
    assert len(store.snapshots()) == 2
    store.close()


def test_store_group_commit_window_recovers_synced_prefix(tmp_path, built):
    # a large group-commit window defers fsync, but records still land in
    # the OS page cache — a process crash (vs power loss) loses nothing,
    # and recover() replays the full committed sequence
    t = mutation.delete(built, [7], id_space=2048)
    store = DurableStore.create(tmp_path / "dur", t,
                                config=WalConfig(group_window_s=3600.0))
    store.delete([9, 10])
    live = store.index
    store.wal._f.flush()  # simulate crash without close(): no fsync
    rec = DurableStore.recover(tmp_path / "dur")
    assert_bit_identical(live, rec.index)
    rec.close()


def test_tombstoned_and_brute_serialize_roundtrip(tmp_path, db, built):
    t = mutation.delete(built, [2, 4, 8], id_space=512)
    p1 = tmp_path / "tomb"
    save_index(p1, t)
    back = load_index(p1, verify=True)
    assert isinstance(back, mutation.Tombstoned)
    assert_bit_identical(t, back)

    p2 = tmp_path / "brute"
    save_index(p2, db, manifest={"wal_lsn": 0})
    flat = load_index(p2, verify=True)
    np.testing.assert_array_equal(np.asarray(jax.device_get(flat)), db)

    tb = mutation.delete(db, [0, 1], id_space=N)
    p3 = tmp_path / "tomb-brute"
    save_index(p3, tb)
    tback = load_index(p3, verify=True)
    assert isinstance(tback, mutation.Tombstoned)
    assert_bit_identical(tb, tback)


def test_brute_compact_matches_filtered_search(db):
    k = 5
    from raft_tpu.neighbors import brute_force

    dead = [0, 3, 17, 100, 255]
    t = mutation.delete(db, dead, id_space=N)
    compacted = mutation.compact(t)
    assert compacted.shape == (N - len(dead), D)
    q = np.random.default_rng(53).standard_normal((6, D)).astype(np.float32)
    df, i_f = mutation.search(t, q, k)           # filtered, uncompacted
    dc, i_c = brute_force.knn(q, compacted, k)   # compacted, unfiltered
    kept = np.flatnonzero(~np.isin(np.arange(N), dead))
    np.testing.assert_array_equal(np.asarray(jax.device_get(df)),
                                  np.asarray(jax.device_get(dc)))
    # compaction renumbers rows positionally: map back through kept
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(i_f)),
        kept[np.asarray(jax.device_get(i_c))])


def test_brute_compact_plain_and_empty_guard(db):
    out = mutation.compact(db[:16])  # no tombstones: a plain copy
    np.testing.assert_array_equal(np.asarray(jax.device_get(out)), db[:16])
    t = mutation.delete(db[:4], [0, 1, 2, 3], id_space=4)
    with pytest.raises(RaftError):
        mutation.compact(t)  # dropping every row is a refusal, not (0, d)


# ---------------------------------------------------------------------------
# WAL pruning (ISSUE 15): the follower-ack floor


def test_wal_prune_retains_newest_record_and_resumes_lsn(tmp_path):
    path = tmp_path / "wal.log"
    w = WriteAheadLog(path)
    for _ in range(5):
        w.append("compact", {}, {})
    # asking past the end still keeps the newest record: a reopen must
    # be able to resume the LSN sequence from the file alone
    assert w.prune(99) == 4
    records, _, problems = read_wal(path)
    assert problems == [] and [r.lsn for r in records] == [5]
    assert w.append("compact", {}, {}) == 6
    w.close()
    w2 = WriteAheadLog(path)
    assert w2.lsn == 6
    w2.close()
    # pruning below the oldest retained record is a no-op
    w3 = WriteAheadLog(path)
    assert w3.prune(4) == 0
    w3.close()


def test_store_prune_wal_floors_at_follower_ack(tmp_path, built):
    # retain=1: only the mid-history snapshot (watermark lsn 2) remains,
    # so the snapshot floor alone would discard records 1 AND 2
    store = _store_with_history(tmp_path, built, retain=1)
    assert [r.lsn for r in read_wal(store.wal.path)[0]] == [1, 2, 3, 4]
    # a slow follower caps the floor: prune may not discard past its ack
    store.register_follower("standby", 1)
    assert store.prune_wal() == 1
    assert [r.lsn for r in read_wal(store.wal.path)[0]] == [2, 3, 4], \
        "record 2 (> follower ack 1) must survive"
    # the follower catches up: the floor rises to the snapshot watermark
    store.follower_acked("standby", store.wal_lsn)
    assert store.prune_wal() == 1
    assert [r.lsn for r in read_wal(store.wal.path)[0]] == [3, 4]
    assert store.counters["wal_pruned"] == 2
    # replay from the retained snapshot + tail recovers bit-identically
    store.close()
    re = DurableStore.recover(tmp_path / "dur")
    ref = _store_with_history(tmp_path / "ref", built)
    assert_bit_identical(re.index, ref.index)
    re.close()
    ref.close()


def test_store_prune_wal_registry_lifecycle(tmp_path, built):
    store = _store_with_history(tmp_path, built)
    store.register_follower("a", 2)
    store.register_follower("b", 4)
    assert store.follower_floor() == 2
    store.follower_acked("a", 1)  # acks are monotonic: never regress
    assert store.followers()["a"] == 2
    store.drop_follower("a")
    assert store.follower_floor() == 4
    store.drop_follower("b")
    assert store.follower_floor() is None
    store.close()
