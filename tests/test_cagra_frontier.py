"""Frontier-blocked CAGRA search engine gates.

Three contracts pinned here:

* **Engine parity** — the production frontier engine (one ``[nq, w·deg]``
  slab gather + one unsorted ``select_k`` fold + sorted-ring visited
  filter per iteration) is BIT-IDENTICAL, values and ids, to the
  retained per-parent reference engine at every ``search_width``,
  including the filtered and sharded paths.  This is the CAGRA analog of
  the probe-block invariance contract: blocking is a schedule, never a
  semantic.
* **Dedup keep-best** — ``_dedup_by_id`` must invalidate a duplicate
  slot COMPLETELY (value → +inf AND id → −1).  The pre-fix behavior kept
  the loser's real id, letting a downstream ``select_k(..., in_idx=...)``
  fold resurrect the duplicate at its WORST distance.
* **Steady state** — one executable serves every ``max_iterations`` up
  to the compiled scan length, and the serving ``searcher()`` runs mixed
  query shapes with zero retraces and zero implicit transfers after
  warmup.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import TraceGuard
from raft_tpu.core.bitset import Bitmap, Bitset
from raft_tpu.neighbors import cagra
from raft_tpu.random.datagen import make_blobs

K = 10
ITOPK = 16


@pytest.fixture(scope="module")
def data():
    x, _ = make_blobs(jax.random.PRNGKey(7), n_samples=4000, n_features=32,
                      n_clusters=20, cluster_std=1.0)
    return np.asarray(x), np.asarray(x[:100])


@pytest.fixture(scope="module")
def index(data):
    x, _ = data
    return cagra.build(x, cagra.CagraIndexParams(
        intermediate_graph_degree=32, graph_degree=16))


def _params(impl, width, **kw):
    return cagra.CagraSearchParams(itopk_size=kw.pop("itopk", ITOPK),
                                   search_width=width, n_seeds=16,
                                   search_impl=impl, **kw)


def _both(index, q, width, **kw):
    dv_f, di_f = cagra.search(index, q, K, _params("frontier", width, **kw))
    dv_p, di_p = cagra.search(index, q, K, _params("per_parent", width, **kw))
    return (np.asarray(dv_f), np.asarray(di_f),
            np.asarray(dv_p), np.asarray(di_p))


# ---------------------------------------------------------------------------
# dedup keep-best regression


def test_dedup_by_id_invalidates_loser_completely():
    vals = jnp.asarray([[5.0, 3.0]])
    ids = jnp.asarray([[7, 7]], jnp.int32)
    dv, di = cagra._dedup_by_id(vals, ids)
    dv, di = np.asarray(dv), np.asarray(di)
    # best copy survives; the loser slot is fully invalidated
    assert (dv[0] == 3.0).sum() == 1
    assert (di[0] == 7).sum() == 1
    drop = dv[0] != 3.0
    assert np.isinf(dv[0][drop]).all()
    assert (di[0][drop] == -1).all()


def test_dedup_fold_never_resurrects_duplicate():
    """dedup → ranked select_k(in_idx) with selection slack must not
    return a duplicate id at its worst distance (the pre-fix bug)."""
    from raft_tpu.matrix import select_k

    vals = jnp.asarray([[5.0, 3.0, 4.0, 6.0]])
    ids = jnp.asarray([[7, 7, 9, 11]], jnp.int32)
    dv, di = cagra._dedup_by_id(vals, ids)
    out_v, out_i = select_k(dv, 3, in_idx=di, select_min=True)
    out_v, out_i = np.asarray(out_v), np.asarray(out_i)
    np.testing.assert_array_equal(out_i[0], [7, 9, 11])
    np.testing.assert_array_equal(out_v[0], [3.0, 4.0, 6.0])
    # id 7 appears exactly once — never again at distance 5.0
    assert (out_i[0] == 7).sum() == 1


# ---------------------------------------------------------------------------
# engine parity: frontier == per-parent, bit for bit


@pytest.mark.parametrize("width", [1, 2, ITOPK])
def test_engine_parity_widths(index, data, width):
    _, q = data
    dv_f, di_f, dv_p, di_p = _both(index, q, width)
    np.testing.assert_array_equal(di_f, di_p)
    np.testing.assert_array_equal(dv_f, dv_p)


@pytest.mark.parametrize("metric", ["inner_product", "euclidean"])
def test_engine_parity_metrics(data, metric):
    x, q = data
    idx = cagra.build(x, cagra.CagraIndexParams(
        intermediate_graph_degree=32, graph_degree=16, metric=metric))
    dv_f, di_f, dv_p, di_p = _both(idx, q, 4)
    np.testing.assert_array_equal(di_f, di_p)
    np.testing.assert_array_equal(dv_f, dv_p)


def test_engine_parity_capped_iterations(index, data):
    _, q = data
    dv_f, di_f, dv_p, di_p = _both(index, q, 2, max_iterations=3)
    np.testing.assert_array_equal(di_f, di_p)
    np.testing.assert_array_equal(dv_f, dv_p)


@pytest.mark.parametrize("kind", ["bitset", "bitmap"])
def test_engine_parity_filtered(index, data, kind):
    x, q = data
    rng = np.random.default_rng(3)
    if kind == "bitset":
        keep = rng.random(x.shape[0]) < 0.7
        filt = Bitset.from_bool_array(keep)
    else:
        keep = rng.random((q.shape[0], x.shape[0])) < 0.7
        filt = Bitmap(Bitset.from_bool_array(keep.reshape(-1)).words,
                      *keep.shape)
    dv_f, di_f = cagra.search(index, q, K, _params("frontier", 4),
                              filter=filt)
    dv_p, di_p = cagra.search(index, q, K, _params("per_parent", 4),
                              filter=filt)
    np.testing.assert_array_equal(np.asarray(di_f), np.asarray(di_p))
    np.testing.assert_array_equal(np.asarray(dv_f), np.asarray(dv_p))
    # filtered-out rows never appear (result-stage filter semantics)
    ids = np.asarray(di_f)
    if kind == "bitset":
        valid = ids[ids >= 0]
        assert keep[valid].all()
    else:
        for r in range(ids.shape[0]):
            valid = ids[r][ids[r] >= 0]
            assert keep[r, valid].all()


def test_engine_parity_sharded(data, mesh8):
    x, q = data
    index = cagra.build_sharded(x, mesh8, cagra.CagraIndexParams(
        intermediate_graph_degree=32, graph_degree=16))
    dv_f, di_f = cagra.search_sharded(index, q, K, _params("frontier", 4),
                                      mesh=mesh8)
    dv_p, di_p = cagra.search_sharded(index, q, K, _params("per_parent", 4),
                                      mesh=mesh8)
    np.testing.assert_array_equal(np.asarray(di_f), np.asarray(di_p))
    np.testing.assert_array_equal(np.asarray(dv_f), np.asarray(dv_p))


def test_beam_ids_unique(index, data):
    """The sorted-ring visited filter's whole job: the result can never
    contain one node twice."""
    _, q = data
    for width in (1, 4):
        _, ids = cagra.search(index, q, K, _params("frontier", width))
        for row in np.asarray(ids):
            valid = row[row >= 0]
            assert len(set(valid.tolist())) == len(valid)


# ---------------------------------------------------------------------------
# steady state: shared executables + serving searcher


def test_max_iterations_shares_executable(index, data):
    """``max_iterations`` ≤ the auto count is a DEVICE-scalar cap change,
    not a new program: after warming the auto config, a capped search
    must neither retrace nor transfer."""
    _, q = data
    qd = jax.device_put(q)
    p_auto = _params("frontier", 4)
    d0, i0 = cagra.search(index, qd, K, p_auto)  # warm (auto iters)
    jax.block_until_ready((d0, i0))
    p_cap = dataclasses.replace(p_auto, max_iterations=2)
    d1, i1 = cagra.search(index, qd, K, p_cap)   # warm the cap operand memo
    jax.block_until_ready((d1, i1))
    with TraceGuard() as tg:
        d2, i2 = cagra.search(index, qd, K, p_cap)
        d3, i3 = cagra.search(index, qd, K, p_auto)
        jax.block_until_ready((d2, i2, d3, i3))
    tg.assert_steady_state()
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i3))


def test_searcher_bit_identical_and_mixed_shape_steady(index, data):
    """Serving contract: ``searcher()``'s fn matches direct ``search()``
    bit-for-bit, and mixed query shapes run steady-state after warmup."""
    _, q = data
    fn, operands = cagra.searcher(index, K, _params("frontier", 4))
    shapes = [jax.device_put(q[:4]), jax.device_put(q[:32])]
    for qd in shapes:  # warm every shape bucket
        jax.block_until_ready(fn(qd, *operands))
    with TraceGuard() as tg:
        for _ in range(3):
            for qd in shapes:
                d, i = fn(qd, *operands)
        jax.block_until_ready((d, i))
    tg.assert_steady_state()
    dv, di = cagra.search(index, np.asarray(q[:32]), K,
                          _params("frontier", 4))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(di))
    np.testing.assert_array_equal(np.asarray(d), np.asarray(dv))


def test_resolved_search_params_concretizes_auto(index):
    p = cagra.resolved_search_params(
        index, K, cagra.CagraSearchParams(itopk_size=0, search_width=0))
    assert p.itopk_size >= K and p.search_width >= 1
    assert p.search_width <= p.itopk_size
    # explicit values pass through untouched
    p2 = cagra.resolved_search_params(index, K, _params("frontier", 4))
    assert (p2.itopk_size, p2.search_width) == (ITOPK, 4)
