"""Search-quality telemetry tests (ISSUE 11).

All tier-1 (CPU, fast).  The quality contract under test:

* the shadow-sampling oracle is EXACT: its top-k agrees with
  ``brute_force.knn`` over the same stored vectors, for every family's
  corpus extraction (including tombstone exclusion);
* sampling is deterministic (seeded hash over the request sequence) and
  the work queue is bounded — overflow drops and counts, never blocks;
* Wilson intervals are honest at small n / extreme p;
* index-health gauges expose occupancy imbalance / dead fraction /
  graph-degree stats per generation, pruned to the newest K;
* the PSI drift detector separates same-distribution from shifted;
* ACCEPTANCE — the injected-regression drill runs deterministically:
  recall drop at the degraded level → estimator CI below the floor →
  recall SLO burn-rate alert → degradation guard refuses the level,
  each step visible in the Prometheus exposition (parse_text
  round-trip);
* the stall-dump quarantine obeys the newest-K retention policy.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from raft_tpu.neighbors import brute_force, ivf_flat, mutation
from raft_tpu.neighbors.health import export_index_health, index_health
from raft_tpu.obs import (DriftDetector, MetricRegistry, QualityConfig,
                          RecallEstimator, SloEvaluator, SloPolicy,
                          SpanRecorder, parse_text, wilson_interval)
from raft_tpu.obs.quality import oracle_database
from raft_tpu.serve import SearchServer, ServerConfig, ServingMetrics

N, D, K = 900, 24, 8


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(11).standard_normal((N, D)).astype(
        np.float32)


@pytest.fixture(scope="module")
def ivf(db):
    return ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(
        n_lists=32, kmeans_n_iters=4, seed=3))


# ---------------------------------------------------------------------------
# wilson intervals


def test_wilson_interval_honest_at_extremes():
    lo, hi = wilson_interval(95, 100)
    assert lo < 0.95 < hi
    # perfect observed recall still admits doubt at small n ...
    lo1, hi1 = wilson_interval(10, 10)
    assert hi1 == 1.0 and lo1 < 1.0
    # ... and the doubt shrinks with evidence
    lo2, _ = wilson_interval(1000, 1000)
    assert lo2 > lo1
    assert wilson_interval(0, 0) == (0.0, 1.0)
    lo3, hi3 = wilson_interval(0, 20)
    assert lo3 == 0.0 and 0.0 < hi3 < 0.5


# ---------------------------------------------------------------------------
# the exact oracle


def test_oracle_matches_brute_force_knn(db):
    est = RecallEstimator(db, K, QualityConfig(rows_cap=16),
                          registry=MetricRegistry())
    q = db[100:116] + 0.01
    oids = est.oracle_ids(q)
    _, ref = brute_force.knn(q, db, K)
    assert np.array_equal(np.sort(oids, axis=1),
                          np.sort(np.asarray(jax.device_get(ref)), axis=1))


def test_oracle_corpus_per_family(db, ivf):
    vecs, ids = oracle_database(db)
    assert vecs.shape == (N, D) and np.array_equal(ids, np.arange(N))
    vecs, ids = oracle_database(ivf)
    assert vecs.shape[0] == N and sorted(ids) == list(range(N))
    # ivf oracle ranks like the brute oracle over the same stored vectors
    est = RecallEstimator(ivf, K, QualityConfig(rows_cap=4),
                          registry=MetricRegistry())
    q = db[:4]
    _, ref = brute_force.knn(q, db, K)
    assert np.array_equal(np.sort(est.oracle_ids(q), axis=1),
                          np.sort(np.asarray(jax.device_get(ref)), axis=1))


def test_oracle_excludes_tombstoned_ids(db, ivf):
    q = db[:2]
    _, ref = brute_force.knn(q, db, K)
    doomed = np.unique(np.asarray(jax.device_get(ref)).reshape(-1))[:5]
    t = mutation.delete(ivf, doomed)
    est = RecallEstimator(t, K, QualityConfig(rows_cap=2),
                          registry=MetricRegistry())
    oids = est.oracle_ids(q)
    assert not (set(oids.reshape(-1).tolist()) & set(doomed.tolist()))


# ---------------------------------------------------------------------------
# sampling determinism + bounded queue


def test_sampling_is_deterministic_and_seeded(db):
    def selections(seed, fraction, n=4000):
        est = RecallEstimator(db, K, QualityConfig(
            sample_fraction=fraction, seed=seed),
            registry=MetricRegistry())
        return [est._selected(i) for i in range(n)]

    a = selections(seed=1, fraction=0.05)
    assert a == selections(seed=1, fraction=0.05)      # replayable
    assert a != selections(seed=2, fraction=0.05)      # seed matters
    assert sum(a) / len(a) == pytest.approx(0.05, abs=0.02)
    assert all(selections(seed=1, fraction=1.0))


def test_bounded_queue_drops_and_counts(db):
    metrics = ServingMetrics()
    est = RecallEstimator(db, K, QualityConfig(
        sample_fraction=1.0, queue_max=2, rows_cap=2),
        registry=metrics.registry, metrics=metrics)
    ids = np.zeros((2, K), dtype=np.int32)
    enqueued = sum(est.maybe_sample(db[:2], ids, level=0) for _ in range(5))
    assert enqueued == 2                   # queue bound respected
    assert metrics.quality_samples == 2
    assert metrics.quality_sample_drops == 3
    assert est.drain() == 2                # drops never reach the oracle


def test_estimator_thread_lifecycle(db):
    est = RecallEstimator(db, K, QualityConfig(
        sample_fraction=1.0, rows_cap=2), registry=MetricRegistry())
    import time

    _, i = brute_force.knn(db[:2], db, K)
    with est:
        est.maybe_sample(db[:2], np.asarray(jax.device_get(i)), level=0)
        for _ in range(500):
            if est.estimate(0).samples:
                break
            time.sleep(0.01)
    assert est.estimate(0).samples == 1
    assert est.estimate(0).mean == 1.0     # self-queries, exact serving


# ---------------------------------------------------------------------------
# index health


def test_index_health_per_family(db, ivf):
    h = index_health(db)
    assert h["family"] == "brute_force" and h["rows"] == N
    assert h["dead_fraction"] == 0.0

    h = index_health(ivf)
    assert h["family"] == "ivf_flat" and h["rows"] == N
    assert h["lists"] == 32 and h["occupancy_cv"] >= 0.0
    assert 1.0 / 32 <= h["occupancy_max_fraction"] <= 1.0
    assert 0.0 < h["occupancy_max"] <= 1.0

    t = mutation.delete(ivf, np.arange(90))
    h = index_health(t)
    assert h["dead"] == 90
    assert h["dead_fraction"] == pytest.approx(0.1)

    from raft_tpu.neighbors import cagra

    g = cagra.build(db[:256], cagra.CagraIndexParams(
        intermediate_graph_degree=16, graph_degree=8))
    h = index_health(g)
    assert h["family"] == "cagra" and h["graph_degree"] == 8
    assert h["rows"] == 256 and h["in_degree_cv"] >= 0.0
    assert 0.0 <= h["orphan_fraction"] < 1.0
    assert 0.0 <= h["self_loop_fraction"] <= 1.0


def test_export_index_health_prunes_old_generations(ivf):
    reg = MetricRegistry()
    for gen in range(6):
        export_index_health(reg, ivf, generation=gen, keep_generations=3)
    gens = {labels["generation"]
            for labels, _ in reg.get("raft_index_health").samples()}
    assert gens == {"3", "4", "5"}


def test_compaction_stats_ride_shared_health(db, ivf):
    srv = SearchServer(mutation.delete(ivf, np.arange(90)), k=K,
                       clock=FakeClock(), recorder=SpanRecorder(32))
    from raft_tpu.serve import CompactionScheduler

    s = CompactionScheduler(srv).stats()
    assert s["rows"] == N and s["dead"] == 90
    assert s["dead_fraction"] == pytest.approx(0.1)
    assert 0.0 < s["occupancy"] <= 1.0


# ---------------------------------------------------------------------------
# drift


def test_drift_detector_stable_vs_shifted(db):
    reg = MetricRegistry()
    dd = DriftDetector.from_index(db, db[:400], registry=reg)
    dd.observe_queries(db[400:800])        # same distribution
    assert dd.psi() < 0.1 and dd.status() == "stable"
    assert reg.get("raft_quality_drift_psi").value() == pytest.approx(
        dd.psi())
    dd.observe_queries(db[400:800] + 8.0)  # gross covariate shift
    assert dd.psi() >= 0.25 and dd.status() == "shifted"


def test_drift_baseline_validation():
    from raft_tpu.core.errors import RaftError

    with pytest.raises(RaftError):
        DriftDetector([1.0], registry=MetricRegistry())
    dd = DriftDetector(np.ones(64), registry=MetricRegistry())
    assert dd.psi() == 0.0                 # empty window: no verdict
    with pytest.raises(RaftError):
        dd.observe_queries(np.ones((2, 4)))  # no reference points


# ---------------------------------------------------------------------------
# SLO burn rates


def _slo_fixture(**kw):
    metrics = ServingMetrics()
    policy = SloPolicy(latency_ms=8.0, short_window=8, long_window=32, **kw)
    return metrics, SloEvaluator(metrics, policy=policy,
                                 recorder=SpanRecorder(32))


def test_latency_burn_rate_pages_and_recovers():
    metrics, slo = _slo_fixture()
    for _ in range(64):
        metrics.observe_latency(1.0)
    assert slo.evaluate()["latency"]["state"] == "ok"
    for _ in range(64):                    # sustained target misses
        metrics.observe_latency(50.0)
    out = slo.evaluate()["latency"]
    assert out["burn_short"] >= 8.0 and out["state"] == "page"
    assert metrics.registry.get("raft_slo_alerts_total").value(
        slo="latency", severity="page") == 1.0
    for _ in range(64):                    # recovery resets via short window
        metrics.observe_latency(1.0)
    assert slo.evaluate()["latency"]["state"] == "ok"


def test_availability_burn_counts_rejections():
    metrics, slo = _slo_fixture()
    for _ in range(40):
        metrics.count("completed")
    assert slo.evaluate()["availability"]["state"] == "ok"
    for _ in range(40):
        metrics.count("rejected_deadline")
    assert slo.evaluate()["availability"]["state"] == "page"


def test_quality_guard_passes_unknown_levels(db):
    metrics = ServingMetrics()
    est = RecallEstimator(db, K, QualityConfig(sample_fraction=1.0),
                          registry=metrics.registry)
    slo = SloEvaluator(metrics, est, SloPolicy(min_samples=4),
                       recorder=SpanRecorder(32))
    # no evidence anywhere: the cold ladder must still work
    assert slo.quality_guard(2) == 2
    assert slo.quality_guard(0) == 0


# ---------------------------------------------------------------------------
# ACCEPTANCE: the injected-regression drill


@pytest.fixture(scope="module")
def drill_db():
    return np.random.default_rng(7).standard_normal((4000, 32)).astype(
        np.float32)


@pytest.fixture(scope="module")
def drill_index(drill_db):
    return ivf_flat.build(drill_db, ivf_flat.IvfFlatIndexParams(
        n_lists=64, kmeans_n_iters=4))


def _drill_server(index, clock):
    # level 0 probes every list (exact search, recall 1); level 1's
    # effort scale floors n_probes to 1 — a gross, *measurable* recall
    # regression that only load (queue depth >= 4) can trigger
    cfg = ServerConfig(ladder=(8,), max_queue=16, max_wait_ms=0.0,
                       degrade_queue_fractions=(0.25,),
                       degrade_effort_scales=(1.0, 0.02))
    return SearchServer(index, k=K,
                        params=ivf_flat.IvfFlatSearchParams(n_probes=64),
                        config=cfg, clock=clock, recorder=SpanRecorder(512))


def test_quality_regression_drill(drill_index, drill_db):
    """Recall drop -> estimator CI below floor -> SLO burn-rate alert ->
    guard refuses the level, all deterministic, each step scrapeable."""
    db = drill_db
    srv = _drill_server(drill_index, FakeClock())
    est = srv.attach_quality(
        QualityConfig(sample_fraction=1.0, rows_cap=8),
        policy=SloPolicy(recall_floor=0.9, min_samples=4,
                         short_window=4, long_window=8),
        baseline_queries=db[:256])

    def drive(n_parallel: int):
        futs = [srv.submit(db[(j * 8) % 256:(j * 8) % 256 + 8])
                for j in range(n_parallel)]
        while srv.step():
            pass
        for f in futs:
            f.result(timeout=5)
        est.drain()
        srv.slo.evaluate()

    # phase 1 — healthy traffic, level 0 only: recall ~1, SLO ok
    for _ in range(6):
        drive(1)
    healthy = est.estimate(0)
    assert healthy.samples >= 6 and healthy.ci_low > 0.9
    assert srv.slo.states["recall"] == "ok"
    assert est.levels() == [0]

    # phase 2 — the injected regression: saturate the queue so the
    # ladder enters level 1, whose effort scale guts n_probes
    drive(8)
    bad = est.estimate(1)
    assert bad.samples >= 4
    assert bad.ci_high < 0.9               # estimator detected the drop

    # phase 3 — the SLO enters burn-rate alerting on the recall floor,
    # and the alert is on the scrape surface while it burns
    assert srv.slo.states["recall"] in ("warn", "page")
    burning = parse_text(srv.prometheus_text())
    assert any(labels == {"slo": "recall", "window": "short"} and v >= 2.0
               for labels, v in burning["raft_slo_burn_rate"])
    assert any(labels["slo"] == "recall" and v >= 1.0
               for labels, v in burning["raft_slo_state"])

    # phase 4 — the guard refuses level 1 on the next pressure burst:
    # batches dispatch at level 0 despite the saturated queue, and the
    # recall SLO recovers because of it
    before = dict(srv.metrics.degrade_dispatches)
    drive(8)
    after = srv.metrics.degrade_dispatches
    assert after.get(1, 0) == before.get(1, 0)   # no new level-1 batches
    assert after.get(0, 0) > before.get(0, 0)    # served at full effort
    assert srv.metrics.quality_guard_overrides > 0
    assert srv.slo.states["recall"] == "ok"      # the loop closed

    # every step left scrapeable evidence: prometheus round-trip over
    # the new quality / drift / SLO / health families
    parsed = parse_text(srv.prometheus_text())
    assert any(labels.get("level") == "1"
               for labels, _ in parsed["raft_quality_recall_bucket"])
    assert parsed["raft_quality_recall_ci_high"]
    assert parsed["raft_quality_drift_psi"][0][1] < 0.25   # no query drift
    assert any(labels["slo"] == "recall" and v >= 1.0
               for labels, v in parsed["raft_slo_alerts_total"])
    assert parsed["raft_serve_quality_guard_overrides_total"][0][1] > 0
    assert any(labels.get("stat") == "occupancy_cv"
               for labels, _ in parsed["raft_index_health"])
    # and the JSON snapshot carries the same story
    snap = srv.metrics_snapshot()
    assert snap["quality"]["levels"]["1"]["ci_high"] < 0.9
    assert snap["slo"]["overrides"] == srv.metrics.quality_guard_overrides


def test_drill_is_deterministic(drill_index, drill_db):
    """Two fresh runs of the drill's sampling produce identical sample
    selections and identical per-level windows — the replayable-evidence
    property the drill rests on."""
    def run():
        srv = _drill_server(drill_index, FakeClock())
        est = srv.attach_quality(QualityConfig(sample_fraction=0.5,
                                               rows_cap=8))
        for j in range(8):
            fut = srv.submit(drill_db[j * 8:(j + 1) * 8])
            while srv.step():
                pass
            fut.result(timeout=5)
        est.drain()
        return est.stats()

    assert run() == run()


# ---------------------------------------------------------------------------
# watchdog quarantine retention


def test_watchdog_retention_prunes_oldest(db, tmp_path):
    clock = FakeClock()
    srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4,)),
                       clock=clock, recorder=SpanRecorder(32))
    wd = srv.attach_watchdog(tmp_path, stall_timeout_s=5.0, capture_s=0.0,
                             max_dumps=3)
    import os

    for _ in range(5):
        srv._inflight = ("execute", clock())
        clock.advance(10.0)
        assert wd.check() is not None
        srv._inflight = None
        assert wd.check() is None          # re-arm between episodes
    kept = sorted(os.listdir(tmp_path))
    assert kept == ["stall-003-execute", "stall-004-execute",
                    "stall-005-execute"]
    assert wd.pruned_total == 2
    assert srv.metrics.stall_dumps_pruned == 2
    assert wd.dumps == [os.path.join(str(tmp_path), k) for k in kept]
