"""Tests for LAP (vs scipy linear_sum_assignment), spectral analysis (vs
naive formulas), and label utils — reference suites ``cpp/tests/lap/lap.cu``,
``cpp/tests/sparse/spectral_matrix.cu``, ``cpp/tests/label/``."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.label import get_ovr_labels, get_unique_labels, make_monotonic, merge_labels
from raft_tpu.solver import LinearAssignmentProblem, lap_solve
from raft_tpu.sparse import CSR
from raft_tpu.spectral import analyze_modularity, analyze_partition, spectral_partition

try:
    from scipy.optimize import linear_sum_assignment

    HAVE_SCIPY = True
except ImportError:
    HAVE_SCIPY = False


# -- LAP ---------------------------------------------------------------------

@pytest.mark.skipif(not HAVE_SCIPY, reason="scipy unavailable")
@pytest.mark.parametrize("n", [4, 16, 32])
def test_lap_matches_scipy(rng, n):
    cost = rng.random((n, n)).astype(np.float32)
    row, col = lap_solve(cost, epsilon=1e-5)
    ri, ci = linear_sum_assignment(cost)
    want = cost[ri, ci].sum()
    got = cost[np.arange(n), np.asarray(row)].sum()
    np.testing.assert_allclose(got, want, rtol=1e-4)
    # valid permutation
    assert sorted(np.asarray(row).tolist()) == list(range(n))
    # col assignment is the inverse permutation
    np.testing.assert_array_equal(np.asarray(col)[np.asarray(row)], np.arange(n))


@pytest.mark.skipif(not HAVE_SCIPY, reason="scipy unavailable")
def test_lap_batched(rng):
    n, b = 12, 5
    cost = rng.random((b, n, n)).astype(np.float32)
    lap = LinearAssignmentProblem(n, b, epsilon=1e-5)
    row, col = lap.solve(cost)
    prim = np.asarray(lap.get_primal_objective())
    for i in range(b):
        ri, ci = linear_sum_assignment(cost[i])
        np.testing.assert_allclose(prim[i], cost[i][ri, ci].sum(), rtol=1e-4)


def test_lap_integer_costs():
    cost = np.asarray([[4, 1, 3], [2, 0, 5], [3, 2, 2]], np.float32)
    row, _ = lap_solve(cost)
    got = cost[np.arange(3), np.asarray(row)].sum()
    assert got == 5.0  # known optimum


# -- spectral analysis -------------------------------------------------------

def _two_cliques(n1=5, n2=5, bridges=1):
    n = n1 + n2
    a = np.zeros((n, n), np.float32)
    a[:n1, :n1] = 1
    a[n1:, n1:] = 1
    np.fill_diagonal(a, 0)
    for i in range(bridges):
        a[i, n1 + i] = a[n1 + i, i] = 1
    return a


def test_analyze_partition_two_cliques():
    a = _two_cliques()
    csr = CSR.from_dense(a)
    labels = np.r_[np.zeros(5, np.int32), np.ones(5, np.int32)]
    edge_cut, cost = analyze_partition(csr, 2, jnp.asarray(labels))
    assert float(edge_cut) == 1.0  # single bridge
    np.testing.assert_allclose(float(cost), 1 / 5 + 1 / 5, rtol=1e-5)


def test_analyze_modularity_matches_naive(rng):
    a = _two_cliques(6, 6, 2)
    csr = CSR.from_dense(a)
    labels = np.r_[np.zeros(6, np.int32), np.ones(6, np.int32)]
    got = float(analyze_modularity(csr, 2, jnp.asarray(labels)))
    # naive Newman modularity
    deg = a.sum(1)
    two_m = deg.sum()
    q = 0.0
    for c in (0, 1):
        idx = labels == c
        q += a[np.ix_(idx, idx)].sum() - deg[idx].sum() ** 2 / two_m
    q /= two_m
    np.testing.assert_allclose(got, q, rtol=1e-5)
    # good partition → positive modularity; random labels → lower
    bad = float(analyze_modularity(csr, 2, jnp.asarray(labels[::-1].copy() * 0)))
    assert got > bad


def test_spectral_partition_recovers_cliques():
    a = _two_cliques(8, 8, 1)
    labels, vals, _ = spectral_partition(CSR.from_dense(a), 2, seed=0)
    labels = np.asarray(labels)
    # the two cliques must land in different clusters
    assert len(set(labels[:8].tolist())) == 1
    assert len(set(labels[8:].tolist())) == 1
    assert labels[0] != labels[8]
    assert abs(float(vals[0])) < 1e-2  # lambda_0(L) = 0


# -- label utils -------------------------------------------------------------

def test_unique_and_ovr():
    y = jnp.asarray([3.0, 1.0, 3.0, 9.0, 1.0])
    u = get_unique_labels(y)
    np.testing.assert_array_equal(np.asarray(u), [1.0, 3.0, 9.0])
    ovr = get_ovr_labels(y, u, 1)
    np.testing.assert_array_equal(np.asarray(ovr), [1, -1, 1, -1, -1])


def test_make_monotonic():
    y = jnp.asarray([10, 20, 10, 40], jnp.int32)
    out = make_monotonic(y)
    np.testing.assert_array_equal(np.asarray(out), [0, 1, 0, 2])
    out1 = make_monotonic(y, zero_based=False)
    np.testing.assert_array_equal(np.asarray(out1), [1, 2, 1, 3])


def test_make_monotonic_filtered():
    y = jnp.asarray([7, 5, 7, -1, 5], jnp.int32)
    out = make_monotonic(y, filter_op=lambda v: v >= 0)
    np.testing.assert_array_equal(np.asarray(out), [1, 0, 1, -1, 0])


def test_merge_labels_components():
    # A: {0,1} -> 1, {2,3} -> 3 ; B: {1,2} -> 2 (core) links the groups
    a = jnp.asarray([1, 1, 3, 3], jnp.int32)
    b = jnp.asarray([9, 2, 2, 8], jnp.int32)
    mask = jnp.asarray([False, True, True, False])
    out = np.asarray(merge_labels(a, b, mask))
    assert out[0] == out[1] == out[2] == out[3]  # all merged through B's core
