"""Probe-blocked IVF search: bit-exact parity with the per-probe scan.

The blocked engine (``probe_block`` search param) gathers B probe lists
per scan step and merges once per block instead of once per probe.  The
per-candidate arithmetic is identical for every block size — same
elementwise op order, same masks — so results must match the per-probe
scan **bit-for-bit** (values AND ids), at every block size, including
block sizes that don't divide ``n_probes`` (pad probes are masked, never
duplicated).  These tests pin that contract for both families, both
IVF-PQ tiers, packed 4-bit codes, filtered search, and the sharded path,
plus steady-state behavior when block sizes are mixed at runtime.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import TraceGuard
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors._packing import blocked_probe_plan, resolve_probe_block
from raft_tpu.random.datagen import make_blobs

K = 10
N_PROBES = 25  # deliberately not a multiple of the tested block sizes
BLOCKS = (1, 4, N_PROBES)
METRICS = ("sqeuclidean", "inner_product")


@pytest.fixture(scope="module")
def data():
    x, _ = make_blobs(jax.random.PRNGKey(3), n_samples=4000, n_features=32,
                      n_clusters=40, cluster_std=1.2)
    return np.asarray(x), np.asarray(x[:64]) + 0.05


@pytest.fixture(scope="module")
def flat_indexes(data):
    x, _ = data
    # 60 lists → a list cap that is NOT lane-aligned: einsum retiling
    # masked by power-of-two caps shows up here (the pq fixture's cap is
    # odd already via its 1.5 cap ratio)
    return {m: ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(
        n_lists=60, metric=m, seed=7)) for m in METRICS}


@pytest.fixture(scope="module")
def pq_indexes(data):
    x, _ = data
    return {m: ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=64, pq_dim=8, metric=m, seed=7)) for m in METRICS}


@pytest.fixture(scope="module")
def packed_pq_index(data):
    x, _ = data
    idx = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=64, pq_dim=8, pq_bits=4, pack_codes=True, seed=7))
    assert idx.packed
    return idx


def _run_flat(index, q, pb, filt=None):
    p = ivf_flat.IvfFlatSearchParams(n_probes=N_PROBES, probe_block=pb)
    d, i = ivf_flat.search(index, q, K, p, filter=filt)
    return np.asarray(d), np.asarray(i)


def _run_pq(index, q, mode, pb, filt=None):
    p = ivf_pq.IvfPqSearchParams(n_probes=N_PROBES, mode=mode,
                                 probe_block=pb)
    d, i = ivf_pq.search(index, q, K, p, filter=filt)
    return np.asarray(d), np.asarray(i)


def _assert_identical(ref, got, ctx):
    np.testing.assert_array_equal(ref[0], got[0], err_msg=f"values {ctx}")
    np.testing.assert_array_equal(ref[1], got[1], err_msg=f"ids {ctx}")


# ---------------------------------------------------------------------------
# bit-exact parity across block sizes


@pytest.mark.parametrize("metric", METRICS)
def test_ivf_flat_blocked_parity(flat_indexes, data, metric):
    _, q = data
    index = flat_indexes[metric]
    ref = _run_flat(index, q, 1)
    for pb in BLOCKS[1:]:
        _assert_identical(ref, _run_flat(index, q, pb),
                          f"flat {metric} pb={pb}")


@pytest.mark.parametrize("mode", ["recon", "lut"])
@pytest.mark.parametrize("metric", METRICS)
def test_ivf_pq_blocked_parity(pq_indexes, data, metric, mode):
    _, q = data
    index = pq_indexes[metric]
    ref = _run_pq(index, q, mode, 1)
    for pb in BLOCKS[1:]:
        _assert_identical(ref, _run_pq(index, q, mode, pb),
                          f"pq {mode} {metric} pb={pb}")


def test_ivf_pq_packed_blocked_parity(packed_pq_index, data):
    """4-bit packed codes: the in-scan unpack composes with blocking."""
    _, q = data
    ref = _run_pq(packed_pq_index, q, "lut", 1)
    for pb in BLOCKS[1:]:
        _assert_identical(ref, _run_pq(packed_pq_index, q, "lut", pb),
                          f"packed lut pb={pb}")


def test_filtered_blocked_parity(flat_indexes, pq_indexes, data):
    """Blocked gathers flatten probe-block vids before the bitmap lookup —
    filtered results must stay bit-identical at every block size, for
    both the shared-bitset and the per-query-bitmap filter forms."""
    x, q = data
    n = x.shape[0]
    rng = np.random.default_rng(11)
    bitset = rng.random(n) < 0.6                      # shared over queries
    bitmap = rng.random((q.shape[0], n)) < 0.6        # per-query
    fi, pi = flat_indexes["sqeuclidean"], pq_indexes["sqeuclidean"]
    for filt in (bitset, bitmap):
        ref_f = _run_flat(fi, q, 1, filt)
        ref_p = _run_pq(pi, q, "lut", 1, filt)
        for pb in BLOCKS[1:]:
            _assert_identical(ref_f, _run_flat(fi, q, pb, filt),
                              f"flat filtered pb={pb} ndim={np.ndim(filt)}")
            _assert_identical(ref_p, _run_pq(pi, q, "lut", pb, filt),
                              f"pq filtered pb={pb} ndim={np.ndim(filt)}")


def test_sharded_blocked_parity(data, mesh8):
    x, q = data
    sf = ivf_flat.build_sharded(x, mesh8,
                                ivf_flat.IvfFlatIndexParams(n_lists=64, seed=7))
    ref = None
    for pb in BLOCKS:
        d, i = ivf_flat.search_sharded(
            sf, q, K, ivf_flat.IvfFlatSearchParams(n_probes=N_PROBES,
                                                   probe_block=pb),
            mesh=mesh8)
        got = (np.asarray(d), np.asarray(i))
        if ref is None:
            ref = got
        else:
            _assert_identical(ref, got, f"sharded flat pb={pb}")

    sp = ivf_pq.build_sharded(x, mesh8,
                              ivf_pq.IvfPqIndexParams(n_lists=64, pq_dim=8,
                                                      seed=7))
    for mode in ("recon", "lut"):
        ref = None
        for pb in BLOCKS:
            d, i = ivf_pq.search_sharded(
                sp, q, K, ivf_pq.IvfPqSearchParams(n_probes=N_PROBES,
                                                   mode=mode, probe_block=pb),
                mesh=mesh8)
            got = (np.asarray(d), np.asarray(i))
            if ref is None:
                ref = got
            else:
                _assert_identical(ref, got, f"sharded pq {mode} pb={pb}")


# ---------------------------------------------------------------------------
# hoisted ADC tables


def test_adc_tables_match_fresh_rebuild(pq_indexes):
    """Build-time tables == tables rebuilt from persisted state alone."""
    index = pq_indexes["sqeuclidean"]
    rebuilt = dataclasses.replace(index, centroid_lut=None,
                                  adc_norms=None).with_adc_luts()
    np.testing.assert_array_equal(np.asarray(index.centroid_lut),
                                  np.asarray(rebuilt.centroid_lut))
    np.testing.assert_array_equal(np.asarray(index.adc_norms),
                                  np.asarray(rebuilt.adc_norms))


def test_legacy_index_without_tables_still_searches(pq_indexes, data):
    """An index lacking the precomputed tables (old artifact shape) must
    produce identical LUT results — search derives the tables on the fly."""
    _, q = data
    index = pq_indexes["sqeuclidean"]
    legacy = dataclasses.replace(index, centroid_lut=None, adc_norms=None)
    ref = _run_pq(index, q, "lut", 4)
    got = _run_pq(legacy, q, "lut", 4)
    _assert_identical(ref, got, "legacy vs precomputed tables")


# ---------------------------------------------------------------------------
# probe-block planning units


def test_blocked_probe_plan_shapes_and_masks():
    probes = jnp.arange(12).reshape(2, 6)  # nq=2, n_probes=6
    xs, pvalid = blocked_probe_plan(probes, 4)
    assert xs.shape == (2, 2, 4)          # [n_blocks, nq, B]
    assert pvalid.shape == (2, 4)
    # pad probes are masked invalid, real probes valid, order preserved
    np.testing.assert_array_equal(np.asarray(pvalid),
                                  [[True] * 4, [True, True, False, False]])
    flat = np.moveaxis(np.asarray(xs), 0, 1).reshape(2, -1)[:, :6]
    np.testing.assert_array_equal(flat, np.arange(12).reshape(2, 6))


def test_blocked_probe_plan_exact_division():
    probes = jnp.arange(8).reshape(2, 4)
    xs, pvalid = blocked_probe_plan(probes, 2)
    assert xs.shape == (2, 2, 2) and bool(pvalid.all())


def test_resolve_probe_block_clamps():
    # explicit request clamps into [1, n_probes]
    assert resolve_probe_block(4, 32, 512, "ivf_flat") == 4
    assert resolve_probe_block(64, 32, 512, "ivf_flat") == 32
    assert resolve_probe_block(-3, 32, 512, "ivf_flat") == 1
    # auto (0): always a valid block size
    for n_probes in (1, 2, 7, 32, 257):
        for cap in (1, 64, 4096, 100_000):
            got = resolve_probe_block(0, n_probes, cap, "ivf_pq")
            assert 1 <= got <= n_probes, (n_probes, cap, got)


# ---------------------------------------------------------------------------
# steady state across mixed block sizes


def test_mixed_probe_block_steady_state(flat_indexes, pq_indexes, data):
    """Each distinct probe_block is its own specialization; once each is
    warm, alternating between them must not re-trace or transfer."""
    _, q = data
    qd = jax.device_put(jnp.asarray(q))
    fi, pi = flat_indexes["sqeuclidean"], pq_indexes["sqeuclidean"]

    def run(pb):
        d, i = ivf_flat.search(
            fi, qd, K,
            ivf_flat.IvfFlatSearchParams(n_probes=N_PROBES, probe_block=pb))
        d2, i2 = ivf_pq.search(
            pi, qd, K,
            ivf_pq.IvfPqSearchParams(n_probes=N_PROBES, mode="lut",
                                     probe_block=pb))
        jax.block_until_ready((d, i, d2, i2))

    for pb in (1, 4):  # warm both specializations
        run(pb)
    with TraceGuard() as tg, jax.transfer_guard("disallow"):
        for _ in range(4):
            run(1)
            run(4)
    tg.assert_steady_state()
