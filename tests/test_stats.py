"""stats tests — parity with ``cpp/tests/stats/`` (23 suites): validated
against numpy formulations and known closed-form cases."""

import numpy as np
import pytest

from raft_tpu import stats
from raft_tpu.stats import IC_Type


def assert_close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


class TestSummary:
    def test_mean_stddev_sum(self, rng):
        x = rng.standard_normal((50, 6)).astype(np.float32)
        assert_close(stats.mean(x), x.mean(axis=0), rtol=1e-4)
        assert_close(stats.stddev(x), x.std(axis=0, ddof=1), rtol=1e-3)
        assert_close(stats.sum(x), x.sum(axis=0), rtol=1e-4)

    def test_meanvar_center(self, rng):
        x = rng.standard_normal((40, 5)).astype(np.float32)
        mu, var = stats.meanvar(x)
        assert_close(mu, x.mean(axis=0), rtol=1e-4)
        assert_close(var, x.var(axis=0, ddof=1), rtol=1e-3)
        centered = np.asarray(stats.mean_center(x))
        assert_close(centered.mean(axis=0), np.zeros(5), atol=1e-5)
        assert_close(stats.mean_add(centered, x.mean(axis=0)), x, rtol=1e-4)

    def test_minmax_cov(self, rng):
        x = rng.standard_normal((100, 4)).astype(np.float32)
        mn, mx = stats.minmax(x)
        assert_close(mn, x.min(axis=0))
        assert_close(mx, x.max(axis=0))
        assert_close(stats.cov(x), np.cov(x.T), rtol=1e-3, atol=1e-4)

    def test_weighted_mean(self, rng):
        x = rng.standard_normal((10, 3)).astype(np.float32)
        w = rng.random(10).astype(np.float32)
        assert_close(stats.weighted_mean(x, w), (x * w[:, None]).sum(0) / w.sum(), rtol=1e-4)

    def test_histogram(self, rng):
        x = rng.random((1000, 1)).astype(np.float32)
        h = np.asarray(stats.histogram(x, 10, 0.0, 1.0))[:, 0]
        ref, _ = np.histogram(x[:, 0], bins=10, range=(0, 1))
        np.testing.assert_array_equal(h, ref)

    def test_dispersion(self):
        centroids = np.array([[0.0, 0.0], [4.0, 0.0]], np.float32)
        sizes = np.array([10, 10], np.float32)
        # global centroid (2,0); each centroid at distance 2 → sqrt(20*4)
        assert_close(stats.dispersion(centroids, sizes), np.sqrt(80.0), rtol=1e-5)


class TestMetrics:
    def test_accuracy(self):
        assert float(stats.accuracy([1, 2, 3, 4], [1, 2, 0, 4])) == pytest.approx(0.75)

    def test_r2(self, rng):
        y = rng.standard_normal(100).astype(np.float32)
        assert float(stats.r2_score(y, y)) == pytest.approx(1.0)
        assert float(stats.r2_score(y, np.full_like(y, y.mean()))) == pytest.approx(0.0, abs=1e-5)

    def test_regression_metrics(self):
        p = np.array([1.0, 2.0, 3.0], np.float32)
        r = np.array([2.0, 2.0, 5.0], np.float32)
        m = stats.regression_metrics(p, r)
        assert float(m.mean_abs_error) == pytest.approx(1.0)
        assert float(m.mean_squared_error) == pytest.approx(5 / 3, rel=1e-5)
        assert float(m.median_abs_error) == pytest.approx(1.0)

    def test_contingency(self):
        c = np.asarray(stats.contingency_matrix([0, 0, 1, 1], [0, 1, 1, 1]))
        np.testing.assert_array_equal(c, [[1, 1], [0, 2]])


class TestClusteringMetrics:
    def test_perfect_and_permuted_labels(self):
        y = np.array([0, 0, 1, 1, 2, 2])
        y_perm = np.array([1, 1, 2, 2, 0, 0])  # same partition, renamed
        assert float(stats.adjusted_rand_index(y, y_perm)) == pytest.approx(1.0)
        assert float(stats.v_measure(y, y_perm)) == pytest.approx(1.0)
        assert float(stats.homogeneity_score(y, y_perm)) == pytest.approx(1.0)
        assert float(stats.completeness_score(y, y_perm)) == pytest.approx(1.0)

    def test_random_labels_near_zero_ari(self, rng):
        a = rng.integers(0, 5, 2000)
        b = rng.integers(0, 5, 2000)
        assert abs(float(stats.adjusted_rand_index(a, b))) < 0.02

    def test_entropy(self):
        # uniform over 4 classes → ln(4)
        y = np.repeat(np.arange(4), 25)
        assert float(stats.entropy(y)) == pytest.approx(np.log(4), rel=1e-4)

    def test_mutual_info_identical(self):
        y = np.repeat(np.arange(3), 10)
        assert float(stats.mutual_info_score(y, y)) == pytest.approx(np.log(3), rel=1e-4)

    def test_rand_index(self):
        assert float(stats.rand_index([0, 0, 1, 1], [0, 0, 1, 1])) == pytest.approx(1.0)

    def test_kl_divergence(self):
        p = np.array([0.5, 0.5], np.float32)
        q = np.array([0.25, 0.75], np.float32)
        ref = 0.5 * np.log(2) + 0.5 * np.log(2 / 3)
        assert float(stats.kl_divergence(p, q)) == pytest.approx(ref, rel=1e-4)

    def test_silhouette_clear_clusters(self, rng):
        a = rng.standard_normal((50, 2)).astype(np.float32) * 0.1
        b = a + 10.0
        x = np.concatenate([a, b])
        y = np.array([0] * 50 + [1] * 50)
        s = float(stats.silhouette_score(x, y))
        assert s > 0.95
        # batched variant agrees
        s_b = float(stats.silhouette_score(x, y, batch_size=16))
        assert s_b == pytest.approx(s, rel=1e-3)

    def test_silhouette_cluster_reduce_modes_agree(self, rng):
        # segment (scatter) vs matmul (one-hot) reductions must agree
        # exactly, on both the dense and the padded batched paths — the
        # segment branch is what large-k CPU runs rely on
        n, d, k = 700, 16, 9
        x = rng.standard_normal((n, d)).astype(np.float32)
        y = rng.integers(0, k, n).astype(np.int32)
        vals = [float(stats.silhouette_score(x, y, cluster_reduce=r,
                                             batch_size=b))
                for r in ("matmul", "segment") for b in (None, 128)]
        for v in vals[1:]:
            assert v == pytest.approx(vals[0], abs=1e-5)
        with pytest.raises(Exception, match="cluster_reduce"):
            stats.silhouette_score(x, y, cluster_reduce="scatter")

    def test_silhouette_batched_matches_dense(self, rng):
        # n deliberately NOT a multiple of batch_size: padded rows/columns
        # must drop out of both the cluster sums and the mean
        n, d, k = 1337, 24, 5
        x = (rng.standard_normal((n, d)).astype(np.float32)
             + (np.arange(n) % k)[:, None] * 2.0)
        y = (np.arange(n) % k).astype(np.int32)
        s_dense = float(stats.silhouette_score(x, y))
        s_batch = float(stats.silhouette_score(x, y, batch_size=256))
        assert s_batch == pytest.approx(s_dense, abs=1e-5)

    def test_information_criterion(self):
        ll = np.array([-100.0], np.float32)
        aic = float(stats.information_criterion_batched(ll, IC_Type.AIC, 3, 50)[0])
        bic = float(stats.information_criterion_batched(ll, IC_Type.BIC, 3, 50)[0])
        assert aic == pytest.approx(206.0)
        assert bic == pytest.approx(200 + 3 * np.log(50), rel=1e-5)


class TestNeighborhood:
    def test_recall_perfect_and_partial(self):
        ref = np.array([[0, 1, 2], [3, 4, 5]])
        assert float(stats.neighborhood_recall(ref, ref)) == pytest.approx(1.0)
        got = np.array([[0, 1, 9], [3, 4, 5]])
        assert float(stats.neighborhood_recall(got, ref)) == pytest.approx(5 / 6, rel=1e-5)

    def test_recall_distance_ties(self):
        ref = np.array([[0, 1]])
        got = np.array([[0, 9]])  # wrong id but identical distance
        d = np.array([[0.0, 1.0]], np.float32)
        assert float(stats.neighborhood_recall(got, ref, distances=d, ref_distances=d)) == 1.0

    def test_trustworthiness_identity_embedding(self, rng):
        x = rng.standard_normal((60, 5)).astype(np.float32)
        t = float(stats.trustworthiness_score(x, x.copy(), n_neighbors=5))
        assert t == pytest.approx(1.0, abs=1e-5)

    def test_trustworthiness_random_embedding_lower(self, rng):
        x = rng.standard_normal((60, 5)).astype(np.float32)
        e = rng.standard_normal((60, 2)).astype(np.float32)
        t = float(stats.trustworthiness_score(x, e, n_neighbors=5))
        assert t < 0.95

    def test_trustworthiness_colchunked_matches(self, rng):
        # database axis streamed in chunks (col_batch_size): must agree
        # exactly with the single-strip path — n not a multiple of either
        # tile size so both padding paths are exercised
        n = 533
        x = rng.standard_normal((n, 12)).astype(np.float32)
        e = (x[:, :3] + 0.3 * rng.standard_normal((n, 3))).astype(np.float32)
        t1 = float(stats.trustworthiness_score(x, e, n_neighbors=7))
        t2 = float(stats.trustworthiness_score(x, e, n_neighbors=7,
                                               batch_size=200,
                                               col_batch_size=100))
        assert t2 == pytest.approx(t1, abs=1e-6)


@pytest.mark.skipif(__import__("os").environ.get("RAFT_RUN_SLOW") != "1",
                    reason="100k-row O(n^2) sweep; set RAFT_RUN_SLOW=1")
def test_silhouette_batched_100k(rng):
    """VERDICT r4 next #8 gate: the double-tiled batched path streams 100k
    rows through an O(c^2) working set (never O(c*n)) and finishes in
    about a minute per core."""
    n, d, k = 100_000, 96, 100
    x = (rng.standard_normal((n, d)).astype(np.float32)
         + (np.arange(n) % k)[:, None] * 1.0)
    y = (np.arange(n) % k).astype(np.int32)
    s = float(stats.silhouette_score(x, y, batch_size=4096))
    # unit-spaced centers under 96-d unit noise (pairwise noise distance
    # ~sqrt(2*96)~14) give a real but moderate structure signal; random
    # labels score ~0 and this measured 0.16 on the CPU backend
    assert 0.05 < s < 0.5, s
