"""Compiled-path discipline: jit vs AOT vs exported artifacts.

The reference compiles the same sources three ways (header-only, compiled
implicit, compiled explicit — ``cpp/tests/CMakeLists.txt:128-139``) and
holds them to identical behavior.  The TPU analog (SURVEY.md §4): the same
program must agree across (a) plain ``jit`` dispatch, (b) AOT
``lower().compile()``, and (c) a ``jax.export`` serialized artifact
round-tripped through bytes — the path a serving system would ship.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import export

from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors.brute_force import _knn_impl


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    q = rng.standard_normal((32, 16)).astype(np.float32)
    db = rng.standard_normal((500, 16)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(db)


def test_knn_aot_matches_jit(data):
    q, db = data
    fn = lambda a, b: _knn_impl(a, b, 5, "sqeuclidean", 128)
    d_jit, i_jit = fn(q, db)
    compiled = jax.jit(fn).lower(q, db).compile()
    d_aot, i_aot = compiled(q, db)
    np.testing.assert_array_equal(np.asarray(i_jit), np.asarray(i_aot))
    np.testing.assert_allclose(np.asarray(d_jit), np.asarray(d_aot))


def test_knn_export_roundtrip_matches_jit(data):
    q, db = data
    fn = jax.jit(lambda a, b: _knn_impl(a, b, 5, "sqeuclidean", 128))
    exported = export.export(fn)(q, db)
    blob = exported.serialize()
    assert isinstance(blob, (bytes, bytearray)) and len(blob) > 0
    restored = export.deserialize(blob)
    d_ref, i_ref = fn(q, db)
    d_exp, i_exp = restored.call(q, db)
    np.testing.assert_array_equal(np.asarray(i_ref), np.asarray(i_exp))
    np.testing.assert_allclose(np.asarray(d_ref), np.asarray(d_exp),
                               rtol=1e-6)


def test_select_k_export_roundtrip():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((64, 200)).astype(np.float32))
    fn = jax.jit(lambda v: select_k(v, 8, select_min=True))
    exported = export.export(fn)(x)
    restored = export.deserialize(exported.serialize())
    v_ref, i_ref = fn(x)
    v_exp, i_exp = restored.call(x)
    np.testing.assert_array_equal(np.asarray(i_ref), np.asarray(i_exp))
    np.testing.assert_allclose(np.asarray(v_ref), np.asarray(v_exp))
