"""raft_tpu.neighbors.ivf_rabitq — the 1-bit RaBitQ IVF tier.

The contract under test (ISSUE 13):

* **rerank-everything oracle** — with ``rerank_k = n`` every stored row
  reaches the exact rerank, so results must be bit-identical (values AND
  ids) to ``brute_force.knn``: the estimator may only *order* candidates,
  never change what an admitted candidate scores.
* **estimator quality** — at practical ``rerank_k`` the unbiased 1-bit
  estimate must recover near the probe-coverage recall ceiling.
* **lifecycle** — extend / delete / compact / serialize compose exactly
  as for the other IVF families (extend-from-empty ≡ build bit-identity
  with capacity headroom, compaction preserves search results, v4
  artifacts round-trip, steady-state extend is retrace/transfer-free).

Bitwise comparisons use integer-valued f32 data (each arithmetic step
exact in f32); gaussian data checks ids + allclose (einsum tilings of
different shapes may differ in the last ulp).
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import TraceGuard
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force, ivf_rabitq, mutation, serialize
from raft_tpu.neighbors.ivf_rabitq import (IvfRabitqIndex,
                                           IvfRabitqIndexParams,
                                           IvfRabitqSearchParams)
from raft_tpu.ops import blocked_scan

N, D, NQ, K = 3000, 64, 16, 10
# capacity headroom: the extend-vs-rebuild oracle (like ivf_flat's) is
# only exact while no list saturates — capped assignment spills
# differently between the one-shot and chunked engines at the cap
PARAMS = IvfRabitqIndexParams(n_lists=8, kmeans_n_iters=10,
                              list_cap_ratio=3.0)


def _int_data(rng, rows, d=D):
    """Integer-valued f32: every arithmetic step lands on exact floats,
    enabling bitwise comparisons across accumulation orders."""
    return rng.integers(0, 256, size=(rows, d)).astype(np.float32)


@pytest.fixture(scope="module")
def db():
    return jnp.asarray(_int_data(np.random.default_rng(7), N))


@pytest.fixture(scope="module")
def queries():
    return jnp.asarray(_int_data(np.random.default_rng(8), NQ))


@pytest.fixture(scope="module")
def index(db):
    return ivf_rabitq.build(db, PARAMS)


# ---------------------------------------------------------------------------
# packed-sign primitives (the quantized-scan sub-API)


def test_sign_bits_roundtrip(rng):
    x = rng.standard_normal((5, 7, 33)).astype(np.float32)
    packed = blocked_scan.pack_sign_bits(jnp.asarray(x))
    assert packed.dtype == jnp.uint8 and packed.shape == (5, 7, 5)
    bits = blocked_scan.unpack_sign_bits(packed, 33)
    np.testing.assert_array_equal(np.asarray(bits), (x >= 0).astype(np.int8))


def test_packed_sign_dots_exact(rng):
    nq, b, c, d = 3, 4, 6, 48
    x = rng.standard_normal((nq, b, c, d)).astype(np.float32)
    q8 = rng.integers(-127, 128, size=(nq, d)).astype(np.int8)
    packed = blocked_scan.pack_sign_bits(jnp.asarray(x))
    got = blocked_scan.packed_sign_dots(packed, jnp.asarray(q8))
    signs = np.where(x >= 0, 1.0, -1.0)
    want = np.einsum("qbcd,qd->qbc", signs, q8.astype(np.float32))
    np.testing.assert_array_equal(np.asarray(got), want)


def test_slab_dots_packed_sign_dispatch(rng):
    x = rng.standard_normal((2, 3, 5, 32)).astype(np.float32)
    q8 = rng.integers(-127, 128, size=(2, 32)).astype(np.int8)
    packed = blocked_scan.pack_sign_bits(jnp.asarray(x))
    via_slab = blocked_scan.slab_dots(packed, jnp.asarray(q8),
                                      packed_sign=True)
    direct = blocked_scan.packed_sign_dots(packed, jnp.asarray(q8))
    np.testing.assert_array_equal(np.asarray(via_slab), np.asarray(direct))


# ---------------------------------------------------------------------------
# the rerank-everything oracle


def test_rerank_all_bit_identical_to_brute_force(index, db, queries):
    """rerank_k = n: values AND ids bitwise equal to brute force — the
    estimator gates nothing, the exact rerank recomputes everything in
    brute-force accumulation order."""
    p = IvfRabitqSearchParams(n_probes=PARAMS.n_lists, rerank_k=N)
    dv, di = ivf_rabitq.search(index, queries, K, p)
    bv, bi = brute_force.knn(queries, db, K)
    np.testing.assert_array_equal(np.asarray(di), np.asarray(bi))
    np.testing.assert_array_equal(np.asarray(dv), np.asarray(bv))


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean",
                                    "inner_product"])
def test_rerank_all_matches_brute_all_metrics(metric):
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1200, 48)).astype(np.float32))
    q = jnp.asarray(rng.standard_normal((8, 48)).astype(np.float32))
    idx = ivf_rabitq.build(x, dataclasses.replace(PARAMS, metric=metric))
    p = IvfRabitqSearchParams(n_probes=PARAMS.n_lists, rerank_k=1200)
    dv, di = ivf_rabitq.search(idx, q, K, p)
    bv, bi = brute_force.knn(q, x, K, metric=metric)
    np.testing.assert_array_equal(np.asarray(di), np.asarray(bi))
    np.testing.assert_allclose(np.asarray(dv), np.asarray(bv),
                               rtol=1e-5, atol=1e-4)


def test_estimator_recall_near_coverage_ceiling(index, db, queries):
    """At rerank_k ≪ n the 1-bit estimate must still surface most true
    neighbors the probes cover — uniform data is the estimator's worst
    case (1-bit relative error ~ 1/√d against near-equidistant rows),
    so the gate sits at ~10 % of n and recall must grow with rerank_k."""
    _, gt = brute_force.knn(queries, db, K)
    gt = np.asarray(gt)

    def recall_at(rk):
        p = IvfRabitqSearchParams(n_probes=PARAMS.n_lists, rerank_k=rk)
        _, ids = ivf_rabitq.search(index, queries, K, p)
        return np.mean([len(set(a) & set(b)) / K
                        for a, b in zip(np.asarray(ids), gt)])

    lo, hi = recall_at(8 * K), recall_at(32 * K)
    assert hi >= 0.95, (lo, hi)
    assert hi >= lo  # more exact-reranked candidates never hurts


# ---------------------------------------------------------------------------
# knob resolution + invariances


def test_resolve_rerank_k_contract():
    assert ivf_rabitq.resolve_rerank_k(100, 10, 8, 500) == 100
    assert ivf_rabitq.resolve_rerank_k(10 ** 9, 10, 8, 500) == 8 * 500
    auto = ivf_rabitq.resolve_rerank_k(0, 10, 8, 500)
    assert 10 <= auto <= 8 * 500
    with pytest.raises(RaftError):
        ivf_rabitq.resolve_rerank_k(5, 10, 8, 500)  # requested < k


def test_probe_block_invariance(index, queries):
    base = None
    for pb in (1, 2, 4):
        p = IvfRabitqSearchParams(n_probes=8, rerank_k=64, probe_block=pb)
        dv, di = ivf_rabitq.search(index, queries, K, p)
        if base is None:
            base = (np.asarray(dv), np.asarray(di))
        else:
            np.testing.assert_array_equal(base[0], np.asarray(dv))
            np.testing.assert_array_equal(base[1], np.asarray(di))


def test_scan_kernel_arms_agree(index, queries):
    """'fused' dispatches to the XLA scan today (gate hook; the Pallas
    arm is follow-up) — every arm must return identical results."""
    outs = []
    for arm in ("auto", "xla", "fused"):
        p = IvfRabitqSearchParams(n_probes=8, rerank_k=64, scan_kernel=arm)
        dv, di = ivf_rabitq.search(index, queries, K, p)
        outs.append((np.asarray(dv), np.asarray(di)))
    for dv, di in outs[1:]:
        np.testing.assert_array_equal(outs[0][0], dv)
        np.testing.assert_array_equal(outs[0][1], di)


def test_fused_scan_fallback_is_counted(index, queries):
    """Requesting the not-yet-implemented fused estimator scan must be a
    COUNTED fallback — ``raft_pallas_gate_fallback_total{kernel=
    "rabitq_scan"}`` increments — never a silent dispatch, and the
    results must equal the xla arm exactly."""
    from raft_tpu.obs.metrics import registry

    c = registry().counter("raft_pallas_gate_fallback_total", "x")

    def count():
        return sum(v for labels, v in c.samples()
                   if labels.get("kernel") == "rabitq_scan")

    before = count()
    p = IvfRabitqSearchParams(n_probes=8, rerank_k=64, scan_kernel="fused")
    fv, fi = ivf_rabitq.search(index, queries, K, p)
    assert count() > before
    xp = IvfRabitqSearchParams(n_probes=8, rerank_k=64, scan_kernel="xla")
    xv, xi = ivf_rabitq.search(index, queries, K, xp)
    np.testing.assert_array_equal(np.asarray(fv), np.asarray(xv))
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(xi))
    # "xla" and "auto" never count a fallback (they asked for nothing
    # they didn't get)
    mid = count()
    ivf_rabitq.search(index, queries, K, xp)
    assert count() == mid


def test_searcher_matches_search(index, queries):
    p = IvfRabitqSearchParams(n_probes=8, rerank_k=64)
    dv, di = ivf_rabitq.search(index, queries, K, p)
    fn, ops = ivf_rabitq.searcher(index, K, p)
    dv2, di2 = fn(queries, *ops)
    np.testing.assert_array_equal(np.asarray(dv), np.asarray(dv2))
    np.testing.assert_array_equal(np.asarray(di), np.asarray(di2))


def test_filtered_search_excludes(index, queries):
    p = IvfRabitqSearchParams(n_probes=8, rerank_k=64)
    _, di = ivf_rabitq.search(index, queries, K, p)
    banned = sorted({int(i) for i in np.asarray(di)[:, 0]})[:4]
    keep = np.ones(N, bool)
    keep[banned] = False
    _, df = ivf_rabitq.search(index, queries, K, p, filter=keep)
    assert not np.isin(np.asarray(df), banned).any()


def test_build_validation(db):
    with pytest.raises(RaftError):
        ivf_rabitq.build(db, dataclasses.replace(PARAMS, metric="cosine"))


# ---------------------------------------------------------------------------
# chunked build + extend


def test_chunked_engines_bit_identical(db):
    a = ivf_rabitq.build_chunked(np.asarray(db), PARAMS, chunk_rows=700)
    b = ivf_rabitq._build_chunked_perop(np.asarray(db), PARAMS,
                                        chunk_rows=700)
    for f in ("centroids", "rotation", "codes", "sabs", "res_norms",
              "code_cdots", "data", "ids", "counts"):
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)),
                                      err_msg=f)


def _empty_like(full: IvfRabitqIndex) -> IvfRabitqIndex:
    return IvfRabitqIndex(
        full.centroids, full.rotation,
        jnp.zeros_like(full.codes), jnp.zeros_like(full.sabs),
        jnp.zeros_like(full.res_norms), jnp.zeros_like(full.code_cdots),
        jnp.zeros_like(full.data), jnp.full_like(full.ids, -1),
        jnp.zeros_like(full.counts), full.metric)


def test_extend_bit_identical_to_build(index, db):
    """Extending an empty clone (same centroids/rotation) with the full
    dataset reproduces the built index bit-for-bit — the encode path is
    batch-size invariant.  Needs capacity headroom (see PARAMS note)."""
    grown = ivf_rabitq.extend(_empty_like(index), db, np.arange(N))
    for f in ("codes", "sabs", "res_norms", "code_cdots", "data", "ids",
              "counts"):
        np.testing.assert_array_equal(np.asarray(getattr(index, f)),
                                      np.asarray(getattr(grown, f)),
                                      err_msg=f)


def test_extend_grows_capacity(db):
    rng = np.random.default_rng(5)
    small = ivf_rabitq.build(db[:200], dataclasses.replace(PARAMS,
                                                           n_lists=4))
    extra = jnp.asarray(_int_data(rng, 800))
    grown = ivf_rabitq.extend(small, extra, np.arange(200, 1000))
    assert grown.size == 1000
    assert grown.list_cap > small.list_cap


def test_extend_steady_state_trace_guard(db):
    """After one warm insert, further same-sized inserts run with zero
    retraces, zero compiles, zero implicit transfers."""
    rng = np.random.default_rng(22)
    idx = ivf_rabitq.build(db, PARAMS)
    nxt = N
    idx = ivf_rabitq.extend(idx, _int_data(rng, 16), np.arange(nxt, nxt + 16))
    nxt += 16
    jax.block_until_ready(idx.counts)
    with TraceGuard() as tg:
        for _ in range(4):
            idx = ivf_rabitq.extend(idx, _int_data(rng, 16),
                                    np.arange(nxt, nxt + 16))
            nxt += 16
        jax.block_until_ready(idx.counts)
    tg.assert_steady_state()
    assert idx.size == N + 5 * 16


def test_search_steady_state_trace_guard(index, queries):
    p = IvfRabitqSearchParams(n_probes=8, rerank_k=64)
    fn, ops = ivf_rabitq.searcher(index, K, p)
    jax.block_until_ready(fn(queries, *ops))
    with TraceGuard() as tg:
        for _ in range(4):
            out = fn(queries, *ops)
        jax.block_until_ready(out)
    tg.assert_steady_state()


# ---------------------------------------------------------------------------
# delete / compact


def test_delete_and_compact_preserve_results(index, queries):
    p = IvfRabitqSearchParams(n_probes=8, rerank_k=64)
    _, di = ivf_rabitq.search(index, queries, K, p)
    dead = sorted({int(i) for i in np.asarray(di)[:, 0]})[:3]
    ts = mutation.delete(index, dead)
    dv_t, di_t = mutation.search(ts, queries, K, p)
    assert not np.isin(np.asarray(di_t), dead).any()
    comp = mutation.compact(ts)
    assert isinstance(comp, IvfRabitqIndex)
    assert comp.size == index.size - len(dead)
    dv_c, di_c = ivf_rabitq.search(comp, queries, K, p)
    np.testing.assert_array_equal(np.asarray(di_t), np.asarray(di_c))
    np.testing.assert_array_equal(np.asarray(dv_t), np.asarray(dv_c))


def test_compact_roundtrip_is_identity(index):
    """Compacting with no tombstones repacks every live row (cap may
    shrink) — same rows per list, correction scalars verbatim."""
    comp = mutation.compact(index, headroom=3.0)
    assert comp.size == index.size
    for lst in range(PARAMS.n_lists):
        c0 = int(np.asarray(index.counts)[lst])
        c1 = int(np.asarray(comp.counts)[lst])
        assert c0 == c1
        np.testing.assert_array_equal(
            np.asarray(index.ids)[lst, :c0], np.asarray(comp.ids)[lst, :c1])
        np.testing.assert_array_equal(
            np.asarray(index.sabs)[lst, :c0], np.asarray(comp.sabs)[lst, :c1])


# ---------------------------------------------------------------------------
# serialization (format v4 + compat)


def test_serialize_roundtrip_v4(index, queries, tmp_path):
    path = tmp_path / "rq"
    serialize.save_index(path, index, manifest={"lsn": 11})
    meta = json.loads((path / "meta.json").read_text())
    assert meta["metadata"]["format_version"] == 4
    assert serialize.verify_index(path) == []
    assert serialize.index_manifest(path) == {"lsn": 11}
    loaded = serialize.load_index(path, verify=True)
    for f in ("centroids", "rotation", "codes", "sabs", "res_norms",
              "code_cdots", "data", "ids", "counts"):
        np.testing.assert_array_equal(np.asarray(getattr(index, f)),
                                      np.asarray(getattr(loaded, f)),
                                      err_msg=f)
    assert loaded.metric == index.metric
    p = IvfRabitqSearchParams(n_probes=8, rerank_k=64)
    dv, di = ivf_rabitq.search(index, queries, K, p)
    dv2, di2 = ivf_rabitq.search(loaded, queries, K, p)
    np.testing.assert_array_equal(np.asarray(di), np.asarray(di2))
    np.testing.assert_array_equal(np.asarray(dv), np.asarray(dv2))


def test_serialize_tombstoned_stamps_v4(index, tmp_path):
    ts = mutation.delete(index, [1, 2])
    path = tmp_path / "rq_ts"
    serialize.save_index(path, ts)
    meta = json.loads((path / "meta.json").read_text())
    assert meta["metadata"]["format_version"] == 4
    back = serialize.load_index(path)
    assert isinstance(back, mutation.Tombstoned)
    assert isinstance(back.index, IvfRabitqIndex)


def test_legacy_artifacts_still_write_old_versions(db, tmp_path):
    """The version bump must not inflate non-RaBitQ artifacts: a flat
    index still stamps v1 (readable by every deployed reader)."""
    from raft_tpu.neighbors import ivf_flat

    fidx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(
        n_lists=8, kmeans_n_iters=4))
    path = tmp_path / "flat"
    serialize.save_index(path, fidx)
    meta = json.loads((path / "meta.json").read_text())
    assert meta["metadata"]["format_version"] == 1
    ts = mutation.delete(fidx, [1])
    path2 = tmp_path / "flat_ts"
    serialize.save_index(path2, ts)
    meta2 = json.loads((path2 / "meta.json").read_text())
    assert meta2["metadata"]["format_version"] == 3


def test_v4_rejected_by_v3_reader(index, tmp_path, monkeypatch):
    """A reader from before this format bump must refuse a v4 artifact
    loudly (not mis-parse it)."""
    path = tmp_path / "rq"
    serialize.save_index(path, index)
    monkeypatch.setattr(serialize, "_FORMAT_VERSION", 3)
    with pytest.raises(ValueError, match="newer than supported"):
        serialize.load_index(path)
    assert any("newer than supported" in p
               for p in serialize.verify_index(path))


def test_future_version_rejected(index, tmp_path):
    path = tmp_path / "rq"
    serialize.save_index(path, index)
    mpath = path / "meta.json"
    meta = json.loads(mpath.read_text())
    meta["metadata"]["format_version"] = serialize._FORMAT_VERSION + 1
    mpath.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="newer than supported"):
        serialize.load_index(path)


# ---------------------------------------------------------------------------
# serve / observability coverage


def test_family_and_searcher_dispatch(index, queries):
    from raft_tpu.serve.searchers import family_of, make_searcher

    assert family_of(index) == "ivf_rabitq"
    assert family_of(mutation.delete(index, [0])) == "ivf_rabitq"
    p = IvfRabitqSearchParams(n_probes=8, rerank_k=64)
    fn, ops = make_searcher(index, K, p)
    dv, di = fn(queries, *ops)
    dv0, di0 = ivf_rabitq.search(index, queries, K, p)
    np.testing.assert_array_equal(np.asarray(di0), np.asarray(di))
    np.testing.assert_array_equal(np.asarray(dv0), np.asarray(dv))
    # effort scaling shrinks n_probes but still returns K valid results
    fn2, ops2 = make_searcher(index, K, p, effort_scale=0.25)
    _, di2 = fn2(queries, *ops2)
    assert (np.asarray(di2) >= 0).all()


def test_index_health(index):
    from raft_tpu.neighbors.health import index_health

    h = index_health(index)
    assert h["family"] == "ivf_rabitq"
    assert h["rows"] == N
    assert h["residual_energy_mean"] > 0
    assert h["residual_energy_p95"] >= h["residual_energy_mean"] * 0.1
    ts = mutation.delete(index, [0, 1])
    h2 = index_health(ts)
    assert h2["dead"] == 2.0


def test_oracle_database_covers_rabitq(index):
    from raft_tpu.obs.quality import oracle_database

    vecs, ids = oracle_database(index)
    assert vecs.shape == (N, D)
    assert sorted(ids.tolist()) == list(range(N))
    dead = [4, 9]
    vecs2, ids2 = oracle_database(mutation.delete(index, dead))
    assert ids2.shape[0] == N - len(dead)
    assert not np.isin(ids2, dead).any()


def test_tune_table_key_matches_tuner():
    """bench/tune_rabitq.py and the resolver must agree on the bucket
    key scheme, or tuned entries are silently dead."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tune_rabitq", os.path.join(os.path.dirname(__file__), "..",
                                    "bench", "tune_rabitq.py"))
    src = open(spec.origin).read()
    assert 'f"ivf_rabitq:{k.bit_length()}:{n_probes.bit_length()}"' in src
