"""KMeans tests — convergence on separable blobs, sharded-fit equivalence on
the virtual mesh, balanced variant list-size uniformity."""

import numpy as np
import pytest

from raft_tpu.cluster import (
    KMeansParams,
    kmeans_fit,
    kmeans_fit_predict,
    kmeans_predict,
    kmeans_transform,
    kmeans_balanced_fit,
    kmeans_balanced_fit_predict,
    kmeans_plus_plus_init,
)
from raft_tpu.random import RngState, make_blobs
from raft_tpu.stats import adjusted_rand_index


def _blobs(rng, n=512, d=8, k=5, seed=7):
    x, y = make_blobs(RngState(seed), n, d, n_clusters=k, cluster_std=0.3)
    return np.asarray(x), np.asarray(y)


def test_kmeans_recovers_blobs(rng):
    x, y = _blobs(rng)
    p = KMeansParams(n_clusters=5, max_iter=50, seed=1)
    c, labels, inertia, n_iter = kmeans_fit_predict(x, p)
    assert c.shape == (5, 8)
    ari = float(adjusted_rand_index(np.asarray(labels), y))
    assert ari > 0.95, f"ARI {ari}"
    assert float(inertia) > 0


def test_kmeans_inertia_decreases(rng):
    x, _ = _blobs(rng, n=256, k=4)
    p1 = KMeansParams(n_clusters=4, max_iter=1, seed=0)
    p2 = KMeansParams(n_clusters=4, max_iter=30, seed=0)
    _, i1, _ = kmeans_fit(x, p1)
    _, i2, _ = kmeans_fit(x, p2)
    assert float(i2) <= float(i1) + 1e-3


def test_kmeans_predict_transform(rng):
    x, _ = _blobs(rng, n=128, k=3)
    c, _, _ = kmeans_fit(x, KMeansParams(n_clusters=3, max_iter=20))
    labels = np.asarray(kmeans_predict(x, c))
    t = np.asarray(kmeans_transform(x, c))
    assert t.shape == (128, 3)
    np.testing.assert_array_equal(labels, t.argmin(1))


def test_kmeans_plus_plus_spread(rng):
    x, _ = _blobs(rng, n=200, k=4, seed=9)
    import jax

    c = np.asarray(kmeans_plus_plus_init(jax.random.PRNGKey(0), x, 4))
    # seeding should pick 4 distinct, well-separated points
    from scipy.spatial.distance import pdist

    assert pdist(c).min() > 1.0


def test_kmeans_sharded_fit(rng, mesh8):
    x, y = _blobs(rng, n=512, k=4, seed=11)
    p = KMeansParams(n_clusters=4, max_iter=25, seed=2)
    c, inertia, _ = kmeans_fit(x, p, mesh=mesh8)
    labels = np.asarray(kmeans_predict(x, c))
    ari = float(adjusted_rand_index(labels, y))
    assert ari > 0.9, f"sharded ARI {ari}"


def test_kmeans_balanced_sizes(rng):
    x, _ = _blobs(rng, n=480, d=6, k=3, seed=5)
    p = KMeansParams(n_clusters=8, max_iter=30, balanced_penalty=2.0, seed=0)
    c, sizes, inertia = kmeans_balanced_fit(x, p)
    sizes = np.asarray(sizes)
    assert sizes.sum() == 480
    # balanced: no list more than 3x the target size
    assert sizes.max() <= 3 * 480 / 8, sizes


def test_kmeans_balanced_fit_predict(rng):
    x, y = _blobs(rng, n=300, d=5, k=5, seed=13)
    p = KMeansParams(n_clusters=5, max_iter=40, balanced_penalty=0.5, seed=4)
    c, labels, sizes, _ = kmeans_balanced_fit_predict(x, p)
    ari = float(adjusted_rand_index(np.asarray(labels), y))
    assert ari > 0.8, f"balanced ARI {ari}"
