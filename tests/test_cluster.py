"""KMeans tests — convergence on separable blobs, sharded-fit equivalence on
the virtual mesh, balanced variant list-size uniformity."""

import numpy as np
import pytest

from raft_tpu.cluster import (
    KMeansParams,
    kmeans_fit,
    kmeans_fit_predict,
    kmeans_predict,
    kmeans_transform,
    kmeans_balanced_fit,
    kmeans_balanced_fit_predict,
    kmeans_plus_plus_init,
)
from raft_tpu.random import RngState, make_blobs
from raft_tpu.stats import adjusted_rand_index


def _blobs(rng, n=512, d=8, k=5, seed=7):
    x, y = make_blobs(RngState(seed), n, d, n_clusters=k, cluster_std=0.3)
    return np.asarray(x), np.asarray(y)


def test_kmeans_recovers_blobs(rng):
    x, y = _blobs(rng)
    p = KMeansParams(n_clusters=5, max_iter=50, seed=1)
    c, labels, inertia, n_iter = kmeans_fit_predict(x, p)
    assert c.shape == (5, 8)
    ari = float(adjusted_rand_index(np.asarray(labels), y))
    assert ari > 0.95, f"ARI {ari}"
    assert float(inertia) > 0


def test_kmeans_inertia_decreases(rng):
    x, _ = _blobs(rng, n=256, k=4)
    p1 = KMeansParams(n_clusters=4, max_iter=1, seed=0)
    p2 = KMeansParams(n_clusters=4, max_iter=30, seed=0)
    _, i1, _ = kmeans_fit(x, p1)
    _, i2, _ = kmeans_fit(x, p2)
    assert float(i2) <= float(i1) + 1e-3


def test_kmeans_predict_transform(rng):
    x, _ = _blobs(rng, n=128, k=3)
    c, _, _ = kmeans_fit(x, KMeansParams(n_clusters=3, max_iter=20))
    labels = np.asarray(kmeans_predict(x, c))
    t = np.asarray(kmeans_transform(x, c))
    assert t.shape == (128, 3)
    np.testing.assert_array_equal(labels, t.argmin(1))


def test_kmeans_plus_plus_spread(rng):
    x, _ = _blobs(rng, n=200, k=4, seed=9)
    import jax

    c = np.asarray(kmeans_plus_plus_init(jax.random.PRNGKey(0), x, 4))
    # seeding should pick 4 distinct, well-separated points
    from scipy.spatial.distance import pdist

    assert pdist(c).min() > 1.0


def test_kmeans_sharded_fit(rng, mesh8):
    x, y = _blobs(rng, n=512, k=4, seed=11)
    p = KMeansParams(n_clusters=4, max_iter=25, seed=2)
    c, inertia, _ = kmeans_fit(x, p, mesh=mesh8)
    labels = np.asarray(kmeans_predict(x, c))
    ari = float(adjusted_rand_index(labels, y))
    assert ari > 0.9, f"sharded ARI {ari}"


def test_kmeans_balanced_sizes(rng):
    x, _ = _blobs(rng, n=480, d=6, k=3, seed=5)
    p = KMeansParams(n_clusters=8, max_iter=30, balanced_penalty=2.0, seed=0)
    c, sizes, inertia = kmeans_balanced_fit(x, p)
    sizes = np.asarray(sizes)
    assert sizes.sum() == 480
    # balanced: no list more than 3x the target size
    assert sizes.max() <= 3 * 480 / 8, sizes


def test_kmeans_balanced_bf16_assign_tier(rng):
    """balanced_assign_precision="bf16" speeds the TRAINING gemm only:
    the returned partition stays valid and the quality (inertia, measured
    exactly in both cases) stays within a 5% tolerance of the
    exact-assignment fit — loose enough to hold on TPU, where DEFAULT
    precision really is bf16 and assignments can flip near ties."""
    x, _ = _blobs(rng, n=480, d=6, k=3, seed=5)
    exact = KMeansParams(n_clusters=8, max_iter=30, balanced_penalty=2.0,
                         seed=0)
    fast = KMeansParams(n_clusters=8, max_iter=30, balanced_penalty=2.0,
                        seed=0, balanced_assign_precision="bf16")
    _, sizes_e, inertia_e = kmeans_balanced_fit(x, exact)
    _, sizes_f, inertia_f = kmeans_balanced_fit(x, fast)
    assert np.asarray(sizes_f).sum() == 480
    assert float(inertia_f) <= float(inertia_e) * 1.05

    with pytest.raises(Exception, match="balanced_assign_precision"):
        kmeans_balanced_fit(x, KMeansParams(n_clusters=8,
                                            balanced_assign_precision="bf17"))
    # the plain fit rejects the balanced-only knob instead of ignoring it
    with pytest.raises(Exception, match="balanced_assign_precision"):
        kmeans_fit(x, KMeansParams(n_clusters=8,
                                   balanced_assign_precision="bf16"))


def test_kmeans_balanced_fit_predict(rng):
    x, y = _blobs(rng, n=300, d=5, k=5, seed=13)
    p = KMeansParams(n_clusters=5, max_iter=40, balanced_penalty=0.5, seed=4)
    c, labels, sizes, _ = kmeans_balanced_fit_predict(x, p)
    ari = float(adjusted_rand_index(np.asarray(labels), y))
    assert ari > 0.8, f"balanced ARI {ari}"


def test_kmeans_sample_weight():
    """Weighted fit (classic cluster::kmeans sample_weights parity):
    heavily-weighted points dominate their centroid."""
    import jax.numpy as jnp

    from raft_tpu.cluster import KMeansParams, kmeans_fit

    rng = np.random.default_rng(5)
    a = rng.normal(0.0, 0.05, (100, 2)).astype(np.float32)
    b = rng.normal(4.0, 0.05, (100, 2)).astype(np.float32)
    outlier = np.array([[100.0, 100.0]], np.float32)
    x = np.concatenate([a, b, outlier])
    w = np.ones(201, np.float32)
    w[-1] = 1e-6  # the outlier is almost weightless
    c, inertia, _ = kmeans_fit(x, KMeansParams(n_clusters=2, seed=3),
                               sample_weight=w)
    c = np.sort(np.asarray(c)[:, 0])
    # both centroids land on the real clusters, not the outlier
    assert abs(c[0] - 0.0) < 0.5 and abs(c[1] - 4.0) < 0.5, c
    # weighted inertia excludes (almost all of) the outlier's huge d2
    assert float(inertia) < 100.0


def test_kmeans_sample_weight_validation():
    from raft_tpu.cluster import KMeansParams, kmeans_fit
    from raft_tpu.core.errors import LogicError

    x = np.random.default_rng(0).random((50, 4)).astype(np.float32)
    with pytest.raises(LogicError):
        kmeans_fit(x, KMeansParams(n_clusters=4), sample_weight=np.ones(10))


def test_kmeans_uniform_small_weights_match_unweighted():
    """sample_weight=c (any constant) must reproduce the unweighted fit —
    the fractional-mass normalization regression test."""
    from raft_tpu.cluster import KMeansParams, kmeans_fit

    x = np.random.default_rng(7).normal(size=(300, 4)).astype(np.float32)
    p = KMeansParams(n_clusters=8, seed=1, init="random")
    c0, i0, _ = kmeans_fit(x, p)
    c1, i1, _ = kmeans_fit(x, p, sample_weight=np.full(300, 0.01, np.float32))
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c0), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(i1), 0.01 * float(i0), rtol=1e-4)
