"""Executes every ```python block in the prose guides
(docs/tuning_guide.md, docs/serving_guide.md) in one shared namespace per
guide — the guides' snippets are tested code, extending the doctest
discipline (SURVEY.md §4) to the prose docs."""

import os
import re

DOCS = os.path.join(os.path.dirname(__file__), "..", "docs")


def _run_guide(name: str, min_blocks: int) -> None:
    with open(os.path.join(DOCS, name)) as f:
        text = f.read()
    blocks = re.findall(r"```python\n(.*?)```", text, re.DOTALL)
    assert len(blocks) >= min_blocks, f"{name} lost its examples"
    ns: dict = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"{name}[block {i}]", "exec"), ns)
        except AssertionError as e:
            raise AssertionError(
                f"{name} block {i} failed its own assert: {e}"
            ) from e


def test_tuning_guide_snippets_execute():
    _run_guide("tuning_guide.md", min_blocks=5)


def test_serving_guide_snippets_execute():
    _run_guide("serving_guide.md", min_blocks=2)


def test_jax_hygiene_snippets_execute():
    _run_guide("jax_hygiene.md", min_blocks=9)


def test_mutability_guide_snippets_execute():
    _run_guide("mutability_guide.md", min_blocks=5)


def test_observability_guide_snippets_execute():
    _run_guide("observability_guide.md", min_blocks=4)


def test_perf_analysis_snippets_execute():
    _run_guide("perf_analysis.md", min_blocks=1)
