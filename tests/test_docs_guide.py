"""Executes every ```python block in docs/tuning_guide.md in one shared
namespace — the guide's snippets are tested code, extending the doctest
discipline (SURVEY.md §4) to the prose docs."""

import os
import re

GUIDE = os.path.join(os.path.dirname(__file__), "..", "docs", "tuning_guide.md")


def test_tuning_guide_snippets_execute():
    with open(GUIDE) as f:
        text = f.read()
    blocks = re.findall(r"```python\n(.*?)```", text, re.DOTALL)
    assert len(blocks) >= 5, "guide lost its examples"
    ns: dict = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"tuning_guide.md[block {i}]", "exec"), ns)
        except AssertionError as e:
            raise AssertionError(
                f"tuning_guide.md block {i} failed its own assert: {e}"
            ) from e
