"""Subprocess crash/recover drills + corruption injection (ISSUE 7).

The acceptance criteria these pin:

* for EVERY armed crash site (``wal_append``, ``extend``, ``snapshot``,
  ``rename``, ``compact``) a SIGKILL-style abort mid-operation recovers
  to an index bit-identical (values AND ids) to the pre-crash committed
  state — the exact rung of the expected-state ladder the WAL contract
  promises — and ``SearchServer.recover()`` serves immediately after;
* ``corrupt``-kind faults (byte-flips into snapshot/WAL artifacts) are
  caught by checksums, quarantined (never parsed), and recovery falls
  back with ``quarantined_files`` > 0.

The child process lives in ``tests/_durability_driver.py`` — the same
module computes the parent's expected states, so child mutations and
parent expectations are one code path.
"""

from __future__ import annotations

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import _durability_driver as driver  # noqa: E402

from raft_tpu.neighbors import mutation  # noqa: E402
from raft_tpu.neighbors.wal import DurableStore, WalConfig, read_wal  # noqa: E402
from raft_tpu.serve import (CRASH_EXIT_CODE, FaultInjector,  # noqa: E402
                            SearchServer, ServerConfig)

D = driver.D


def _leaves(tree):
    return [np.asarray(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(tree)]


def assert_bit_identical(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype and x.shape == y.shape
        np.testing.assert_array_equal(x, y)


@pytest.fixture(scope="module")
def states(tmp_path_factory):
    """The fault-free expected-state ladder (built once per module)."""
    return driver.expected_states(
        tmp_path_factory.mktemp("expected") / "store")


def _run_child(root, site):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, DUR_ROOT=str(root), DUR_SITE=site,
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.environ.get("PYTHONPATH")) if p))
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "_durability_driver.py")],
        env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == CRASH_EXIT_CODE, \
        f"child should die at the armed {site!r} site " \
        f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}"
    m = int((root / "progress").read_text())
    return m


# site -> which ladder rung the recovered index must equal, relative to
# the op the child was inside when it died (marker m):
#   wal_append fires BEFORE the record is written -> the op is lost (m);
#   extend/compact fire AFTER the fsynced append  -> replay includes it
#   (m+1); snapshot/rename crash while publishing -> the index itself is
#   unchanged by a snapshot op (m == m+1 there), previous snapshot +
#   longer replay must land on it.
_SITES = [("wal_append", 0), ("extend", 1), ("compact", 1),
          ("snapshot", 0), ("rename", 0)]


@pytest.mark.parametrize("site,offset", _SITES,
                         ids=[s for s, _ in _SITES])
def test_crash_recovery_bit_identical_per_site(site, offset, states,
                                               tmp_path):
    root = tmp_path / "store"
    m = _run_child(root, site)
    srv = SearchServer.recover(root, k=3, config=ServerConfig(ladder=(4,)))
    store = srv.durable_store
    assert_bit_identical(states[m + offset], store.index)
    if site in ("snapshot", "rename"):
        # the half-published temp snapshot was quarantined, not trusted
        assert store.counters["quarantined_files"] >= 1
        assert os.listdir(root / "quarantine")
    # search serves immediately after recover(), against the recovered
    # generation, bit-identical to a direct search on the expected state
    q = np.random.default_rng(13).standard_normal((3, D)).astype(np.float32)
    d_srv, i_srv = srv.search(q)
    d_ref, i_ref = mutation.search(states[m + offset], q, 3) \
        if isinstance(states[m + offset], mutation.Tombstoned) else (None,
                                                                     None)
    assert d_ref is not None  # every ladder rung stays tombstoned
    np.testing.assert_array_equal(np.asarray(d_srv),
                                  np.asarray(jax.device_get(d_ref)))
    np.testing.assert_array_equal(np.asarray(i_srv),
                                  np.asarray(jax.device_get(i_ref)))
    assert srv.metrics.recoveries == 1
    assert srv.metrics_snapshot()["server"]["wal_lsn"] == store.wal_lsn
    store.close()


def test_crash_recovered_store_keeps_mutating(states, tmp_path):
    """Recovery is a beginning, not a postmortem: the recovered store
    accepts new durable mutations and a SECOND recovery sees them."""
    root = tmp_path / "store"
    _run_child(root, "compact")
    store = DurableStore.recover(root)
    store.delete([100, 101])
    store.snapshot()
    live = store.index
    store.close()
    again = DurableStore.recover(root)
    assert_bit_identical(live, again.index)
    assert again.counters["wal_replayed"] == 0  # snapshot caught up
    again.close()


# ---------------------------------------------------------------------------
# corrupt-kind fault injection


def test_corrupt_fault_on_snapshot_quarantined_on_recover(tmp_path):
    root = tmp_path / "store"
    store = DurableStore.create(root, driver.initial_tombstoned(),
                                config=WalConfig(retain_snapshots=4))
    store.delete([7, 8])
    store.snapshot()  # good fallback snapshot
    store.delete([9])
    store.faults = FaultInjector().arm("snapshot", "corrupt")
    store.snapshot()  # byte-flipped while staged, then published
    live = store.index
    store.close()
    rec = DurableStore.recover(root)
    # checksums caught the flip: newest snapshot quarantined, fell back
    # to the previous good one + longer replay, landing on the live state
    assert rec.counters["quarantined_files"] == 1
    assert rec.counters["wal_replayed"] == 1
    assert_bit_identical(live, rec.index)
    q = [n for n in os.listdir(root / "quarantine")
         if n.startswith("snap-") and not n.endswith(".reason")]
    assert len(q) == 1
    rec.close()


def test_corrupt_fault_on_wal_quarantines_tail(tmp_path):
    root = tmp_path / "store"
    store = DurableStore.create(root, driver.initial_tombstoned())
    store.delete([7])
    store.delete([8])
    store.delete([9])
    # the corrupt fires BEFORE the next append: mid-log byte flip, then
    # the new record lands after it — a prefix survives, the rest is tail
    store.faults = FaultInjector().arm("wal_append", "corrupt")
    store.delete([10])
    store.close()
    records, good_end, problems = read_wal(root / "wal.log")
    assert problems and len(records) < 4
    rec = DurableStore.recover(root)
    assert rec.counters["quarantined_files"] == 1
    assert rec.counters["wal_replayed"] == len(records)
    # recovered == snapshot + surviving prefix, and the log is clean again
    clean, _, clean_problems = read_wal(root / "wal.log")
    assert clean_problems == [] and len(clean) == len(records)
    assert any(n.startswith("wal-tail-")
               for n in os.listdir(root / "quarantine"))
    rec.close()


def test_recover_requires_a_snapshot(tmp_path):
    with pytest.raises(Exception):
        DurableStore.recover(tmp_path / "nothing-here")
