"""Sparse solver tests — vs dense numpy references (the reference validates
eigsh against cupyx.scipy, ``pylibraft/tests/test_sparse.py``; SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse import CSR, COO
from raft_tpu.sparse.solver import eigsh, mst, svds


def _sym_sparse(rng, n, density=0.2, shift=0.0):
    d = rng.standard_normal((n, n)).astype(np.float32)
    mask = rng.random((n, n)) < density
    d = d * mask
    d = (d + d.T) / 2
    d = d + shift * np.eye(n, dtype=np.float32)
    return d


# -- Lanczos -----------------------------------------------------------------

def test_eigsh_smallest(rng):
    d = _sym_sparse(rng, 60, 0.3, shift=0.5)
    csr = CSR.from_dense(d)
    vals, vecs = eigsh(csr, k=4, which="SA", ncv=24, maxiter=600, tol=1e-6)
    want = np.sort(np.linalg.eigvalsh(d.astype(np.float64)))[:4]
    np.testing.assert_allclose(np.sort(np.asarray(vals)), want, rtol=2e-3, atol=2e-3)
    # residual check ||A v - lambda v||
    for i in range(4):
        v = np.asarray(vecs[:, i])
        lam = float(vals[i])
        assert np.linalg.norm(d @ v - lam * v) < 5e-2


def test_eigsh_largest(rng):
    d = _sym_sparse(rng, 50, 0.3)
    csr = CSR.from_dense(d)
    vals, _ = eigsh(csr, k=3, which="LA", ncv=25, maxiter=500, tol=1e-6)
    want = np.sort(np.linalg.eigvalsh(d.astype(np.float64)))[-3:]
    np.testing.assert_allclose(np.sort(np.asarray(vals)), want, rtol=2e-3, atol=2e-3)


def test_eigsh_laplacian_smallest_is_zero(rng):
    # graph Laplacian: smallest eigenvalue must be ~0
    from raft_tpu.sparse import compute_graph_laplacian

    a = (rng.random((30, 30)) < 0.3)
    a = np.triu(a, 1)
    a = (a | a.T).astype(np.float32)
    # make it connected
    for i in range(29):
        a[i, i + 1] = a[i + 1, i] = 1.0
    lap = compute_graph_laplacian(CSR.from_dense(a))
    vals, _ = eigsh(lap, k=2, which="SA", ncv=20, tol=1e-6)
    assert abs(float(vals[0])) < 1e-2


# -- randomized SVD ----------------------------------------------------------

def test_svds_matches_dense(rng):
    d = (rng.standard_normal((80, 40)) * (rng.random((80, 40)) < 0.3)).astype(np.float32)
    csr = CSR.from_dense(d)
    u, s, v = svds(csr, k=5, p=10, n_iters=6)
    want = np.linalg.svd(d.astype(np.float64), compute_uv=False)[:5]
    np.testing.assert_allclose(np.asarray(s), want, rtol=5e-3, atol=5e-3)
    # reconstruction on the top-5 subspace
    approx = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(v).T
    best = None
    uu, ss, vvt = np.linalg.svd(d.astype(np.float64))
    best = (uu[:, :5] * ss[:5]) @ vvt[:5]
    assert np.linalg.norm(approx - best) / max(np.linalg.norm(best), 1e-9) < 0.05


def test_svds_orthonormal_factors(rng):
    d = (rng.standard_normal((50, 30)) * (rng.random((50, 30)) < 0.4)).astype(np.float32)
    u, s, v = svds(CSR.from_dense(d), k=4)
    np.testing.assert_allclose(np.asarray(u.T @ u), np.eye(4), atol=1e-3)
    np.testing.assert_allclose(np.asarray(v.T @ v), np.eye(4), atol=1e-3)
    assert np.all(np.diff(np.asarray(s)) <= 1e-6)  # descending


def test_svds_sign_deterministic(rng):
    d = (rng.standard_normal((40, 25)) * (rng.random((40, 25)) < 0.4)).astype(np.float32)
    u1, _, v1 = svds(CSR.from_dense(d), k=3, seed=1, n_iters=8)
    u2, _, v2 = svds(CSR.from_dense(d), k=3, seed=2, n_iters=8)
    # different sketches converge to the same vectors with the same signs
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), atol=2e-2)


# -- MST ---------------------------------------------------------------------

def _mst_weight_reference(n, edges):
    """Kruskal on the host for ground truth."""
    parent = list(range(n))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    total, count = 0.0, 0
    for w, a, b in sorted(edges):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb
            total += w
            count += 1
    return total, count


def test_mst_path_graph():
    # path 0-1-2-3 with known weights: MST = all edges
    rows = [0, 1, 1, 2, 2, 3]
    cols = [1, 0, 2, 1, 3, 2]
    vals = [1.0, 1.0, 2.0, 2.0, 3.0, 3.0]
    g = COO.from_arrays(rows, cols, vals, (4, 4))
    result = mst(g)
    assert result.n_edges == 3
    assert float(jnp.sum(result.weight[: result.n_edges])) == 6.0
    assert len(set(np.asarray(result.color).tolist())) == 1


def test_mst_random_graph_weight(rng):
    n = 40
    d = rng.random((n, n)).astype(np.float32)
    mask = rng.random((n, n)) < 0.15
    d = d * mask
    d = np.triu(d, 1)
    for i in range(n - 1):  # ensure connected
        if d[i, i + 1] == 0:
            d[i, i + 1] = rng.random() + 0.5
    sym = d + d.T
    g = COO.from_dense(sym)
    result = mst(g)
    edges = [(float(sym[i, j]), i, j) for i in range(n) for j in range(i + 1, n)
             if sym[i, j] != 0]
    want_w, want_n = _mst_weight_reference(n, edges)
    assert result.n_edges == want_n == n - 1
    got_w = float(jnp.sum(result.weight[: result.n_edges]))
    np.testing.assert_allclose(got_w, want_w, rtol=1e-5)


def test_mst_forest_disconnected():
    # two disjoint triangles -> forest with 4 edges, 2 colors
    def tri(base):
        r, c, v = [], [], []
        for (a, b, w) in [(0, 1, 1.0), (1, 2, 2.0), (0, 2, 3.0)]:
            r += [base + a, base + b]
            c += [base + b, base + a]
            v += [w, w]
        return r, c, v

    r1, c1, v1 = tri(0)
    r2, c2, v2 = tri(3)
    g = COO.from_arrays(r1 + r2, c1 + c2, v1 + v2, (6, 6))
    result = mst(g)
    assert result.n_edges == 4
    assert float(jnp.sum(result.weight[: result.n_edges])) == 6.0
    assert len(set(np.asarray(result.color).tolist())) == 2
