"""IVF-Flat tests: recall vs exact brute force (the neighborhood_recall
metric is the north-star acceptance gauge, ``stats/neighborhood_recall.cuh:77``
parity), plus extend and the sharded path on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.random.datagen import make_blobs
from raft_tpu.stats.neighborhood import neighborhood_recall


@pytest.fixture(scope="module")
def blob_data():
    x, _ = make_blobs(jax.random.PRNGKey(0), n_samples=4000, n_features=32,
                      n_clusters=20, cluster_std=1.5)
    q = x[:200]
    return np.asarray(x), np.asarray(q)


def _recall(got_ids, want_ids):
    return float(neighborhood_recall(jnp.asarray(got_ids), jnp.asarray(want_ids)))


def test_ivf_flat_recall(blob_data):
    x, q = blob_data
    params = ivf_flat.IvfFlatIndexParams(n_lists=64, kmeans_n_iters=10,
                                         kmeans_trainset_fraction=0.5)
    index = ivf_flat.build(x, params)
    assert index.size == x.shape[0]  # every vector landed in a list
    _, want = brute_force.knn(q, x, 10)
    dist, got = ivf_flat.search(index, q, 10,
                                ivf_flat.IvfFlatSearchParams(n_probes=16))
    assert _recall(got, want) > 0.95
    # distances ascending
    d = np.asarray(dist)
    assert np.all(np.diff(d, axis=1) >= -1e-5)


def test_ivf_flat_full_probe_is_exact(blob_data):
    x, q = blob_data
    params = ivf_flat.IvfFlatIndexParams(n_lists=32, kmeans_n_iters=8,
                                         kmeans_trainset_fraction=0.5)
    index = ivf_flat.build(x, params)
    wd, want = brute_force.knn(q, x, 5)
    dist, got = ivf_flat.search(index, q, 5,
                                ivf_flat.IvfFlatSearchParams(n_probes=32))
    assert _recall(got, want) > 0.999
    np.testing.assert_allclose(np.asarray(dist), np.asarray(wd), rtol=1e-3,
                               atol=1e-2)


def test_ivf_flat_inner_product(blob_data):
    x, q = blob_data
    params = ivf_flat.IvfFlatIndexParams(n_lists=32, metric="inner_product",
                                         kmeans_trainset_fraction=0.5)
    index = ivf_flat.build(x, params)
    _, want = brute_force.knn(q, x, 10, metric="inner_product")
    _, got = ivf_flat.search(index, q, 10,
                             ivf_flat.IvfFlatSearchParams(n_probes=32))
    assert _recall(got, want) > 0.999


def test_ivf_flat_extend(blob_data):
    x, q = blob_data
    base, extra = x[:3000], x[3000:]
    params = ivf_flat.IvfFlatIndexParams(n_lists=48, kmeans_trainset_fraction=0.5,
                                         list_cap_ratio=3.0)
    index = ivf_flat.build(base, params)
    index = ivf_flat.extend(index, extra,
                            np.arange(3000, x.shape[0], dtype=np.int32))
    assert index.size == x.shape[0]
    _, want = brute_force.knn(q, x, 10)
    _, got = ivf_flat.search(index, q, 10,
                             ivf_flat.IvfFlatSearchParams(n_probes=24))
    assert _recall(got, want) > 0.9


def test_ivf_flat_sharded_matches_single(blob_data, mesh8):
    x, q = blob_data
    params = ivf_flat.IvfFlatIndexParams(n_lists=64, kmeans_n_iters=8,
                                         kmeans_trainset_fraction=0.5)
    index = ivf_flat.build_sharded(x, mesh8, params)
    _, want = brute_force.knn(q, x, 10)
    _, got = ivf_flat.search_sharded(index, q, 10,
                                     ivf_flat.IvfFlatSearchParams(n_probes=8),
                                     mesh=mesh8)
    # 8 probes per shard × 8 shards ≥ recall of 16 global probes
    assert _recall(got, want) > 0.95
