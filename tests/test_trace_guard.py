"""raft_tpu.core.trace_guard — runtime steady-state gates.

Two layers:

* unit tests for the :class:`TraceGuard` counters themselves (a cold
  jit call must register, a warm one must not, nesting composes);
* the hot-path regression gates this harness exists for — after warmup,
  the serve dispatch loop and every index family's ``search()`` must run
  with **zero jit cache misses and zero implicit host<->device
  transfers** (``jax.transfer_guard("disallow")`` raises on any implicit
  transfer even on CPU; the trace/compile census is backend-independent).

Operands are placed on device *before* entering a guard: creating an
array inside the region is itself an implicit transfer, and catching
exactly that class of accident is the point of the gate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import SteadyStateError, TraceGuard
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.serve import SearchServer, ServerConfig

# ---------------------------------------------------------------------------
# TraceGuard unit behavior


def test_cold_call_counts_trace_and_compile():
    f = jax.jit(lambda x: x * 3 + 1)
    x = jnp.ones((8,))
    with TraceGuard() as tg:
        f(x).block_until_ready()
    assert tg.traces >= 1
    assert tg.compiles >= 1
    with pytest.raises(SteadyStateError, match="not steady-state"):
        tg.assert_steady_state()


def test_warm_call_is_silent():
    f = jax.jit(lambda x: x - 2)
    x = jnp.ones((8,))
    f(x)  # warm outside the guard
    with TraceGuard() as tg:
        for _ in range(16):
            f(x)
    assert (tg.traces, tg.compiles) == (0, 0)
    tg.assert_steady_state()  # must not raise


def test_budgeted_assertion():
    f = jax.jit(lambda x: x / 7)
    x = jnp.ones((8,))
    with TraceGuard() as tg:
        f(x)
    tg.assert_steady_state(max_traces=tg.traces, max_compiles=tg.compiles)
    with pytest.raises(SteadyStateError):
        tg.assert_steady_state(max_traces=tg.traces - 1,
                               max_compiles=tg.compiles)


def test_nested_guards_both_observe():
    f = jax.jit(lambda x: x + 11)
    x = jnp.ones((8,))
    with TraceGuard() as outer:
        with TraceGuard() as inner:
            f(x)
    assert outer.traces == inner.traces >= 1
    assert outer.compiles == inner.compiles >= 1


def test_guard_reports_transfer_violations():
    # creating an array from a Python constant inside the region is an
    # implicit host->device transfer: "disallow" must raise even on CPU
    with pytest.raises(Exception, match="[Dd]isallow"):
        with TraceGuard():
            jnp.ones((4,)).block_until_ready()
    # the same region under "allow" is fine (counters still run)
    with TraceGuard(transfer="allow") as tg:
        jnp.ones((4,)).block_until_ready()
    assert tg.transfer == "allow"


# ---------------------------------------------------------------------------
# hot-path gates

N, D, K = 192, 16, 4


@pytest.fixture(scope="module")
def db():
    return np.random.default_rng(7).standard_normal((N, D)).astype(np.float32)


def test_server_steady_state_200_mixed_requests(db):
    """The acceptance gate: after warmup, 200 mixed-shape requests through
    the serve loop with zero traces, zero compiles, zero implicit
    transfers — the AOT ladder plus explicit device_put/device_get must
    cover the entire dispatch path."""
    ladder = (1, 8, 64)
    srv = SearchServer(db, k=K, config=ServerConfig(ladder=ladder))
    assert srv.warmup() == len(ladder)
    rng = np.random.default_rng(11)
    requests = [rng.standard_normal((int(rng.integers(1, 40)), D))
                .astype(np.float32) for _ in range(200)]
    futs = []
    with TraceGuard() as tg:
        for q in requests:
            futs.append((q, srv.submit(q)))
            while len(srv._pending) >= 32:
                srv.step()
        while srv.step():
            pass
    tg.assert_steady_state()
    assert srv.cache.compiles == len(ladder)  # warmup only
    for q, fut in futs:
        d, i = fut.result(timeout=0)
        assert i.shape == (q.shape[0], K)
    assert srv.metrics.completed == 200


@pytest.fixture(scope="module")
def family_searches(db):
    """(description, zero-arg warm-callable) per family; queries live on
    device before any guard is entered."""
    q = jax.device_put(np.random.default_rng(8)
                       .standard_normal((7, D)).astype(np.float32))
    dbd = jax.device_put(db)
    fi = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=6))
    fp = ivf_flat.IvfFlatSearchParams(n_probes=3)
    pi = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(n_lists=6, pq_dim=8,
                                                  pq_bits=4))
    pp = ivf_pq.IvfPqSearchParams(n_probes=3)
    ci = cagra.build(db, cagra.CagraIndexParams(graph_degree=8))
    cp = cagra.CagraSearchParams(itopk_size=16)
    return {
        "brute_force": lambda: brute_force.knn(q, dbd, k=K),
        "ivf_flat": lambda: ivf_flat.search(fi, q, K, params=fp),
        "ivf_pq": lambda: ivf_pq.search(pi, q, K, params=pp),
        "cagra": lambda: cagra.search(ci, q, K, params=cp),
    }


@pytest.mark.parametrize("family", ["brute_force", "ivf_flat", "ivf_pq",
                                    "cagra"])
def test_family_search_steady_state(family_searches, family):
    """Repeated ``search()`` on a warm index: zero jit cache misses and
    clean under ``transfer_guard("disallow")`` for every family."""
    search = family_searches[family]
    d, i = search()  # warm: first call may trace/compile freely
    jax.block_until_ready((d, i))
    with TraceGuard() as tg:
        for _ in range(3):
            d2, i2 = search()
        jax.block_until_ready((d2, i2))
    tg.assert_steady_state()
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i2))
