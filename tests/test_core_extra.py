"""Tests for core parity additions: resources manager, memory accounting,
mdbuffer dispatch (SURVEY.md §2.1 rows: device_resources_manager,
memory accounting, mdbuffer + dispatcher)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core.buffer import MDBuffer, memory_type, memory_type_dispatcher
from raft_tpu.core.memory import (MemoryTracker, analyze_memory,
                                  device_memory_stats, live_bytes)
from raft_tpu.core.resources_manager import DeviceResourcesManager, get_device_resources


class TestResourcesManager:
    def test_pooled_handles_are_shared(self):
        a = get_device_resources()
        b = get_device_resources()
        assert a is b

    def test_per_device_handles_distinct_seeds(self):
        mgr = DeviceResourcesManager()
        mgr.set_seed(100)
        h0 = mgr.get_device_resources(0)
        h1 = mgr.get_device_resources(1)
        assert h0 is not h1
        k0, k1 = h0.rng_key(), h1.rng_key()
        assert not np.array_equal(np.asarray(k0), np.asarray(k1))
        assert len(h0.devices) == 1 and len(h1.devices) == 1

    def test_settings_before_first_use(self):
        mgr = DeviceResourcesManager()
        mgr.set_workspace_limit(1 << 20)
        h = mgr.get_device_resources()
        from raft_tpu.core.resources import get_workspace_limit

        assert get_workspace_limit(h) == 1 << 20

    def test_late_setting_keeps_old_handles(self):
        mgr = DeviceResourcesManager()
        h = mgr.get_device_resources()
        mgr.set_seed(7)  # logs a warning, must not rebuild vended handles
        assert mgr.get_device_resources() is h

    def test_mesh_axes(self):
        mgr = DeviceResourcesManager()
        mgr.set_mesh_axes(("replica", "shard"))
        h = mgr.get_device_resources()
        assert h.mesh.axis_names == ("replica", "shard")


class TestMemory:
    def test_analyze_memory_static(self):
        ma = analyze_memory(lambda x: jnp.dot(x, x.T), jnp.zeros((64, 32)))
        assert ma.argument_size >= 64 * 32 * 4
        assert ma.output_size >= 64 * 64 * 4
        assert ma.peak_estimate >= ma.argument_size

    def test_tracker_counts_growth(self):
        with MemoryTracker() as mt:
            keep = jax.block_until_ready(jnp.zeros((128, 128), jnp.float32))
        assert mt.allocated_delta >= 128 * 128 * 4
        del keep

    def test_stats_and_live_bytes_run(self):
        assert live_bytes() >= 0
        assert isinstance(device_memory_stats(), dict)


class TestMDBuffer:
    def test_memory_type(self):
        assert memory_type(np.zeros(3)) == "host"
        assert memory_type(jnp.zeros(3)) == "device"

    def test_lazy_single_conversion(self):
        buf = MDBuffer(np.arange(6, dtype=np.float32))
        d1 = buf.device()
        d2 = buf.device()
        assert d1 is d2
        np.testing.assert_array_equal(buf.host(), np.arange(6, dtype=np.float32))

    def test_device_origin_host_view(self):
        buf = MDBuffer(jnp.arange(4))
        assert buf.memory_type == "device"
        np.testing.assert_array_equal(buf.host(), np.arange(4))

    def test_dispatcher_routes_by_residency(self):
        host_called, dev_called = [], []
        memory_type_dispatcher(lambda a: host_called.append(type(a)),
                               lambda a: dev_called.append(type(a)),
                               np.zeros(2))
        assert host_called and not dev_called
        memory_type_dispatcher(lambda a: host_called.clear(),
                               lambda a: dev_called.append(type(a)),
                               jnp.zeros(2))
        assert dev_called

    def test_dispatcher_prefer_forces_conversion(self):
        out = memory_type_dispatcher(lambda a: "host", lambda a: "device",
                                     np.zeros(2), prefer="device")
        assert out == "device"

    def test_unknown_memory_type(self):
        with pytest.raises(ValueError):
            MDBuffer(np.zeros(1)).view("managed")


class TestLayoutCopy:
    """``raft::copy`` parity (``core/copy.hpp``): layout/memory/dtype moves."""

    def test_f_order_host_to_device_preserves_values(self):
        from raft_tpu.core import copy
        f = np.asfortranarray(np.arange(12, dtype=np.float32).reshape(3, 4))
        d = copy(f, memory="device")
        assert isinstance(d, jax.Array)
        np.testing.assert_array_equal(np.asarray(d), f)

    def test_device_to_host_f_layout(self):
        from raft_tpu.core import copy
        d = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
        h = copy(d, memory="host", layout="F")
        assert h.flags.f_contiguous and not h.flags.c_contiguous
        np.testing.assert_array_equal(h, np.asarray(d))

    def test_host_layout_transposing_copy(self):
        from raft_tpu.core import copy
        c = np.arange(6, dtype=np.float64).reshape(2, 3)
        f = copy(c, layout="F")
        assert f.flags.f_contiguous
        back = copy(f, layout="C")
        assert back.flags.c_contiguous
        np.testing.assert_array_equal(back, c)

    def test_dtype_conversion_and_noop_fast_path(self):
        from raft_tpu.core import copy
        d = jnp.arange(4, dtype=jnp.float32)
        assert copy(d) is d  # nothing requested → no copy
        h = copy(d, memory="host", dtype=np.float64)
        assert h.dtype == np.float64

    def test_device_f_layout_rejected(self):
        from raft_tpu.core import copy
        with pytest.raises(Exception):
            copy(np.zeros((2, 2)), memory="device", layout="F")

    def test_strided_host_source_normalized(self):
        from raft_tpu.core import copy
        base = np.arange(24, dtype=np.float32).reshape(4, 6)
        view = base[::2, ::3]  # non-contiguous strides
        d = copy(view, memory="device")
        np.testing.assert_array_equal(np.asarray(d), view)
