"""raft_tpu.analysis.jaxlint — seeded-violation fixtures + tree gate.

Three layers:

* one fixture per rule proving it FIRES on a minimal violation and goes
  quiet when the hazard is written the blessed way (the good/bad pairs
  mirror ``docs/jax_hygiene.md``);
* the waiver contract: a ``# jaxlint: disable=<CODE> reason`` comment
  waives exactly that code on that line, a bare ``disable=`` is itself a
  finding (JXW0), and waivers carry their reason into the report;
* the tier-1 tree gate: ``raft_tpu/`` scans to **zero unwaived
  findings**, and every waiver in the tree has a written reason — the
  same contract ``python scripts/mini_lint.py --jax raft_tpu`` enforces
  in CI.

jaxlint itself is pure stdlib — ``scripts/mini_lint.py`` loads it by
file path so linting never imports jax (the package import here goes
through ``raft_tpu/__init__``, which does).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from raft_tpu.analysis import ALL_RULES, scan_source, scan_tree

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(findings, only_active=True):
    return [f.code for f in findings if not (only_active and f.waived)]


def scan(src, rel="raft_tpu/somelib.py"):
    """Scan a snippet as if it lived in library (non-exempt) code."""
    return scan_source(src, rel, rel)


# ---------------------------------------------------------------------------
# JX01 — host sync in library code


def test_jx01_fires_on_sync_sinks():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    r = jnp.sum(x)\n"
        "    a = float(r)\n"
        "    b = r.item()\n"
        "    c = np.asarray(r)\n"
        "    d = jax.device_get(r)\n"
        "    return a, b, c, d\n")
    assert codes(scan(src)) == ["JX01"] * 4


def test_jx01_quiet_on_device_and_static_values():
    src = (
        "import jax.numpy as jnp\n"
        "def f(x, cfg):\n"
        "    r = jnp.sum(x)                  # stays on device\n"
        "    n = int(x.shape[0])             # static metadata, not traced\n"
        "    lim = float(cfg.tolerance)      # plain host value\n"
        "    return jnp.where(r > lim, r, 0.0), n\n")
    assert codes(scan(src)) == []


def test_jx01_exempt_at_host_boundary():
    src = ("import jax.numpy as jnp\n"
           "def fetch(x):\n"
           "    return float(jnp.sum(x))\n")
    assert codes(scan(src, rel="raft_tpu/serve/server.py")) == []
    assert codes(scan(src, rel="raft_tpu/io/reader.py")) == []
    assert codes(scan(src, rel="tests/test_thing.py")) == []
    assert codes(scan(src, rel="raft_tpu/stats/metrics.py")) == ["JX01"]


# ---------------------------------------------------------------------------
# JX02 — recompilation hazards


def test_jx02_fires_on_traced_branch_inside_jit():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    s = jnp.sum(x)\n"
        "    if s > 0:\n"
        "        return x\n"
        "    return -x\n")
    assert codes(scan(src)) == ["JX02"]


def test_jx02_quiet_on_lax_cond_and_static_branch():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x, flag=None):\n"
        "    if flag is None:                # `is None` is static dispatch\n"
        "        return x\n"
        "    return jax.lax.cond(jnp.sum(x) > 0, lambda v: v,\n"
        "                        lambda v: -v, x)\n")
    assert codes(scan(src)) == []


def test_jx02_fires_on_jit_per_call_and_jit_in_loop():
    src = (
        "import jax\n"
        "def f(xs, g):\n"
        "    out = jax.jit(g)(xs[0])\n"
        "    fns = []\n"
        "    for _ in range(3):\n"
        "        fns.append(jax.jit(g))\n"
        "    return out, fns\n")
    assert codes(scan(src)) == ["JX02", "JX02"]


def test_jx02_quiet_on_def_site_jit():
    src = ("import jax\n"
           "@jax.jit\n"
           "def g(x):\n"
           "    return x + 1\n"
           "def f(xs):\n"
           "    return [g(x) for x in xs]\n")
    assert codes(scan(src)) == []


# ---------------------------------------------------------------------------
# JX03 — float64 leaks


def test_jx03_fires_on_float64_request():
    src = ("import jax.numpy as jnp\n"
           "import numpy as np\n"
           "def f(x):\n"
           "    return jnp.zeros((4,), jnp.float64) + np.float64(0)\n")
    assert codes(scan(src)) == ["JX03", "JX03"]


def test_jx03_quiet_under_x64_gate_and_on_f32():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    if jax.config.jax_enable_x64:\n"
        "        acc = jnp.float64\n"
        "    else:\n"
        "        acc = jnp.float32\n"
        "    return jnp.zeros((4,), acc), jnp.ones((4,), jnp.float32)\n")
    assert codes(scan(src)) == []


# ---------------------------------------------------------------------------
# JX04 — impure host calls inside jit


def test_jx04_fires_on_np_random_and_time_inside_jit():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "import time\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    noise = np.random.normal(size=4)\n"
        "    t = time.perf_counter()\n"
        "    return x + noise, t\n")
    assert codes(scan(src)) == ["JX04", "JX04"]


def test_jx04_quiet_outside_jit_and_with_jax_random():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "def make_data():\n"
        "    return np.random.normal(size=4)   # host-side setup: fine\n"
        "@jax.jit\n"
        "def f(x, key):\n"
        "    return x + jax.random.normal(key, (4,))\n")
    assert codes(scan(src)) == []


# ---------------------------------------------------------------------------
# JX05 — completion barriers in library code


def test_jx05_fires_in_library_quiet_in_serve_bench():
    src = ("def f(x):\n"
           "    return x.block_until_ready()\n")
    assert codes(scan(src)) == ["JX05"]
    assert codes(scan(src, rel="raft_tpu/serve/server.py")) == []
    assert codes(scan(src, rel="bench/serve.py")) == []
    assert codes(scan(src, rel="scripts/driver.py")) == []


# ---------------------------------------------------------------------------
# waiver contract


@pytest.mark.parametrize("code,bad_line", [
    ("JX01", "    return float(jnp.sum(x))"),
    ("JX05", "    return x.block_until_ready()"),
])
def test_waiver_silences_exactly_its_code(code, bad_line):
    src = "import jax.numpy as jnp\ndef f(x):\n" + bad_line + "\n"
    assert codes(scan(src)) == [code]
    waived = src.replace(
        bad_line, bad_line + f"  # jaxlint: disable={code} measured, one"
        " sync per call is the contract")
    out = scan(waived)
    assert codes(out) == []
    w = [f for f in out if f.waived]
    assert len(w) == 1 and w[0].code == code
    assert "measured" in w[0].reason
    # the waiver names a DIFFERENT code: the finding stays active
    wrong = src.replace(bad_line,
                        bad_line + "  # jaxlint: disable=JX03 mismatched")
    assert codes(scan(wrong)) == [code]


def test_waiver_on_multiline_statement_end_line():
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    return float(jnp.sum(x)\n"
           "                 + 1.0)  # jaxlint: disable=JX01 spans lines\n")
    out = scan(src)
    assert codes(out) == []
    assert [f.code for f in out if f.waived] == ["JX01"]


def test_bare_waiver_is_jxw0():
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    return float(jnp.sum(x))  # jaxlint: disable=JX01\n")
    out = scan(src)
    assert codes(out) == ["JXW0"]  # the JX01 itself is waived...
    assert [f.code for f in out if f.waived] == ["JX01"]
    # ...but the reasonless waiver is an unwaivable finding of its own


def test_syntax_error_reports_jx99():
    out = scan("def broken(:\n")
    assert [f.code for f in out] == ["JX99"]


# ---------------------------------------------------------------------------
# the tree gate


def test_rule_catalog_is_complete():
    assert set(ALL_RULES) == {"JX01", "JX02", "JX03", "JX04", "JX05", "JXW0"}


def test_tree_scan_zero_unwaived_and_reasons_written():
    rep = scan_tree(os.path.join(REPO, "raft_tpu"))
    assert rep.files > 100
    assert rep.findings == [], [
        f"{f.path}:{f.line} {f.code} {f.msg}" for f in rep.findings]
    for f in rep.waived:
        assert f.reason, f"bare waiver at {f.path}:{f.line}"
    stats = rep.stats()
    assert stats["unwaived_findings"] == 0
    assert stats["waiver_total"] == len(rep.waived)
    assert stats["rule_catalog"] == ALL_RULES


def test_mini_lint_jax_entry_point_exits_zero(tmp_path):
    """The CI command: one lint entry point, one exit-code contract, and
    the stats artifact lands where bench/JAXLINT.json is committed from."""
    stats = tmp_path / "JAXLINT.json"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mini_lint.py"),
         "--jax", os.path.join(REPO, "raft_tpu"),
         "--stats-json", str(stats)],
        capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    blob = json.loads(stats.read_text())
    assert blob["tool"] == "jaxlint"
    assert blob["unwaived_findings"] == 0
    assert blob["files_scanned"] > 100
