"""pylibraft-compatible surface (raft_tpu.compat.pylibraft): upstream
module paths, names, and call conventions keep working."""

import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")


def test_module_layout_matches_upstream():
    from raft_tpu.compat import pylibraft
    from raft_tpu.compat.pylibraft.common import Handle, DeviceResources, device_ndarray
    from raft_tpu.compat.pylibraft.sparse.linalg import eigsh, svds
    from raft_tpu.compat.pylibraft.random import rmat
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    assert pylibraft.__version__.endswith("+tpu")
    assert Handle is DeviceResources  # deprecated alias, as upstream


def test_eigsh_scipy_input_matches_dense_eig():
    from raft_tpu.compat.pylibraft.sparse.linalg import eigsh
    rng = np.random.default_rng(0)
    m = rng.standard_normal((60, 60)).astype(np.float32)
    a = (m + m.T) / 2
    a[np.abs(a) < 0.8] = 0.0
    sp = scipy_sparse.csr_matrix(a)
    w, v = eigsh(sp, k=4, which="SA", maxiter=500)
    ref = np.sort(np.linalg.eigvalsh(a))[:4]
    np.testing.assert_allclose(np.sort(np.asarray(w)), ref, atol=2e-2)


def test_svds_scipy_input():
    from raft_tpu.compat.pylibraft.sparse.linalg import svds
    rng = np.random.default_rng(1)
    a = rng.standard_normal((50, 30)).astype(np.float32)
    a[np.abs(a) < 1.0] = 0.0
    u, s, v = svds(scipy_sparse.csr_matrix(a), k=3)
    ref = np.linalg.svd(a, compute_uv=False)[:3]
    np.testing.assert_allclose(np.asarray(s), ref, rtol=0.1)


def test_rmat_out_param():
    from raft_tpu.compat.pylibraft.random import rmat
    out = np.zeros((500, 2), np.int64)
    ret = rmat(out, np.array([0.57, 0.19, 0.19, 0.05] * 5, np.float32), 5, 5,
               seed=7)
    assert ret is out
    assert out.min() >= 0 and out.max() < 32


def test_pairwise_distance_out_param():
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    rng = np.random.default_rng(2)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((6, 4)).astype(np.float32)
    out = np.zeros((8, 6), np.float32)
    ret = pairwise_distance(x, y, out=out, metric="sqeuclidean")
    assert ret is out
    import scipy.spatial.distance as spd
    np.testing.assert_allclose(out, spd.cdist(x, y, "sqeuclidean"),
                               rtol=1e-4, atol=1e-4)


def test_device_ndarray_roundtrip():
    from raft_tpu.compat.pylibraft.common import device_ndarray
    a = device_ndarray.empty((3, 4), np.float32)
    assert a.shape == (3, 4) and a.dtype == np.float32
    # 64-bit dtypes follow JAX's x64 policy (stored as 32-bit by default)
    b64 = device_ndarray.empty((2,), np.float64)
    assert b64.dtype in (np.float32, np.float64)
    h = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = device_ndarray(h)
    np.testing.assert_array_equal(b.copy_to_host(), h)
    np.testing.assert_array_equal(np.asarray(b), h)


def test_handle_sync():
    from raft_tpu.compat.pylibraft.common import Handle
    h = Handle()
    h.sync()  # no-op barrier must not raise


def test_out_param_device_ndarray_filled_in_place():
    """Upstream's canonical usage passes a device array as out — the fill
    must land in the caller's object, not a host copy."""
    from raft_tpu.compat.pylibraft.common import device_ndarray
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    rng = np.random.default_rng(3)
    x = rng.standard_normal((5, 4)).astype(np.float32)
    out = device_ndarray.empty((5, 5), np.float32)
    ret = pairwise_distance(x, out=out, metric="sqeuclidean")
    assert ret is out
    assert float(np.abs(out.copy_to_host()).sum()) > 0


def test_taxicab_metric_accepted():
    from raft_tpu.compat.pylibraft.distance import DISTANCE_TYPES, pairwise_distance
    assert "taxicab" in DISTANCE_TYPES
    x = np.asarray([[0.0, 0.0], [1.0, 2.0]], np.float32)
    d = np.asarray(pairwise_distance(x, metric="taxicab"))
    np.testing.assert_allclose(d[0, 1], 3.0, rtol=1e-6)


def test_f_order_empty_rejected():
    from raft_tpu.compat.pylibraft.common import device_ndarray
    with pytest.raises(ValueError):
        device_ndarray.empty((2, 2), order="F")


def test_handle_sync_accepts_arrays():
    from raft_tpu.compat.pylibraft.common import Handle
    import jax.numpy as jnp
    Handle().sync(jnp.zeros(3))  # per-buffer sync path kept from core


def test_output_conversion_policy():
    from raft_tpu.compat.pylibraft import config
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    x = np.eye(4, dtype=np.float32)
    try:
        config.set_output_as("numpy")
        assert isinstance(pairwise_distance(x), np.ndarray)
        config.set_output_as(lambda a: "custom")
        assert pairwise_distance(x) == "custom"
        with pytest.raises(ValueError):
            config.set_output_as("cupy")  # no CUDA on TPU builds
    finally:
        config.set_output_as("raft")
    import jax
    assert isinstance(pairwise_distance(x), jax.Array)


def test_output_conversion_torch():
    torch = pytest.importorskip("torch")
    from raft_tpu.compat.pylibraft import config
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    x = np.eye(4, dtype=np.float32)
    try:
        config.set_output_as("torch")
        t = pairwise_distance(x)
        assert isinstance(t, torch.Tensor)
        before = np.asarray(pairwise_distance(x.copy()))
        t.add_(1.0)  # must not corrupt JAX's cached host buffer (copy made)
        after = np.asarray(pairwise_distance(x.copy()))
        np.testing.assert_array_equal(before, after)
    finally:
        config.set_output_as("raft")


def test_interruptible_surface():
    from raft_tpu.compat.pylibraft.common import interruptible
    interruptible.clear()
    interruptible.cancel()
    with pytest.raises(interruptible.InterruptedException):
        interruptible.synchronize()
    interruptible.synchronize()  # flag auto-cleared on raise


def test_neighbors_upstream_convention_end_to_end():
    """The pre-cuVS pylibraft.neighbors flow: params-first build/search,
    handle= accepted, refine composes."""
    from raft_tpu.compat.pylibraft.neighbors import cagra, ivf_pq, refine

    rng = np.random.default_rng(1)
    x = (rng.standard_normal((600, 16)) +
         4 * rng.standard_normal((20, 16))[rng.integers(0, 20, 600)]
         ).astype(np.float32)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8), x, handle=object())
    d, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, x[:8], 20)
    d2, found = refine(x, x[:8], cand, 5)
    assert (np.asarray(found)[:, 0] == np.arange(8)).all()

    g = cagra.build(cagra.IndexParams(intermediate_graph_degree=16,
                                      graph_degree=8,
                                      build_algo="nn_descent"), x)
    _, gi = cagra.search(cagra.SearchParams(itopk_size=32, search_width=4),
                         g, x[:8], 5)
    assert (np.asarray(gi)[:, 0] == np.arange(8)).all()


def test_neighbors_lut_dtype_selects_lut_tier():
    from raft_tpu.compat.pylibraft.neighbors import ivf_pq

    rng = np.random.default_rng(2)
    x = rng.standard_normal((400, 16)).astype(np.float32)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8), x)
    # fp8-style LUT request routes to the code-resident tier and still works
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=8, lut_dtype="float16"),
                         idx, x[:4], 3)
    assert np.asarray(i).shape == (4, 3)


def test_neighbors_add_data_on_build_false():
    from raft_tpu.compat.pylibraft.neighbors import ivf_flat

    rng = np.random.default_rng(3)
    x = rng.standard_normal((300, 8)).astype(np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8,
                                              add_data_on_build=False), x)
    assert int(np.asarray(idx.counts).sum()) == 0
    idx = ivf_flat.extend(idx, x, np.arange(300))
    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx, x[:6], 1)
    assert (np.asarray(i)[:, 0] == np.arange(6)).all()  # no duplicates


def test_neighbors_add_data_on_build_false_ivf_pq():
    """The empty index must be empty in EVERY search tier: the recon slab
    built from the training dataset must not survive ``_clear_lists``
    (ADVICE r3: stale slab returned finite recon-mode distances)."""
    from raft_tpu.compat.pylibraft.neighbors import ivf_pq

    rng = np.random.default_rng(5)
    x = rng.standard_normal((300, 16)).astype(np.float32)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8,
                                          add_data_on_build=False), x)
    assert int(np.asarray(idx.counts).sum()) == 0
    # recon-mode search on the empty index: every slot masked
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, x[:4], 3)
    assert (np.asarray(i) == -1).all()
    assert not np.isfinite(np.asarray(d)).any()
    # extend then search: results come from the extended rows only
    idx = ivf_pq.extend(idx, x, np.arange(300))
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, x[:6], 1)
    assert (np.asarray(i)[:, 0] == np.arange(6)).all()


def test_common_input_validation():
    from raft_tpu.compat.pylibraft.common import input_validation as iv
    import jax.numpy as jnp

    a = np.zeros((4, 3), np.float32)
    b = jnp.ones((4, 3), jnp.float32)
    assert iv.do_dtypes_match(a, b) and iv.do_shapes_match(a, b)
    assert iv.do_rows_match(a, b) and iv.do_cols_match(a, b)
    assert not iv.do_dtypes_match(a, a.astype(np.int32))
    assert not iv.do_rows_match(a, np.zeros((5, 3), np.float32))
    assert iv.is_c_contiguous(a) and iv.is_c_contiguous(b)
    assert not iv.is_c_contiguous(np.asfortranarray(np.zeros((4, 3))))


def test_common_mdspan_roundtrip():
    from raft_tpu.compat.pylibraft.common.mdspan import (
        run_roundtrip_test_for_mdspan)

    run_roundtrip_test_for_mdspan(
        np.arange(12, dtype=np.float32).reshape(3, 4))
    run_roundtrip_test_for_mdspan(
        np.arange(12, dtype=np.int64).reshape(3, 4), fortran_order=True)


def test_neighbors_out_params_filled():
    from raft_tpu.compat.pylibraft.neighbors import brute_force

    rng = np.random.default_rng(4)
    x = rng.standard_normal((80, 8)).astype(np.float32)
    iout = np.zeros((4, 3), np.int32)
    dout = np.zeros((4, 3), np.float32)
    d, i = brute_force.knn(x, x[:4], 3, iout, dout)
    assert i is iout and d is dout
    assert (iout[:, 0] == np.arange(4)).all()


def test_neighbors_serving_adapter():
    """serving.Server speaks the params-first convention over compat
    indexes/params and serves bit-identical results."""
    from raft_tpu.compat.pylibraft.neighbors import ivf_flat, serving
    from raft_tpu.serve import ServerConfig

    rng = np.random.default_rng(5)
    x = rng.standard_normal((300, 12)).astype(np.float32)
    sp = ivf_flat.SearchParams(n_probes=6)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=6), x, handle=object())
    d0, i0 = ivf_flat.search(sp, idx, x[:5], 4)
    with serving.Server(sp, idx, 4,
                        config=ServerConfig(ladder=(2, 8))) as srv:
        d, i = srv.search(x[:5])
        snap = srv.metrics()
    np.testing.assert_array_equal(np.asarray(i0), i)
    np.testing.assert_array_equal(np.asarray(d0), d)
    assert snap["completed"] == 1 and snap["cache"]["compiles"] == 2


def test_neighbors_extend_parity_with_native_online_insert():
    """compat ``extend`` rides the native online-insert path: growing a
    LIVE index through the adapter matches the native ``extend`` (and a
    from-scratch rebuild of the union) bit-for-bit, for both families."""
    from raft_tpu.compat.pylibraft.neighbors import ivf_flat as c_flat
    from raft_tpu.compat.pylibraft.neighbors import ivf_pq as c_pq
    from raft_tpu.neighbors import ivf_flat as n_flat
    from raft_tpu.neighbors import ivf_pq as n_pq

    rng = np.random.default_rng(9)
    x = rng.standard_normal((260, 16)).astype(np.float32)
    more = rng.standard_normal((40, 16)).astype(np.float32)

    built = c_flat.build(c_flat.IndexParams(n_lists=8), x)
    via_compat = c_flat.extend(built, more, np.arange(260, 300))
    via_native = n_flat.extend(built, more, np.arange(260, 300))
    sp_c, sp_n = c_flat.SearchParams(n_probes=8), \
        n_flat.IvfFlatSearchParams(n_probes=8)
    d0, i0 = c_flat.search(sp_c, via_compat, x[:7], 5)
    d1, i1 = n_flat.search(via_native, x[:7], 5, sp_n)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    # auto-assigned ids continue from the current size, upstream-style
    auto = c_flat.extend(built, more)
    assert int(np.asarray(auto.counts).sum()) == 300
    d2, i2 = c_flat.search(sp_c, auto, more[:4], 1)
    assert (np.asarray(i2)[:, 0] >= 260).all()

    pq = c_pq.build(c_pq.IndexParams(n_lists=8, pq_dim=8), x)
    pq_c = c_pq.extend(pq, more, np.arange(260, 300))
    pq_n = n_pq.extend(pq, more, np.arange(260, 300))
    d3, i3 = c_pq.search(c_pq.SearchParams(n_probes=8), pq_c, x[:7], 5)
    d4, i4 = n_pq.search(pq_n, x[:7], 5, n_pq.IvfPqSearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i3), np.asarray(i4))
    np.testing.assert_array_equal(np.asarray(d3), np.asarray(d4))
