"""pylibraft-compatible surface (raft_tpu.compat.pylibraft): upstream
module paths, names, and call conventions keep working."""

import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")


def test_module_layout_matches_upstream():
    from raft_tpu.compat import pylibraft
    from raft_tpu.compat.pylibraft.common import Handle, DeviceResources, device_ndarray
    from raft_tpu.compat.pylibraft.sparse.linalg import eigsh, svds
    from raft_tpu.compat.pylibraft.random import rmat
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    assert pylibraft.__version__.endswith("+tpu")
    assert Handle is DeviceResources  # deprecated alias, as upstream


def test_eigsh_scipy_input_matches_dense_eig():
    from raft_tpu.compat.pylibraft.sparse.linalg import eigsh
    rng = np.random.default_rng(0)
    m = rng.standard_normal((60, 60)).astype(np.float32)
    a = (m + m.T) / 2
    a[np.abs(a) < 0.8] = 0.0
    sp = scipy_sparse.csr_matrix(a)
    w, v = eigsh(sp, k=4, which="SA", maxiter=500)
    ref = np.sort(np.linalg.eigvalsh(a))[:4]
    np.testing.assert_allclose(np.sort(np.asarray(w)), ref, atol=2e-2)


def test_svds_scipy_input():
    from raft_tpu.compat.pylibraft.sparse.linalg import svds
    rng = np.random.default_rng(1)
    a = rng.standard_normal((50, 30)).astype(np.float32)
    a[np.abs(a) < 1.0] = 0.0
    u, s, v = svds(scipy_sparse.csr_matrix(a), k=3)
    ref = np.linalg.svd(a, compute_uv=False)[:3]
    np.testing.assert_allclose(np.asarray(s), ref, rtol=0.1)


def test_rmat_out_param():
    from raft_tpu.compat.pylibraft.random import rmat
    out = np.zeros((500, 2), np.int64)
    ret = rmat(out, np.array([0.57, 0.19, 0.19, 0.05] * 5, np.float32), 5, 5,
               seed=7)
    assert ret is out
    assert out.min() >= 0 and out.max() < 32


def test_pairwise_distance_out_param():
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    rng = np.random.default_rng(2)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((6, 4)).astype(np.float32)
    out = np.zeros((8, 6), np.float32)
    ret = pairwise_distance(x, y, out=out, metric="sqeuclidean")
    assert ret is out
    import scipy.spatial.distance as spd
    np.testing.assert_allclose(out, spd.cdist(x, y, "sqeuclidean"),
                               rtol=1e-4, atol=1e-4)


def test_device_ndarray_roundtrip():
    from raft_tpu.compat.pylibraft.common import device_ndarray
    a = device_ndarray.empty((3, 4), np.float32)
    assert a.shape == (3, 4) and a.dtype == np.float32
    # 64-bit dtypes follow JAX's x64 policy (stored as 32-bit by default)
    b64 = device_ndarray.empty((2,), np.float64)
    assert b64.dtype in (np.float32, np.float64)
    h = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = device_ndarray(h)
    np.testing.assert_array_equal(b.copy_to_host(), h)
    np.testing.assert_array_equal(np.asarray(b), h)


def test_handle_sync():
    from raft_tpu.compat.pylibraft.common import Handle
    h = Handle()
    h.sync()  # no-op barrier must not raise


def test_out_param_device_ndarray_filled_in_place():
    """Upstream's canonical usage passes a device array as out — the fill
    must land in the caller's object, not a host copy."""
    from raft_tpu.compat.pylibraft.common import device_ndarray
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    rng = np.random.default_rng(3)
    x = rng.standard_normal((5, 4)).astype(np.float32)
    out = device_ndarray.empty((5, 5), np.float32)
    ret = pairwise_distance(x, out=out, metric="sqeuclidean")
    assert ret is out
    assert float(np.abs(out.copy_to_host()).sum()) > 0


def test_taxicab_metric_accepted():
    from raft_tpu.compat.pylibraft.distance import DISTANCE_TYPES, pairwise_distance
    assert "taxicab" in DISTANCE_TYPES
    x = np.asarray([[0.0, 0.0], [1.0, 2.0]], np.float32)
    d = np.asarray(pairwise_distance(x, metric="taxicab"))
    np.testing.assert_allclose(d[0, 1], 3.0, rtol=1e-6)


def test_f_order_empty_rejected():
    from raft_tpu.compat.pylibraft.common import device_ndarray
    with pytest.raises(ValueError):
        device_ndarray.empty((2, 2), order="F")


def test_handle_sync_accepts_arrays():
    from raft_tpu.compat.pylibraft.common import Handle
    import jax.numpy as jnp
    Handle().sync(jnp.zeros(3))  # per-buffer sync path kept from core


def test_output_conversion_policy():
    from raft_tpu.compat.pylibraft import config
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    x = np.eye(4, dtype=np.float32)
    try:
        config.set_output_as("numpy")
        assert isinstance(pairwise_distance(x), np.ndarray)
        config.set_output_as(lambda a: "custom")
        assert pairwise_distance(x) == "custom"
        with pytest.raises(ValueError):
            config.set_output_as("cupy")  # no CUDA on TPU builds
    finally:
        config.set_output_as("raft")
    import jax
    assert isinstance(pairwise_distance(x), jax.Array)


def test_output_conversion_torch():
    torch = pytest.importorskip("torch")
    from raft_tpu.compat.pylibraft import config
    from raft_tpu.compat.pylibraft.distance import pairwise_distance
    x = np.eye(4, dtype=np.float32)
    try:
        config.set_output_as("torch")
        t = pairwise_distance(x)
        assert isinstance(t, torch.Tensor)
        before = np.asarray(pairwise_distance(x.copy()))
        t.add_(1.0)  # must not corrupt JAX's cached host buffer (copy made)
        after = np.asarray(pairwise_distance(x.copy()))
        np.testing.assert_array_equal(before, after)
    finally:
        config.set_output_as("raft")


def test_interruptible_surface():
    from raft_tpu.compat.pylibraft.common import interruptible
    interruptible.clear()
    interruptible.cancel()
    with pytest.raises(interruptible.InterruptedException):
        interruptible.synchronize()
    interruptible.synchronize()  # flag auto-cleared on raise
