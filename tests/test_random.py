"""random tests — parity with ``cpp/tests/random/`` (11 suites) and
``pylibraft/tests/test_random.py``: distribution moments, sampling invariants,
blob separability, rmat bounds/skew."""

import numpy as np

from raft_tpu import random as rnd
from raft_tpu.random import RngState


class TestDistributions:
    def setup_method(self):
        self.state = RngState(seed=42)

    def test_uniform_bounds_and_mean(self):
        x = np.asarray(rnd.uniform(self.state, (20000,), -2.0, 3.0))
        assert x.min() >= -2.0 and x.max() < 3.0
        assert abs(x.mean() - 0.5) < 0.05

    def test_normal_moments(self):
        x = np.asarray(rnd.normal(self.state, (20000,), mu=1.5, sigma=2.0))
        assert abs(x.mean() - 1.5) < 0.06
        assert abs(x.std() - 2.0) < 0.06

    def test_uniform_int(self):
        x = np.asarray(rnd.uniform_int(self.state, (5000,), 3, 10))
        assert x.min() >= 3 and x.max() < 10
        assert set(np.unique(x)) == set(range(3, 10))

    def test_bernoulli(self):
        x = np.asarray(rnd.bernoulli(self.state, (20000,), 0.3))
        assert abs(x.mean() - 0.3) < 0.02

    def test_scaled_bernoulli(self):
        x = np.asarray(rnd.scaled_bernoulli(self.state, (10000,), 0.5, 2.5))
        assert set(np.unique(np.abs(x))) == {2.5}

    def test_lognormal(self):
        x = np.asarray(rnd.lognormal(self.state, (20000,), mu=0.0, sigma=0.5))
        assert (x > 0).all()
        assert abs(np.log(x).mean()) < 0.05

    def test_exponential_rayleigh_laplace_logistic_gumbel(self):
        n = 20000
        assert abs(np.asarray(rnd.exponential(self.state, (n,), lam=2.0)).mean() - 0.5) < 0.03
        sigma = 1.5
        assert abs(np.asarray(rnd.rayleigh(self.state, (n,), sigma)).mean() - sigma * np.sqrt(np.pi / 2)) < 0.05
        assert abs(np.asarray(rnd.laplace(self.state, (n,), mu=1.0)).mean() - 1.0) < 0.06
        assert abs(np.asarray(rnd.logistic(self.state, (n,), mu=-1.0)).mean() + 1.0) < 0.08
        g = np.asarray(rnd.gumbel(self.state, (n,)))
        assert abs(g.mean() - 0.5772) < 0.05

    def test_normal_table(self):
        mu = np.array([0.0, 10.0, -5.0], np.float32)
        sig = np.array([1.0, 0.1, 2.0], np.float32)
        x = np.asarray(rnd.normal_table(self.state, 5000, mu, sig))
        np.testing.assert_allclose(x.mean(axis=0), mu, atol=0.15)
        np.testing.assert_allclose(x.std(axis=0), sig, rtol=0.1)

    def test_discrete(self):
        w = np.array([0.1, 0.0, 0.6, 0.3], np.float32)
        x = np.asarray(rnd.discrete(self.state, (20000,), w))
        counts = np.bincount(x, minlength=4) / 20000
        np.testing.assert_allclose(counts, w / w.sum(), atol=0.02)
        assert counts[1] == 0

    def test_stream_independence(self):
        a = np.asarray(rnd.normal(self.state, (100,)))
        b = np.asarray(rnd.normal(self.state, (100,)))
        assert not np.allclose(a, b)

    def test_determinism_same_seed(self):
        a = np.asarray(rnd.normal(RngState(7), (50,)))
        b = np.asarray(rnd.normal(RngState(7), (50,)))
        np.testing.assert_array_equal(a, b)


class TestSampling:
    def test_sample_without_replacement_unique(self):
        idx = np.asarray(rnd.sample_without_replacement(RngState(0), 100, 50))
        assert len(np.unique(idx)) == 50
        assert idx.min() >= 0 and idx.max() < 100

    def test_weighted_sampling_respects_weights(self):
        w = np.zeros(100, np.float32)
        w[:10] = 1.0  # only first 10 have mass
        idx = np.asarray(rnd.sample_without_replacement(RngState(1), 100, 10, weights=w))
        assert set(idx.tolist()) == set(range(10))

    def test_permute(self):
        x = np.arange(40, dtype=np.float32).reshape(20, 2)
        out, perm = rnd.permute(RngState(3), x)
        np.testing.assert_allclose(np.sort(np.asarray(out), axis=0), x)
        assert not np.array_equal(np.asarray(out), x)


class TestDatagen:
    def test_make_blobs_separable(self):
        x, y = rnd.make_blobs(RngState(5), 500, 8, n_clusters=3, cluster_std=0.1)
        x, y = np.asarray(x), np.asarray(y)
        assert x.shape == (500, 8) and set(np.unique(y)) <= {0, 1, 2}
        # within-cluster scatter far below between-cluster distances
        centers = np.stack([x[y == c].mean(axis=0) for c in np.unique(y)])
        d = np.linalg.norm(centers[:, None] - centers[None, :], axis=2)
        within = max(x[y == c].std(axis=0).max() for c in np.unique(y))
        assert d[d > 0].min() > 10 * within

    def test_make_blobs_given_centers(self):
        centers = np.array([[0.0, 0.0], [100.0, 100.0]], np.float32)
        x, y = rnd.make_blobs(RngState(6), 200, 2, centers=centers, cluster_std=0.5)
        x, y = np.asarray(x), np.asarray(y)
        for c in (0, 1):
            np.testing.assert_allclose(x[y == c].mean(axis=0), centers[c], atol=0.5)

    def test_make_regression_recoverable(self):
        x, y, coef = rnd.make_regression(RngState(8), 300, 5, noise=0.0)
        x, y, coef = np.asarray(x), np.asarray(y), np.asarray(coef)
        fit, *_ = np.linalg.lstsq(x, y, rcond=None)
        np.testing.assert_allclose(fit, coef[:, 0], rtol=1e-3, atol=1e-2)

    def test_multi_variable_gaussian(self):
        mean = np.array([1.0, -2.0], np.float32)
        cov = np.array([[2.0, 0.8], [0.8, 1.0]], np.float32)
        x = np.asarray(rnd.multi_variable_gaussian(RngState(9), 20000, mean, cov))
        np.testing.assert_allclose(x.mean(axis=0), mean, atol=0.1)
        np.testing.assert_allclose(np.cov(x.T), cov, atol=0.1)


class TestRmat:
    def test_bounds_and_shape(self):
        theta = np.full((12, 4), 0.25, np.float32)
        edges = np.asarray(rnd.rmat(RngState(11), 5000, theta, 12, 10))
        assert edges.shape == (5000, 2)
        assert edges[:, 0].max() < 2**12 and edges[:, 0].min() >= 0
        assert edges[:, 1].max() < 2**10

    def test_uniform_theta_is_uniform(self):
        theta = np.full((8, 4), 0.25, np.float32)
        edges = np.asarray(rnd.rmat(RngState(12), 50000, theta, 8, 8))
        # with uniform theta, mean src ≈ (2^8 - 1)/2
        assert abs(edges[:, 0].mean() - 127.5) < 3.0

    def test_skewed_theta_concentrates(self):
        # heavy 'a' quadrant → ids concentrate near 0
        theta = np.tile(np.array([[0.7, 0.1, 0.1, 0.1]], np.float32), (8, 1))
        edges = np.asarray(rnd.rmat(RngState(13), 20000, theta, 8, 8))
        assert edges[:, 0].mean() < 60
        assert np.bincount(edges[:, 0], minlength=256)[0] > 200
