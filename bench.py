"""Headline benchmark — brute-force kNN throughput (SIFT-1M shape).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config mirrors the driver ladder entry "neighbors::brute_force kNN on
SIFT-1M" (`BASELINE.json` configs[1]): 1M × 128 float32 database, 10k
queries, k=10.  Measured path: ``knn(mode="fast")`` — the fused Pallas
bf16-shortlist kernel + exact f32 refine — **recall-gated**: ground truth
is computed once with the exact path (not timed) and the fast path must
reach recall@10 ≥ 0.999 or the benchmark falls back to timing the exact
path.  Throughput is measured over pipelined dispatches (standard serving
setup: keep the device queue full, sync once), which also amortizes the
~80 ms per-call round-trip of the remote-TPU tunnel.

The reference repo publishes no numbers ("published": {});
``vs_baseline`` therefore reports against the recorded best of PREVIOUS
rounds of this repo (ratcheted in BENCH_HISTORY.json) — 1.0 on first run.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_DB = 1_000_000
N_QUERY = 10_000
DIM = 128
K = 10
RECALL_GATE = 0.999
REPS = 4
# Measurement-protocol version, recorded in BENCH_HISTORY.json so cross-round
# comparisons are interpretable.  1 = exact mode, per-call sync (rounds ≤ 1
# early).  2 = recall-gated fast mode, pipelined dispatch.  vs_baseline spans
# protocols by design (the ratchet tracks "best this repo has achieved").
PROTOCOL = 2
HISTORY = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")


def main() -> None:
    import jax
    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.neighbors.brute_force import _fast_knn_impl, _knn_impl

    key = jax.random.PRNGKey(42)
    kq, kd = jax.random.split(key)
    db = jax.block_until_ready(jax.random.normal(kd, (N_DB, DIM), jnp.float32))
    q = jax.block_until_ready(jax.random.normal(kq, (N_QUERY, DIM), jnp.float32))

    def fetch(out):
        # host fetch is the only reliable barrier on the axon tunnel backend
        return np.asarray(out[0]), np.asarray(out[1])

    # ground truth (exact path, untimed) for the recall gate
    _, gt_idx = fetch(_knn_impl(q, db, K, "sqeuclidean", 65536))

    from raft_tpu.stats import neighborhood_recall

    fast = lambda: _fast_knn_impl(q, db, K, "sqeuclidean", 64, 1024, 1024)
    _, fi = fetch(fast())  # compile + warm
    recall = float(neighborhood_recall(fi, gt_idx))

    if recall >= RECALL_GATE:
        run = fast
    else:  # fall back to the exact path rather than report inflated QPS
        run = lambda: _knn_impl(q, db, K, "sqeuclidean", 65536)
        fetch(run())
        recall = 1.0  # the timed run is now the exact path

    best = float("inf")
    for _ in range(2):  # pipelined throughput: dispatch all reps, sync once
        t0 = time.perf_counter()
        outs = [run() for _ in range(REPS)]
        for o in outs:
            fetch(o)
        best = min(best, (time.perf_counter() - t0) / REPS)
    qps = N_QUERY / best

    hist = {}
    try:
        with open(HISTORY) as f:
            hist = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    prev = hist.get("knn_qps")
    vs = (qps / prev) if prev else 1.0
    if prev is None or qps > prev:  # record recall only with the run it belongs to
        hist = {"knn_qps": qps, "recall": recall, "protocol": PROTOCOL}
    try:
        with open(HISTORY, "w") as f:
            json.dump(hist, f)
    except OSError:
        pass

    print(json.dumps({
        "metric": "brute_force_knn_qps_1Mx128_k10_recall>=0.999",
        "value": round(qps, 2),
        "unit": "queries/s",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
