"""Headline + north-star benchmarks.

Prints one JSON line per config, then ONE final JSON line
{"metric", "value", "unit", "vs_baseline", "north_star": {...}} — the final
line is what the driver parses/ratchets; the north_star field carries the
QPS@recall-0.95 results the flagship exists for (``BASELINE.json``
configs[3-4], VERDICT r2 next #1).

Configs:

1. **brute_force** (headline, protocol 2 — unchanged from r2 for ratchet
   continuity): 1M×128 f32, 10k queries, k=10, recall-gated fast mode
   (fused Pallas bf16 shortlist + exact f32 refine), pipelined dispatch.
   Also reports the single-dispatch latency vs pipelined per-call time —
   the tunnel-RTT split VERDICT r2 weak #1 asked for — and effective
   TFLOP/s.
2. **ivf_pq @ DEEP-10M-class** (10M×96 clustered synthetic — DEEP files
   are not in-image; ``bench/ann.py``): out-of-core ``build_chunked``,
   then an n_probes sweep with 4× exact refine; reports the best
   QPS at recall ≥ 0.95 (gating metric = ``stats.neighborhood_recall``,
   the ``neighborhood_recall.cuh:77`` role).
3. **cagra @ 1M**: IVF-sourced optimized graph, (itopk × width) sweep,
   best QPS at recall ≥ 0.95.
4. **pairwise @ 10k×128** (ladder config #1): L2 + cosine full distance
   matrix, reported as effective TFLOP/s.
5. **ivf_flat + kmeans_balanced @ SIFT-1M-class** (ladder config #3):
   ``kmeans_balanced_fit`` throughput (rows/s) at the IVF coarse-quantizer
   shape, then an IVF-Flat n_probes sweep → best QPS at recall ≥ 0.95.

Scale knobs (smoke-testing): RAFT_BENCH_PQ_ROWS, RAFT_BENCH_CAGRA_ROWS,
RAFT_BENCH_IF_ROWS, RAFT_BENCH_SKIP (comma list of
{ivf_pq,cagra,pairwise,ivf_flat}).  Each config is independently
fault-isolated so a failure cannot take down the headline line.

The reference repo publishes no numbers ("published": {}); ``vs_baseline``
reports against the recorded best of PREVIOUS rounds (BENCH_HISTORY.json),
1.0 on first run of a metric.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench"))

# persistent XLA executable cache, inherited by the probe and every config
# subprocess: A/B reruns of the same config pay each compile once per
# machine, not once per process (the parent itself never imports jax)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

N_DB = int(os.environ.get("RAFT_BENCH_BF_ROWS", 1_000_000))
N_QUERY = min(10_000, max(100, N_DB // 100))
DIM = 128
K = 10
RECALL_GATE = 0.999
REPS = 4
RECALL_FLOOR = 0.95
# Measurement-protocol version, recorded in BENCH_HISTORY.json so cross-round
# comparisons are interpretable.  1 = exact mode, per-call sync (rounds ≤ 1
# early).  2 = recall-gated fast mode, pipelined dispatch (r2+).
PROTOCOL = 2
HISTORY = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")

PQ_ROWS = int(os.environ.get("RAFT_BENCH_PQ_ROWS", 10_000_000))
CAGRA_ROWS = int(os.environ.get("RAFT_BENCH_CAGRA_ROWS", 1_000_000))
IF_ROWS = int(os.environ.get("RAFT_BENCH_IF_ROWS", 1_000_000))
SKIP = set(filter(None, os.environ.get("RAFT_BENCH_SKIP", "").split(",")))
# soft wall budget: the driver must always see the final JSON line, so we
# stop STARTING north-star configs once the budget is spent (a config in
# flight still finishes; the skipped ones are recorded as budget-skipped)
BUDGET_S = float(os.environ.get("RAFT_BENCH_BUDGET_S", 2400))


def _bench_brute_force():
    """Headline config — returns (qps, recall, profile dict)."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    from ann import fetch, measure_qps, single_latency
    from raft_tpu.neighbors.brute_force import _fast_knn_impl, _knn_impl

    key = jax.random.PRNGKey(42)
    kq, kd = jax.random.split(key)
    db = jax.block_until_ready(jax.random.normal(kd, (N_DB, DIM), jnp.float32))
    q = jax.block_until_ready(jax.random.normal(kq, (N_QUERY, DIM), jnp.float32))

    # ground truth (exact path, untimed) for the recall gate
    gt_idx = np.asarray(fetch(_knn_impl(q, db, K, "sqeuclidean", 65536))[1])

    from raft_tpu.stats import neighborhood_recall

    # fast-path tuning knobs (A/B on hardware without code edits; the
    # recall gate below still protects every combination)
    cand = int(os.environ.get("RAFT_BENCH_CAND", 64))
    bm = int(os.environ.get("RAFT_BENCH_BM", 1024))
    bn = int(os.environ.get("RAFT_BENCH_BN", 1024))
    cut = os.environ.get("RAFT_BENCH_CUT", "exact").lower()
    prec = os.environ.get("RAFT_BENCH_REFINE_PREC", "highest").lower()
    # a typo'd knob must fail the config loudly, not silently measure the
    # default while labeled as the variant (_fast_knn_impl treats unknown
    # strings as the defaults; only knn() carries the expects guard)
    if cut not in ("exact", "approx"):
        raise ValueError(f"RAFT_BENCH_CUT={cut!r} (want exact|approx)")
    if prec not in ("highest", "high"):
        raise ValueError(f"RAFT_BENCH_REFINE_PREC={prec!r} "
                         f"(want highest|high)")
    fast = lambda: _fast_knn_impl(q, db, K, "sqeuclidean", cand, bm, bn,
                                  None, cut, prec)
    fi = np.asarray(fetch(fast())[1])  # compile + warm
    recall = float(neighborhood_recall(fi, gt_idx))

    if recall >= RECALL_GATE:
        run = fast
        path = "fast"
    else:  # fall back to the exact path rather than report inflated QPS
        run = lambda: _knn_impl(q, db, K, "sqeuclidean", 65536)
        fetch(run())
        recall = 1.0  # the timed run is now the exact path
        path = "exact"  # A/B selectors must not crown a fallen-back combo

    lat1 = single_latency(run)        # includes one tunnel round trip
    qps = measure_qps(run, N_QUERY, reps=REPS)
    per_call = N_QUERY / qps
    flops = 2.0 * N_QUERY * N_DB * DIM
    profile = {
        "path": path,
        "single_dispatch_ms": round(lat1 * 1e3, 1),
        "pipelined_per_call_ms": round(per_call * 1e3, 1),
        "tunnel_overhead_ms": round((lat1 - per_call) * 1e3, 1),
        "effective_tflops": round(flops / per_call / 1e12, 1),
    }
    return qps, recall, profile


def _bench_ivf_pq(rows=None, nq=None, on_point=None):
    """North-star config #4: QPS@recall-0.95, DEEP-10M-class.

    The refine-ratio ladder below is THE flagship search policy — consumed
    by both the bench ladder and ``scripts/ivf_pq_10m.py`` (full-scale
    validation), so it lives exactly once.  ``nq`` bounds the query
    subsample (CPU full-scale runs); ``on_point`` is a per-sweep-point
    progress callback for multi-hour runs.
    """
    import jax.numpy as jnp
    import numpy as np

    from ann import best_at_recall, ground_truth, make_clustered, sweep_ivf_pq
    from raft_tpu.neighbors import ivf_pq

    n, d, nq = rows or PQ_ROWS, 96, nq or 10_000
    n_clusters = max(64, n // 1000)
    # explicit bench config (not the CLI default): 4096 lists at 10M keeps
    # the (160k-trainset, n_lists) balanced-fit distance matrix ~2.6 GB so
    # build fits HBM alongside the slabs, and keeps ivf_pq_qps95 ratchet
    # history comparable across rounds
    n_lists = min(4096, max(64, n // 256))
    db_dev = make_clustered(n, d, n_clusters, seed=11, scale=2.0)
    q = make_clustered(nq, d, n_clusters, seed=11, scale=2.0, point_seed=1)
    gt = ground_truth(q, db_dev, K)
    db_host = np.asarray(db_dev)  # build streams from host (out-of-core path)

    t0 = time.time()
    p = ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, pq_dim=d // 2, seed=0,
        # trainset ≈ 160k rows so the balanced fit's (n_train, n_lists)
        # distance matrix stays ~2.6 GB at L=4096 (fits HBM with the slabs)
        kmeans_trainset_fraction=min(0.1, 160_000 / max(n, 1)))
    # peak device memory ATTRIBUTABLE to the build (VERDICT r3 next #5:
    # report HBM alongside wall time) — scoped tracker, not the process-
    # lifetime high-water mark the GT computation above already raised
    from raft_tpu.core.memory import MemoryTracker

    with MemoryTracker() as mt:
        index = ivf_pq.build_chunked(db_host, p, chunk_rows=131072)
    build_s = time.time() - t0
    peak_mb = (round(mt.peak_bytes / 1e6, 1)
               if mt.peak_bytes is not None else None)

    # The escalation PLAN is scale-dependent, set by measured regimes
    # (recall behavior is backend-independent; all numbers 2026-07-31):
    #   * ≤300k: probes AND ratio both matter — full ladder from ratio 4.
    #   * ~1M: shortlist-bound — raw PQ recall saturates with probes
    #     (0.7261→0.7276 from 16→64) and the ceiling is set by the refine
    #     ratio (4 caps ~0.94, 8 ~0.96, 16 ~0.977); escalate ratio.
    #   * ≥10M: PROBE-bound — ratio 16 ≈ ratio 8 recall at every probe
    #     count ≤32 (Δ ≤ 0.0003, bench/IVF_PQ_10M_CPU.json; QPS deltas
    #     there are 1-core CPU noise — one point even reads faster at 16).
    #     The measured floor crossing is probes 64 AT RATIO 16
    #     (recall 0.9689); the ratio-8 wide-probe leg below extrapolates
    #     that crossing from the recall equivalence and is confirmed or
    #     corrected by the first run of this plan. Escalate probes at
    #     ratio 8 (cheaper refine), ratio-16 wide stage as guard.
    # Stop at the first stage that clears the floor: past it, more work
    # only buys recall the gate doesn't ask for.  The expected-crossing
    # point (64) ends stage 1 so the costliest sweep point (128 probes)
    # is only paid when 64 misses.
    if n >= 10_000_000:
        # stage order: expected-cheapest crossing first, then the MEASURED
        # crossing (16, 64) — so a miss on the extrapolated ratio-8 leg
        # falls back to the confirmed operating point before paying any
        # 128-probe sweep
        plan = [(8, [16, 32, 64]), (16, [64]), (8, [128]), (16, [128])]
    elif n >= 1_000_000:
        plan = [(8, [4, 8, 16, 32]), (16, [4, 8, 16, 32]), (16, [64, 128])]
    else:
        plan = [(4, [4, 8, 16, 32]), (8, [4, 8, 16, 32]),
                (16, [4, 8, 16, 32]), (16, [64, 128])]
    curve = []
    for ratio, grid in plan:
        pts = sweep_ivf_pq(index, q, gt, K, grid,
                           refine_dataset=db_dev, refine_ratio=ratio)
        for pt in pts:
            pt["refine_ratio"] = ratio
            if on_point:
                on_point(pt)
        curve += pts
        if best_at_recall(pts, RECALL_FLOOR) is not None:
            break
    best = best_at_recall(curve, RECALL_FLOOR)
    return {"rows": n, "dim": d, "nq": nq, "n_lists": n_lists, "pq_dim": d // 2,
            "build_s": round(build_s, 1), "peak_device_mb": peak_mb,
            "curve": curve,
            "qps_at_recall95": None if best is None else best["qps"],
            "best": best}


def _bench_cagra(rows=None):
    """North-star config #5 (single-chip scale point): QPS@recall-0.95."""
    import numpy as np

    from ann import best_at_recall, ground_truth, make_clustered, sweep_cagra
    from raft_tpu.neighbors import cagra

    n, d, nq = rows or CAGRA_ROWS, 96, 10_000
    n_clusters = max(64, n // 1000)
    db = make_clustered(n, d, n_clusters, seed=13, scale=2.0)
    q = make_clustered(nq, d, n_clusters, seed=13, scale=2.0, point_seed=1)
    gt = ground_truth(q, db, K)

    t0 = time.time()
    # n_routers auto (≈2·√n): the 300k CPU scaling probe showed recall
    # plateaus at the router-coverage fraction when the table under-counts
    # the data's clusters (150 routers / 300 clusters → 0.49 at ANY beam
    # effort) — never cap routers below the region count
    p = cagra.CagraIndexParams(
        intermediate_graph_degree=64, graph_degree=32,
        build_algo="ivf" if n > 200_000 else "brute_force")
    index = cagra.build(db, p)
    build_s = time.time() - t0

    # grid bracketing the 0.95 floor: the 300k router-fixed probe reads
    # 0.944 @ (32,4) and 0.993 @ (64,4) — the crossing sits between them
    curve = sweep_cagra(index, q, gt, K, [(32, 4), (48, 4), (64, 4), (64, 8)])
    if best_at_recall(curve, RECALL_FLOOR) is None:
        # (128, 8) guards the recall floor at 1M rows (the 100k quality
        # table reads 0.966 at itopk=64 and recall drops with scale) —
        # but only when the cheap grid missed, it is ~2.5x slower
        curve += sweep_cagra(index, q, gt, K, [(128, 8)])
    best = best_at_recall(curve, RECALL_FLOOR)
    return {"rows": n, "dim": d, "graph_degree": 32,
            "build_s": round(build_s, 1), "curve": curve,
            "qps_at_recall95": None if best is None else best["qps"],
            "best": best}


def _bench_pairwise(rows=None):
    """Ladder config #1: pairwise_distance (L2 + cosine) on 10k×128."""
    import jax
    import jax.numpy as jnp

    from ann import measure_qps
    from raft_tpu.distance import pairwise_distance

    n, d = rows or 10_000, 128
    key = jax.random.PRNGKey(5)
    x = jax.block_until_ready(jax.random.normal(key, (n, d), jnp.float32))
    out = {"rows": n, "dim": d}
    flops = 2.0 * n * n * d
    for metric in ("sqeuclidean", "cosine"):
        # reduce to a scalar on device: fetching the (n, n) matrix per rep
        # (~400 MB over the tunnel) would time transfer, not compute
        run = lambda metric=metric: jnp.sum(
            pairwise_distance(x, x, metric=metric))
        per_call = 1.0 / measure_qps(run, 1, reps=4)
        out[metric] = {"ms": round(per_call * 1e3, 2),
                       "tflops": round(flops / per_call / 1e12, 2)}
    out["tflops"] = out["sqeuclidean"]["tflops"]
    return out


def _bench_ivf_flat_kmeans(rows=None):
    """Ladder config #3: kmeans_balanced fit throughput + IVF-Flat
    QPS@recall-0.95 on a SIFT-1M-class corpus."""
    import numpy as np

    from ann import best_at_recall, ground_truth, make_clustered, sweep_ivf_flat
    from raft_tpu.cluster.kmeans import KMeansParams, kmeans_balanced_fit
    from raft_tpu.neighbors import ivf_flat

    n, d, nq = rows or IF_ROWS, 128, 10_000
    n_clusters = max(64, n // 1000)
    n_lists = min(1024, max(64, n // 1000))
    db = make_clustered(n, d, n_clusters, seed=17, scale=2.0)
    q = make_clustered(nq, d, n_clusters, seed=17, scale=2.0, point_seed=1)
    gt = ground_truth(q, db, K)

    # kmeans_balanced fit throughput at the coarse-quantizer shape.  The
    # warm-up must run the FULL shape: the fit program is jit-specialized
    # on (n, k, max_iter, cap), so a small-slice warm-up would leave the
    # timed fit paying compilation
    kp = KMeansParams(n_clusters=n_lists, max_iter=10, seed=0)
    np.asarray(kmeans_balanced_fit(db, kp)[0])
    t0 = time.time()
    centroids, _, inertia = kmeans_balanced_fit(db, kp)
    np.asarray(centroids)  # completion barrier (see ann.fetch)
    fit_s = time.time() - t0
    kmeans_rows_s = n * kp.max_iter / fit_s

    # bf16-assignment training tier (single-pass MXU gemm): reported as its
    # own key — the exact-path number above stays ratchet-comparable.
    # Inertia ratio quantifies the quality cost of the fast tier in-line
    kpf = KMeansParams(n_clusters=n_lists, max_iter=10, seed=0,
                       balanced_assign_precision="bf16")
    np.asarray(kmeans_balanced_fit(db, kpf)[0])
    t0 = time.time()
    cf, _, inertia_f = kmeans_balanced_fit(db, kpf)
    np.asarray(cf)
    fit_f_s = time.time() - t0
    kmeans_bf16_rows_s = n * kpf.max_iter / fit_f_s
    inertia_ratio = float(inertia_f) / max(float(inertia), 1e-30)

    t0 = time.time()
    index = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=n_lists,
                                                           seed=0))
    build_s = time.time() - t0
    curve = sweep_ivf_flat(index, q, gt, K, [1, 2, 4, 8, 16])
    if best_at_recall(curve, RECALL_FLOOR) is None:
        curve += sweep_ivf_flat(index, q, gt, K, [32])  # recall guard
    best = best_at_recall(curve, RECALL_FLOOR)
    return {"rows": n, "dim": d, "n_lists": n_lists,
            "kmeans_fit_s": round(fit_s, 1),
            "kmeans_rows_per_s": round(kmeans_rows_s, 0),
            "kmeans_bf16_rows_per_s": round(kmeans_bf16_rows_s, 0),
            "kmeans_bf16_inertia_ratio": round(inertia_ratio, 4),
            "build_s": round(build_s, 1), "curve": curve,
            "qps_at_recall95": None if best is None else best["qps"],
            "best": best}


# ---------------------------------------------------------------------------
# Orchestration (round-4 redesign, VERDICT r3 weak #1/#6).
#
# Round 3 was lost to a wedged TPU tunnel: the bench process imported jax,
# the import hung, and the driver's external timeout (rc=124) killed it
# before any final JSON line existed.  The fix is structural:
#
#   * The PARENT process never imports jax.  It cannot hang on a wedged
#     backend; it only orchestrates subprocesses.
#   * A bounded PROBE subprocess runs one real matmul before the ladder.
#     If the backend is wedged, the final line (with an ``error`` field)
#     prints immediately and the process exits 0.
#   * Each config runs in its own WATCHDOGGED subprocess — a hung jax op
#     costs at most that config's timeout, never the driver window.
#   * SIGTERM/SIGINT flush the final line with whatever completed.
#   * The final-format line is re-printed after every config, so even
#     SIGKILL leaves the most recent complete snapshot as the last JSON
#     line on stdout (the driver parses the tail).
#   * The ratchet history is written incrementally after each config.
#
# Test hooks (exercised by tests/test_bench_robustness.py):
#   RAFT_BENCH_FAKE_WEDGE=1      — probe child sleeps forever (wedged tunnel)
#   RAFT_BENCH_FAKE_SLOW_CONFIG  — config children sleep forever (hung op):
#     "1" wedges every config, a comma list (e.g. "ivf_pq") just those
#   RAFT_BENCH_CONFIG_TIMEOUT_S  — watchdog override: one global float, or
#     per-config "short=seconds" comma pairs (unmatched configs keep their
#     default caps)
# ---------------------------------------------------------------------------

PROBE_TIMEOUT_S = float(os.environ.get("RAFT_BENCH_PROBE_TIMEOUT_S", 180))

ANCHORS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "bench", "anchors.json")


def _load_anchor() -> dict:
    """External (A100) anchor for the north-star ratio.  No trustworthy
    number is available offline (BASELINE.md 'External A100 anchor'), so
    the default records that fact machine-readably instead of an empty
    dict the reader must interpret; a later sourced ``bench/anchors.json``
    flips it to ratios without code changes."""
    try:
        with open(ANCHORS) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"available": False,
                "note": "no offline A100 QPS@recall0.95 source in-image; "
                        "see BASELINE.md 'External A100 anchor'"}


def _anchor_report(north_star: dict) -> dict:
    anchor = _load_anchor()
    if not anchor.get("available"):
        return anchor
    out = {"available": True, "source": anchor.get("source")}
    for name, target in (anchor.get("configs") or {}).items():
        res = north_star.get(name)
        qps = res.get("qps_at_recall95") if isinstance(res, dict) else None
        if qps and target:
            out[name] = {"anchor_qps": target,
                         "vs_anchor": round(qps / target, 3)}
    return out

_PROBE_SRC = """
import os, time
# test hooks: "1" models the real tunnel failure (bare backend init hangs,
# a CPU-pinned process is healthy — the shape of the r5 wedge), "hard"
# wedges unconditionally (machine-level hang; no fallback can help)
_fw = os.environ.get("RAFT_BENCH_FAKE_WEDGE")
if _fw == "hard" or (_fw and not os.environ.get("RAFT_BENCH_PLATFORM")):
    time.sleep(3600)
import jax
if os.environ.get("RAFT_BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["RAFT_BENCH_PLATFORM"])
import jax.numpy as jnp
(jnp.ones((128, 128), jnp.float32) @ jnp.ones((128, 128), jnp.float32)).sum().item()
print("PROBE_OK", jax.default_backend())
"""

# The one table every per-config decision reads: --config key (= SKIP key),
# north-star name, bench fn, full-scale rows, retry floor, watchdog cap.
# Timeout caps are generous; the budget guard, not these, bounds the normal
# ladder — the caps only bound the damage of a mid-run tunnel wedge.
_CONFIGS = (
    # order = budget priority: headline first, then the ~30 s pairwise
    # metric (cheap insurance before the big builds can eat a tight driver
    # window), then the north-star index configs by importance
    ("brute_force", "brute_force_1Mx128", _bench_brute_force, None, None, 1500),
    ("pairwise", "pairwise_10kx128", _bench_pairwise, 10_000, 1_000, 600),
    ("ivf_pq", "ivf_pq_deep10m_class", _bench_ivf_pq, PQ_ROWS, 100_000, 2700),
    ("cagra", "cagra_1m", _bench_cagra, CAGRA_ROWS, 100_000, 2100),
    # ivf_flat's cap covers TWO phases (kmeans_balanced fit + the n_probes
    # sweep) — 1800 s left it the tightest big config and a first-compile
    # TPU run could hit the watchdog mid-sweep; match ivf_pq's 2700 cap
    ("ivf_flat", "ivf_flat_kmeans_1m", _bench_ivf_flat_kmeans, IF_ROWS,
     100_000, 2700),
)


def _config_row(short: str):
    return next(row for row in _CONFIGS if row[0] == short)


def _source_hash() -> str:
    """Content hash of the measurement code (this file + bench/ann.py),
    part of the checkpoint scope: a checkpoint written by one version of
    the sweeps/gates must not replay under another.  Content-based rather
    than git HEAD so an uncommitted edit also invalidates."""
    import hashlib

    h = hashlib.sha256()
    here = os.path.dirname(os.path.abspath(__file__))
    for path in (os.path.abspath(__file__),
                 os.path.join(here, "bench", "ann.py")):
        try:
            with open(path, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"<unreadable>")
    return h.hexdigest()[:12]


def _config_timeout(short: str) -> float:
    # either one global float, or per-config "short=seconds" comma pairs
    # (the checkpoint drill wedges one config and must not spend the other
    # configs' full caps waiting on it).  A malformed value falls back to
    # the default cap instead of raising — this runs in the PARENT, whose
    # final-JSON-line guarantee outranks loud validation
    default = float(_config_row(short)[5])
    env = os.environ.get("RAFT_BENCH_CONFIG_TIMEOUT_S")
    if not env:
        return default
    try:
        if "=" in env:
            for item in env.split(","):
                k, _, v = item.partition("=")
                if k == short:
                    return float(v)
            return default
        return float(env)
    except ValueError:
        print(f"WARN: unparseable RAFT_BENCH_CONFIG_TIMEOUT_S={env!r}; "
              f"using default {default}s for {short}", file=sys.stderr)
        return default


def _child_main(short: str) -> None:
    """Run ONE config in this process (invoked as a watchdogged subprocess).

    The last stdout line is the config's result JSON — errors included, so
    the parent never has to guess why a child produced nothing.
    """
    fake = os.environ.get("RAFT_BENCH_FAKE_SLOW_CONFIG")
    if fake and (fake == "1" or short in fake.split(",")):  # test hook: hung op
        time.sleep(3600)
    from _platform import pin_backend  # RAFT_BENCH_PLATFORM=cpu for smoke runs

    pin_backend()

    _, name, fn, full_rows, floor, _ = _config_row(short)
    if short == "brute_force":
        try:
            qps, recall, profile = _bench_brute_force()
            res = {"qps": round(qps, 2), "recall": round(recall, 5),
                   "profile": profile}
        except Exception as e:  # noqa: BLE001 — result line must still print
            traceback.print_exc()
            res = {"qps": 0.0, "recall": 0.0,
                   "profile": {"error": f"{type(e).__name__}: {e}"}}
        print(json.dumps({"config": name, **res}), flush=True)
        return
    try:
        res = fn()
    except Exception as e:  # noqa: BLE001 — keep the ladder alive
        traceback.print_exc()
        # a quarter-scale number still anchors the curve; an OOM at full
        # scale must not zero out the whole config.  The floor is
        # per-config: clamping every retry up to 100k would scale the
        # 10k pairwise config UP on failure
        retry_rows = min(full_rows, max(floor, full_rows // 4))
        if retry_rows == full_rows:  # nothing smaller to try
            res = {"error": f"{type(e).__name__}: {e}"}
        else:
            try:
                res = fn(rows=retry_rows)
                res["reduced_scale"] = True
            except Exception as e2:  # noqa: BLE001
                traceback.print_exc()
                res = {"error": f"{type(e).__name__}: {e}",
                       "retry_error": f"{type(e2).__name__}: {e2}"}
    print(json.dumps({"config": name, **res}), flush=True)


def _probe(timeout_s: float, state=None):
    """Bounded backend-health check in a subprocess (a real matmul — on the
    remote-TPU tunnel, backend init can succeed while the compute leg is
    wedged).  Returns (ok, backend_name_or_error).  The child is registered
    in ``state["child"]`` so the SIGTERM handler can kill it — an orphaned
    probe client would hold the single-client tunnel wedged after we exit."""
    p = subprocess.Popen([sys.executable, "-c", _PROBE_SRC],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
    if state is not None:
        state["child"] = p
    try:
        out, err = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        p.kill()
        p.communicate()
        return False, f"probe timed out after {timeout_s:.0f}s (backend wedged)"
    finally:
        if state is not None:
            state["child"] = None
    for line in reversed(out.splitlines()):
        if line.startswith("PROBE_OK"):
            return True, line.split()[1]
    tail = (err or out or "").strip().splitlines()[-3:]
    return False, f"probe failed rc={p.returncode}: {' | '.join(tail)}"


def _is_record_run(backend) -> bool:
    """Only production (TPU, full-scale) runs may move the ratchet or claim
    the canonical 1M label — reduced RAFT_BENCH_* smoke runs must not
    pollute history.  The single home of the predicate (label + ratchet
    must never disagree)."""
    return backend == "tpu" and not any(
        k in os.environ for k in ("RAFT_BENCH_BF_ROWS", "RAFT_BENCH_PQ_ROWS",
                                  "RAFT_BENCH_CAGRA_ROWS", "RAFT_BENCH_IF_ROWS"))


def _load_history() -> dict:
    try:
        with open(HISTORY) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


_RATCHET_KEYS = (
    ("ivf_pq_deep10m_class", "qps_at_recall95", "ivf_pq_qps95"),
    ("cagra_1m", "qps_at_recall95", "cagra_qps95"),
    ("ivf_flat_kmeans_1m", "qps_at_recall95", "ivf_flat_qps95"),
    ("pairwise_10kx128", "tflops", "pairwise_tflops"),
    ("ivf_flat_kmeans_1m", "kmeans_rows_per_s", "kmeans_rows_s"),
    ("ivf_flat_kmeans_1m", "kmeans_bf16_rows_per_s", "kmeans_bf16_rows_s"),
)


def main() -> None:
    t_start = time.time()
    hist = _load_history()
    prev = hist.get("knn_qps")
    state = {"north_star": {}, "qps": 0.0, "recall": 0.0, "profile": {},
             "backend": None, "error": None, "child": None, "done": 0}

    def flush_final() -> None:
        """Print the final-format line reflecting everything completed so
        far.  Called after every config (and from the signal handler), so
        the last JSON line on stdout is always the best snapshot."""
        qps = state["qps"]
        record = _is_record_run(state["backend"])
        # the canonical label names the full-scale config; reduced smoke
        # runs must not masquerade as (or be ratioed against) 1M-scale
        if record:
            label = "brute_force_knn_qps_1Mx128_k10_recall>=0.999"
            vs = (qps / prev) if prev else 1.0
        else:
            label = f"brute_force_knn_qps_{N_DB}x{DIM}_k{K}_smoke"
            vs = 0.0
        line = {
            "metric": label,
            "value": round(qps, 2),
            "unit": "queries/s",
            "vs_baseline": round(vs, 4),
            "backend": state["backend"],
            "configs_done": state["done"],
            "elapsed_s": round(time.time() - t_start, 1),
            "profile": state["profile"],
            "north_star": {
                name: {k: v for k, v in res.items() if k != "curve"}
                if isinstance(res, dict) else res
                for name, res in state["north_star"].items()
            },
            "anchor": _anchor_report(state["north_star"]),
        }
        if state["error"]:
            line["error"] = state["error"]
        print(json.dumps(line), flush=True)

    def on_signal(signum, frame):  # noqa: ARG001 — signal API
        child = state.get("child")
        if child is not None:
            try:
                child.kill()
            except OSError:
                pass
        state["error"] = state["error"] or f"killed by signal {signum}"
        # the signal may have landed mid-write of a previous (non-atomic >
        # PIPE_BUF) line: a leading newline keeps the handler's JSON from
        # gluing onto the truncated line (same guard as forward())
        sys.stdout.write("\n")
        flush_final()
        sys.stdout.flush()
        os._exit(0)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    fallback_info = None
    ok, info = _probe(PROBE_TIMEOUT_S, state)
    if not ok and not os.environ.get("RAFT_BENCH_PLATFORM"):
        # Wedged-backend fallback (the r5 failure: BENCH_r05.json recorded
        # value 0.0 / "probe timed out after 180s" and the round lost its
        # measurement).  The common wedge is the remote-TPU tunnel — bare
        # backend init hangs while the host itself is healthy — so pin the
        # CPU backend, re-probe, and record a CPU-tagged smoke measurement
        # instead of an empty errored run.  Config children inherit the
        # pin via RAFT_BENCH_PLATFORM (_platform.pin_backend); the scale
        # caps keep the ladder CPU-feasible and, with backend != tpu,
        # already exclude the run from the record label and the ratchet
        # (_is_record_run).
        primary_err = info
        os.environ["RAFT_BENCH_PLATFORM"] = "cpu"
        os.environ["JAX_PLATFORMS"] = "cpu"
        for knob, val in (("RAFT_BENCH_BF_ROWS", "100000"),
                          ("RAFT_BENCH_PQ_ROWS", "200000"),
                          ("RAFT_BENCH_CAGRA_ROWS", "100000"),
                          ("RAFT_BENCH_IF_ROWS", "100000")):
            os.environ.setdefault(knob, val)
        global N_DB, N_QUERY
        N_DB = int(os.environ["RAFT_BENCH_BF_ROWS"])
        N_QUERY = min(10_000, max(100, N_DB // 100))
        ok, info = _probe(min(PROBE_TIMEOUT_S, 60.0), state)
        if ok:
            fallback_info = {"backend": info, "primary_error": primary_err}
            state["profile"]["probe_fallback"] = fallback_info
            print(json.dumps({"event": "probe_fallback", "backend": info,
                              "primary_error": primary_err}), flush=True)
    if not ok:
        state["error"] = f"backend unavailable: {info}"
        flush_final()
        return
    state["backend"] = info
    record = _is_record_run(info)

    # Per-config checkpointing (VERDICT r4 weak #5 / next #6): when the
    # queue sets RAFT_BENCH_CKPT_DIR, every completed measurement is written
    # to a run-scoped file the moment it lands, and a rerun (queue attempt 2
    # after a mid-ladder wedge) reuses completed configs instead of losing
    # everything after the wedge point.  Off by default — the driver's
    # round-end run must measure, not replay.
    ckpt_dir = os.environ.get("RAFT_BENCH_CKPT_DIR")
    if ckpt_dir:
        try:
            os.makedirs(ckpt_dir, exist_ok=True)
        except OSError:
            ckpt_dir = None

    # everything that changes WHAT a config measures must match for a
    # checkpoint to be reusable: backend (cpu smoke vs tpu), the scale
    # knobs (a reduced-rows sanity run must not replay into a record run
    # and ratchet smoke numbers as 1M-scale), the fast-path tuning
    # knobs (an A/B combo is a different measurement), and the bench
    # source itself — an edited sweep/gate must re-measure, not replay
    # stale numbers written by different code
    _ckpt_scope = {"backend": state["backend"], "src": _source_hash()}
    _ckpt_scope.update({k: os.environ.get(k, "") for k in (
        "RAFT_BENCH_BF_ROWS", "RAFT_BENCH_PQ_ROWS", "RAFT_BENCH_CAGRA_ROWS",
        "RAFT_BENCH_IF_ROWS", "RAFT_BENCH_CUT", "RAFT_BENCH_REFINE_PREC",
        "RAFT_BENCH_CAND", "RAFT_BENCH_BM", "RAFT_BENCH_BN")})

    def load_ckpt(short: str):
        if not ckpt_dir:
            return None
        try:
            with open(os.path.join(ckpt_dir, short + ".json")) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if d.get("scope") != _ckpt_scope:
            return None
        return d.get("res")

    def save_ckpt(short: str, res: dict) -> None:
        """Checkpoint only full, real measurements — a watchdog skip, an
        errored config, or a reduced-scale fallback (which exists only
        because full scale failed) must stay retryable on the rerun."""
        if not ckpt_dir or res.get("skipped") or res.get("error") \
                or res.get("retry_error") or res.get("reduced_scale"):
            return
        if short == "brute_force" and not res.get("qps"):
            return
        try:
            # post_timeout_kill is run-local metadata (it triggers a wedge
            # re-probe after the config) — replaying it would re-probe, and
            # possibly falsely abort, a healthy rerun.  from_checkpoint is
            # likewise run-local: a replayed result re-saved to persist its
            # catch-up ``ratcheted`` flag must not bake the marker in.
            res = {k: v for k, v in res.items()
                   if k not in ("post_timeout_kill", "from_checkpoint")}
            path = os.path.join(ckpt_dir, short + ".json")
            with open(path + ".tmp", "w") as f:
                json.dump({"scope": _ckpt_scope, "res": res}, f)
            os.replace(path + ".tmp", path)
        except OSError:
            pass

    def run_config(short: str):
        """One config in a watchdogged subprocess; returns its result dict."""
        timeout_s = _config_timeout(short)
        cmd = [sys.executable, os.path.abspath(__file__), "--config", short]
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
        state["child"] = p
        def forward(text):
            if text:
                sys.stdout.write(text)
                if not text.endswith("\n"):
                    # a killed child can die mid-line; an unterminated line
                    # would glue itself to our next JSON line and corrupt
                    # the driver's tail parse
                    sys.stdout.write("\n")
                sys.stdout.flush()

        def parse_result(text):
            for line in reversed(text.splitlines()):
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(d, dict) and d.get("config"):
                    return d
            return None

        try:
            out, _ = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            forward(out)
            # the child may have PRINTED its result and then hung in
            # teardown on the wedged tunnel — a completed measurement must
            # not be discarded for dying badly
            res = parse_result(out or "")
            if res is not None:
                res["post_timeout_kill"] = True
                return res
            return {"skipped": "watchdog_timeout", "timeout_s": timeout_s}
        finally:
            state["child"] = None
        forward(out)  # per-config lines stay on stdout
        res = parse_result(out or "")
        if res is not None:
            return res
        return {"error": f"config subprocess rc={p.returncode}, no result line"}

    def ratchet(short: str, res: dict) -> None:
        """Fold one config's result into BENCH_HISTORY (written after every
        config so a later kill cannot lose an earlier result)."""
        if short == "brute_force":
            if state["qps"] > (hist.get("knn_qps") or 0):
                hist.update({"knn_qps": state["qps"],
                             "recall": state["recall"],
                             "protocol": PROTOCOL})
        for name, field, key in _RATCHET_KEYS:
            r = state["north_star"].get(name) or {}
            val = r.get(field)
            # reduced-scale retries report but never ratchet (smaller
            # corpus = inflated numbers; keys track the full-scale config)
            if val is not None and not r.get("reduced_scale") \
                    and val > hist.get(key, 0):
                hist[key] = val
        if record:
            try:
                # provenance stamp (VERDICT r3 next #8) + atomic replace (a
                # SIGTERM between configs must never truncate the ratchet)
                import datetime

                # per-backend stamp (the file accumulates bests across runs;
                # a flat stamp would let a later run relabel another
                # backend's numbers — the prims.py pattern)
                hist.setdefault("_meta", {})[state["backend"]] = {
                    "date": datetime.date.today().isoformat(),
                    "protocol": PROTOCOL,
                    "rows": {"brute_force": N_DB, "ivf_pq": PQ_ROWS,
                             "cagra": CAGRA_ROWS, "ivf_flat": IF_ROWS}}
                tmp = HISTORY + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(hist, f)
                    f.write("\n")
                os.replace(tmp, HISTORY)
            except OSError:
                pass

    for short, name, *_ in _CONFIGS:
        if short != "brute_force" and short in SKIP:
            continue
        if short != "brute_force" and time.time() - t_start > BUDGET_S:
            state["north_star"][name] = {
                "skipped": "budget",
                "elapsed_s": round(time.time() - t_start, 1)}
            print(json.dumps({"config": name,
                              **state["north_star"][name]}), flush=True)
            continue
        res = load_ckpt(short)
        if res is not None:
            res = dict(res)
            res["from_checkpoint"] = True
            if isinstance(res.get("profile"), dict):
                res["profile"]["from_checkpoint"] = True
            print(json.dumps({"config": name, **res}), flush=True)
        else:
            res = run_config(short)
            res.pop("config", None)
        if short == "brute_force":
            state["qps"] = float(res.get("qps") or 0.0)
            state["recall"] = float(res.get("recall") or 0.0)
            state["profile"] = res.get("profile") or \
                {k: v for k, v in res.items() if k != "qps"}
            if fallback_info:  # must survive the config's profile dict
                state["profile"]["probe_fallback"] = fallback_info
        else:
            state["north_star"][name] = res
        state["done"] += 1
        if not res.get("ratcheted"):
            # ratchet BEFORE checkpointing: the old order (save_ckpt, then
            # ratchet) had a kill window where a measurement was
            # checkpointed but never entered BENCH_HISTORY — the rerun
            # replayed it as "already ratcheted" and the number was lost
            # for good.  The ``ratcheted`` flag rides in the checkpoint:
            # a replay that carries it is genuinely done (re-ratcheting
            # would re-stamp _meta's date, relabeling an old measurement
            # as made today); a replay without it catches up here.
            ratchet(short, res)
            res["ratcheted"] = True
            save_ckpt(short, res)
        flush_final()
        if res.get("skipped") == "watchdog_timeout" or \
                res.get("post_timeout_kill"):
            # a killed client can wedge the tunnel for every later config;
            # re-probe before burning more watchdog windows on a dead link
            ok2, info2 = _probe(min(PROBE_TIMEOUT_S, 120), state)
            if not ok2:
                state["error"] = f"backend lost mid-run: {info2}"
                break
    flush_final()


if __name__ == "__main__":
    if "--config" in sys.argv:
        _child_main(sys.argv[sys.argv.index("--config") + 1])
    else:
        main()
