"""Headline benchmark — exact brute-force kNN throughput (SIFT-1M shape).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config mirrors the driver ladder entry "neighbors::brute_force kNN on
SIFT-1M" (`BASELINE.json` configs[1]): 1M × 128 float32 database, 10k
queries, k=10.  The reference repo publishes no numbers ("published": {});
``vs_baseline`` therefore reports against the recorded best of PREVIOUS
rounds of this repo (ratcheted in BENCH_HISTORY.json) — 1.0 on first run.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_DB = 1_000_000
N_QUERY = 10_000
DIM = 128
K = 10
HISTORY = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")


def main() -> None:
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors.brute_force import _knn_impl

    key = jax.random.PRNGKey(42)
    kq, kd = jax.random.split(key)
    db = jax.random.normal(kd, (N_DB, DIM), jnp.float32)
    q = jax.random.normal(kq, (N_QUERY, DIM), jnp.float32)
    db = jax.block_until_ready(db)
    q = jax.block_until_ready(q)

    tile = 65536

    import numpy as np

    def run():
        d, i = _knn_impl(q, db, K, "sqeuclidean", tile)
        # sync via host fetch: on the axon tunnel backend block_until_ready
        # returns before execution finishes; fetching the (small) outputs is
        # the only reliable barrier, and its transfer cost is negligible.
        return np.asarray(d), np.asarray(i)

    run()  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    qps = N_QUERY / min(times)

    prev = None
    try:
        with open(HISTORY) as f:
            prev = json.load(f).get("knn_qps")
    except (OSError, json.JSONDecodeError):
        pass
    vs = (qps / prev) if prev else 1.0
    try:
        with open(HISTORY, "w") as f:
            json.dump({"knn_qps": max(qps, prev or 0.0)}, f)
    except OSError:
        pass

    print(json.dumps({
        "metric": "brute_force_knn_qps_1Mx128_k10",
        "value": round(qps, 2),
        "unit": "queries/s",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
