// raft_tpu native IO — the framework's C++ runtime layer.
//
// TPU-native parity for the reference's native-by-necessity pieces:
//  * .npy mmap fast path        (cpp/include/raft/core/detail/mdspan_numpy_serializer.hpp,
//                                core/serialize.hpp:26,73 — there: CUDA-side stream writer)
//  * .fvecs/.bvecs/.ivecs       (raft-ann-bench's dataset loaders, removed upstream with
//                                the cuVS migration; needed for SIFT/DEEP/GIST benchmarks)
//  * multithreaded strided read (host-side analog of the reference's pinned-memory
//                                bulk transfer paths; keeps the feeding side of the TPU
//                                input pipeline off the Python GIL)
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this build).
// All functions return 0 on success, negative errno-style codes on failure.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// .npy
// ---------------------------------------------------------------------------

// Parse a v1.0/v2.0 .npy header. Writes the dtype descr (e.g. "<f4") into
// `descr` (cap bytes incl. NUL), ndim and shape (max 8 dims), fortran flag,
// and the byte offset of the data section.
int rt_npy_header(const char* path, char* descr, int descr_cap, int* ndim,
                  int64_t* shape, int* fortran, int64_t* data_offset) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -errno;
  unsigned char magic[8];
  if (std::fread(magic, 1, 8, f) != 8 || std::memcmp(magic, "\x93NUMPY", 6) != 0) {
    std::fclose(f);
    return -EINVAL;
  }
  int major = magic[6];
  uint32_t hlen = 0;
  size_t pre = 0;
  if (major >= 2) {
    unsigned char b[4];
    if (std::fread(b, 1, 4, f) != 4) { std::fclose(f); return -EINVAL; }
    hlen = b[0] | (b[1] << 8) | (uint32_t(b[2]) << 16) | (uint32_t(b[3]) << 24);
    pre = 12;
  } else {
    unsigned char b[2];
    if (std::fread(b, 1, 2, f) != 2) { std::fclose(f); return -EINVAL; }
    hlen = b[0] | (b[1] << 8);
    pre = 10;
  }
  std::string hdr(hlen, '\0');
  if (std::fread(&hdr[0], 1, hlen, f) != hlen) { std::fclose(f); return -EINVAL; }
  std::fclose(f);
  *data_offset = static_cast<int64_t>(pre + hlen);

  auto find_val = [&](const char* key) -> std::string {
    size_t p = hdr.find(key);
    if (p == std::string::npos) return "";
    p = hdr.find(':', p);
    if (p == std::string::npos) return "";
    ++p;
    while (p < hdr.size() && (hdr[p] == ' ')) ++p;
    return hdr.substr(p);
  };

  std::string d = find_val("'descr'");
  if (d.empty() || d[0] != '\'') return -EINVAL;
  size_t e = d.find('\'', 1);
  if (e == std::string::npos) return -EINVAL;
  std::string dv = d.substr(1, e - 1);
  if ((int)dv.size() + 1 > descr_cap) return -ERANGE;
  std::memcpy(descr, dv.c_str(), dv.size() + 1);

  std::string fo = find_val("'fortran_order'");
  *fortran = fo.rfind("True", 0) == 0 ? 1 : 0;

  std::string sh = find_val("'shape'");
  size_t p = sh.find('(');
  size_t q = sh.find(')', p);
  if (p == std::string::npos || q == std::string::npos) return -EINVAL;
  std::string tup = sh.substr(p + 1, q - p - 1);
  int nd = 0;
  const char* s = tup.c_str();
  while (*s && nd < 8) {
    while (*s == ' ' || *s == ',') ++s;
    if (!*s) break;
    char* end = nullptr;
    long long v = std::strtoll(s, &end, 10);
    if (end == s) break;
    shape[nd++] = v;
    s = end;
  }
  // Unconsumed digits mean the tuple has more than 8 dims: error out so the
  // caller falls back to np.load instead of a silently truncated shape.
  while (*s == ' ' || *s == ',') ++s;
  if (*s) return -EINVAL;
  *ndim = nd;
  return 0;
}

// mmap a file read-only. Returns base pointer + length via out params.
int rt_mmap(const char* path, void** base, int64_t* length) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) { int e = errno; ::close(fd); return -e; }
  void* p = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  ::close(fd);
  if (p == MAP_FAILED) return -errno;
  *base = p;
  *length = st.st_size;
  return 0;
}

int rt_munmap(void* base, int64_t length) {
  return ::munmap(base, length) == 0 ? 0 : -errno;
}

// ---------------------------------------------------------------------------
// .fvecs / .bvecs / .ivecs (TexMex format: per-row int32 dim prefix)
// ---------------------------------------------------------------------------

// elem_size: 4 for f/i-vecs, 1 for bvecs. Returns rows and dim.
int rt_vecs_info(const char* path, int elem_size, int64_t* rows, int64_t* dim) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -errno;
  int32_t d = 0;
  if (std::fread(&d, 4, 1, f) != 1 || d <= 0) { std::fclose(f); return -EINVAL; }
  struct stat st;
  if (fstat(fileno(f), &st) != 0) { int e = errno; std::fclose(f); return -e; }
  std::fclose(f);
  int64_t row_bytes = 4 + int64_t(d) * elem_size;
  if (st.st_size % row_bytes != 0) return -EINVAL;
  *rows = st.st_size / row_bytes;
  *dim = d;
  return 0;
}

// Read rows [row_start, row_start+n_rows) into dst (densely packed, no dim
// prefixes), fanned out over `threads` workers with pread (thread-safe,
// no shared file offset).
int rt_vecs_read(const char* path, int elem_size, int64_t dim,
                 int64_t row_start, int64_t n_rows, void* dst, int threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  const int64_t row_bytes = 4 + dim * elem_size;
  const int64_t out_row = dim * elem_size;
  if (threads < 1) threads = 1;
  if (threads > 64) threads = 64;
  std::atomic<int> err{0};
  auto worker = [&](int64_t lo, int64_t hi) {
    std::vector<char> buf;
    const int64_t CHUNK = 4096;  // rows per pread batch
    for (int64_t r = lo; r < hi && !err.load(std::memory_order_relaxed); r += CHUNK) {
      int64_t n = std::min(CHUNK, hi - r);
      buf.resize(size_t(n * row_bytes));
      int64_t off = (row_start + r) * row_bytes;
      int64_t want = n * row_bytes, got = 0;
      while (got < want) {
        ssize_t k = ::pread(fd, buf.data() + got, want - got, off + got);
        if (k <= 0) { err.store(k == 0 ? EINVAL : errno); return; }
        got += k;
      }
      for (int64_t i = 0; i < n; ++i) {
        int32_t d;
        std::memcpy(&d, buf.data() + i * row_bytes, 4);
        if (d != dim) { err.store(EINVAL); return; }
        std::memcpy(static_cast<char*>(dst) + (r + i) * out_row,
                    buf.data() + i * row_bytes + 4, size_t(out_row));
      }
    }
  };
  std::vector<std::thread> ts;
  int64_t per = (n_rows + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * per, hi = std::min(n_rows, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
  ::close(fd);
  int e = err.load();
  return e ? -e : 0;
}

// Dense binary read (e.g. the data section of an .npy): threaded pread into dst.
int rt_pread_dense(const char* path, int64_t offset, int64_t nbytes, void* dst,
                   int threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  if (threads < 1) threads = 1;
  if (threads > 64) threads = 64;
  std::atomic<int> err{0};
  auto worker = [&](int64_t lo, int64_t hi) {
    int64_t got = lo;
    while (got < hi) {
      ssize_t k = ::pread(fd, static_cast<char*>(dst) + got, hi - got, offset + got);
      if (k <= 0) { err.store(k == 0 ? EINVAL : errno); return; }
      got += k;
    }
  };
  std::vector<std::thread> ts;
  int64_t per = (nbytes + threads - 1) / threads;
  // align splits to 1 MiB so each worker streams big sequential extents
  per = ((per + (1 << 20) - 1) >> 20) << 20;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * per, hi = std::min(nbytes, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
  ::close(fd);
  int e = err.load();
  return e ? -e : 0;
}

}  // extern "C"
