"""Zero-dependency docs builder — the ``docs`` role of the reference's
``build.sh docs`` target (`/root/reference/build.sh:22`, sphinx tree at
`/root/reference/docs/source/`).

The container has no sphinx and no egress, so this renders the markdown tree
to a small static HTML site with stdlib only:

    python docs/build_docs.py            # writes docs/_build/*.html
    python docs/build_docs.py --check    # link check only (CI mode)

``docs/gen_api.py`` regenerates ``api.md`` from live docstrings first when
``--api`` is passed.  Supported markdown: ATX headings, fenced code blocks,
tables, ordered/unordered lists, links, inline code / bold / italic,
blockquotes.  Inter-page links (``foo.md`` → ``foo.html``) are rewritten and
verified; a dead relative link fails the build.
"""

from __future__ import annotations

import html
import os
import re
import sys

DOCS = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(DOCS, "_build")

PAGES = [  # (file, nav title) — nav order
    ("../README.md", "Overview"),
    ("architecture.md", "Architecture"),
    ("api.md", "API reference"),
    ("tuning_guide.md", "Tuning guide"),
    ("perf_analysis.md", "Performance analysis"),
    ("developer_guide.md", "Developer guide"),
    ("contributing.md", "Contributing"),
    ("parity_status.md", "Parity status"),
]

_CSS = """
body{font-family:system-ui,sans-serif;max-width:56rem;margin:2rem auto;
     padding:0 1rem;line-height:1.55;color:#1a1a2e}
nav{border-bottom:1px solid #ddd;padding-bottom:.6rem;margin-bottom:1.2rem}
nav a{margin-right:.9rem;text-decoration:none;color:#0b5394}
pre{background:#f6f8fa;padding:.8rem;overflow-x:auto;border-radius:6px}
code{background:#f6f8fa;padding:.1rem .25rem;border-radius:4px;
     font-size:.92em}
pre code{padding:0;background:none}
table{border-collapse:collapse;margin:1rem 0}
td,th{border:1px solid #ccc;padding:.35rem .6rem;text-align:left}
blockquote{border-left:3px solid #bbb;margin-left:0;padding-left:1rem;
           color:#444}
h1,h2,h3{line-height:1.25}
"""


def _inline(text: str) -> str:
    text = html.escape(text, quote=False)
    text = re.sub(r"`([^`]+)`", r"<code>\1</code>", text)
    text = re.sub(r"\*\*([^*]+)\*\*", r"<strong>\1</strong>", text)
    text = re.sub(r"(?<![\w*])\*([^*\s][^*]*)\*", r"<em>\1</em>", text)
    text = re.sub(r"\[([^\]]+)\]\(([^)\s]+)\)",
                  lambda m: f'<a href="{_fix_href(m.group(2))}">'
                            f"{m.group(1)}</a>", text)
    return text


def _fix_href(href: str) -> str:
    if href.startswith(("http://", "https://", "#", "mailto:")):
        return href
    return re.sub(r"\.md(#|$)", r".html\1", href)


def render(md: str) -> str:
    out, lines = [], md.split("\n")
    i, in_code, in_list, in_table = 0, False, None, False

    def close_list():
        nonlocal in_list
        if in_list:
            out.append(f"</{in_list}>")
            in_list = None

    def close_table():
        nonlocal in_table
        if in_table:
            out.append("</table>")
            in_table = False

    while i < len(lines):
        line = lines[i]
        if line.startswith("```"):
            close_list()
            close_table()
            out.append("<pre><code>" if not in_code else "</code></pre>")
            in_code = not in_code
        elif in_code:
            out.append(html.escape(line))
        elif re.match(r"^#{1,6} ", line):
            close_list()
            close_table()
            level = len(line) - len(line.lstrip("#"))
            out.append(f"<h{level}>{_inline(line[level + 1:])}</h{level}>")
        elif re.match(r"^\s*\|.*\|\s*$", line):
            close_list()
            if re.match(r"^\s*\|[\s\-:|]+\|\s*$", line):  # separator row
                i += 1
                continue
            cells = [c.strip().replace("\\|", "|") for c in
                     re.split(r"(?<!\\)\|", line.strip().strip("|"))]
            tag = "th" if not in_table else "td"
            if not in_table:
                out.append("<table>")
                in_table = True
            out.append("<tr>" + "".join(
                f"<{tag}>{_inline(c)}</{tag}>" for c in cells) + "</tr>")
        elif re.match(r"^\s*[-*] ", line):
            close_table()
            if in_list != "ul":
                close_list()
                out.append("<ul>")
                in_list = "ul"
            item = re.sub(r"^\s*[-*] ", "", line)
            out.append(f"<li>{_inline(item)}</li>")
        elif re.match(r"^\s*\d+\. ", line):
            close_table()
            if in_list != "ol":
                close_list()
                out.append("<ol>")
                in_list = "ol"
            item = re.sub(r"^\s*\d+\. ", "", line)
            out.append(f"<li>{_inline(item)}</li>")
        elif line.startswith(">"):
            close_list()
            close_table()
            out.append(f"<blockquote>{_inline(line[1:].strip())}</blockquote>")
        elif not line.strip():
            close_list()
            close_table()
        else:
            close_list()
            close_table()
            out.append(f"<p>{_inline(line)}</p>")
        i += 1
    close_list()
    close_table()
    return "\n".join(out)


def check_links() -> int:
    """Every relative .md link in every page must resolve.  Returns the
    number of dead links (CI gate)."""
    dead = 0
    for page, _ in PAGES:
        path = os.path.join(DOCS, page)
        if not os.path.exists(path):
            print(f"MISSING PAGE {page}")
            dead += 1
            continue
        src = open(path, encoding="utf-8").read()
        for m in re.finditer(r"\]\(([^)\s#]+\.md)", src):
            target = os.path.normpath(
                os.path.join(os.path.dirname(path), m.group(1)))
            if not os.path.exists(target):
                print(f"{page}: dead link → {m.group(1)}")
                dead += 1
    return dead


def main() -> int:
    if "--api" in sys.argv:
        import subprocess

        subprocess.run([sys.executable, os.path.join(DOCS, "gen_api.py")],
                       check=True)
    dead = check_links()
    if "--check" in sys.argv:
        print(f"link check: {dead} dead link(s)")
        return 1 if dead else 0
    os.makedirs(OUT, exist_ok=True)
    nav = "<nav>" + "".join(
        f'<a href="{os.path.basename(p).replace(".md", ".html")}">{t}</a>'
        for p, t in PAGES) + "</nav>"
    for page, title in PAGES:
        path = os.path.join(DOCS, page)
        if not os.path.exists(path):
            continue
        body = render(open(path, encoding="utf-8").read())
        name = os.path.basename(page).replace(".md", ".html")
        with open(os.path.join(OUT, name), "w", encoding="utf-8") as f:
            f.write(f"<!doctype html><html><head><meta charset='utf-8'>"
                    f"<title>raft_tpu — {title}</title>"
                    f"<style>{_CSS}</style></head><body>{nav}{body}"
                    f"</body></html>")
    # README.html doubles as the landing page
    readme = os.path.join(OUT, "README.html")
    if os.path.exists(readme):
        with open(readme, encoding="utf-8") as f:
            content = f.read()
        with open(os.path.join(OUT, "index.html"), "w",
                  encoding="utf-8") as f:
            f.write(content)
    print(f"wrote {len(PAGES)} pages → {os.path.relpath(OUT)}; "
          f"{dead} dead link(s)")
    return 1 if dead else 0


if __name__ == "__main__":
    sys.exit(main())
