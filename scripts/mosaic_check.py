"""Mosaic-compile validation of the Pallas kernels at production block
shapes (VERDICT r4 next #4).

The test suite pins CPU and runs every Pallas kernel in interpret mode
(``select_k.py:128``, ``fused_l2_topk.py:161``), so CI can be green while
a kernel fails to *compile* on hardware — and the select_k tuner has
observed real Mosaic failures (k=32, cols >= 16384, pre-fori_loop).  This
script is the hardware gate: it runs each kernel NON-interpreted on
whatever backend is present and asserts agreement with interpret mode
(exact for the integer paths, allclose for bf16 where accumulation order
may differ).  Reference analog: the ext_headers discipline of compiling
the same sources in every consumption mode
(``/root/reference/cpp/tests/CMakeLists.txt:128-139``).

Cheap by design (~1 min + compiles) so any healthy tunnel minute can run
it — wired FIRST in ``scripts/tpu_jobs_r5.sh``.  Writes a backend-stamped
artifact to ``bench/MOSAIC_CHECK.json`` and exits nonzero on any failure.
"""

import datetime
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "bench"))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))
# the validation run itself must exercise Mosaic BEFORE the artifact it
# writes exists (or when the existing stamp is sha-stale) — bypass the
# dispatch gate for this process only (ops/pallas/gate.py honors it)
os.environ.setdefault("RAFT_MOSAIC_GATE", "off")

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "bench", "MOSAIC_CHECK.json")


def main() -> None:
    import jax

    # RAFT_BENCH_PLATFORM smoke-runs the *script logic* on CPU (kernels
    # fall back to interpret — compile coverage needs a real TPU)
    from _platform import pin_backend

    pin_backend(sys.argv)

    import jax.numpy as jnp
    import numpy as np

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    if os.environ.get("RAFT_MOSAIC_REQUIRE_TPU") and not on_tpu:
        # queue gate: a CPU fallback passing in interpret mode must not
        # latch the step's .done marker as Mosaic coverage
        print(json.dumps({"mosaic_check": "refused",
                          "backend": backend,
                          "error": "RAFT_MOSAIC_REQUIRE_TPU set but backend "
                                   "is not tpu"}), flush=True)
        sys.exit(1)
    checks = {}

    def run(name, fn):
        t0 = time.time()
        try:
            fn()
            checks[name] = {"ok": True, "s": round(time.time() - t0, 1)}
        except Exception as e:  # noqa: BLE001 — record, keep probing others
            checks[name] = {"ok": False, "s": round(time.time() - t0, 1),
                            "error": f"{type(e).__name__}: {e}"}
        print(json.dumps({"check": name, **checks[name]}), flush=True)

    rng = np.random.default_rng(7)

    # --- select_k: production fast-path bucket (brute-force refine stage
    # shape class: cols 2048, k 64, default blocks bm=256/bn=2048) --------
    def check_select_k(batch, length, k):
        from raft_tpu.ops.pallas.select_k import _call, select_k_pallas

        x = jnp.asarray(rng.normal(size=(batch, length)).astype(np.float32))
        v, i = select_k_pallas(x, k)          # non-interpreted on TPU
        v, i = np.asarray(v), np.asarray(i)
        xs = np.sort(np.asarray(x), axis=1)[:, :k]
        np.testing.assert_allclose(v, xs, rtol=0, atol=0)
        if on_tpu:  # Mosaic vs interpret on identical inputs: exact
            bn = min(2048, length)
            vi, ii = _call(x, k, min(256, batch), bn, True)
            np.testing.assert_array_equal(v, np.asarray(vi))
            np.testing.assert_array_equal(i, np.asarray(ii))

    run("select_k_prod_2048_k64", lambda: check_select_k(1024, 2048, 64))
    # the shape class the tuner saw Mosaic REJECT pre-fori_loop
    run("select_k_wide_16384_k32", lambda: check_select_k(256, 16384, 32))

    # --- fused_shortlist bf16 + int8 at production blocks (bm 256/1024,
    # bn 2048 — the bench fast path's defaults) ---------------------------
    def check_shortlist(dtype):
        from raft_tpu.ops.pallas.fused_l2_topk import (fused_shortlist,
                                                       int8_surrogate_norms)

        m, n, d, k = 256, 8192, 128, 10
        if dtype == np.float32:
            x = rng.normal(size=(m, d)).astype(dtype)
            y = rng.normal(size=(n, d)).astype(dtype)
            yn = jnp.asarray((y * y).sum(axis=1).astype(np.float32))
            d2 = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        else:
            x = rng.integers(0, 256, (m, d)).astype(dtype)
            y = rng.integers(0, 256, (n, d)).astype(dtype)
            yn = int8_surrogate_norms(jnp.asarray(y))
            d2 = ((x.astype(np.int64)[:, None, :]
                   - y.astype(np.int64)[None, :, :]) ** 2).sum(-1)
        sv, si = fused_shortlist(jnp.asarray(x), jnp.asarray(y), yn, bn=2048)
        si = np.asarray(si)
        true = np.argsort(d2, axis=1)[:, :k]
        rec = np.mean([len(set(t) & set(s)) for t, s in zip(true, si)]) / k
        assert rec > 0.99, f"shortlist recall {rec}"
        if on_tpu:  # Mosaic vs interpret (int8: exact int32 accumulation)
            from raft_tpu.ops.pallas.fused_l2_topk import _call, center_int8

            xb, yb = jnp.asarray(x), jnp.asarray(y)
            if dtype == np.uint8:
                xb, yb = center_int8(xb), center_int8(yb)
            else:
                xb, yb = xb.astype(jnp.bfloat16), yb.astype(jnp.bfloat16)
            ref = _call(xb, yb, yn.reshape(1, -1).astype(jnp.float32),
                        256, 2048, True)
            tol = 0 if dtype == np.uint8 else 1e-3
            np.testing.assert_allclose(np.asarray(sv), np.asarray(ref[0]),
                                       rtol=tol, atol=tol)

    run("fused_shortlist_bf16", lambda: check_shortlist(np.float32))
    run("fused_shortlist_int8", lambda: check_shortlist(np.uint8))

    # --- fused_slab_topk (blocked-scan fused arm) at an IVF-flat slab
    # shape class: probe_block 8 × cap 512 candidates, bn 512 -------------
    def check_fused_slab():
        from raft_tpu.ops.pallas.fused_scan import fused_slab_topk

        nq, c, d, k = 256, 4096, 128, 10
        vecs1 = rng.normal(size=(nq, c, d)).astype(np.float32)
        q = rng.normal(size=(nq, d)).astype(np.float32)
        base = (vecs1 ** 2).sum(axis=2).astype(np.float32)
        sv, spos = fused_slab_topk(jnp.asarray(vecs1), jnp.asarray(base),
                                   jnp.asarray(q), bn=512)
        spos = np.asarray(spos)
        d2 = base - 2.0 * np.einsum("qcd,qd->qc", vecs1, q)
        true = np.argsort(d2, axis=1)[:, :k]
        rec = np.mean([len(set(t) & set(s)) for t, s in zip(true, spos)]) / k
        assert rec > 0.99, f"fused slab shortlist recall {rec}"
        if on_tpu:  # Mosaic vs interpret on identical inputs
            from raft_tpu.ops.pallas.fused_scan import _call

            vb = jnp.asarray(vecs1).astype(jnp.bfloat16)
            qb = jnp.asarray(q).astype(jnp.bfloat16)
            ref = _call(qb, vb, jnp.asarray(base), 8, 512, True)
            np.testing.assert_allclose(np.asarray(sv), np.asarray(ref[0]),
                                       rtol=1e-3, atol=1e-3)

    run("fused_slab_topk_4096_k10", lambda: check_fused_slab())

    # --- bin_select (XLA two-pass path, no Pallas — still worth a TPU
    # compile pass since kAuto can dispatch production rows onto it) ------
    def check_bin_select():
        from raft_tpu.ops.bin_select import bin_select_k

        x = rng.normal(size=(512, 16384)).astype(np.float32)
        v, i = bin_select_k(jnp.asarray(x), 64)
        np.testing.assert_allclose(np.sort(np.asarray(v), axis=1),
                                   np.sort(x, axis=1)[:, :64], rtol=1e-6)

    run("bin_select_16384_k64", lambda: check_bin_select())

    ok = all(c["ok"] for c in checks.values())
    from raft_tpu.ops.pallas.gate import pallas_kernel_sha

    art = {"backend": backend, "mosaic": on_tpu,
           "date": datetime.date.today().isoformat(),
           # the sha the dispatch gate (ops/pallas/gate.py) validates the
           # stamp against — a stamp from older kernel sources is stale
           "kernel_sha": pallas_kernel_sha(),
           "ok": ok, "checks": checks}
    # only a real-hardware pass may overwrite a previous real-hardware
    # stamp; a CPU smoke may refresh a CPU (or unreadable) stamp
    try:
        with open(OUT) as f:
            prev_tpu = json.load(f).get("backend") == "tpu"
    except (OSError, ValueError):
        prev_tpu = False
    if on_tpu or not prev_tpu:
        with open(OUT, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
    print(json.dumps({"mosaic_check": "done", **{k: v for k, v in art.items()
                                                 if k != "checks"}}),
          flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
