#!/bin/bash
# Round-15 TPU job queue: first hardware round for the out-of-core
# cooperative tier (raft_tpu.neighbors.ooc + io.shards — ISSUE 14).
#   * mosaic re-stamps bench/MOSAIC_CHECK.json first, as always — the
#     dispatch gate rejects stale kernel_sha stamps.
#   * ooc_smoke — the memory-split oracle on hardware: rerank_k = n must
#     be bit-identical (values AND ids) to brute force THROUGH the host
#     round-trip (estimator scan on device codes -> survivor ids ->
#     shard-store gather -> staged exact rerank), the search loop must
#     stay transfer-bounded (max_put_bytes <= one staged chunk), and a
#     format-v5 manifest-directory roundtrip must survive.  The CPU tier
#     already proves all three; this step proves them where HBM is real.
#   * ooc_100m — the headline scale point: 100M x 64 f32 (25.6 GB raw,
#     inadmissible as a flat slab) under an 8 GB device budget, with the
#     prefetch-overlap on/off A/B -> bench/OOC_TPU.json.  On TPU the
#     overlap column finally measures something the CPU tier cannot:
#     the PCIe stage of chunk t+1 hiding behind chunk t's rerank.
#   * ann_ooc — the standing ann bench gains the ooc arm's curve on
#     hardware (10M so the sweep fits the step budget).
# Stage order: jaxlint -> mosaic -> ooc smoke -> 100M A/B -> ann ooc ->
# bench.py.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r15
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

echo "$(date) [r15 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass (the ooc search loop's device_gets
# and pool-lifetime barriers carry explicit JX01/JX05 waivers), zero
# chip time
run_step jaxlint_r15    300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates the kernels
# on hardware and stamps the sha-scoped artifact the dispatch gate needs
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# the exactness + boundedness + lifecycle smoke on hardware (written to
# a file first: run_step retries must not re-read stdin)
cat > "$LOG/ooc_smoke.py" <<'PY'
import json, os, sys, tempfile

sys.path.insert(0, os.getcwd())        # the queue runs this from /root/repo

import numpy as np
from raft_tpu.neighbors import brute_force, ooc, serialize
from raft_tpu.stats import neighborhood_recall

# integer-valued f32 at the tier-1 suite's exact shapes/seeds: every
# arithmetic step lands on exact floats AND the brute-force oracle is
# tie-free for these draws (distinct distances => unique top-k to pin
# bit-identity against; a fresh draw may tie at the k boundary)
db = np.random.default_rng(7).integers(0, 256, (3000, 64)).astype(np.float32)
q = np.random.default_rng(8).integers(0, 256, (16, 64)).astype(np.float32)
td_store = tempfile.mkdtemp()
index = ooc.build(db, ooc.OocIndexParams(
    n_lists=16, kmeans_n_iters=8, list_cap_ratio=2.0),
    store_path=os.path.join(td_store, "shards"))
bd, bi = brute_force.knn(q, db, 10)
# rerank everything at total coverage == brute force, bit for bit —
# THROUGH the host round-trip (device estimator -> shard gather -> rerank)
d, i = ooc.search(index, q, 10, ooc.OocSearchParams(
    n_probes=16, rerank_k=db.shape[0]))
np.testing.assert_array_equal(np.asarray(i), np.asarray(bi))
np.testing.assert_array_equal(np.asarray(d), np.asarray(bd))
# the estimator tier at a realistic rerank budget, and the transfer bound:
# the search loop stages at most one (chunk, rerank_k, d) slab + queries
ooc.reset_transfer_stats()
d8, i8 = ooc.search(index, q, 10, ooc.OocSearchParams(
    n_probes=8, rerank_k=160))
ts = ooc.transfer_stats()
assert ts["max_put_bytes"] <= 16 * 160 * 64 * 4 + 16 * 64 * 4, ts
recall = float(neighborhood_recall(np.asarray(i8), np.asarray(bi)))
assert recall > 0.85, recall
# serialize v5 (manifest directory + sharded store) survives the roundtrip
with tempfile.TemporaryDirectory() as td:
    p = os.path.join(td, "oc")
    serialize.save_index(p, index)
    assert serialize.verify_index(p) == []
    re = serialize.load_index(p)
    d2, i2 = ooc.search(re, q, 10, ooc.OocSearchParams(
        n_probes=8, rerank_k=160))
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i8))
print(json.dumps({"config": "ooc_smoke", "bitwise_vs_brute": True,
                  "max_put_bytes": int(ts["max_put_bytes"]),
                  "resident_bytes": int(index.resident_bytes),
                  "recall_p8_r160": round(recall, 4)}))
PY
run_step ooc_smoke      900 python "$LOG/ooc_smoke.py"
# the headline: 100M x 64 (25.6 GB raw — no flat slab fits) under an
# 8 GB device budget, overlap on/off A/B -> bench/OOC_TPU.json
run_step ooc_100m     10800 python bench/ooc_bench.py --rows 100000000 \
  --queries 1024 --n-lists 8192 --device-budget $((8 * 1024 * 1024 * 1024)) \
  --slab-budget $((512 * 1024 * 1024)) --sweep 16,32,64 --rerank-k 800 \
  --train-fraction 0.002 --train-iters 5
# the standing ann bench gains the ooc arm's curve on hardware
run_step ann_ooc       1800 python bench/ann_bench.py ooc --base synthetic:10000000x64
run_step bench         4500 python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
