"""TPU validation for the int8 MXU shortlist path (queued: tpu_jobs_r3.sh).

Interpret-mode tests cover the math on CPU; this confirms the int8 pallas
matmul actually compiles and ranks correctly on the real chip, and prints
an int8-vs-bf16 shortlist timing so the 2x MXU-rate claim is measured.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench"))
from _platform import pin_backend

# MUST precede any backend use (axon sitecustomize overrides the env var)
pin_backend(sys.argv)

import jax.numpy as jnp
import numpy as np


def main() -> None:
    from raft_tpu.neighbors.brute_force import knn
    from raft_tpu.ops.pallas.fused_l2_topk import (fused_shortlist,
                                                   int8_surrogate_norms)

    rng = np.random.default_rng(0)
    m, n, d = 1024, 1_000_000, 128
    x = rng.integers(0, 256, (m, d)).astype(np.uint8)
    y = rng.integers(0, 256, (n, d)).astype(np.uint8)
    xd, yd = jnp.asarray(x), jnp.asarray(y)

    v, i = knn(xd[:64], yd, 10, mode="fast")
    gt_v, gt_i = knn(xd[:64], yd, 10)
    from raft_tpu.stats import neighborhood_recall

    rec = float(neighborhood_recall(np.asarray(i), np.asarray(gt_i)))
    print(json.dumps({"case": "uint8_fast_recall@10_1M", "recall": rec}))
    assert rec >= 0.999, rec

    def timed(fn):
        np.asarray(fn()[0])  # warm/compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn()[0])
            best = min(best, time.perf_counter() - t0)
        return best

    yn_i = int8_surrogate_norms(yd)
    t_int8 = timed(lambda: fused_shortlist(xd, yd, yn_i, bm=1024, bn=1024))
    xf = xd.astype(jnp.float32)
    yf = yd.astype(jnp.float32)
    yn_f = jnp.sum(yf * yf, axis=1)
    t_bf16 = timed(lambda: fused_shortlist(xf, yf, yn_f, bm=1024, bn=1024))
    print(json.dumps({"case": "shortlist_1024x1Mx128",
                      "int8_ms": round(t_int8 * 1e3, 2),
                      "bf16_ms": round(t_bf16 * 1e3, 2),
                      "speedup": round(t_bf16 / t_int8, 2)}))


if __name__ == "__main__":
    main()
