#!/bin/bash
# Round-16 TPU job queue: first hardware round for replicated
# durability (raft_tpu.serve.replication — ISSUE 15).
#   * mosaic re-stamps bench/MOSAIC_CHECK.json first, as always — the
#     dispatch gate rejects stale kernel_sha stamps.
#   * replication_smoke — the ship/promote contract where the serving
#     backend is real: a semi-sync primary replicates extend/delete/
#     compact into a warm standby over the in-process pair, the standby
#     promotes, and the promoted index must be bit-identical (values
#     AND ids) to the primary THROUGH the device round-trip (the folds
#     run on the hardware backend, not the CPU tier the suite pins).
#     The deposed primary's append and swap must raise FencedError, and
#     lag + failover counters must land in prometheus_text().
#   * failover_bench — detection -> promotion -> first-good-reply vs
#     WAL tail length at serving scale (200k x 96) on hardware; the
#     CPU curve is committed as bench/FAILOVER_CPU.json, this one is
#     harvested from the step log into FAILOVER_TPU.json next round.
# Stage order: jaxlint -> mosaic -> replication smoke -> failover bench
# -> bench.py.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r16
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

echo "$(date) [r16 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass (the replication transport and fence
# are host code — zero new device entry points to waive), zero chip time
run_step jaxlint_r16    300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates the kernels
# on hardware and stamps the sha-scoped artifact the dispatch gate needs
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# the ship/fence/promote contract on the hardware backend (written to a
# file first: run_step retries must not re-read stdin)
cat > "$LOG/replication_smoke.py" <<'PY'
import json, os, sys, tempfile

sys.path.insert(0, os.getcwd())        # the queue runs this from /root/repo

import jax
import numpy as np
from raft_tpu.neighbors import ivf_flat, mutation
from raft_tpu.neighbors.wal import DurableStore
from raft_tpu.serve import (FencedError, LogShipper, QueuePair,
                            ReplicationConfig, SearchServer, ServerConfig,
                            StandbyReplica)

def leaves(t):
    return [np.asarray(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(t)]

db = np.random.default_rng(7).standard_normal((4096, 64)).astype(np.float32)
idx = mutation.delete(
    ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=16, seed=0)),
    [2], id_space=2 * 4096)
proot, sroot = tempfile.mkdtemp(), tempfile.mkdtemp()
a, b = QueuePair.create()
store = DurableStore.create(proot, idx)
cfg = ReplicationConfig(ack_mode="semi_sync", ack_timeout_s=60.0)
shipper = LogShipper(store, a, config=cfg)
replica = StandbyReplica(sroot, b, config=cfg)
shipper.pump(); replica.poll(); shipper.pump()   # cold snapshot bootstrap
rng = np.random.default_rng(11)
srv = SearchServer(replica.store.index, k=10,
                   config=ServerConfig(ladder=(8,)))
replica.attach_server(srv)
replica.start()                                   # semi-sync needs live acks
try:
    store.extend(rng.standard_normal((256, 64)).astype(np.float32))
    store.delete([5, 9])
    store.compact()
    store.extend(rng.standard_normal((64, 64)).astype(np.float32))
finally:
    replica.stop()
while replica.poll(0.05):
    pass
assert replica.applied == store.wal_lsn == 4, replica.applied
for x, y in zip(leaves(replica.store.index), leaves(store.index)):
    np.testing.assert_array_equal(x, y)           # values AND ids
promoted = replica.promote(drain_timeout_s=0.05)
shipper.pump()                                    # fence reaches the primary
fenced = 0
for attempt in (lambda: store.extend(np.zeros((2, 64), np.float32)),
                lambda: store.snapshot()):
    try:
        attempt()
    except FencedError:
        fenced += 1
assert fenced == 2, fenced
promoted.extend(rng.standard_normal((8, 64)).astype(np.float32))
text = srv.prometheus_text()
assert "raft_replication_lag_lsn" in text
assert "raft_failovers_total" in text
q = rng.standard_normal((4, 64)).astype(np.float32)
d, i = srv.search(q)
print(json.dumps({"config": "replication_smoke",
                  "backend": jax.default_backend(),
                  "bitwise_standby": True, "fenced_writes": fenced,
                  "promoted_lsn": promoted.wal_lsn,
                  "epoch": replica.fence.epoch}))
PY
run_step replication_smoke 900 python "$LOG/replication_smoke.py"
# failover timing at serving scale: tail sweep on hardware; the final
# JSON line becomes bench/FAILOVER_TPU.json next round
run_step failover_bench 3600 env RAFT_BENCH_SERVE_ROWS=200000 \
  RAFT_BENCH_SERVE_DIM=96 RAFT_BENCH_SERVE_LADDER=8,64 \
  RAFT_BENCH_SERVE_FAILOVER=16,64,256,1024 python bench/serve.py
run_step bench         4500 python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
