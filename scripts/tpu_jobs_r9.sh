#!/bin/bash
# Round-9 TPU job queue.  The r8 ladder plus the round-9 additions:
#   * mutation_tp — bench/mutation_throughput.py measures the online
#     extend() path against rebuild-from-scratch for both IVF families
#     (plus tombstone-delete and compact timings) and writes
#     bench/MUTATION_<BACKEND>.json — the on-hardware counterpart of
#     the committed CPU artifact.
#   * serve_swap — bench/serve.py in swap-under-load mode: generation
#     handoffs while the measured client load runs; the final JSON's
#     "swap" dict must report dropped == 0 and recompiles == 0 (the
#     zero-downtime contract, tests/test_serve_lifecycle.py).
#   * chaos_smoke — the same driver with RAFT_SERVE_FAULTS armed
#     (wedged dispatches + one failed swap): proves the retry/backoff
#     and swap-rollback paths on real hardware, not just under the
#     deterministic fault tests.  Staged right after jaxlint — it is
#     cheap and failure here means serving robustness regressed, which
#     should gate the expensive benches.
# Stage order: jaxlint -> chaos smoke -> Mosaic check -> build-throughput
# -> mutation throughput -> probe/chunk tuners -> bench.py -> select_k
# tuner -> prims -> cagra tuner -> cagra quality -> serve swap -> int8
# -> profile.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated and tpu_ab_r4.sh's wait-chain keeps working.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r9

export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

# un-latch a bench.done that lacks a headline measurement (r3/r4 queues
# gated on any measured line; a wedged-headline run must be retried)
if [ -f "$LOG/bench.done" ] && \
    ! bench_measured "$LOG/bench.log" brute_force 2>/dev/null; then
  echo "$(date) removing stale bench.done (no headline measurement)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/bench.done"
fi

# r9 refreshed the jaxlint census (extend-path waivers moved into the
# rewritten extend(); the _max_source_id waiver was removed outright):
# a pre-r9 jaxlint.done would leave the stale census committed
if [ -f "$LOG/jaxlint.done" ] && \
    grep -q "_max_source_id" bench/JAXLINT.json 2>/dev/null; then
  echo "$(date) removing pre-r9 jaxlint.done (stale waiver census)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/jaxlint.done"
fi

echo "$(date) [r9 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass, ~seconds, zero chip time — a hazard
# (hidden sync, retrace loop, f64 leak) must never cost TPU minutes to find
run_step jaxlint        300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# chaos smoke: small index, short sweep, faults armed — two wedged
# dispatches (recovered by retry) and one failed swap (rolled back).
# Success = clean exit with a final JSON line; the armed faults are
# consumed against the REAL backend dispatch path.
run_step chaos_smoke    900 env RAFT_SERVE_FAULTS="execute:wedge:2,swap:fail" \
    RAFT_BENCH_SERVE_ROWS=20000 RAFT_BENCH_SERVE_SECONDS=2 \
    RAFT_BENCH_SERVE_CLIENTS=2,4 RAFT_BENCH_SERVE_SWAPS=2 \
    python bench/serve.py
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
run_step build_tp      2400 python bench/build_throughput.py
run_step mutation_tp   2400 python bench/mutation_throughput.py
# tuners before the big benches: all three have /tmp resume checkpoints
# (kernel-sha scoped), so a wedge mid-grid resumes on attempt 2
run_step probe_tuner   3000 python bench/tune_probe_block.py
run_step chunk_tuner   3000 python bench/tune_chunk_rows.py
run_step bench         4500 python bench.py
# the checkpoints exist to survive a wedge WITHIN a bench run; once the
# headline-gated .done latches they are spent — leaving them would turn a
# deliberately forced re-measurement (rm bench.done) into a silent replay
[ -f "$LOG/bench.done" ] && rm -rf "$RAFT_BENCH_CKPT_DIR"
run_step tuner         3000 python bench/tune_select_k.py
run_step prims         3000 python bench/prims.py
# cagra tuner immediately before the quality sweep: the sweep's auto
# (itopk=0/width=0) points must consult the table this run just measured
run_step cagra_tuner   3000 python bench/tune_cagra.py
run_step cagra_quality 3000 python bench/cagra_quality.py
# swap-under-load at bench scale, no faults: the recorded handoff numbers
# (drops, p95 during swap, recompiles) for the round artifact
run_step serve_swap    2400 env RAFT_BENCH_SERVE_SWAPS=3 python bench/serve.py
run_step int8          1500 python scripts/tpu_validate_int8.py
run_step profile       3000 python bench/profile_knn.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
