#!/bin/bash
# Round-11 TPU job queue: first hardware round for the shared blocked-scan
# core + fused Pallas slab top-k kernel.
#   * mosaic must re-stamp bench/MOSAIC_CHECK.json BEFORE any bench/tuner
#     consults the gate: r11 added fused_slab_topk to the checker and the
#     dispatch gate (ops/pallas/gate.py) now rejects stamps whose
#     kernel_sha doesn't match the sources — the committed CPU stamp
#     deliberately keeps the gate closed until this step passes on TPU.
#   * fused_scan — bench/fused_scan.py microbench: per-engine vs
#     shared-core A/B plus the fused-arm interpret probe, the hardware
#     counterpart of the committed bench/FUSED_SCAN_CPU.json.
#   * tuner (tune_select_k.py) now also sweeps the fused-vs-xla scan arm
#     and writes raft_tpu/ops/_scan_kernel_table.json — it must run
#     after mosaic so "auto" resolutions during the ann A/B are real.
# Stage order: jaxlint -> mosaic -> fused_scan microbench -> tuners ->
# bench.py -> prims -> cagra quality.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r11
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

# r11 moved the fold/scoring core into ops/blocked_scan.py and re-keyed
# the select_k tuner sha over the fused-scan sources: pre-r11 markers for
# mosaic/tuner/bench latched against kernels that no longer exist
if [ -f "$LOG/mosaic.done" ] && \
    ! grep -q '"kernel_sha"' bench/MOSAIC_CHECK.json 2>/dev/null; then
  echo "$(date) removing pre-r11 mosaic.done (stamp lacks kernel_sha)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/mosaic.done"
fi
if [ -f "$LOG/tuner.done" ] && \
    [ ! -f raft_tpu/ops/_scan_kernel_table.json ]; then
  echo "$(date) removing pre-r11 tuner.done (no scan-kernel table)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/tuner.done"
fi

echo "$(date) [r11 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass, ~seconds, zero chip time
run_step jaxlint        300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates every
# kernel (incl. the new fused_slab_topk) on hardware and stamps the
# sha-scoped artifact the dispatch gate requires
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# fused-kernel microbench: per-engine-vs-shared-core A/B on hardware (the
# shared_core tag pairs with the committed per_engine baseline), plus the
# fused-arm probe
run_step fused_scan    2400 python bench/fused_scan.py --tag shared_core_tpu --out "$LOG/FUSED_SCAN_TPU.json"
# tuners before the big benches (resume checkpoints are sha-scoped);
# tune_select_k's fused arm writes raft_tpu/ops/_scan_kernel_table.json,
# which "auto" engines consult during the ann A/B below
run_step tuner         3000 python bench/tune_select_k.py
run_step probe_tuner   3000 python bench/tune_probe_block.py
run_step bench         4500 python bench.py
[ -f "$LOG/bench.done" ] && rm -rf "$RAFT_BENCH_CKPT_DIR"
run_step prims         3000 python bench/prims.py
run_step cagra_quality 3000 python bench/cagra_quality.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
