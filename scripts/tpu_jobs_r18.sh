#!/bin/bash
# Round-18 TPU job queue: concurrency-discipline round for the threaded
# serving stack (racelint + lockdep — ISSUE 17).
#   * racelint runs FIRST and costs zero chip time: the AST pass
#     (JX10..JX14) over the whole library must report zero active
#     findings and re-stamp bench/RACELINT.json.  jaxlint rides along —
#     the two analyzers share the reporting contract.
#   * mosaic re-stamps bench/MOSAIC_CHECK.json, as always — the dispatch
#     gate rejects stale kernel_sha stamps.
#   * lockdep_gate — the runtime arm where the threads are real: the
#     four threaded suites (serve lifecycle, compaction, replication,
#     fleet) run with RAFT_LOCKDEP=1 and the session census must record
#     zero lock-order inversions while actually observing edges (a
#     vacuous empty graph fails the step).  On TPU the dispatch thread
#     holds real device waits, so the hold-time histogram
#     (raft_lockdep_hold_seconds) gets its first hardware-true samples.
#   * serve_bench re-baselines the serving QPS with the instrumented
#     (but disarmed) locks in place — the wrappers must cost nothing on
#     the hot path, and this curve is the evidence.
# Stage order: racelint -> mosaic -> lockdep gate -> serve bench ->
# bench.py.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r18
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

echo "$(date) [r18 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# racelint first: pure-host AST pass, zero chip time — the concurrency
# census must stay at zero active findings before any threaded step runs
run_step racelint_r18   300 python scripts/mini_lint.py --race raft_tpu \
  --race-stats-json bench/RACELINT.json
run_step jaxlint_r18    300 python scripts/mini_lint.py --jax raft_tpu \
  --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates the kernels
# on hardware and stamps the sha-scoped artifact the dispatch gate needs
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# the runtime gate: four threaded suites with lockdep armed; the census
# must show zero inversions AND a non-empty order graph (written to a
# file first: run_step retries must not re-read stdin)
cat > "$LOG/lockdep_gate.py" <<'PY'
import json, os, subprocess, sys

os.chdir("/root/repo")
report = "/tmp/tpu_jobs_r3/lockdep_report.json"
env = dict(os.environ, RAFT_LOCKDEP="1", RAFT_LOCKDEP_REPORT=report)
proc = subprocess.run(
    [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
     "-m", "not slow",
     "tests/test_serve_lifecycle.py", "tests/test_compaction.py",
     "tests/test_replication.py", "tests/test_fleet.py"],
    env=env)
assert proc.returncode == 0, proc.returncode
census = json.load(open(report))
assert census["inversion_total"] == 0, census["inversions"]
assert census["edges"], "no lock-order edges recorded — lockdep unarmed?"
print(json.dumps({"config": "lockdep_gate", "inversions": 0,
                  "edges": len(census["edges"])}))
PY
run_step lockdep_gate   1800 python "$LOG/lockdep_gate.py"
# QPS re-baseline with the instrumented-but-disarmed locks on the hot
# path: the serving curve must hold the r16 ratchet
run_step serve_bench    1800 env RAFT_BENCH_SERVE_ROWS=2000 \
  RAFT_BENCH_SERVE_DIM=32 RAFT_BENCH_SERVE_K=8 \
  RAFT_BENCH_SERVE_LADDER=1,8 RAFT_BENCH_SERVE_SECONDS=6 \
  python bench/serve.py
run_step bench         4500 python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
