#!/bin/bash
# Round-3 TPU job queue: waits for the axon tunnel to come back, then runs
# the benchmark/validation sequence in priority order, logging to /tmp.
# Safe to re-run; each step is skipped if its marker file exists.
# DEPRECATED in favor of scripts/tpu_jobs_r4.sh (risk-reordered ladder,
# measurement-gated markers).  Kept runnable; shares the queue lock so the
# two can never drive the tunnel concurrently.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r3

# a real computation, not just jax.devices(): backend init can succeed
# while the compute leg of the tunnel is wedged
# probe() comes from tpu_queue_lib.sh (600s timeout, stderr capture, 9<&-)

echo "$(date) waiting for TPU..." >> "$LOG/driver.log"
# Long sleeps between probes: each failed probe kills a client mid-init,
# which is itself the action that wedges the tunnel — aggressive polling
# can prevent the server-side grant from ever clearing.  Give the relay a
# quiet window, then test.
SLEEP_S=${TPU_PROBE_SLEEP:-1800}
until probe; do
  echo "$(date) probe failed; quiet for ${SLEEP_S}s" >> "$LOG/driver.log"
  sleep "$SLEEP_S"
done
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, command...  (bounded: a hung tunnel must not block
  local name=$1; shift            #  the rest of the queue)
  [ -f "$LOG/$name.done" ] && return 0
  echo "$(date) start $name" >> "$LOG/driver.log"
  if timeout 3000 "$@" > "$LOG/$name.log" 2>&1 9<&-; then
    touch "$LOG/$name.done"
    echo "$(date) done $name" >> "$LOG/driver.log"
  else
    rc=$?
    echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    # a killed client can wedge the tunnel for every later step; re-probe
    # before letting the queue continue
    until probe; do sleep 120; done
  fi
}

# 0. int8 MXU shortlist path must compile+rank on the real chip
run_step int8 python scripts/tpu_validate_int8.py
# 1. kernel profile + block-size sweep (informs any tuning before bench)
run_step profile python bench/profile_knn.py
# 2. select_k tuner re-run (fori_loop kernel fix may change winners/fix k=32)
run_step tuner python bench/tune_select_k.py
# 3. micro-bench ratchet baseline (records bench/PRIMS_HISTORY.json)
run_step prims python bench/prims.py
# 4. CAGRA quality table at 1M rows
run_step cagra_quality python bench/cagra_quality.py
# 5. the full north-star bench (what the driver will run at round end)
run_step bench python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
