#!/bin/bash
# Round-4 fast-path A/B ladder.  Waits for the round-3 TPU job queue to
# finish (single-client tunnel discipline: never two TPU clients at once),
# then measures the headline brute-force config under each tuning-knob
# combination from the decision tree (docs/perf_analysis.md), picks the
# winner, and re-runs the FULL bench ladder under it.
#
# Safe to re-run: each step is marker-file idempotent.  All runs are
# recall-gated (recall >= 0.999 or the fast path is rejected in-config)
# and ratchet BENCH_HISTORY.json only on genuine full-scale TPU wins.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_ab_r4
mkdir -p "$LOG"
R3LOG=/tmp/tpu_jobs_r3/driver.log
. "$(dirname "$0")/tpu_queue_lib.sh"

echo "$(date) waiting for the r3 queue to finish..." >> "$LOG/driver.log"
until [ -f "$R3LOG" ] && grep -q "all steps attempted" "$R3LOG"; do
  sleep 120
done
# take the shared tunnel lock, blocking: the marker line can be a stale one
# from an earlier completed round while a re-run queue is still mid-ladder —
# wait it out, however long
exec 9> /tmp/tpu_jobs_r3/queue.lock
flock 9
echo "$(date) r3 queue done; starting A/B" >> "$LOG/driver.log"

# .done requires an actual headline MEASUREMENT (see tpu_queue_lib.sh)
measured() { bench_measured "$1" brute_force; }

run_step() {
  local name=$1; shift
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt): $*" >> "$LOG/driver.log"
    timeout 1500 env "$@" python bench.py > "$LOG/$name.log" 2>&1 9<&-
    rc=$?
    if [ "$rc" -eq 0 ] && measured "$LOG/$name.log"; then
      touch "$LOG/$name.done"
      echo "$(date) done $name" >> "$LOG/driver.log"
      return 0
    fi
    echo "$(date) FAILED $name (rc=$rc; 124=timeout, 0=no measurement)" \
      >> "$LOG/driver.log"
    # a killed client can wedge the tunnel; re-probe with the lib's quiet-
    # window cadence (aggressive 120 s polling is the documented wedge
    # trigger), then retry once
    wait_probe
  done
}

# headline-only runs (north-star configs skipped) under each combo
SKIP=RAFT_BENCH_SKIP=ivf_pq,cagra,pairwise,ivf_flat
run_step ab_prec_high  "$SKIP" RAFT_BENCH_REFINE_PREC=high
run_step ab_cut_approx "$SKIP" RAFT_BENCH_CUT=approx
run_step ab_both       "$SKIP" RAFT_BENCH_CUT=approx RAFT_BENCH_REFINE_PREC=high
run_step ab_both_bm512 "$SKIP" RAFT_BENCH_CUT=approx RAFT_BENCH_REFINE_PREC=high RAFT_BENCH_BM=512
run_step ab_both_bn2k  "$SKIP" RAFT_BENCH_CUT=approx RAFT_BENCH_REFINE_PREC=high RAFT_BENCH_BN=2048

# pick the winning combo by recall-gated headline QPS and run the full
# ladder once under it (the r3 queue already measured the default combo).
# Winner selection requires EVERY A/B step to have completed — a winner
# computed from partial data must never get locked in by final.done
for s in ab_prec_high ab_cut_approx ab_both ab_both_bm512 ab_both_bn2k; do
  if [ ! -f "$LOG/$s.done" ]; then
    echo "$(date) $s incomplete; deferring winner selection to a re-run" \
      >> "$LOG/driver.log"
    exit 1
  fi
done
if [ ! -f "$LOG/final.done" ]; then
  best=$(python - "$LOG" <<'EOF'
import json, os, sys
log = sys.argv[1]
combos = {
    "ab_prec_high":  {"RAFT_BENCH_REFINE_PREC": "high"},
    "ab_cut_approx": {"RAFT_BENCH_CUT": "approx"},
    "ab_both":       {"RAFT_BENCH_CUT": "approx", "RAFT_BENCH_REFINE_PREC": "high"},
    "ab_both_bm512": {"RAFT_BENCH_CUT": "approx", "RAFT_BENCH_REFINE_PREC": "high", "RAFT_BENCH_BM": "512"},
    "ab_both_bn2k":  {"RAFT_BENCH_CUT": "approx", "RAFT_BENCH_REFINE_PREC": "high", "RAFT_BENCH_BN": "2048"},
}
best_name, best_qps = None, -1.0
for name, env in combos.items():
    try:
        lines = [ln for ln in open(os.path.join(log, name + ".log"))
                 if ln.startswith("{")]
        for ln in lines:
            d = json.loads(ln)
            # only genuine fast-path wins count: a combo that failed the
            # recall gate falls back to the exact path (path="exact") and
            # must not be crowned on the fallback's numbers
            if d.get("config", "").startswith("brute_force") and \
                    d.get("recall", 0) >= 0.999 and \
                    d.get("profile", {}).get("path") == "fast" and \
                    d.get("qps", 0) > best_qps:
                best_qps, best_name = d["qps"], name
    except (OSError, json.JSONDecodeError, ValueError):
        continue
if best_name is None:
    print("")
else:
    print(" ".join(f"{k}={v}" for k, v in combos[best_name].items()))
EOF
)
  echo "$(date) winning combo: '${best}'" >> "$LOG/driver.log"
  if [ -z "$best" ]; then
    # no combo beat the gate on the fast path — the default config (already
    # measured by the r3 queue) stands; re-running the full ladder under
    # default env would burn hours duplicating it
    echo "$(date) no gated fast-path winner; skipping final ladder" \
      >> "$LOG/driver.log"
    exit 0
  fi
  timeout 3000 env $best python bench.py > "$LOG/final.log" 2>&1 9<&-
  rc=$?
  # same measured() gate as the A/B steps: exit-0 on a wedged backend must
  # not latch final.done on an empty run
  if [ "$rc" -eq 0 ] && measured "$LOG/final.log"; then
    touch "$LOG/final.done"
    echo "$(date) final full ladder done" >> "$LOG/driver.log"
  else
    echo "$(date) final full ladder FAILED (rc=$rc)" >> "$LOG/driver.log"
  fi
fi
echo "$(date) A/B ladder complete" >> "$LOG/driver.log"
