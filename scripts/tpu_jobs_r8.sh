#!/bin/bash
# Round-8 TPU job queue.  The r7 ladder plus the round-8 addition:
#   * cagra_tuner — bench/tune_cagra.py measures the recall-gated
#     (itopk_size, search_width) table (raft_tpu/neighbors/
#     _cagra_search_table.json) that resolve_cagra_search's 0 = auto
#     consults, and writes the frontier-vs-per-parent A/B artifact
#     bench/CAGRA_FRONTIER_<BACKEND>.json.  Staged before cagra_quality
#     so the quality sweep's auto configs see the tuned table, and after
#     the generic benches (the tuner builds its own 40k index — cheap
#     next to bench.py but still chip time).
# Stage order: jaxlint -> Mosaic check -> build-throughput bench ->
# probe/chunk tuners -> bench.py -> select_k tuner -> prims ->
# cagra tuner -> cagra quality -> int8 -> profile.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated and tpu_ab_r4.sh's wait-chain keeps working.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r8

export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

# un-latch a bench.done that lacks a headline measurement (r3/r4 queues
# gated on any measured line; a wedged-headline run must be retried)
if [ -f "$LOG/bench.done" ] && \
    ! bench_measured "$LOG/bench.log" brute_force 2>/dev/null; then
  echo "$(date) removing stale bench.done (no headline measurement)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/bench.done"
fi

# the r8 frontier engine obsoletes any pre-r8 cagra_quality marker: the
# committed artifact must carry the new engine + scope fields
if [ -f "$LOG/cagra_quality.done" ] && \
    ! grep -q search_impl "$LOG/cagra_quality.log" 2>/dev/null && \
    ! grep -q search_impl bench/CAGRA_QUALITY.json 2>/dev/null; then
  echo "$(date) removing pre-r8 cagra_quality.done (no engine scope)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/cagra_quality.done"
fi

echo "$(date) [r8 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass, ~seconds, zero chip time — a hazard
# (hidden sync, retrace loop, f64 leak) must never cost TPU minutes to find
run_step jaxlint        300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
run_step build_tp      2400 python bench/build_throughput.py
# tuners before the big benches: all three have /tmp resume checkpoints
# (kernel-sha scoped), so a wedge mid-grid resumes on attempt 2
run_step probe_tuner   3000 python bench/tune_probe_block.py
run_step chunk_tuner   3000 python bench/tune_chunk_rows.py
run_step bench         4500 python bench.py
# the checkpoints exist to survive a wedge WITHIN a bench run; once the
# headline-gated .done latches they are spent — leaving them would turn a
# deliberately forced re-measurement (rm bench.done) into a silent replay
[ -f "$LOG/bench.done" ] && rm -rf "$RAFT_BENCH_CKPT_DIR"
run_step tuner         3000 python bench/tune_select_k.py
run_step prims         3000 python bench/prims.py
# cagra tuner immediately before the quality sweep: the sweep's auto
# (itopk=0/width=0) points must consult the table this run just measured
run_step cagra_tuner   3000 python bench/tune_cagra.py
run_step cagra_quality 3000 python bench/cagra_quality.py
run_step int8          1500 python scripts/tpu_validate_int8.py
run_step profile       3000 python bench/profile_knn.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
