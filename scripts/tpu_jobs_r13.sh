#!/bin/bash
# Round-13 TPU job queue: first hardware round for search-quality
# telemetry (raft_tpu.obs quality/drift/slo + neighbors.health —
# ISSUE 11).
#   * mosaic re-stamps bench/MOSAIC_CHECK.json first, as always — the
#     dispatch gate rejects stale kernel_sha stamps.
#   * quality_drill — the injected-regression drill from
#     tests/test_quality.py staged on real hardware: saturate the queue
#     so the ladder degrades, the shadow-sampled estimator catches the
#     recall drop, the recall SLO burns, and the guard pins dispatch
#     back to level 0.  The CPU tier proves the control loop; this step
#     proves the oracle (blocked_scan off the hot path) and the sampler
#     behave on the device that serves.
#   * obs_overhead_r13 — bench/obs_overhead.py re-run under a NEW
#     marker: the bench gained the quality-sampler arm this round, so
#     r12's obs_overhead.done must not short-circuit it.  Hardware
#     counterpart of the committed bench/QUALITY_OVERHEAD_CPU.json.
# Stage order: jaxlint -> mosaic -> quality drill -> obs overhead ->
# serve bench -> bench.py.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r13
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

echo "$(date) [r13 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass (quality/drift/slo/health carry
# explicit JX01 waivers on their oracle-side device_gets), zero chip time
run_step jaxlint_r13    300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates the kernels
# on hardware and stamps the sha-scoped artifact the dispatch gate needs
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# the quality-regression drill on hardware: recall drop at the degraded
# level -> estimator CI below floor -> recall SLO burn -> guard refuses
# the level (written to a file first: run_step retries must not re-read
# stdin)
cat > "$LOG/quality_drill_smoke.py" <<'PY'
import json, os, sys

sys.path.insert(0, os.getcwd())        # the queue runs this from /root/repo

import numpy as np
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs import QualityConfig, SloPolicy, SpanRecorder, parse_text
from raft_tpu.serve import SearchServer, ServerConfig

db = np.random.default_rng(7).standard_normal((4000, 32)).astype(np.float32)
index = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(
    n_lists=64, kmeans_n_iters=4))
# level 0 probes every list (exact); level 1's effort scale floors
# n_probes to 1 — a gross recall regression only queue pressure triggers
srv = SearchServer(index, k=8,
                   params=ivf_flat.IvfFlatSearchParams(n_probes=64),
                   config=ServerConfig(ladder=(8,), max_queue=16,
                                       max_wait_ms=0.0,
                                       degrade_queue_fractions=(0.25,),
                                       degrade_effort_scales=(1.0, 0.02)),
                   recorder=SpanRecorder(512))
est = srv.attach_quality(
    QualityConfig(sample_fraction=1.0, rows_cap=8),
    policy=SloPolicy(recall_floor=0.9, min_samples=4,
                     short_window=4, long_window=8),
    baseline_queries=db[:256])
srv.warmup()


def drive(n_parallel):
    futs = [srv.submit(db[(j * 8) % 256:(j * 8) % 256 + 8])
            for j in range(n_parallel)]
    while srv.step():
        pass
    for f in futs:
        f.result(timeout=60)
    est.drain()
    srv.slo.evaluate()


for _ in range(6):                       # healthy: level 0, recall ~1
    drive(1)
healthy = est.estimate(0)
assert healthy.samples >= 6 and healthy.ci_low > 0.9, est.stats()
drive(8)                                 # saturate -> level 1 regression
bad = est.estimate(1)
assert bad.samples >= 4 and bad.ci_high < 0.9, est.stats()
assert srv.slo.states["recall"] in ("warn", "page"), srv.slo.states
before = dict(srv.metrics.degrade_dispatches)
drive(8)                                 # guard pins dispatch to level 0
after = srv.metrics.degrade_dispatches
assert after.get(1, 0) == before.get(1, 0), (before, after)
assert srv.metrics.quality_guard_overrides > 0
assert srv.slo.states["recall"] == "ok", srv.slo.states
parsed = parse_text(srv.prometheus_text())
assert any(labels["slo"] == "recall" and v >= 1.0
           for labels, v in parsed["raft_slo_alerts_total"])
assert any(labels.get("stat") == "occupancy_cv"
           for labels, _ in parsed["raft_index_health"])
print(json.dumps({"config": "quality_drill_smoke",
                  "healthy_ci_low": round(healthy.ci_low, 4),
                  "degraded_ci_high": round(bad.ci_high, 4),
                  "overrides": srv.metrics.quality_guard_overrides,
                  "drift_psi": parsed["raft_quality_drift_psi"][0][1]}))
PY
run_step quality_drill  900 python "$LOG/quality_drill_smoke.py"
# telemetry overhead on hardware, now including the quality-sampler arm
run_step obs_overhead_r13 1800 python bench/obs_overhead.py
# serve bench rides along for the Prometheus surface under real load
run_step serve_bench   3000 python bench/serve.py
run_step bench         4500 python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
