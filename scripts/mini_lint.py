"""Minimal offline linter — a conservative subset of the ruff rules CI runs.

The container has no egress, so ruff itself cannot be installed here
(VERDICT r3 weak #8: lint is configured but has never run anywhere).  This
implements the highest-signal subset of ruff's default rule set (E4/E7/E9/F)
plus the two whitespace pre-commit hooks, so the first real CI run is not a
surprise:

* E9xx  — syntax/indentation errors (``compile()``)
* F401  — unused imports (``__all__``-exported and redundant-alias names
          exempt, matching ruff's re-export convention; ``__init__.py``
          re-exports listed in ``__all__`` are fine)
* E711/E712 — ``== None`` / ``== True`` / ``== False`` comparisons
* E722  — bare ``except:``
* E741  — ambiguous variable names ``l``, ``O``, ``I`` (assign/arg targets)
* F841  — local variable assigned but never used (simple assignments only)
* W291/W293 + end-of-file — trailing whitespace, missing/extra final newline

``--jax`` additionally runs the TPU-hazard analyzer
(:mod:`raft_tpu.analysis.jaxlint` — JX01..JX05, see docs/jax_hygiene.md)
over the same tree through the same reporting and exit-code contract;
``--stats-json PATH`` dumps the analyzer census (rules fired, waivers,
files scanned) as a JSON artifact.  ``--race`` does the same with the
concurrency analyzer (:mod:`raft_tpu.analysis.racelint` — JX10..JX14;
``--race-stats-json PATH`` for its census).  Analyzer modules are loaded
by file path, so running the linter never imports jax.

Exit 1 when findings exist.  ``--fix`` repairs the whitespace class only
(the code classes deserve human eyes).
"""

from __future__ import annotations

import ast
import os
import sys

SKIP_DIRS = {".git", "__pycache__", ".claude", "node_modules", ".venv"}


def py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # attribute roots count as use of the base name (handled via the
            # Name node of the base); nothing extra needed
            pass
    return used


def _exported(tree: ast.AST) -> set:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "__all__" in targets and isinstance(node.value,
                                                   (ast.List, ast.Tuple)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        out.add(elt.value)
    return out


def _f841_unused_locals(tree: ast.AST):
    """F841: locals assigned (simple single-``Name`` targets) and never
    loaded anywhere in the function subtree.  Tuple unpacking, attribute/
    subscript targets, augmented/annotated assigns, ``for``/``with``
    targets, underscore-prefixed names, and ``global``/``nonlocal`` names
    are all left alone — those are either intentional or another rule's
    business."""
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        loaded, escaped = set(), set()
        assigns = {}  # name -> first assign lineno
        # loads anywhere in the subtree count (a closure reading an outer
        # local is a use), but assigns are scope-confined — a nested def's
        # own locals belong to its visit, not its enclosing function's
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Load, ast.Del)):
                loaded.add(node.id)  # an explicit ``del x`` is a reference
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                escaped.update(node.names)
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and not t.id.startswith("_"):
                        assigns.setdefault(t.id, node.lineno)
            stack.extend(ast.iter_child_nodes(node))
        for name, lineno in sorted(assigns.items(), key=lambda kv: kv[1]):
            if name not in loaded and name not in escaped:
                out.append((lineno, name))
    return out


def check_file(path: str, fix: bool = False):
    findings = []
    with open(path, encoding="utf-8") as f:
        src = f.read()

    # E9: must parse
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, "E999", f"syntax error: {e.msg}")]

    used = _used_names(tree)
    exported = _exported(tree)
    # names referenced inside docstring doctests still count as used? ruff
    # says no — but our doctests exercise the module's own API via imports
    # local to the doctest, so module-level imports are unaffected.

    for node in ast.walk(tree):
        # F401 — only module-level imports (function-local lazy imports are
        # the codebase's idiom and are used immediately)
        if isinstance(node, (ast.Import, ast.ImportFrom)) \
                and node.col_offset == 0:
            for alias in node.names:
                name = alias.asname or alias.name
                if alias.name == "*":
                    continue
                if alias.asname and alias.asname == alias.name.split(".")[-1] \
                        and alias.asname != alias.name:
                    continue  # redundant alias = explicit re-export
                root_name = name.split(".")[0]
                if root_name in used or name in exported:
                    continue
                if isinstance(node, ast.ImportFrom) and node.module \
                        and node.module == "__future__":
                    continue
                findings.append((path, node.lineno, "F401",
                                 f"unused import: {name}"))
        elif isinstance(node, ast.Compare):
            for op, cmp_ in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(
                        cmp_, ast.Constant) and (cmp_.value is None
                                                 or cmp_.value is True
                                                 or cmp_.value is False):
                    code = "E711" if cmp_.value is None else "E712"
                    findings.append((path, node.lineno, code,
                                     f"comparison to {cmp_.value} with =="))
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append((path, node.lineno, "E722", "bare except"))
        elif isinstance(node, (ast.Name, ast.arg)):
            ident = node.id if isinstance(node, ast.Name) else node.arg
            storing = isinstance(node, ast.arg) or isinstance(
                getattr(node, "ctx", None), ast.Store)
            if storing and ident in ("l", "O", "I"):
                findings.append((path, node.lineno, "E741",
                                 f"ambiguous variable name {ident!r}"))

    for lineno, name in _f841_unused_locals(tree):
        findings.append((path, lineno, "F841",
                         f"local variable {name!r} assigned but never used"))

    # whitespace hooks
    lines = src.split("\n")
    dirty = False
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            findings.append((path, i, "W291", "trailing whitespace"))
            dirty = True
    if src and not src.endswith("\n"):
        findings.append((path, len(lines), "W292", "no newline at EOF"))
        dirty = True
    if src.endswith("\n\n") and src.strip():
        findings.append((path, len(lines), "W391", "blank line(s) at EOF"))
        dirty = True
    if fix and dirty:
        fixed = "\n".join(ln.rstrip() for ln in lines).rstrip("\n") + "\n"
        with open(path, "w", encoding="utf-8") as f:
            f.write(fixed)

    return findings


def _load_analyzer(name: str):
    """Load an analyzer module by file path — never imports raft_tpu (and
    therefore never imports jax): the linter must run on a bare host."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mod_path = os.path.join(repo, "raft_tpu", "analysis", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, mod_path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module  # dataclasses needs the module registered
    spec.loader.exec_module(module)
    return module


def _load_jaxlint():
    return _load_analyzer("jaxlint")


def _run_analyzer(name: str, root: str, stats_path, all_findings) -> str:
    """Run one analyzer over ``root`` through the shared reporting/exit
    contract; returns the summary note for the footer line."""
    mod = _load_analyzer(name)
    rep = mod.scan_tree(root)
    for f in rep.findings:
        all_findings.append((f.path, f.line, f.code, f.msg))
    note = (f"; {name}: {rep.files} files, "
            f"{len(rep.findings)} active, {len(rep.waived)} waived")
    if stats_path:
        import json

        os.makedirs(os.path.dirname(stats_path) or ".", exist_ok=True)
        with open(stats_path, "w", encoding="utf-8") as fh:
            json.dump(rep.stats(), fh, indent=2, sort_keys=True)
            fh.write("\n")
        note += f"; stats -> {stats_path}"
    return note


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    fix = "--fix" in argv
    jax_pass = "--jax" in argv
    race_pass = "--race" in argv
    stats_path = None
    if "--stats-json" in argv:
        stats_path = argv[argv.index("--stats-json") + 1]
    race_stats_path = None
    if "--race-stats-json" in argv:
        race_stats_path = argv[argv.index("--race-stats-json") + 1]
    skip_next = False
    root = "."
    for a in argv:
        if skip_next:
            skip_next = False
            continue
        if a in ("--stats-json", "--race-stats-json"):
            skip_next = True
        elif not a.startswith("-"):
            root = a
            break
    all_findings = []
    n = 0
    for path in sorted(py_files(root)):
        n += 1
        all_findings.extend(check_file(path, fix=fix))

    jax_note = ""
    if jax_pass:
        jax_note += _run_analyzer("jaxlint", root, stats_path, all_findings)
    if race_pass:
        jax_note += _run_analyzer("racelint", root, race_stats_path,
                                  all_findings)

    for path, line, code, msg in all_findings:
        print(f"{path}:{line}: {code} {msg}")
    print(f"mini-lint: {n} files, {len(all_findings)} finding(s)"
          f"{' (whitespace auto-fixed)' if fix else ''}{jax_note}",
          file=sys.stderr)
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main())
