#!/bin/bash
# Round-14 TPU job queue: first hardware round for the RaBitQ 1-bit IVF
# tier (raft_tpu.neighbors.ivf_rabitq — ISSUE 13).
#   * mosaic re-stamps bench/MOSAIC_CHECK.json first, as always — the
#     dispatch gate rejects stale kernel_sha stamps (scan_kernel_sha now
#     also covers the packed-sign helpers in ops/blocked_scan.py, so
#     both the fused-scan stamp and the rabitq tune table went stale
#     this round by construction).
#   * rabitq_smoke — the exactness oracle on hardware: rerank_k = n must
#     be bit-identical (values AND ids) to brute force, the packed-sign
#     int8 einsum must hit the MXU path, and a serialize v4 roundtrip
#     must survive.  The CPU tier already proves all three; this step
#     proves them on the device that serves.
#   * tune_rabitq — writes the CANONICAL recall-gated
#     (rerank_k, probe_block) table (_rabitq_tune_table.json): only a
#     TPU run may stamp the un-suffixed table the search paths consult.
#   * rabitq_ab — the estimator-scan vs ivf_pq recon-tier A/B
#     (bench/RABITQ_TPU.json), hardware counterpart of the committed
#     bench/RABITQ_CPU.json.
# Stage order: jaxlint -> mosaic -> rabitq smoke -> tuner -> A/B ->
# ann bench rabitq arm -> bench.py.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r14
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

echo "$(date) [r14 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass (ivf_rabitq's rerank resolve + the
# health/quality oracle device_gets carry explicit JX01 waivers), zero
# chip time
run_step jaxlint_r14    300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates the kernels
# on hardware and stamps the sha-scoped artifact the dispatch gate needs
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# the exactness + lifecycle smoke on hardware (written to a file first:
# run_step retries must not re-read stdin)
cat > "$LOG/rabitq_smoke.py" <<'PY'
import json, os, sys, tempfile

sys.path.insert(0, os.getcwd())        # the queue runs this from /root/repo

import numpy as np
from raft_tpu.neighbors import brute_force, ivf_rabitq, serialize
from raft_tpu.stats import neighborhood_recall

rng = np.random.default_rng(7)
db = rng.integers(0, 256, (6000, 64)).astype(np.float32)   # integer-valued:
q = rng.integers(0, 256, (32, 64)).astype(np.float32)      # bitwise oracle
index = ivf_rabitq.build(db, ivf_rabitq.IvfRabitqIndexParams(
    n_lists=16, kmeans_n_iters=8, list_cap_ratio=2.0))
bd, bi = brute_force.knn(q, db, 10)
# rerank everything probed at total coverage == brute force, bit for bit
d, i = ivf_rabitq.search(index, q, 10, ivf_rabitq.IvfRabitqSearchParams(
    n_probes=16, rerank_k=db.shape[0]))
np.testing.assert_array_equal(np.asarray(i), np.asarray(bi))
np.testing.assert_array_equal(np.asarray(d), np.asarray(bd))
# the estimator tier at a realistic rerank budget
d8, i8 = ivf_rabitq.search(index, q, 10, ivf_rabitq.IvfRabitqSearchParams(
    n_probes=8, rerank_k=160))
recall = float(neighborhood_recall(np.asarray(i8), np.asarray(bi)))
assert recall > 0.85, recall
# serialize v4 survives the device roundtrip
with tempfile.TemporaryDirectory() as td:
    p = os.path.join(td, "rq")
    serialize.save(p, index)
    re = serialize.load(p)
    assert serialize.verify_index(re) == []
    d2, i2 = ivf_rabitq.search(re, q, 10, ivf_rabitq.IvfRabitqSearchParams(
        n_probes=8, rerank_k=160))
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i8))
print(json.dumps({"config": "rabitq_smoke", "bitwise_vs_brute": True,
                  "recall_p8_r160": round(recall, 4)}))
PY
run_step rabitq_smoke   900 python "$LOG/rabitq_smoke.py"
# the canonical recall-gated tune table — TPU runs write the un-suffixed
# file the search paths consult (off-TPU runs self-quarantine)
run_step tune_rabitq   3600 python bench/tune_rabitq.py
# estimator scan vs ivf_pq recon tier at matched recall, plus the
# codebook-free build race -> bench/RABITQ_TPU.json
run_step rabitq_ab     3600 python bench/rabitq_ab.py
# the standing ann bench gains the rabitq arm's curve on hardware
run_step ann_rabitq    1800 python bench/ann_bench.py ivf_rabitq --base synthetic:1000000x64
run_step bench         4500 python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
