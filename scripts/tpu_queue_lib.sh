# Shared helpers for the TPU job-queue scripts (tpu_jobs_r3.sh,
# tpu_jobs_r4.sh, tpu_ab_r4.sh).  Source after setting LOG:
#   LOG=/tmp/tpu_jobs_r3; . "$(dirname "$0")/tpu_queue_lib.sh"
# Single-client tunnel discipline lives here so every queue enforces the
# same rules and fixes land exactly once.

# Generous timeout: the tunnel can take minutes to grant a new client
# after the previous one exits, and killing a would-have-succeeded client
# mid-init is the very action that wedges the grant.  stderr accumulates
# (append) so wedge-era diagnostics survive the recovering probe.
# 9<&- : children must not inherit the queue-lock fd — an orphaned child
# of a killed queue would otherwise hold the flock until it exits.
probe() {
  timeout "${TPU_PROBE_TIMEOUT:-600}" python -c "import jax, jax.numpy as jnp; (jnp.ones((8,8)) @ jnp.ones((8,8))).sum().item()" \
    > /dev/null 2>> "$LOG/probe_stderr.log" 9<&-
}

# Long quiet windows between failed probes: losing chip minutes to a
# sleep beats extending a wedge with another killed client.
wait_probe() {
  local sleep_s="${TPU_PROBE_SLEEP:-1200}"
  until probe; do
    echo "$(date) probe failed; quiet for ${sleep_s}s" >> "$LOG/driver.log"
    sleep "$sleep_s" 9<&-
  done
}

# All queue scripts share one flock: exactly one may drive the tunnel.
# Call with the script's own name for the log line.
acquire_queue_lock() {
  exec 9> "$LOG/queue.lock"
  if ! flock -n 9; then
    echo "$(date) $1: another queue holds $LOG/queue.lock; exiting" >&2
    exit 1
  fi
}

# bench.py exits 0 even on a wedged backend (by design: the round driver
# must always get a final line), so exit status alone must never latch a
# .done marker — require an actual measurement in the log.  Optional 2nd
# arg restricts the check to configs whose name starts with that prefix.
bench_measured() {
  python - "$1" "${2:-}" <<'EOF'
import json, sys
path, prefix = sys.argv[1], sys.argv[2]
ok = False
for ln in open(path):
    if not ln.startswith("{"):
        continue
    try:
        d = json.loads(ln)
    except ValueError:
        continue
    if prefix and not d.get("config", "").startswith(prefix):
        continue
    if d.get("qps", 0) > 0 or d.get("tflops", 0) > 0:
        ok = True
sys.exit(0 if ok else 1)
EOF
}
