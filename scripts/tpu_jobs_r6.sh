#!/bin/bash
# Round-6 TPU job queue.  The r5 ladder plus one round-6 addition:
#   * probe_tuner — bench/tune_probe_block.py writes the measured
#     probe_block dispatch table (raft_tpu/neighbors/_probe_block_table
#     .json) the blocked IVF scans consult.  Staged right after jaxlint:
#     it is cheap next to bench.py, and its table influences how every
#     later IVF bench config runs, so it must land before them.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated and tpu_ab_r4.sh's wait-chain keeps working.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r6

export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

# un-latch a bench.done that lacks a headline measurement (r3/r4 queues
# gated on any measured line; a wedged-headline run must be retried)
if [ -f "$LOG/bench.done" ] && \
    ! bench_measured "$LOG/bench.log" brute_force 2>/dev/null; then
  echo "$(date) removing stale bench.done (no headline measurement)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/bench.done"
fi

echo "$(date) [r6 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass, ~seconds, zero chip time — a hazard
# (hidden sync, retrace loop, f64 leak) must never cost TPU minutes to find
run_step jaxlint        300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# probe_tuner before the big benches: it has its own /tmp resume
# checkpoint (kernel-sha scoped), so a wedge mid-grid resumes on attempt 2
run_step probe_tuner   3000 python bench/tune_probe_block.py
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
run_step bench         4500 python bench.py
# the checkpoints exist to survive a wedge WITHIN a bench run; once the
# headline-gated .done latches they are spent — leaving them would turn a
# deliberately forced re-measurement (rm bench.done) into a silent replay
[ -f "$LOG/bench.done" ] && rm -rf "$RAFT_BENCH_CKPT_DIR"
run_step tuner         3000 python bench/tune_select_k.py
run_step prims         3000 python bench/prims.py
run_step cagra_quality 3000 python bench/cagra_quality.py
run_step int8          1500 python scripts/tpu_validate_int8.py
run_step profile       3000 python bench/profile_knn.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
