#!/bin/bash
# Round-4 TPU job queue — replaces tpu_jobs_r3.sh with a risk-reordered
# ladder.  Rationale: the tunnel has wedged twice (r3 whole-round, r4 at
# 03:50 UTC); if uptime is scarce, the north-star bench entries are worth
# more than any tuning step, so they go FIRST.  Order:
#   1. bench          — full 5-config ladder; ratchets BENCH_HISTORY.json
#   2. tuner          — select_k table regen (direct prod-bucket entry)
#   3. prims          — TPU micro-bench ratchet baseline
#   4. cagra_quality  — 1M-row quality table
#   5. int8           — int8 MXU shortlist compile/rank validation
#   6. profile        — stage-by-stage flagship profile (diagnostic)
# Markers live in the SAME dir as the r3 queue (/tmp/tpu_jobs_r3) so
# tpu_ab_r4.sh's wait-for-"all steps attempted" chain keeps working and
# any step the r3 queue already completed is not repeated.  The shared
# queue.lock (tpu_queue_lib.sh) enforces one queue per tunnel.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r4

# a bench.done latched by the r3 queue's status-only gate (or an earlier
# r4 run against a wedged backend) must not skip the top-priority step
if [ -f "$LOG/bench.done" ] && ! bench_measured "$LOG/bench.log" 2>/dev/null; then
  echo "$(date) removing stale bench.done (no measurement in bench.log)" >> "$LOG/driver.log"
  rm -f "$LOG/bench.done"
fi

echo "$(date) [r4 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log"; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

run_step bench         4500 python bench.py
run_step tuner         3000 python bench/tune_select_k.py
run_step prims         3000 python bench/prims.py
run_step cagra_quality 3000 python bench/cagra_quality.py
run_step int8          1500 python scripts/tpu_validate_int8.py
run_step profile       3000 python bench/profile_knn.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
