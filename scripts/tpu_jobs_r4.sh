#!/bin/bash
# Round-4 TPU job queue — replaces tpu_jobs_r3.sh with a risk-reordered
# ladder.  Rationale: the tunnel has wedged twice (r3 whole-round, r4 at
# 03:50 UTC); if uptime is scarce, the north-star bench entries are worth
# more than any tuning step, so they go FIRST.  Order:
#   1. bench          — full 5-config ladder; ratchets BENCH_HISTORY.json
#   2. tuner          — select_k table regen (direct prod-bucket entry)
#   3. prims          — TPU micro-bench ratchet baseline
#   4. cagra_quality  — 1M-row quality table
#   5. int8           — int8 MXU shortlist compile/rank validation
#   6. profile        — stage-by-stage flagship profile (diagnostic)
# Markers live in the SAME dir as the r3 queue (/tmp/tpu_jobs_r3) so
# tpu_ab_r4.sh's wait-for-"all steps attempted" chain keeps working and
# any step the r3 queue already completed is not repeated.  Only ONE of
# tpu_jobs_r3.sh / tpu_jobs_r4.sh may run at a time (single-client tunnel).
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"

# single-queue lock: r3/r4 queue scripts share the marker dir and the
# single-client tunnel, so exactly one may run
exec 9> "$LOG/queue.lock"
if ! flock -n 9; then
  echo "$(date) another queue instance holds $LOG/queue.lock; exiting" >&2
  exit 1
fi

probe() { timeout 120 python -c "import jax, jax.numpy as jnp; (jnp.ones((8,8)) @ jnp.ones((8,8))).sum().item()" >/dev/null 2>&1; }

wait_probe() {
  until probe; do
    echo "$(date) probe failed; quiet for ${SLEEP_S}s" >> "$LOG/driver.log"
    sleep "$SLEEP_S"
  done
}

# bench.py exits 0 even on a wedged backend (by design: the round driver
# must always get a final line), so exit status alone must never latch
# bench.done — require an actual qps measurement in the log.
bench_measured() {
  python - "$1" <<'EOF'
import json, sys
ok = False
for ln in open(sys.argv[1]):
    if not ln.startswith("{"):
        continue
    try:
        d = json.loads(ln)
    except ValueError:
        continue
    if d.get("qps", 0) > 0 or d.get("tflops", 0) > 0:
        ok = True
sys.exit(0 if ok else 1)
EOF
}

# a bench.done latched by the r3 queue's status-only gate (or an earlier
# r4 run against a wedged backend) must not skip the top-priority step
if [ -f "$LOG/bench.done" ] && ! bench_measured "$LOG/bench.log" 2>/dev/null; then
  echo "$(date) removing stale bench.done (no measurement in bench.log)" >> "$LOG/driver.log"
  rm -f "$LOG/bench.done"
fi

echo "$(date) [r4 queue] waiting for TPU..." >> "$LOG/driver.log"
# Long quiet windows: a probe killed mid-init is itself what wedges the
# tunnel, so losing chip minutes to a sleep beats extending the wedge.
SLEEP_S=${TPU_PROBE_SLEEP:-1200}
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log"; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

run_step bench         4500 python bench.py
run_step tuner         3000 python bench/tune_select_k.py
run_step prims         3000 python bench/prims.py
run_step cagra_quality 3000 python bench/cagra_quality.py
run_step int8          1500 python scripts/tpu_validate_int8.py
run_step profile       3000 python bench/profile_knn.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
