"""Flagship IVF-PQ at its real scale: 10M×96 ``build_chunked`` + laddered
search (VERDICT r4 next #5).

``bench.py`` defaults ``PQ_ROWS=10_000_000`` but no executed run had ever
used it — the r4 validation stopped at 1M (where it found the
refine-ratio null-metric bug; this run either validates or falsifies that
ladder at the scale it was designed for).  On CPU the build phase is
accepted at full cost while search validation is bounded to a query
subsample (``--nq``, default 1000).  On TPU (no ``--cpu``) the full 10k
query set is used.

Delegates to ``bench._bench_ivf_pq`` — the ladder policy lives exactly
once, so this artifact is evidence about the same code the bench ladder
runs.  Writes sweep-point progress JSON lines and a final backend-stamped
artifact to ``bench/IVF_PQ_<scale>_<BACKEND>.json`` (``10M`` only for an
exactly-10M-row run; other scales are named by row count).
"""

import argparse
import datetime
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "bench"))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))


_raw = None  # duplicate of stdout, kept NEXT TO the artifact (not tmpfs)


def log(**kw):
    line = json.dumps(kw)
    print(line, flush=True)
    if _raw is not None:
        _raw.write(line + "\n")
        _raw.flush()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--nq", type=int, default=None,
                    help="query count (default: 1000 on cpu, 10000 else)")
    args = ap.parse_args()

    import jax

    from _platform import pin_backend

    pin_backend(sys.argv)

    import bench

    backend = jax.default_backend()
    nq = args.nq or (1000 if backend == "cpu" else 10_000)
    # the canonical 10M name is reserved for exactly-full-scale runs — a
    # reduced smoke OR an enlarged run must never overwrite the real
    # artifact under the wrong label
    scale = "10M" if args.rows == 10_000_000 else str(args.rows)
    out_path = os.path.join(_ROOT, "bench",
                            f"IVF_PQ_{scale}_{backend.upper()}.json")

    # raw run log next to the artifact, written as the run goes: the
    # original 10M CPU run's only log lived on tmpfs and died with the
    # container (see IVF_PQ_10M_CPU.provenance.md) — a multi-hour
    # measurement must never again depend on stdout capture for survival
    global _raw
    _raw = open(out_path.replace(".json", ".run.log"), "w")

    log(stage="start", rows=args.rows, nq=nq, backend=backend,
        argv=sys.argv[1:])
    t0 = time.time()
    res = bench._bench_ivf_pq(rows=args.rows, nq=nq,
                              on_point=lambda pt: log(stage="sweep", **pt))
    art = {**res, "backend": backend,
           "date": datetime.date.today().isoformat(),
           "total_s": round(time.time() - t0, 1)}
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    log(stage="done", out=out_path, build_s=art["build_s"],
        qps_at_recall95=art["qps_at_recall95"], best=art["best"])


if __name__ == "__main__":
    main()
