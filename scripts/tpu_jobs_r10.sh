#!/bin/bash
# Round-10 TPU job queue.  The r9 ladder plus the round-10 additions:
#   * crash_recovery — tests/test_durability.py against the real
#     backend: a subprocess is SIGKILL-equivalently aborted at every
#     armed crash site (wal_append / extend / snapshot / rename /
#     compact) and recovery must land bit-identically; corruption
#     drills must quarantine, never parse.  Staged right after jaxlint
#     alongside the chaos smoke — both are cheap and a failure means
#     serving durability regressed, which should gate the expensive
#     benches.
#   * serve_recovery — bench/serve.py in recovery-time mode
#     (RAFT_BENCH_SERVE_RECOVERY): restore + replay + first answered
#     query, swept over WAL tail lengths — the on-hardware counterpart
#     of the committed bench/RECOVERY_CPU.json snapshot-cadence curve.
# Stage order: jaxlint -> chaos smoke -> crash recovery -> Mosaic check
# -> build-throughput -> mutation throughput -> probe/chunk tuners ->
# bench.py -> select_k tuner -> prims -> cagra tuner -> cagra quality ->
# serve swap -> serve recovery -> int8 -> profile.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated and tpu_ab_r4.sh's wait-chain keeps working.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r10

export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

# un-latch a bench.done that lacks a headline measurement (r3/r4 queues
# gated on any measured line; a wedged-headline run must be retried)
if [ -f "$LOG/bench.done" ] && \
    ! bench_measured "$LOG/bench.log" brute_force 2>/dev/null; then
  echo "$(date) removing stale bench.done (no headline measurement)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/bench.done"
fi

# r10 regrew the census (wal.py/compaction.py scanned; the brute-compact
# rewrite shifted mutation.py's waiver lines): a pre-r10 jaxlint.done
# would leave the stale census committed
if [ -f "$LOG/jaxlint.done" ] && \
    ! grep -q "mutation.py:112" bench/JAXLINT.json 2>/dev/null; then
  echo "$(date) removing pre-r10 jaxlint.done (stale waiver census)" \
    >> "$LOG/driver.log"
  rm -f "$LOG/jaxlint.done"
fi

echo "$(date) [r10 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass, ~seconds, zero chip time — a hazard
# (hidden sync, retrace loop, f64 leak) must never cost TPU minutes to find
run_step jaxlint        300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# chaos smoke: small index, short sweep, faults armed — two wedged
# dispatches (recovered by retry) and one failed swap (rolled back).
run_step chaos_smoke    900 env RAFT_SERVE_FAULTS="execute:wedge:2,swap:fail" \
    RAFT_BENCH_SERVE_ROWS=20000 RAFT_BENCH_SERVE_SECONDS=2 \
    RAFT_BENCH_SERVE_CLIENTS=2,4 RAFT_BENCH_SERVE_SWAPS=2 \
    python bench/serve.py
# crash-recovery smoke: every armed crash site killed mid-operation must
# recover bit-identically, corruption must quarantine (subprocess drills)
run_step crash_recovery 1200 python -m pytest tests/test_durability.py \
    tests/test_wal.py tests/test_compaction.py -q -p no:cacheprovider
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
run_step build_tp      2400 python bench/build_throughput.py
run_step mutation_tp   2400 python bench/mutation_throughput.py
# tuners before the big benches: all three have /tmp resume checkpoints
# (kernel-sha scoped), so a wedge mid-grid resumes on attempt 2
run_step probe_tuner   3000 python bench/tune_probe_block.py
run_step chunk_tuner   3000 python bench/tune_chunk_rows.py
run_step bench         4500 python bench.py
# the checkpoints exist to survive a wedge WITHIN a bench run; once the
# headline-gated .done latches they are spent — leaving them would turn a
# deliberately forced re-measurement (rm bench.done) into a silent replay
[ -f "$LOG/bench.done" ] && rm -rf "$RAFT_BENCH_CKPT_DIR"
run_step tuner         3000 python bench/tune_select_k.py
run_step prims         3000 python bench/prims.py
# cagra tuner immediately before the quality sweep: the sweep's auto
# (itopk=0/width=0) points must consult the table this run just measured
run_step cagra_tuner   3000 python bench/tune_cagra.py
run_step cagra_quality 3000 python bench/cagra_quality.py
# swap-under-load at bench scale, no faults: the recorded handoff numbers
# (drops, p95 during swap, recompiles) for the round artifact
run_step serve_swap    2400 env RAFT_BENCH_SERVE_SWAPS=3 python bench/serve.py
# recovery-time curve at bench scale: restore + replay vs WAL tail length
run_step serve_recovery 2400 env RAFT_BENCH_SERVE_RECOVERY=0,64,256 \
    RAFT_BENCH_SERVE_ROWS=100000 python bench/serve.py
run_step int8          1500 python scripts/tpu_validate_int8.py
run_step profile       3000 python bench/profile_knn.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
