#!/bin/bash
# Round-12 TPU job queue: first hardware round for the unified telemetry
# subsystem (raft_tpu.obs — ISSUE 9).
#   * mosaic re-stamps bench/MOSAIC_CHECK.json first, as always: the
#     dispatch gate rejects stale kernel_sha stamps, and every gate
#     fallback is now a COUNTED event
#     (raft_pallas_gate_fallback_total{kernel,reason}) — after this
#     round the scrape body is where "replica silently on stock XLA"
#     shows up, so the stamp must be fresh before anything dispatches.
#   * obs_watchdog — the stall-watchdog smoke on real hardware: a serve
#     loop with an injected wedge must trip StallWatchdog, leave a
#     stall-*/ dump (flight recorder + metrics + jax.profiler capture
#     with capture_s > 0 — the CPU tier runs capture_s=0) and keep
#     answering.  This is the BENCH_r04/r05 failure mode finally
#     producing evidence instead of a bench timeout.
#   * obs_overhead — bench/obs_overhead.py on TPU: spans-on vs spans-off
#     per-request cost, hardware counterpart of the committed
#     bench/OBS_OVERHEAD_CPU.json.
# Stage order: jaxlint -> mosaic -> watchdog smoke -> obs overhead ->
# serve bench -> bench.py.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r12
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

echo "$(date) [r12 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass (now covers raft_tpu/obs), zero chip time
run_step jaxlint_r12    300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates the kernels
# on hardware and stamps the sha-scoped artifact the dispatch gate needs
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# stall-watchdog smoke + profiler capture: wedge-fault a serve loop on
# hardware, require a stall dump with a non-empty profile/ capture
# (written to a file first: run_step retries must not re-read stdin)
cat > "$LOG/obs_watchdog_smoke.py" <<'PY'
import glob, json, os, sys, tempfile

sys.path.insert(0, os.getcwd())        # the queue runs this from /root/repo

import numpy as np
from raft_tpu.obs import SpanRecorder
from raft_tpu.serve import (FaultInjector, RetryPolicy, SearchServer,
                            ServerConfig)

db = np.random.default_rng(0).standard_normal((20000, 64)).astype(np.float32)
qdir = tempfile.mkdtemp(prefix="raft-stall-")
rec = SpanRecorder(2048)
dumps = []
srv = SearchServer(db, k=10,
                   config=ServerConfig(ladder=(8,),
                                       retry=RetryPolicy(max_retries=2)),
                   recorder=rec, faults=FaultInjector(),
                   sleep=lambda s: dumps.append(wd.check(now=srv.clock()
                                                         + 60.0)))
wd = srv.attach_watchdog(qdir, stall_timeout_s=30.0, capture_s=0.5)
srv.warmup()
d, i = srv.search(db[:4])                      # healthy baseline
srv.faults.arm("execute", "wedge", times=1)
d, i = srv.search(db[:4])                      # wedged, retried, answered
dump = next(d for d in dumps if d)
cap = json.load(open(os.path.join(dump, "capture.json")))
assert srv.metrics.stalls == 1, srv.metrics.snapshot()
assert cap.get("ok"), cap                      # profiler captured for real
assert glob.glob(os.path.join(dump, "profile", "**", "*.pb"),
                 recursive=True) or \
    glob.glob(os.path.join(dump, "profile", "**", "*.json"),
              recursive=True), "empty profiler capture"
print(json.dumps({"config": "obs_watchdog_smoke", "dump": dump,
                  "stalls": srv.metrics.stalls, "capture": cap}))
PY
run_step obs_watchdog   900 python "$LOG/obs_watchdog_smoke.py"
# telemetry overhead on hardware: spans-on vs spans-off serve loop
# (hardware counterpart of bench/OBS_OVERHEAD_CPU.json)
run_step obs_overhead  1800 python bench/obs_overhead.py
# serve bench rides along for the Prometheus surface under real load
run_step serve_bench   3000 python bench/serve.py
run_step bench         4500 python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
