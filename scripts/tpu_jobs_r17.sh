#!/bin/bash
# Round-17 TPU job queue: first hardware round for the pod-scale
# serving fleet (raft_tpu.serve.fleet — ISSUE 16).
#   * mosaic re-stamps bench/MOSAIC_CHECK.json first, as always — the
#     dispatch gate rejects stale kernel_sha stamps.
#   * fleet_smoke — the fan-out contract where the mesh is real: the
#     comms selftest battery must pass on the hardware collectives, and
#     the shard_map fan-out (brute + ivf_flat + ivf_rabitq) must be
#     bit-identical — values AND ids — to the single-device searcher at
#     the full device width, THROUGH FleetServer's router, including a
#     replica-kill shed and a lease-expiry promote sweep.
#   * fleet_bench — the multi-process closed-loop driver
#     (RAFT_BENCH_SERVE_REPLICAS): replica workers are host processes
#     here exactly as on CPU (one accelerator host = one replica in a
#     real pod; a TPU chip cannot be shared across processes), so the
#     step runs --cpu on the host and the harvested final line becomes
#     FLEET_TPUHOST.json next round.  The CPU curve is committed as
#     bench/FLEET_CPU.json.
# Stage order: jaxlint -> mosaic -> fleet smoke -> fleet bench ->
# bench.py.
# Markers stay in /tmp/tpu_jobs_r3 so steps completed by earlier rounds'
# queues are not repeated.
set -u
cd /root/repo || exit 1
LOG=/tmp/tpu_jobs_r3
mkdir -p "$LOG"
. "$(dirname "$0")/tpu_queue_lib.sh"
acquire_queue_lock tpu_jobs_r17
export RAFT_BENCH_CKPT_DIR="$LOG/bench_ckpt"

echo "$(date) [r17 queue] waiting for TPU..." >> "$LOG/driver.log"
wait_probe
echo "$(date) TPU is back" >> "$LOG/driver.log"

run_step() {  # name, timeout_s, command...   (two attempts, gated .done)
  local name=$1 tmo=$2; shift 2
  [ -f "$LOG/$name.done" ] && return 0
  local attempt
  for attempt in 1 2; do
    echo "$(date) start $name (attempt $attempt)" >> "$LOG/driver.log"
    timeout "$tmo" "$@" > "$LOG/$name.$attempt.log" 2>&1 9<&-
    rc=$?
    cp -f "$LOG/$name.$attempt.log" "$LOG/$name.log"  # latest = canonical
    if [ "$rc" -eq 0 ]; then
      if [ "$name" != bench ] || bench_measured "$LOG/$name.log" brute_force; then
        touch "$LOG/$name.done"
        echo "$(date) done $name" >> "$LOG/driver.log"
        return 0
      fi
      echo "$(date) $name exited 0 with no headline measurement (wedged backend)" \
        >> "$LOG/driver.log"
    else
      echo "$(date) FAILED $name (rc=$rc)" >> "$LOG/driver.log"
    fi
    # a killed/wedged client can poison the tunnel for the next step too:
    # re-probe before the retry (or before handing on to the next step)
    wait_probe
  done
}

# jaxlint first: pure-host AST pass over the new fleet/placement modules
# (the fan-out itself is shard_map over existing kernels — zero new
# device entry points to waive), zero chip time
run_step jaxlint_r17    300 python scripts/mini_lint.py --jax raft_tpu --stats-json bench/JAXLINT.json
# mosaic BEFORE anything that dispatches Pallas: re-validates the kernels
# on hardware and stamps the sha-scoped artifact the dispatch gate needs
run_step mosaic         900 env RAFT_MOSAIC_REQUIRE_TPU=1 python scripts/mosaic_check.py
# the fan-out contract on real collectives (written to a file first:
# run_step retries must not re-read stdin)
cat > "$LOG/fleet_smoke.py" <<'PY'
import json, os, sys, tempfile

sys.path.insert(0, os.getcwd())        # the queue runs this from /root/repo

import jax
import numpy as np
from jax.sharding import Mesh

from raft_tpu.comms import Comms, verify_comms
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_rabitq, mutation
from raft_tpu.serve import (FleetServer, ReplicationConfig, ServerConfig,
                            make_fleet_searcher, make_searcher)

devs = jax.devices()
assert len(devs) >= 2, devs
mesh = Mesh(np.asarray(devs), ("shard",))
selftest = verify_comms(Comms(mesh, "shard"))
assert selftest and all(selftest.values()), selftest

rng = np.random.default_rng(42)
db = rng.standard_normal((4096, 64)).astype(np.float32)
q = (1.3 * rng.standard_normal((16, 64))).astype(np.float32)
K = 10

def check(tag, index, params, **kw):
    fn, ops = make_fleet_searcher(index, K, params, mesh=mesh, **kw)
    rfn, rops = make_searcher(index, K, params, **kw)
    d, i = fn(q, *ops)
    rd, ri = rfn(q, *rops)
    np.testing.assert_array_equal(np.asarray(jax.device_get(d)),
                                  np.asarray(jax.device_get(rd)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(i)),
                                  np.asarray(jax.device_get(ri)))
    return tag

flat = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=32, seed=0))
checked = [
    check("brute", db, None),
    check("ivf_flat", flat, ivf_flat.IvfFlatSearchParams(n_probes=8)),
    check("ivf_rabitq",
          ivf_rabitq.build(db, ivf_rabitq.IvfRabitqIndexParams(n_lists=32)),
          ivf_rabitq.IvfRabitqSearchParams(n_probes=8, rerank_k=32)),
    check("tombstoned", mutation.delete(flat, np.arange(40)),
          ivf_flat.IvfFlatSearchParams(n_probes=8)),
]

# the full server: routed search == direct search, shed on kill, promote
fleet = FleetServer(flat, k=K,
                    params=ivf_flat.IvfFlatSearchParams(n_probes=8),
                    mesh=mesh, n_replicas=2,
                    config=ServerConfig(ladder=(16,)))
rd, ri = ivf_flat.search(flat, q, K,
                         ivf_flat.IvfFlatSearchParams(n_probes=8))
d, i = fleet.search(q)
np.testing.assert_array_equal(np.asarray(jax.device_get(i)),
                              np.asarray(jax.device_get(ri)))
fleet.kill_replica("r0")
d, i = fleet.search(q)
np.testing.assert_array_equal(np.asarray(jax.device_get(i)),
                              np.asarray(jax.device_get(ri)))
dur = fleet.attach_durability(
    tempfile.mkdtemp(prefix="raft-fleet-smoke-"),
    ["hostA", "hostB", "hostC"], n_standbys=2,
    config=ReplicationConfig(ack_mode="async", lease_s=3.0))
dur.pump()
promoted = fleet.promote_expired(fleet.replicas[0].server.clock() + 100.0)
assert promoted == list(range(fleet.n_shards)), promoted
fleet.stop()
print(json.dumps({"config": "fleet_smoke",
                  "backend": jax.default_backend(),
                  "mesh_width": len(devs), "bitwise": checked,
                  "selftest": sorted(selftest),
                  "promoted_shards": promoted}))
PY
run_step fleet_smoke    1800 python "$LOG/fleet_smoke.py"
# the replica-scaling ratchet: subprocess workers on the host CPU (one
# process per replica — the topology a real pod runs per host); the
# final line is harvested into FLEET_TPUHOST.json next round
run_step fleet_bench    1800 env RAFT_BENCH_SERVE_ROWS=2000 \
  RAFT_BENCH_SERVE_DIM=32 RAFT_BENCH_SERVE_K=8 \
  RAFT_BENCH_SERVE_LADDER=1,8 RAFT_BENCH_SERVE_FLEET_WAIT_MS=25 \
  RAFT_BENCH_SERVE_FLEET_CLIENTS=4 RAFT_BENCH_SERVE_SECONDS=6 \
  RAFT_BENCH_SERVE_REPLICAS=1,2,4 python bench/serve.py --cpu
run_step bench         4500 python bench.py
echo "$(date) all steps attempted" >> "$LOG/driver.log"
