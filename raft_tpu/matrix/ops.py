"""Matrix element/structure ops — parity with the small ``cpp/include/raft/matrix``
headers: ``argmax.cuh:28`` / ``argmin.cuh``, ``col_wise_sort.cuh``,
``sample_rows.cuh:30``, ``copy.cuh``, ``diagonal.cuh``, ``init.cuh``,
``linewise_op.cuh``, ``norm.cuh``, ``power.cuh``, ``ratio.cuh``,
``reciprocal.cuh``, ``reverse.cuh``, ``shift.cuh``, ``sign_flip.cuh``,
``slice.cuh``, ``sqrt.cuh``, ``threshold.cuh``, ``triangular.cuh``.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = [
    "argmax", "argmin", "col_wise_sort", "sample_rows",
    "get_diagonal", "set_diagonal", "invert_diagonal",
    "linewise_op", "reverse", "sign_flip", "slice", "shift_rows",
    "threshold", "lower_triangular", "upper_triangular", "ratio", "reciprocal",
    "eye", "fill",
]


def argmax(matrix) -> jax.Array:
    """Per-row argmax (``matrix/argmax.cuh:28``)."""
    return jnp.argmax(wrap_array(matrix, ndim=2), axis=1).astype(jnp.int32)


def argmin(matrix) -> jax.Array:
    """Per-row argmin (``matrix/argmin.cuh``)."""
    return jnp.argmin(wrap_array(matrix, ndim=2), axis=1).astype(jnp.int32)


def col_wise_sort(matrix, ascending: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Sort each column, returning (sorted, source-row indices)
    (``col_wise_sort.cuh``)."""
    matrix = wrap_array(matrix, ndim=2)
    key = matrix if ascending else -matrix
    order = jnp.argsort(key, axis=0)
    return jnp.take_along_axis(matrix, order, axis=0), order.astype(jnp.int32)


def sample_rows(matrix, n_samples: int, key=None, replace: bool = False):
    """Uniform row subsample (``sample_rows.cuh:30`` w/ ``excess_subsample``)."""
    matrix = wrap_array(matrix, ndim=2)
    if key is None:
        key = jax.random.PRNGKey(0)
    idx = jax.random.choice(key, matrix.shape[0], shape=(n_samples,), replace=replace)
    return jnp.take(matrix, idx, axis=0)


def get_diagonal(matrix) -> jax.Array:
    """``diagonal.cuh`` getter."""
    return jnp.diagonal(wrap_array(matrix, ndim=2))


def set_diagonal(matrix, values):
    m = wrap_array(matrix, ndim=2)
    values = wrap_array(values, ndim=1)
    n = min(m.shape)
    return m.at[jnp.arange(n), jnp.arange(n)].set(values[:n])


def invert_diagonal(matrix):
    """``diagonal.cuh`` inverse-in-place analog."""
    m = wrap_array(matrix, ndim=2)
    d = jnp.diagonal(m)
    return set_diagonal(m, 1.0 / d)


def linewise_op(matrix, vectors, op: Callable, along_lines: bool = True):
    """Apply op(row_element, vec_element) across lines (``linewise_op.cuh``,
    the row/col broadcast engine behind matrix_vector_op)."""
    from ..linalg.norm import matrix_vector_op

    return matrix_vector_op(matrix, vectors, op, along_rows=along_lines)


def reverse(matrix, along_rows: bool = True):
    """``reverse.cuh``: flip each row (or column)."""
    m = wrap_array(matrix, ndim=2)
    return m[:, ::-1] if along_rows else m[::-1, :]


def sign_flip(matrix):
    """``sign_flip.cuh``: flip column signs so the max-|x| entry per column is
    positive (deterministic eigenvector orientation)."""
    m = wrap_array(matrix, ndim=2)
    idx = jnp.argmax(jnp.abs(m), axis=0)
    signs = jnp.sign(m[idx, jnp.arange(m.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return m * signs[None, :]


def slice(matrix, row_range: Tuple[int, int], col_range: Tuple[int, int]):
    """``slice.cuh``: submatrix copy."""
    m = wrap_array(matrix, ndim=2)
    (r0, r1), (c0, c1) = row_range, col_range
    expects(0 <= r0 <= r1 <= m.shape[0] and 0 <= c0 <= c1 <= m.shape[1], "slice out of bounds")
    return m[r0:r1, c0:c1]


def shift_rows(matrix, offset: int, fill_value=0.0):
    """``shift.cuh``: shift columns right by ``offset`` filling with
    ``fill_value`` (used to prepend self-indices in ANN graphs)."""
    m = wrap_array(matrix, ndim=2)
    return jnp.roll(m, offset, axis=1).at[:, :offset].set(fill_value) if offset > 0 else m


def threshold(matrix, value, set_to=0.0, keep_above: bool = True):
    """``threshold.cuh``: zero out entries below (or above) a threshold."""
    m = wrap_array(matrix)
    mask = m >= value if keep_above else m <= value
    return jnp.where(mask, m, jnp.asarray(set_to, m.dtype))


def lower_triangular(matrix):
    """``triangular.cuh``."""
    return jnp.tril(wrap_array(matrix, ndim=2))


def upper_triangular(matrix):
    return jnp.triu(wrap_array(matrix, ndim=2))


def ratio(matrix):
    """``ratio.cuh``: each element divided by the total sum."""
    m = wrap_array(matrix)
    return m / jnp.sum(m)


def reciprocal(matrix, scalar: float = 1.0, thres: float = 0.0):
    """``reciprocal.cuh``: scalar/x with small-value guard."""
    m = wrap_array(matrix)
    return jnp.where(jnp.abs(m) > thres, scalar / m, jnp.zeros_like(m))


def eye(n: int, m: Optional[int] = None, dtype=jnp.float32):
    """``init.cuh`` identity."""
    return jnp.eye(n, m, dtype=dtype)


def fill(shape, value, dtype=jnp.float32):
    """``init.cuh`` fill."""
    return jnp.full(shape, value, dtype=dtype)
