"""Gather / scatter — parity with ``cpp/include/raft/matrix/gather.cuh:43-458``
and ``matrix/scatter.cuh`` (+ ``detail/gather_inplace.cuh`` /
``detail/scatter_inplace.cuh``).

XLA gather/scatter are native ops; the "inplace/buffered" CUDA variants exist
only to bound workspace — under XLA, donation covers that.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["gather", "gather_if", "scatter"]


def gather(matrix, row_map, transform_op: Optional[Callable] = None):
    """out[i, :] = in[map[i], :] (``matrix::gather``, ``gather.cuh:43``),
    with the optional map-transform overloads folded in."""
    matrix = wrap_array(matrix, ndim=2)
    row_map = wrap_array(row_map, ndim=1)
    if transform_op is not None:
        row_map = transform_op(row_map)
    return jnp.take(matrix, row_map, axis=0)


def gather_if(matrix, row_map, stencil, pred_op: Callable, fallback=0.0):
    """Conditional gather (``gather_if``): rows where ``pred_op(stencil)`` is
    false produce ``fallback`` (the reference leaves them untouched in-place;
    functionally that's a fill)."""
    matrix = wrap_array(matrix, ndim=2)
    row_map = wrap_array(row_map, ndim=1)
    stencil = wrap_array(stencil, ndim=1)
    expects(stencil.shape[0] == row_map.shape[0], "stencil must match map length")
    out = jnp.take(matrix, row_map, axis=0)
    mask = pred_op(stencil).astype(bool)
    return jnp.where(mask[:, None], out, jnp.asarray(fallback, out.dtype))


def scatter(matrix, row_map):
    """out[map[i], :] = in[i, :] (``matrix::scatter``, ``scatter.cuh``)."""
    matrix = wrap_array(matrix, ndim=2)
    row_map = wrap_array(row_map, ndim=1)
    expects(row_map.shape[0] == matrix.shape[0], "one destination per row required")
    return jnp.zeros_like(matrix).at[row_map].set(matrix)
