"""Batched top-k selection — parity with ``cpp/include/raft/matrix/select_k.cuh:75``
(+ ``select_k_types.hpp:28`` ``SelectAlgo``; dispatch heuristic
``detail/select_k-inl.cuh:40-64``; radix kernel ``detail/select_radix.cuh``;
warpsort kernel ``detail/select_warpsort.cuh``).

This is the most performance-critical ANN primitive.  The reference picks
between radix-histogram and warp-bitonic-queue kernels with an offline-trained
decision tree.  The TPU design (TPU-KNN paper, arXiv 2206.14286) differs:

* ``kTopK`` — XLA's ``lax.top_k`` (sort-based; robust for any k),
* ``kPartialBitonic`` — Pallas kernel keeping per-lane partial queues with a
  cross-lane log-merge (``raft_tpu.ops.pallas.select_k``), best for small k
  over long rows,
* ``kBinSelect`` — two-pass threshold refinement (radix-select analog): a
  cheap per-row threshold pass bounds the k-th value, then a filtered compact
  — avoids full sorts for huge rows,
* ``kAuto`` — shape-bucketed dispatch table (the reference's offline-trained
  heuristic pattern, ``cpp/scripts/heuristics/select_k``), tuned on-TPU by
  ``bench/tune_select_k.py``.

All variants return ``(values, indices)`` sorted best-first, with an optional
``in_idx`` payload translating positions to caller indices, exactly like the
reference.
"""

from __future__ import annotations

import enum
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["SelectAlgo", "select_k"]


class SelectAlgo(enum.Enum):
    """Algorithm choice (``select_k_types.hpp:28``)."""

    kAuto = "auto"
    kTopK = "top_k"                  # XLA lax.top_k
    kSortFull = "sort_full"          # full argsort (reference's cub fallback)
    kPartialBitonic = "partial_bitonic"  # Pallas partial-queue kernel
    kBinSelect = "bin_select"        # threshold-refinement two-pass


def _choose_algo(batch: int, length: int, k: int) -> SelectAlgo:
    """Shape-bucketed dispatch (parity with the offline-trained decision tree
    at ``detail/select_k-inl.cuh:40-64``).  ``bench/tune_select_k.py``
    regenerates the measured table; absent a table entry the default is
    ``lax.top_k``, which measured within noise of the Pallas kernel at the
    bench shapes (both latency-floored on the remote-TPU link)."""
    if k >= length:
        return SelectAlgo.kSortFull
    entry = _tuned_entry(batch, length, k)
    if entry is not None:
        return SelectAlgo(entry)
    return SelectAlgo.kTopK


@functools.lru_cache(maxsize=1)
def _tuned_table():
    """Measured dispatch table written by ``bench/tune_select_k.py`` —
    the reference's offline-trained-heuristic pattern
    (``cpp/scripts/heuristics/select_k``)."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "_select_k_table.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _tuned_entry(batch: int, length: int, k: int):
    table = _tuned_table()
    if not table:
        return None
    # bucket by log2 like the reference's decision tree features
    key = f"{batch.bit_length()}:{length.bit_length()}:{k.bit_length()}"
    hit = table.get(key)
    if hit is not None:
        return hit
    # nearest-bucket fallback: the tuner measures a grid, but callers'
    # shapes land between grid points (e.g. 10k rows → bucket 14, grid has
    # 12/15).  Interpolate to the closest measured bucket — capped at one
    # octave per axis so a wildly different shape still gets the default.
    want = (batch.bit_length(), length.bit_length(), k.bit_length())
    best_key, best_d = None, 4  # total log2 distance bound
    for tk in table:
        try:
            tb, tl, tkk = (int(v) for v in tk.split(":"))
        except ValueError:
            continue
        # one octave per axis, hard: extrapolating further (e.g. a batch
        # 8x off the grid) must fall through to the default instead
        if abs(tb - want[0]) > 1 or abs(tl - want[1]) > 1 \
                or abs(tkk - want[2]) > 1:
            continue
        d = abs(tb - want[0]) + abs(tl - want[1]) + abs(tkk - want[2])
        if d < best_d:
            best_key, best_d = tk, d
    return table.get(best_key) if best_key else None


def select_k(
    in_val,
    k: int,
    *,
    in_idx=None,
    select_min: bool = True,
    sorted: bool = True,
    algo: SelectAlgo = SelectAlgo.kAuto,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (or largest) per row (``matrix::select_k``).

    Parameters mirror ``select_k.cuh:75``: ``in_val`` is ``(batch, len)``;
    ``in_idx`` optionally maps positions to caller-provided indices.
    Returns ``(out_val, out_idx)`` of shape ``(batch, k)``.

    ``sorted=False`` relaxes the output-order contract as in the reference:
    the returned (value, index) pairs are the exact top-k *set*, in
    unspecified row order.  The ``kSortFull``/``kBinSelect`` paths then skip
    their final ordering (``argpartition`` instead of a ranked sort/top_k);
    ``kTopK``/``kPartialBitonic`` still emit sorted output, which is a valid
    refinement of the relaxed contract.  Intermediate ``tile_knn_merge``
    carries use the unsorted form — only a scan's final merge needs order.
    """
    in_val = wrap_array(in_val, ndim=2)
    batch, length = in_val.shape
    expects(k >= 1, "k must be >= 1")
    k_eff = min(k, length)

    auto = algo == SelectAlgo.kAuto
    if auto:
        algo = _choose_algo(batch, length, k_eff)

    if algo == SelectAlgo.kPartialBitonic:
        try:
            from ..ops.pallas.select_k import select_k_pallas
        except ImportError:
            # Only the auto heuristic may silently downgrade; an explicit
            # request for the Pallas kernel must surface its absence.
            if not auto:
                raise
            algo = SelectAlgo.kTopK
        else:
            # Real kernel failures (lowering, shapes) propagate — never masked
            # as a silent algorithm switch.
            vals, idx = select_k_pallas(in_val, k_eff, select_min=select_min,
                                        sorted=sorted)
    if algo == SelectAlgo.kTopK:
        # lax.top_k selects largest; negate for min-selection.
        if select_min:
            vals, idx = jax.lax.top_k(-in_val, k_eff)
            vals = -vals
        else:
            vals, idx = jax.lax.top_k(in_val, k_eff)
    elif algo == SelectAlgo.kSortFull:
        signed = in_val if select_min else -in_val
        if sorted:
            order = jnp.argsort(signed, axis=1)[:, :k_eff]
        else:  # exact top-k set, order unspecified: partition, don't rank
            order = jnp.argpartition(signed, k_eff - 1, axis=1)[:, :k_eff]
        vals = jnp.take_along_axis(in_val, order, axis=1)
        idx = order
    elif algo == SelectAlgo.kBinSelect:
        from ..ops.bin_select import bin_select_k

        vals, idx = bin_select_k(in_val, k_eff, select_min=select_min,
                                 sorted=sorted)

    if in_idx is not None:
        in_idx = wrap_array(in_idx, ndim=2)
        idx = jnp.take_along_axis(in_idx, idx, axis=1)
    idx = idx.astype(jnp.int32) if in_idx is None else idx

    if k_eff < k:  # pad to requested k like the reference's bounds contract
        if jnp.issubdtype(in_val.dtype, jnp.integer):
            # jnp.full(..., inf, int_dtype) raises — pad with the dtype's
            # own never-selected extreme instead
            info = jnp.iinfo(in_val.dtype)
            fill = info.max if select_min else info.min
        else:
            fill = jnp.inf if select_min else -jnp.inf
        pad_val = jnp.full((batch, k - k_eff), fill, in_val.dtype)
        pad_idx = jnp.full((batch, k - k_eff), -1, idx.dtype)
        vals = jnp.concatenate([vals, pad_val], axis=1)
        idx = jnp.concatenate([idx, pad_idx], axis=1)
    return vals, idx
