"""raft_tpu.matrix — matrix ops incl. the select_k top-k keystone.

TPU-native analog of ``cpp/include/raft/matrix`` (SURVEY.md §2.4).
"""

from .select_k import SelectAlgo, select_k
from .gather import gather, gather_if, scatter
from .ops import (
    argmax, argmin, col_wise_sort, sample_rows,
    get_diagonal, set_diagonal, invert_diagonal,
    linewise_op, reverse, sign_flip, slice, shift_rows,
    threshold, lower_triangular, upper_triangular, ratio, reciprocal,
    eye, fill,
)

__all__ = ["SelectAlgo", "select_k", "gather", "gather_if", "scatter",
    "argmax", "argmin", "col_wise_sort", "sample_rows", "get_diagonal",
    "set_diagonal", "invert_diagonal", "linewise_op", "reverse", "sign_flip",
    "slice", "shift_rows", "threshold", "lower_triangular", "upper_triangular",
    "ratio", "reciprocal", "eye", "fill"]
