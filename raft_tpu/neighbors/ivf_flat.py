"""IVF-Flat — inverted-file index with flat (uncompressed) lists.

No in-tree CUDA ancestor (cuVS migration, SURVEY.md scope note); designed
from the north-star capability list (``BASELINE.json`` configs: ivf_flat +
kmeans_balanced on SIFT-1M) and the TPU-KNN paper (PAPERS.md).

TPU-first design:
* **Coarse quantizer** = :func:`raft_tpu.cluster.kmeans_balanced_fit` — the
  balanced variant exists precisely because dense padded lists need a hard
  size bound (list capacity is a static shape).
* **List layout**: one dense ``[n_lists, cap, d]`` slab + ``[n_lists, cap]``
  source ids, pad entries masked by per-list counts.  Gathers of whole lists
  are contiguous HBM reads; no pointer-chasing.
* **Search**: query→centroid distances on the MXU, ``top_k`` probe pick,
  then one scan iteration per probe rank: gather the probed list slab,
  batched dot on the MXU, mask pads, merge into the running top-k via
  ``select_k`` (same merge primitive as brute force).  Everything
  static-shape, jit-compiled once per (nq, k, n_probes) config.
* **Sharded variant**: lists are partitioned round-robin over the mesh axis;
  every shard searches its local lists with the same program and the
  per-shard candidates merge with one ``all_gather`` + ``select_k`` -- the
  index-shard MNMG model of SURVEY.md §5.7 on ICI instead of NCCL.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..cluster.kmeans import KMeansParams, capped_assign, kmeans_balanced_fit
from ..core.array import wrap_array
from ..core.errors import expects
from ..distance.pairwise import sq_l2
from .brute_force import tile_knn_merge

__all__ = [
    "IvfFlatIndexParams",
    "IvfFlatSearchParams",
    "IvfFlatIndex",
    "build",
    "search",
    "extend",
    "build_sharded",
    "search_sharded",
]


@dataclasses.dataclass(frozen=True)
class IvfFlatIndexParams:
    """Build configuration (per-call parameter struct idiom, SURVEY.md §5.6b)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"  # sqeuclidean | euclidean | inner_product
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.1
    list_cap_ratio: float = 2.0  # capacity = ratio * n / n_lists
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class IvfFlatSearchParams:
    n_probes: int = 32
    query_chunk: int = 4096  # cap on the [chunk, cap, d] gather working set


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IvfFlatIndex:
    centroids: jax.Array   # [L, d]
    data: jax.Array        # [L, cap, d]
    ids: jax.Array         # [L, cap] int32, -1 pad
    counts: jax.Array      # [L] int32
    norms: jax.Array       # [L, cap] f32 squared L2 of stored vectors
    metric: str = dataclasses.field(metadata=dict(static=True))

    @property
    def n_lists(self) -> int:
        return int(self.data.shape[0])

    @property
    def list_cap(self) -> int:
        return int(self.data.shape[1])

    @property
    def dim(self) -> int:
        return int(self.data.shape[2])

    @property
    def size(self) -> int:
        return int(jnp.sum(self.counts))


def build(dataset, params: Optional[IvfFlatIndexParams] = None, *,
          source_ids=None, res=None) -> IvfFlatIndex:
    """Train the coarse quantizer and pack inverted lists (all on device —
    the packing is one jitted sort+scatter, :mod:`._packing`)."""
    p = params or IvfFlatIndexParams()
    x = wrap_array(dataset, ndim=2, name="dataset")
    n, d = x.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))

    # 1. train balanced kmeans on a subsample (trainset_fraction idiom)
    n_train = max(p.n_lists * 4, int(n * p.kmeans_trainset_fraction))
    n_train = min(n, n_train)
    key = jax.random.PRNGKey(p.seed)
    sel = (jax.random.permutation(key, n)[:n_train] if n_train < n
           else jnp.arange(n))
    kp = KMeansParams(n_clusters=p.n_lists, max_iter=p.kmeans_n_iters,
                      seed=p.seed)
    centroids, _, _ = kmeans_balanced_fit(x[sel], kp)

    # 2. capacity-constrained assignment of the full dataset
    labels, _ = capped_assign(x, centroids, cap)

    # 3. pack lists — jitted sort+scatter, no host round-trip
    from ._packing import pack_lists

    ids = (jnp.asarray(source_ids, jnp.int32) if source_ids is not None
           else jnp.arange(n, dtype=jnp.int32))
    (data, out_ids), counts = pack_lists(
        labels, (x, ids), n_lists=p.n_lists, cap=cap, fills=(0.0, -1))
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(centroids, data, out_ids, counts, norms, p.metric)


def extend(index: IvfFlatIndex, new_vectors, new_ids=None) -> IvfFlatIndex:
    """Append vectors to existing lists (device-side, like cuVS extend).

    The list slab is a static shape, so capacity grows when the new rows
    overflow it (rebuild-the-slab, the padded-layout price of extend).
    """
    from ._packing import pack_lists

    x = wrap_array(new_vectors, ndim=2)
    ids = (jnp.asarray(new_ids, jnp.int32) if new_ids is not None
           else jnp.arange(index.size, index.size + x.shape[0], dtype=jnp.int32))
    labels = jnp.argmin(sq_l2(x, index.centroids), axis=1).astype(jnp.int32)
    added = jax.ops.segment_sum(
        jnp.ones_like(labels), labels, num_segments=index.n_lists)
    new_cap = max(index.list_cap, int(jnp.max(index.counts + added)))

    # pack the new rows into their own slab, then splice after the old rows
    (nd, nids), ncounts = pack_lists(
        labels, (x.astype(index.data.dtype), ids),
        n_lists=index.n_lists, cap=new_cap, fills=(0.0, -1))
    pad = new_cap - index.list_cap
    data = jnp.concatenate(
        [index.data, jnp.zeros((index.n_lists, pad, index.dim), index.data.dtype)],
        axis=1) if pad else index.data
    out_ids = jnp.concatenate(
        [index.ids, jnp.full((index.n_lists, pad), -1, jnp.int32)], axis=1
    ) if pad else index.ids
    # shift each list's new rows to start at the old count: roll via gather
    col = jnp.arange(new_cap)[None, :]
    src = col - index.counts[:, None]           # position in the new slab
    take = (src >= 0) & (src < ncounts[:, None])
    src_safe = jnp.clip(src, 0, new_cap - 1)
    nd_shift = jnp.take_along_axis(nd, src_safe[:, :, None], axis=1)
    nids_shift = jnp.take_along_axis(nids, src_safe, axis=1)
    data = jnp.where(take[:, :, None], nd_shift, data)
    out_ids = jnp.where(take, nids_shift, out_ids)
    counts = (index.counts + ncounts).astype(jnp.int32)
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(index.centroids, data, out_ids, counts, norms,
                        index.metric)


def _probe_scan(q, qn, data, ids, counts, norms, probes, k: int, metric: str):
    """Scan probe ranks, merging each probed list into the running top-k.

    q: [nq, d]; probes: [nq, P].  One iteration gathers the p-th probed list
    of every query ([nq, cap, d] slab) and computes the distance block with a
    batched MXU dot.
    """
    nq = q.shape[0]
    cap = data.shape[1]
    n_probes = probes.shape[1]

    def step(carry, p):
        best_val, best_idx = carry
        lists = probes[:, p]                      # [nq]
        vecs = data[lists]                        # [nq, cap, d]
        vids = ids[lists]                         # [nq, cap]
        dots = jnp.einsum(
            "qcd,qd->qc", vecs, q,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric == "inner_product":
            dist = -dots
        else:  # sqeuclidean / euclidean rank by squared L2
            dist = norms[lists] - 2.0 * dots + qn[:, None]
            dist = jnp.maximum(dist, 0.0)
        valid = jnp.arange(cap)[None, :] < counts[lists][:, None]
        dist = jnp.where(valid & (vids >= 0), dist, jnp.inf)
        return tile_knn_merge(best_val, best_idx, dist, vids, k), None

    init = (jnp.full((nq, k), jnp.inf, jnp.float32),
            jnp.full((nq, k), -1, jnp.int32))
    (bv, bi), _ = jax.lax.scan(step, init, jnp.arange(n_probes))
    return bv, bi


@partial(jax.jit, static_argnames=("k", "n_probes", "metric"))
def _search_impl(centroids, data, ids, counts, norms, q, k: int,
                 n_probes: int, metric: str):
    qf = q.astype(jnp.float32)
    qn = jnp.sum(qf * qf, axis=1)
    cd = sq_l2(q, centroids)                      # [nq, L] MXU block
    _, probes = jax.lax.top_k(-cd, n_probes)      # nearest lists
    bv, bi = _probe_scan(q, qn, data, ids, counts, norms, probes, k, metric)
    if metric == "euclidean":
        bv = jnp.sqrt(jnp.maximum(bv, 0.0))
    elif metric == "inner_product":
        bv = -bv
    return bv, bi


def search(index: IvfFlatIndex, queries, k: int,
           params: Optional[IvfFlatSearchParams] = None, *, res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """Approximate kNN: returns ``(distances, ids)`` of (nq, k), best first."""
    p = params or IvfFlatSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    n_probes = min(p.n_probes, index.n_lists)
    from ._packing import chunked_queries

    run = lambda qc: _search_impl(index.centroids, index.data, index.ids,
                                  index.counts, index.norms, qc, int(k),
                                  int(n_probes), index.metric)
    return chunked_queries(run, q, int(p.query_chunk))


# ---------------------------------------------------------------------------
# Sharded (multi-chip) variant: lists partitioned over the mesh axis.
# ---------------------------------------------------------------------------


def build_sharded(dataset, mesh: Mesh, params: Optional[IvfFlatIndexParams] = None,
                  *, axis: str = "shard") -> IvfFlatIndex:
    """Build with ``n_lists`` padded to the axis size and the list slabs laid
    out shard-major so device d owns lists [d*L/n, (d+1)*L/n)."""
    p = params or IvfFlatIndexParams()
    n_dev = int(mesh.shape[axis])
    n_lists = ((p.n_lists + n_dev - 1) // n_dev) * n_dev
    p = dataclasses.replace(p, n_lists=n_lists)
    index = build(dataset, p)
    sharding = jax.sharding.NamedSharding(mesh, P(axis))
    return IvfFlatIndex(
        jax.device_put(index.centroids, sharding),
        jax.device_put(index.data, sharding),
        jax.device_put(index.ids, sharding),
        jax.device_put(index.counts, sharding),
        jax.device_put(index.norms, sharding),
        index.metric,
    )


@partial(jax.jit, static_argnames=("k", "n_probes", "metric", "axis", "mesh"))
def _search_sharded_impl(mesh, axis, centroids, data, ids, counts, norms, q,
                         k: int, n_probes: int, metric: str):
    def local(centroids_l, data_l, ids_l, counts_l, norms_l, q_l):
        bv, bi = _search_impl(centroids_l, data_l, ids_l, counts_l, norms_l,
                              q_l, k, n_probes, metric)
        # candidates from all shards → final top-k everywhere
        if metric == "inner_product":
            bv = -bv  # back to min-selectable
        av = jax.lax.all_gather(bv, axis, tiled=False)  # [S, nq, k]
        ai = jax.lax.all_gather(bi, axis, tiled=False)
        av = jnp.moveaxis(av, 0, 1).reshape(q_l.shape[0], -1)
        ai = jnp.moveaxis(ai, 0, 1).reshape(q_l.shape[0], -1)
        from ..matrix.select_k import select_k

        fv, fi = select_k(av, k, in_idx=ai, select_min=True)
        if metric == "inner_product":
            fv = -fv
        return fv, fi

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )(centroids, data, ids, counts, norms, q)


def search_sharded(index: IvfFlatIndex, queries, k: int,
                   params: Optional[IvfFlatSearchParams] = None, *,
                   mesh: Mesh, axis: str = "shard"
                   ) -> Tuple[jax.Array, jax.Array]:
    """Multi-chip search: each shard probes its local lists (n_probes per
    shard — recall ≥ single-chip at equal n_probes), one all_gather merges.

    Per-shard probing searches each shard's nearest local lists, so the union
    over shards always covers the globally nearest lists.
    """
    p = params or IvfFlatSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    n_dev = int(mesh.shape[axis])
    local_lists = index.n_lists // n_dev
    n_probes = min(p.n_probes, local_lists)
    return _search_sharded_impl(mesh, axis, index.centroids, index.data,
                                index.ids, index.counts, index.norms, q,
                                int(k), int(n_probes), index.metric)
