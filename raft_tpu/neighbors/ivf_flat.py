"""IVF-Flat — inverted-file index with flat (uncompressed) lists.

No in-tree CUDA ancestor (cuVS migration, SURVEY.md scope note); designed
from the north-star capability list (``BASELINE.json`` configs: ivf_flat +
kmeans_balanced on SIFT-1M) and the TPU-KNN paper (PAPERS.md).

TPU-first design:
* **Coarse quantizer** = :func:`raft_tpu.cluster.kmeans_balanced_fit` — the
  balanced variant exists precisely because dense padded lists need a hard
  size bound (list capacity is a static shape).
* **List layout**: one dense ``[n_lists, cap, d]`` slab + ``[n_lists, cap]``
  source ids, pad entries masked by per-list counts.  Gathers of whole lists
  are contiguous HBM reads; no pointer-chasing.
* **Search**: query→centroid distances on the MXU, ``top_k`` probe pick,
  then one scan iteration per **probe block** of B probe ranks: one
  ``[nq, B·cap, d]`` slab gather, one batched MXU dot, pads masked, ONE
  merge into the running top-k via ``select_k`` (same merge primitive as
  brute force) — ⌈n_probes/B⌉ merges instead of n_probes, with unsorted
  intermediate carries and a single ranked selection after the scan.
  Everything static-shape, jit-compiled once per
  (nq, k, n_probes, probe_block) config; B defaults from the measured
  ``_probe_block_table`` (``bench/tune_probe_block.py``).
* **Sharded variant**: lists are partitioned round-robin over the mesh axis;
  every shard searches its local lists with the same program and the
  per-shard candidates merge with one ``all_gather`` + ``select_k`` -- the
  index-shard MNMG model of SURVEY.md §5.7 on ICI instead of NCCL.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..cluster.kmeans import KMeansParams, capped_assign, kmeans_balanced_fit
from ..core import tracing
from ..core.array import wrap_array
from ..core.compat import shard_map
from ..core.errors import expects
from ..distance.pairwise import sq_l2

__all__ = [
    "IvfFlatIndexParams",
    "IvfFlatSearchParams",
    "IvfFlatIndex",
    "build",
    "build_chunked",
    "build_chunked_sharded",
    "search",
    "searcher",
    "extend",
    "build_sharded",
    "search_sharded",
    "fleet_slices",
    "IvfFlatFleetSlices",
]


@dataclasses.dataclass(frozen=True)
class IvfFlatIndexParams:
    """Build configuration (per-call parameter struct idiom, SURVEY.md §5.6b)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"  # sqeuclidean | euclidean | inner_product
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.1
    list_cap_ratio: float = 2.0  # capacity = ratio * n / n_lists
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class IvfFlatSearchParams:
    n_probes: int = 32
    query_chunk: int = 4096  # cap on the [chunk, cap, d] gather working set
    # probes gathered+scored+merged per scan step; 0 = auto (measured
    # table via bench/tune_probe_block.py, else a working-set heuristic).
    # Results are bit-identical for every value — this is a pure
    # latency/throughput knob (docs/tuning_guide.md).
    probe_block: int = 0
    # blocked-scan engine: "auto" | "xla" | "fused".  "xla" is the
    # bit-exact two-pass scan; "fused" runs the Pallas distance+partial
    # top-k kernel per block with an exact re-score of the k finalists
    # (recall-gated, not bit-pinned).  "auto" resolves through
    # ops.blocked_scan.resolve_scan_kernel (Mosaic gate + tuned table) and
    # is always "xla" off-TPU (docs/tuning_guide.md).
    scan_kernel: str = "auto"


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IvfFlatIndex:
    centroids: jax.Array   # [L, d]
    data: jax.Array        # [L, cap, d]
    ids: jax.Array         # [L, cap] int32, -1 pad
    counts: jax.Array      # [L] int32
    norms: jax.Array       # [L, cap] f32 squared L2 of stored vectors
    metric: str = dataclasses.field(metadata=dict(static=True))

    @property
    def n_lists(self) -> int:
        return int(self.data.shape[0])

    @property
    def list_cap(self) -> int:
        return int(self.data.shape[1])

    @property
    def dim(self) -> int:
        return int(self.data.shape[2])

    @property
    def size(self) -> int:
        return int(jnp.sum(self.counts))  # jaxlint: disable=JX01 size is a host-facing API scalar, not on the search path


@tracing.annotate("ivf_flat.build")
def build(dataset, params: Optional[IvfFlatIndexParams] = None, *,
          source_ids=None, res=None) -> IvfFlatIndex:
    """Train the coarse quantizer and pack inverted lists (all on device —
    the packing is one jitted sort+scatter, :mod:`._packing`)."""
    p = params or IvfFlatIndexParams()
    x = wrap_array(dataset, ndim=2, name="dataset")
    n, d = x.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))

    # 1. train balanced kmeans on a subsample (trainset_fraction idiom)
    n_train = max(p.n_lists * 4, int(n * p.kmeans_trainset_fraction))
    n_train = min(n, n_train)
    key = jax.random.PRNGKey(p.seed)
    sel = (jax.random.permutation(key, n)[:n_train] if n_train < n
           else jnp.arange(n))
    kp = KMeansParams(n_clusters=p.n_lists, max_iter=p.kmeans_n_iters,
                      seed=p.seed)
    centroids, _, _ = kmeans_balanced_fit(x[sel], kp)

    # 2. capacity-constrained assignment of the full dataset
    labels, _ = capped_assign(x, centroids, cap)

    # 3. pack lists — jitted sort+scatter, no host round-trip
    from ._packing import pack_lists

    ids = (jnp.asarray(source_ids, jnp.int32) if source_ids is not None
           else jnp.arange(n, dtype=jnp.int32))
    (data, out_ids), counts = pack_lists(
        labels, (x, ids), n_lists=p.n_lists, cap=cap, fills=(0.0, -1))
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(centroids, data, out_ids, counts, norms, p.metric)


def _train_subsample(n: int, n_train: int, seed: int):
    """Host-side subsample indices for quantizer training (sorted for
    memmap-friendly reads)."""
    if n_train >= n:
        return np.arange(n)
    rs = np.random.default_rng(seed)
    return np.sort(rs.choice(n, n_train, replace=False))


def _coarse_train_chunked(dataset, p: IvfFlatIndexParams, n: int):
    """Coarse-quantizer training for the streaming builds: balanced kmeans
    over a host-sampled subset (the only phase that touches more than one
    chunk of host data at a time)."""
    n_train = min(n, max(p.n_lists * 4, int(n * p.kmeans_trainset_fraction)))
    sel = _train_subsample(n, n_train, p.seed)
    kp = KMeansParams(n_clusters=p.n_lists, max_iter=p.kmeans_n_iters,
                      seed=p.seed)
    centroids, _, _ = kmeans_balanced_fit(np.asarray(dataset[sel]), kp)
    return centroids


def _flat_step_impl(slabs, counts, centroids, xc, idc, *,
                    n_lists: int, cap: int):
    """ONE fused program per chunk: masked capped assignment against
    remaining room + scatter-append, fused so XLA sees (and schedules) the
    whole chunk as a single dispatch — no host round-trip for ``counts``
    between the stages.  Pad rows (``idc < 0``, from the fixed-shape tail
    padding) never request a list, never consume capacity, and
    scatter-drop via label −1, so the padded stream is bit-identical to
    the unpadded per-op loop.

    Two jitted forms: :func:`_flat_chunk_step` donates the slabs (build
    loops own their buffers); :func:`_flat_chunk_step_cow` leaves the
    inputs alive — the copy-on-write first step of the online
    :func:`extend`, whose input slabs belong to the LIVE index a serving
    snapshot may still be dispatching against."""
    from ..cluster.kmeans import _capped_assign_impl
    from ._packing import _scatter_append_impl

    valid = idc >= 0
    labels, _ = _capped_assign_impl(xc, centroids, cap - counts, valid)
    return _scatter_append_impl(slabs, counts, labels, (xc, idc),
                                n_lists=n_lists, cap=cap)


_flat_chunk_step = partial(jax.jit, static_argnames=("n_lists", "cap"),
                           donate_argnums=(0, 1))(_flat_step_impl)
_flat_chunk_step_cow = partial(jax.jit, static_argnames=("n_lists", "cap"))(
    _flat_step_impl)


def _stream_pipelined(dataset, centroids, p: IvfFlatIndexParams, n: int,
                      cap: int, chunk_rows: int, source_ids, dtype,
                      heartbeat=None):
    """Pipelined chunk engine: fixed-shape double-buffered device staging
    (:func:`~._packing.prefetch_chunks_padded`) feeding the fused donated
    :func:`_flat_chunk_step` — one executable, one dispatch per chunk."""
    from ._packing import device_full, prefetch_chunks_padded

    d = dataset.shape[1]
    data = device_full((p.n_lists, cap, d), 0, dtype)
    ids_slab = device_full((p.n_lists, cap), -1, jnp.int32)
    counts = device_full((p.n_lists,), 0, jnp.int32)
    for lo, hi, xc, idc in prefetch_chunks_padded(dataset, chunk_rows,
                                                  source_ids, dtype=dtype):
        (data, ids_slab), counts = _flat_chunk_step(
            (data, ids_slab), counts, centroids, xc, idc,
            n_lists=p.n_lists, cap=cap)
        if heartbeat is not None:
            heartbeat(hi)
    return data, ids_slab, counts


def _stream_perop(dataset, centroids, p: IvfFlatIndexParams, n: int,
                  cap: int, chunk_rows: int, source_ids, dtype):
    """Reference per-op chunk loop (the pre-pipelining engine): blocking
    H2D ``jnp.asarray``, separate assign / scatter dispatches, tail chunk
    at its own shape.  Kept verbatim as the bit-parity oracle for the
    fused engine (tests/test_chunked_builds.py) and the A/B baseline of
    ``bench/build_throughput.py``."""
    from ..cluster.kmeans import capped_assign_room
    from ._packing import prefetch_chunks, scatter_append

    data = jnp.zeros((p.n_lists, cap, dataset.shape[1]), dtype)
    ids_slab = jnp.full((p.n_lists, cap), -1, jnp.int32)
    counts = jnp.zeros((p.n_lists,), jnp.int32)
    for lo, hi, xc_h, idc_h in prefetch_chunks(dataset, chunk_rows,
                                               source_ids):
        xc = jnp.asarray(xc_h, dtype)
        idc = jnp.asarray(idc_h, jnp.int32)
        labels, _ = capped_assign_room(xc, centroids, cap - counts)
        (data, ids_slab), counts = scatter_append(
            (data, ids_slab), counts, labels, (xc, idc),
            n_lists=p.n_lists, cap=cap)
    return data, ids_slab, counts


def build_chunked(dataset, params: Optional[IvfFlatIndexParams] = None, *,
                  chunk_rows: int = 0, source_ids=None,
                  res=None) -> IvfFlatIndex:
    """Out-of-core build: the dataset stays on host (any numpy-indexable —
    ``np.ndarray``, ``np.memmap``, an ``io.BatchLoader``-backed array) and
    streams through the device in fixed-size chunks.

    Device peak = list slabs + two staged chunks + one (chunk, n_lists)
    distance block — never the whole dataset (the r2 builds were
    whole-dataset-resident; VERDICT r2 missing #2).  The chunk engine is
    pipelined: each chunk is ONE jitted, slab-donating program
    (:func:`_flat_chunk_step` — capped assign against remaining room fused
    with the scatter-append), the tail chunk is padded to ``chunk_rows``
    with masked rows so a single executable serves the whole stream (zero
    steady-state recompiles, assertable under
    :class:`~raft_tpu.core.TraceGuard`), and chunk t+1 is staged
    host→device with a non-blocking ``device_put`` while chunk t computes
    (:func:`~raft_tpu.core.device_prefetch`).

    ``chunk_rows=0`` (default) = auto: the measured table written by
    ``bench/tune_chunk_rows.py``, else 65536
    (:func:`~._packing.resolve_chunk_rows`) — a pure throughput knob, the
    built index is identical for every value.

    Reference analog: the SNMG streaming/batch build model
    (``core/device_resources_snmg.hpp:36``) without a CUDA ancestor for the
    chunk loop itself (cuVS migration).
    """
    from ._packing import build_heartbeat, resolve_chunk_rows

    p = params or IvfFlatIndexParams()
    n, d = dataset.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    dtype = jnp.asarray(np.asarray(dataset[:1])).dtype
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_flat")

    centroids = _coarse_train_chunked(dataset, p, n)
    data, ids_slab, counts = _stream_pipelined(
        dataset, centroids, p, n, cap, chunk_rows, source_ids, dtype,
        heartbeat=build_heartbeat("ivf_flat.build_chunked", n))
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(centroids, data, ids_slab, counts, norms, p.metric)


def _build_chunked_perop(dataset, params: Optional[IvfFlatIndexParams] = None,
                         *, chunk_rows: int = 0,
                         source_ids=None) -> IvfFlatIndex:
    """:func:`build_chunked` on the reference per-op chunk loop
    (:func:`_stream_perop`) — the parity oracle / A/B baseline; not part
    of the public API."""
    from ._packing import resolve_chunk_rows

    p = params or IvfFlatIndexParams()
    n, d = dataset.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    dtype = jnp.asarray(np.asarray(dataset[:1])).dtype
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_flat")
    centroids = _coarse_train_chunked(dataset, p, n)
    data, ids_slab, counts = _stream_perop(
        dataset, centroids, p, n, cap, chunk_rows, source_ids, dtype)
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(centroids, data, ids_slab, counts, norms, p.metric)


def extend(index: IvfFlatIndex, new_vectors, new_ids=None, *,
           insert_chunk: int = 0) -> IvfFlatIndex:
    """Online streaming insert (cuVS ``extend`` parity), rebuilt around
    the chunked builder's fused slab-donating step.

    The insert batch is host-padded to a fixed ``insert_chunk`` row bucket
    (0 = :data:`~._packing.DEFAULT_INSERT_CHUNK`; pad rows carry id −1 and
    are masked out of assignment and capacity) and streamed through
    :func:`_flat_chunk_step`: ONE jitted executable serves every insert
    size, counts never leave the device between assign and scatter, and
    the only host↔device crossings are the explicit per-chunk
    ``device_put`` and one scalar spill check — the steady-state insert
    path is zero-retrace / zero-implicit-transfer under
    :class:`~raft_tpu.core.TraceGuard`.

    Copy-on-write: the first chunk step is the non-donating
    :func:`_flat_chunk_step_cow` (the source slabs may back a live serving
    snapshot mid-dispatch), later chunks donate the fresh private buffers.
    The source ``index`` stays fully usable after the call.

    When the batch overflows list capacity the slab grows (a host-sized
    static shape — the padded layout's rebuild price) with geometric
    headroom and the stream re-runs from the untouched source slabs.
    With capacity to spare, capped assignment degenerates to
    nearest-centroid for every row, so extending is bit-identical (values
    AND ids) to a from-scratch pack at the same centroids
    (tests/test_mutation.py pins this).
    """
    from ._packing import (DEFAULT_INSERT_CHUNK, host_rows,
                           staged_insert_chunks)

    L, cap, d = index.n_lists, index.list_cap, index.dim
    x = host_rows(new_vectors)
    expects(x.ndim == 2 and x.shape[1] == d, "vector dim mismatch")
    n_new = x.shape[0]
    expects(n_new >= 1, "no rows to insert")
    base = int(jax.device_get(jnp.sum(index.counts)))  # jaxlint: disable=JX01 one scalar sync per extend call: sizes auto-assigned ids and the spill check baseline
    ids = (np.asarray(host_rows(new_ids), np.int32) if new_ids is not None
           else np.arange(base, base + n_new, dtype=np.int32))
    expects(ids.shape == (n_new,), "new_ids must be one id per row")
    expects(int(ids.min()) >= 0, "source ids must be >= 0 (−1 is the pad)")
    chunk = int(insert_chunk) or DEFAULT_INSERT_CHUNK

    def stream(slabs, counts, slab_cap):
        step = _flat_chunk_step_cow  # inputs may back a live snapshot
        for xc, idc in staged_insert_chunks(x, ids, chunk, index.data.dtype):
            slabs, counts = step(slabs, counts, index.centroids, xc, idc,
                                 n_lists=L, cap=slab_cap)
            step = _flat_chunk_step  # fresh private buffers: donate
        return slabs, counts

    (data, out_ids), counts = stream((index.data, index.ids), index.counts,
                                     cap)
    placed = int(jax.device_get(jnp.sum(counts))) - base  # jaxlint: disable=JX01 explicit spill check: one scalar per extend gates the rare slab-growth path
    if placed < n_new:  # capacity exhausted — grow + re-run (rare)
        xd = jnp.asarray(x.astype(index.data.dtype, copy=False))
        labels = jnp.argmin(sq_l2(xd, index.centroids), axis=1)
        added = jax.ops.segment_sum(jnp.ones_like(labels, jnp.int32),
                                    labels, num_segments=L)
        need = int(jnp.max(index.counts + added))  # jaxlint: disable=JX01 slab capacity must be a host int at extend time (static shapes)
        new_cap = max(need, cap + (cap + 1) // 2)  # geometric headroom
        pad = new_cap - cap
        grown = (jnp.pad(index.data, ((0, 0), (0, pad), (0, 0))),
                 jnp.pad(index.ids, ((0, 0), (0, pad)), constant_values=-1))
        (data, out_ids), counts = stream(grown, index.counts, new_cap)
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(index.centroids, data, out_ids, counts, norms,
                        index.metric)


def _probe_scan(q, qn, data, ids, counts, norms, probes, k: int, metric: str,
                keep=None, probe_block: int = 1, scan_kernel: str = "xla"):
    """Scan probe *blocks* through the shared ``ops.blocked_scan`` core.

    q: [nq, d]; probes: [nq, P].  One iteration gathers the next B probed
    lists of every query (one ``[nq, B·cap, d]`` slab), scores it with
    ``slab_dots`` (B pinned in the einsum's batch dims — the bit-invariance
    contract: results identical across block sizes) and folds it into the
    running top-k — ⌈P/B⌉ merges instead of P.  Pad probes (P not
    divisible by B) are masked to +inf, never duplicated.
    ``keep``: optional bool prefilter by source id.  ``scan_kernel``:
    ``"xla"`` (bit-exact two-pass) or ``"fused"`` (Pallas distance+partial
    top-k in one kernel, exact re-score of the k finalists — recall-gated,
    not bit-pinned)."""
    from ..ops import blocked_scan as _scan
    from ._packing import blocked_probe_plan

    nq = q.shape[0]
    cap = data.shape[1]
    lists_xs, pvalid = blocked_probe_plan(probes, probe_block)

    def gather(inp):
        lists, pv = inp                           # [nq, B], [B]
        bcap = lists.shape[1] * cap
        vecs = data[lists]                        # [nq, B, cap, d] gather
        vids = ids[lists].reshape(nq, bcap)       # [nq, B·cap]
        valid = (jnp.arange(cap)[None, None, :]
                 < counts[lists][:, :, None]).reshape(nq, bcap)
        valid = valid & (vids >= 0) & jnp.repeat(pv, cap)[None, :]
        if keep is not None:
            from ._packing import keep_lookup

            valid = valid & keep_lookup(keep, vids)
        return lists, vecs, vids, valid

    if scan_kernel == "fused":
        def slab_step(inp):
            lists, vecs, vids, valid = gather(inp)
            bcap = vids.shape[1]
            if metric == "inner_product":
                base = jnp.zeros((nq, bcap), jnp.float32)
            else:
                base = norms[lists].reshape(nq, bcap)
            return (vecs.reshape(nq, bcap, vecs.shape[-1]),
                    jnp.where(valid, base, jnp.inf), vids,
                    _scan.list_slab_ptr(lists, cap))

        rescore = _scan.l2_rescorer(data, norms, q, qn, metric)
        return _scan.scan_topk_fused(q, slab_step, (lists_xs, pvalid),
                                     rescore, nq, k)

    def score(inp):
        lists, vecs, vids, valid = gather(inp)
        dots = _scan.slab_dots(vecs, q).reshape(nq, -1)
        if metric == "inner_product":
            dist = -dots
        else:  # sqeuclidean / euclidean rank by squared L2
            dist = norms[lists].reshape(nq, dots.shape[1]) - 2.0 * dots \
                + qn[:, None]
            dist = jnp.maximum(dist, 0.0)
        return jnp.where(valid, dist, jnp.inf), vids

    return _scan.scan_topk(score, (lists_xs, pvalid), nq, k)


@partial(jax.jit, static_argnames=("k", "n_probes", "metric", "probe_block",
                                   "scan_kernel"))
def _search_impl(centroids, data, ids, counts, norms, q, k: int,
                 n_probes: int, metric: str, keep=None,
                 probe_block: int = 1, scan_kernel: str = "xla"):
    from ..ops.blocked_scan import row_sq_norms

    qf = q.astype(jnp.float32)
    qn = row_sq_norms(qf)   # dot-contraction: rounds the same in the
    # fleet's SPMD executable (serve bit-identity, ops.blocked_scan doc)
    cd = sq_l2(q, centroids)                      # [nq, L] MXU block
    _, probes = jax.lax.top_k(-cd, n_probes)      # nearest lists
    bv, bi = _probe_scan(q, qn, data, ids, counts, norms, probes, k, metric,
                         keep, probe_block, scan_kernel)
    if metric == "euclidean":
        bv = jnp.sqrt(jnp.maximum(bv, 0.0))
    elif metric == "inner_product":
        bv = -bv
    return bv, bi


@tracing.annotate("ivf_flat.search")
def search(index: IvfFlatIndex, queries, k: int,
           params: Optional[IvfFlatSearchParams] = None, *, filter=None,
           res=None) -> Tuple[jax.Array, jax.Array]:
    """Approximate kNN: returns ``(distances, ids)`` of (nq, k), best first.

    ``filter``: optional prefilter by source id over the ORIGINAL row
    numbering, True = keep — a shared ``core.Bitset``/(n,) bools (cuVS
    bitset filter) or a per-query ``core.Bitmap``/(nq, n) bools (bitmap
    filter)."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           chunked_filtered_queries, resolve_probe_block,
                           sentinel_filtered_ids)

    p = params or IvfFlatSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    n_probes = min(p.n_probes, index.n_lists)
    probe_block = resolve_probe_block(p.probe_block, int(n_probes),
                                      index.list_cap, "ivf_flat")
    from ..ops.blocked_scan import resolve_scan_kernel

    scan_kernel = resolve_scan_kernel(p.scan_kernel, "ivf_flat",
                                      probe_block * index.list_cap, int(k))
    keep = as_keep_mask(filter, nq=q.shape[0])  # indexes source ids
    if keep is not None:
        check_filter_covers_ids(keep, index.ids)

    impl = lambda qc, kc: _search_impl(
        index.centroids, index.data, index.ids, index.counts,
        index.norms, qc, int(k), int(n_probes), index.metric, kc,
        probe_block, scan_kernel)
    dv, di = chunked_filtered_queries(impl, q, int(p.query_chunk), keep)
    if keep is not None:  # sub-k survivors: sentinel tail, not real ids
        di = sentinel_filtered_ids(dv, di)
    return dv, di


def searcher(index: IvfFlatIndex, k: int,
             params: Optional[IvfFlatSearchParams] = None, *, filter=None):
    """Uniform serving entry point (``raft_tpu.serve`` contract): returns
    ``(fn, operands)`` with ``fn(queries, *operands)`` equal to
    :func:`search` for query batches up to ``params.query_chunk`` rows
    (above that :func:`search` chunks; serving buckets stay well below).
    ``fn`` AOT-compiles via
    ``jax.jit(fn).lower(q_spec, *operands).compile()``; the index slabs
    ride as operands so bucket executables share them instead of baking
    per-bucket constants.

    ``filter``: optional shared prefilter (``core.Bitset`` / 1-D bools
    over source ids, True = keep) — rides as one more operand, so
    tombstone deletes (:func:`raft_tpu.neighbors.mutation.delete`) swap
    in a new mask without recompiling.  Per-query bitmaps can't ride a
    fixed operand across variable-row buckets and are rejected."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           resolve_probe_block, sentinel_filtered_ids)

    p = params or IvfFlatSearchParams()
    expects(k >= 1, "k must be >= 1")
    n_probes = int(min(p.n_probes, index.n_lists))
    probe_block = resolve_probe_block(p.probe_block, n_probes,
                                      index.list_cap, "ivf_flat")
    from ..ops.blocked_scan import resolve_scan_kernel

    scan_kernel = resolve_scan_kernel(p.scan_kernel, "ivf_flat",
                                      probe_block * index.list_cap, int(k))
    metric = index.metric
    keep = as_keep_mask(filter)
    if keep is not None:
        expects(keep.ndim == 1,
                "serving filters are shared bitsets (1-D); per-query "
                "bitmaps can't ride a fixed operand across buckets")
        check_filter_covers_ids(keep, index.ids)

        def fn(q, centroids, data, ids, counts, norms, kp):
            dv, di = _search_impl(centroids, data, ids, counts, norms, q,
                                  int(k), n_probes, metric, kp, probe_block,
                                  scan_kernel)
            return dv, sentinel_filtered_ids(dv, di)

        return fn, (index.centroids, index.data, index.ids, index.counts,
                    index.norms, keep)

    def fn(q, centroids, data, ids, counts, norms):
        return _search_impl(centroids, data, ids, counts, norms, q,
                            int(k), n_probes, metric, None, probe_block,
                            scan_kernel)

    return fn, (index.centroids, index.data, index.ids, index.counts,
                index.norms)


# ---------------------------------------------------------------------------
# Sharded (multi-chip) variant: lists partitioned over the mesh axis.
# ---------------------------------------------------------------------------


@lru_cache(maxsize=16)
def _sharded_build_program(mesh: Mesh, axis: str, n_orig: int, per: int,
                           n_lists_local: int, cap: int, n_train: int,
                           max_iter: int, penalty: float, bal_cap: int,
                           seed: int):
    """Compile-once distributed build: every device trains a coarse
    quantizer on ITS rows and packs ITS lists — no single-device
    whole-dataset build, no post-hoc device_put (the r2 shape;
    VERDICT r2 missing #2).  SNMG model of
    ``core/device_resources_snmg.hpp:36``: shard-local sub-indexes,
    global ids ``shard·per + local``."""
    from ..cluster.kmeans import _balanced_fit_impl
    from ._packing import pack_lists

    def local(x_l):
        shard = jax.lax.axis_index(axis)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), shard)
        sel = jax.random.permutation(key, per)[:n_train]
        c, _, _, _ = _balanced_fit_impl(
            x_l[sel], key, n_lists_local, max_iter, penalty, bal_cap)
        gid = (shard * per + jnp.arange(per)).astype(jnp.int32)
        labels, _ = capped_assign(x_l, c, cap)
        # rows padded to even out the shards are dropped here, not stored
        labels = jnp.where(gid < n_orig, labels, -1)
        (data, out_ids), counts = pack_lists(
            labels, (x_l, gid), n_lists=n_lists_local, cap=cap,
            fills=(0.0, -1))
        norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
        # centroids keep the fit dtype (f32 for integer corpora —
        # rounding to uint8 would quantize the probe routing)
        return c, data, out_ids, counts, norms

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=P(axis),
        out_specs=(P(axis),) * 5, check_vma=False,
    ))


def build_sharded(dataset, mesh: Mesh, params: Optional[IvfFlatIndexParams] = None,
                  *, axis: str = "shard") -> IvfFlatIndex:
    """Distributed build: rows are sharded over the mesh axis and **each
    device builds its own sub-index from its own rows** (one shard_map
    program — S parallel builds, one compile).  Device d owns lists
    ``[d·L/S, (d+1)·L/S)`` trained on its row shard; ids are global row
    positions.  :func:`search_sharded` probes every shard's local lists and
    merges, so the union covers the globally nearest lists."""
    from ._packing import shard_rows, sharded_train_sizes

    p = params or IvfFlatIndexParams()
    n_dev = int(mesh.shape[axis])
    x_sh, n, per = shard_rows(dataset, mesh, axis)
    n_lists_local = max(1, (p.n_lists + n_dev - 1) // n_dev)
    expects(n_lists_local <= per, "n_lists exceeds rows per shard")
    cap = max(1, int(np.ceil(p.list_cap_ratio * per / n_lists_local)))
    kp = KMeansParams()  # balanced-cap ratio for the trainset fit
    n_train, bal_cap = sharded_train_sizes(
        per, n_lists_local, p.kmeans_trainset_fraction, kp.balanced_max_ratio)
    prog = _sharded_build_program(
        mesh, axis, n, per, n_lists_local, cap, n_train,
        p.kmeans_n_iters, float(kp.balanced_penalty), bal_cap, p.seed)
    c, data, ids, counts, norms = prog(x_sh)
    return IvfFlatIndex(c, data, ids, counts, norms, p.metric)


@lru_cache(maxsize=16)
def _sharded_chunk_train_program(mesh: Mesh, axis: str, n_lists_local: int,
                                 max_iter: int, penalty: float, bal_cap: int,
                                 seed: int):
    """Per-shard coarse-quantizer fit for the sharded streaming build:
    each device balanced-fits ITS local centroids on ITS host-sampled
    trainset stripe (``[S·n_train, d]`` sharded in) — one shard_map
    program, S parallel fits, one compile."""
    from ..cluster.kmeans import _balanced_fit_impl

    def local(xt_l):
        shard = jax.lax.axis_index(axis)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), shard)
        c, _, _, _ = _balanced_fit_impl(
            xt_l, key, n_lists_local, max_iter, penalty, bal_cap)
        return c

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False))


@lru_cache(maxsize=16)
def _sharded_chunk_step_program(mesh: Mesh, axis: str, n_lists_local: int,
                                cap: int):
    """Data-parallel fused chunk step: every device runs
    :func:`_flat_chunk_step`'s body on ITS slice of the chunk against ITS
    local lists — one jitted shard_map program per chunk, slabs donated,
    zero cross-device data movement (rows only ever land in the lists of
    the shard they streamed through)."""
    from ..cluster.kmeans import _capped_assign_impl
    from ._packing import _scatter_append_impl

    def local(data_l, ids_l, counts_l, c_l, xc_l, idc_l):
        valid = idc_l >= 0
        labels, _ = _capped_assign_impl(xc_l, c_l, cap - counts_l, valid)
        (data_l, ids_l), counts_l = _scatter_append_impl(
            (data_l, ids_l), counts_l, labels, (xc_l, idc_l),
            n_lists=n_lists_local, cap=cap)
        return data_l, ids_l, counts_l

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(axis),) * 6, out_specs=(P(axis),) * 3,
        check_vma=False), donate_argnums=(0, 1, 2))


def build_chunked_sharded(dataset, mesh: Mesh,
                          params: Optional[IvfFlatIndexParams] = None, *,
                          chunk_rows: int = 0, source_ids=None,
                          axis: str = "shard") -> IvfFlatIndex:
    """Distributed streaming build — the build-side analog of
    :func:`search_sharded`: the dataset stays on host and each fixed-size
    chunk is split contiguously over the mesh axis (one sharded
    ``device_put``, staged a chunk ahead), with every device appending its
    slice into ITS OWN local lists via the fused donated chunk step.
    Combines :func:`build_chunked`'s out-of-core pipeline (fixed shapes,
    padded tail, single executable) with :func:`build_sharded`'s
    shard-local sub-index model (device s owns lists
    ``[s·L/S, (s+1)·L/S)`` trained on its own row stripes; ids are global
    row positions; :func:`search_sharded` probes every shard and merges).
    Per-device peak = local slabs + its chunk slice — corpora larger than
    ONE chip's HBM stream through S chips in parallel."""
    from jax.sharding import NamedSharding

    from ._packing import (build_heartbeat, chunked_shard_rows,
                           chunked_shard_trainsets, prefetch_chunks_padded,
                           resolve_chunk_rows, sharded_train_sizes)

    p = params or IvfFlatIndexParams()
    n, d = dataset.shape
    n_dev = int(mesh.shape[axis])
    n_lists_local = max(1, (p.n_lists + n_dev - 1) // n_dev)
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_flat")
    # chunks split evenly over the axis; never a chunk beyond one padded pass
    chunk_rows = min(-(-chunk_rows // n_dev), -(-n // n_dev)) * n_dev
    shard_valid = chunked_shard_rows(n, chunk_rows, n_dev)
    expects(int(shard_valid.min()) >= 1,
            f"chunk layout leaves a shard with no rows (n={n}, "
            f"chunk_rows={chunk_rows}, shards={n_dev}): lower chunk_rows "
            f"or use fewer shards")
    per = int(shard_valid.max())
    expects(n_lists_local <= per, "n_lists exceeds rows per shard")
    cap = max(1, int(np.ceil(p.list_cap_ratio * per / n_lists_local)))
    kp = KMeansParams()
    n_train, bal_cap = sharded_train_sizes(
        per, n_lists_local, p.kmeans_trainset_fraction, kp.balanced_max_ratio)
    dtype = jnp.asarray(np.asarray(dataset[:1])).dtype
    sharding = NamedSharding(mesh, P(axis))

    xt = chunked_shard_trainsets(dataset, n, chunk_rows, n_dev, n_train,
                                 p.seed)
    xt_sh = jax.device_put(xt.reshape(n_dev * n_train, d), sharding)
    train = _sharded_chunk_train_program(
        mesh, axis, n_lists_local, p.kmeans_n_iters,
        float(kp.balanced_penalty), bal_cap, p.seed)
    centroids = train(xt_sh)

    L = n_dev * n_lists_local
    data = jax.device_put(jnp.zeros((L, cap, d), dtype), sharding)
    ids_slab = jax.device_put(jnp.full((L, cap), -1, jnp.int32), sharding)
    counts = jax.device_put(jnp.zeros((L,), jnp.int32), sharding)
    step = _sharded_chunk_step_program(mesh, axis, n_lists_local, cap)
    heartbeat = build_heartbeat("ivf_flat.build_chunked_sharded", n)
    for lo, hi, xc, idc in prefetch_chunks_padded(
            dataset, chunk_rows, source_ids, dtype=dtype, sharding=sharding):
        data, ids_slab, counts = step(data, ids_slab, counts, centroids,
                                      xc, idc)
        heartbeat(hi)
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(centroids, data, ids_slab, counts, norms, p.metric)


@partial(jax.jit, static_argnames=("k", "n_probes", "metric", "axis", "mesh",
                                   "data_axis", "probe_block"))
def _search_sharded_impl(mesh, axis, centroids, data, ids, counts, norms, q,
                         k: int, n_probes: int, metric: str,
                         data_axis: Optional[str] = None, keep=None,
                         probe_block: int = 1):
    def local(centroids_l, data_l, ids_l, counts_l, norms_l, q_l, keep_l):
        bv, bi = _search_impl(centroids_l, data_l, ids_l, counts_l, norms_l,
                              q_l, k, n_probes, metric, keep_l, probe_block)
        # candidates from all shards → final top-k everywhere
        if metric == "inner_product":
            bv = -bv  # back to min-selectable
        av = jax.lax.all_gather(bv, axis, tiled=False)  # [S, nq, k]
        ai = jax.lax.all_gather(bi, axis, tiled=False)
        av = jnp.moveaxis(av, 0, 1).reshape(q_l.shape[0], -1)
        ai = jnp.moveaxis(ai, 0, 1).reshape(q_l.shape[0], -1)
        from ..matrix.select_k import select_k

        fv, fi = select_k(av, k, in_idx=ai, select_min=True)
        if metric == "inner_product":
            fv = -fv
        return fv, fi

    qspec = P(data_axis) if data_axis else P()
    # keep masks GLOBAL source ids, so it rides replicated over the shard
    # axis; a 2-D bitmap's query rows follow the query partitioning
    kspec = (P(data_axis) if (keep is not None and keep.ndim == 2
                              and data_axis) else P())
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), qspec, kspec),
        out_specs=(qspec, qspec),
        check_vma=False,
    )(centroids, data, ids, counts, norms, q, keep)


def search_sharded(index: IvfFlatIndex, queries, k: int,
                   params: Optional[IvfFlatSearchParams] = None, *,
                   mesh: Mesh, axis: str = "shard",
                   data_axis: Optional[str] = None, filter=None
                   ) -> Tuple[jax.Array, jax.Array]:
    """Multi-chip search: each shard probes its local lists (n_probes per
    shard — recall ≥ single-chip at equal n_probes), one all_gather merges.

    Per-shard probing searches each shard's nearest local lists, so the union
    over shards always covers the globally nearest lists.  On a 2-D mesh,
    ``data_axis`` partitions the queries over that axis (merges stay on the
    shard axis — see :func:`raft_tpu.core.make_hybrid_mesh`).

    ``filter``: bitset/bitmap prefilter over GLOBAL source ids, same
    contract as :func:`search` (replicated over the shard axis).
    """
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           resolve_probe_block, sentinel_filtered_ids)

    p = params or IvfFlatSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    n_dev = int(mesh.shape[axis])
    local_lists = index.n_lists // n_dev
    n_probes = min(p.n_probes, local_lists)
    probe_block = resolve_probe_block(p.probe_block, int(n_probes),
                                      index.list_cap, "ivf_flat")
    if data_axis is not None:
        expects(data_axis in mesh.axis_names, f"axis {data_axis!r} not in mesh")
        expects(q.shape[0] % int(mesh.shape[data_axis]) == 0,
                "queries not divisible by data axis")
    keep = as_keep_mask(filter, nq=q.shape[0])
    if keep is not None:
        check_filter_covers_ids(keep, index.ids)
    dv, di = _search_sharded_impl(mesh, axis, index.centroids, index.data,
                                  index.ids, index.counts, index.norms, q,
                                  int(k), int(n_probes), index.metric,
                                  data_axis, keep, probe_block)
    if keep is not None:
        di = sentinel_filtered_ids(dv, di)
    return dv, di


@dataclasses.dataclass(frozen=True)
class IvfFlatFleetSlices:
    """Device-mesh layout of an IVF-Flat index for the serving fleet
    (:mod:`raft_tpu.serve.fleet`): the list axis padded to a multiple of
    the mesh axis and split contiguously — shard *s* owns global lists
    ``[s*lists_per, (s+1)*lists_per)`` — with the (padded) centroid
    table replicated so every shard ranks the SAME probe order as the
    single-device searcher."""

    centroids: jax.Array  # [S*lists_per, d] replicated; pads finite-far
    data: jax.Array       # [S*lists_per, cap, d] sharded P(axis)
    ids: jax.Array        # [S*lists_per, cap] sharded; pads -1
    counts: jax.Array     # [S*lists_per] sharded; pads 0
    norms: jax.Array      # [S*lists_per, cap] sharded; pads 0
    lists_per: int        # lists per shard (padded count / S)
    n_lists: int          # original (unpadded) list count


# far-but-finite centroid pad: +inf would reach the probe ranking as
# 0*inf = NaN through sq_l2's dot-product expansion; 1e15 ranks last in
# f32 against any real squared distance while staying NaN-free.
_FLEET_CENTROID_PAD = 1e15


def fleet_slices(index: IvfFlatIndex, mesh: Mesh, *,
                 axis: str = "shard") -> IvfFlatFleetSlices:
    """Slice an :class:`IvfFlatIndex` over ``mesh[axis]`` for the fleet
    fan-out.  All padding happens host-side (numpy) and the slabs are
    ``device_put`` with their target sharding, so the single-device peak
    is one shard's slice — never the whole index."""
    from jax.sharding import NamedSharding

    expects(axis in mesh.axis_names, f"axis {axis!r} not in mesh")
    expects(jnp.issubdtype(jnp.asarray(index.centroids).dtype,
                           jnp.floating),
            "fleet slicing needs a float centroid table (the list-axis "
            "pad is a finite-far float sentinel)")
    n_dev = int(mesh.shape[axis])
    L = index.n_lists
    lp = (L + n_dev - 1) // n_dev
    pad = lp * n_dev - L

    def _pad0(x, fill):
        x = np.asarray(x)
        if not pad:
            return x
        shape = (pad,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, x.dtype)], axis=0)

    cen = _pad0(index.centroids, _FLEET_CENTROID_PAD)
    rep = NamedSharding(mesh, P())
    sh = NamedSharding(mesh, P(axis))
    return IvfFlatFleetSlices(
        centroids=jax.device_put(jnp.asarray(cen), rep),
        data=jax.device_put(jnp.asarray(_pad0(index.data, 0)), sh),
        ids=jax.device_put(jnp.asarray(_pad0(index.ids, -1)), sh),
        counts=jax.device_put(jnp.asarray(_pad0(index.counts, 0)), sh),
        norms=jax.device_put(jnp.asarray(_pad0(index.norms, 0)), sh),
        lists_per=int(lp), n_lists=int(L))
