"""IVF-Flat — inverted-file index with flat (uncompressed) lists.

No in-tree CUDA ancestor (cuVS migration, SURVEY.md scope note); designed
from the north-star capability list (``BASELINE.json`` configs: ivf_flat +
kmeans_balanced on SIFT-1M) and the TPU-KNN paper (PAPERS.md).

TPU-first design:
* **Coarse quantizer** = :func:`raft_tpu.cluster.kmeans_balanced_fit` — the
  balanced variant exists precisely because dense padded lists need a hard
  size bound (list capacity is a static shape).
* **List layout**: one dense ``[n_lists, cap, d]`` slab + ``[n_lists, cap]``
  source ids, pad entries masked by per-list counts.  Gathers of whole lists
  are contiguous HBM reads; no pointer-chasing.
* **Search**: query→centroid distances on the MXU, ``top_k`` probe pick,
  then one scan iteration per probe rank: gather the probed list slab,
  batched dot on the MXU, mask pads, merge into the running top-k via
  ``select_k`` (same merge primitive as brute force).  Everything
  static-shape, jit-compiled once per (nq, k, n_probes) config.
* **Sharded variant**: lists are partitioned round-robin over the mesh axis;
  every shard searches its local lists with the same program and the
  per-shard candidates merge with one ``all_gather`` + ``select_k`` -- the
  index-shard MNMG model of SURVEY.md §5.7 on ICI instead of NCCL.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..cluster.kmeans import KMeansParams, capped_assign, kmeans_balanced_fit
from ..core.array import wrap_array
from ..core.errors import expects
from ..distance.pairwise import sq_l2
from .brute_force import tile_knn_merge

__all__ = [
    "IvfFlatIndexParams",
    "IvfFlatSearchParams",
    "IvfFlatIndex",
    "build",
    "search",
    "extend",
    "build_sharded",
    "search_sharded",
]


@dataclasses.dataclass(frozen=True)
class IvfFlatIndexParams:
    """Build configuration (per-call parameter struct idiom, SURVEY.md §5.6b)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"  # sqeuclidean | euclidean | inner_product
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.1
    list_cap_ratio: float = 2.0  # capacity = ratio * n / n_lists
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class IvfFlatSearchParams:
    n_probes: int = 32


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IvfFlatIndex:
    centroids: jax.Array   # [L, d]
    data: jax.Array        # [L, cap, d]
    ids: jax.Array         # [L, cap] int32, -1 pad
    counts: jax.Array      # [L] int32
    norms: jax.Array       # [L, cap] f32 squared L2 of stored vectors
    metric: str = dataclasses.field(metadata=dict(static=True))

    @property
    def n_lists(self) -> int:
        return int(self.data.shape[0])

    @property
    def list_cap(self) -> int:
        return int(self.data.shape[1])

    @property
    def dim(self) -> int:
        return int(self.data.shape[2])

    @property
    def size(self) -> int:
        return int(jnp.sum(self.counts))


def _pack_lists(dataset: np.ndarray, ids: np.ndarray, labels: np.ndarray,
                n_lists: int, cap: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Scatter rows into the dense padded list slab (host-side build step)."""
    n, d = dataset.shape
    data = np.zeros((n_lists, cap, d), dataset.dtype)
    out_ids = np.full((n_lists, cap), -1, np.int32)
    # vectorized scatter: sort by list, position = rank within the list
    keep = labels >= 0
    order = np.argsort(labels[keep] if keep.all() else
                       np.where(keep, labels, n_lists), kind="stable")
    order = order[: int(keep.sum())]
    sl = labels[order]
    counts = np.bincount(sl, minlength=n_lists).astype(np.int32)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(order.shape[0]) - starts[sl]
    ok = pos < cap  # capped_assign guarantees this; belt and braces
    data[sl[ok], pos[ok]] = dataset[order[ok]]
    out_ids[sl[ok], pos[ok]] = ids[order[ok]]
    counts = np.minimum(counts, cap)
    return data, out_ids, counts


def build(dataset, params: Optional[IvfFlatIndexParams] = None, *,
          source_ids=None, res=None) -> IvfFlatIndex:
    """Train the coarse quantizer and pack inverted lists."""
    p = params or IvfFlatIndexParams()
    x = wrap_array(dataset, ndim=2, name="dataset")
    n, d = x.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))

    # 1. train balanced kmeans on a subsample (trainset_fraction idiom)
    n_train = max(p.n_lists * 4, int(n * p.kmeans_trainset_fraction))
    n_train = min(n, n_train)
    key = jax.random.PRNGKey(p.seed)
    sel = (jax.random.permutation(key, n)[:n_train] if n_train < n
           else jnp.arange(n))
    kp = KMeansParams(n_clusters=p.n_lists, max_iter=p.kmeans_n_iters,
                      seed=p.seed)
    centroids, _, _ = kmeans_balanced_fit(x[sel], kp)

    # 2. capacity-constrained assignment of the full dataset
    labels, _ = capped_assign(x, centroids, cap)

    # 3. pack lists (host scatter — build is host-driven like the reference's)
    ids = (np.asarray(source_ids, np.int32) if source_ids is not None
           else np.arange(n, dtype=np.int32))
    data, out_ids, counts = _pack_lists(np.asarray(x), ids,
                                        np.asarray(labels), p.n_lists, cap)
    data_j = jnp.asarray(data)
    norms = jnp.sum(data_j.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(centroids, data_j, jnp.asarray(out_ids),
                        jnp.asarray(counts), norms, p.metric)


def extend(index: IvfFlatIndex, new_vectors, new_ids=None) -> IvfFlatIndex:
    """Append vectors to existing lists (host-eager, like cuVS extend).

    The list slab is a static shape, so capacity grows when the new rows
    overflow it (rebuild-the-slab, the padded-layout price of extend).
    """
    x = np.asarray(wrap_array(new_vectors, ndim=2))
    ids = (np.asarray(new_ids, np.int32) if new_ids is not None
           else np.arange(index.size, index.size + x.shape[0], dtype=np.int32))
    labels = np.asarray(jnp.argmin(sq_l2(jnp.asarray(x), index.centroids), axis=1))
    old_counts = np.asarray(index.counts)
    added = np.bincount(labels, minlength=index.n_lists)
    new_cap = max(index.list_cap, int((old_counts + added).max()))

    n_lists, d = index.n_lists, index.dim
    data = np.zeros((n_lists, new_cap, d), np.asarray(index.data).dtype)
    out_ids = np.full((n_lists, new_cap), -1, np.int32)
    data[:, : index.list_cap] = np.asarray(index.data)
    out_ids[:, : index.list_cap] = np.asarray(index.ids)

    order = np.argsort(labels, kind="stable")
    sl = labels[order]
    starts = np.concatenate([[0], np.cumsum(added)[:-1]])
    pos = old_counts[sl] + (np.arange(order.shape[0]) - starts[sl])
    data[sl, pos] = x[order]
    out_ids[sl, pos] = ids[order]
    counts = (old_counts + added).astype(np.int32)

    data_j = jnp.asarray(data)
    norms = jnp.sum(data_j.astype(jnp.float32) ** 2, axis=2)
    return IvfFlatIndex(index.centroids, data_j, jnp.asarray(out_ids),
                        jnp.asarray(counts), norms, index.metric)


def _probe_scan(q, qn, data, ids, counts, norms, probes, k: int, metric: str):
    """Scan probe ranks, merging each probed list into the running top-k.

    q: [nq, d]; probes: [nq, P].  One iteration gathers the p-th probed list
    of every query ([nq, cap, d] slab) and computes the distance block with a
    batched MXU dot.
    """
    nq = q.shape[0]
    cap = data.shape[1]
    n_probes = probes.shape[1]

    def step(carry, p):
        best_val, best_idx = carry
        lists = probes[:, p]                      # [nq]
        vecs = data[lists]                        # [nq, cap, d]
        vids = ids[lists]                         # [nq, cap]
        dots = jnp.einsum(
            "qcd,qd->qc", vecs, q,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric == "inner_product":
            dist = -dots
        else:  # sqeuclidean / euclidean rank by squared L2
            dist = norms[lists] - 2.0 * dots + qn[:, None]
            dist = jnp.maximum(dist, 0.0)
        valid = jnp.arange(cap)[None, :] < counts[lists][:, None]
        dist = jnp.where(valid & (vids >= 0), dist, jnp.inf)
        return tile_knn_merge(best_val, best_idx, dist, vids, k), None

    init = (jnp.full((nq, k), jnp.inf, jnp.float32),
            jnp.full((nq, k), -1, jnp.int32))
    (bv, bi), _ = jax.lax.scan(step, init, jnp.arange(n_probes))
    return bv, bi


@partial(jax.jit, static_argnames=("k", "n_probes", "metric"))
def _search_impl(centroids, data, ids, counts, norms, q, k: int,
                 n_probes: int, metric: str):
    qf = q.astype(jnp.float32)
    qn = jnp.sum(qf * qf, axis=1)
    cd = sq_l2(q, centroids)                      # [nq, L] MXU block
    _, probes = jax.lax.top_k(-cd, n_probes)      # nearest lists
    bv, bi = _probe_scan(q, qn, data, ids, counts, norms, probes, k, metric)
    if metric == "euclidean":
        bv = jnp.sqrt(jnp.maximum(bv, 0.0))
    elif metric == "inner_product":
        bv = -bv
    return bv, bi


def search(index: IvfFlatIndex, queries, k: int,
           params: Optional[IvfFlatSearchParams] = None, *, res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """Approximate kNN: returns ``(distances, ids)`` of (nq, k), best first."""
    p = params or IvfFlatSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    n_probes = min(p.n_probes, index.n_lists)
    return _search_impl(index.centroids, index.data, index.ids, index.counts,
                        index.norms, q, int(k), int(n_probes), index.metric)


# ---------------------------------------------------------------------------
# Sharded (multi-chip) variant: lists partitioned over the mesh axis.
# ---------------------------------------------------------------------------


def build_sharded(dataset, mesh: Mesh, params: Optional[IvfFlatIndexParams] = None,
                  *, axis: str = "shard") -> IvfFlatIndex:
    """Build with ``n_lists`` padded to the axis size and the list slabs laid
    out shard-major so device d owns lists [d*L/n, (d+1)*L/n)."""
    p = params or IvfFlatIndexParams()
    n_dev = int(mesh.shape[axis])
    n_lists = ((p.n_lists + n_dev - 1) // n_dev) * n_dev
    p = dataclasses.replace(p, n_lists=n_lists)
    index = build(dataset, p)
    sharding = jax.sharding.NamedSharding(mesh, P(axis))
    return IvfFlatIndex(
        jax.device_put(index.centroids, sharding),
        jax.device_put(index.data, sharding),
        jax.device_put(index.ids, sharding),
        jax.device_put(index.counts, sharding),
        jax.device_put(index.norms, sharding),
        index.metric,
    )


@partial(jax.jit, static_argnames=("k", "n_probes", "metric", "axis", "mesh"))
def _search_sharded_impl(mesh, axis, centroids, data, ids, counts, norms, q,
                         k: int, n_probes: int, metric: str):
    def local(centroids_l, data_l, ids_l, counts_l, norms_l, q_l):
        bv, bi = _search_impl(centroids_l, data_l, ids_l, counts_l, norms_l,
                              q_l, k, n_probes, metric)
        # candidates from all shards → final top-k everywhere
        if metric == "inner_product":
            bv = -bv  # back to min-selectable
        av = jax.lax.all_gather(bv, axis, tiled=False)  # [S, nq, k]
        ai = jax.lax.all_gather(bi, axis, tiled=False)
        av = jnp.moveaxis(av, 0, 1).reshape(q_l.shape[0], -1)
        ai = jnp.moveaxis(ai, 0, 1).reshape(q_l.shape[0], -1)
        from ..matrix.select_k import select_k

        fv, fi = select_k(av, k, in_idx=ai, select_min=True)
        if metric == "inner_product":
            fv = -fv
        return fv, fi

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )(centroids, data, ids, counts, norms, q)


def search_sharded(index: IvfFlatIndex, queries, k: int,
                   params: Optional[IvfFlatSearchParams] = None, *,
                   mesh: Mesh, axis: str = "shard"
                   ) -> Tuple[jax.Array, jax.Array]:
    """Multi-chip search: each shard probes its local lists (n_probes per
    shard — recall ≥ single-chip at equal n_probes), one all_gather merges.

    Per-shard probing searches each shard's nearest local lists, so the union
    over shards always covers the globally nearest lists.
    """
    p = params or IvfFlatSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    n_dev = int(mesh.shape[axis])
    local_lists = index.n_lists // n_dev
    n_probes = min(p.n_probes, local_lists)
    return _search_sharded_impl(mesh, axis, index.centroids, index.data,
                                index.ids, index.counts, index.norms, q,
                                int(k), int(n_probes), index.metric)
