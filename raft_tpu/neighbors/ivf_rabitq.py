"""IVF-RaBitQ — inverted-file index with 1-bit random-rotation codes.

The third rung of the memory-vs-recall ladder (flat → pq → rabitq,
docs/perf_analysis.md): each stored vector keeps only the SIGN of its
randomly-rotated residual — ⌈d/8⌉ bytes of code versus ``d`` bytes of
int8-PQ or ``4d`` of f32 — plus three f32 correction scalars, and the
scan estimates distances from those codes with RaBitQ's unbiased
estimator (PAPERS.md).  Returned values are EXACT: the estimator only
gates the candidate set (an unsorted top-``rerank_k`` fold over
estimates), and the survivors re-score against the raw row slab through
the same ``exact_gathered_dots`` tier every exact engine uses.

Design deltas vs :mod:`.ivf_flat` (everything else is shared):

* **No trained codebook.**  The encoder is one seeded random rotation
  (QR of a gaussian, a per-index constant) — no PQ codebook k-means, so
  building is assignment-bound and beats ``ivf_pq.build`` rows/s
  (bench/RABITQ_CPU.json).
* **Packed-binary scoring path.**  The probe scan gathers packed code
  bytes (8 dims/byte — the HBM read is 32× below the f32 slab's),
  unpacks AFTER the gather, and scores ``⟨sign(r), q8⟩`` as ONE int8
  MXU einsum per block (:func:`raft_tpu.ops.blocked_scan
  .packed_sign_dots` — popcount-as-int8-einsum).  The query-side work
  (rotation, int8 quantization) hoists once per query, the PR 3
  ADC-LUT pattern.
* **Estimate → rerank.**  Per block the unbiased estimate folds into an
  unsorted top-``rerank_k`` carry with the flat-slab pointer as a
  payload lane; after the scan the finalists re-gather from ``data``
  and re-score exactly, then ONE ranked selection cuts to k.  With
  ``rerank_k = n`` every candidate survives, making results
  bit-identical to ``brute_force`` (values AND ids) — the
  tests/test_ivf_rabitq.py oracle.

Estimator algebra (RaBitQ, PAPERS.md): store ``s = sign(P(x−c))``
packed, ``sabs = Σ|P(x−c)| = ⟨s, P(x−c)⟩``, ``rn2 = ‖x−c‖²`` and
``cs = ⟨s, Pc⟩``.  With the hoisted ``⟨s, Pq⟩ ≈ Δ·⟨s, q8⟩``:

    ⟨x−c, q−c⟩ ≈ (rn2 / sabs) · (Δ·⟨s, q8⟩ − cs)
    ‖q−x‖²     ≈ ‖q−c‖² + rn2 − 2·⟨x−c, q−c⟩
    ⟨q, x⟩     ≈ ⟨q, c⟩ + (rn2 / sabs) · Δ·⟨s, q8⟩

(the projection of the unit residual onto its sign code, ``sabs``,
normalizes the estimate — the codebook-free unbiasing that lets a plain
sign code rank; ``sabs ≤ 0`` degenerates to the centroid distance).
"""

from __future__ import annotations

import dataclasses
import json
import os
from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cluster.kmeans import KMeansParams, capped_assign, kmeans_balanced_fit
from ..core import tracing
from ..core.array import wrap_array
from ..core.errors import expects
from ..distance.pairwise import sq_l2
from ..ops import blocked_scan as _scan

__all__ = [
    "IvfRabitqIndexParams",
    "IvfRabitqSearchParams",
    "IvfRabitqIndex",
    "build",
    "build_chunked",
    "search",
    "searcher",
    "extend",
    "resolve_rerank_k",
    "fleet_slices",
    "IvfRabitqFleetSlices",
]


@dataclasses.dataclass(frozen=True)
class IvfRabitqIndexParams:
    """Build configuration (per-call parameter struct idiom).  No
    codebook knobs: the encoder is one seeded random rotation."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"  # sqeuclidean | euclidean | inner_product
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.1
    list_cap_ratio: float = 2.0  # capacity = ratio * n / n_lists
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class IvfRabitqSearchParams:
    n_probes: int = 32
    # exact-rerank candidate count: the estimator scan keeps this many
    # best estimates (unsorted fold), survivors re-score exactly.  0 =
    # auto (recall-gated tuned table via bench/tune_rabitq.py, else a
    # heuristic).  rerank_k = index.size makes results bit-identical to
    # brute force; this is THE recall knob (docs/tuning_guide.md).
    rerank_k: int = 0
    query_chunk: int = 4096  # cap on the per-dispatch gather working set
    # probes gathered+scored+merged per scan step; 0 = auto (rabitq
    # tuned table, else the shared probe_block table/heuristic).
    # Results are bit-identical for every value — pure speed knob.
    probe_block: int = 0
    # blocked-scan engine hook: "auto" | "xla" | "fused".  The estimator
    # scan has no fused Pallas arm yet (ROADMAP follow-up) — the gate
    # resolves cleanly and every choice dispatches the XLA path today.
    scan_kernel: str = "auto"


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IvfRabitqIndex:
    centroids: jax.Array    # [L, d]
    rotation: jax.Array     # [d, d] f32 orthonormal P (rows = new basis)
    codes: jax.Array        # [L, cap, ceil(d/8)] uint8 packed sign bits
    sabs: jax.Array         # [L, cap] f32  Σ|P(x−c)|  (estimator scale)
    res_norms: jax.Array    # [L, cap] f32  ‖x−c‖²
    code_cdots: jax.Array   # [L, cap] f32  ⟨sign(P(x−c)), Pc⟩
    data: jax.Array         # [L, cap, d] raw rows (exact-rerank tier)
    ids: jax.Array          # [L, cap] int32, -1 pad
    counts: jax.Array       # [L] int32
    metric: str = dataclasses.field(metadata=dict(static=True))

    @property
    def n_lists(self) -> int:
        return int(self.codes.shape[0])

    @property
    def list_cap(self) -> int:
        return int(self.codes.shape[1])

    @property
    def dim(self) -> int:
        return int(self.data.shape[2])

    @property
    def size(self) -> int:
        return int(jnp.sum(self.counts))  # jaxlint: disable=JX01 size is a host-facing API scalar, not on the search path


def _rotation(d: int, seed: int) -> jax.Array:
    """The per-index random rotation: QR of a seeded gaussian, sign-fixed
    so the factorization is deterministic.  Rows are the rotated basis —
    apply as ``x @ rotation.T``."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), 0x5AB1)
    g = jax.random.normal(key, (d, d), jnp.float32)
    q, r = jnp.linalg.qr(g)
    return (q * jnp.sign(jnp.diagonal(r))[None, :]).T


def _rotated_centroids(centroids, rotation) -> jax.Array:
    """``Pc`` [L, d] — encode-time constant (search never needs it; the
    per-vector ``cs`` scalars already carry ``⟨s, Pc⟩``)."""
    return jnp.einsum("ld,ed->le", centroids.astype(jnp.float32), rotation,
                      precision=jax.lax.Precision.HIGHEST)


def _encode(x, labels, centroids, rotation, rotc):
    """Per-row RaBitQ encoding: packed sign codes + the three correction
    scalars.  Rows with label −1 (pad/dropped) encode garbage the
    scatter/pack drops — values never matter.  All arithmetic in f32 at
    HIGHEST precision: the encode must be bit-stable across batch
    slicing so chunked builds and online extends reproduce the one-shot
    build exactly (tests/test_ivf_rabitq.py pins this)."""
    xf = x.astype(jnp.float32)
    cl = jnp.clip(labels, 0, centroids.shape[0] - 1)
    r = xf - centroids.astype(jnp.float32)[cl]
    rr = jnp.einsum("nd,ed->ne", r, rotation,
                    precision=jax.lax.Precision.HIGHEST)
    codes = _scan.pack_sign_bits(rr)
    s = jnp.where(rr >= 0, 1.0, -1.0)
    sabs = jnp.sum(jnp.abs(rr), axis=1)
    rn2 = jnp.sum(r * r, axis=1)
    cs = jnp.sum(s * rotc[cl], axis=1)
    return codes, sabs, rn2, cs


@tracing.annotate("ivf_rabitq.build")
def build(dataset, params: Optional[IvfRabitqIndexParams] = None, *,
          source_ids=None, res=None) -> IvfRabitqIndex:
    """Train the coarse quantizer, encode every row (one rotation einsum
    + sign pack — no codebook training, the rows/s edge over
    ``ivf_pq.build``), and pack inverted lists on device."""
    p = params or IvfRabitqIndexParams()
    x = wrap_array(dataset, ndim=2, name="dataset")
    n, d = x.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    expects(p.metric in ("sqeuclidean", "euclidean", "inner_product"),
            f"unsupported metric {p.metric!r}")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))

    n_train = min(n, max(p.n_lists * 4, int(n * p.kmeans_trainset_fraction)))
    key = jax.random.PRNGKey(p.seed)
    sel = (jax.random.permutation(key, n)[:n_train] if n_train < n
           else jnp.arange(n))
    kp = KMeansParams(n_clusters=p.n_lists, max_iter=p.kmeans_n_iters,
                      seed=p.seed)
    centroids, _, _ = kmeans_balanced_fit(x[sel], kp)

    rotation = _rotation(d, p.seed)
    rotc = _rotated_centroids(centroids, rotation)
    labels, _ = capped_assign(x, centroids, cap)
    codes, sabs, rn2, cs = _encode(x, labels, centroids, rotation, rotc)

    from ._packing import pack_lists

    ids = (jnp.asarray(source_ids, jnp.int32) if source_ids is not None
           else jnp.arange(n, dtype=jnp.int32))
    (codes, sabs, rn2, cs, data, out_ids), counts = pack_lists(
        labels, (codes, sabs, rn2, cs, x, ids), n_lists=p.n_lists, cap=cap,
        fills=(0, 0.0, 0.0, 0.0, 0.0, -1))
    return IvfRabitqIndex(centroids, rotation, codes, sabs, rn2, cs,
                          data, out_ids, counts, p.metric)


def _rabitq_step_impl(slabs, counts, centroids, rotation, rotc, xc, idc, *,
                      n_lists: int, cap: int):
    """ONE fused program per chunk (the PR 4 slab-donating pipeline):
    masked capped assignment against remaining room + RaBitQ encode +
    scatter-append over all six payload slabs.  Pad rows (``idc < 0``)
    never request a list, never consume capacity, and scatter-drop via
    label −1 — the padded stream is bit-identical to the per-op loop.

    Two jitted forms, exactly the flat pattern:
    :func:`_rabitq_chunk_step` donates the slabs (build loops own their
    buffers); :func:`_rabitq_chunk_step_cow` leaves the inputs alive for
    the copy-on-write first step of the online :func:`extend`."""
    from ..cluster.kmeans import _capped_assign_impl
    from ._packing import _scatter_append_impl

    valid = idc >= 0
    labels, _ = _capped_assign_impl(xc, centroids, cap - counts, valid)
    codes, sabs, rn2, cs = _encode(xc, labels, centroids, rotation, rotc)
    return _scatter_append_impl(slabs, counts, labels,
                                (codes, sabs, rn2, cs, xc, idc),
                                n_lists=n_lists, cap=cap)


_rabitq_chunk_step = partial(jax.jit, static_argnames=("n_lists", "cap"),
                             donate_argnums=(0, 1))(_rabitq_step_impl)
_rabitq_chunk_step_cow = partial(
    jax.jit, static_argnames=("n_lists", "cap"))(_rabitq_step_impl)


def _empty_slabs(n_lists: int, cap: int, d: int, dtype):
    """Fresh device slab set (compiled fills — guard-clean under
    ``transfer_guard("disallow")``)."""
    from ._packing import device_full

    db = -(-d // 8)
    return (device_full((n_lists, cap, db), 0, jnp.uint8),
            device_full((n_lists, cap), 0.0, jnp.float32),
            device_full((n_lists, cap), 0.0, jnp.float32),
            device_full((n_lists, cap), 0.0, jnp.float32),
            device_full((n_lists, cap, d), 0, dtype),
            device_full((n_lists, cap), -1, jnp.int32))


def _stream_pipelined(dataset, centroids, rotation, p: IvfRabitqIndexParams,
                      n: int, cap: int, chunk_rows: int, source_ids, dtype,
                      heartbeat=None):
    """Pipelined chunk engine: fixed-shape double-buffered device staging
    feeding the fused donated :func:`_rabitq_chunk_step` — one
    executable, one dispatch per chunk."""
    from ._packing import device_full, prefetch_chunks_padded

    d = dataset.shape[1]
    slabs = _empty_slabs(p.n_lists, cap, d, dtype)
    counts = device_full((p.n_lists,), 0, jnp.int32)
    rotc = _rotated_centroids(centroids, rotation)
    for lo, hi, xc, idc in prefetch_chunks_padded(dataset, chunk_rows,
                                                  source_ids, dtype=dtype):
        slabs, counts = _rabitq_chunk_step(
            slabs, counts, centroids, rotation, rotc, xc, idc,
            n_lists=p.n_lists, cap=cap)
        if heartbeat is not None:
            heartbeat(hi)
    return slabs, counts


def _stream_perop(dataset, centroids, rotation, p: IvfRabitqIndexParams,
                  n: int, cap: int, chunk_rows: int, source_ids, dtype):
    """Reference per-op chunk loop: blocking H2D ``jnp.asarray``,
    separate assign / encode / scatter dispatches, tail chunk at its own
    shape.  The bit-parity oracle for the fused engine and the A/B
    baseline of ``bench/build_throughput.py``."""
    from ..cluster.kmeans import capped_assign_room
    from ._packing import prefetch_chunks, scatter_append

    d = dataset.shape[1]
    db = -(-d // 8)
    slabs = (jnp.zeros((p.n_lists, cap, db), jnp.uint8),
             jnp.zeros((p.n_lists, cap), jnp.float32),
             jnp.zeros((p.n_lists, cap), jnp.float32),
             jnp.zeros((p.n_lists, cap), jnp.float32),
             jnp.zeros((p.n_lists, cap, d), dtype),
             jnp.full((p.n_lists, cap), -1, jnp.int32))
    counts = jnp.zeros((p.n_lists,), jnp.int32)
    rotc = _rotated_centroids(centroids, rotation)
    for lo, hi, xc_h, idc_h in prefetch_chunks(dataset, chunk_rows,
                                               source_ids):
        xc = jnp.asarray(xc_h, dtype)
        idc = jnp.asarray(idc_h, jnp.int32)
        labels, _ = capped_assign_room(xc, centroids, cap - counts)
        codes, sabs, rn2, cs = _encode(xc, labels, centroids, rotation, rotc)
        slabs, counts = scatter_append(
            slabs, counts, labels, (codes, sabs, rn2, cs, xc, idc),
            n_lists=p.n_lists, cap=cap)
    return slabs, counts


def build_chunked(dataset, params: Optional[IvfRabitqIndexParams] = None, *,
                  chunk_rows: int = 0, source_ids=None,
                  res=None) -> IvfRabitqIndex:
    """Out-of-core build on the PR 4 pipeline: the dataset stays on host
    and streams through the fused slab-donating chunk step (see
    :func:`raft_tpu.neighbors.ivf_flat.build_chunked` — same engine, the
    encode rides inside the chunk program).  Device peak = six list
    slabs + two staged chunks; ``chunk_rows=0`` = auto
    (:func:`~._packing.resolve_chunk_rows`)."""
    from .ivf_flat import _coarse_train_chunked
    from ._packing import build_heartbeat, resolve_chunk_rows

    p = params or IvfRabitqIndexParams()
    n, d = dataset.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    expects(p.metric in ("sqeuclidean", "euclidean", "inner_product"),
            f"unsupported metric {p.metric!r}")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    dtype = jnp.asarray(np.asarray(dataset[:1])).dtype
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_rabitq")

    centroids = _coarse_train_chunked(dataset, p, n)
    rotation = _rotation(d, p.seed)
    (codes, sabs, rn2, cs, data, ids_slab), counts = _stream_pipelined(
        dataset, centroids, rotation, p, n, cap, chunk_rows, source_ids,
        dtype, heartbeat=build_heartbeat("ivf_rabitq.build_chunked", n))
    return IvfRabitqIndex(centroids, rotation, codes, sabs, rn2, cs,
                          data, ids_slab, counts, p.metric)


def _build_chunked_perop(dataset,
                         params: Optional[IvfRabitqIndexParams] = None, *,
                         chunk_rows: int = 0,
                         source_ids=None) -> IvfRabitqIndex:
    """:func:`build_chunked` on the reference per-op chunk loop — the
    parity oracle / A/B baseline; not part of the public API."""
    from .ivf_flat import _coarse_train_chunked
    from ._packing import resolve_chunk_rows

    p = params or IvfRabitqIndexParams()
    n, d = dataset.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    dtype = jnp.asarray(np.asarray(dataset[:1])).dtype
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_rabitq")
    centroids = _coarse_train_chunked(dataset, p, n)
    rotation = _rotation(d, p.seed)
    (codes, sabs, rn2, cs, data, ids_slab), counts = _stream_perop(
        dataset, centroids, rotation, p, n, cap, chunk_rows, source_ids,
        dtype)
    return IvfRabitqIndex(centroids, rotation, codes, sabs, rn2, cs,
                          data, ids_slab, counts, p.metric)


def extend(index: IvfRabitqIndex, new_vectors, new_ids=None, *,
           insert_chunk: int = 0) -> IvfRabitqIndex:
    """Online streaming insert through the fused slab-donating chunk
    step — the :func:`raft_tpu.neighbors.ivf_flat.extend` contract
    verbatim (copy-on-write first step, fixed insert bucket, one scalar
    spill check, geometric slab growth), with the RaBitQ encode fused
    into the chunk program.  With capacity to spare, extending is
    bit-identical to a from-scratch pack at the same centroids."""
    from ._packing import (DEFAULT_INSERT_CHUNK, host_rows,
                           staged_insert_chunks)

    L, cap, d = index.n_lists, index.list_cap, index.dim
    x = host_rows(new_vectors)
    expects(x.ndim == 2 and x.shape[1] == d, "vector dim mismatch")
    n_new = x.shape[0]
    expects(n_new >= 1, "no rows to insert")
    base = int(jax.device_get(jnp.sum(index.counts)))  # jaxlint: disable=JX01 one scalar sync per extend call: sizes auto-assigned ids and the spill check baseline
    ids = (np.asarray(host_rows(new_ids), np.int32) if new_ids is not None
           else np.arange(base, base + n_new, dtype=np.int32))
    expects(ids.shape == (n_new,), "new_ids must be one id per row")
    expects(int(ids.min()) >= 0, "source ids must be >= 0 (−1 is the pad)")
    chunk = int(insert_chunk) or DEFAULT_INSERT_CHUNK
    rotc = _rotated_centroids(index.centroids, index.rotation)

    def stream(slabs, counts, slab_cap):
        step = _rabitq_chunk_step_cow  # inputs may back a live snapshot
        for xc, idc in staged_insert_chunks(x, ids, chunk, index.data.dtype):
            slabs, counts = step(slabs, counts, index.centroids,
                                 index.rotation, rotc, xc, idc,
                                 n_lists=L, cap=slab_cap)
            step = _rabitq_chunk_step  # fresh private buffers: donate
        return slabs, counts

    src = (index.codes, index.sabs, index.res_norms, index.code_cdots,
           index.data, index.ids)
    slabs, counts = stream(src, index.counts, cap)
    placed = int(jax.device_get(jnp.sum(counts))) - base  # jaxlint: disable=JX01 explicit spill check: one scalar per extend gates the rare slab-growth path
    if placed < n_new:  # capacity exhausted — grow + re-run (rare)
        xd = jnp.asarray(x.astype(index.data.dtype, copy=False))
        labels = jnp.argmin(sq_l2(xd, index.centroids), axis=1)
        added = jax.ops.segment_sum(jnp.ones_like(labels, jnp.int32),
                                    labels, num_segments=L)
        need = int(jnp.max(index.counts + added))  # jaxlint: disable=JX01 slab capacity must be a host int at extend time (static shapes)
        new_cap = max(need, cap + (cap + 1) // 2)  # geometric headroom
        pad = new_cap - cap

        def grow(slab, fill):
            width = ((0, 0), (0, pad)) + ((0, 0),) * (slab.ndim - 2)
            return jnp.pad(slab, width, constant_values=fill)

        grown = tuple(grow(s, f) for s, f in
                      zip(src, (0, 0.0, 0.0, 0.0, 0.0, -1)))
        slabs, counts = stream(grown, index.counts, new_cap)
    codes, sabs, rn2, cs, data, out_ids = slabs
    return IvfRabitqIndex(index.centroids, index.rotation, codes, sabs,
                          rn2, cs, data, out_ids, counts, index.metric)


def _estimate_survivors(qf, cd, centroids, rotation, codes, sabs,
                        res_norms, code_cdots, ids, counts, probes,
                        rerank_k: int, metric: str, keep=None,
                        probe_block: int = 1):
    """Probe-blocked estimator scan: the device half shared by the
    in-memory rerank (:func:`_estimate_scan`) and the out-of-core tier
    (:mod:`~raft_tpu.neighbors.ooc`, which reranks against host shards).

    Per block: gather PACKED code bytes (the bandwidth win — ⌈d/8⌉
    bytes/row move, not 4d), score ``⟨s, q8⟩`` via the shared
    packed-binary path, apply the unbiased estimator with the gathered
    per-vector scalars, and fold an unsorted top-``rerank_k`` carrying
    the flat-slab pointer payload.  Returns ``(bv, bi, bp)``: estimator
    values, survivor source ids, and flat raw-slab pointers (meaningful
    only when a raw slab exists — the out-of-core tier ignores it)."""
    from ._packing import blocked_probe_plan, keep_lookup

    nq = qf.shape[0]
    cap = codes.shape[1]
    lists_xs, pvalid = blocked_probe_plan(probes, probe_block)

    # hoisted once per query (the PR 3 ADC-LUT pattern): rotate the
    # query and quantize to int8 for the MXU popcount-einsum
    qrot = jnp.einsum("qd,ed->qe", qf, rotation,
                      precision=jax.lax.Precision.HIGHEST)
    delta = jnp.max(jnp.abs(qrot), axis=1) / 127.0
    delta = jnp.where(delta > 0.0, delta, 1.0)
    q8 = jnp.round(qrot / delta[:, None]).astype(jnp.int8)
    qc = (jnp.einsum("qd,ld->ql", qf, centroids.astype(jnp.float32),
                     precision=jax.lax.Precision.HIGHEST)
          if metric == "inner_product" else None)

    def score(inp):
        lists, pv = inp                            # [nq, B], [B]
        bcap = lists.shape[1] * cap
        sq = _scan.slab_dots(codes[lists], q8,
                             packed_sign=True).reshape(nq, bcap)
        sa = sabs[lists].reshape(nq, bcap)
        rn2 = res_norms[lists].reshape(nq, bcap)
        vids = ids[lists].reshape(nq, bcap)
        g = jnp.where(sa > 0.0, rn2 / sa, 0.0)     # estimator scale
        sqf = delta[:, None] * sq                  # ≈ ⟨s, Pq⟩
        if metric == "inner_product":
            qcl = jnp.repeat(jnp.take_along_axis(qc, lists, axis=1),
                             cap, axis=1)
            est = -(qcl + g * sqf)
        else:
            cs = code_cdots[lists].reshape(nq, bcap)
            cdl = jnp.repeat(jnp.take_along_axis(cd, lists, axis=1),
                             cap, axis=1)
            est = jnp.maximum(cdl + rn2 - 2.0 * g * (sqf - cs), 0.0)
        valid = (jnp.arange(cap)[None, None, :]
                 < counts[lists][:, :, None]).reshape(nq, bcap)
        valid = valid & (vids >= 0) & jnp.repeat(pv, cap)[None, :]
        if keep is not None:
            valid = valid & keep_lookup(keep, vids)
        ptr = _scan.list_slab_ptr(lists, cap)
        return jnp.where(valid, est, jnp.inf), vids, ptr

    def step(carry, inp):
        bv, bi, bp = carry
        est, vids, ptr = score(inp)
        mv, mi, (mp,) = _scan.fold_topk_payload(bv, bi, (bp,), est, vids,
                                                (ptr,), rerank_k)
        return (mv, mi, mp), None

    bv0, bi0 = _scan.topk_carry(nq, rerank_k)
    bp0 = jnp.zeros((nq, rerank_k), jnp.int32)
    (bv, bi, bp), _ = jax.lax.scan(step, (bv0, bi0, bp0),
                                   (lists_xs, pvalid))
    return bv, bi, bp


def _estimate_scan(q, qf, qn, cd, centroids, rotation, codes, sabs,
                   res_norms, code_cdots, data, ids, counts, probes,
                   k: int, rerank_k: int, metric: str, keep=None,
                   probe_block: int = 1):
    """Estimator scan + exact rerank.  After
    :func:`_estimate_survivors`, the survivors re-gather from the raw
    slab and re-score exactly through the
    :func:`~raft_tpu.ops.blocked_scan.l2_rescorer` seam (stored-norm-free
    form — the norms recompute from the gathered rows in brute-force
    accumulation order, which is what makes ``rerank_k = n`` bit-match
    ``brute_force.knn``); ONE ranked selection cuts to k."""
    bv, bi, bp = _estimate_survivors(qf, cd, centroids, rotation, codes,
                                     sabs, res_norms, code_cdots, ids,
                                     counts, probes, rerank_k, metric,
                                     keep, probe_block)
    rescore = _scan.l2_rescorer(data, None, q, qn, metric)
    dist = rescore(bp, bi)
    dist = jnp.where(jnp.isfinite(bv) & (bi >= 0), dist, jnp.inf)
    return _scan.ranked_finish(dist, bi, k)


@partial(jax.jit, static_argnames=("k", "n_probes", "rerank_k", "metric",
                                   "probe_block", "scan_kernel"))
def _search_impl(centroids, rotation, codes, sabs, res_norms, code_cdots,
                 data, ids, counts, q, k: int, n_probes: int,
                 rerank_k: int, metric: str, keep=None,
                 probe_block: int = 1, scan_kernel: str = "xla"):
    # scan_kernel rides the static signature so a future fused estimator
    # kernel slots in without an API change; both "xla" and "fused"
    # dispatch the XLA estimator scan today (gate.py resolves cleanly).
    del scan_kernel
    qf = q.astype(jnp.float32)
    qn = _scan.row_sq_norms(qf)   # dot-contraction; bit-stable across
    # the single-device and fleet SPMD executables (serve bit-identity)
    cd = sq_l2(q, centroids)                      # [nq, L] MXU block
    _, probes = jax.lax.top_k(-cd, n_probes)      # nearest lists
    bv, bi = _estimate_scan(q, qf, qn, cd, centroids, rotation, codes,
                            sabs, res_norms, code_cdots, data, ids, counts,
                            probes, k, rerank_k, metric, keep, probe_block)
    if metric == "euclidean":
        bv = jnp.sqrt(jnp.maximum(bv, 0.0))
    elif metric == "inner_product":
        bv = -bv
    return bv, bi


@lru_cache(maxsize=1)
def _rabitq_tune_table():
    """Recall-gated (rerank_k, probe_block) table written by
    ``bench/tune_rabitq.py``.  Canonical name first; a
    ``.{backend}.json`` suffix holds off-TPU measurements.  A table
    whose ``kernel_sha`` doesn't match the current scan sources is stale
    and ignored (the estimator path lives in ``ops/blocked_scan.py``)."""
    base = os.path.join(os.path.dirname(__file__), "_rabitq_tune_table")
    cands = [base + ".json"]
    try:
        cands.append(base + f".{jax.default_backend()}.json")
    except Exception:  # pragma: no cover - backend probe failure
        pass
    for path in cands:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("kernel_sha") != _scan.scan_kernel_sha():
            from ..core.logging import default_logger

            default_logger().info(
                "rabitq tune table %s is sha-stale (table %s, sources %s); "
                "falling back to heuristics", os.path.basename(path),
                doc.get("kernel_sha"), _scan.scan_kernel_sha())
            continue
        return doc.get("entries", {})
    return {}


def _tune_entry(k: int, n_probes: int, cap: int) -> dict:
    return _rabitq_tune_table().get(
        f"ivf_rabitq:{int(k).bit_length()}:{int(n_probes).bit_length()}"
        f":{int(cap).bit_length()}", {})


def resolve_rerank_k(requested: int, k: int, n_probes: int,
                     cap: int) -> int:
    """Static exact-rerank width.  ``requested > 0`` wins (must be ≥ k);
    ``0`` = auto: the recall-gated tuned table (log2-bucketed by
    ``(k, n_probes, cap)``, written by ``bench/tune_rabitq.py``), else a
    ``8·k`` heuristic.  Unlike ``probe_block`` this knob changes RESULTS
    (it gates the candidate set) — which is why the tuner behind the
    table is recall-gated, exactly the ``resolve_cagra_search`` model.
    Clamped to the probed-candidate total.  Pure host-int arithmetic."""
    total = max(1, int(n_probes) * int(cap))
    if requested:
        expects(int(requested) >= int(k),
                f"rerank_k ({requested}) must be >= k ({k})")
        return max(int(k), min(int(requested), total))
    entry = _tune_entry(k, n_probes, cap).get("rerank_k")
    if entry is None:
        entry = max(32, 8 * int(k))
    return max(int(k), min(int(entry), total))


def _resolve_probe_block(requested: int, n_probes: int, cap: int,
                         k: int) -> int:
    """probe_block with the rabitq tuned table consulted first (the
    packed-code gather moves 32× fewer bytes per probe, so the speed
    optimum differs from the flat families'), falling back to the shared
    :func:`~._packing.resolve_probe_block` table/heuristic."""
    from ._packing import resolve_probe_block

    if requested:
        return resolve_probe_block(requested, n_probes, cap, "ivf_rabitq")
    entry = _tune_entry(k, n_probes, cap).get("probe_block")
    if entry is not None:
        return max(1, min(int(entry), max(1, n_probes)))
    return resolve_probe_block(0, n_probes, cap, "ivf_rabitq")


def _fused_scan_fallback(requested: str) -> str:
    """No fused Mosaic estimator kernel exists yet; an explicit
    ``scan_kernel="fused"`` request dispatches the XLA scan.  That
    fallback is COUNTED like every other gate decision —
    ``raft_pallas_gate_fallback_total{kernel="rabitq_scan"}`` — instead
    of silently rewriting the knob, so fleet dashboards see requested-
    but-unserved fused scans."""
    if requested != "fused":
        return requested
    from ..ops.pallas.gate import _count_fallback

    _count_fallback(
        "rabitq_scan",
        "fused estimator scan not implemented; dispatching xla")
    return "xla"


def _resolved_static(index, k: int, p) -> Tuple[int, int, int, str]:
    """The shared search/searcher static-knob resolution: (n_probes,
    probe_block, rerank_k, scan_kernel).  Also serves the out-of-core
    tier (:mod:`~raft_tpu.neighbors.ooc`), whose device half is this
    family's estimator scan — ``index`` only needs ``n_lists`` and
    ``list_cap``."""
    n_probes = int(min(p.n_probes, index.n_lists))
    probe_block = _resolve_probe_block(p.probe_block, n_probes,
                                       index.list_cap, int(k))
    rerank_k = resolve_rerank_k(p.rerank_k, int(k), n_probes,
                                index.list_cap)
    scan_kernel = _fused_scan_fallback(_scan.resolve_scan_kernel(
        p.scan_kernel, "ivf_rabitq", probe_block * index.list_cap, int(k)))
    return n_probes, probe_block, rerank_k, scan_kernel


@tracing.annotate("ivf_rabitq.search")
def search(index: IvfRabitqIndex, queries, k: int,
           params: Optional[IvfRabitqSearchParams] = None, *, filter=None,
           res=None) -> Tuple[jax.Array, jax.Array]:
    """Approximate kNN with EXACT returned values: the estimator gates
    the candidate set (recall rides ``n_probes`` × ``rerank_k``), the
    survivors re-score against the raw rows.  ``filter``: optional
    prefilter by source id, the shared bitset/bitmap contract."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           chunked_filtered_queries, sentinel_filtered_ids)

    p = params or IvfRabitqSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    n_probes, probe_block, rerank_k, scan_kernel = _resolved_static(
        index, k, p)
    keep = as_keep_mask(filter, nq=q.shape[0])  # indexes source ids
    if keep is not None:
        check_filter_covers_ids(keep, index.ids)

    impl = lambda qc, kc: _search_impl(
        index.centroids, index.rotation, index.codes, index.sabs,
        index.res_norms, index.code_cdots, index.data, index.ids,
        index.counts, qc, int(k), n_probes, rerank_k, index.metric, kc,
        probe_block, scan_kernel)
    dv, di = chunked_filtered_queries(impl, q, int(p.query_chunk), keep)
    if keep is not None:  # sub-k survivors: sentinel tail, not real ids
        di = sentinel_filtered_ids(dv, di)
    return dv, di


def searcher(index: IvfRabitqIndex, k: int,
             params: Optional[IvfRabitqSearchParams] = None, *,
             filter=None):
    """Uniform serving entry point (``raft_tpu.serve`` contract):
    ``(fn, operands)`` with ``fn(queries, *operands)`` equal to
    :func:`search` for batches up to ``params.query_chunk`` rows.  The
    slabs ride as operands so bucket executables share them; an optional
    shared bitset filter rides as one more operand (tombstone deletes
    swap the mask without recompiling)."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           sentinel_filtered_ids)

    p = params or IvfRabitqSearchParams()
    expects(k >= 1, "k must be >= 1")
    n_probes, probe_block, rerank_k, scan_kernel = _resolved_static(
        index, k, p)
    metric = index.metric
    keep = as_keep_mask(filter)
    if keep is not None:
        expects(keep.ndim == 1,
                "serving filters are shared bitsets (1-D); per-query "
                "bitmaps can't ride a fixed operand across buckets")
        check_filter_covers_ids(keep, index.ids)

        def fn(q, centroids, rotation, codes, sabs, res_norms, code_cdots,
               data, ids, counts, kp):
            dv, di = _search_impl(centroids, rotation, codes, sabs,
                                  res_norms, code_cdots, data, ids, counts,
                                  q, int(k), n_probes, rerank_k, metric,
                                  kp, probe_block, scan_kernel)
            return dv, sentinel_filtered_ids(dv, di)

        return fn, (index.centroids, index.rotation, index.codes,
                    index.sabs, index.res_norms, index.code_cdots,
                    index.data, index.ids, index.counts, keep)

    def fn(q, centroids, rotation, codes, sabs, res_norms, code_cdots,
           data, ids, counts):
        return _search_impl(centroids, rotation, codes, sabs, res_norms,
                            code_cdots, data, ids, counts, q, int(k),
                            n_probes, rerank_k, metric, None, probe_block,
                            scan_kernel)

    return fn, (index.centroids, index.rotation, index.codes, index.sabs,
                index.res_norms, index.code_cdots, index.data, index.ids,
                index.counts)


@dataclasses.dataclass(frozen=True)
class IvfRabitqFleetSlices:
    """Device-mesh layout of an IVF-RaBitQ index for the serving fleet
    (:mod:`raft_tpu.serve.fleet`): list axis padded to a multiple of the
    mesh axis and split contiguously (shard *s* owns global lists
    ``[s*lists_per, (s+1)*lists_per)``); the padded centroid table and
    the rotation are replicated so every shard quantizes the query and
    ranks probes identically to the single-device searcher."""

    centroids: jax.Array    # [S*lists_per, d] replicated; pads finite-far
    rotation: jax.Array     # [d, d] replicated
    codes: jax.Array        # [S*lists_per, cap, d/8] sharded P(axis)
    sabs: jax.Array         # [S*lists_per, cap] sharded; pads 0
    res_norms: jax.Array    # [S*lists_per, cap] sharded; pads 0
    code_cdots: jax.Array   # [S*lists_per, cap] sharded; pads 0
    data: jax.Array         # [S*lists_per, cap, d] sharded; pads 0
    ids: jax.Array          # [S*lists_per, cap] sharded; pads -1
    counts: jax.Array       # [S*lists_per] sharded; pads 0
    lists_per: int
    n_lists: int


def fleet_slices(index: IvfRabitqIndex, mesh, *,
                 axis: str = "shard") -> IvfRabitqFleetSlices:
    """Slice an :class:`IvfRabitqIndex` over ``mesh[axis]`` for the
    fleet fan-out.  Padding is host-side numpy and every slab is
    ``device_put`` with its target sharding (single-device peak = one
    shard's slice).  The centroid pad is the same far-but-finite
    sentinel as :func:`ivf_flat.fleet_slices` — +inf turns into NaN
    through ``sq_l2``'s dot expansion."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .ivf_flat import _FLEET_CENTROID_PAD

    expects(axis in mesh.axis_names, f"axis {axis!r} not in mesh")
    expects(jnp.issubdtype(jnp.asarray(index.centroids).dtype,
                           jnp.floating),
            "fleet slicing needs a float centroid table")
    n_dev = int(mesh.shape[axis])
    L = index.n_lists
    lp = (L + n_dev - 1) // n_dev
    pad = lp * n_dev - L

    def _pad0(x, fill):
        x = np.asarray(x)
        if not pad:
            return x
        shape = (pad,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, x.dtype)], axis=0)

    rep = NamedSharding(mesh, P())
    sh = NamedSharding(mesh, P(axis))
    return IvfRabitqFleetSlices(
        centroids=jax.device_put(
            jnp.asarray(_pad0(index.centroids, _FLEET_CENTROID_PAD)), rep),
        rotation=jax.device_put(jnp.asarray(np.asarray(index.rotation)),
                                rep),
        codes=jax.device_put(jnp.asarray(_pad0(index.codes, 0)), sh),
        sabs=jax.device_put(jnp.asarray(_pad0(index.sabs, 0)), sh),
        res_norms=jax.device_put(jnp.asarray(_pad0(index.res_norms, 0)), sh),
        code_cdots=jax.device_put(jnp.asarray(_pad0(index.code_cdots, 0)),
                                  sh),
        data=jax.device_put(jnp.asarray(_pad0(index.data, 0)), sh),
        ids=jax.device_put(jnp.asarray(_pad0(index.ids, -1)), sh),
        counts=jax.device_put(jnp.asarray(_pad0(index.counts, 0)), sh),
        lists_per=int(lp), n_lists=int(L))
