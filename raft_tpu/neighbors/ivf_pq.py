"""IVF-PQ — inverted-file index with product-quantized residuals.

No in-tree CUDA ancestor (cuVS migration); designed from the north-star
configs (``BASELINE.json``: ivf_pq on DEEP-10M) and standard IVF-PQ
(Jégou et al.) restructured for the TPU:

* **Residual PQ**: each vector stores ``pq_dim`` sub-codes indexing
  per-subspace codebooks trained on coarse residuals (x − centroid).
* **Two search tiers** (two points on the memory/bandwidth curve):

  - ``mode="recon"`` (default): at build time the codes are decoded once
    into a bf16 *reconstruction slab* ``[n_lists, cap, d]`` (x̂ = c + r̂,
    with exact f32 ‖x̂‖² kept separately).  Search gathers each probed
    list's slab and scores it with one batched MXU dot —
    ``‖q−x̂‖² = ‖q‖² − 2⟨q,x̂⟩ + ‖x̂‖²`` — so the hot loop is a dense
    bf16 contraction, the shape TPUs are built for.  The slab is
    *derived* state: it is rebuilt from the codes on load and never
    serialized, so the persisted index stays PQ-compressed.
  - ``mode="lut"``: classic ADC from the uint8 codes via lookup tables,
    with the table algebra split so NOTHING query×probe-dependent is
    recomputed inside the probe loop:
    ``⟨q−c, r̂⟩ = ⟨q, r̂⟩ − ⟨c, r̂⟩`` — the probe-invariant query LUT
    ``⟨q, codebooks⟩`` is one einsum per query chunk *outside* the scan,
    and the query-invariant centroid cross term is precomputed at build
    time (``centroid_lut`` ``[L, m, c]`` f32, ~8 MB at typical shapes)
    and folded per slot into ``adc_norms = ‖r̂‖² + 2⟨c, r̂⟩`` (the
    FAISS precomputed-tables identity).  The per-probe work is then just
    a code gather + table lookup.  4× less HBM gather traffic per
    candidate than recon at pq_dim = d/2·…, but the table gather is
    VPU-bound on TPU; use it when HBM capacity, not speed, binds (the
    slab is 2·d bytes/vector vs pq_dim bytes/vector).

* **Probe blocking**: both tiers scan ``probe_block`` probes per step —
  one ``[nq, B·cap]`` slab gather, one fused distance block, ONE top-k
  merge per block (unsorted carries, a single ranked selection after the
  scan).  Results are bit-identical for every block size; B defaults from
  the measured ``_probe_block_table`` (``bench/tune_probe_block.py``).

* Lists reuse the IVF-Flat padded-slab layout (device-packed via
  :mod:`._packing`); optional exact re-ranking lives in
  :mod:`raft_tpu.neighbors.refine`.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cluster.kmeans import KMeansParams, capped_assign, kmeans_balanced_fit
from ..core import tracing
from ..core.array import wrap_array
from ..core.compat import shard_map
from ..core.errors import expects
from ..distance.pairwise import sq_l2
from ._packing import chunked_filtered_queries, pack_lists

__all__ = [
    "IvfPqIndexParams",
    "IvfPqSearchParams",
    "IvfPqIndex",
    "build",
    "build_chunked",
    "extend",
    "search",
    "searcher",
    "build_sharded",
    "build_chunked_sharded",
    "search_sharded",
]


@dataclasses.dataclass(frozen=True)
class IvfPqIndexParams:
    n_lists: int = 1024
    pq_dim: int = 0          # number of sub-quantizers; 0 → dim // 4
    pq_bits: int = 8         # codebook size = 2^pq_bits (4..8)
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.1
    pq_kmeans_n_iters: int = 15
    # capacity = ratio · n/n_lists; capped_assign spills overflow to the
    # next-nearest list, so 1.25–1.5 loses nothing and pads far less than
    # the r1 default of 2.0 (padding = wasted gather bandwidth at search)
    list_cap_ratio: float = 1.5
    store_recon: bool = True  # build the bf16 reconstruction slab
    # 4-bit packing of the stored codes (requires pq_bits <= 4): halves
    # code HBM/disk; the LUT tier unpacks per probed list post-gather
    pack_codes: bool = False
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class IvfPqSearchParams:
    n_probes: int = 32
    mode: str = "auto"       # auto | recon | lut
    query_chunk: int = 4096  # cap on [chunk, cap, d] gather working set
    # probes gathered+scored+merged per scan step; 0 = auto (measured
    # table via bench/tune_probe_block.py, else a working-set heuristic).
    # Bit-identical results at every value — a pure speed knob.
    probe_block: int = 0
    # recon-tier scan kernel: "auto" | "xla" | "fused" — same contract as
    # IvfFlatSearchParams.scan_kernel.  The LUT tier has no distance
    # einsum to fuse and always runs the XLA scan.
    scan_kernel: str = "auto"


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IvfPqIndex:
    centroids: jax.Array     # [L, d] coarse
    codebooks: jax.Array     # [M, C, ds] per-subspace
    codes: jax.Array         # [L, cap, M] uint8
    code_norms: jax.Array    # [L, cap] f32 ‖r̂‖² of decoded residuals
    ids: jax.Array           # [L, cap] int32, -1 pad
    counts: jax.Array        # [L]
    metric: str = dataclasses.field(metadata=dict(static=True))
    # Derived tier (never serialized; rebuilt from codes via with_recon()):
    recon: Optional[jax.Array] = None        # [L, cap, d] bf16 x̂ slab
    recon_norms: Optional[jax.Array] = None  # [L, cap] f32 ‖x̂‖², +inf pads
    # 4-bit packed storage (pq_bits ≤ 4): codes hold TWO sub-codes per
    # byte, [L, cap, ceil(m/2)] — half the HBM/disk of byte codes
    packed: bool = dataclasses.field(default=False,
                                     metadata=dict(static=True))
    # Hoisted-ADC tier (derived like recon — never serialized, rebuilt on
    # load via with_adc_luts(), so old artifacts round-trip unchanged):
    # ⟨c_list, codebooks⟩ per subspace entry, and the per-slot adjusted
    # norm ‖r̂‖² + 2⟨c_list, r̂⟩ that absorbs the centroid cross term of
    # ⟨q−c, r̂⟩ = ⟨q, r̂⟩ − ⟨c, r̂⟩ (FAISS precomputed-tables identity)
    centroid_lut: Optional[jax.Array] = None  # [L, m, c] f32
    adc_norms: Optional[jax.Array] = None     # [L, cap] f32

    # save_index skips these; load_index restores them via with_recon()
    # and with_adc_luts()
    _derived_fields = ("recon", "recon_norms", "centroid_lut", "adc_norms")

    @property
    def n_lists(self) -> int:
        return int(self.codes.shape[0])

    @property
    def list_cap(self) -> int:
        return int(self.codes.shape[1])

    @property
    def pq_dim(self) -> int:
        # codebooks carry the logical m; codes.shape[2] is ceil(m/2) when
        # the 4-bit packing is active
        return int(self.codebooks.shape[0])

    @property
    def dim(self) -> int:
        return int(self.centroids.shape[1])

    @property
    def size(self) -> int:
        return int(jnp.sum(self.counts))  # jaxlint: disable=JX01 size is a host-facing API scalar, not on the search path

    def with_recon(self) -> "IvfPqIndex":
        """Return a copy with the derived reconstruction slab materialized
        (idempotent).  Used after :func:`load_index`, which persists only
        the PQ-compressed state."""
        if self.recon is not None:
            return self
        recon, recon_norms = _decode_slab(
            self.codes, self.centroids, self.codebooks, self.ids)
        return dataclasses.replace(self, recon=recon, recon_norms=recon_norms)

    def without_recon(self) -> "IvfPqIndex":
        """Drop the derived slab (memory tier / pre-serialization)."""
        if self.recon is None:
            return self
        return dataclasses.replace(self, recon=None, recon_norms=None)

    def with_adc_luts(self) -> "IvfPqIndex":
        """Return a copy with the hoisted-ADC tables materialized
        (idempotent): ``centroid_lut`` [L, m, c] and ``adc_norms``
        [L, cap].  Derived state like the recon slab — rebuilt after
        :func:`load_index`, never serialized.  ``search(mode="lut")``
        computes them on the fly when absent; materializing once here
        amortizes that across calls.  Valid for packed and unpacked
        codes alike (``adc_norms`` depends on code *values*, which
        packing preserves)."""
        if self.centroid_lut is not None and self.adc_norms is not None:
            return self
        clut, anorms = _adc_tables(self.codes, self.centroids,
                                   self.codebooks, self.code_norms)
        return dataclasses.replace(self, centroid_lut=clut,
                                   adc_norms=anorms)

    def with_packed_codes(self) -> "IvfPqIndex":
        """4-bit packing: two sub-codes per byte (requires ``pq_bits ≤ 4``
        at build).  Halves code HBM/disk; the LUT tier unpacks per probed
        list after the gather (so gather traffic is halved too).
        ``extend`` requires unpacked codes — round-trip via
        :meth:`with_unpacked_codes`."""
        if self.packed:
            return self
        # static precondition: codebook size 2^pq_bits bounds every code
        expects(self.codebooks.shape[1] <= 16,
                "with_packed_codes needs 4-bit codes (build with pq_bits<=4)")
        return dataclasses.replace(self, codes=_pack_codes4(self.codes),
                                   packed=True)

    def with_unpacked_codes(self) -> "IvfPqIndex":
        if not self.packed:
            return self
        return dataclasses.replace(
            self, codes=_unpack_codes4(self.codes, self.pq_dim),
            packed=False)


def _split_subspaces(x, m: int):
    """[n, d] → [n, m, d/m] (d padded to a multiple of m at build)."""
    n, d = x.shape
    return x.reshape(n, m, d // m)


@partial(jax.jit, static_argnames=("m", "c", "iters"))
def _train_codebooks(residuals, key, m: int, c: int, iters: int):
    """Per-subspace kmeans over residual slices — batched via vmap so all
    subspaces train simultaneously (one big MXU workload, not M small ones)."""
    sub = _split_subspaces(residuals, m)  # [n, m, ds]
    sub_t = jnp.moveaxis(sub, 1, 0)       # [m, n, ds]

    def fit_one(xs, k):
        c0, _, _, _ = _plain_kmeans(xs, k, c, iters)
        return c0

    keys = jax.random.split(key, m)
    return jax.vmap(fit_one)(sub_t, keys)  # [m, c, ds]


def _plain_kmeans(xs, key, k: int, iters: int):
    """Minimal Lloyd loop for codebook training (dedicated to keep
    _train_codebooks vmap-friendly; cluster.kmeans drives the coarse level)."""
    n = xs.shape[0]
    # small trainsets (< codebook size) seed with replacement: duplicate
    # seeds merge over Lloyd iterations, matching the reference's behavior
    # of tolerating n_train < 2^pq_bits
    idx = jax.random.choice(key, n, (k,), replace=n < k)
    c0 = xs[idx]

    def body(c, _):
        d2 = sq_l2(xs, c)
        labels = jnp.argmin(d2, axis=1)
        one = jax.nn.one_hot(labels, k, dtype=xs.dtype)  # [n, k]
        sums = one.T @ xs
        counts = jnp.sum(one, axis=0)
        newc = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), c)
        return newc, None

    c_fit, _ = jax.lax.scan(body, c0, None, length=iters)
    return c_fit, None, None, None


@partial(jax.jit, static_argnames=("m",))
def _encode(residuals, codebooks, m: int):
    """codes[n, m] = argmin_c ‖res_m − cb[m, c]‖² and decoded-residual norms."""
    sub = jnp.moveaxis(_split_subspaces(residuals, m), 1, 0)  # [m, n, ds]

    def enc_one(xs, cb):
        d2 = sq_l2(xs, cb)  # [n, c]
        code = jnp.argmin(d2, axis=1).astype(jnp.uint8)
        deco = cb[code.astype(jnp.int32)]  # [n, ds]
        return code, jnp.sum(deco.astype(jnp.float32) ** 2, axis=1)

    codes, norms = jax.vmap(enc_one)(sub, codebooks)  # [m, n], [m, n]
    return codes.T, jnp.sum(norms, axis=0)  # [n, m], [n]


@jax.jit
def _decode_slab(codes, centroids, codebooks, ids):
    """Decode packed codes → bf16 reconstruction slab + exact f32 ‖x̂‖².

    Chunked over lists (lax.map) so the f32 intermediate never exceeds a
    ~256-list block; pad entries (id < 0) get ‖x̂‖² = +inf so the L2
    search path masks them for free.
    """
    L, cap, mc = codes.shape
    m = codebooks.shape[0]  # logical sub-code count (mc = ceil(m/2) packed)
    d = centroids.shape[1]
    block = max(1, min(L, max(1, (1 << 24) // max(cap * d, 1))))
    pad = (-L) % block
    codes_p = jnp.pad(codes, ((0, pad), (0, 0), (0, 0)))
    cent_p = jnp.pad(centroids, ((0, pad), (0, 0)))
    ids_p = jnp.pad(ids, ((0, pad), (0, 0)), constant_values=-1)
    sub = jnp.arange(m)

    def decode_block(args):
        cb_codes, cb_cent, cb_ids = args
        if mc != m:  # 4-bit packed: unpack one block at a time
            cb_codes = _unpack_codes4(cb_codes, m)
        g = codebooks[sub[None, None, :], cb_codes.astype(jnp.int32)]
        rec = (g.reshape(cb_codes.shape[0], cap, d).astype(jnp.float32)
               + cb_cent[:, None, :].astype(jnp.float32))
        rec_b = rec.astype(jnp.bfloat16)
        # norms of the *rounded* slab: the search dot sees bf16 x̂, so a
        # consistent ‖x̂‖² makes the score the exact distance to the stored
        # point (an inconsistent f32 norm injects rank noise ~2ε‖q‖‖x̂‖)
        rec_f = rec_b.astype(jnp.float32)
        norms = jnp.sum(rec_f * rec_f, axis=2)
        norms = jnp.where(cb_ids >= 0, norms, jnp.inf)
        return rec_b, norms

    rec, norms = jax.lax.map(
        decode_block,
        (codes_p.reshape(-1, block, cap, mc),
         cent_p.reshape(-1, block, d),
         ids_p.reshape(-1, block, cap)),
    )
    return (rec.reshape(-1, cap, d)[:L], norms.reshape(-1, cap)[:L])


@jax.jit
def _adc_tables(codes, centroids, codebooks, code_norms):
    """Build the hoisted-ADC tables: ``centroid_lut[l, m, c] =
    ⟨centroid_l restricted to subspace m, codebook entry c⟩`` and the
    per-slot adjusted norms ``adc_norms[l, j] = ‖r̂_{l,j}‖² +
    2·Σ_m centroid_lut[l, m, codes[l, j, m]]``.

    With these, LUT-mode ADC needs only the probe-invariant query LUT:
    ``‖q−c−r̂‖² = ‖q−c‖² − 2⟨q, r̂⟩ + adc_norms`` — no per-probe einsum.
    Chunked over list blocks (lax.map) so the [block, m, cap] gather
    intermediate stays bounded, mirroring :func:`_decode_slab`.
    """
    L, cap, mc = codes.shape
    m, c, ds = codebooks.shape
    clut = jnp.einsum(
        "lms,mcs->lmc",
        centroids.astype(jnp.float32).reshape(L, m, ds),
        codebooks.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    block = max(1, min(L, max(1, (1 << 24) // max(cap * m, 1))))
    pad = (-L) % block
    codes_p = jnp.pad(codes, ((0, pad), (0, 0), (0, 0)))
    clut_p = jnp.pad(clut, ((0, pad), (0, 0), (0, 0)))
    norms_p = jnp.pad(code_norms, ((0, pad), (0, 0)))

    def cross_block(args):
        cb_codes, cb_clut, cb_norms = args
        if mc != m:  # 4-bit packed storage: unpack one block at a time
            cb_codes = _unpack_codes4(cb_codes, m)
        g = jnp.take_along_axis(
            cb_clut, cb_codes.astype(jnp.int32).transpose(0, 2, 1), axis=2)
        return cb_norms + 2.0 * jnp.sum(g, axis=1)

    anorms = jax.lax.map(
        cross_block,
        (codes_p.reshape(-1, block, cap, mc),
         clut_p.reshape(-1, block, m, c),
         norms_p.reshape(-1, block, cap)),
    )
    return clut, anorms.reshape(-1, cap)[:L]


# 4-bit code packing moved to the quantized-scan sub-API (shared with the
# 1-bit RaBitQ codes); these aliases keep the historical private names
from ..ops.blocked_scan import (  # noqa: E402
    pack_codes4 as _pack_codes4,
    unpack_codes4 as _unpack_codes4,
)


@tracing.annotate("ivf_pq.build")
def build(dataset, params: Optional[IvfPqIndexParams] = None, *,
          source_ids=None, res=None) -> IvfPqIndex:
    p = params or IvfPqIndexParams()
    x = wrap_array(dataset, ndim=2, name="dataset")
    n, d = x.shape
    m = p.pq_dim or max(1, d // 4)
    expects(d % m == 0, f"dim {d} must divide by pq_dim {m}")
    expects(4 <= p.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expects(not p.pack_codes or p.pq_bits <= 4,
            "pack_codes requires pq_bits <= 4")
    c = 1 << p.pq_bits
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))

    # coarse quantizer (shared shape with IVF-Flat build)
    n_train = min(n, max(p.n_lists * 4, int(n * p.kmeans_trainset_fraction)))
    key = jax.random.PRNGKey(p.seed)
    sel = (jax.random.permutation(key, n)[:n_train] if n_train < n
           else jnp.arange(n))
    kp = KMeansParams(n_clusters=p.n_lists, max_iter=p.kmeans_n_iters, seed=p.seed)
    centroids, _, _ = kmeans_balanced_fit(x[sel], kp)
    labels, _ = capped_assign(x, centroids, cap)

    # PQ codebooks on training residuals
    res_train = x[sel] - centroids[jnp.argmin(sq_l2(x[sel], centroids), axis=1)]
    codebooks = _train_codebooks(res_train, jax.random.fold_in(key, 7), m, c,
                                 p.pq_kmeans_n_iters)

    # encode the full dataset against its assigned centroid
    residuals = x - centroids[jnp.clip(labels, 0, p.n_lists - 1)]
    codes, cnorms = _encode(residuals, codebooks, m)

    # pack lists on device (jitted sort+scatter)
    ids = (jnp.asarray(source_ids, jnp.int32) if source_ids is not None
           else jnp.arange(n, dtype=jnp.int32))
    (pk_codes, pk_norms, pk_ids), counts = pack_lists(
        labels, (codes, cnorms, ids),
        n_lists=p.n_lists, cap=cap, fills=(0, 0.0, -1))

    index = IvfPqIndex(centroids, codebooks, pk_codes, pk_norms, pk_ids,
                       counts, p.metric)
    index = index.with_adc_luts()  # hoisted-ADC tables, while codes are unpacked
    index = index.with_recon() if p.store_recon else index
    return index.with_packed_codes() if p.pack_codes else index


def extend(index: IvfPqIndex, new_vectors, new_ids=None, *,
           insert_chunk: int = 0) -> IvfPqIndex:
    """Online streaming insert (cuVS ``extend`` parity), rebuilt around
    the chunked builder's fused slab-donating step.

    The insert batch is host-padded to a fixed ``insert_chunk`` row bucket
    (0 = :data:`~._packing.DEFAULT_INSERT_CHUNK`; pad rows carry id −1 and
    are masked out of assignment and capacity) and streamed through
    :func:`_pq_chunk_step` (capped assign → residual → PQ encode →
    scatter-append, one dispatch per chunk): ONE jitted executable serves
    every insert size, counts never leave the device between the stages,
    and the only host↔device crossings are the explicit per-chunk
    ``device_put`` and one scalar spill check — the steady-state insert
    path is zero-retrace / zero-implicit-transfer under
    :class:`~raft_tpu.core.TraceGuard`.

    Copy-on-write: the first chunk step is the non-donating
    :func:`_pq_chunk_step_cow` (the source slabs may back a live serving
    snapshot mid-dispatch), later chunks donate the fresh private buffers;
    the source ``index`` stays fully usable.  Derived tiers (hoisted-ADC
    tables, recon slab) are re-derived through their fixed-shape jitted
    rebuilds when the source index carried them.

    Capacity overflow grows the slab (host-sized static shape) with
    geometric headroom and re-runs the stream from the untouched source
    slabs; with capacity to spare, capped assignment degenerates to
    nearest-centroid, so extending is bit-identical (values AND ids) to a
    from-scratch pack at the same centroids/codebooks
    (tests/test_mutation.py pins this)."""
    from ._packing import (DEFAULT_INSERT_CHUNK, host_rows,
                           staged_insert_chunks)

    expects(not index.packed,
            "extend needs unpacked codes: index.with_unpacked_codes() "
            "first, then re-pack with with_packed_codes()")
    m = index.pq_dim
    L, cap = index.n_lists, index.list_cap
    x = host_rows(new_vectors)
    expects(x.ndim == 2 and x.shape[1] == index.dim, "vector dim mismatch")
    n_new = x.shape[0]
    expects(n_new >= 1, "no rows to insert")
    base = int(jax.device_get(jnp.sum(index.counts)))  # jaxlint: disable=JX01 one scalar sync per extend call: sizes auto-assigned ids and the spill check baseline
    ids = (np.asarray(host_rows(new_ids), np.int32) if new_ids is not None
           else np.arange(base, base + n_new, dtype=np.int32))
    expects(ids.shape == (n_new,), "new_ids must be one id per row")
    expects(int(ids.min()) >= 0, "source ids must be >= 0 (−1 is the pad)")
    chunk = int(insert_chunk) or DEFAULT_INSERT_CHUNK
    dtype = index.centroids.dtype

    def stream(slabs, counts, slab_cap):
        step = _pq_chunk_step_cow  # inputs may back a live snapshot
        for xc, idc in staged_insert_chunks(x, ids, chunk, dtype):
            slabs, counts = step(slabs, counts, index.centroids,
                                 index.codebooks, xc, idc,
                                 n_lists=L, cap=slab_cap, m=m)
            step = _pq_chunk_step  # fresh private buffers: donate
        return slabs, counts

    (codes, cnorms, slab_ids), counts = stream(
        (index.codes, index.code_norms, index.ids), index.counts, cap)
    placed = int(jax.device_get(jnp.sum(counts))) - base  # jaxlint: disable=JX01 explicit spill check: one scalar per extend gates the rare slab-growth path
    if placed < n_new:  # capacity exhausted — grow + re-run (rare)
        xd = jnp.asarray(x.astype(dtype, copy=False))
        labels = jnp.argmin(sq_l2(xd, index.centroids), axis=1)
        added = jax.ops.segment_sum(jnp.ones_like(labels, jnp.int32),
                                    labels, num_segments=L)
        need = int(jnp.max(index.counts + added))  # jaxlint: disable=JX01 slab capacity must be a host int at extend time (static shapes)
        new_cap = max(need, cap + (cap + 1) // 2)  # geometric headroom
        pad = new_cap - cap
        grown = (jnp.pad(index.codes, ((0, 0), (0, pad), (0, 0))),
                 jnp.pad(index.code_norms, ((0, 0), (0, pad))),
                 jnp.pad(index.ids, ((0, 0), (0, pad)), constant_values=-1))
        (codes, cnorms, slab_ids), counts = stream(grown, index.counts,
                                                   new_cap)
    out = IvfPqIndex(index.centroids, index.codebooks, codes, cnorms,
                     slab_ids, counts, index.metric)
    if index.adc_norms is not None:  # fixed-shape jitted rebuild
        out = out.with_adc_luts()
    return out.with_recon() if index.recon is not None else out


def _pq_train_chunked(dataset, p: IvfPqIndexParams, n: int, m: int, c: int):
    """Coarse quantizer + PQ codebooks from one host-sampled trainset —
    the training phase shared by the pipelined and per-op chunk engines."""
    from .ivf_flat import _train_subsample

    n_train = min(n, max(p.n_lists * 4, int(n * p.kmeans_trainset_fraction)))
    sel = _train_subsample(n, n_train, p.seed)
    xt = jnp.asarray(np.asarray(dataset[sel]))
    kp = KMeansParams(n_clusters=p.n_lists, max_iter=p.kmeans_n_iters,
                      seed=p.seed)
    centroids, _, _ = kmeans_balanced_fit(xt, kp)
    res_train = xt - centroids[jnp.argmin(sq_l2(xt, centroids), axis=1)]
    key = jax.random.PRNGKey(p.seed)
    codebooks = _train_codebooks(res_train, jax.random.fold_in(key, 7), m, c,
                                 p.pq_kmeans_n_iters)
    return centroids, codebooks


def _pq_step_impl(slabs, counts, centroids, codebooks, xc, idc, *,
                  n_lists: int, cap: int, m: int):
    """ONE fused program per chunk: masked capped assign → residual → PQ
    encode → scatter-append, fused so the whole chunk is a single dispatch
    with no host round-trip for ``counts``.  Pad rows (``idc < 0``) never
    request a list, never consume capacity, and scatter-drop via label −1
    — the padded fixed-shape stream is bit-identical to the unpadded
    per-op loop.

    Two jitted forms: :func:`_pq_chunk_step` donates the slabs (build
    loops own their buffers); :func:`_pq_chunk_step_cow` leaves the inputs
    alive — the copy-on-write first step of the online :func:`extend`,
    whose input slabs belong to the LIVE index a serving snapshot may
    still be dispatching against."""
    from ..cluster.kmeans import _capped_assign_impl
    from ._packing import _scatter_append_impl

    valid = idc >= 0
    labels, _ = _capped_assign_impl(xc, centroids, cap - counts, valid)
    residuals = xc - centroids[jnp.clip(labels, 0, n_lists - 1)]
    ch_codes, ch_norms = _encode(residuals, codebooks, m)
    return _scatter_append_impl(slabs, counts, labels,
                                (ch_codes, ch_norms, idc),
                                n_lists=n_lists, cap=cap)


_pq_chunk_step = partial(jax.jit, static_argnames=("n_lists", "cap", "m"),
                         donate_argnums=(0, 1))(_pq_step_impl)
_pq_chunk_step_cow = partial(jax.jit,
                             static_argnames=("n_lists", "cap", "m"))(
    _pq_step_impl)


def _pq_stream_pipelined(dataset, centroids, codebooks,
                         p: IvfPqIndexParams, n: int, m: int, cap: int,
                         chunk_rows: int, source_ids, heartbeat=None):
    """Pipelined chunk engine: fixed-shape double-buffered device staging
    (:func:`~._packing.prefetch_chunks_padded`) feeding the fused donated
    :func:`_pq_chunk_step` — one executable, one dispatch per chunk."""
    from ._packing import device_full, prefetch_chunks_padded

    codes = device_full((p.n_lists, cap, m), 0, jnp.uint8)
    cnorms = device_full((p.n_lists, cap), 0, jnp.float32)
    ids_slab = device_full((p.n_lists, cap), -1, jnp.int32)
    counts = device_full((p.n_lists,), 0, jnp.int32)
    for lo, hi, xc, idc in prefetch_chunks_padded(dataset, chunk_rows,
                                                  source_ids):
        (codes, cnorms, ids_slab), counts = _pq_chunk_step(
            (codes, cnorms, ids_slab), counts, centroids, codebooks, xc,
            idc, n_lists=p.n_lists, cap=cap, m=m)
        if heartbeat is not None:
            heartbeat(hi)
    return codes, cnorms, ids_slab, counts


def _pq_stream_perop(dataset, centroids, codebooks, p: IvfPqIndexParams,
                     n: int, m: int, cap: int, chunk_rows: int, source_ids):
    """Reference per-op chunk loop (the pre-pipelining engine): blocking
    H2D ``jnp.asarray``, separate assign / residual / encode / scatter
    dispatches, tail chunk at its own shape.  Kept verbatim as the
    bit-parity oracle for the fused engine and the A/B baseline of
    ``bench/build_throughput.py``."""
    from ..cluster.kmeans import capped_assign_room
    from ._packing import prefetch_chunks, scatter_append

    codes = jnp.zeros((p.n_lists, cap, m), jnp.uint8)
    cnorms = jnp.zeros((p.n_lists, cap), jnp.float32)
    ids_slab = jnp.full((p.n_lists, cap), -1, jnp.int32)
    counts = jnp.zeros((p.n_lists,), jnp.int32)
    for lo, hi, xc_h, idc_h in prefetch_chunks(dataset, chunk_rows,
                                               source_ids):
        xc = jnp.asarray(xc_h)
        idc = jnp.asarray(idc_h, jnp.int32)
        labels, _ = capped_assign_room(xc, centroids, cap - counts)
        residuals = xc - centroids[jnp.clip(labels, 0, p.n_lists - 1)]
        ch_codes, ch_norms = _encode(residuals, codebooks, m)
        (codes, cnorms, ids_slab), counts = scatter_append(
            (codes, cnorms, ids_slab), counts, labels,
            (ch_codes, ch_norms, idc), n_lists=p.n_lists, cap=cap)
    return codes, cnorms, ids_slab, counts


def build_chunked(dataset, params: Optional[IvfPqIndexParams] = None, *,
                  chunk_rows: int = 0, source_ids=None,
                  res=None) -> IvfPqIndex:
    """Out-of-core build: the dataset stays on host (numpy-indexable —
    ``np.ndarray``/``np.memmap``) and streams through the device in chunks.

    Device peak = PQ slabs (``n·cap_ratio·pq_dim`` **bytes**, ~16× smaller
    than the f32 dataset at the defaults) + two staged chunks + one
    (chunk, L) distance block — a dataset larger than one chip's HBM is
    buildable as long as its *codes* fit (VERDICT r2 missing #2).
    Defaults to ``store_recon=False`` semantics during the stream; call
    ``index.with_recon()`` afterwards if the bf16 slab tier fits.

    The chunk engine is pipelined: each chunk is ONE jitted,
    slab-donating program (:func:`_pq_chunk_step` — capped assign against
    remaining room → residual → PQ encode → scatter-append, fused), the
    tail chunk is padded to ``chunk_rows`` with masked rows so a single
    executable serves the whole stream (zero steady-state recompiles,
    assertable under :class:`~raft_tpu.core.TraceGuard`), and chunk t+1
    is staged host→device with a non-blocking ``device_put`` while chunk
    t computes (:func:`~raft_tpu.core.device_prefetch`).

    ``chunk_rows=0`` (default) = auto: the measured table written by
    ``bench/tune_chunk_rows.py``, else 65536
    (:func:`~._packing.resolve_chunk_rows`) — a pure throughput knob, the
    built index is identical for every value.
    """
    from ._packing import build_heartbeat, resolve_chunk_rows

    p = params or IvfPqIndexParams()
    n, d = dataset.shape
    m = p.pq_dim or max(1, d // 4)
    expects(d % m == 0, f"dim {d} must divide by pq_dim {m}")
    expects(4 <= p.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expects(not p.pack_codes or p.pq_bits <= 4,
            "pack_codes requires pq_bits <= 4")
    c = 1 << p.pq_bits
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_pq")

    centroids, codebooks = _pq_train_chunked(dataset, p, n, m, c)
    codes, cnorms, ids_slab, counts = _pq_stream_pipelined(
        dataset, centroids, codebooks, p, n, m, cap, chunk_rows, source_ids,
        heartbeat=build_heartbeat("ivf_pq.build_chunked", n))

    index = IvfPqIndex(centroids, codebooks, codes, cnorms, ids_slab,
                       counts, p.metric)
    index = index.with_adc_luts()  # hoisted-ADC tables, while codes are unpacked
    index = index.with_recon() if p.store_recon else index
    return index.with_packed_codes() if p.pack_codes else index


def _build_chunked_perop(dataset, params: Optional[IvfPqIndexParams] = None,
                         *, chunk_rows: int = 0,
                         source_ids=None) -> IvfPqIndex:
    """:func:`build_chunked` on the reference per-op chunk loop
    (:func:`_pq_stream_perop`) — the parity oracle / A/B baseline; not
    part of the public API."""
    from ._packing import resolve_chunk_rows

    p = params or IvfPqIndexParams()
    n, d = dataset.shape
    m = p.pq_dim or max(1, d // 4)
    expects(d % m == 0, f"dim {d} must divide by pq_dim {m}")
    expects(4 <= p.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expects(not p.pack_codes or p.pq_bits <= 4,
            "pack_codes requires pq_bits <= 4")
    c = 1 << p.pq_bits
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_pq")
    centroids, codebooks = _pq_train_chunked(dataset, p, n, m, c)
    codes, cnorms, ids_slab, counts = _pq_stream_perop(
        dataset, centroids, codebooks, p, n, m, cap, chunk_rows, source_ids)
    index = IvfPqIndex(centroids, codebooks, codes, cnorms, ids_slab,
                       counts, p.metric)
    index = index.with_adc_luts()
    index = index.with_recon() if p.store_recon else index
    return index.with_packed_codes() if p.pack_codes else index


# ---------------------------------------------------------------------------
# Search — recon tier (dense bf16 MXU scoring over the decoded slab).
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("k", "n_probes", "metric", "probe_block",
                                   "scan_kernel"))
def _search_recon_impl(centroids, recon, recon_norms, ids, q,
                       k: int, n_probes: int, metric: str, keep=None,
                       probe_block: int = 1, scan_kernel: str = "xla"):
    from ..ops import blocked_scan as _scan
    from ._packing import blocked_probe_plan

    nq, d = q.shape
    cap = recon.shape[1]
    qf = q.astype(jnp.float32)
    qn = _scan.row_sq_norms(qf)
    qb = q.astype(jnp.bfloat16)
    cd = sq_l2(q, centroids)                      # [nq, L]
    _, probes = jax.lax.top_k(-cd, n_probes)
    lists_xs, pvalid = blocked_probe_plan(probes, probe_block)

    def gather(inp):
        lists, pv = inp                           # [nq, B], [B]
        bcap = lists.shape[1] * cap
        slab = recon[lists]                       # one [nq, B, cap, d] gather
        vids = ids[lists].reshape(nq, bcap)
        return lists, pv, slab, vids

    def mask(dist, lists, pv, vids):
        # pad probes (n_probes % B != 0) contribute nothing
        dist = jnp.where(jnp.repeat(pv, cap)[None, :], dist, jnp.inf)
        if keep is not None:  # prefilter by source id (True = keep)
            from ._packing import keep_lookup

            dist = jnp.where(keep_lookup(keep, vids), dist, jnp.inf)
        return dist

    if scan_kernel == "fused":
        def slab_step(inp):
            lists, pv, slab, vids = gather(inp)
            bcap = vids.shape[1]
            if metric == "inner_product":
                base = jnp.where(vids >= 0, 0.0, jnp.inf)
            else:
                # recon_norms carries +inf on pad entries — they self-mask
                base = recon_norms[lists].reshape(nq, bcap)
            return (slab.reshape(nq, bcap, d), mask(base, lists, pv, vids),
                    vids, _scan.list_slab_ptr(lists, cap))

        rescore = _scan.l2_rescorer(recon, recon_norms, qb, qn, metric,
                                    exact=False, clamp=False)
        bv, bi = _scan.scan_topk_fused(qb, slab_step, (lists_xs, pvalid),
                                       rescore, nq, k)
    else:
        def score(inp):
            lists, pv, slab, vids = gather(inp)
            # B stays in slab_dots' *batch* dims so the inner [cap, d]·[d]
            # contraction — and with it the f32 accumulation order — is
            # identical for every probe_block (the bit-parity contract);
            # exact=False keeps the recon tier's single bf16 MXU pass.
            dots = _scan.slab_dots(slab, qb, exact=False).reshape(
                nq, vids.shape[1])
            if metric == "inner_product":
                dist = jnp.where(vids >= 0, -dots, jnp.inf)
            else:
                # recon_norms carries +inf on pad entries — they self-mask
                dist = qn[:, None] - 2.0 * dots + recon_norms[lists].reshape(
                    nq, dots.shape[1])
            return mask(dist, lists, pv, vids), vids

        bv, bi = _scan.scan_topk(score, (lists_xs, pvalid), nq, k)
    if metric == "euclidean":
        bv = jnp.sqrt(jnp.maximum(bv, 0.0))
    elif metric == "inner_product":
        bv = -bv
    return bv, bi


# ---------------------------------------------------------------------------
# Search — LUT/ADC tier (uint8 codes, per-query lookup tables).
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("k", "n_probes", "metric", "probe_block"))
def _search_lut_impl(centroids, codebooks, codes, adc_norms, ids, counts, q,
                     k: int, n_probes: int, metric: str, keep=None,
                     probe_block: int = 1):
    """Hoisted-ADC scan: the probe loop does NO einsum.

    ``⟨q−c, r̂⟩ = ⟨q, r̂⟩ − ⟨c, r̂⟩`` splits the classic residual LUT into
    the probe-invariant query LUT (one einsum per query chunk, below) and
    the query-invariant centroid cross term, pre-folded per slot into
    ``adc_norms = ‖r̂‖² + 2⟨c, r̂⟩`` at build time (:func:`_adc_tables`).
    Per probe block that leaves a code gather + table lookup:
    ``‖q−c−r̂‖² = ‖q−c‖² − 2·Σ_m qlut[m, code_m] + adc_norms``.
    """
    from ._packing import blocked_probe_plan

    nq, d = q.shape
    m, c, ds = codebooks.shape
    cap = codes.shape[1]

    qf = q.astype(jnp.float32)
    cd = sq_l2(q, centroids)                      # [nq, L]
    _, probes = jax.lax.top_k(-cd, n_probes)
    # probe-invariant query LUT ⟨q, codebooks⟩ — hoisted out of the scan
    qlut = jnp.einsum("qms,mcs->qmc", qf.reshape(nq, m, ds), codebooks,
                      preferred_element_type=jnp.float32)
    if metric == "inner_product":
        qc = qf @ centroids.T                     # [nq, L] ⟨q, c⟩, hoisted
    lists_xs, pvalid = blocked_probe_plan(probes, probe_block)

    def score(inp):
        lists, pv = inp                           # [nq, B], [B]
        B = lists.shape[1]
        bcap = B * cap
        lcodes = codes[lists]                     # [nq, B, cap, m or ⌈m/2⌉]
        if lcodes.shape[-1] != m:                 # 4-bit packed storage:
            lcodes = _unpack_codes4(lcodes, m)    # unpack AFTER the gather
        lcodes = lcodes.astype(jnp.int32).reshape(nq, bcap, m)
        # lookup: ip[nq, B·cap] = Σ_m qlut[q, m, code[q, j, m]]
        ip = jnp.sum(
            jnp.take_along_axis(qlut, lcodes.transpose(0, 2, 1), axis=2),
            axis=1,
        )
        vids = ids[lists].reshape(nq, bcap)
        if metric == "inner_product":
            # ⟨q, c + r̂⟩ = ⟨q, c⟩ + ⟨q, r̂⟩ — both terms precomputed
            qc_sel = jnp.take_along_axis(qc, lists, axis=1)   # [nq, B]
            dist = -(qc_sel[:, :, None]
                     + ip.reshape(nq, B, cap)).reshape(nq, bcap)
        else:
            cd_sel = jnp.take_along_axis(cd, lists, axis=1)   # [nq, B]
            dist = (cd_sel[:, :, None] - 2.0 * ip.reshape(nq, B, cap)
                    + adc_norms[lists]).reshape(nq, bcap)
            dist = jnp.maximum(dist, 0.0)
        valid = (jnp.arange(cap)[None, None, :]
                 < counts[lists][:, :, None]).reshape(nq, bcap)
        valid = valid & (vids >= 0) & jnp.repeat(pv, cap)[None, :]
        if keep is not None:  # prefilter by source id (True = keep)
            from ._packing import keep_lookup

            valid = valid & keep_lookup(keep, vids)
        return jnp.where(valid, dist, jnp.inf), vids

    from ..ops.blocked_scan import scan_topk

    bv, bi = scan_topk(score, (lists_xs, pvalid), nq, k)
    if metric == "euclidean":
        bv = jnp.sqrt(jnp.maximum(bv, 0.0))
    elif metric == "inner_product":
        bv = -bv
    return bv, bi


@tracing.annotate("ivf_pq.search")
def search(index: IvfPqIndex, queries, k: int,
           params: Optional[IvfPqSearchParams] = None, *, filter=None,
           res=None) -> Tuple[jax.Array, jax.Array]:
    """Approximate kNN over the PQ index; combine with
    :func:`raft_tpu.neighbors.refine.refine` for exact re-ranking.

    ``filter``: optional prefilter by source id, True = keep — a shared
    ``core.Bitset``/(n,) bools or a per-query ``core.Bitmap``/(nq, n)
    bools (cuVS bitset/bitmap filter parity)."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           resolve_probe_block, sentinel_filtered_ids)

    p = params or IvfPqSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    expects(p.mode in ("auto", "recon", "lut"), f"unknown mode {p.mode!r}")
    n_probes = min(p.n_probes, index.n_lists)
    probe_block = resolve_probe_block(p.probe_block, int(n_probes),
                                      index.list_cap, "ivf_pq")
    keep = as_keep_mask(filter, nq=q.shape[0])  # indexes source ids
    if keep is not None:
        check_filter_covers_ids(keep, index.ids)
    mode = p.mode
    if mode == "auto":
        mode = "recon" if index.recon is not None else "lut"
    if mode == "recon":
        expects(index.recon is not None,
                "mode='recon' needs the reconstruction slab — call "
                "index.with_recon() (e.g. after load_index)")
        from ..ops.blocked_scan import resolve_scan_kernel

        scan_kernel = resolve_scan_kernel(p.scan_kernel, "ivf_pq",
                                          probe_block * index.list_cap,
                                          int(k))
        impl = lambda qc, kc: _search_recon_impl(
            index.centroids, index.recon, index.recon_norms, index.ids,
            qc, int(k), int(n_probes), index.metric, kc, probe_block,
            scan_kernel)
    else:
        # legacy/hand-built indexes without the hoisted-ADC tables:
        # derive them here (per call — materialize with with_adc_luts()
        # once to amortize, as build/load already do)
        index = index.with_adc_luts()
        impl = lambda qc, kc: _search_lut_impl(
            index.centroids, index.codebooks, index.codes, index.adc_norms,
            index.ids, index.counts, qc, int(k), int(n_probes), index.metric,
            kc, probe_block)
    dv, di = chunked_filtered_queries(impl, q, int(p.query_chunk), keep)
    if keep is not None:  # sub-k survivors: sentinel tail, not real ids
        di = sentinel_filtered_ids(dv, di)
    return dv, di


def searcher(index: IvfPqIndex, k: int,
             params: Optional[IvfPqSearchParams] = None, *, filter=None):
    """Uniform serving entry point (``raft_tpu.serve`` contract): returns
    ``(fn, operands)`` with ``fn(queries, *operands)`` equal to
    :func:`search` for query batches up to ``params.query_chunk`` rows.
    Mode resolution matches :func:`search` (``auto`` → recon tier when the
    slab is materialized, LUT otherwise); index state rides as operands so
    per-bucket executables never embed slab copies.

    ``filter``: optional shared prefilter (``core.Bitset`` / 1-D bools
    over source ids, True = keep) — rides as one more operand, so
    tombstone deletes (:func:`raft_tpu.neighbors.mutation.delete`) swap
    in a new mask without recompiling.  Per-query bitmaps can't ride a
    fixed operand across variable-row buckets and are rejected."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           resolve_probe_block, sentinel_filtered_ids)

    p = params or IvfPqSearchParams()
    expects(k >= 1, "k must be >= 1")
    expects(p.mode in ("auto", "recon", "lut"), f"unknown mode {p.mode!r}")
    n_probes = int(min(p.n_probes, index.n_lists))
    probe_block = resolve_probe_block(p.probe_block, n_probes,
                                      index.list_cap, "ivf_pq")
    metric = index.metric
    keep = as_keep_mask(filter)
    if keep is not None:
        expects(keep.ndim == 1,
                "serving filters are shared bitsets (1-D); per-query "
                "bitmaps can't ride a fixed operand across buckets")
        check_filter_covers_ids(keep, index.ids)
    mode = p.mode
    if mode == "auto":
        mode = "recon" if index.recon is not None else "lut"
    if mode == "recon":
        expects(index.recon is not None,
                "mode='recon' needs the reconstruction slab — call "
                "index.with_recon() (e.g. after load_index)")
        from ..ops.blocked_scan import resolve_scan_kernel

        scan_kernel = resolve_scan_kernel(p.scan_kernel, "ivf_pq",
                                          probe_block * index.list_cap,
                                          int(k))
        if keep is not None:

            def fn(q, centroids, recon, recon_norms, ids, kp):
                dv, di = _search_recon_impl(centroids, recon, recon_norms,
                                            ids, q, int(k), n_probes,
                                            metric, kp, probe_block,
                                            scan_kernel)
                return dv, sentinel_filtered_ids(dv, di)

            return fn, (index.centroids, index.recon, index.recon_norms,
                        index.ids, keep)

        def fn(q, centroids, recon, recon_norms, ids):
            return _search_recon_impl(centroids, recon, recon_norms, ids,
                                      q, int(k), n_probes, metric, None,
                                      probe_block, scan_kernel)

        return fn, (index.centroids, index.recon, index.recon_norms,
                    index.ids)

    index = index.with_adc_luts()  # once, here — operands carry the tables
    if keep is not None:

        def fn(q, centroids, codebooks, codes, adc_norms, ids, counts, kp):
            dv, di = _search_lut_impl(centroids, codebooks, codes,
                                      adc_norms, ids, counts, q, int(k),
                                      n_probes, metric, kp, probe_block)
            return dv, sentinel_filtered_ids(dv, di)

        return fn, (index.centroids, index.codebooks, index.codes,
                    index.adc_norms, index.ids, index.counts, keep)

    def fn(q, centroids, codebooks, codes, adc_norms, ids, counts):
        return _search_lut_impl(centroids, codebooks, codes, adc_norms,
                                ids, counts, q, int(k), n_probes, metric,
                                None, probe_block)

    return fn, (index.centroids, index.codebooks, index.codes,
                index.adc_norms, index.ids, index.counts)


# ---------------------------------------------------------------------------
# Sharded (multi-chip) variant: lists partitioned over the mesh axis,
# codebooks replicated (they are tiny: m * 2^bits * ds floats).
# Mirrors ivf_flat.build_sharded/search_sharded; the TPU analog of the
# reference's MNMG rank-sharded indexes over comms_t (SURVEY.md §5.7).
# ---------------------------------------------------------------------------


@lru_cache(maxsize=16)
def _sharded_coarse_program(mesh, axis: str, per: int, n_lists_local: int,
                            n_train: int, max_iter: int, penalty: float,
                            bal_cap: int, seed: int):
    """Phase A of the distributed build: every device trains its coarse
    quantizer on ITS rows and emits a residual sample for the (tiny,
    shared) PQ codebook fit."""
    from jax.sharding import PartitionSpec as P

    from ..cluster.kmeans import _balanced_fit_impl

    def local(x_l):
        shard = jax.lax.axis_index(axis)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), shard)
        sel = jax.random.permutation(key, per)[:n_train]
        xt = x_l[sel]
        c, _, _, _ = _balanced_fit_impl(
            xt, key, n_lists_local, max_iter, penalty, bal_cap)
        lbl = jnp.argmin(sq_l2(xt, c), axis=1)
        # residual arithmetic in f32: integer subtraction would wrap
        # (cluster._centroid_dtype rationale); c is already f32 for
        # integer corpora
        return c, xt.astype(c.dtype) - c[lbl]

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=P(axis), out_specs=(P(axis), P(axis)),
        check_vma=False,
    ))


@lru_cache(maxsize=16)
def _sharded_encode_program(mesh, axis: str, n_orig: int, per: int,
                            n_lists_local: int, cap: int, m: int,
                            store_recon: bool):
    """Phase B: every device cap-assigns, PQ-encodes and packs ITS rows
    against ITS centroids (codebooks replicated — they are tiny), and
    decodes its recon slab in place when requested."""
    from jax.sharding import PartitionSpec as P

    def local(x_l, c_l, codebooks):
        shard = jax.lax.axis_index(axis)
        gid = (shard * per + jnp.arange(per)).astype(jnp.int32)
        labels, _ = capped_assign(x_l, c_l, cap)
        labels = jnp.where(gid < n_orig, labels, -1)
        residuals = x_l - c_l[jnp.clip(labels, 0, n_lists_local - 1)]
        codes, cnorms = _encode(residuals, codebooks, m)
        (pk_codes, pk_norms, pk_ids), counts = pack_lists(
            labels, (codes, cnorms, gid),
            n_lists=n_lists_local, cap=cap, fills=(0, 0.0, -1))
        if store_recon:
            rec, rnorms = _decode_slab(pk_codes, c_l, codebooks, pk_ids)
        else:  # static-shape placeholders dropped by the caller
            rec = jnp.zeros((n_lists_local, 1, 1), jnp.bfloat16)
            rnorms = jnp.zeros((n_lists_local, 1), jnp.float32)
        # hoisted-ADC tables per LOCAL lists — elementwise over the list
        # axis, so the shard layout is preserved without cross-device moves
        clut, anorms = _adc_tables(pk_codes, c_l, codebooks, pk_norms)
        return pk_codes, pk_norms, pk_ids, counts, rec, rnorms, clut, anorms

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(axis), P(axis), P()),
        out_specs=(P(axis),) * 8, check_vma=False,
    ))


def build_sharded(dataset, mesh, params: Optional[IvfPqIndexParams] = None,
                  *, axis: str = "shard") -> IvfPqIndex:
    """Distributed build: rows sharded over the mesh axis; **each device
    builds its own lists from its own rows on its own device** (two
    shard_map programs — coarse+sample, then encode+pack+decode), with only
    the tiny PQ codebook fit centralized on a gathered residual sample.
    Replaces the r2 build-once-then-device_put shape (VERDICT r2 missing
    #2); SNMG model of ``core/device_resources_snmg.hpp:36``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ._packing import shard_rows, sharded_train_sizes

    p = params or IvfPqIndexParams()
    d = int(dataset.shape[1])
    m = p.pq_dim or max(1, d // 4)
    expects(d % m == 0, f"dim {d} must divide by pq_dim {m}")
    expects(4 <= p.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expects(not p.pack_codes or p.pq_bits <= 4,
            "pack_codes requires pq_bits <= 4")
    cc = 1 << p.pq_bits
    n_dev = int(mesh.shape[axis])
    x_sh, n, per = shard_rows(dataset, mesh, axis)
    n_lists_local = max(1, (p.n_lists + n_dev - 1) // n_dev)
    expects(n_lists_local <= per, "n_lists exceeds rows per shard")
    cap = max(1, int(np.ceil(p.list_cap_ratio * per / n_lists_local)))
    kp = KMeansParams()
    n_train, bal_cap = sharded_train_sizes(
        per, n_lists_local, p.kmeans_trainset_fraction, kp.balanced_max_ratio)

    coarse = _sharded_coarse_program(
        mesh, axis, per, n_lists_local, n_train, p.kmeans_n_iters,
        float(kp.balanced_penalty), bal_cap, p.seed)
    centroids, res_sample = coarse(x_sh)
    # codebooks: tiny (m·2^bits·ds floats) — one central fit, replicated
    codebooks = _train_codebooks(
        res_sample, jax.random.fold_in(jax.random.PRNGKey(p.seed), 7),
        m, cc, p.pq_kmeans_n_iters)
    codebooks = jax.device_put(codebooks, NamedSharding(mesh, P()))

    encode = _sharded_encode_program(
        mesh, axis, n, per, n_lists_local, cap, m, bool(p.store_recon))
    codes, cnorms, ids, counts, rec, rnorms, clut, anorms = encode(
        x_sh, centroids, codebooks)
    index = IvfPqIndex(
        centroids, codebooks, codes, cnorms, ids, counts, p.metric,
        rec if p.store_recon else None,
        rnorms if p.store_recon else None,
        centroid_lut=clut, adc_norms=anorms,
    )
    # packing is elementwise, so it preserves the per-shard layout
    return index.with_packed_codes() if p.pack_codes else index


@lru_cache(maxsize=16)
def _sharded_chunk_coarse_program(mesh, axis: str, n_lists_local: int,
                                  max_iter: int, penalty: float,
                                  bal_cap: int, seed: int):
    """Per-shard coarse fit for the sharded streaming build: each device
    balanced-fits ITS local centroids on ITS host-sampled trainset stripe
    and emits a residual sample for the central (tiny) codebook fit —
    the chunked analog of :func:`_sharded_coarse_program`, taking the
    trainset directly instead of sampling device-resident rows."""
    from jax.sharding import PartitionSpec as P

    from ..cluster.kmeans import _balanced_fit_impl

    def local(xt_l):
        shard = jax.lax.axis_index(axis)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), shard)
        c, _, _, _ = _balanced_fit_impl(
            xt_l, key, n_lists_local, max_iter, penalty, bal_cap)
        lbl = jnp.argmin(sq_l2(xt_l, c), axis=1)
        return c, xt_l.astype(c.dtype) - c[lbl]

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=P(axis), out_specs=(P(axis), P(axis)),
        check_vma=False))


@lru_cache(maxsize=16)
def _sharded_chunk_step_program(mesh, axis: str, n_lists_local: int,
                                cap: int, m: int):
    """Data-parallel fused chunk step: every device runs
    :func:`_pq_chunk_step`'s body (assign → residual → encode → scatter)
    on ITS slice of the chunk against ITS local lists — one jitted
    shard_map program per chunk, slabs donated, codebooks replicated,
    zero cross-device data movement."""
    from jax.sharding import PartitionSpec as P

    from ..cluster.kmeans import _capped_assign_impl
    from ._packing import _scatter_append_impl

    def local(codes_l, cn_l, ids_l, counts_l, c_l, cb, xc_l, idc_l):
        valid = idc_l >= 0
        labels, _ = _capped_assign_impl(xc_l, c_l, cap - counts_l, valid)
        residuals = xc_l - c_l[jnp.clip(labels, 0, n_lists_local - 1)]
        ch_codes, ch_norms = _encode(residuals, cb, m)
        (codes_l, cn_l, ids_l), counts_l = _scatter_append_impl(
            (codes_l, cn_l, ids_l), counts_l, labels,
            (ch_codes, ch_norms, idc_l), n_lists=n_lists_local, cap=cap)
        return codes_l, cn_l, ids_l, counts_l

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(axis),) * 5 + (P(), P(axis), P(axis)),
        out_specs=(P(axis),) * 4, check_vma=False),
        donate_argnums=(0, 1, 2, 3))


@lru_cache(maxsize=16)
def _sharded_chunk_finalize_program(mesh, axis: str, n_lists_local: int,
                                    store_recon: bool):
    """Derived-tier finalize for the sharded streaming build: per-shard
    recon slab decode and hoisted-ADC tables, elementwise over the local
    list axis so the shard layout is preserved (same shape as the tail of
    :func:`_sharded_encode_program`)."""
    from jax.sharding import PartitionSpec as P

    def local(codes_l, cnorms_l, ids_l, c_l, cb):
        if store_recon:
            rec, rnorms = _decode_slab(codes_l, c_l, cb, ids_l)
        else:  # static-shape placeholders dropped by the caller
            rec = jnp.zeros((n_lists_local, 1, 1), jnp.bfloat16)
            rnorms = jnp.zeros((n_lists_local, 1), jnp.float32)
        clut, anorms = _adc_tables(codes_l, c_l, cb, cnorms_l)
        return rec, rnorms, clut, anorms

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(axis),) * 4 + (P(),),
        out_specs=(P(axis),) * 4, check_vma=False))


def build_chunked_sharded(dataset, mesh,
                          params: Optional[IvfPqIndexParams] = None, *,
                          chunk_rows: int = 0, source_ids=None,
                          axis: str = "shard") -> IvfPqIndex:
    """Distributed streaming build — the build-side analog of
    :func:`search_sharded`: the dataset stays on host and each fixed-size
    chunk splits contiguously over the mesh axis (one sharded
    ``device_put``, staged a chunk ahead), every device encoding and
    appending its slice into ITS OWN local lists via the fused donated
    chunk step.  :func:`build_chunked`'s out-of-core pipeline (fixed
    shapes, padded tail, single executable) on
    :func:`build_sharded`'s shard-local sub-index model; only the tiny PQ
    codebook fit is centralized (on a gathered per-shard residual
    sample), then replicated.  Per-device peak = local code slabs + its
    chunk slice."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ._packing import (build_heartbeat, chunked_shard_rows,
                           chunked_shard_trainsets, prefetch_chunks_padded,
                           resolve_chunk_rows, sharded_train_sizes)

    p = params or IvfPqIndexParams()
    n, d = dataset.shape
    m = p.pq_dim or max(1, d // 4)
    expects(d % m == 0, f"dim {d} must divide by pq_dim {m}")
    expects(4 <= p.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expects(not p.pack_codes or p.pq_bits <= 4,
            "pack_codes requires pq_bits <= 4")
    cc = 1 << p.pq_bits
    n_dev = int(mesh.shape[axis])
    n_lists_local = max(1, (p.n_lists + n_dev - 1) // n_dev)
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_pq")
    chunk_rows = min(-(-chunk_rows // n_dev), -(-n // n_dev)) * n_dev
    shard_valid = chunked_shard_rows(n, chunk_rows, n_dev)
    expects(int(shard_valid.min()) >= 1,
            f"chunk layout leaves a shard with no rows (n={n}, "
            f"chunk_rows={chunk_rows}, shards={n_dev}): lower chunk_rows "
            f"or use fewer shards")
    per = int(shard_valid.max())
    expects(n_lists_local <= per, "n_lists exceeds rows per shard")
    cap = max(1, int(np.ceil(p.list_cap_ratio * per / n_lists_local)))
    kp = KMeansParams()
    n_train, bal_cap = sharded_train_sizes(
        per, n_lists_local, p.kmeans_trainset_fraction, kp.balanced_max_ratio)
    sharding = NamedSharding(mesh, P(axis))

    xt = chunked_shard_trainsets(dataset, n, chunk_rows, n_dev, n_train,
                                 p.seed)
    xt_sh = jax.device_put(xt.reshape(n_dev * n_train, d), sharding)
    coarse = _sharded_chunk_coarse_program(
        mesh, axis, n_lists_local, p.kmeans_n_iters,
        float(kp.balanced_penalty), bal_cap, p.seed)
    centroids, res_sample = coarse(xt_sh)
    # codebooks: tiny (m·2^bits·ds floats) — one central fit, replicated
    codebooks = _train_codebooks(
        res_sample, jax.random.fold_in(jax.random.PRNGKey(p.seed), 7),
        m, cc, p.pq_kmeans_n_iters)
    codebooks = jax.device_put(codebooks, NamedSharding(mesh, P()))

    L = n_dev * n_lists_local
    codes = jax.device_put(jnp.zeros((L, cap, m), jnp.uint8), sharding)
    cnorms = jax.device_put(jnp.zeros((L, cap), jnp.float32), sharding)
    ids_slab = jax.device_put(jnp.full((L, cap), -1, jnp.int32), sharding)
    counts = jax.device_put(jnp.zeros((L,), jnp.int32), sharding)
    step = _sharded_chunk_step_program(mesh, axis, n_lists_local, cap, m)
    heartbeat = build_heartbeat("ivf_pq.build_chunked_sharded", n)
    for lo, hi, xc, idc in prefetch_chunks_padded(
            dataset, chunk_rows, source_ids, sharding=sharding):
        codes, cnorms, ids_slab, counts = step(
            codes, cnorms, ids_slab, counts, centroids, codebooks, xc, idc)
        heartbeat(hi)

    finalize = _sharded_chunk_finalize_program(
        mesh, axis, n_lists_local, bool(p.store_recon))
    rec, rnorms, clut, anorms = finalize(codes, cnorms, ids_slab, centroids,
                                         codebooks)
    index = IvfPqIndex(
        centroids, codebooks, codes, cnorms, ids_slab, counts, p.metric,
        rec if p.store_recon else None,
        rnorms if p.store_recon else None,
        centroid_lut=clut, adc_norms=anorms,
    )
    return index.with_packed_codes() if p.pack_codes else index


@partial(jax.jit, static_argnames=("k", "n_probes", "metric", "axis", "mesh",
                                   "mode", "data_axis", "probe_block"))
def _search_sharded_impl(mesh, axis, centroids, codebooks, codes, adc_norms,
                         ids, counts, recon, recon_norms, q,
                         k: int, n_probes: int, metric: str, mode: str,
                         data_axis: Optional[str] = None, keep=None,
                         probe_block: int = 1):
    from jax.sharding import PartitionSpec as P

    def merge(bv, bi, nq_l):
        if metric == "inner_product":
            bv = -bv  # back to min-selectable for the cross-shard merge
        av = jax.lax.all_gather(bv, axis, tiled=False)   # [S, nq, k]
        ai = jax.lax.all_gather(bi, axis, tiled=False)
        av = jnp.moveaxis(av, 0, 1).reshape(nq_l, -1)
        ai = jnp.moveaxis(ai, 0, 1).reshape(nq_l, -1)
        from ..matrix.select_k import select_k

        fv, fi = select_k(av, k, in_idx=ai, select_min=True)
        if metric == "inner_product":
            fv = -fv
        return fv, fi

    qspec = P(data_axis) if data_axis else P()
    # keep masks GLOBAL source ids → replicated over the shard axis; a 2-D
    # bitmap's query rows follow the query partitioning
    kspec = (P(data_axis) if (keep is not None and keep.ndim == 2
                              and data_axis) else P())
    if mode == "recon":
        def local(centroids_l, recon_l, recon_norms_l, ids_l, q_l, keep_l):
            bv, bi = _search_recon_impl(centroids_l, recon_l, recon_norms_l,
                                        ids_l, q_l, k, n_probes, metric,
                                        keep_l, probe_block)
            return merge(bv, bi, q_l.shape[0])

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), qspec, kspec),
            out_specs=(qspec, qspec), check_vma=False,
        )(centroids, recon, recon_norms, ids, q, keep)

    def local(centroids_l, codebooks_l, codes_l, adc_norms_l, ids_l,
              counts_l, q_l, keep_l):
        bv, bi = _search_lut_impl(centroids_l, codebooks_l, codes_l,
                                  adc_norms_l, ids_l, counts_l, q_l,
                                  k, n_probes, metric, keep_l, probe_block)
        return merge(bv, bi, q_l.shape[0])

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis), P(axis), P(axis), qspec,
                  kspec),
        out_specs=(qspec, qspec), check_vma=False,
    )(centroids, codebooks, codes, adc_norms, ids, counts, q, keep)


def search_sharded(index: IvfPqIndex, queries, k: int,
                   params: Optional[IvfPqSearchParams] = None, *,
                   mesh, axis: str = "shard",
                   data_axis: Optional[str] = None, filter=None
                   ) -> Tuple[jax.Array, jax.Array]:
    """Multi-chip search: each shard probes its ``n_probes`` nearest
    *local* lists (union over shards covers the globally nearest lists),
    one all_gather of (nq, k) candidates merges over ICI.  On a 2-D mesh,
    ``data_axis`` partitions the queries over that axis.

    ``filter``: bitset/bitmap prefilter over GLOBAL source ids, same
    contract as :func:`search` (replicated over the shard axis)."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           resolve_probe_block, sentinel_filtered_ids)

    p = params or IvfPqSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    expects(p.mode in ("auto", "recon", "lut"), f"unknown mode {p.mode!r}")
    n_dev = int(mesh.shape[axis])
    local_lists = index.n_lists // n_dev
    n_probes = min(p.n_probes, local_lists)
    probe_block = resolve_probe_block(p.probe_block, int(n_probes),
                                      index.list_cap, "ivf_pq")
    if data_axis is not None:
        expects(data_axis in mesh.axis_names, f"axis {data_axis!r} not in mesh")
        expects(q.shape[0] % int(mesh.shape[data_axis]) == 0,
                "queries not divisible by data axis")
    keep = as_keep_mask(filter, nq=q.shape[0])
    if keep is not None:
        check_filter_covers_ids(keep, index.ids)
    mode = p.mode
    if mode == "auto":
        mode = "recon" if index.recon is not None else "lut"
    if mode == "recon":
        expects(index.recon is not None,
                "mode='recon' needs the reconstruction slab — call "
                "index.with_recon() (e.g. after load_index)")
    elif index.adc_norms is None:
        # hoisted-ADC tables are elementwise over the list axis, so this
        # preserves a sharded index's layout (build_sharded pre-computes
        # them inside the encode program; this covers hand-built indexes)
        index = index.with_adc_luts()
    dv, di = _search_sharded_impl(mesh, axis, index.centroids,
                                  index.codebooks, index.codes,
                                  index.adc_norms if mode == "lut"
                                  else index.code_norms,
                                  index.ids, index.counts,
                                  index.recon, index.recon_norms,
                                  q, int(k), int(n_probes), index.metric,
                                  mode, data_axis, keep, probe_block)
    if keep is not None:
        di = sentinel_filtered_ids(dv, di)
    return dv, di
