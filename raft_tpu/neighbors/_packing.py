"""Device-side inverted-list packing shared by IVF-Flat and IVF-PQ builds.

The round-1 builds scattered rows into the padded ``[n_lists, cap]`` slabs
with host numpy (``ivf_flat.py:98`` r1) — fine at 10⁴ rows, hopeless at
10⁷⁺.  This is the jitted replacement: one stable device sort by list id
turns the scatter into a dense segment layout, and a single ``.at[].set``
with out-of-bounds drop does the packing.  Everything stays on device; a
10M-row build never round-trips through the host.

Reference analog: the list-packing step of the cuVS IVF builds (no in-tree
ancestor, SURVEY.md scope note); the sort-based formulation is the TPU
replacement for CUDA atomic-append list construction.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["pack_lists", "chunked_queries", "chunked_filtered_queries",
           "check_filter_covers_ids", "keep_lookup", "scatter_append",
           "scatter_append_copy", "device_full", "shard_rows",
           "sharded_train_sizes",
           "as_keep_mask", "sentinel_filtered_ids", "prefetch_chunks",
           "prefetch_chunks_padded", "build_heartbeat",
           "chunked_shard_rows", "chunked_shard_trainsets",
           "blocked_probe_plan", "resolve_probe_block",
           "resolve_chunk_rows", "resolve_cagra_search",
           "DEFAULT_INSERT_CHUNK", "host_rows", "staged_insert_chunks"]


def prefetch_chunks(dataset, chunk_rows: int, ids=None):
    """Yield ``(lo, hi, chunk_array, id_array)`` with the NEXT chunk's host
    read running on a background worker while the caller's device work
    consumes the current one — double-buffered host→device feeding for the
    out-of-core builds (the native IO layer's ``pread`` releases the GIL,
    so the overlap is real for memmap/np sources).

    Same one-worker future pattern as ``io.BatchLoader.__iter__``: read
    exceptions re-raise at the consumer (``future.result()``) and the
    executor context joins the in-flight read even when the consumer's
    loop body raises or breaks out early.
    """
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    n = dataset.shape[0]
    bounds = [(lo, min(n, lo + chunk_rows)) for lo in range(0, n, chunk_rows)]

    def read(lo, hi):
        xc = np.asarray(dataset[lo:hi])
        idc = (np.asarray(ids[lo:hi]) if ids is not None
               else np.arange(lo, hi, dtype=np.int32))
        return xc, idc

    with ThreadPoolExecutor(max_workers=1) as pool:
        future = None
        for i, (lo, hi) in enumerate(bounds):
            cur = read(lo, hi) if future is None else future.result()
            future = (pool.submit(read, *bounds[i + 1])
                      if i + 1 < len(bounds) else None)
            yield lo, hi, cur[0], cur[1]


def prefetch_chunks_padded(dataset, chunk_rows: int, ids=None, *,
                           dtype=None, sharding=None):
    """Fixed-shape, double-buffered *device* feeding for the pipelined
    streaming builds: :func:`prefetch_chunks` (background host reads) with
    two pipeline stages on top —

    * the tail chunk is padded up to ``chunk_rows`` rows with id −1, so
      every chunk has the SAME shape and one jitted chunk-step executable
      serves the whole stream (zero steady-state recompiles; the fused
      steps mask ``idc < 0`` rows out of assignment and capacity);
    * each chunk is staged onto the device with a non-blocking
      ``jax.device_put`` issued one chunk AHEAD of the consumer
      (:func:`raft_tpu.core.device_prefetch`), so the H2D copy of chunk
      t+1 overlaps the device compute on chunk t.

    Yields ``(lo, hi, xc_dev, idc_dev)`` with ``xc_dev: [chunk_rows, d]``
    and ``idc_dev: [chunk_rows] int32``; ``hi − lo`` is the REAL row count
    (< ``chunk_rows`` only for a padded tail).  ``dtype``: optional cast
    applied host-side (before the put).  ``sharding``: optional
    ``jax.sharding.Sharding`` for the put — the sharded builds pass
    ``NamedSharding(mesh, P(axis))`` so each device receives only its row
    slice (``chunk_rows`` must then divide by the axis size).

    ``device_put`` is an explicit transfer: consumers stay clean under
    ``jax.transfer_guard("disallow")``.
    """
    import numpy as np

    from ..core.double_buffer import device_prefetch

    n = dataset.shape[0]
    chunk_rows = max(1, min(int(chunk_rows), n))

    def stage(item):
        lo, hi, xc_h, idc_h = item
        xc_h = np.asarray(xc_h)
        if dtype is not None:
            xc_h = xc_h.astype(dtype, copy=False)
        idc_h = np.asarray(idc_h, np.int32)
        rows = hi - lo
        if rows < chunk_rows:  # pad the tail to the one fixed shape
            xp = np.zeros((chunk_rows,) + xc_h.shape[1:], xc_h.dtype)
            xp[:rows] = xc_h
            ip = np.full((chunk_rows,), -1, np.int32)
            ip[:rows] = idc_h
            xc_h, idc_h = xp, ip
        return (lo, hi, jax.device_put(xc_h, sharding),
                jax.device_put(idc_h, sharding))

    yield from device_prefetch(prefetch_chunks(dataset, chunk_rows, ids),
                               stage)


def build_heartbeat(tag: str, total_rows: int):
    """Liveness reporter for multi-hour streaming builds: returns a
    ``tick(rows_done)`` closure that debug-logs CUMULATIVE throughput
    (rows/s) and the ETA to completion, not just the row range
    (``RAFT_TPU_LOG_LEVEL=DEBUG``).  Pure host arithmetic on the dispatch
    side — never syncs the device (with async dispatch the rate reads as
    dispatch throughput, which converges to device throughput once the
    pipeline fills)."""
    import time

    from ..core.logging import default_logger

    logger = default_logger()
    t0 = time.perf_counter()

    def tick(rows_done: int) -> None:
        dt = max(time.perf_counter() - t0, 1e-9)
        rate = rows_done / dt
        eta = (total_rows - rows_done) / max(rate, 1e-9)
        logger.debug("%s: %d/%d rows (%.0f rows/s, ETA %.0fs)",
                     tag, rows_done, total_rows, rate, eta)

    return tick


def as_keep_mask(filter, n=None, nq=None):
    """Normalize a prefilter (True/1 = keep) to a bool mask — the cuVS
    filter contract.  Accepts:

    * ``core.Bitset`` or 1-D boolean array — one shared mask over source
      rows (``bitset_filter``), returns ``(n,)``;
    * ``core.Bitmap`` or 2-D boolean array — a PER-QUERY mask
      (``bitmap_filter``), returns ``(nq, n)``.

    With ``n`` the row count is checked exactly (positional numbering);
    IVF callers instead validate against their max source id.  ``nq``
    checks the query count of 2-D masks."""
    if filter is None:
        return None
    from ..core.bitset import Bitmap, Bitset
    from ..core.errors import expects

    if isinstance(filter, Bitmap):
        keep = filter.to_bool_array().reshape(filter.rows, filter.cols)
    elif isinstance(filter, Bitset):
        keep = filter.to_bool_array()
    else:
        keep = jnp.asarray(filter, bool)
    expects(keep.ndim in (1, 2), "filter must be 1-D (bitset) or 2-D (bitmap)")
    if n is not None:
        expects(keep.shape[-1] == n,
                f"filter covers {keep.shape[-1]} rows, need {n}")
    if nq is not None and keep.ndim == 2:
        expects(keep.shape[0] == nq,
                f"bitmap filter has {keep.shape[0]} rows, need nq={nq}")
    return keep


_max_id_cache: dict = {}


def cached_by_id(cache: dict, obj, compute, bound: int = 256):
    """id()-keyed memo for a host scalar derived from a device array —
    avoids putting a device reduction + host sync on every dispatch when
    the same object is reused across calls.  A weakref guard ensures a
    recycled id() can never return a stale value.  Dead entries are purged
    at the bound, and the bound holds even when every entry is live (a
    process holding hundreds of loaded indexes): oldest-inserted entries
    are evicted FIFO — a refill costs one recompute, not correctness."""
    import weakref

    key = id(obj)
    hit = cache.get(key)
    if hit is not None and hit[0]() is obj:
        return hit[1]
    val = compute()
    try:
        ref = weakref.ref(obj)
    except TypeError:
        return val  # un-weakref-able subject (e.g. a list) — skip caching
    if len(cache) > bound:
        for k in [k for k, (r, _) in cache.items() if r() is None]:
            del cache[k]
        while len(cache) > bound:
            del cache[next(iter(cache))]
    cache[key] = (ref, val)
    return val


def _max_source_id(ids) -> int:
    """max(ids) — a per-index constant, memoized per id-array object.  The
    transfer is explicit (``device_get``) so generation swaps that derive
    a fresh searcher stay clean under ``transfer_guard("disallow")``."""
    return cached_by_id(_max_id_cache, ids,
                        lambda: int(jax.device_get(jnp.max(ids))))  # jaxlint: disable=JX01 per-index constant, memoized per id-array object; explicit transfer stays clean under transfer_guard


def check_filter_covers_ids(keep, ids):
    """Validate a keep mask covers every stored source id (the gather
    clamps OOB indices, which would silently read an unrelated id's
    bit)."""
    from ..core.errors import expects

    max_id = _max_source_id(ids)
    expects(keep.shape[-1] > max_id,
            f"filter covers {keep.shape[-1]} ids, index ids reach {max_id}")


# NOTE: the scoring-tier rule (int8_tier_eligible) and the gathered-dots
# einsum live in ops.blocked_scan's documented quantized-scan sub-API —
# import them from there (the historical _packing re-exports are gone).


def keep_lookup(keep, vids):
    """Gather the keep bit for a (possibly −1-padded) id matrix — the one
    id-indexed filter gather every search path shares.  OOB/pad ids are
    clamped; callers mask validity separately."""
    vc = jnp.maximum(vids, 0)
    return keep[vc] if keep.ndim == 1 \
        else jnp.take_along_axis(keep, vc, axis=1)


def blocked_probe_plan(probes, block: int):
    """Reshape a ``(nq, P)`` probe table into per-step scan inputs for a
    probe-blocked search: ``block`` probes are gathered, scored, and merged
    per ``lax.scan`` step instead of one (⌈P/B⌉ top-k merges instead of P).

    P is padded up to a multiple of ``block`` with a *validity* row — never
    duplicate probes, which would insert the same candidates into the
    running top-k twice.  Pad positions must contribute dist = +inf.

    Returns ``(lists_xs, probe_valid_xs)`` of shapes ``[n_blocks, nq, B]``
    and ``[n_blocks, B]`` (both scan xs).
    """
    nq, n_probes = probes.shape
    pad = (-n_probes) % block
    if pad:
        probes = jnp.pad(probes, ((0, 0), (0, pad)))
    pvalid = (jnp.arange(n_probes + pad) < n_probes).reshape(-1, block)
    lists_xs = jnp.moveaxis(probes.reshape(nq, -1, block), 1, 0)
    return lists_xs, pvalid


@lru_cache(maxsize=1)
def _probe_block_table():
    """Measured probe_block table written by ``bench/tune_probe_block.py``
    (same offline-tuned-dispatch pattern as ``matrix/_select_k_table.json``).
    Canonical name first; a ``.{backend}.json`` suffix holds off-TPU
    measurements without clobbering the TPU table."""
    import json
    import os

    base = os.path.join(os.path.dirname(__file__), "_probe_block_table")
    for suffix in (".json", f".{jax.default_backend()}.json"):
        try:
            with open(base + suffix) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return {}


_probe_block_cache: dict = {}


def resolve_probe_block(requested: int, n_probes: int, cap: int,
                        family: str) -> int:
    """Static probe-block width for an IVF search config.

    ``requested > 0`` wins (clamped to ``[1, n_probes]``); ``0`` = auto:
    the measured table (log2-bucketed like ``select_k``'s dispatch table),
    else a heuristic bounding both the merge width and the per-step gather
    working set.  Pure host-int arithmetic — never touches the device."""
    if requested:
        return max(1, min(int(requested), max(1, n_probes)))
    key = f"{family}:{n_probes.bit_length()}:{cap.bit_length()}"
    hit = _probe_block_cache.get(key)
    if hit is None:
        entry = _probe_block_table().get(key)
        if entry is None:
            # bound the [nq, B*cap] slab + merge width: ~16k candidates
            # per step, at most 8 probes, never more than n_probes
            entry = min(max(1, n_probes), 8, max(1, 16384 // max(cap, 1)))
        hit = _probe_block_cache[key] = max(1, min(int(entry),
                                                  max(1, n_probes)))
    return hit


@lru_cache(maxsize=1)
def _chunk_rows_table():
    """Measured chunk_rows table written by ``bench/tune_chunk_rows.py``
    (same offline-tuned-dispatch pattern as ``_probe_block_table``).
    Canonical name first; a ``.{backend}.json`` suffix holds off-TPU
    measurements without clobbering the TPU table."""
    import json
    import os

    base = os.path.join(os.path.dirname(__file__), "_chunk_rows_table")
    for suffix in (".json", f".{jax.default_backend()}.json"):
        try:
            with open(base + suffix) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return {}


#: fallback streaming chunk size when no measured table entry exists —
#: the historical ``build_chunked`` default
DEFAULT_CHUNK_ROWS = 65536


def resolve_chunk_rows(requested: int, n: int, dim: int, family: str) -> int:
    """Static chunk size for a streaming (``build_chunked``) index build.

    ``requested > 0`` wins (clamped to ``[1, n]``); ``0`` = auto: the
    measured table (log2-bucketed by dim, written by
    ``bench/tune_chunk_rows.py``), else :data:`DEFAULT_CHUNK_ROWS`.
    Results are identical for every value — chunk size is a pure
    throughput knob (docs/tuning_guide.md) — so auto never changes what
    gets built, only how fast.  Pure host-int arithmetic."""
    if requested:
        return max(1, min(int(requested), max(1, int(n))))
    entry = _chunk_rows_table().get(f"{family}:{int(dim).bit_length()}")
    if entry is None:
        entry = DEFAULT_CHUNK_ROWS
    return max(1, min(int(entry), max(1, int(n))))


@lru_cache(maxsize=1)
def _cagra_search_table():
    """Measured (itopk, width) table written by ``bench/tune_cagra.py``
    (same offline-tuned-dispatch pattern as ``_probe_block_table``).
    Canonical name first; a ``.{backend}.json`` suffix holds off-TPU
    measurements without clobbering the TPU table."""
    import json
    import os

    base = os.path.join(os.path.dirname(__file__), "_cagra_search_table")
    for suffix in (".json", f".{jax.default_backend()}.json"):
        try:
            with open(base + suffix) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return {}


def resolve_cagra_search(itopk_size: int, search_width: int, k: int,
                         n: int) -> Tuple[int, int]:
    """Static ``(itopk, width)`` for a CAGRA search config.

    Nonzero values win; ``0`` = auto: the measured table (log2-bucketed by
    ``(k, n)``, written by ``bench/tune_cagra.py``; EXACT bucket match
    only — a point tuned at one scale never extrapolates to another),
    else the historical defaults ``(64, 4)``.  The resolved itopk is
    clamped to ≥ k and width to ``[1, itopk]`` (the frontier cannot be
    wider than the beam).  Unlike ``probe_block``, this knob changes
    RESULTS (recall/effort), not just speed — which is why the tuner
    behind the table is recall-gated.  Pure host-int arithmetic."""
    it, w = int(itopk_size), int(search_width)
    if not (it and w):
        entry = _cagra_search_table().get(
            f"cagra:{int(k).bit_length()}:{int(n).bit_length()}")
        if entry is None:
            entry = (64, 4)
        it = it or int(entry[0])
        w = w or int(entry[1])
    it = max(it, int(k))
    return it, max(1, min(w, it))


def sentinel_filtered_ids(vals, ids):
    """Filtered-search output contract: slots that hold no real survivor
    (±inf distance) report id −1, never a filtered row's id."""
    return jnp.where(jnp.isfinite(vals), ids, -1)


def shard_rows(dataset, mesh, axis: str):
    """Pad rows to a multiple of the mesh axis and lay them out sharded —
    **without staging the full array on one device**: host (numpy) data is
    padded in numpy and ``device_put`` with the target ``NamedSharding``
    slices it straight to each device, so the single-device peak is one
    shard, not the dataset.  Returns ``(x_sharded, n_orig, rows_per_shard)``.

    Shared preamble of every distributed ``build_sharded``
    (ivf_flat/ivf_pq/cagra).
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    n_dev = int(mesh.shape[axis])
    n, d = dataset.shape
    per = (n + n_dev - 1) // n_dev
    pad = per * n_dev - n
    if isinstance(dataset, jax.Array):
        x = dataset
        if pad:
            x = jnp.concatenate([x, jnp.tile(x[:1], (pad, 1))], axis=0)
    else:
        x = np.asarray(dataset)
        if pad:
            x = np.concatenate([x, np.tile(x[:1], (pad, 1))], axis=0)
    sharding = NamedSharding(mesh, PartitionSpec(axis))
    return jax.device_put(x, sharding), n, per


def sharded_train_sizes(per: int, n_lists_local: int, trainset_fraction: float,
                        balanced_max_ratio: float = 2.0):
    """Per-shard quantizer-training sizes: ``(n_train, bal_cap)``.

    Floor of 32 rows per local list — per-shard trainsets are 1/S of the
    dataset, and the ``n_lists·4`` floor that suffices globally starves the
    per-shard balanced fit (and the PQ codebook sample union) at test
    scales.
    """
    n_train = min(per, max(n_lists_local * 32, int(per * trainset_fraction)))
    bal_cap = max(1, -(-int(balanced_max_ratio * n_train) // n_lists_local))
    return n_train, bal_cap


def chunked_shard_rows(n: int, chunk_rows: int, n_dev: int):
    """Per-shard REAL row counts under the chunk-striped layout of the
    sharded streaming builds: every chunk of ``chunk_rows`` rows (the last
    one padded) splits contiguously over the ``n_dev`` mesh devices, so
    shard ``s`` owns rows ``[t·C + s·C/S, t·C + (s+1)·C/S)`` of every
    chunk ``t``.  Returns an ``(n_dev,)`` numpy int array — used to size
    per-shard list capacity and to validate no shard streams zero rows."""
    import numpy as np

    pc = chunk_rows // n_dev
    n_chunks = -(-n // chunk_rows)
    starts = (np.arange(n_chunks)[:, None] * chunk_rows
              + np.arange(n_dev)[None, :] * pc)
    return np.clip(n - starts, 0, pc).sum(axis=0)


def chunked_shard_trainsets(dataset, n: int, chunk_rows: int, n_dev: int,
                            n_train: int, seed: int):
    """Host-sampled per-shard quantizer trainsets for the sharded
    streaming builds: shard ``s`` trains on rows sampled from ITS OWN
    chunk stripes (:func:`chunked_shard_rows` layout), so each shard's
    coarse quantizer models exactly the rows that will stream through it.
    Returns ``[n_dev, n_train, d]`` numpy (shards with fewer than
    ``n_train`` real rows sample with replacement — shapes must be static
    across the mesh).  Reads are sorted per shard (memmap-friendly)."""
    import numpy as np

    pc = chunk_rows // n_dev
    n_chunks = -(-n // chunk_rows)
    rng = np.random.default_rng(seed)
    out = []
    for s in range(n_dev):
        starts = np.arange(n_chunks) * chunk_rows + s * pc
        avail = np.clip(n - starts, 0, pc)
        total = int(avail.sum())
        pos = np.sort(rng.choice(total, n_train, replace=total < n_train))
        cum = np.cumsum(avail) - avail
        ci = np.searchsorted(cum, pos, side="right") - 1
        out.append(np.asarray(dataset[starts[ci] + (pos - cum[ci])]))
    return np.stack(out)


def chunked_queries(run, q, chunk: int, aux=None):
    """Apply ``run(q_chunk[, aux_chunk]) -> (vals, idx)`` over fixed-size
    query chunks (pads the tail chunk so only one program is compiled);
    bounds the per-dispatch gather working set of the IVF search paths.
    ``aux``: optional per-query array (e.g. a bitmap filter's rows),
    sliced in lockstep with the queries."""
    nq = q.shape[0]
    call = (lambda qc, ac: run(qc)) if aux is None else run
    if chunk <= 0 or nq <= chunk:
        return call(q, aux)
    pad = (-nq) % chunk

    def padded(a):
        if not pad:
            return a
        return jnp.concatenate([a, jnp.tile(a[:1], (pad,) + (1,) * (a.ndim - 1))],
                               axis=0)

    qp = padded(q)
    ap = padded(aux) if aux is not None else None
    vals, idxs = [], []
    for i in range(qp.shape[0] // chunk):
        sl = slice(i * chunk, (i + 1) * chunk)
        v, ix = call(qp[sl], None if ap is None else ap[sl])
        vals.append(v)
        idxs.append(ix)
    return (jnp.concatenate(vals, axis=0)[:nq],
            jnp.concatenate(idxs, axis=0)[:nq])


def chunked_filtered_queries(impl, q, chunk: int, keep):
    """``impl(q_chunk, keep_chunk)`` over query chunks with the filter
    contract shared by the IVF searches: a 2-D (bitmap) ``keep`` is
    sliced in lockstep with the queries; ``None``/1-D rides the closure."""
    if keep is not None and keep.ndim == 2:
        return chunked_queries(impl, q, chunk, aux=keep)
    return chunked_queries(lambda qc: impl(qc, keep), q, chunk)


@partial(jax.jit, static_argnames=("n_lists", "cap", "fills"))
def pack_lists(
    labels: jax.Array,
    arrays: Tuple[jax.Array, ...],
    *,
    n_lists: int,
    cap: int,
    fills: Tuple[float, ...],
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Pack per-row payloads into padded per-list slabs, on device.

    ``labels``: (n,) int32 list assignment, −1 = drop the row.
    ``arrays``: tuple of payloads with leading dim n (e.g. vectors, ids).
    ``fills``: pad value per payload (static, e.g. ``(0.0, -1)``).

    Returns ``(packed, counts)`` where ``packed[i]`` has shape
    ``(n_lists, cap, *arrays[i].shape[1:])`` and ``counts`` is (n_lists,)
    int32 clamped to ``cap``.  Rows beyond a list's capacity are dropped
    (callers using :func:`raft_tpu.cluster.kmeans.capped_assign` never hit
    this).
    """
    n = labels.shape[0]
    labels = labels.astype(jnp.int32)
    valid = labels >= 0
    # stable sort by list id; dropped rows sort to the end
    sort_key = jnp.where(valid, labels, n_lists)
    order = jnp.argsort(sort_key, stable=True)
    sl = labels[order]
    svalid = sl >= 0
    sl_safe = jnp.where(svalid, sl, 0)
    counts = jax.ops.segment_sum(
        svalid.astype(jnp.int32), sl_safe, num_segments=n_lists
    )
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(n, dtype=jnp.int32) - starts[sl_safe]
    ok = svalid & (pos < cap)
    # out-of-range destination rows are dropped by scatter mode="drop"
    dest = jnp.where(ok, sl_safe * cap + pos, n_lists * cap)
    packed = []
    for arr, fill in zip(arrays, fills):
        flat = jnp.full((n_lists * cap,) + arr.shape[1:], fill, arr.dtype)
        flat = flat.at[dest].set(arr[order], mode="drop")
        packed.append(flat.reshape((n_lists, cap) + arr.shape[1:]))
    return tuple(packed), jnp.minimum(counts, cap)


def _scatter_append_impl(
    slabs: Tuple[jax.Array, ...],
    counts: jax.Array,
    labels: jax.Array,
    payloads: Tuple[jax.Array, ...],
    *,
    n_lists: int,
    cap: int,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Append one chunk's rows into existing padded slabs, on device.

    The streaming counterpart of :func:`pack_lists`: rows labeled ``l`` land
    at positions ``counts[l] + rank-within-chunk``, so successive calls build
    the same layout ``pack_lists`` would have produced in one shot.

    Two jitted forms: :func:`scatter_append` **donates** ``slabs`` and
    ``counts`` — in-place update, peak device memory stays slab + chunk
    (what makes larger-than-HBM datasets buildable chunk by chunk; VERDICT
    r2 missing #2) — callers must own the buffers (build loops do).
    :func:`scatter_append_copy` leaves the inputs alive, for callers
    updating a LIVE index's arrays (``ivf_pq.extend``) where donation
    would delete the source index's buffers out from under it.

    ``labels``: (chunk,) int32, −1 = drop (callers cap against remaining
    room via :func:`raft_tpu.cluster.kmeans.capped_assign_room`, so −1 only
    appears when total capacity is exhausted).  Rows that would still
    overflow a list are dropped, matching ``pack_lists``.
    """
    nrows = labels.shape[0]
    labels = labels.astype(jnp.int32)
    valid = labels >= 0
    sort_key = jnp.where(valid, labels, n_lists)
    order = jnp.argsort(sort_key, stable=True)
    sl = labels[order]
    svalid = sl >= 0
    sl_safe = jnp.where(svalid, sl, 0)
    added = jax.ops.segment_sum(
        svalid.astype(jnp.int32), sl_safe, num_segments=n_lists)
    starts = jnp.cumsum(added) - added
    pos = jnp.arange(nrows, dtype=jnp.int32) - starts[sl_safe] + counts[sl_safe]
    ok = svalid & (pos < cap)
    dest = jnp.where(ok, sl_safe * cap + pos, n_lists * cap)
    out = []
    for slab, arr in zip(slabs, payloads):
        flat = slab.reshape((n_lists * cap,) + slab.shape[2:])
        flat = flat.at[dest].set(arr[order], mode="drop")
        out.append(flat.reshape(slab.shape))
    new_counts = jnp.minimum(counts + added, cap)
    return tuple(out), new_counts.astype(jnp.int32)


scatter_append = partial(jax.jit, static_argnames=("n_lists", "cap"),
                         donate_argnums=(0, 1))(_scatter_append_impl)
scatter_append_copy = partial(jax.jit, static_argnames=("n_lists", "cap"))(
    _scatter_append_impl)


#: fixed row bucket the online ``extend()`` paths pad every insert batch
#: to — one chunk-step executable serves every insert size (zero
#: steady-state retraces; the serve ladder's counterpart for writes)
DEFAULT_INSERT_CHUNK = 1024


def host_rows(a):
    """Materialize a row batch on host as numpy — an EXPLICIT
    ``device_get`` for jax arrays (passes
    ``jax.transfer_guard("disallow")``), zero-copy for numpy/memmap."""
    import numpy as np

    if isinstance(a, jax.Array):
        return np.asarray(jax.device_get(a))  # jaxlint: disable=JX01 explicit host staging: callers slice insert chunks on host before a non-blocking device_put
    return np.asarray(a)


def staged_insert_chunks(x, ids, chunk: int, dtype):
    """Stage an in-memory insert batch as fixed-shape device chunks for
    the online ``extend()`` streams: rows are host-padded to a multiple
    of ``chunk`` with id −1 (pad rows never request a list, never consume
    capacity — the fused chunk steps mask them), so ONE executable serves
    every insert size.  ``device_put`` is an explicit transfer — the
    consumer loop stays clean under ``jax.transfer_guard("disallow")``.

    The streaming-build analog is :func:`prefetch_chunks_padded`; this
    variant skips the read pipeline (the batch is already in memory) and
    never clamps ``chunk`` to the batch size — the fixed shape IS the
    zero-retrace contract."""
    import numpy as np

    n = x.shape[0]
    total = -(-n // chunk) * chunk
    xh = np.zeros((total, x.shape[1]), dtype)
    xh[:n] = x
    ih = np.full((total,), -1, np.int32)
    ih[:n] = ids
    for lo in range(0, total, chunk):
        yield (jax.device_put(xh[lo:lo + chunk]),
               jax.device_put(ih[lo:lo + chunk]))


@partial(jax.jit, static_argnames=("shape", "fill", "dtype"))
def device_full(shape, fill, dtype):
    """Allocate a filled device buffer via a compiled program rather than
    an eager ``jnp.full`` — eager fill broadcasts a HOST scalar, an
    implicit H2D transfer that trips ``jax.transfer_guard("disallow")``
    (:class:`~raft_tpu.core.TraceGuard`).  Used for the streaming builds'
    slab initialisation so the whole build is guard-clean."""
    return jnp.full(shape, fill, dtype)
