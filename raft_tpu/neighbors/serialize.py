"""ANN index persistence — the checkpoint/resume story for the index
family (SURVEY.md §5.4: the reference ships mdspan↔``.npy`` streams,
``core/serialize.hpp:26,73``, which downstream libraries use for index
save/load; here the same on-disk building block backs first-class
``save_index``/``load_index``).

Layout: one directory per index — a ``.npy`` file per array field plus a
``meta.json`` carrying the index type, static fields, and a format
version (``core.serialize.save_arrays``).  Everything is plain NumPy on
disk: artifacts are portable, inspectable, and loadable without JAX.

Durability tier (ISSUE 7): per-array CRC32s ride ``meta.json``, writes
stage into a temp directory + fsync + one atomic rename (a crash never
leaves a half-written index where a reader looks), :func:`verify_index`
detects truncation/bit-flips without constructing an index, and a
``manifest`` (e.g. the WAL LSN watermark a snapshot is consistent with,
``neighbors.wal``) travels inside the metadata.  ``mutation.Tombstoned``
views and raw brute-force (n, d) databases serialize through the same
entry points, so every serving family has a snapshot story.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Union

import jax
import numpy as np

from ..core.serialize import load_arrays, save_arrays, verify_arrays

__all__ = ["save_index", "load_index", "verify_index", "index_manifest",
           "save_index_checkpoint", "load_index_checkpoint"]

# Readers accept <= _FORMAT_VERSION.  Writers stamp the LOWEST version
# that can faithfully represent the artifact (_artifact_version), so only
# genuinely new-format artifacts (4-bit packed codes, v2; tombstoned /
# brute-force wrappers, v3; 1-bit RaBitQ sign codes, v4; the out-of-core
# manifest-directory layout, v5) are rejected by older readers —
# everything else stays interchangeable.
_FORMAT_VERSION = 5

#: index_type names handled structurally rather than via the dataclass
#: registry: a raw (n, d) database, the tombstoned wrapper, and the
#: out-of-core manifest directory (device bundle + shard store — its
#: layout lives in :mod:`raft_tpu.neighbors.ooc`)
_BRUTE_TYPE = "BruteForce"
_TOMBSTONED_TYPE = "Tombstoned"
_OOC_TYPE = "OocIndex"
_KEEP_FIELD = "__keep_words"


def _artifact_version(index) -> int:
    from .ivf_rabitq import IvfRabitqIndex
    from .mutation import Tombstoned
    from .ooc import OocIndex

    if isinstance(index, OocIndex):
        return 5
    if isinstance(index, IvfRabitqIndex):
        return 4
    if isinstance(index, Tombstoned) or not hasattr(index, "metric"):
        return 3
    return 2 if getattr(index, "packed", False) else 1


def _index_registry():
    from .cagra import CagraIndex, ShardedCagraIndex
    from .ivf_flat import IvfFlatIndex
    from .ivf_pq import IvfPqIndex
    from .ivf_rabitq import IvfRabitqIndex

    return {c.__name__: c for c in
            (IvfFlatIndex, IvfPqIndex, IvfRabitqIndex,
             CagraIndex, ShardedCagraIndex)}


def _validate_meta(meta, path):
    """Shared metadata gate for both artifact tiers → the index class
    (None for the structural types: brute-force / tombstoned)."""
    type_name = meta.get("index_type")
    registry = _index_registry()
    if type_name not in registry and type_name not in (
            _BRUTE_TYPE, _TOMBSTONED_TYPE, _OOC_TYPE):
        raise ValueError(f"{path!r}: unknown or missing index_type {type_name!r}")
    if meta.get("format_version", 0) > _FORMAT_VERSION:
        raise ValueError(f"{path!r}: format_version {meta['format_version']} "
                         f"is newer than supported {_FORMAT_VERSION}")
    return registry.get(type_name)


def _index_meta(index, manifest=None):
    """The meta dict for any serializable index shape; returns
    ``(arrays, meta)`` with arrays as numpy."""
    from .mutation import Tombstoned

    if isinstance(index, Tombstoned):
        arrays, meta = _index_meta(index.index, manifest)
        assert _KEEP_FIELD not in arrays
        arrays[_KEEP_FIELD] = np.asarray(index.keep.words)
        meta = dict(meta, index_type=_TOMBSTONED_TYPE,
                    # the wrapper needs v3; a wrapped index may need more
                    # (RaBitQ, v4) — stamp whichever is newer
                    format_version=max(3, meta["format_version"]),
                    tombstone={"wrapped_type": meta["index_type"],
                               "n_bits": int(index.keep.n_bits)})
        return arrays, meta
    if isinstance(index, (jax.Array, np.ndarray)):
        if np.ndim(index) != 2:
            raise TypeError("a brute-force database must be a 2-D array")
        return {"data": np.asarray(index)}, {
            "index_type": _BRUTE_TYPE, "format_version": 3,
            "static": {}, "derived_present": [],
            "manifest": dict(manifest or {}),
        }
    cls = type(index)
    if cls.__name__ not in _index_registry():
        raise TypeError(f"not a serializable index type: {cls.__name__}")
    # derived fields (e.g. IVF-PQ's bf16 reconstruction slab) are rebuilt
    # from the persisted state on load — writing them would double the
    # artifact and defeat PQ compression on disk
    arrays, static, derived = _split_fields(index)
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    return arrays, {
        "index_type": cls.__name__,
        "format_version": _artifact_version(index),
        "static": static,
        "derived_present": [f for f in derived
                            if getattr(index, f, None) is not None],
        "manifest": dict(manifest or {}),
    }


def save_index(path: Union[str, os.PathLike], index, *,
               manifest: Optional[dict] = None, atomic: bool = True,
               fsync: bool = True) -> None:
    """Persist any serving index — the ANN index dataclasses (IVF-Flat,
    IVF-PQ, CAGRA, sharded CAGRA), a raw (n, d) brute-force database, or
    a ``mutation.Tombstoned`` view of any of them — to a directory of
    ``.npy`` files + JSON metadata.

    Crash-consistent by default: every array carries a CRC32, files are
    fsynced, and the bundle is staged in a temp directory and published
    by one atomic rename — a reader (or :func:`verify_index`) never sees
    a torn artifact.  ``manifest`` attaches caller metadata (the WAL LSN
    watermark for ``neighbors.wal`` snapshots).

    An out-of-core :class:`~raft_tpu.neighbors.ooc.OocIndex` routes to
    its v5 manifest-directory layout (device bundle + shard store;
    ``atomic`` applies to the device bundle and the meta publish — the
    shard files copy in place first)."""
    from .ooc import OocIndex
    from . import ooc as _ooc

    if isinstance(index, OocIndex):
        _ooc.save(path, index, manifest=manifest, fsync=fsync)
        return
    arrays, meta = _index_meta(index, manifest)
    save_arrays(path, arrays, metadata=meta, atomic=atomic, fsync=fsync)


def load_index(path: Union[str, os.PathLike], *, device: bool = True,
               verify: bool = False):
    """Load an index saved by :func:`save_index`.  ``device=True`` places
    array fields on the default device; ``device=False`` keeps NumPy
    (useful to inspect or re-shard before transfer).  ``verify=True``
    checks every array's CRC32 first (``core.serialize.CorruptArtifact``
    on mismatch — the recovery path quarantines instead of parsing)."""
    if _peek_index_type(path) == _OOC_TYPE:
        from . import ooc as _ooc

        return _ooc.open(path, verify=verify)
    arrays, meta = load_arrays(path, verify=verify)
    return _index_from_parts(arrays, meta, path, device)


def _peek_index_type(path):
    """index_type of the artifact at ``path`` without array IO — reads
    ``meta.json`` only.  Both layouts answer: the v5 out-of-core
    manifest carries ``index_type`` at top level, ``save_arrays``
    bundles nest it under ``metadata``."""
    import json

    try:
        with open(os.path.join(os.fspath(path), "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    return meta.get("index_type") or \
        (meta.get("metadata") or {}).get("index_type")


def _index_from_parts(arrays, meta, path, device: bool):
    cls = _validate_meta(meta, path)
    if meta.get("index_type") == _TOMBSTONED_TYPE:
        from ..core.bitset import Bitset
        from .mutation import Tombstoned

        ts = meta.get("tombstone") or {}
        words = arrays.pop(_KEEP_FIELD)
        inner_meta = dict(meta, index_type=ts.get("wrapped_type"))
        inner = _index_from_parts(arrays, inner_meta, path, device)
        keep = Bitset(jnp_words(words, device), int(ts["n_bits"]))
        return Tombstoned(inner, keep)
    if meta.get("index_type") == _BRUTE_TYPE:
        data = arrays["data"]
        return jax.device_put(data) if device else data
    fields = dict(meta.get("static", {}))
    for name, arr in arrays.items():
        fields[name] = jax.device_put(arr) if device else arr
    index = cls(**fields)
    if device:
        index = _rebuild_derived(index, meta)
    return index


def jnp_words(words, device: bool):
    """Bitset words as the right array type for the load mode."""
    return jax.device_put(words) if device else np.asarray(words)


def index_manifest(path: Union[str, os.PathLike]) -> dict:
    """The ``manifest`` dict a :func:`save_index` artifact was written
    with (empty for pre-durability artifacts) — read from ``meta.json``
    only, no array IO."""
    import json

    with open(os.path.join(os.fspath(path), "meta.json")) as f:
        meta = json.load(f)
    # save_arrays bundles nest the index meta; the v5 out-of-core layout
    # carries its manifest at top level
    return dict((meta.get("metadata") or {}).get("manifest")
                or meta.get("manifest") or {})


def verify_index(path: Union[str, os.PathLike]) -> List[str]:
    """Integrity-check a :func:`save_index` artifact without constructing
    an index: metadata well-formed, index type known, every array file
    present with a matching CRC32 (detects truncation AND bit-flips).
    Returns a list of problems — empty means the artifact is loadable.
    Recovery (``neighbors.wal.DurableStore``) quarantines any snapshot
    this flags instead of parsing it into a live index."""
    import json

    path = os.fspath(path)
    if _peek_index_type(path) == _OOC_TYPE:
        from . import ooc as _ooc

        return _ooc.verify(path)
    problems = verify_arrays(path)
    if any("meta.json" in p for p in problems):
        return problems
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    try:
        _validate_meta(meta.get("metadata") or {}, path)
    except ValueError as exc:
        problems.append(str(exc))
    return problems


def _rebuild_derived(index, meta):
    """Rebuild exactly the derived search tiers the artifact carried when
    it was saved (``derived_present``) — a ``store_recon=False`` index must
    not grow a recon slab on load.  The hoisted-ADC tables ride the same
    mechanism; artifacts from before they existed rebuild them too (they
    are cheap, and LUT search derives them on the fly otherwise), keeping
    old artifacts fully usable without a format-version bump."""
    present = set(meta.get("derived_present") or ())
    if "recon" in present and hasattr(index, "with_recon"):
        index = index.with_recon()
    if hasattr(index, "with_adc_luts"):
        index = index.with_adc_luts()
    return index


# ---------------------------------------------------------------------------
# Orbax tier: sharded, parallel, multi-host checkpointing.  The ``.npy``
# tier above funnels every shard through one host (np.asarray); this tier
# writes each host's shards in parallel — the TPU-native equivalent of the
# role SURVEY.md §5.4 sketches ("orbax-style checkpoint of index arrays +
# metadata header").
# ---------------------------------------------------------------------------


def _multihost_barrier(tag: str) -> None:
    """No-op in single-process runs; a device-sync barrier across hosts
    otherwise (meta.json has exactly one writer, readers must wait)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _split_fields(index):
    cls = type(index)
    derived = tuple(getattr(cls, "_derived_fields", ()))
    arrays, static = {}, {}
    for f in dataclasses.fields(index):
        if f.name in derived:
            continue
        v = getattr(index, f.name)
        if isinstance(v, (jax.Array, np.ndarray)):
            arrays[f.name] = v
        else:
            static[f.name] = v
    return arrays, static, derived


def save_index_checkpoint(path: Union[str, os.PathLike], index) -> None:
    """Persist an index via orbax — sharded ``jax.Array`` fields are
    written by their owning hosts in parallel (no single-host funnel,
    unlike :func:`save_index`'s portable ``.npy`` tier).  Layout:
    ``<path>/arrays`` (orbax checkpoint) + ``<path>/meta.json``."""
    import json

    import orbax.checkpoint as ocp

    cls = type(index)
    if cls.__name__ not in _index_registry():
        raise TypeError(f"not a serializable index type: {cls.__name__}")
    arrays, static, derived = _split_fields(index)
    path = os.path.abspath(os.fspath(path))
    if jax.process_index() == 0:
        os.makedirs(path, exist_ok=True)
    _multihost_barrier("raft_tpu:ckpt_mkdir")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
    if jax.process_index() != 0:  # one writer for the shared meta file
        _multihost_barrier("raft_tpu:ckpt_meta")
        return
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({
            "index_type": cls.__name__,
            "format_version": _artifact_version(index),
            "static": static,
            "derived_present": [g for g in derived
                                if getattr(index, g, None) is not None],
            # shapes/dtypes let load build abstract arrays for direct
            # sharded restore without relying on orbax-internal metadata
            "array_meta": {name: {"shape": list(np.shape(a)),
                                  "dtype": str(np.dtype(a.dtype))}
                           for name, a in arrays.items()},
        }, f)
    _multihost_barrier("raft_tpu:ckpt_meta")


def load_index_checkpoint(path: Union[str, os.PathLike], *, shardings=None):
    """Load a :func:`save_index_checkpoint` artifact.

    ``shardings``: optional ``{field_name: jax.sharding.NamedSharding}``
    — fields restore *directly* into that placement (each host reads
    only its shards; the multi-host restore path).  Unlisted fields
    restore replicated over the same mesh, so every field lives on one
    device set (mixed placements would fail the first jitted consumer).
    """
    import json

    import orbax.checkpoint as ocp

    path = os.path.abspath(os.fspath(path))
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    cls = _validate_meta(meta, path)
    adir = os.path.join(path, "arrays")
    with ocp.StandardCheckpointer() as ckptr:
        if shardings:
            # direct sharded restore: each host reads only its shards
            am = meta.get("array_meta") or {}
            if not am:
                raise ValueError(
                    f"{path!r}: artifact predates array_meta; re-save with "
                    "save_index_checkpoint to enable sharded restore")
            unknown = set(shardings) - set(am)
            if unknown:  # a typo'd key would silently restore replicated
                raise ValueError(
                    f"shardings for unknown fields {sorted(unknown)}; "
                    f"artifact has {sorted(am)}")
            for name, s in shardings.items():
                if not hasattr(s, "mesh"):
                    raise TypeError(
                        f"shardings[{name!r}] must be a NamedSharding "
                        "(mesh-based); got "
                        f"{type(s).__name__}")
            # unlisted fields restore REPLICATED over the same mesh —
            # mixing sharded fields with single-device ones would fail
            # the first jitted consumer (e.g. with_recon's decode)
            from jax.sharding import NamedSharding, PartitionSpec

            mesh = next(iter(shardings.values())).mesh
            replicated = NamedSharding(mesh, PartitionSpec())
            abstract = {
                name: jax.ShapeDtypeStruct(tuple(m["shape"]),
                                           np.dtype(m["dtype"]),
                                           sharding=shardings.get(
                                               name, replicated))
                for name, m in am.items()
            }
            arrays = ckptr.restore(adir, abstract)
        else:
            arrays = ckptr.restore(adir)
    fields = dict(meta.get("static", {}))
    for name, arr in arrays.items():
        fields[name] = arr if isinstance(arr, jax.Array) \
            else jax.device_put(arr)
    index = cls(**fields)
    return _rebuild_derived(index, meta)
