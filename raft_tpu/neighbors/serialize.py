"""ANN index persistence — the checkpoint/resume story for the index
family (SURVEY.md §5.4: the reference ships mdspan↔``.npy`` streams,
``core/serialize.hpp:26,73``, which downstream libraries use for index
save/load; here the same on-disk building block backs first-class
``save_index``/``load_index``).

Layout: one directory per index — a ``.npy`` file per array field plus a
``meta.json`` carrying the index type, static fields, and a format
version (``core.serialize.save_arrays``).  Everything is plain NumPy on
disk: artifacts are portable, inspectable, and loadable without JAX.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Union

import jax
import numpy as np

from ..core.serialize import load_arrays, save_arrays

__all__ = ["save_index", "load_index"]

_FORMAT_VERSION = 1


def _index_registry():
    from .cagra import CagraIndex, ShardedCagraIndex
    from .ivf_flat import IvfFlatIndex
    from .ivf_pq import IvfPqIndex

    return {c.__name__: c for c in
            (IvfFlatIndex, IvfPqIndex, CagraIndex, ShardedCagraIndex)}


def save_index(path: Union[str, os.PathLike], index) -> None:
    """Persist any of the ANN index dataclasses (IVF-Flat, IVF-PQ, CAGRA,
    sharded CAGRA) to a directory of ``.npy`` files + JSON metadata."""
    cls = type(index)
    if cls.__name__ not in _index_registry():
        raise TypeError(f"not a serializable index type: {cls.__name__}")
    # derived fields (e.g. IVF-PQ's bf16 reconstruction slab) are rebuilt
    # from the persisted state on load — writing them would double the
    # artifact and defeat PQ compression on disk
    derived = tuple(getattr(cls, "_derived_fields", ()))
    arrays, static = {}, {}
    for f in dataclasses.fields(index):
        if f.name in derived:
            continue
        v = getattr(index, f.name)
        if isinstance(v, (jax.Array, np.ndarray)):
            arrays[f.name] = np.asarray(v)
        else:
            static[f.name] = v
    save_arrays(path, arrays, metadata={
        "index_type": cls.__name__,
        "format_version": _FORMAT_VERSION,
        "static": static,
        "derived_present": [f for f in derived
                            if getattr(index, f, None) is not None],
    })


def load_index(path: Union[str, os.PathLike], *, device: bool = True):
    """Load an index saved by :func:`save_index`.  ``device=True`` places
    array fields on the default device; ``device=False`` keeps NumPy
    (useful to inspect or re-shard before transfer)."""
    arrays, meta = load_arrays(path)
    type_name = meta.get("index_type")
    registry = _index_registry()
    if type_name not in registry:
        raise ValueError(f"{path!r}: unknown or missing index_type {type_name!r}")
    if meta.get("format_version", 0) > _FORMAT_VERSION:
        raise ValueError(f"{path!r}: format_version {meta['format_version']} "
                         f"is newer than supported {_FORMAT_VERSION}")
    fields = dict(meta.get("static", {}))
    for name, arr in arrays.items():
        fields[name] = jax.device_put(arr) if device else arr
    index = registry[type_name](**fields)
    if meta.get("derived_present") and device and hasattr(index, "with_recon"):
        index = index.with_recon()  # rebuild the derived search tier
    return index
