"""Brute-force (exact) k-nearest-neighbors — the ``neighbors::brute_force``
capability (north-star config #2: SIFT-1M).  No CUDA ancestor in-tree; design
follows the TPU-KNN paper (PAPERS.md): distances in MXU-sized tiles, top-k
merged in a running candidate buffer so HBM never holds the (m, n) matrix.

Single-chip: ``knn``.  Multi-chip: ``knn_sharded`` — database rows sharded
over one mesh axis, each shard computes a local top-k, candidates are
``all_gather``-ed over ICI and merged (the TPU analog of the reference's MNMG
index shards + allgather over ``comms_t``, SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["knn", "knn_sharded", "tile_knn_merge"]

_NEG_INF = jnp.float32(-jnp.inf)


def _tile_distances(x, yt, metric: str, xn=None):
    """(m, tile) distance block; smaller-is-nearer for all metrics here."""
    # HIGHEST: default bf16 MXU passes are coarser than neighbor gaps
    dots = jnp.dot(
        x, yt.T, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    if metric == "inner_product":
        return -dots  # larger dot = nearer → negate so min-select works
    ytf = yt.astype(jnp.float32)
    yn = jnp.sum(ytf * ytf, axis=1)
    if metric in ("sqeuclidean", "euclidean"):
        d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * dots, 0.0)
        return jnp.sqrt(d2) if metric == "euclidean" else d2
    if metric == "cosine":
        xnorm = jnp.sqrt(jnp.maximum(xn, 1e-30))
        ynorm = jnp.sqrt(jnp.maximum(yn, 1e-30))
        return 1.0 - dots / (xnorm[:, None] * ynorm[None, :])
    raise ValueError(f"unsupported brute-force metric {metric!r}")


def tile_knn_merge(best_val, best_idx, tile_val, tile_idx, k: int):
    """Merge a new candidate block into the running (m, k) best buffers via
    ``matrix.select_k`` — one selection primitive owns all top-k tuning."""
    from ..matrix.select_k import select_k

    vals = jnp.concatenate([best_val, tile_val], axis=1)
    idxs = jnp.concatenate([best_idx, tile_idx], axis=1)
    return select_k(vals, k, in_idx=idxs, select_min=True)


@partial(jax.jit, static_argnames=("k", "metric", "tile"))
def _knn_impl(x, y, k: int, metric: str, tile: int) -> Tuple[jax.Array, jax.Array]:
    m, d = x.shape
    n = y.shape[0]
    pad = (-n) % tile
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad, d), y.dtype)], axis=0)
    ytiles = y.reshape(-1, tile, d)
    xf = x.astype(jnp.float32)
    xn = jnp.sum(xf * xf, axis=1)

    kk = min(k, tile)

    def step(carry, inp):
        best_val, best_idx = carry
        t, yt = inp
        dist = _tile_distances(x, yt, metric, xn)
        col = t * tile + jnp.arange(tile)
        dist = jnp.where(col[None, :] < n, dist, jnp.inf)
        neg, loc = jax.lax.top_k(-dist, kk)
        tv, ti = -neg, t * tile + loc
        return tile_knn_merge(best_val, best_idx, tv, ti, k), None

    init = (
        jnp.full((m, k), jnp.inf, jnp.float32),
        jnp.zeros((m, k), jnp.int32),
    )
    (bv, bi), _ = jax.lax.scan(
        step, init, (jnp.arange(ytiles.shape[0], dtype=jnp.int32), ytiles)
    )
    if metric == "inner_product":
        bv = -bv  # undo the similarity negation
    return bv, bi


def knn(
    queries,
    database,
    k: int,
    *,
    metric: str = "sqeuclidean",
    tile: int = 8192,
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN: returns ``(distances, indices)`` of shape (n_queries, k),
    nearest first.  ``metric`` ∈ {sqeuclidean, euclidean, cosine,
    inner_product}."""
    x = wrap_array(queries, ndim=2, name="queries")
    y = wrap_array(database, ndim=2, name="database")
    expects(x.shape[1] == y.shape[1], f"dim mismatch {x.shape} vs {y.shape}")
    expects(k >= 1, "k must be >= 1")
    expects(k <= y.shape[0], f"k={k} exceeds database size {y.shape[0]}")
    return _knn_impl(x, y, int(k), metric, int(min(tile, max(y.shape[0], 1))))


@functools.lru_cache(maxsize=64)
def _sharded_knn_program(mesh: Mesh, axis: str, rows: int, k: int, kk: int, metric: str, tile: int):
    """Compile-once sharded search: jit keyed on the static config instead of
    a per-call closure (which would re-trace every knn_sharded call)."""
    nsh = mesh.shape[axis]

    def local(xq, ysh):
        # ysh: (1, rows, d) block of this shard
        ysh = ysh[0]
        shard = jax.lax.axis_index(axis)
        v, i = _knn_impl(xq, ysh, kk, metric, tile)
        if metric == "inner_product":
            v = -v  # back to smaller-is-nearer for the cross-shard merge
        gi = i + shard * rows
        # gather all shards' candidates: (nsh, m, kk)
        gv = jax.lax.all_gather(v, axis)
        gidx = jax.lax.all_gather(gi, axis)
        m = xq.shape[0]
        gv = jnp.moveaxis(gv, 0, 1).reshape(m, nsh * kk)
        gidx = jnp.moveaxis(gidx, 0, 1).reshape(m, nsh * kk)
        neg, pos = jax.lax.top_k(-gv, k)
        out_v = -neg
        if metric == "inner_product":
            out_v = -out_v
        return out_v, jnp.take_along_axis(gidx, pos, axis=1)

    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def knn_sharded(
    queries,
    database,
    k: int,
    *,
    mesh: Mesh,
    axis: str = "shard",
    metric: str = "sqeuclidean",
    tile: int = 8192,
) -> Tuple[jax.Array, jax.Array]:
    """Database-sharded exact kNN over a mesh axis.

    Each device holds ``n/n_shards`` database rows (queries replicated),
    computes a local top-k with *global* index numbering, then candidates are
    gathered over ICI and merged.  One all_gather of (m, k) per shard — tiny
    vs. the distance FLOPs, so this scales ~linearly until queries replicate
    poorly.
    """
    x = wrap_array(queries, ndim=2, name="queries")
    y = wrap_array(database, ndim=2, name="database")
    nsh = mesh.shape[axis]
    n = y.shape[0]
    expects(n % nsh == 0, f"database rows {n} not divisible by mesh axis {nsh}")
    rows = n // nsh
    kk = min(k, rows)
    fn = _sharded_knn_program(mesh, axis, rows, int(k), kk, metric, int(min(tile, rows)))
    yb = y.reshape(nsh, rows, y.shape[1])
    return fn(x, yb)
