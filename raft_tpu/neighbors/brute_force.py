"""Brute-force k-nearest-neighbors — the ``neighbors::brute_force``
capability (north-star config #2: SIFT-1M).  No CUDA ancestor in-tree; design
follows the TPU-KNN paper (PAPERS.md): distances in MXU-sized tiles, top-k
merged in a running candidate buffer so HBM never holds the (m, n) matrix.

Two single-chip modes:

* ``mode="exact"`` — f32 distances at ``Precision.HIGHEST`` (bf16x6 MXU
  passes), exact ``top_k`` per tile.  Bit-accurate ranking.
* ``mode="fast"`` — single-pass bf16 MXU distances feeding the fused
  Pallas shortlist kernel (``ops.pallas.fused_l2_topk``; never
  materializes distances in HBM), then **exact f32 re-scoring** of the
  shortlist.  Measured recall@10 ≥ 0.999 on 1M×128 (misses need a 3-way
  bucket collision among the true top-k) at ~3.5× exact-mode QPS.  Falls
  back to an XLA ``approx_max_k`` shortlist off-TPU.

Multi-chip: ``knn_sharded`` — database rows sharded over one mesh axis,
each shard computes a local top-k, candidates are ``all_gather``-ed over
ICI and merged (the TPU analog of the reference's MNMG index shards +
allgather over ``comms_t``, SURVEY.md §5.7).
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import tracing
from ..core.array import wrap_array
from ..core.compat import shard_map
from ..core.errors import expects
from ..ops.blocked_scan import row_sq_norms as _scan_norms

__all__ = ["knn", "knn_sharded", "searcher", "tile_knn_merge",
           "fleet_slices", "BruteFleetSlices"]

_NEG_INF = jnp.float32(-jnp.inf)


def _metric_from_dots(dots, xn, yn, metric: str):
    """Smaller-is-nearer distance from precomputed dot products and squared
    norms.  ``xn``: (m,); ``yn`` must already broadcast against ``dots``
    ((tile,)→[None, :] for tiles, (m, cand) for gathered candidates).
    Single home of the per-metric algebra for both the tiled exact path
    and the fast-mode refine."""
    if metric == "inner_product":
        return -dots  # larger dot = nearer → negate so min-select works
    if metric in ("sqeuclidean", "euclidean"):
        d2 = jnp.maximum(xn[:, None] + yn - 2.0 * dots, 0.0)
        return jnp.sqrt(d2) if metric == "euclidean" else d2
    if metric == "cosine":
        xnorm = jnp.sqrt(jnp.maximum(xn, 1e-30))
        ynorm = jnp.sqrt(jnp.maximum(yn, 1e-30))
        return 1.0 - dots / (xnorm[:, None] * ynorm)
    raise ValueError(f"unsupported brute-force metric {metric!r}")


def _tile_distances(x, yt, metric: str, xn=None):
    """(m, tile) distance block; smaller-is-nearer for all metrics here."""
    # HIGHEST: default bf16 MXU passes are coarser than neighbor gaps —
    # except for 8-bit corpora, where one bf16 pass is already exact
    # (values are bf16-exact, products accumulate in f32; see
    # ops.blocked_scan.exact_gathered_dots) at ~6x the MXU rate
    from ..ops.blocked_scan import exact_gathered_dots

    dots = exact_gathered_dots("md,nd->mn", x, yt)
    if metric == "inner_product":
        return _metric_from_dots(dots, None, None, metric)
    ytf = yt.astype(jnp.float32)
    yn = _scan_norms(ytf)
    return _metric_from_dots(dots, xn, yn[None, :], metric)


# the running-buffer merge moved to the shared blocked-scan core as
# fold_topk (same signature/semantics); alias retained for existing callers
from ..ops.blocked_scan import fold_topk as tile_knn_merge  # noqa: E402


@partial(jax.jit, static_argnames=("k", "metric", "tile"))
def _knn_impl(x, y, k: int, metric: str, tile: int,
              keep=None) -> Tuple[jax.Array, jax.Array]:
    m, d = x.shape
    n = y.shape[0]
    pad = (-n) % tile
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad, d), y.dtype)], axis=0)
    ytiles = y.reshape(-1, tile, d)
    keep_xs = None
    if keep is not None:  # bitset/bool (n,) or per-query bitmap (m, n)
        if keep.ndim == 1:
            keep_t = jnp.pad(keep, (0, pad),
                             constant_values=False).reshape(-1, tile)
        else:  # (m, n) → scan xs of (n_tiles, m, tile) per-query tiles
            keep_xs = jnp.moveaxis(
                jnp.pad(keep, ((0, 0), (0, pad)), constant_values=False)
                .reshape(m, -1, tile), 1, 0)
    xf = x.astype(jnp.float32)
    xn = _scan_norms(xf)

    kk = min(k, tile)

    def score(inp):
        t, yt, kt = inp
        dist = _tile_distances(x, yt, metric, xn)
        col = t * tile + jnp.arange(tile)
        valid = col[None, :] < n
        if keep is not None:
            valid = valid & (keep_t[t][None, :] if kt is None else kt)
        dist = jnp.where(valid, dist, jnp.inf)
        # pre-cut each tile to kk before the fold: the top-k over
        # (carry ∪ tile) equals top-k over (carry ∪ top-kk(tile)), and the
        # fold then merges k+kk lanes instead of k+tile
        neg, loc = jax.lax.top_k(-dist, kk)
        return -neg, t * tile + loc

    from ..ops.blocked_scan import scan_topk

    bv, bi = scan_topk(
        score,
        (jnp.arange(ytiles.shape[0], dtype=jnp.int32), ytiles, keep_xs),
        m, k, id_fill=0)
    if metric == "inner_product":
        bv = -bv  # undo the similarity negation
    return bv, bi


def _exact_candidate_distances(x, yc, metric: str, precision=None):
    """Exact f32 metric between each query and its (cand,) gathered rows.
    ``yc``: (m, cand, d).  ``precision`` defaults to HIGHEST (bf16x6 MXU
    passes); pass ``jax.lax.Precision.HIGH`` (bf16x3) to trade the last
    ~0.5 ulp of the rescore for ~2× einsum throughput — the refine stage
    re-ranks a shortlist whose gaps are usually ≫ bf16x3 error, so HIGH
    is the first knob of the fast-path tuning tree (docs/perf_analysis.md)."""
    xf = x.astype(jnp.float32)
    ycf = yc.astype(jnp.float32)
    from ..ops.blocked_scan import exact_gathered_dots, int8_tier_eligible

    if int8_tier_eligible(yc, x, x.shape[1]):
        # 8-bit pair: one bf16 pass is exact (see exact_gathered_dots)
        dots = exact_gathered_dots("mcd,md->mc", yc, x)
    else:
        dots = jnp.einsum("md,mcd->mc", xf, ycf,
                          precision=precision or jax.lax.Precision.HIGHEST)
    if metric == "inner_product":
        return _metric_from_dots(dots, None, None, metric)
    xn = _scan_norms(xf)
    yn = _scan_norms(ycf)
    return _metric_from_dots(dots, xn, yn, metric)


@partial(jax.jit, static_argnames=("k", "metric", "cand", "bm", "bn", "cut",
                                   "refine_precision"))
def _fast_knn_impl(x, y, k: int, metric: str, cand: int, bm: int, bn: int,
                   keep=None, cut: str = "exact",
                   refine_precision: str = "highest"):
    """bf16 shortlist (fused Pallas kernel on TPU, XLA approx_max_k
    elsewhere) + exact f32 refine.  Smaller-is-nearer surrogate:
    ``‖y‖² − 2·x·yᵀ`` for L2/cosine-normalized data, ``−x·yᵀ`` for
    inner product (yn ≡ 0).  The prefilter rides the norm vector: a
    filtered row's ``yn = +inf`` makes its surrogate +inf, so it can
    never enter the shortlist (and the refine's isfinite guard drops
    any that slip through a padded slot)."""
    m, d = x.shape
    n = y.shape[0]
    if metric == "cosine":
        # normalize in f32: integer squares would wrap in-dtype (200² mod
        # 256), so cast before the norm sums
        xf32, yf32 = x.astype(jnp.float32), y.astype(jnp.float32)
        xs = xf32 / jnp.sqrt(jnp.maximum(
            jnp.sum(xf32 * xf32, axis=1, keepdims=True), 1e-30))
        ys = yf32 / jnp.sqrt(jnp.maximum(
            jnp.sum(yf32 * yf32, axis=1, keepdims=True), 1e-30))
    else:
        xs, ys = x, y
    # int8 MXU path: BOTH sides must be the same integer dtype, and only
    # for L2 metrics (centering shifts inner-product rankings per row)
    integer = (xs.dtype == ys.dtype and ys.dtype in (jnp.uint8, jnp.int8)
               and metric != "inner_product")
    if metric == "inner_product":
        yn = jnp.zeros((n,), jnp.float32)
    elif integer:
        # center uint8 to int8 once, fold the correction into the
        # surrogate norms (per-query terms drop out of the ranking); the
        # CPU fallback scores the same centered values in bf16
        from ..ops.pallas.fused_l2_topk import center_int8, int8_surrogate_norms

        yn = int8_surrogate_norms(ys)
        xs, ys = center_int8(xs), center_int8(ys)
    else:
        ysf = ys.astype(jnp.float32)
        yn = jnp.sum(ysf * ysf, axis=1)
    if not integer and (jnp.issubdtype(xs.dtype, jnp.integer)
                        or jnp.issubdtype(ys.dtype, jnp.integer)):
        # mixed or non-L2 integer inputs take the float path (≤255 is
        # bf16-exact); also keeps fused_shortlist's dtype-equality contract
        xs, ys = xs.astype(jnp.float32), ys.astype(jnp.float32)
    if keep is not None:
        # 1-D masks ride the norm vector (zero extra cost); a per-query
        # bitmap can only pre-drop rows NO query wants — the per-query
        # part is applied exactly at the refine stage below
        row_keep = keep if keep.ndim == 1 else jnp.any(keep, axis=0)
        yn = jnp.where(row_keep, yn, jnp.inf)

    cand = min(cand, n)
    from ..ops.pallas.gate import dispatch_mode

    if dispatch_mode("fused_l2_topk") == "mosaic":
        # validated TPU only: a stale MOSAIC_CHECK stamp or a wedged
        # platform probe takes the XLA approx path below (gate logs why)
        from ..ops.pallas.fused_l2_topk import fused_shortlist

        sv, si = fused_shortlist(xs, ys, yn, bm=bm, bn=bn)
    else:
        # off-TPU fallback: tiled bf16 surrogate + approx_max_k per tile,
        # so the (m, n) matrix is never materialized here either
        tile = min(65536, n)
        pad = (-n) % tile
        ysb = ys.astype(jnp.bfloat16)
        if pad:
            ysb = jnp.concatenate([ysb, jnp.zeros((pad, d), ysb.dtype)], axis=0)
            yn_p = jnp.concatenate([yn, jnp.full((pad,), jnp.inf, jnp.float32)])
        else:
            yn_p = yn
        xsb = xs.astype(jnp.bfloat16)
        ytiles = ysb.reshape(-1, tile, d)
        kk = min(cand, tile)

        def step(carry, inp):
            t, yt = inp
            dots = jnp.dot(xsb, yt.T, preferred_element_type=jnp.float32)
            yn_t = jax.lax.dynamic_slice_in_dim(yn_p, t * tile, tile)
            surr = yn_t[None, :] - 2.0 * dots
            neg, loc = jax.lax.approx_max_k(-surr, kk)
            return carry, (-neg, t * tile + loc)

        _, (cv, ci) = jax.lax.scan(
            step, 0, (jnp.arange(ytiles.shape[0], dtype=jnp.int32), ytiles))
        sv = jnp.moveaxis(cv, 0, 1).reshape(m, -1)
        si = jnp.moveaxis(ci, 0, 1).reshape(m, -1)
    cand = min(cand, sv.shape[1])
    if cut == "approx":
        # approx_max_k is the TPU-optimized partial reduction (the op the
        # TPU-KNN paper introduced); misses are recovered nowhere, so it
        # trades a sliver of recall for a cheaper (m, 2·bn)→cand cut.
        # The exact f32 rescore below keeps the *ranking* exact either way.
        neg, pos = jax.lax.approx_max_k(-sv, cand, recall_target=0.99)
        sel_sv = -neg
    else:
        # route through select_k so the offline-tuned dispatch table
        # (which covers this (m, 2·bn, cand) bucket) picks the kernel
        from ..matrix.select_k import select_k

        sel_sv, pos = select_k(sv, cand, select_min=True)
    short = jnp.take_along_axis(si, pos, axis=1)
    dc = _exact_candidate_distances(
        x, y[short], metric,
        precision=(jax.lax.Precision.HIGH if refine_precision == "high"
                   else jax.lax.Precision.HIGHEST))
    # shortlist slots that were never filled (inf sentinel, id clamped to 0)
    # must not be re-scored into fake neighbors
    dc = jnp.where(jnp.isfinite(sel_sv), dc, jnp.inf)
    if keep is not None and keep.ndim == 2:
        # per-query bitmap: exact exclusion at the re-ranking stage
        # (cand ≫ k, so dropped candidates rarely starve the top-k)
        dc = jnp.where(jnp.take_along_axis(keep, short, axis=1), dc, jnp.inf)
    negv, p2 = jax.lax.top_k(-dc, k)
    vals = -negv
    if metric == "inner_product":
        vals = -vals  # report similarities, matching exact mode's contract
    return vals, jnp.take_along_axis(short, p2, axis=1)


_excl_cache: dict = {}
_excl_checked: set = set()


def _bitmap_max_exclusions(filter_obj, keep):
    """Worst query's exclusion count among globally-wanted rows — the
    headroom a fast-mode shortlist needs over k (ADVICE r3).  Because
    ``row_keep = any_q keep[q]`` every query's exclusion count among wanted
    rows is ``popcount(row_keep) − popcount(keep[q])``: two row reductions,
    no (nq, n) intermediate.  Memoized per mask object; returns None when
    tracing (abstract mask inside user jit)."""
    from ._packing import cached_by_id

    def compute():
        return int(jnp.sum(jnp.any(keep, axis=0))  # jaxlint: disable=JX01 build-time constant, memoized per mask object; under tracing the ConcretizationTypeError path returns None
                   - jnp.min(jnp.sum(keep, axis=1)))

    try:
        return cached_by_id(_excl_cache, filter_obj, compute)
    except jax.errors.ConcretizationTypeError:
        return None


@tracing.annotate("brute_force.knn")
def knn(
    queries,
    database,
    k: int,
    *,
    metric: str = "sqeuclidean",
    tile: int = 8192,
    mode: str = "exact",
    cand: int = 64,
    cut: str = "exact",
    refine_precision: str = "highest",
    filter=None,
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """kNN: returns ``(distances, indices)`` of shape (n_queries, k),
    nearest first.  ``metric`` ∈ {sqeuclidean, euclidean, cosine,
    inner_product}.  ``mode="exact"`` (default) or ``"fast"`` (bf16 MXU
    shortlist + exact refine; recall@k ≥ ~0.999, ~3.5× faster — see
    module docstring).  ``cand`` is the fast-mode shortlist width
    (≥ 4·k recommended); ``cut`` picks the (m, shortlist)→cand
    reduction — ``"exact"`` (lax.top_k) or ``"approx"``
    (``approx_max_k`` at recall_target 0.99, cheaper on TPU);
    ``refine_precision`` ∈ {"highest", "high"} sets the rescore einsum's
    MXU precision (bf16x6 vs ~2× faster bf16x3 — shortlist gaps usually
    dwarf the extra error; see docs/perf_analysis.md).

    ``filter``: optional prefilter, True = keep (cuVS parity).  Either a
    shared row mask (``core.Bitset`` / (n,) bools — ``bitset_filter``) or
    a PER-QUERY mask (``core.Bitmap`` / (n_queries, n) bools —
    ``bitmap_filter``, e.g. excluding each query's own document set).
    Filtered rows never appear in results; if fewer than k rows pass, the
    tail carries id −1 with ±inf distance.  In ``mode="fast"`` a bitmap's
    per-query exclusions are applied exactly at the re-ranking stage (the
    shortlist is shared across queries), so keep ``cand ≫`` the number of
    per-query exclusions expected inside any query's shortlist.
    """
    x = wrap_array(queries, ndim=2, name="queries")
    y = wrap_array(database, ndim=2, name="database")
    expects(x.shape[1] == y.shape[1], f"dim mismatch {x.shape} vs {y.shape}")
    expects(k >= 1, "k must be >= 1")
    expects(k <= y.shape[0], f"k={k} exceeds database size {y.shape[0]}")
    expects(mode in ("exact", "fast"), f"unknown mode {mode!r}")
    from ._packing import as_keep_mask, sentinel_filtered_ids

    keep = as_keep_mask(filter, y.shape[0], nq=x.shape[0])
    expects(cut in ("exact", "approx"), f"unknown cut {cut!r}")
    # effective shortlist width: the impl clamps cand to the database size,
    # and a whole-database shortlist is exhaustive — it cannot starve
    cand_eff = min(max(cand, k), y.shape[0])
    if mode == "fast" and keep is not None and keep.ndim == 2 \
            and cand_eff < y.shape[0] \
            and (keep.shape, cand_eff, k) not in _excl_checked:
        # serving loops build a FRESH mask per batch (id-cache misses every
        # call) but at a constant shape: checking once per (shape, cand, k)
        # keeps the detection while paying the host sync on the first batch
        # only, never per dispatch
        max_excl = _bitmap_max_exclusions(filter, keep)
        if max_excl is not None:
            if len(_excl_checked) > 4096:
                _excl_checked.clear()
            _excl_checked.add((keep.shape, cand_eff, k))
            if cand_eff < min(k + max_excl, y.shape[0]):
                from ..core.logging import default_logger

                default_logger().warning(
                    "bitmap-filtered fast knn: a query excludes up to %d "
                    "shortlist-eligible rows but cand=%d leaves only %d slots "
                    "of headroom over k=%d; results may carry -1/inf "
                    "sentinels — use cand >= k + max per-query exclusions "
                    "(%d) or mode='exact'",
                    max_excl, cand_eff, cand_eff - k, k,
                    min(k + max_excl, y.shape[0]))
    expects(refine_precision in ("highest", "high"),
            f"unknown refine_precision {refine_precision!r}")
    if mode == "fast":
        vals, ids = _fast_knn_impl(x, y, int(k), metric, int(max(cand, k)),
                                   1024, 1024, keep, cut, refine_precision)
    else:
        vals, ids = _knn_impl(x, y, int(k), metric,
                              int(min(tile, max(y.shape[0], 1))), keep)
    if keep is not None:
        ids = sentinel_filtered_ids(vals, ids)
    return vals, ids


def searcher(database, k: int, *, metric: str = "sqeuclidean",
             mode: str = "exact", tile: int = 8192, cand: int = 64,
             cut: str = "exact", refine_precision: str = "highest",
             filter=None):
    """Uniform serving entry point (``raft_tpu.serve`` contract): returns
    ``(fn, operands)`` where ``fn(queries, *operands)`` produces the same
    ``(distances, indices)`` as :func:`knn` for these arguments — every
    static knob pre-bound so ``queries`` is the only shape-varying input,
    and ``fn`` AOT-compiles via
    ``jax.jit(fn).lower(q_spec, *operands).compile()``.  Index state rides
    as operands (not closure constants) so one executable per query bucket
    never embeds a copy of the database.

    ``filter``: optional shared prefilter (``core.Bitset`` / 1-D bools
    over database rows, True = keep) — rides as one more operand so
    tombstone deletes swap in a new mask without recompiling.  Per-query
    bitmaps can't ride a fixed operand across variable-row buckets and
    are rejected."""
    from ._packing import as_keep_mask, sentinel_filtered_ids

    y = wrap_array(database, ndim=2, name="database")
    expects(k >= 1, "k must be >= 1")
    expects(k <= y.shape[0], f"k={k} exceeds database size {y.shape[0]}")
    expects(mode in ("exact", "fast"), f"unknown mode {mode!r}")
    expects(cut in ("exact", "approx"), f"unknown cut {cut!r}")
    expects(refine_precision in ("highest", "high"),
            f"unknown refine_precision {refine_precision!r}")
    keep = as_keep_mask(filter, n=y.shape[0])
    if keep is not None:
        expects(keep.ndim == 1,
                "serving filters are shared bitsets (1-D); per-query "
                "bitmaps can't ride a fixed operand across buckets")
        if mode == "fast":
            c = int(max(cand, k))

            def fn(q, yy, kp):
                dv, di = _fast_knn_impl(q, yy, int(k), metric, c, 1024,
                                        1024, kp, cut, refine_precision)
                return dv, sentinel_filtered_ids(dv, di)
        else:
            t = int(min(tile, max(y.shape[0], 1)))

            def fn(q, yy, kp):
                dv, di = _knn_impl(q, yy, int(k), metric, t, kp)
                return dv, sentinel_filtered_ids(dv, di)

        return fn, (y, keep)
    if mode == "fast":
        c = int(max(cand, k))
        fn = lambda q, yy: _fast_knn_impl(q, yy, int(k), metric, c,
                                          1024, 1024, None, cut,
                                          refine_precision)
    else:
        t = int(min(tile, max(y.shape[0], 1)))
        fn = lambda q, yy: _knn_impl(q, yy, int(k), metric, t, None)
    return fn, (y,)


@functools.lru_cache(maxsize=64)
def _sharded_knn_program(mesh: Mesh, axis: str, rows: int, k: int, kk: int,
                         metric: str, tile: int, merge: str,
                         data_axis: Optional[str] = None,
                         keep_ndim: int = 0):
    """Compile-once sharded search: jit keyed on the static config instead of
    a per-call closure (which would re-trace every knn_sharded call).

    With ``data_axis`` (2-D mesh), queries are additionally partitioned
    over that axis — each (data, shard) device scores its query block
    against its database shard; merges stay on the shard axis (ICI), and
    no collective crosses the data axis (DCN-safe when the data axis spans
    slices; see ``core.mesh.make_hybrid_mesh``)."""
    nsh = mesh.shape[axis]

    def local(xq, ysh, kp):
        # ysh: (1, rows, d) block of this shard; kp: this shard's slice of
        # the keep mask ((rows,) bitset / (m_local, rows) bitmap) or None
        ysh = ysh[0]
        shard = jax.lax.axis_index(axis)
        v, i = _knn_impl(xq, ysh, kk, metric, tile, kp)
        if metric == "inner_product":
            v = -v  # back to smaller-is-nearer for the cross-shard merge
        gi = i + shard * rows
        if merge == "ring":
            # ppermute ring: constant memory, hop transfers overlap merges
            from ..comms.ring import ring_topk_merge

            pad = k - kk
            if pad:  # ring buffers must already be (m, k)
                v = jnp.concatenate(
                    [v, jnp.full((v.shape[0], pad), jnp.inf, v.dtype)], axis=1)
                gi = jnp.concatenate(
                    [gi, jnp.full((gi.shape[0], pad), -1, gi.dtype)], axis=1)
            out_v, out_i = ring_topk_merge(v, gi, k, axis)
        else:
            # all_gather everyone's candidates: (nsh, m, kk) → one wide select
            gv = jax.lax.all_gather(v, axis)
            gidx = jax.lax.all_gather(gi, axis)
            m = xq.shape[0]
            gv = jnp.moveaxis(gv, 0, 1).reshape(m, nsh * kk)
            gidx = jnp.moveaxis(gidx, 0, 1).reshape(m, nsh * kk)
            neg, pos = jax.lax.top_k(-gv, k)
            out_v = -neg
            out_i = jnp.take_along_axis(gidx, pos, axis=1)
        if metric == "inner_product":
            out_v = -out_v
        return out_v, out_i

    qspec = P(data_axis) if data_axis else P()
    # keep slices along the DATABASE axis: (n,) → P(axis); a (m, n) bitmap
    # additionally follows the query partitioning on its rows
    kspec = (P() if keep_ndim == 0
             else P(axis) if keep_ndim == 1
             else P(data_axis, axis))
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(qspec, P(axis), kspec),
            out_specs=(qspec, qspec),
            check_vma=False,
        )
    )


def knn_sharded(
    queries,
    database,
    k: int,
    *,
    mesh: Mesh,
    axis: str = "shard",
    data_axis: Optional[str] = None,
    metric: str = "sqeuclidean",
    tile: int = 8192,
    merge: str = "gather",
    filter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Database-sharded exact kNN over a mesh axis.

    Each device holds ``n/n_shards`` database rows (queries replicated) and
    computes a local top-k with *global* index numbering; cross-shard merge
    is either ``merge="gather"`` (one all_gather of every shard's (m, k),
    then a wide select — lowest latency at small S·k) or ``merge="ring"``
    (S−1 ppermute hops folding one neighbor's buffer at a time — constant
    memory, transfers overlap merges; the ring-attention-style pipeline for
    large k or many shards, :mod:`raft_tpu.comms.ring`).

    On a 2-D mesh, ``data_axis`` additionally partitions the *queries*
    over that axis (query-data-parallel × index-shard-parallel): merges
    stay on the shard axis, nothing crosses the data axis — lay the data
    axis over DCN and the shard axis over ICI
    (:func:`raft_tpu.core.make_hybrid_mesh`).

    ``filter``: bitset/bitmap prefilter, same contract as :func:`knn`
    (masks slice along the database axis with the shards).
    """
    from ._packing import as_keep_mask, sentinel_filtered_ids

    x = wrap_array(queries, ndim=2, name="queries")
    y = wrap_array(database, ndim=2, name="database")
    expects(merge in ("gather", "ring"), f"unknown merge {merge!r}")
    expects(k >= 1, "k must be >= 1")
    expects(k <= y.shape[0], f"k={k} exceeds database size {y.shape[0]}")
    nsh = mesh.shape[axis]
    n = y.shape[0]
    expects(n % nsh == 0, f"database rows {n} not divisible by mesh axis {nsh}")
    if data_axis is not None:
        expects(data_axis in mesh.axis_names, f"axis {data_axis!r} not in mesh")
        nd = mesh.shape[data_axis]
        expects(x.shape[0] % nd == 0,
                f"queries {x.shape[0]} not divisible by data axis {nd}")
    keep = as_keep_mask(filter, n, nq=x.shape[0])
    rows = n // nsh
    kk = min(k, rows)
    fn = _sharded_knn_program(mesh, axis, rows, int(k), kk, metric,
                              int(min(tile, rows)), merge, data_axis,
                              0 if keep is None else keep.ndim)
    yb = y.reshape(nsh, rows, y.shape[1])
    dv, di = fn(x, yb, keep)
    if keep is not None:
        di = sentinel_filtered_ids(dv, di)
    return dv, di


@dataclasses.dataclass(frozen=True)
class BruteFleetSlices:
    """Device-mesh layout of a brute-force database for the serving
    fleet (:mod:`raft_tpu.serve.fleet`): rows padded to a multiple of
    the mesh axis and laid out contiguously — shard *s* owns global rows
    ``[s*per, (s+1)*per)`` — plus a sharded validity mask with the pad
    rows False (global ids for brute force ARE row positions, so the
    mask doubles as the filter carrier: a user prefilter is padded and
    sharded the same way, then ANDed in)."""

    data: jax.Array    # [S*per, d] sharded P(axis)
    mask: jax.Array    # [S*per] bool sharded P(axis); pad rows False
    n: int             # original row count
    per: int           # rows per shard


def fleet_slices(database, mesh: Mesh, *, axis: str = "shard",
                 filter=None) -> BruteFleetSlices:
    """Slice a brute-force database over ``mesh[axis]`` for the fleet
    fan-out.  Host (numpy) input is padded in numpy and ``device_put``
    with the target sharding, so the single-device peak is one shard.
    Pad rows are ZEROS under a False mask — unlike
    :func:`._packing.shard_rows` (which tiles row 0 for build pipelines
    that track validity by count), a serving shard must never score a
    duplicated real row."""
    import numpy as np
    from jax.sharding import NamedSharding

    from ._packing import as_keep_mask

    y = database if isinstance(database, jax.Array) else np.asarray(database)
    expects(y.ndim == 2, "database must be [n, d]")
    n, d = y.shape
    n_dev = int(mesh.shape[axis])
    per = (n + n_dev - 1) // n_dev
    pad = per * n_dev - n
    keep = as_keep_mask(filter, n=n)
    if keep is not None:
        expects(keep.ndim == 1,
                "fleet filters are shared bitsets (1-D) over rows")
        mask = np.asarray(keep).astype(bool)
    else:
        mask = np.ones((n,), bool)
    if pad:
        zeros = (jnp.zeros if isinstance(y, jax.Array) else np.zeros)
        cat = (jnp.concatenate if isinstance(y, jax.Array)
               else np.concatenate)
        y = cat([y, zeros((pad, d), y.dtype)], axis=0)
        mask = np.concatenate([mask, np.zeros((pad,), bool)])
    sh = NamedSharding(mesh, P(axis))
    return BruteFleetSlices(jax.device_put(y, sh),
                            jax.device_put(jnp.asarray(mask), sh),
                            int(n), int(per))
